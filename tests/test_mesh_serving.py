"""Mesh-scale serving (server/shards.py + feed/fanin.py + main.py).

Three cross-lane guarantees behind --serve-shards at device scale:

- PLACEMENT is behavior-free: K lanes pinned onto one device and K
  lanes spread round-robin across every visible device produce
  bit-identical books/fills/rejects for the same stream (conftest forces
  8 virtual CPU devices, so this runs multi-device without a TPU).
- The all-symbols call-auction close is ATOMIC across lanes: a lane
  failing mid-barrier rolls every lane's books back bit-identically and
  keeps the call period open; the retry without the fault commits.
- The sequenced feed fan-in (--feed-fanin merged) delivers every lane's
  publishes in lane order, declares (and survives) seq gaps, and is
  observationally identical to single-hub mode per (channel, key).

Plus the --shard-devices placement parser, the sampler's device
identity/aggregate gauges, and main()'s structured CONFIG-ERROR
refusals for unsupported flag combinations.
"""

from __future__ import annotations

import time

import jax
import numpy as np
import pytest

import test_serve_shards as tss
from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.kernel import OP_SUBMIT
from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo
from matching_engine_tpu.server.shards import (
    build_serving_shards,
    parse_shard_devices,
)
from matching_engine_tpu.server.streams import StreamHub
from matching_engine_tpu.utils.metrics import Metrics

# -- placement parsing -------------------------------------------------------


def test_parse_shard_devices_policies():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest forces 8 virtual CPU devices"
    auto = parse_shard_devices("auto", 4)
    assert auto == [devs[i % len(devs)] for i in range(4)]
    assert parse_shard_devices(None, 4) == auto
    assert parse_shard_devices("", 4) == auto
    rr = parse_shard_devices("roundrobin", 10)
    assert rr == [devs[i % len(devs)] for i in range(10)]
    pinned = parse_shard_devices("pinned:0,0,3,3", 4)
    assert [d.id for d in pinned] == [0, 0, 3, 3]
    # Auto on a single visible device keeps jax default placement.
    assert parse_shard_devices("auto", 2, devices=devs[:1]) == [None, None]
    # Roundrobin commits explicitly even on one device.
    assert parse_shard_devices("roundrobin", 2, devices=devs[:1]) \
        == [devs[0], devs[0]]


def test_parse_shard_devices_refusals():
    for bad in ("pinned:0",        # count != K
                "pinned:0,99",     # ordinal out of range
                "pinned:0,x",      # non-integer ordinal
                "pinned:",
                "sideways"):       # unknown policy
        with pytest.raises(ValueError):
            parse_shard_devices(bad, 2)


def test_lane_books_committed_to_devices():
    """roundrobin at K=4 lands four DISTINCT devices and each lane's
    book arrays actually live on its device."""
    shards = build_serving_shards(
        tss.make_cfg(), 4, with_dispatchers=False, sample_interval_s=0,
        shard_devices="roundrobin")
    try:
        ids = []
        for lane in shards.lanes:
            dev = lane.runner.device
            assert dev is not None
            ids.append(dev.id)
            leaf = jax.tree_util.tree_leaves(lane.runner.book)[0]
            assert {d.id for d in leaf.devices()} == {dev.id}
        assert sorted(ids) == [0, 1, 2, 3]
    finally:
        shards.close()


# -- K-lanes-on-1-device vs K-lanes-on-N-devices bit-parity ------------------


def test_device_placement_parity_python():
    pinned = tss.drive_python(tss.make_cfg(), 4, tss.gen_stream(3),
                              shard_devices="pinned:0,0,0,0")
    spread = tss.drive_python(tss.make_cfg(), 4, tss.gen_stream(3),
                              shard_devices="roundrobin")
    assert pinned["books"] == spread["books"]
    assert sorted(pinned["fills"]) == sorted(spread["fills"])
    assert pinned["rejected"].keys() == spread["rejected"].keys()


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_device_placement_parity_native():
    pinned = tss.drive_native(tss.make_cfg(), 4, tss.gen_stream(3),
                              shard_devices="pinned:0,0,0,0")
    spread = tss.drive_native(tss.make_cfg(), 4, tss.gen_stream(3),
                              shard_devices="roundrobin")
    assert pinned["books"] == spread["books"]
    assert sorted(pinned["fills"]) == sorted(spread["fills"])


# -- cross-lane auction barrier ----------------------------------------------


def _rest_crossed(shards):
    """Open the call period and rest a crossed pair on every symbol
    (bid 10100 over ask 10000 — auction-mode submits never match)."""
    shards.set_auction_mode(True)
    for s in range(tss.SYMS):
        sym = f"S{s}"
        runner = shards.lane_for_symbol(sym).runner
        for side, price in ((1, 10_100), (2, 10_000)):
            assert runner.slot_acquire(sym) is not None
            num, oid = runner.assign_oid()
            info = OrderInfo(
                oid=num, order_id=oid, client_id="c0", symbol=sym,
                side=side, otype=0, price_q4=price, quantity=5,
                remaining=5, status=0, handle=runner.assign_handle())
            box = {}
            runner.dispatch_pipelined(
                [EngineOp(OP_SUBMIT, info)],
                lambda r, e, box=box: box.update(r=r, e=e))
            runner.finish_pending()
            assert box["e"] is None, box["e"]


def _books_host(shards):
    return [jax.tree_util.tree_map(np.asarray, lane.runner.book)
            for lane in shards.lanes]


def test_cross_lane_barrier_abort_is_atomic_then_retry_commits():
    """A lane failing MID-BARRIER aborts the whole uncross: every lane's
    books come back bit-identical to never having auctioned, the call
    period stays open, and both barrier counters account for it. The
    retry without the fault commits all lanes at one venue point."""
    metrics = Metrics()
    shards = build_serving_shards(
        tss.make_cfg(), 4, metrics=metrics, with_dispatchers=False,
        sample_interval_s=0, shard_devices="roundrobin")
    try:
        _rest_crossed(shards)
        all_syms = sorted(f"S{s}" for s in range(tss.SYMS))
        assert sorted(shards.crossed_symbols()) == all_syms
        before = _books_host(shards)

        victim = shards.lanes[2].runner
        orig_prepare = victim.auction_prepare

        def boom(symbols):
            raise RuntimeError("injected mid-barrier lane failure")

        victim.auction_prepare = boom
        summary = shards.run_auction(None)
        assert summary["aborted"]
        assert summary["crossed"] == []
        assert "barrier aborted" in summary["error"]
        assert "lane 2" in summary["error"]
        counters, _ = metrics.snapshot()
        assert counters.get("auction_barrier_aborts") == 1
        assert not counters.get("auction_barrier_commits")
        # All-or-nothing: every lane (not just the victim) restored
        # bit-identically, call period still open, books still crossed.
        for b, a in zip(before, _books_host(shards)):
            bl, al = (jax.tree_util.tree_leaves(b),
                      jax.tree_util.tree_leaves(a))
            assert len(bl) == len(al)
            for x, y in zip(bl, al):
                np.testing.assert_array_equal(x, y)
        assert shards.auction_mode
        assert sorted(shards.crossed_symbols()) == all_syms

        victim.auction_prepare = orig_prepare
        retry = shards.run_auction(None)
        assert retry["error"] == "", retry["error"]
        # crossed entries are (symbol, clear_price, executed) triples.
        assert sorted(c[0] for c in retry["crossed"]) == all_syms
        assert all(c[1] in (10_000, 10_100) or 10_000 <= c[1] <= 10_100
                   for c in retry["crossed"])
        assert all(c[2] == 5 for c in retry["crossed"])
        counters, _ = metrics.snapshot()
        assert counters.get("auction_barrier_commits") == 1
        assert not shards.auction_mode, "commit must close the call period"
        assert shards.crossed_symbols() == []
    finally:
        shards.close()


# -- sequenced feed fan-in ---------------------------------------------------


class _RecordingHub:
    """Hub stand-in for direct merger tests: records delivery order."""

    sequencer = None

    def __init__(self, fail_md: bool = False):
        self.events: list = []
        self.fail_md = fail_md

    def has_market_data_subs(self):
        return True

    def has_order_update_subs(self):
        return True

    def publish_market_data(self, updates):
        if self.fail_md:
            raise RuntimeError("md pipe broken")
        self.events.append(("md", updates))

    def publish_order_updates(self, updates):
        self.events.append(("ou", updates))

    def publish_oplog(self, updates):
        self.events.append(("oplog", updates))

    def publish_audit_rows(self, rows, env, n, drop=None, observer=None):
        self.events.append(("audit", rows))
        return list(range(n))


def _wait_until(pred, timeout_s: float = 5.0):
    deadline = time.monotonic() + timeout_s
    while not pred():
        assert time.monotonic() < deadline, "fan-in merger never caught up"
        time.sleep(0.01)


def test_fanin_delivers_in_lane_order_and_drains_on_close():
    from matching_engine_tpu.feed import FeedFanIn

    metrics = Metrics()
    hub = _RecordingHub()
    fanin = FeedFanIn(hub, 2, metrics=metrics)
    p0, p1 = fanin.lane_publisher(0), fanin.lane_publisher(1)
    p0.publish_market_data(["a"])
    p0.publish_order_updates(["b"])
    p1.publish_oplog(["c"])
    p0.publish_market_data(["d"])
    assert p0.publish_audit_rows(["row"], None, 1) == []
    p0.publish_market_data([])   # empty batches never enqueue
    fanin.close()
    assert len(hub.events) == 5
    # Per-lane relative order is the lane's publish order.
    ev = hub.events
    assert ev.index(("md", ["a"])) < ev.index(("ou", ["b"])) \
        < ev.index(("md", ["d"]))
    assert ("oplog", ["c"]) in ev and ("audit", ["row"]) in ev
    counters, _ = metrics.snapshot()
    # The lane facade returned []; the merger accounts the audit rows.
    assert counters.get("audit_records") == 1
    assert not counters.get("feed_fanin_gaps")
    fanin.close()   # idempotent


def test_fanin_declares_gaps_and_counts_stale_dups():
    from matching_engine_tpu.feed import FeedFanIn

    metrics = Metrics()
    hub = _RecordingHub()
    fanin = FeedFanIn(hub, 1, metrics=metrics, gap_wait_s=0.05)
    # Lane 0's seq line with a hole at 2: 1 delivers; 3 and 4 park until
    # the gap window lapses, then the gap is DECLARED and they flush.
    fanin._q.put((0, 0, 1, 0, ["s1"]))
    fanin._q.put((0, 0, 3, 0, ["s3"]))
    fanin._q.put((0, 0, 4, 0, ["s4"]))
    _wait_until(lambda: len(hub.events) == 3)
    assert hub.events == [("md", ["s1"]), ("md", ["s3"]), ("md", ["s4"])]
    counters, _ = metrics.snapshot()
    assert counters.get("feed_fanin_gaps") == 1
    # The straggler arriving after its gap was declared is stale.
    fanin._q.put((0, 0, 2, 0, ["s2"]))
    _wait_until(lambda: metrics.snapshot()[0].get("feed_fanin_dups") == 1)
    assert len(hub.events) == 3
    fanin.close()


def test_fanin_delivery_errors_are_counted_not_fatal():
    from matching_engine_tpu.feed import FeedFanIn

    metrics = Metrics()
    hub = _RecordingHub(fail_md=True)
    fanin = FeedFanIn(hub, 1, metrics=metrics)
    pub = fanin.lane_publisher(0)
    pub.publish_market_data(["boom"])
    pub.publish_order_updates(["fine"])
    fanin.close()
    assert hub.events == [("ou", ["fine"])]
    counters, _ = metrics.snapshot()
    assert counters.get("feed_fanin_errors") == 1


def test_fanin_merged_matches_hub_mode_per_key():
    """hub vs merged over the same per-lane publish sequences: every
    (channel, key) domain's delivered payloads and seq line must be
    identical — merged mode changes WHO serializes, not what the
    subscriber sees."""
    from matching_engine_tpu.feed import FeedFanIn, FeedSequencer
    from matching_engine_tpu.proto import pb2

    clients = ("c0", "c1")

    def run(mode: str):
        metrics = Metrics()
        hub = StreamHub(maxsize=100_000, metrics=metrics,
                        sequencer=FeedSequencer(metrics=metrics))
        subs = {c: hub.subscribe_order_updates(c) for c in clients}
        fanin = (FeedFanIn(hub, 2, metrics=metrics)
                 if mode == "merged" else None)
        pubs = [fanin.lane_publisher(i) if fanin is not None else hub
                for i in range(2)]
        for j in range(50):
            for i, p in enumerate(pubs):
                p.publish_order_updates([
                    pb2.OrderUpdate(order_id=f"OID-{1 + i + 2 * j}",
                                    client_id=c, symbol=f"S{i}", status=0)
                    for c in clients])
        if fanin is not None:
            fanin.close()   # drains every queued publish first
        hub.close_all()
        out = {}
        for c, sub in subs.items():
            items = []
            while True:
                try:
                    _, item = sub.q.get_nowait()
                except Exception:
                    break
                if hasattr(item, "seq"):
                    items.append(item)
            assert [it.seq for it in items] == \
                list(range(1, len(items) + 1)), f"{c}: seq line has gaps"
            out[c] = [(it.order_id, it.symbol, it.status) for it in items]
        return out

    assert run("hub") == run("merged")


# -- sampler placement gauges ------------------------------------------------


def test_sampler_publishes_device_identity_and_aggregates():
    metrics = Metrics()
    shards = build_serving_shards(
        tss.make_cfg(), 2, metrics=metrics, with_dispatchers=False,
        sample_interval_s=0, shard_devices="pinned:0,1")
    try:
        shards._sample_once([0, 0], time.perf_counter() - 0.1)
        _, gauges = metrics.snapshot()
        assert gauges["lane0_device"] == 0
        assert gauges["lane1_device"] == 1
        assert "device0_ops_per_s" in gauges
        assert "device1_ops_per_s" in gauges
        assert "lane_imbalance" in gauges
    finally:
        shards.close()


# -- main() structured refusals ----------------------------------------------


REFUSALS = [
    (["--shard-devices", "roundrobin"], "CONFIG-ERROR"),
    (["--serve-shards", "2", "--shard-devices", "pinned:0"],
     "bad --shard-devices"),
    (["--feed-fanin", "merged"], "CONFIG-ERROR"),
    (["--serve-shards", "2", "--feed-fanin", "merged",
      "--gateway-addr", "127.0.0.1:1"], "CONFIG-ERROR"),
    (["--mesh-serve", "--mesh", "2"], "CONFIG-ERROR"),
    (["--mesh-serve", "--serve-shards", "2"], "CONFIG-ERROR"),
    (["--serve-shards", "2", "--native-lanes",
      "--gateway-addr", "127.0.0.1:1"], "CONFIG-ERROR"),
]


@pytest.mark.parametrize("argv,marker", REFUSALS,
                         ids=[" ".join(a) for a, _ in REFUSALS])
def test_main_refuses_unsupported_combos(argv, marker, capsys):
    """Unsupported flag combinations exit 3 with a structured line an
    operator can grep — CONFIG-ERROR lines NAME the supported combos."""
    from matching_engine_tpu.server.main import main

    assert main(argv) == 3
    err = capsys.readouterr().err
    assert marker in err, err
    if marker == "CONFIG-ERROR":
        assert "supported:" in err, err
