"""Storage layer: schema semantics, id recovery, async sink, fixed ref bugs."""

import os

import pytest

from matching_engine_tpu.storage import AsyncStorageSink, FillRow, Storage
from matching_engine_tpu.storage.storage import (
    STATUS_FILLED,
    STATUS_NEW,
    STATUS_PARTIALLY_FILLED,
)


@pytest.fixture
def store(tmp_path):
    s = Storage(str(tmp_path / "me.db"))
    assert s.init()
    yield s
    s.close()


def test_insert_and_get(store):
    assert store.insert_new_order("OID-1", "c1", "SYM", 1, 0, 10050, 5)
    row = store.get_order("OID-1")
    assert row[:9] == ("OID-1", "c1", "SYM", 1, 0, 10050, 5, 5, STATUS_NEW)
    assert row[11] == 0  # tif defaults to GTC


def test_market_order_stores_null_price(store):
    # Fixes reference bug (c): MARKET price is NULL, and the actual
    # order_type is stored (storage.cpp:106-107 hardcoded type, kept price).
    assert store.insert_new_order("OID-1", "c1", "SYM", 2, 1, None, 5)
    row = store.get_order("OID-1")
    assert row[4] == 1 and row[5] is None


def test_best_bid_ask_use_stored_side_encoding(store):
    # Fixes reference bug (a): side filters are 1/2, matching what inserts
    # store (storage.cpp:218,239 filtered 0/1 and always returned empty).
    store.insert_new_order("OID-1", "c1", "SYM", 1, 0, 10000, 5)
    store.insert_new_order("OID-2", "c1", "SYM", 1, 0, 10100, 3)
    store.insert_new_order("OID-3", "c1", "SYM", 2, 0, 10200, 2)
    store.insert_new_order("OID-4", "c2", "SYM", 1, 0, 10100, 4)
    assert store.best_bid("SYM") == (10100, 7)
    assert store.best_ask("SYM") == (10200, 2)
    assert store.best_bid("OTHER") is None


def test_add_fill_and_read_back(store):
    # Fixes reference bug (b): add_fill binds all placeholders
    # (storage.cpp:189-196 skipped index 4 and always threw).
    store.insert_new_order("OID-1", "c1", "SYM", 1, 0, 10000, 5)
    assert store.add_fill(FillRow("OID-1", "OID-9", 10000, 5))
    rows = store.fills_for_order("OID-1")
    assert len(rows) == 1 and rows[0][:4] == ("OID-1", "OID-9", 10000, 5)


def test_fill_requires_existing_order(store):
    # FK enforcement: a fill for an unknown order is refused, not crashed.
    assert not store.add_fill(FillRow("OID-404", "OID-9", 10000, 5))


def test_status_update(store):
    store.insert_new_order("OID-1", "c1", "SYM", 1, 0, 10000, 5)
    assert store.update_order_status("OID-1", STATUS_PARTIALLY_FILLED, 2)
    row = store.get_order("OID-1")
    assert row[7] == 2 and row[8] == STATUS_PARTIALLY_FILLED


def test_oid_sequence_recovery(tmp_path):
    path = str(tmp_path / "me.db")
    s = Storage(path)
    s.init()
    assert s.load_next_oid_seq() == 1
    s.insert_new_order("OID-41", "c", "S", 1, 0, 1, 1)
    s.insert_new_order("OID-7", "c", "S", 1, 0, 1, 1)
    s.close()
    # Fresh process: sequence resumes from MAX.
    s2 = Storage(path)
    s2.init()
    assert s2.load_next_oid_seq() == 42
    s2.close()


def test_open_orders_recovery_set(store):
    store.insert_new_order("OID-1", "c", "S", 1, 0, 100, 5)                      # NEW
    store.insert_new_order("OID-2", "c", "S", 1, 0, 100, 5, status=STATUS_FILLED, remaining=0)
    store.insert_new_order("OID-3", "c", "S", 2, 0, 100, 5, status=STATUS_PARTIALLY_FILLED, remaining=2)
    store.insert_new_order("OID-4", "c", "S", 1, 1, None, 5, status=STATUS_FILLED, remaining=0)
    rows = store.open_orders("S")
    assert [r[0] for r in rows] == ["OID-1", "OID-3"]


def test_duplicate_order_id_rejected(store):
    assert store.insert_new_order("OID-1", "c", "S", 1, 0, 100, 5)
    assert not store.insert_new_order("OID-1", "c", "S", 1, 0, 100, 5)


def test_async_sink_batches_and_flushes(store):
    sink = AsyncStorageSink(store)
    for i in range(50):
        sink.submit(
            orders=[(f"OID-{i}", "c", "S", 1, 0, 100, 5, 5, STATUS_NEW)],
            fills=[FillRow(f"OID-{i}", "OID-X", 100, 5)] if i % 2 == 0 else [],
        )
    sink.flush()
    assert store.count("orders") == 50
    assert store.count("fills") == 25
    sink.close()


def test_async_sink_dropped_count_is_locked_and_exact(store):
    """`dropped` is a cross-thread read-modify-write: K serving lanes
    share one sink and can hit queue.Full together, so the counter
    increments under _drop_lock (lockset analyzer finding, PR 10) —
    concurrent failing submits must account every drop exactly."""
    import threading

    gate = threading.Event()
    entered = threading.Event()
    orig = store.apply_batch

    def stalled(*a, **kw):
        entered.set()
        gate.wait(10)
        return orig(*a, **kw)

    store.apply_batch = stalled
    sink = AsyncStorageSink(store, max_queue=1)
    # Park the flusher inside the stalled commit, THEN fill the queue —
    # filling earlier races the coalescing drain and some of the
    # "failing" submits below would sneak through.
    sink.submit(orders=[("OID-F", "c", "S", 1, 0, 100, 5, 5,
                         STATUS_NEW)])
    assert entered.wait(10)
    while sink.submit(orders=[("OID-F", "c", "S", 1, 0, 100, 5, 5,
                               STATUS_NEW)], block=False):
        pass
    base = sink.dropped
    threads = [
        threading.Thread(target=lambda: [
            sink.submit(orders=[("OID-X", "c", "S", 1, 0, 100, 5, 5,
                                 STATUS_NEW)], block=False)
            for _ in range(50)])
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sink.dropped == base + 200
    gate.set()
    sink.close()


def test_async_sink_transaction_per_batch(store):
    sink = AsyncStorageSink(store)
    sink.submit(
        orders=[("OID-1", "c", "S", 1, 0, 100, 5, 5, STATUS_NEW)],
        updates=[("OID-1", STATUS_FILLED, 0)],
        fills=[FillRow("OID-1", "OID-2", 100, 5)],
    )
    sink.flush()
    assert store.get_order("OID-1")[8] == STATUS_FILLED
    assert len(store.fills_for_order("OID-1")) == 1
    sink.close()
