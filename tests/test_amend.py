"""OP_AMEND: priority-preserving quantity reduction, device vs oracle.

The venue "amend down" op — reduce a resting order's quantity in place,
keeping its price and arrival seq (and therefore its spot in the
price-time queue). Anything else (qty up, price move) is REJECTED: those
re-price priority and belong to cancel+submit at the service layer. The
reference has no amend surface at all (its only RPC family is
SubmitOrder + stubs, /root/reference/proto/matching_engine.proto:29-35);
this is an additive venue-parity extension like CancelOrder/RunAuction.
"""

import random

import pytest

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import (
    HostOrder,
    apply_orders,
    snapshot_books,
)
from matching_engine_tpu.engine.kernel import (
    BUY,
    LIMIT,
    MARKET,
    NEW,
    OP_AMEND,
    OP_CANCEL,
    OP_SUBMIT,
    REJECTED,
    SELL,
)
from matching_engine_tpu.engine.oracle import OracleBook

KERNELS = ["matrix", "sorted"]


def run_both(cfg, host_orders):
    """test_kernel_parity.run_both with OP_AMEND dispatch added."""
    oracles = [OracleBook(capacity=cfg.capacity)
               for _ in range(cfg.num_symbols)]
    o_results, o_fills = [], []
    for o in host_orders:
        ob = oracles[o.sym]
        if o.op == OP_SUBMIT:
            r = ob.submit(o.oid, o.side, o.otype, o.price, o.qty)
        elif o.op == OP_AMEND:
            r = ob.amend(o.oid, o.qty)
        else:
            r = ob.cancel(o.oid)
        o_results.append((o.oid, o.sym, r.status, r.filled, r.remaining))
        o_fills.extend((o.sym, f.taker_oid, f.maker_oid, f.price_q4,
                        f.quantity) for f in r.fills)

    book = init_book(cfg)
    book, d_results, d_fills = apply_orders(cfg, book, host_orders)
    d_results = [(r.oid, r.sym, r.status, r.filled, r.remaining)
                 for r in d_results]
    d_fills = [(f.sym, f.taker_oid, f.maker_oid, f.price_q4, f.quantity)
               for f in d_fills]
    d_snaps = snapshot_books(book)
    o_snaps = [ob.snapshot() for ob in oracles]
    return book, (d_results, d_fills, d_snaps), (o_results, o_fills, o_snaps)


def assert_parity(cfg, host_orders):
    book, (d_res, d_fills, d_snaps), (o_res, o_fills, o_snaps) = run_both(
        cfg, host_orders)
    assert sorted(d_res) == sorted(o_res)
    for s in range(cfg.num_symbols):
        dev = [f for f in d_fills if f[0] == s]
        orc = [f for f in o_fills if f[0] == s]
        assert dev == orc, f"fills sym {s}:\n dev={dev}\n orc={orc}"
        assert d_snaps[s][0] == o_snaps[s][0], f"bid book sym {s}"
        assert d_snaps[s][1] == o_snaps[s][1], f"ask book sym {s}"
    if cfg.kernel == "sorted":
        from tests.test_kernel_sorted import assert_sorted_invariant
        assert_sorted_invariant(book)
    return d_res


@pytest.mark.parametrize("kernel", KERNELS)
def test_amend_reduces_and_keeps_priority(kernel):
    """Two makers at one price; the first amends DOWN and must still fill
    first (seq preserved) — the defining property of amend vs
    cancel+resubmit."""
    cfg = EngineConfig(num_symbols=1, capacity=8, batch=8, kernel=kernel)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 10, oid=1),
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 10, oid=2),
        HostOrder(0, OP_AMEND, SELL, qty=3, oid=1),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10_000, 5, oid=3),
    ]
    res = assert_parity(cfg, orders)
    by_oid = {r[0]: r for r in res}
    assert by_oid[1][2] == NEW and by_oid[1][4] == 3  # amend ack, rem 3
    # Taker crossed maker 1 FIRST (3 units), then maker 2 (2 units).
    _, (_, d_fills, _), _ = run_both(cfg, orders)
    assert [(f[2], f[4]) for f in d_fills] == [(1, 3), (2, 2)]


@pytest.mark.parametrize("kernel", KERNELS)
def test_amend_rejections(kernel):
    cfg = EngineConfig(num_symbols=1, capacity=8, batch=8, kernel=kernel)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 10, oid=1),
        HostOrder(0, OP_AMEND, SELL, qty=10, oid=1),   # not a reduction
        HostOrder(0, OP_AMEND, SELL, qty=15, oid=1),   # qty up
        HostOrder(0, OP_AMEND, SELL, qty=0, oid=1),    # to zero
        HostOrder(0, OP_AMEND, SELL, qty=5, oid=99),   # unknown oid
    ]
    res = assert_parity(cfg, orders)
    statuses = [r[2] for r in sorted(res)][1:]
    assert statuses == [REJECTED] * 4
    # Wrong-side amend: device-only probe (the serving stack's host
    # directory always supplies the true resting side; the oracle, like
    # its cancel, is side-agnostic) — the device must REJECT and leave
    # the book untouched.
    book = init_book(cfg)
    book, d_res, _ = apply_orders(cfg, book, orders + [
        HostOrder(0, OP_AMEND, BUY, qty=5, oid=1)])
    assert d_res[-1].status == REJECTED
    bids, asks = snapshot_books(book)[0]
    assert asks == [(1, 10_000, 10, 0)]


@pytest.mark.parametrize("kernel", KERNELS)
def test_amend_after_partial_fill_then_cancel(kernel):
    cfg = EngineConfig(num_symbols=1, capacity=8, batch=8, kernel=kernel)
    orders = [
        HostOrder(0, OP_SUBMIT, SELL, LIMIT, 10_000, 10, oid=1),
        HostOrder(0, OP_SUBMIT, BUY, LIMIT, 10_000, 4, oid=2),  # rem 6
        HostOrder(0, OP_AMEND, SELL, qty=2, oid=1),             # 6 -> 2
        HostOrder(0, OP_CANCEL, SELL, oid=1),                   # frees 2
    ]
    res = assert_parity(cfg, orders)
    by_oid = {r[0]: r for r in res}
    assert by_oid[1][4] == 2  # the cancel released the amended remainder


def test_amend_then_cancel_same_dispatch_attribution():
    """Two ops on ONE order in ONE dispatch batch: the runner's per-handle
    FIFO must attribute the device's two result rows to the right ops —
    amend acks with the reduced remaining, the cancel then releases it
    (regression: a plain handle->op dict returned 'order not open' to the
    cancel and no outcome at all to the amend)."""
    from matching_engine_tpu.server.engine_runner import (
        EngineOp,
        EngineRunner,
        OrderInfo,
    )
    from matching_engine_tpu.engine.kernel import (
        CANCELED as K_CANCELED,
        OP_AMEND as K_AMEND,
        OP_CANCEL as K_CANCEL,
    )

    cfg = EngineConfig(num_symbols=2, capacity=8, batch=4, max_fills=256)
    r = EngineRunner(cfg)
    assert r.slot_acquire("AMC") is not None
    num, oid = r.assign_oid()
    info = OrderInfo(oid=num, order_id=oid, client_id="c", symbol="AMC",
                     side=BUY, otype=0, price_q4=10_000, quantity=9,
                     remaining=9, status=0, handle=r.assign_handle())
    out = r.run_dispatch([EngineOp(OP_SUBMIT, info)])
    assert out.outcomes[0].status == NEW

    res = r.run_dispatch([
        EngineOp(K_AMEND, info, amend_qty=4),
        EngineOp(K_CANCEL, info, cancel_requester="c"),
    ])
    by_op = {o.op.op: o for o in res.outcomes}
    assert by_op[K_AMEND].status == NEW
    assert by_op[K_AMEND].remaining == 4
    assert by_op[K_CANCEL].status == K_CANCELED
    assert by_op[K_CANCEL].remaining == 4  # released the amended size
    # The storage stream carries the amend 4-tuple BEFORE the cancel
    # update, and a replaying store must end CANCELED (order-preserving
    # update application).
    lens = [len(u) for u in res.storage_updates]
    assert lens == [4, 3]


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("seed", [21, 22, 23])
def test_amend_fuzz_parity(kernel, seed):
    """Random submits/cancels/amends; amends target live and dead oids
    with quantities spanning reduce/equal/increase."""
    cfg = EngineConfig(num_symbols=4, capacity=16, batch=8, kernel=kernel)
    rng = random.Random(seed)
    orders = []
    live: list[dict[int, int]] = [dict() for _ in range(4)]
    oid = 0
    for _ in range(240):
        sym = rng.randrange(4)
        roll = rng.random()
        if live[sym] and roll < 0.15:
            target = rng.choice(list(live[sym]))
            side = live[sym].pop(target)
            orders.append(HostOrder(sym, OP_CANCEL, side, oid=target))
        elif live[sym] and roll < 0.40:
            target = rng.choice(list(live[sym]))
            side = live[sym][target]
            orders.append(HostOrder(
                sym, OP_AMEND, side, qty=rng.randrange(0, 25), oid=target))
        else:
            oid += 1
            side = rng.choice((BUY, SELL))
            market = rng.random() < 0.15
            price = 0 if market else 10_000 + 100 * rng.randrange(6)
            orders.append(HostOrder(
                sym, OP_SUBMIT, side, MARKET if market else LIMIT,
                price, rng.randrange(1, 20), oid=oid))
            if not market:
                live[sym][oid] = side
    assert_parity(cfg, orders)
