"""Partitioned serving (server/shards.py): K-lane parity and fan-in.

The shard cut must be INVISIBLE per symbol: a symbol's ops all land on
one lane in stream order, so matching, statuses, storage rows and the
final book for that symbol must be bit-identical whether the market runs
as one lane or K — only order-id NUMBERS differ (strided allocation),
so every surface is compared after normalizing ids back to the
generating stream's tags. Proven for the python serving path and the
C++ lane engine (--native-lanes), mirroring tests/test_native_lanes.py.

Also here: strided-OID allocator unit tests (uniqueness + storage
reseed rounding), the concurrent-lane feed invariant (per-(channel,key)
seq lines stay gapless when K dispatcher threads publish into one
sequenced hub at once), and a full-stack sharded-server e2e including a
restart at a DIFFERENT shard count over the same durable store.
"""

from __future__ import annotations

import random
import threading

import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import (
    OP_AMEND,
    OP_CANCEL,
    OP_SUBMIT,
)
from matching_engine_tpu.server.engine_runner import (
    EngineOp,
    EngineRunner,
    OrderInfo,
)
from matching_engine_tpu.server.shards import (
    ShardRouter,
    make_lane_runner,
)
from matching_engine_tpu.server.streams import StreamHub

SYMS = 8          # global symbol namespace of the fuzz market
CFG = dict(capacity=16, batch=4, max_fills=1 << 12)


def make_cfg(kernel: str = "matrix") -> EngineConfig:
    return EngineConfig(num_symbols=SYMS, kernel=kernel, **CFG)


# -- strided OID allocation --------------------------------------------------


def test_oid_stride_uniqueness_and_reseed():
    cfg = make_cfg()
    router = ShardRouter(4)
    runners = [make_lane_runner(cfg, router, i) for i in range(4)]
    seen = set()
    for r in runners:
        for _ in range(50):
            n, oid = r.assign_oid()
            assert oid == f"OID-{n}"
            assert (n - 1) % 4 == r.oid_offset
            assert n not in seen
            seen.add(n)
    # Reseed from a store whose max oid belongs to ANY residue class:
    # each lane rounds up to its own class, past the seed.
    for r in runners:
        r.seed_oid_sequence(1000)
        n, _ = r.assign_oid()
        assert n >= 1000
        assert (n - 1) % 4 == r.oid_offset
        assert n not in seen
        seen.add(n)


def test_router_order_id_residue():
    router = ShardRouter(4)
    assert router.shard_of_order_id("OID-1") == 0
    assert router.shard_of_order_id("OID-6") == 1
    assert router.shard_of_order_id("OID-999") == (999 - 1) % 4
    assert router.shard_of_order_id("garbled") is None
    assert router.shard_of_order_id("OID-x") is None
    # Symbol routing is the stable multi-host hash: deterministic and
    # total over arbitrary names.
    assert all(0 <= router.shard_of(f"S{i}") < 4 for i in range(100))
    assert router.shard_of("ACME") == router.shard_of("ACME")


# -- the fuzz stream ---------------------------------------------------------


def gen_stream(seed: int, n_batches: int = 10, batch_n: int = 16):
    """Batches of tagged ops. Cancel/amend targets reference the TAG of
    an earlier LIMIT submit (ids differ per shard count; tags don't)."""
    rng = random.Random(seed)
    tag = [0]
    limit_targets: list[tuple[int, str, str]] = []  # (tag, sym, cid)
    batches = []

    def t():
        tag[0] += 1
        return tag[0]

    for _ in range(n_batches):
        ops = []
        for _ in range(batch_n):
            r = rng.random()
            if r < 0.7 or not limit_targets:
                sym = f"S{rng.randrange(SYMS)}"
                cid = f"c{rng.randrange(4)}"
                side = 1 if rng.random() < 0.5 else 2
                otype = rng.choice((0, 0, 0, 1, 2, 3)) \
                    if rng.random() < 0.3 else 0
                price = 0 if otype == 1 else 10_000 + rng.randrange(-6, 7)
                qty = rng.randrange(1, 12)
                mytag = t()
                ops.append(("submit", mytag, sym, cid, side, otype, price,
                            qty))
                if otype == 0:
                    limit_targets.append((mytag, sym, cid))
            elif r < 0.88:
                tt, sym, cid = rng.choice(limit_targets)
                if rng.random() < 0.15:
                    cid = "mallory"
                ops.append(("cancel", t(), tt, cid))
            else:
                tt, sym, cid = rng.choice(limit_targets)
                ops.append(("amend", t(), tt, cid, rng.randrange(1, 15)))
        batches.append(ops)
    return batches


# -- drains ------------------------------------------------------------------


def drive_python(cfg: EngineConfig, K: int, stream,
                 shard_devices: str | None = None) -> dict:
    """Run the stream through K python lanes; returns the normalized
    per-symbol surface. Submits route by symbol shard, cancels/amends to
    their target's lane — each lane sees its ops in stream order, as its
    dispatcher thread would pop them. `shard_devices` is the placement
    spec (--shard-devices); None keeps the auto policy."""
    from matching_engine_tpu.server.shards import parse_shard_devices

    router = ShardRouter(K)
    hub = StreamHub()
    placement = parse_shard_devices(shard_devices, K)
    runners = [make_lane_runner(cfg, router, i, hub=hub,
                                device=placement[i]) for i in range(K)]
    tag_oid: dict[int, str] = {}      # submit tag -> order id
    oid_tag: dict[str, str] = {}
    tag_info: dict[int, OrderInfo] = {}
    statuses: dict[int, tuple] = {}   # submit tag -> (status, remaining)
    fills = []                        # (taker_tag, maker_tag, price, qty)
    rejected: dict[int, str] = {}     # op tag -> edge error

    for ops in stream:
        per_lane: dict[int, list] = {}
        for op in ops:
            if op[0] == "submit":
                _, tg, sym, cid, side, otype, price, qty = op
                lane = router.shard_of(sym)
            else:
                target = tag_oid.get(op[2])
                if target is None:
                    rejected[op[1]] = "unknown order id"
                    continue
                lane = router.shard_of(tag_info[op[2]].symbol)
            per_lane.setdefault(lane, []).append(op)
        for lane, lops in per_lane.items():
            runner = runners[lane]
            engine_ops = []
            for op in lops:
                if op[0] == "submit":
                    _, tg, sym, cid, side, otype, price, qty = op
                    if runner.slot_acquire(sym) is None:
                        rejected[tg] = "capacity"
                        continue
                    num, oid = runner.assign_oid()
                    info = OrderInfo(
                        oid=num, order_id=oid, client_id=cid, symbol=sym,
                        side=side, otype=otype, price_q4=price,
                        quantity=qty, remaining=qty, status=0,
                        handle=runner.assign_handle())
                    tag_oid[tg] = oid
                    oid_tag[oid] = tg
                    tag_info[tg] = info
                    engine_ops.append((tg, EngineOp(OP_SUBMIT, info)))
                elif op[0] == "cancel":
                    _, tg, tt, cid = op
                    info = runner.orders_by_id.get(tag_oid[tt])
                    if info is None or info.client_id != cid:
                        rejected[tg] = "unknown/foreign"
                        continue
                    engine_ops.append((tg, EngineOp(
                        OP_CANCEL, info, cancel_requester=cid)))
                else:
                    _, tg, tt, cid, qty = op
                    info = runner.orders_by_id.get(tag_oid[tt])
                    if info is None or info.client_id != cid:
                        rejected[tg] = "unknown/foreign"
                        continue
                    engine_ops.append((tg, EngineOp(
                        OP_AMEND, info, amend_qty=qty)))
            if not engine_ops:
                continue
            box = {}

            def on_finish(result, error):
                assert error is None, error
                box["r"] = result
                return None

            runner.dispatch_pipelined([e for _, e in engine_ops], on_finish)
            runner.finish_pending()
            res = box["r"]
            for out in res.outcomes:
                tg = next(tg for tg, e in engine_ops if e is out.op)
                statuses[tg] = (out.status, out.remaining)
            for f in res.storage_fills:
                fills.append((oid_tag[f.order_id],
                              oid_tag[f.counter_order_id],
                              f.price_q4, f.quantity))
    return _surface(runners, router, oid_tag, statuses, fills, rejected)


def drive_native(cfg: EngineConfig, K: int, stream,
                 shard_devices: str | None = None) -> dict:
    """Same stream through K C++ lane engines (dispatch_records)."""
    from matching_engine_tpu.server.native_lanes import pack_record_batch
    from matching_engine_tpu.server.shards import parse_shard_devices

    router = ShardRouter(K)
    hub = StreamHub()
    placement = parse_shard_devices(shard_devices, K)
    runners = [make_lane_runner(cfg, router, i, hub=hub, native_lanes=True,
                                device=placement[i])
               for i in range(K)]
    tag_oid: dict[int, str] = {}
    oid_tag: dict[str, str] = {}
    tag_sym: dict[int, str] = {}
    statuses: dict[int, tuple] = {}
    fills = []
    rejected: dict[int, str] = {}

    for ops in stream:
        per_lane: dict[int, list] = {}
        for op in ops:
            if op[0] == "submit":
                lane = router.shard_of(op[2])
                tag_sym[op[1]] = op[2]
            else:
                target = tag_oid.get(op[2])
                if target is None:
                    rejected[op[1]] = "unknown order id"
                    continue
                lane = router.shard_of(tag_sym[op[2]])
            per_lane.setdefault(lane, []).append(op)
        for lane, lops in per_lane.items():
            runner = runners[lane]
            recs = []
            for op in lops:
                if op[0] == "submit":
                    _, tg, sym, cid, side, otype, price, qty = op
                    recs.append((tg, 1, side, otype, price, qty, sym, cid,
                                 ""))
                elif op[0] == "cancel":
                    _, tg, tt, cid = op
                    recs.append((tg, 2, 0, 0, 0, 0, "", cid, tag_oid[tt]))
                else:
                    _, tg, tt, cid, qty = op
                    recs.append((tg, 3, 0, 0, 0, qty, "", cid, tag_oid[tt]))
            buf, n = pack_record_batch(recs)
            box = {}

            def on_finish(result, error):
                assert error is None, error
                box["r"] = result
                return None

            runner.dispatch_records(buf, n, on_finish)
            runner.finish_pending()
            r = box["r"]
            for (tg, kind, ok, oid, err) in me_native.parse_comp_buf(
                    r.comp_buf):
                if kind == 0 and ok:
                    tag_oid[tg] = oid
                    oid_tag[oid] = tg
                    statuses[tg] = ("accepted",)
                elif not ok:
                    rejected.setdefault(tg, err)
            _, _, store_fills = me_native.unpack_store_buf(r.store_buf)
            for f in store_fills:
                fills.append((oid_tag[f.order_id],
                              oid_tag[f.counter_order_id],
                              f.price_q4, f.quantity))
    return _surface(runners, router, oid_tag, statuses, fills, rejected)


def _surface(runners, router, oid_tag, statuses, fills, rejected) -> dict:
    """The shard-count-invariant observable surface, keyed per symbol:
    fills in stream order, the final priority-sorted books, and the
    reject set — ids normalized to tags."""
    books = {}
    for s in range(SYMS):
        sym = f"S{s}"
        runner = runners[router.shard_of(sym)]
        bids, asks = runner.book_snapshot(sym)
        books[sym] = (
            [(oid_tag[i.order_id], i.price_q4, q) for i, q in bids],
            [(oid_tag[i.order_id], i.price_q4, q) for i, q in asks],
        )
    return {"books": books, "fills": list(fills), "rejected": rejected}


@pytest.mark.parametrize("seed", [3, 11])
def test_shard_parity_python(seed):
    cfg1 = make_cfg()
    # K=4 lanes each get SYMS // 4 rows — same global capacity.
    one = drive_python(cfg1, 1, gen_stream(seed))
    four = drive_python(make_cfg(), 4, gen_stream(seed))
    assert one["books"] == four["books"]
    assert sorted(one["fills"]) == sorted(four["fills"])
    assert one["rejected"].keys() == four["rejected"].keys()


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
@pytest.mark.parametrize("seed", [3, 11])
def test_shard_parity_native(seed):
    one = drive_native(make_cfg(), 1, gen_stream(seed))
    four = drive_native(make_cfg(), 4, gen_stream(seed))
    assert one["books"] == four["books"]
    assert sorted(one["fills"]) == sorted(four["fills"])


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_shard_parity_python_vs_native():
    """The cross-path diagonal: K=4 native == K=4 python, per symbol."""
    py = drive_python(make_cfg(), 4, gen_stream(7))
    nat = drive_native(make_cfg(), 4, gen_stream(7))
    assert py["books"] == nat["books"]
    assert sorted(py["fills"]) == sorted(nat["fills"])


# -- concurrent-lane feed: per-key seq lines stay gapless --------------------


def test_concurrent_lane_publish_keeps_per_key_seq_gapless():
    """K dispatcher threads publishing into ONE sequenced hub at once:
    every (channel, key) domain's seq line must come out dense (1..n) —
    the cross-lane fan-in invariant the sharded feed rests on."""
    from matching_engine_tpu.feed import FeedSequencer
    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.utils.metrics import Metrics

    metrics = Metrics()
    hub = StreamHub(maxsize=100_000, metrics=metrics,
                    sequencer=FeedSequencer(metrics=metrics))
    clients = [f"c{i}" for i in range(4)]
    subs = {c: hub.subscribe_order_updates(c) for c in clients}
    K, per_lane = 4, 300

    def lane(i):
        for j in range(per_lane):
            # Every lane publishes to EVERY client key: order-update
            # domains are client-keyed and clients trade on all lanes.
            hub.publish_order_updates([
                pb2.OrderUpdate(order_id=f"OID-{1 + i + 4 * j}",
                                client_id=c, symbol=f"S{i}", status=0)
                for c in clients])

    threads = [threading.Thread(target=lane, args=(i,)) for i in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    hub.close_all()
    for c in clients:
        # Drain the subscription queue directly (no consumer thread ran).
        seqs = []
        while True:
            try:
                _, item = subs[c].q.get_nowait()
            except Exception:
                break
            if hasattr(item, "seq"):
                seqs.append(item.seq)
        assert len(seqs) == K * per_lane
        assert seqs == sorted(seqs), f"{c}: out-of-order seqs"
        assert seqs == list(range(1, K * per_lane + 1)), \
            f"{c}: seq line has gaps"


# -- full-stack e2e ----------------------------------------------------------


@pytest.mark.slow
def test_sharded_server_e2e_and_recount_restart(tmp_path):
    """Boot K=4, trade across lanes, restart the SAME store at K=2:
    resting orders recover onto their symbol's new lane, the OID line
    stays globally unique across both boots, and cancels route to
    recovered orders whose id residue no longer matches their lane."""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    db = str(tmp_path / "db.sqlite")
    cfg = EngineConfig(num_symbols=16, capacity=32, batch=4,
                       max_fills=1 << 12)
    server, port, parts = build_server(
        "127.0.0.1:0", db, cfg, window_ms=1, log=False, native=False,
        serve_shards=4)
    server.start()
    stub = MatchingEngineStub(
        grpc.insecure_channel(f"127.0.0.1:{port}"))
    oids = []
    for i in range(24):
        r = stub.SubmitOrder(pb2.OrderRequest(
            client_id=f"c{i % 3}", symbol=f"SYM{i % 6}", side=1 + i % 2,
            order_type=pb2.LIMIT, price=10_000 + 40 * (i % 3) * (1 if i % 2 else -1),
            scale=4, quantity=5))
        assert r.success, r.error_message
        oids.append(r.order_id)
    assert len(set(oids)) == len(oids)
    lanes_used = {(int(o[4:]) - 1) % 4 for o in oids}
    assert len(lanes_used) > 1, "stream never spread across lanes"
    book = stub.GetOrderBook(pb2.OrderBookRequest(symbol="SYM0"))
    resting = {o.order_id for o in list(book.bids) + list(book.asks)}
    shutdown(server, parts)

    # Restart at K=2 over the same store.
    server2, port2, parts2 = build_server(
        "127.0.0.1:0", db, cfg, window_ms=1, log=False, native=False,
        serve_shards=2)
    server2.start()
    stub2 = MatchingEngineStub(
        grpc.insecure_channel(f"127.0.0.1:{port2}"))
    book2 = stub2.GetOrderBook(pb2.OrderBookRequest(symbol="SYM0"))
    resting2 = {o.order_id for o in list(book2.bids) + list(book2.asks)}
    assert resting == resting2, "restart at a new K lost resting orders"
    # A recovered order cancels through the probe even when its K=4-era
    # residue points at the wrong K=2 lane.
    victim = sorted(resting2)[0]
    owner = next(o for o in list(book2.bids) + list(book2.asks)
                 if o.order_id == victim).client_id
    c = stub2.CancelOrder(pb2.CancelRequest(client_id=owner,
                                            order_id=victim))
    assert c.success, c.error_message
    new = stub2.SubmitOrder(pb2.OrderRequest(
        client_id="cx", symbol="SYM7", side=1, order_type=pb2.LIMIT,
        price=9_000, scale=4, quantity=1))
    assert new.success
    assert new.order_id not in set(oids), "OID line reused across boots"
    shutdown(server2, parts2)


def test_lane_sampler_gauges():
    """The balance sampler publishes the documented me_lane_* aggregates
    plus the per-shard series."""
    from matching_engine_tpu.server.shards import build_serving_shards
    from matching_engine_tpu.utils.metrics import Metrics

    metrics = Metrics()
    shards = build_serving_shards(
        make_cfg(), 2, metrics=metrics, with_dispatchers=False,
        sample_interval_s=0)  # no thread; tick by hand
    shards.lanes[0].runner.ops_dispatched = 30
    shards.lanes[1].runner.ops_dispatched = 10
    shards._sample_once([0, 0], 0.0)
    _, gauges = metrics.snapshot()
    assert "lane_queue_depth_max" in gauges
    assert gauges["lane_dispatch_rate"] > 0
    assert gauges["lane_imbalance"] >= 1.0
    assert "lane0_ops_per_s" in gauges and "lane1_ops_per_s" in gauges
    shards.close()


@pytest.mark.slow
def test_proportional_recut_restore_guard(tmp_path, capfd):
    """--symbols 16 --serve-shards 2 → --symbols 32 --serve-shards 4:
    per-lane checkpoint shapes MATCH (8 symbols each) so restore_runner's
    semantic-key/slice guards pass, but the K=2 snapshots cover a
    COARSER cut — K=4 lane 0 would inherit crc32%2==0 symbols including
    the crc32%4==2 ones that now home on lane 2. The foreign-symbol
    guard must force full replay instead of restoring another cut's
    books onto the wrong lane. (The halving direction needs no guard:
    crc32 residue classes NEST when the new K divides the old, so every
    restored symbol stays owned.)"""
    import grpc

    from matching_engine_tpu.proto import pb2
    from matching_engine_tpu.proto.rpc import MatchingEngineStub
    from matching_engine_tpu.server.main import build_server, shutdown

    db = str(tmp_path / "db.sqlite")
    ckpts = str(tmp_path / "ckpts")
    cfg2 = EngineConfig(num_symbols=16, capacity=16, batch=4,
                        max_fills=1 << 12)
    server, port, parts = build_server(
        "127.0.0.1:0", db, cfg2, window_ms=1, log=False, native=False,
        serve_shards=2, checkpoint_dir=ckpts, checkpoint_interval_s=3600)
    server.start()
    stub = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port}"))
    resting = {}
    for i in range(16):
        r = stub.SubmitOrder(pb2.OrderRequest(
            client_id="c0", symbol=f"SYM{i % 8}", side=1,
            order_type=pb2.LIMIT, price=100 + i, scale=4, quantity=2))
        assert r.success
        resting.setdefault(f"SYM{i % 8}", set()).add(r.order_id)
    shutdown(server, parts)  # final checkpoint per lane
    # The fuzz namespace must actually straddle the finer cut, or the
    # guard has nothing to prove.
    r4 = ShardRouter(4)
    assert len({r4.shard_of(s) for s in resting}) > 2

    cfg4 = EngineConfig(num_symbols=32, capacity=16, batch=4,
                        max_fills=1 << 12)
    server2, port2, parts2 = build_server(
        "127.0.0.1:0", db, cfg4, window_ms=1, log=False, native=False,
        serve_shards=4, checkpoint_dir=ckpts)
    out = capfd.readouterr().out
    assert "outside this lane's shard cut" in out, \
        "foreign-symbol restore guard never fired"
    server2.start()
    stub2 = MatchingEngineStub(grpc.insecure_channel(f"127.0.0.1:{port2}"))
    for sym, ids in resting.items():
        book = stub2.GetOrderBook(pb2.OrderBookRequest(symbol=sym))
        got = {o.order_id for o in list(book.bids) + list(book.asks)}
        assert got == ids, f"{sym}: {got} != {ids}"
    shutdown(server2, parts2)
