"""Sequenced feed tests (matching_engine_tpu/feed/).

Layers under test:
- unit: FeedSequencer seq-domain monotonicity, RetransmissionRing
  bounds + miss accounting, disk spill (atomic segments) extending the
  replay window, conflated latest-state subscriptions, and the
  stream_dropped_events legacy-drop counter.
- e2e (python path): a real server — sequenced streams, reconnect with
  resume_from_seq replaying a bit-identical missed range (verified
  against the retransmission store), fault-injected slow subscriber
  recovering through client-side gap-fill (zero-gaps-or-all-recovered
  invariant), conflated snapshots for a slow L2 consumer with the
  feed counters visible in Prometheus exposition, and the `subscribe`
  CLI verb's summary/exit contract.
- e2e (--native-lanes): the same resume/bit-identity assertion through
  the C++ lane path (skip-guarded on the built native runtime).
"""

import json
import threading
import time

import grpc
import pytest

from matching_engine_tpu import native as me_native
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.feed import CHANNEL_MD, CHANNEL_OU, FeedSequencer
from matching_engine_tpu.feed.client import SequencedSubscriber
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.server.streams import StreamHub
from matching_engine_tpu.utils.metrics import Metrics
from matching_engine_tpu.utils.obs import render_prometheus

CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)


def md(symbol="SYM", bid=10000, n=1):
    return [pb2.MarketDataUpdate(symbol=symbol, best_bid=bid + i, scale=4,
                                 bid_size=1) for i in range(n)]


# -- unit: sequencer + retransmission store ----------------------------------


def test_sequencer_stamps_monotonic_per_domain():
    s = FeedSequencer(depth=64)
    a, b = md("AAA", n=3), md("BBB", n=2)
    s.stamp_market_data(a + b)
    assert [u.seq for u in a] == [1, 2, 3]
    assert [u.seq for u in b] == [1, 2]   # independent domain per symbol
    ou = [pb2.OrderUpdate(order_id=f"OID-{i}", client_id="c1")
          for i in range(2)]
    s.stamp_order_updates(ou)
    assert [u.seq for u in ou] == [1, 2]  # ou domain independent of md
    assert s.last_seq(CHANNEL_MD, "AAA") == 3
    assert s.last_seq(CHANNEL_OU, "c1") == 2
    assert s.last_seq(CHANNEL_MD, "NOPE") == 0


def test_replay_range_bounds_and_miss_accounting():
    m = Metrics()
    s = FeedSequencer(metrics=m, depth=4)
    updates = md(n=10)
    s.stamp_market_data(updates)
    # Window holds the newest 4 (seq 7..10); 1..6 are gone (no spill).
    events, missed = s.replay(CHANNEL_MD, "SYM", 0)
    assert [e.seq for e in events] == [7, 8, 9, 10] and missed == 6
    # Fully-covered range: exact, oldest-first, bit-identical objects.
    events, missed = s.replay(CHANNEL_MD, "SYM", 7, to_seq=9)
    assert [e.seq for e in events] == [8, 9] and missed == 0
    assert [e.SerializeToString() for e in events] == \
        [u.SerializeToString() for u in updates[7:9]]
    counters, _ = m.snapshot()
    assert counters["feed_retransmit_requests"] == 2
    assert counters["feed_retransmit_misses"] == 6
    assert counters["feed_retransmit_events"] == 6
    # Unknown domain: empty, not an error.
    assert s.replay(CHANNEL_OU, "nobody", 0) == ([], 0)


def test_spill_extends_replay_window_bit_identically(tmp_path):
    m = Metrics()
    s = FeedSequencer(metrics=m, depth=4, spill_dir=str(tmp_path / "spill"),
                      spill_segment=3)
    updates = md(n=12)
    for u in updates:          # one-by-one: exercises eviction per append
        s.stamp_market_data([u])
    s.flush_spill()
    events, missed = s.replay(CHANNEL_MD, "SYM", 0)
    assert missed == 0
    assert [e.seq for e in events] == list(range(1, 13))
    # Bit-identical across the memory/disk seam.
    assert [e.SerializeToString() for e in events] == \
        [u.SerializeToString() for u in updates]
    segs = list((tmp_path / "spill").rglob("seg_*.json"))
    assert segs, "evictions produced no spill segments"
    assert not list((tmp_path / "spill").rglob(".seg-tmp-*")), \
        "spill left non-atomic temp files"
    counters, _ = m.snapshot()
    assert counters["feed_spilled_events"] >= 6


def test_spill_epochs_do_not_leak_across_restarts(tmp_path):
    """Seq domains restart at 1 per boot: a new sequencer on the same
    spill dir must purge the old epoch's segments, never serve them as
    the new epoch's seq range."""
    spill = str(tmp_path / "spill")
    s1 = FeedSequencer(depth=2, spill_dir=spill, spill_segment=2)
    s1.stamp_market_data(md(bid=10_000, n=8))
    s1.flush_spill()
    assert list((tmp_path / "spill").rglob("seg_*.json"))
    # "Restart": fresh sequencer, same dir, new epoch with FEWER events.
    s2 = FeedSequencer(depth=2, spill_dir=spill, spill_segment=2)
    s2.stamp_market_data(md(bid=20_000, n=4))
    s2.flush_spill()
    events, missed = s2.replay(CHANNEL_MD, "SYM", 0)
    assert [e.seq for e in events] == [1, 2, 3, 4] and missed == 0
    assert all(e.best_bid >= 20_000 for e in events), \
        "replay served the previous boot's payloads"
    epochs = [p.name for p in (tmp_path / "spill").iterdir()
              if p.name.startswith("epoch-")]
    assert len(epochs) == 1, f"stale epoch dirs survived: {epochs}"


def test_stale_resume_cursor_is_an_epoch_rebase(tmp_path):
    """A resume_from_seq ahead of the current head (client outlived a
    server restart) must serve live events from the new epoch — and the
    client reports a rebase — instead of filtering everything below the
    stale cursor into silence."""
    hs = Harness(str(tmp_path / "rebase.db"))
    try:
        rebases = []
        feed = SequencedSubscriber(
            hs.stub, CHANNEL_MD, "SYM", from_seq=50_000,
            on_rebase=lambda cur, seq: rebases.append((cur, seq)))
        seen = []

        def consume():
            for u in feed:
                seen.append(u.seq)
                if len(seen) >= 3:
                    feed.cancel()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        hs.wait_md_sub()
        for i in range(3):
            submit(hs.stub, price=10000 + i)
        t.join(timeout=15)
        assert not t.is_alive(), "stale-cursor subscriber got nothing"
        assert seen == [1, 2, 3]
        assert feed.epoch_rebases == 1 and rebases == [(50_000, 1)]
        assert feed.unrecovered_events == 0 and feed.gaps_detected == 0
    finally:
        hs.close()


def test_domain_lru_retire_preserves_seq_line():
    """Past max_domains, the least-recently-published domain retires:
    ring memory is freed, but a revived key CONTINUES its seq line (a
    reused seq would corrupt client gap accounting)."""
    m = Metrics()
    s = FeedSequencer(metrics=m, depth=64, max_domains=2)
    s.stamp_market_data(md("AAA", n=3))
    s.stamp_market_data(md("BBB", n=2))
    s.stamp_market_data(md("CCC", n=1))   # retires AAA (LRU)
    counters, _ = m.snapshot()
    assert counters["feed_domains_retired"] == 1
    assert len(s._domains) == 2
    # Retired head still reported; its replay window is gone (a miss).
    assert s.last_seq(CHANNEL_MD, "AAA") == 3
    events, missed = s.replay(CHANNEL_MD, "AAA", 0)
    assert events == [] and missed == 3
    # Revival continues the line at 4 — never back to 1.
    revived = md("AAA", n=1)
    s.stamp_market_data(revived)
    assert revived[0].seq == 4
    assert s.last_seq(CHANNEL_MD, "AAA") == 4


def test_events_carry_boot_epoch_and_mismatch_rebases(tmp_path):
    """feed_epoch closes the undetectable-rebase hole: a resume whose
    cursor is WITHIN the new boot's head but from another epoch must be
    served live (no wrong-epoch replay) and reported as a rebase."""
    hs = Harness(str(tmp_path / "epoch.db"))
    try:
        seqr = hs.parts["sequencer"]
        for i in range(5):
            submit(hs.stub, price=10000 + i)
        events, _ = seqr.replay(CHANNEL_MD, "SYM", 0)
        assert events and all(e.feed_epoch == seqr.epoch for e in events)
        # Stale cursor 2 <= head 5, but from a different epoch.
        rebases = []
        feed = SequencedSubscriber(
            hs.stub, CHANNEL_MD, "SYM", from_seq=2, epoch=seqr.epoch + 1,
            on_rebase=lambda cur, seq: rebases.append((cur, seq)))
        seen = []

        def consume():
            for u in feed:
                seen.append(u.seq)
                feed.cancel()
                return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        hs.wait_md_sub()
        submit(hs.stub, price=10100)
        t.join(timeout=15)
        assert not t.is_alive()
        # Live event 6, NOT a replay of the (would-be wrong-epoch) 3..5.
        assert seen == [6]
        assert feed.epoch_rebases == 1 and rebases == [(2, 6)]
        assert feed.epoch == seqr.epoch  # cursor re-homed to the new boot
        assert feed.unrecovered_events == 0 and feed.gaps_detected == 0
    finally:
        hs.close()


def test_hub_counts_legacy_drops_and_sequences_events():
    m = Metrics()
    hub = StreamHub(maxsize=4, metrics=m,
                    sequencer=FeedSequencer(metrics=m, depth=64))
    sub = hub.subscribe_market_data("SYM")
    hub.publish_market_data(md(n=10))
    counters, gauges = m.snapshot()
    assert counters["stream_dropped_events"] == 6  # drop-oldest, visible
    assert counters["feed_md_published"] == 10
    assert gauges["feed_publish_seq"] == 10
    # The queue retains the NEWEST 4 (the close sentinel evicts one more);
    # the store still replays everything that was dropped.
    hub.close_all()
    got = [u for u in sub.stream()]
    assert [u.seq for u in got] == [8, 9, 10]
    counters, _ = m.snapshot()
    assert counters["stream_dropped_events"] == 7
    events, missed = hub.sequencer.replay(CHANNEL_MD, "SYM", 0, to_seq=7)
    assert [e.seq for e in events] == [1, 2, 3, 4, 5, 6, 7] and missed == 0


def test_conflated_subscription_yields_latest_state():
    m = Metrics()
    hub = StreamHub(maxsize=256, metrics=m,
                    sequencer=FeedSequencer(metrics=m, depth=64))
    sub = hub.subscribe_market_data("SYM", conflate=True)
    hub.publish_market_data(md(n=50))
    hub.close_all()
    got = list(sub.stream())
    assert got, "conflated channel delivered nothing"
    assert got[-1].seq == 50          # newest state always survives
    assert len(got) <= 2              # backlog conflated away, not queued
    counters, _ = m.snapshot()
    assert counters["feed_conflated_events"] >= 48
    assert counters.get("stream_dropped_events", 0) == 0  # not drops


def test_subscriber_lag_gauge_tracks_worst_consumer():
    m = Metrics()
    hub = StreamHub(maxsize=512, metrics=m,
                    sequencer=FeedSequencer(metrics=m, depth=64))
    hub.publish_market_data(md(n=5))      # pre-attach history
    sub = hub.subscribe_market_data("SYM")
    hub.publish_market_data(md(n=7))
    _, gauges = m.snapshot()
    # Attached at seq 5, consumed nothing, head now 12 -> lag 7.
    assert gauges["feed_subscriber_lag_max"] == 7
    hub.close_all()
    list(sub.stream())


# -- e2e ---------------------------------------------------------------------


class Harness:
    def __init__(self, db_path, **kw):
        kw.setdefault("window_ms", 1.0)
        kw.setdefault("log", False)
        self.server, self.port, self.parts = build_server(
            "127.0.0.1:0", db_path, CFG, **kw)
        self.server.start()
        self.addr = f"127.0.0.1:{self.port}"
        self.channel = grpc.insecure_channel(self.addr)
        self.stub = MatchingEngineStub(self.channel)

    def wait_md_sub(self, timeout=5.0):
        hub = self.parts["hub"]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if hub._md_subs:
                return
            time.sleep(0.01)
        raise AssertionError("subscription never registered")

    def close(self):
        self.channel.close()
        shutdown(self.server, self.parts)


def submit(stub, client="c1", symbol="SYM", side=pb2.BUY, price=10000, qty=5):
    r = stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol=symbol,
                         order_type=pb2.LIMIT, side=side, price=price,
                         scale=4, quantity=qty), timeout=10)
    assert r.success, r.error_message
    return r


def _collect(stub, symbol, n, out, resume_from=0, conflate=False):
    """Read n MD events on a thread; out gets the call first (cancelable)."""
    call = stub.StreamMarketData(pb2.MarketDataRequest(
        symbol=symbol, resume_from_seq=resume_from, conflate=conflate))
    out.append(call)

    def run():
        try:
            for u in call:
                out.append(u)
                if len([x for x in out[1:]]) >= n:
                    return
        except grpc.RpcError:
            pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _resume_and_verify(hs, last_seen):
    """Reconnect with resume_from_seq and assert the replayed range is
    bit-identical to the retransmission store (acceptance criterion)."""
    seq = hs.parts["sequencer"]
    head = seq.last_seq(CHANNEL_MD, "SYM")
    assert head > last_seen, "no missed traffic to recover"
    call = hs.stub.StreamMarketData(pb2.MarketDataRequest(
        symbol="SYM", resume_from_seq=last_seen), timeout=10)
    got = []
    try:
        for u in call:
            got.append(u)
            if u.seq >= head:
                break
    finally:
        call.cancel()
    assert [u.seq for u in got] == list(range(last_seen + 1, head + 1))
    stored, missed = seq.replay(CHANNEL_MD, "SYM", last_seen, to_seq=head)
    assert missed == 0
    assert [u.SerializeToString() for u in got] == \
        [e.SerializeToString() for e in stored], \
        "replayed range is not bit-identical to the retransmission store"


def test_e2e_sequenced_stream_and_resume_replay(tmp_path):
    hs = Harness(str(tmp_path / "feed.db"))
    try:
        out = []
        _collect(hs.stub, "SYM", 3, out)
        hs.wait_md_sub()
        for i in range(3):
            submit(hs.stub, price=10000 + i)
        deadline = time.monotonic() + 10
        while len(out) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        events = out[1:]
        assert [u.seq for u in events] == [1, 2, 3], \
            "live events are not densely sequenced from 1"
        out[0].cancel()  # subscriber dies mid-traffic
        for i in range(4):
            submit(hs.stub, price=10100 + i)
        # ... and reconnects: the missed range replays exactly.
        _resume_and_verify(hs, last_seen=3)
    finally:
        hs.close()


def test_e2e_slow_subscriber_gap_fill_integrity(tmp_path):
    """Fault injection: the subscriber stalls while the feed bursts far
    past its queue, then consumes through SequencedSubscriber. The
    invariant (either zero gaps, or every gap detected AND gap-filled)
    must hold regardless of how much the transport buffered."""
    hs = Harness(str(tmp_path / "gap.db"), stream_maxsize=8,
                 feed_depth=1 << 15)
    try:
        hub, metrics = hs.parts["hub"], hs.parts["metrics"]
        gaps = []
        feed = SequencedSubscriber(
            hs.stub, CHANNEL_MD, "SYM",
            on_gap=lambda s, e, filled, missing: gaps.append(
                (s, e, filled, missing)))
        seen = []
        stall = threading.Event()

        def consume():
            for u in feed:
                seen.append(u.seq)
                stall.wait()  # stalled until the burst is over
                if u.seq >= 20_000:
                    feed.cancel()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        hs.wait_md_sub()
        # Burst 20k tiny events through the hub's publish path (the same
        # entry the dispatch loops use) — far past the 8-slot queue and
        # any transport buffering.
        for base in range(0, 20_000, 500):
            hub.publish_market_data(md(bid=base, n=500))
        stall.set()
        t.join(timeout=60)
        assert not t.is_alive(), "consumer wedged"
        assert feed.last_seq == 20_000
        assert feed.unrecovered_events == 0, \
            f"lost events for good: {feed.unrecovered_events}"
        assert seen == sorted(seen) and len(set(seen)) == len(seen)
        assert seen == list(range(seen[0], 20_001)), \
            "delivered range is not contiguous after gap-fill"
        counters, _ = metrics.snapshot()
        if feed.gaps_detected:  # drops happened: recovery must show up
            assert counters["stream_dropped_events"] > 0
            assert counters["feed_retransmit_events"] > 0
            assert all(missing == 0 for *_x, missing in gaps)
    finally:
        hs.close()


def test_e2e_conflated_snapshots_for_slow_consumer(tmp_path):
    hs = Harness(str(tmp_path / "confl.db"), stream_maxsize=64)
    try:
        hub, metrics = hs.parts["hub"], hs.parts["metrics"]
        feed = SequencedSubscriber(hs.stub, CHANNEL_MD, "SYM",
                                   conflate=True)
        seen = []
        stall = threading.Event()

        def consume():
            for u in feed:
                seen.append(u)
                stall.wait()
                if u.seq >= 5_000:
                    feed.cancel()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        hs.wait_md_sub()
        for base in range(0, 5_000, 500):
            hub.publish_market_data(md(bid=base, n=500))
        stall.set()
        t.join(timeout=30)
        assert not t.is_alive(), "conflated consumer wedged"
        # Latest state arrived; the backlog did not.
        assert seen[-1].seq == 5_000
        assert seen[-1].best_bid == 4_999
        assert len(seen) < 1_000, "conflation never engaged"
        assert feed.unrecovered_events == 0 and feed.gaps_detected == 0
        counters, _ = metrics.snapshot()
        assert counters["feed_conflated_events"] > 0
        # The feed counters are on the Prometheus surface (/metrics body).
        prom = render_prometheus(metrics)
        for name in ("me_feed_conflated_events_total",
                     "me_feed_md_published_total",
                     "me_stream_dropped_events_total",
                     "me_feed_publish_seq",
                     "me_feed_subscriber_lag_max"):
            if name.endswith("_total") and "dropped" in name:
                continue  # drops may legitimately be zero here
            assert name in prom, f"{name} missing from /metrics"
    finally:
        hs.close()


def test_e2e_order_update_channel_sequenced(tmp_path):
    hs = Harness(str(tmp_path / "ou.db"))
    try:
        feed = SequencedSubscriber(hs.stub, CHANNEL_OU, "maker")
        seen = []

        def consume():
            for u in feed:
                seen.append(u)
                if len(seen) >= 2:
                    feed.cancel()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while not hs.parts["hub"]._ou_subs and time.monotonic() < deadline:
            time.sleep(0.01)
        submit(hs.stub, client="maker", side=pb2.SELL, price=10000, qty=5)
        submit(hs.stub, client="taker", side=pb2.BUY, price=10000, qty=2)
        t.join(timeout=10)
        assert not t.is_alive()
        assert [u.seq for u in seen] == [1, 2]
        assert seen[1].status == pb2.OrderUpdate.Status.PARTIALLY_FILLED
    finally:
        hs.close()


def test_e2e_feed_disabled_serves_unsequenced_streams(tmp_path):
    """--feed-depth 0: the legacy contract — seq stays 0, resume_from_seq
    is ignored, streams still deliver."""
    hs = Harness(str(tmp_path / "off.db"), feed_depth=0)
    try:
        assert hs.parts["sequencer"] is None
        out = []
        _collect(hs.stub, "SYM", 1, out, resume_from=99)
        hs.wait_md_sub()
        submit(hs.stub)
        deadline = time.monotonic() + 10
        while len(out) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(out) >= 2 and out[1].seq == 0
        out[0].cancel()
    finally:
        hs.close()


def test_cli_subscribe_verb_summary_and_exit(tmp_path, capsys):
    from matching_engine_tpu.client import cli

    hs = Harness(str(tmp_path / "cli.db"))
    try:
        summary_path = tmp_path / "summary.json"
        rc = {}

        def run():
            rc["v"] = cli.main([
                "subscribe", hs.addr, "md", "SYM", "--max-events", "3",
                "--idle-exit", "30", "--summary-json", str(summary_path)])

        t = threading.Thread(target=run, daemon=True)
        t.start()
        hs.wait_md_sub()
        for i in range(3):
            submit(hs.stub, price=10000 + i)
        t.join(timeout=20)
        assert not t.is_alive(), "subscribe verb never exited"
        assert rc["v"] == 0
        doc = json.loads(summary_path.read_text())
        assert doc["events"] == 3 and doc["last_seq"] == 3
        assert doc["unrecovered_events"] == 0
    finally:
        hs.close()


@pytest.mark.skipif(not me_native.available(),
                    reason="native runtime not built")
def test_e2e_native_lanes_resume_replay(tmp_path):
    """The acceptance e2e on the C++ lane path: disconnect mid-traffic,
    reconnect with resume_from_seq, bit-identical replayed range."""
    hs = Harness(str(tmp_path / "lanes.db"), native_lanes=True)
    try:
        out = []
        _collect(hs.stub, "SYM", 2, out)
        hs.wait_md_sub()
        for i in range(2):
            submit(hs.stub, price=10000 + i)
        deadline = time.monotonic() + 10
        while len(out) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        events = out[1:]
        assert [u.seq for u in events[:2]] == [1, 2]
        out[0].cancel()
        for i in range(3):
            submit(hs.stub, price=10200 + i)
        _resume_and_verify(hs, last_seen=2)
        counters, _ = hs.parts["metrics"].snapshot()
        assert counters["feed_md_published"] >= 5
        assert counters["feed_retransmit_events"] >= 3
    finally:
        hs.close()
