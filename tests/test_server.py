"""Server integration tests: real gRPC in, out-of-band SQLite asserts.

The reference's main correctness oracle (SURVEY.md §4: tests/test_submit_order.cpp)
— a real in-process server on an OS-assigned loopback port, a real temp
SQLite file, behavior verified by querying the DB independently — extended
to the paths the reference never tested: matching, rejects, MARKET orders,
cancels, book queries, streams, restart recovery.
"""

import threading

import grpc
import pytest

from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.server.main import build_server, shutdown
from matching_engine_tpu.storage import Storage


CFG = EngineConfig(num_symbols=8, capacity=16, batch=4)


class Harness:
    def __init__(self, db_path, cfg=CFG):
        self.db_path = db_path
        self.server, self.port, self.parts = build_server(
            "127.0.0.1:0", db_path, cfg, window_ms=1.0, log=False
        )
        self.server.start()
        self.channel = grpc.insecure_channel(f"127.0.0.1:{self.port}")
        self.stub = MatchingEngineStub(self.channel)

    def flush(self):
        self.parts["sink"].flush()

    def close(self):
        self.channel.close()
        shutdown(self.server, self.parts)


@pytest.fixture
def hs(tmp_path):
    h = Harness(str(tmp_path / "it.db"))
    yield h
    h.close()


def submit(stub, client="c1", symbol="SYM", otype=pb2.LIMIT, side=pb2.BUY,
           price=10000, scale=4, qty=5):
    return stub.SubmitOrder(
        pb2.OrderRequest(client_id=client, symbol=symbol, order_type=otype,
                         side=side, price=price, scale=scale, quantity=qty),
        timeout=10,
    )


def test_submit_normalizes_and_persists(hs):
    # The reference integration oracle: scale-8 price 10000 -> stored Q4 1.
    resp = submit(hs.stub, price=10000, scale=8, qty=3)
    assert resp.success and resp.order_id.startswith("OID-")
    hs.flush()
    row = Storage(hs.db_path).get_order(resp.order_id)
    assert row is not None
    assert row[5] == 1          # price, Q4-normalized
    assert row[7] == 3          # remaining
    assert row[8] == 0          # status NEW


def test_validation_rejects_are_application_level(hs):
    # gRPC status stays OK; success=false + message (reference semantics).
    r = submit(hs.stub, symbol="")
    assert not r.success and "symbol" in r.error_message
    r = submit(hs.stub, qty=0)
    assert not r.success and "quantity" in r.error_message
    r = submit(hs.stub, price=0)
    assert not r.success and "price" in r.error_message


def test_matching_end_to_end_with_fills_in_db(hs):
    s = submit(hs.stub, client="maker", side=pb2.SELL, price=10000, qty=5)
    b = submit(hs.stub, client="taker", side=pb2.BUY, price=10100, qty=5)
    assert s.success and b.success
    hs.flush()
    st = Storage(hs.db_path)
    maker = st.get_order(s.order_id)
    taker = st.get_order(b.order_id)
    assert maker[8] == 2 and maker[7] == 0   # FILLED, remaining 0
    assert taker[8] == 2 and taker[7] == 0
    fills = st.fills_for_order(b.order_id)   # taker is the aggressor row
    assert len(fills) == 1
    assert fills[0][1] == s.order_id and fills[0][2] == 10000 and fills[0][3] == 5


def test_market_order_null_price_and_cancel_status(hs):
    r = submit(hs.stub, otype=pb2.MARKET, price=0, qty=4)
    assert r.success
    hs.flush()
    row = Storage(hs.db_path).get_order(r.order_id)
    assert row[5] is None       # MARKET stores NULL price
    assert row[8] == 3          # CANCELED (no liquidity, IOC remainder)


def test_get_order_book_snapshot(hs):
    submit(hs.stub, side=pb2.BUY, price=10000, qty=5)
    submit(hs.stub, side=pb2.BUY, price=10100, qty=2)
    submit(hs.stub, side=pb2.SELL, price=10300, qty=7)
    book = hs.stub.GetOrderBook(pb2.OrderBookRequest(symbol="SYM"), timeout=10)
    assert [(o.price, o.quantity) for o in book.bids] == [(10100, 2), (10000, 5)]
    assert [(o.price, o.quantity) for o in book.asks] == [(10300, 7)]
    # Unknown symbol: empty book, OK status (reference stub returned OK too).
    empty = hs.stub.GetOrderBook(pb2.OrderBookRequest(symbol="NOPE"), timeout=10)
    assert not empty.bids and not empty.asks


def test_cancel_rpc(hs):
    r = submit(hs.stub, client="c1", price=10000, qty=5)
    c = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="c1", order_id=r.order_id), timeout=10
    )
    assert c.success
    hs.flush()
    assert Storage(hs.db_path).get_order(r.order_id)[8] == 3  # CANCELED
    # wrong client
    r2 = submit(hs.stub, client="c1", price=10000, qty=5)
    c2 = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="evil", order_id=r2.order_id), timeout=10
    )
    assert not c2.success and "different client" in c2.error_message
    # unknown id
    c3 = hs.stub.CancelOrder(
        pb2.CancelRequest(client_id="c1", order_id="OID-999"), timeout=10
    )
    assert not c3.success


def test_order_update_stream(hs):
    updates = []
    got_two = threading.Event()

    def watch():
        for u in hs.stub.StreamOrderUpdates(
            pb2.OrderUpdatesRequest(client_id="maker")
        ):
            updates.append(u)
            if len(updates) >= 2:
                got_two.set()
                return

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    import time
    time.sleep(0.3)  # let the subscription register
    submit(hs.stub, client="maker", side=pb2.SELL, price=10000, qty=5)
    submit(hs.stub, client="taker", side=pb2.BUY, price=10000, qty=2)
    assert got_two.wait(timeout=10)
    assert updates[0].status == pb2.OrderUpdate.Status.NEW
    assert updates[1].status == pb2.OrderUpdate.Status.PARTIALLY_FILLED
    assert updates[1].fill_quantity == 2 and updates[1].remaining_quantity == 3


def test_market_data_stream(hs):
    got = []
    evt = threading.Event()

    def watch():
        for u in hs.stub.StreamMarketData(pb2.MarketDataRequest(symbol="SYM")):
            got.append(u)
            evt.set()
            return

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    import time
    time.sleep(0.3)
    submit(hs.stub, side=pb2.BUY, price=10000, qty=5)
    assert evt.wait(timeout=10)
    assert got[0].best_bid == 10000 and got[0].bid_size == 5


def test_restart_resumes_oid_sequence_and_recovers_book(tmp_path):
    db = str(tmp_path / "restart.db")
    h1 = Harness(db)
    r1 = submit(h1.stub, side=pb2.BUY, price=10000, qty=5)
    assert r1.order_id == "OID-1"
    h1.close()

    h2 = Harness(db)
    try:
        # OID sequence resumed
        r2 = submit(h2.stub, side=pb2.BUY, price=9000, qty=1)
        assert r2.order_id == "OID-2"
        # recovered resting bid still matches
        r3 = submit(h2.stub, client="c2", side=pb2.SELL, price=10000, qty=5)
        assert r3.success
        h2.flush()
        st = Storage(db)
        assert st.get_order("OID-1")[8] == 2  # FILLED after recovery match
        fills = st.fills_for_order(r3.order_id)
        assert len(fills) == 1 and fills[0][1] == "OID-1"
    finally:
        h2.close()


def test_unimplemented_like_unknown_method_is_clean(hs):
    # A bogus method path aborts with UNIMPLEMENTED, not a hang/crash.
    ch = hs.channel
    call = ch.unary_unary(
        "/matching_engine.v1.MatchingEngine/NoSuchMethod",
        request_serializer=lambda x: b"",
        response_deserializer=lambda b: b,
    )
    with pytest.raises(grpc.RpcError) as ei:
        call(b"", timeout=5)
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_metrics_rpc(hs):
    submit(hs.stub)
    m = hs.stub.GetMetrics(pb2.MetricsRequest(), timeout=10)
    assert m.counters["rpc_submit"] >= 1
    assert m.counters["orders_accepted"] >= 1


def test_book_l2_levels(hs):
    """The additive L2 view aggregates per price in book order."""
    for price, qty in [(12000, 3), (12000, 2), (11000, 7)]:
        assert submit(hs.stub, client="lv", symbol="LVLS", side=pb2.BUY,
                      price=price, qty=qty).success
    assert submit(hs.stub, client="lv", symbol="LVLS", side=pb2.SELL,
                  price=13000, qty=4).success
    book = hs.stub.GetOrderBook(pb2.OrderBookRequest(symbol="LVLS"),
                                timeout=10)
    assert [(lv.price, lv.quantity, lv.order_count)
            for lv in book.bid_levels] == [(12000, 5, 2), (11000, 7, 1)]
    assert [(lv.price, lv.quantity, lv.order_count)
            for lv in book.ask_levels] == [(13000, 4, 1)]
    # Per-order rows unchanged (L2 is additive).
    assert len(book.bids) == 3 and len(book.asks) == 1
