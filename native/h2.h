// Minimal HTTP/2 + HPACK codec for the native gRPC edge.
//
// The reference's serving edge is grpc++ (src/server/main.cpp:34-38,
// src/client/client.cpp:32); this image has libprotobuf but no grpc++/nghttp2
// development files, so the framework carries its own purpose-built HTTP/2
// server/client transport: enough of RFC 7540 (framing, flow control,
// settings, streams) and RFC 7541 (full HPACK decode incl. Huffman and the
// dynamic table; simple literal encode) to interoperate with gRPC
// implementations over cleartext h2c with prior knowledge — which is exactly
// what insecure-creds gRPC speaks. Interop is enforced end-to-end by
// tests/test_gateway.py (grpc C-core client -> this server) and
// tests/test_native_client.py (this client -> grpcio server).
#pragma once

#include <cstddef>
#include <cstdint>
#include <deque>
#include <string>
#include <string_view>
#include <vector>

namespace h2 {

// ---------------------------------------------------------------------------
// HPACK (RFC 7541)
// ---------------------------------------------------------------------------

struct Header {
  std::string name;
  std::string value;
};

// Decodes a Huffman-coded string (RFC 7541 §5.2 + Appendix B table).
// Returns false on invalid padding / EOS in stream.
bool huffman_decode(const uint8_t* p, size_t n, std::string* out);

class HpackDecoder {
 public:
  // Decode one complete header block fragment sequence. Appends to `out`.
  // Returns false on any decoding error (connection error per RFC).
  bool decode(const uint8_t* p, size_t n, std::vector<Header>* out);

  // The cap we advertise via SETTINGS_HEADER_TABLE_SIZE (default 4096).
  void set_capacity_limit(size_t cap) { cap_limit_ = cap; }

 private:
  bool read_int(const uint8_t*& p, const uint8_t* end, int prefix_bits,
                uint64_t* out);
  bool read_string(const uint8_t*& p, const uint8_t* end, std::string* out);
  bool table_lookup(uint64_t index, Header* out) const;
  void table_insert(const Header& h);

  std::deque<Header> dyn_;   // front() = most recent = index 62
  size_t dyn_size_ = 0;      // sum of (name+value+32) per RFC §4.1
  size_t cap_ = 4096;        // current dynamic-table max (peer-controlled)
  size_t cap_limit_ = 4096;  // protocol max we advertised
};

// Encoder: emits every header as "literal without indexing, raw strings" —
// always valid for any peer decoder and keeps the encoder stateless (no
// dynamic-table sync to get wrong). Responses/requests here are tiny; the
// hot-path cost is on the engine, not header bytes.
void hpack_encode(std::string_view name, std::string_view value,
                  std::string* out);

// ---------------------------------------------------------------------------
// HTTP/2 framing (RFC 7540 §4)
// ---------------------------------------------------------------------------

enum FrameType : uint8_t {
  F_DATA = 0x0,
  F_HEADERS = 0x1,
  F_PRIORITY = 0x2,
  F_RST_STREAM = 0x3,
  F_SETTINGS = 0x4,
  F_PUSH_PROMISE = 0x5,
  F_PING = 0x6,
  F_GOAWAY = 0x7,
  F_WINDOW_UPDATE = 0x8,
  F_CONTINUATION = 0x9,
};

enum FrameFlags : uint8_t {
  FLAG_END_STREAM = 0x1,   // DATA, HEADERS
  FLAG_ACK = 0x1,          // SETTINGS, PING
  FLAG_END_HEADERS = 0x4,  // HEADERS, CONTINUATION
  FLAG_PADDED = 0x8,       // DATA, HEADERS
  FLAG_PRIORITY = 0x20,    // HEADERS
};

struct FrameHeader {
  uint32_t length;
  uint8_t type;
  uint8_t flags;
  uint32_t stream_id;  // high bit masked off
};

inline constexpr const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
inline constexpr size_t kPrefaceLen = 24;
inline constexpr uint32_t kDefaultWindow = 65535;
inline constexpr uint32_t kMaxFrameSize = 16384;  // we advertise the default

// Serializes a 9-byte frame header.
void write_frame_header(uint8_t type, uint8_t flags, uint32_t stream_id,
                        size_t length, std::string* out);
// Parses a 9-byte frame header.
FrameHeader parse_frame_header(const uint8_t p[9]);

// gRPC message framing (5-byte prefix: compressed flag + u32 length).
void grpc_frame(std::string_view message, std::string* out);

}  // namespace h2
