// me_shmring: the zero-copy shared-memory ingress ring (ROADMAP Open
// item 3a — the CoinTossX design point, arXiv:2102.10925), version 2:
// a true MULTI-PRODUCER edge (ROADMAP Open item 2 — N co-located
// producer processes is the realistic heavy-traffic shape).
//
// N co-located client processes map one file-backed segment and write
// flat 384-byte op-records (MeOpRec — the PR 7 codec, unchanged on the
// wire) straight into ring slots; the server's poller thread consumes
// committed runs, screens them through the vectorized admission
// pipeline, and bulk-pushes them into the lane rings — no proto, no
// python per-op, no copy beyond the ring slot. Responses flow back
// through PER-WRITER response sub-rings of fixed 48-byte MeShmResp
// records keyed by the request's ring sequence: each registered writer
// owns a private lane (cursors + doorbell + slots), so every client
// sees exactly its own positional acks and nothing else.
//
// CRASH-SAFETY CONTRACT (pinned by the kill-fuzz tests, single- and
// multi-writer): a writer SIGKILLed at ANY instruction must never yield
// a torn, lost, or duplicated admitted record, and must never stall the
// OTHER writers' committed runs for longer than the torn window.
//   - Every slot has a COMMIT/SEQ word. A writer first CLAIMS a run of
//     sequences (CAS on req_tail) and stamps each claimed slot's word
//     with a CLAIM marker carrying its writer id + registration
//     generation, then writes the record bytes, then publishes with a
//     release-store of seq+1 into the word. The poller admits a slot
//     only when its word equals seq+1 (acquire) — a record the death
//     interrupted mid-write was never published and can never be read
//     torn.
//   - A claimed-but-never-committed slot would stall the FIFO forever
//     (claims are unique; the dead writer can't finish). The poller
//     waits `torn_wait_us` for the commit and then RECOVERS the slot —
//     but only once the claim is provably ORPHANED: the marker's
//     (writer, generation) is checked against the registry and the
//     registrant's pid against the kernel (kill(pid, 0) == ESRCH). A
//     merely SLOW registered writer is waited out (its claim is leased
//     on its life); a dead one's consecutive claims are swept in ONE
//     recovery pass, so a victim holding a chunk claim costs one torn
//     window, not one per slot. Anonymous (unregistered, writer 0)
//     claims keep the v1 deadline-only rule — there is no pid to
//     check. The client never saw an ack for a recovered sequence, so
//     nothing acknowledged is lost; the sequence is consumed, so
//     nothing can be admitted twice.
//   - Cursors are monotonic uint64 (never wrapped); slot reuse a lap
//     later re-publishes with a strictly larger commit value and claim
//     markers embed the sequence, so a stale word can never satisfy a
//     newer sequence.
//   - Residual (documented, not closed): liveness is by pid — a zombie
//     (dead but unreaped) or a recycled pid reads as alive and extends
//     the wait; an ANONYMOUS claimant recovered while alive-but-stalled
//     can, if the ring also wraps back to that slot within the torn
//     window, race its late bytes against the new claimant's. Register
//     writers (ids 1..15) to get the leased behavior; keep torn windows
//     well above scheduler jitter.
//
// The doorbell is a futex word in the shared mapping (eventfd would
// need fd passing between unrelated processes): writers bump-and-wake
// after a committed run, the poller waits on the word's value with a
// timeout — a wake between the value read and the wait returns
// immediately (classic futex protocol), so no doorbell is ever missed.
// Each response lane has its own doorbell so one client's wake never
// spuriously rouses another.
//
// Compiled into libme_native.so (no protobuf dependency). Linux-only
// (SYS_futex); every entry point degrades to an error return, never a
// crash, on a bad handle.

#include <atomic>
#include <cerrno>
#include <csignal>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

#include "me_gwop.h"

namespace {

constexpr char kMagic[8] = {'M', 'E', 'S', 'H', 'M', 'R', 'G', '1'};
constexpr uint32_t kVersion = 2;       // v2: multi-producer + resp lanes
constexpr size_t kHeaderBytes = 4096;  // one page; sections follow aligned
constexpr uint32_t kMaxWriters = 16;   // lane 0 = anonymous; 1..15 register

// Commit-word states. A slot word is exactly one of:
//   seq + 1                         committed (record readable)
//   kClaimBit | gen | wid | seq+1   claimed by (wid, gen), uncommitted
//   anything else                   stale (prior lap) or pre-stamp claim
// The claim marker embeds the low 51 bits of seq+1 so a stale marker
// from an earlier lap can never be mistaken for the current claim.
constexpr uint64_t kClaimBit = 1ull << 63;
constexpr int kGenShift = 55;  // 8 bits of registration generation
constexpr int kWidShift = 51;  // 4 bits of writer id
constexpr uint64_t kSeqMask = (1ull << 51) - 1;

uint64_t claim_word(uint64_t seq, uint32_t wid, uint32_t gen) {
  return kClaimBit | (uint64_t{gen & 0xff} << kGenShift) |
         (uint64_t{wid & 0xf} << kWidShift) | ((seq + 1) & kSeqMask);
}

// One writer's private response lane: the server is the sole publisher
// (tail), the owning client the sole consumer (head). One cacheline per
// lane keeps lanes from false-sharing each other; tail/head sharing a
// line within a lane is the classic SPSC trade accepted here.
struct RespLane {
  alignas(64) std::atomic<uint64_t> tail;  // server publish cursor
  std::atomic<uint64_t> head;              // owning client consume cursor
  std::atomic<uint64_t> dropped;           // lane-full response drops
  std::atomic<uint32_t> doorbell;
};
static_assert(sizeof(RespLane) == 64, "one cacheline per response lane");

// Writer registry entry. pid == 0 marks a free slot; gen bumps on every
// (re)registration of the slot so a claim stamped under a previous
// registrant is recognizably orphaned even after the slot is reused.
struct WriterEnt {
  std::atomic<uint32_t> pid;
  std::atomic<uint32_t> gen;
};

struct ShmHeader {
  char magic[8];
  uint32_t version;
  uint32_t req_cap;      // request slots (power of two)
  uint32_t resp_cap;     // response slots PER WRITER LANE (power of two)
  uint32_t record_size;  // sizeof(MeOpRec); attach refuses a skewed build
  // Cursors are monotonic sequence numbers, never wrapped; slot index is
  // seq & (cap - 1). Cacheline-separated: the claim word is contended by
  // writers, the head only by the poller.
  alignas(64) std::atomic<uint64_t> req_tail;  // writer claim cursor
  alignas(64) std::atomic<uint64_t> req_head;  // poller consume cursor
  alignas(64) std::atomic<uint32_t> req_doorbell;
  std::atomic<uint32_t> closed;  // server shutdown latch
  // Shared counters (the server scrapes these into me_ingress_*).
  alignas(64) std::atomic<uint64_t> torn_recovered;
  std::atomic<uint64_t> doorbell_wakes;
  alignas(64) WriterEnt writers[kMaxWriters];
  alignas(64) RespLane resp[kMaxWriters];
};
static_assert(sizeof(ShmHeader) <= kHeaderBytes, "header must fit its page");

struct ShmRing {
  void* map = nullptr;
  size_t map_len = 0;
  int fd = -1;
  bool owner = false;
  uint32_t wid = 0;  // this handle's writer lane (0 = anonymous)
  uint32_t gen = 0;  // registration generation stamped into claims

  ShmHeader* hdr = nullptr;
  std::atomic<uint64_t>* req_seq = nullptr;  // [req_cap] commit words
  uint8_t* req_recs = nullptr;               // [req_cap] MeOpRec slots
  MeShmResp* resp_recs = nullptr;            // [kMaxWriters * resp_cap]
};

bool pow2(uint32_t v) { return v != 0 && (v & (v - 1)) == 0; }

size_t layout_len(uint32_t req_cap, uint32_t resp_cap) {
  size_t n = kHeaderBytes;
  n += sizeof(uint64_t) * req_cap;  // commit words
  n = (n + 63) & ~size_t{63};
  n += sizeof(MeOpRec) * req_cap;
  n = (n + 63) & ~size_t{63};
  n += sizeof(MeShmResp) * resp_cap * kMaxWriters;
  return (n + 4095) & ~size_t{4095};
}

void wire_sections(ShmRing* r) {
  uint8_t* base = static_cast<uint8_t*>(r->map);
  r->hdr = reinterpret_cast<ShmHeader*>(base);
  size_t off = kHeaderBytes;
  r->req_seq = reinterpret_cast<std::atomic<uint64_t>*>(base + off);
  off += sizeof(uint64_t) * r->hdr->req_cap;
  off = (off + 63) & ~size_t{63};
  r->req_recs = base + off;
  off += sizeof(MeOpRec) * r->hdr->req_cap;
  off = (off + 63) & ~size_t{63};
  r->resp_recs = reinterpret_cast<MeShmResp*>(base + off);
}

int futex_wait(std::atomic<uint32_t>* addr, uint32_t expect,
               int64_t timeout_us) {
  struct timespec ts;
  ts.tv_sec = timeout_us / 1000000;
  ts.tv_nsec = (timeout_us % 1000000) * 1000;
  // Shared futex (no PRIVATE flag): the waiter and waker are different
  // processes mapping the same file.
  return static_cast<int>(syscall(SYS_futex, addr, FUTEX_WAIT, expect,
                                  timeout_us >= 0 ? &ts : nullptr, nullptr,
                                  0));
}

void futex_wake_all(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, addr, FUTEX_WAKE, 0x7fffffff, nullptr, nullptr, 0);
}

int64_t now_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

// Is the claim stamped (wid, gen) provably orphaned? True for anonymous
// claims (no pid to lease on — the caller's torn deadline is the only
// protection, the v1 rule), for claims whose registry slot moved on to a
// new generation or was cleanly freed, and for registrants the kernel
// says are gone. Zombies and recycled pids read as alive (documented).
bool claim_orphaned(ShmHeader* hd, uint32_t wid, uint32_t gen) {
  if (wid == 0 || wid >= kMaxWriters) return true;
  if ((hd->writers[wid].gen.load(std::memory_order_acquire) & 0xff) != gen)
    return true;
  uint32_t pid = hd->writers[wid].pid.load(std::memory_order_acquire);
  if (pid == 0) return true;
  return ::kill(static_cast<pid_t>(pid), 0) != 0 && errno == ESRCH;
}

}  // namespace

extern "C" {

// Server side: create (or truncate) the segment file and initialize the
// layout. Caps must be powers of two; resp_cap is PER writer lane.
// Returns a handle or nullptr.
void* me_shmring_create(const char* path, uint32_t req_cap,
                        uint32_t resp_cap) {
  if (!path || !pow2(req_cap) || !pow2(resp_cap)) return nullptr;
  int fd = ::open(path, O_CREAT | O_RDWR | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  size_t len = layout_len(req_cap, resp_cap);
  if (ftruncate(fd, static_cast<off_t>(len)) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  std::memset(map, 0, len);
  auto* r = new ShmRing;
  r->map = map;
  r->map_len = len;
  r->fd = fd;
  r->owner = true;
  auto* h = reinterpret_cast<ShmHeader*>(map);
  h->version = kVersion;
  h->req_cap = req_cap;
  h->resp_cap = resp_cap;
  h->record_size = static_cast<uint32_t>(sizeof(MeOpRec));
  wire_sections(r);
  // Magic LAST (release): an attacher that sees the magic sees a fully
  // initialized header.
  std::atomic_thread_fence(std::memory_order_release);
  std::memcpy(h->magic, kMagic, sizeof(kMagic));
  return r;
}

// Client side: map an existing segment. Refuses a bad magic/version or a
// record-size skew (a mismatched build must fail loudly, not corrupt).
void* me_shmring_attach(const char* path) {
  if (!path) return nullptr;
  int fd = ::open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)kHeaderBytes) {
    ::close(fd);
    return nullptr;
  }
  size_t len = static_cast<size_t>(st.st_size);
  void* map = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* h = reinterpret_cast<ShmHeader*>(map);
  if (std::memcmp(h->magic, kMagic, sizeof(kMagic)) != 0 ||
      h->version != kVersion ||
      h->record_size != sizeof(MeOpRec) ||
      !pow2(h->req_cap) || !pow2(h->resp_cap) ||
      layout_len(h->req_cap, h->resp_cap) > len) {
    munmap(map, len);
    ::close(fd);
    return nullptr;
  }
  auto* r = new ShmRing;
  r->map = map;
  r->map_len = len;
  r->fd = fd;
  r->owner = false;
  wire_sections(r);
  return r;
}

// Register this handle as a writer: claim a registry slot (ids 1..15),
// bump its generation, record our pid — claims stamped under this
// registration are leased on our life (the poller recovers them only
// once we are dead). Returns the writer id, or -1 when every slot is
// held by a live registrant (the caller may fall back to anonymous
// writer 0, which keeps v1 deadline-only recovery semantics).
int me_shmring_register(void* h) {
  if (!h) return -1;
  auto* r = static_cast<ShmRing*>(h);
  if (r->wid != 0) return static_cast<int>(r->wid);  // idempotent
  ShmHeader* hd = r->hdr;
  uint32_t me = static_cast<uint32_t>(::getpid());
  for (int pass = 0; pass < 2; pass++) {
    for (uint32_t i = 1; i < kMaxWriters; i++) {
      uint32_t cur = hd->writers[i].pid.load(std::memory_order_acquire);
      if (pass == 0 && cur != 0) continue;  // first pass: free slots only
      if (pass == 1) {
        // Reap pass: take over a slot whose registrant is gone (its
        // pending claims, if any, are orphaned by the gen bump and will
        // be recovered by the poller's torn sweep).
        if (cur == 0 || me == cur) continue;
        if (::kill(static_cast<pid_t>(cur), 0) == 0 || errno != ESRCH)
          continue;
      } else {
        cur = 0;
      }
      if (hd->writers[i].pid.compare_exchange_strong(
              cur, me, std::memory_order_acq_rel))
      {
        uint32_t g =
            hd->writers[i].gen.fetch_add(1, std::memory_order_acq_rel) + 1;
        r->wid = i;
        r->gen = g & 0xff;
        return static_cast<int>(i);
      }
    }
  }
  return -1;
}

// Release this handle's registry slot (clean shutdown). Pending
// uncommitted claims, if the caller leaked any, become orphaned and are
// recovered by the poller after the torn window.
void me_shmring_deregister(void* h) {
  if (!h) return;
  auto* r = static_cast<ShmRing*>(h);
  if (r->wid == 0 || r->wid >= kMaxWriters) return;
  uint32_t me = static_cast<uint32_t>(::getpid());
  r->hdr->writers[r->wid].pid.compare_exchange_strong(
      me, 0u, std::memory_order_acq_rel);
  r->wid = 0;
  r->gen = 0;
}

// This handle's writer id (0 = anonymous / unregistered).
int me_shmring_writer_id(void* h) {
  if (!h) return 0;
  return static_cast<int>(static_cast<ShmRing*>(h)->wid);
}

// Live registered writers (the me_ingress_writers gauge): registry slots
// whose registrant pid still resolves. The anonymous lane is not counted.
int me_shmring_writer_count(void* h) {
  if (!h) return 0;
  auto* r = static_cast<ShmRing*>(h);
  int n = 0;
  for (uint32_t i = 1; i < kMaxWriters; i++) {
    uint32_t pid = r->hdr->writers[i].pid.load(std::memory_order_acquire);
    if (pid != 0 &&
        (::kill(static_cast<pid_t>(pid), 0) == 0 || errno != ESRCH))
      n++;
  }
  return n;
}

void me_shmring_close(void* h) {
  if (!h) return;
  auto* r = static_cast<ShmRing*>(h);
  me_shmring_deregister(h);
  if (r->map) munmap(r->map, r->map_len);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

// Server shutdown latch: attached writers see -2 from claim/push and
// every client's response poll returns -2 once its lane is drained.
void me_shmring_shutdown(void* h) {
  if (!h) return;
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  hd->closed.store(1, std::memory_order_release);
  hd->req_doorbell.fetch_add(1, std::memory_order_release);
  futex_wake_all(&hd->req_doorbell);
  for (uint32_t w = 0; w < kMaxWriters; w++) {
    hd->resp[w].doorbell.fetch_add(1, std::memory_order_release);
    futex_wake_all(&hd->resp[w].doorbell);
  }
}

// -- writer (client process) ------------------------------------------------

// Claim n consecutive sequences and stamp each claimed slot's commit
// word with this handle's (writer, generation) marker — the poller's
// torn recovery attributes the claim through the stamp. Returns the base
// sequence, -1 when the ring can't hold n more records (backpressure:
// the writer retries), -2 when the server shut the segment down.
long long me_shmring_claim(void* h, uint32_t n) {
  if (!h || n == 0) return -1;
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  if (hd->closed.load(std::memory_order_acquire)) return -2;
  for (;;) {
    uint64_t t = hd->req_tail.load(std::memory_order_relaxed);
    uint64_t head = hd->req_head.load(std::memory_order_acquire);
    if (t + n - head > hd->req_cap) return -1;  // full
    if (hd->req_tail.compare_exchange_weak(t, t + n,
                                           std::memory_order_acq_rel)) {
      const uint32_t mask = hd->req_cap - 1;
      for (uint32_t i = 0; i < n; i++) {
        uint64_t s = t + i;
        r->req_seq[s & mask].store(claim_word(s, r->wid, r->gen),
                                   std::memory_order_release);
      }
      return static_cast<long long>(t);
    }
  }
}

// Zero-copy slot access: the writer builds the record IN the mapped slot.
uint8_t* me_shmring_slot(void* h, long long seq) {
  if (!h || seq < 0) return nullptr;
  auto* r = static_cast<ShmRing*>(h);
  uint64_t idx = static_cast<uint64_t>(seq) & (r->hdr->req_cap - 1);
  return r->req_recs + idx * sizeof(MeOpRec);
}

// Publish one claimed slot (release): after this store the poller may
// admit the record — the record bytes must be fully written first. The
// record's writer field is stamped HERE from the committing handle (not
// trusted from the payload), so responses demux to the lane that
// actually owns the claim.
void me_shmring_commit(void* h, long long seq) {
  if (!h || seq < 0) return;
  auto* r = static_cast<ShmRing*>(h);
  uint64_t s = static_cast<uint64_t>(seq);
  uint64_t idx = s & (r->hdr->req_cap - 1);
  reinterpret_cast<MeOpRec*>(r->req_recs + idx * sizeof(MeOpRec))->writer =
      static_cast<uint16_t>(r->wid);
  r->req_seq[idx].store(s + 1, std::memory_order_release);
}

// Ring the request doorbell (after a run of commits — one wake per
// batch, not per record).
void me_shmring_wake(void* h) {
  if (!h) return;
  auto* r = static_cast<ShmRing*>(h);
  r->hdr->req_doorbell.fetch_add(1, std::memory_order_release);
  futex_wake_all(&r->hdr->req_doorbell);
}

// Copy-in convenience writer: claim + write + commit + wake for a packed
// run of records. Returns the base sequence, -1 full, -2 closed.
long long me_shmring_push_n(void* h, const MeOpRec* recs, uint32_t n) {
  if (!h || (!recs && n)) return -1;
  long long base = me_shmring_claim(h, n);
  if (base < 0) return base;
  for (uint32_t i = 0; i < n; i++) {
    std::memcpy(me_shmring_slot(h, base + i), &recs[i], sizeof(MeOpRec));
    me_shmring_commit(h, base + i);
  }
  me_shmring_wake(h);
  return base;
}

// -- poller (server thread) -------------------------------------------------

// Pop committed records: up to `max` copied into `out`, their ring
// sequences into `seqs` (torn-slot recovery makes runs non-contiguous,
// so responses key by sequence, not position). Blocks up to wait_us for
// the FIRST record, then keeps collecting for up to window_us more (the
// GwRing batching-window semantics: one big dispatch beats many small
// ones). A claimed slot whose commit doesn't arrive within torn_wait_us
// is a recovery CANDIDATE; it is actually recovered only when the claim
// is orphaned (registrant dead / superseded, or anonymous): skipped,
// counted (shared header counter + *torn for this call), and — for a
// dead registrant — swept together with its consecutive same-claim
// neighbors, so one dead chunk claim costs one torn window. A live
// registrant's claim is waited out indefinitely (leased on its life);
// committed runs BEHIND the gap are therefore delayed at most one torn
// window per dead writer, never lost. Returns n (possibly 0 on
// timeout), or -2 when the segment is shut down and drained.
int me_shmring_poll(void* h, MeOpRec* out, long long* seqs, uint32_t max,
                    int64_t wait_us, int64_t window_us,
                    int64_t torn_wait_us, long long* torn) {
  if (torn) *torn = 0;
  if (!h || !out || !seqs || max == 0) return -1;
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  const uint32_t mask = hd->req_cap - 1;
  int64_t deadline = now_us() + (wait_us >= 0 ? wait_us : 0);
  int64_t window_deadline = -1;  // armed by the first collected record
  int64_t torn_deadline = -1;
  uint32_t n = 0;
  for (;;) {
    uint64_t head = hd->req_head.load(std::memory_order_relaxed);
    uint64_t tail = hd->req_tail.load(std::memory_order_acquire);
    uint64_t pos = head;
    long long torn_now = 0;
    uint32_t got = 0;
    while (n < max && pos < tail) {
      uint64_t s = r->req_seq[pos & mask].load(std::memory_order_acquire);
      if (s == pos + 1) {
        std::memcpy(&out[n], r->req_recs + (pos & mask) * sizeof(MeOpRec),
                    sizeof(MeOpRec));
        seqs[n] = static_cast<long long>(pos);
        n++;
        got++;
        pos++;
        torn_deadline = -1;  // progress: any later gap restarts the clock
      } else if (got == 0 && n == 0 && torn_deadline >= 0 &&
                 now_us() >= torn_deadline) {
        // The front slot's commit never arrived within the torn window.
        // Attribute the claim through its stamp and recover it only if
        // it is provably orphaned; a live registered claimant re-arms
        // the window instead (its claim is leased on its life).
        bool attributed = (s & kClaimBit) != 0 &&
                          (s & kSeqMask) == ((pos + 1) & kSeqMask);
        uint32_t wid =
            attributed ? static_cast<uint32_t>((s >> kWidShift) & 0xf) : 0;
        uint32_t gen =
            attributed ? static_cast<uint32_t>((s >> kGenShift) & 0xff) : 0;
        if (attributed && wid != 0 && !claim_orphaned(hd, wid, gen)) {
          torn_deadline = now_us() + torn_wait_us;
          break;  // claimant alive: keep waiting at the gap
        }
        pos++;
        torn_now++;
        if (attributed && wid != 0) {
          // Dead registrant: sweep its consecutive claims in one pass —
          // same (writer, generation) markers can never commit now.
          while (pos < tail) {
            uint64_t w = r->req_seq[pos & mask].load(
                std::memory_order_acquire);
            if (w != claim_word(pos, wid, gen)) break;
            pos++;
            torn_now++;
          }
        }
        torn_deadline = -1;
      } else {
        break;  // uncommitted claim: stop at the contiguous prefix
      }
    }
    if (torn_now) {
      hd->torn_recovered.fetch_add(static_cast<uint64_t>(torn_now),
                                   std::memory_order_relaxed);
      if (torn) *torn += torn_now;
    }
    if (got > 0 || torn_now > 0) {
      // Release: writers' fullness check (claim) must observe the freed
      // slots only after our record copies are done.
      hd->req_head.store(pos, std::memory_order_release);
    }
    if (n >= max) return static_cast<int>(n);
    if (n > 0) {
      // Batching window: first record arms it; keep collecting until it
      // closes or the buffer fills.
      int64_t now = now_us();
      if (window_deadline < 0) window_deadline = now + window_us;
      if (now >= window_deadline) return static_cast<int>(n);
      if (got > 0) continue;  // something arrived: rescan immediately
      uint32_t d = hd->req_doorbell.load(std::memory_order_acquire);
      if (hd->req_tail.load(std::memory_order_acquire) ==
          hd->req_head.load(std::memory_order_relaxed)) {
        futex_wait(&hd->req_doorbell, d, window_deadline - now);
      } else {
        struct timespec ts = {0, 100 * 1000};  // gap mid-window: 100us
        nanosleep(&ts, nullptr);
      }
      continue;
    }
    if (head < tail && got == 0 && torn_now == 0) {
      // Claimed but uncommitted at the front: arm the torn clock and
      // wait it out in short slices (the writer is normally a few
      // STORES away from committing; death is the rare case).
      if (torn_deadline < 0) torn_deadline = now_us() + torn_wait_us;
      struct timespec ts = {0, 200 * 1000};  // 200us
      nanosleep(&ts, nullptr);
    } else if (got == 0 && torn_now == 0) {
      if (hd->closed.load(std::memory_order_acquire)) return -2;
      uint32_t d = hd->req_doorbell.load(std::memory_order_acquire);
      // Re-check after the doorbell read (the futex protocol: a writer
      // that committed and bumped between our tail read and here makes
      // the wait return immediately on value mismatch).
      if (hd->req_tail.load(std::memory_order_acquire) == head) {
        int64_t left = deadline - now_us();
        if (left <= 0) return 0;
        if (futex_wait(&hd->req_doorbell, d, left) == 0)
          hd->doorbell_wakes.fetch_add(1, std::memory_order_relaxed);
      }
    }
    if (n == 0 && now_us() >= deadline && torn_deadline < 0) return 0;
  }
}

// -- responses (server publisher, per-writer consumer lanes) ----------------

// Publish n response records, each routed into ITS writer's lane by the
// record's `writer` stamp (echoed by the poller from the request
// record, which me_shmring_commit stamped from the claiming handle).
// The server never blocks the serving path on a slow client: when a
// lane's unread backlog leaves no room, that record is DROPPED and
// counted on the lane (the client re-derives outcomes from the store /
// re-submits; acks are a convenience channel, admission is what is
// durable). Returns the number written across all lanes.
int me_shmring_respond_n(void* h, const MeShmResp* rs, uint32_t n) {
  if (!h || (!rs && n)) return -1;
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  const uint32_t cap = hd->resp_cap;
  uint32_t wrote = 0;
  uint32_t touched = 0;  // bitmask of lanes to ring after the batch
  for (uint32_t i = 0; i < n; i++) {
    uint32_t w = rs[i].writer;
    if (w >= kMaxWriters) w = 0;  // stale/garbage stamp: anonymous lane
    RespLane& lane = hd->resp[w];
    uint64_t tail = lane.tail.load(std::memory_order_relaxed);
    uint64_t head = lane.head.load(std::memory_order_acquire);
    if (tail - head >= cap) {
      lane.dropped.fetch_add(1, std::memory_order_relaxed);
      continue;
    }
    r->resp_recs[static_cast<size_t>(w) * cap + (tail & (cap - 1))] = rs[i];
    lane.tail.store(tail + 1, std::memory_order_release);
    touched |= 1u << w;
    wrote++;
  }
  for (uint32_t w = 0; w < kMaxWriters; w++) {
    if (!(touched & (1u << w))) continue;
    hd->resp[w].doorbell.fetch_add(1, std::memory_order_release);
    futex_wake_all(&hd->resp[w].doorbell);
  }
  return static_cast<int>(wrote);
}

// Client: pop up to max responses from THIS handle's writer lane,
// blocking up to wait_us for the first. An anonymous handle consumes
// lane 0 (the v1 single-client behavior, unchanged); a registered
// handle sees exactly its own acks. Returns n (0 on timeout), -2 when
// the server shut down AND every published response on the lane was
// consumed.
int me_shmring_resp_poll(void* h, MeShmResp* out, uint32_t max,
                         int64_t wait_us) {
  if (!h || !out || max == 0) return -1;
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  const uint32_t cap = hd->resp_cap;
  RespLane& lane = hd->resp[r->wid];
  const MeShmResp* recs =
      r->resp_recs + static_cast<size_t>(r->wid) * cap;
  int64_t deadline = now_us() + (wait_us >= 0 ? wait_us : 0);
  for (;;) {
    uint64_t head = lane.head.load(std::memory_order_relaxed);
    uint64_t tail = lane.tail.load(std::memory_order_acquire);
    if (tail > head) {
      uint32_t n = static_cast<uint32_t>(tail - head);
      if (n > max) n = max;
      for (uint32_t i = 0; i < n; i++)
        out[i] = recs[(head + i) & (cap - 1)];
      lane.head.store(head + n, std::memory_order_release);
      return static_cast<int>(n);
    }
    if (hd->closed.load(std::memory_order_acquire)) return -2;
    uint32_t d = lane.doorbell.load(std::memory_order_acquire);
    if (lane.tail.load(std::memory_order_acquire) == head) {
      int64_t left = deadline - now_us();
      if (left <= 0) return 0;
      futex_wait(&lane.doorbell, d, left);
    }
  }
}

// Shared-header stats for the server's metrics sampler. resp_dropped
// aggregates every writer lane's drop counter.
void me_shmring_stats(void* h, long long* depth, long long* torn,
                      long long* resp_dropped, long long* wakes) {
  if (!h) {
    if (depth) *depth = 0;
    if (torn) *torn = 0;
    if (resp_dropped) *resp_dropped = 0;
    if (wakes) *wakes = 0;
    return;
  }
  auto* r = static_cast<ShmRing*>(h);
  ShmHeader* hd = r->hdr;
  if (depth)
    *depth = static_cast<long long>(
        hd->req_tail.load(std::memory_order_acquire) -
        hd->req_head.load(std::memory_order_acquire));
  if (torn)
    *torn = static_cast<long long>(
        hd->torn_recovered.load(std::memory_order_relaxed));
  if (resp_dropped) {
    uint64_t d = 0;
    for (uint32_t w = 0; w < kMaxWriters; w++)
      d += hd->resp[w].dropped.load(std::memory_order_relaxed);
    *resp_dropped = static_cast<long long>(d);
  }
  if (wakes)
    *wakes = static_cast<long long>(
        hd->doorbell_wakes.load(std::memory_order_relaxed));
}

}  // extern "C"
