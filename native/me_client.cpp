// me_client: the native CLI order submitter.
//
// Argv/exit-code/output parity with the reference client
// (src/client/client.cpp:10-29,49-56) and with the Python CLI
// (matching_engine_tpu/client/cli.py): positional args
//   <addr> <client_id> <symbol> <BUY|SELL> <LIMIT|MARKET> <price> <scale> <qty>
// plus a `cancel <addr> <client_id> <order_id>` subcommand; prints
// `[client] accepted order_id=...` / `[client] rejected: ...`;
// exit codes: 0 accepted, 1 usage, 2 RPC failure, 3 rejected.
//
// The transport is the framework's own HTTP/2 client (native/h2.cpp) — this
// image has no grpc++ — speaking cleartext h2c with prior knowledge, which
// is what insecure-creds gRPC servers accept. Interop with grpcio servers is
// tested in tests/test_native_client.py.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "gen/matching_engine.pb.h"
#include "h2.h"

namespace pb = matching_engine::v1;

namespace {

const char kUsage[] =
    "usage: me_client <addr> <client_id> <symbol> <BUY|SELL> "
    "<LIMIT|MARKET[:IOC|:FOK]> <price> <scale> <quantity>\n"
    "   or: me_client cancel <addr> <client_id> <order_id>\n"
    "   or: me_client amend <addr> <client_id> <order_id> <new_qty>\n"
    "   or: me_client book <addr> <symbol>\n"
    "   or: me_client metrics <addr>\n"
    "   or: me_client watch-md <addr> <symbol> [max_events]\n"
    "   or: me_client watch-orders <addr> <client_id> [max_events]\n"
    "   or: me_client auction <addr> [symbol]\n"
    "   or: me_client bench <addr> <clients> <per_client> [symbols] [inflight] [prefix]";

int dial(const std::string& addr) {
  std::string host = addr;
  std::string port = "50051";
  auto colon = addr.rfind(':');
  if (colon != std::string::npos) {
    host = addr.substr(0, colon);
    port = addr.substr(colon + 1);
  }
  if (host.empty() || host == "0.0.0.0") host = "127.0.0.1";
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // Same 30s deadline the Python CLI passes per call — a silent server
    // must fail the RPC, not hang the client forever.
    timeval tv{30, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  return fd;
}

bool send_all(int fd, const std::string& buf) {
  const char* p = buf.data();
  size_t left = buf.size();
  while (left > 0) {
    ssize_t n = ::send(fd, p, left, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    left -= static_cast<size_t>(n);
  }
  return true;
}

bool read_exact(int fd, uint8_t* dst, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, dst + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

// One unary gRPC call over a fresh h2c connection. Returns 0 and fills
// `response_payload` on success (any grpc-status, including errors, is
// reported via *grpc_status/*grpc_message).
int unary_call(const std::string& addr, const std::string& path,
               const std::string& request_bytes, std::string* response_payload,
               int* grpc_status, std::string* grpc_message) {
  int fd = dial(addr);
  if (fd < 0) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: connect %s\n",
                 addr.c_str());
    return -1;
  }
  std::string out(h2::kPreface, h2::kPrefaceLen);
  h2::write_frame_header(h2::F_SETTINGS, 0, 0, 0, &out);  // empty SETTINGS
  // Request headers (stream 1).
  std::string block;
  h2::hpack_encode(":method", "POST", &block);
  h2::hpack_encode(":scheme", "http", &block);
  h2::hpack_encode(":path", path, &block);
  h2::hpack_encode(":authority", addr, &block);
  h2::hpack_encode("te", "trailers", &block);
  h2::hpack_encode("content-type", "application/grpc", &block);
  h2::write_frame_header(h2::F_HEADERS, h2::FLAG_END_HEADERS, 1, block.size(),
                         &out);
  out += block;
  std::string data;
  h2::grpc_frame(request_bytes, &data);
  h2::write_frame_header(h2::F_DATA, h2::FLAG_END_STREAM, 1, data.size(),
                         &out);
  out += data;
  if (!send_all(fd, out)) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: send\n");
    ::close(fd);
    return -1;
  }

  // Read until our stream ends.
  h2::HpackDecoder hpack;
  std::string body;
  std::string header_block;
  bool stream_done = false;
  *grpc_status = -1;
  std::vector<uint8_t> payload;
  while (!stream_done) {
    uint8_t raw[9];
    if (!read_exact(fd, raw, 9)) break;
    h2::FrameHeader fh = h2::parse_frame_header(raw);
    if (fh.length > (1u << 24)) break;
    payload.resize(fh.length);
    if (fh.length && !read_exact(fd, payload.data(), fh.length)) break;
    switch (fh.type) {
      case h2::F_SETTINGS:
        if (!(fh.flags & h2::FLAG_ACK)) {
          std::string ack;
          h2::write_frame_header(h2::F_SETTINGS, h2::FLAG_ACK, 0, 0, &ack);
          send_all(fd, ack);
        }
        break;
      case h2::F_PING:
        if (!(fh.flags & h2::FLAG_ACK) && fh.length == 8) {
          std::string pong;
          h2::write_frame_header(h2::F_PING, h2::FLAG_ACK, 0, 8, &pong);
          pong.append(reinterpret_cast<char*>(payload.data()), 8);
          send_all(fd, pong);
        }
        break;
      case h2::F_HEADERS: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (fh.flags & h2::FLAG_PADDED) {
          if (n < 1) break;
          uint8_t pad = p[0];
          p += 1;
          n -= 1;
          if (pad > n) break;  // malformed padding: drop the frame
          n -= pad;
        }
        if (fh.flags & h2::FLAG_PRIORITY) {
          if (n < 5) break;
          p += 5;
          n -= 5;
        }
        header_block.assign(reinterpret_cast<const char*>(p), n);
        if (fh.flags & h2::FLAG_END_HEADERS) {
          std::vector<h2::Header> hs;
          if (!hpack.decode(
                  reinterpret_cast<const uint8_t*>(header_block.data()),
                  header_block.size(), &hs)) {
            ::close(fd);
            std::fprintf(stderr, "[client] rpc failed: INTERNAL: hpack\n");
            return -1;
          }
          header_block.clear();
          for (auto& h : hs) {
            if (h.name == "grpc-status") *grpc_status = std::atoi(h.value.c_str());
            if (h.name == "grpc-message") *grpc_message = h.value;
          }
          if (fh.flags & h2::FLAG_END_STREAM) stream_done = true;
        }
        break;
      }
      case h2::F_CONTINUATION: {
        header_block.append(reinterpret_cast<const char*>(payload.data()),
                            payload.size());
        if (fh.flags & h2::FLAG_END_HEADERS) {
          std::vector<h2::Header> hs;
          if (!hpack.decode(
                  reinterpret_cast<const uint8_t*>(header_block.data()),
                  header_block.size(), &hs)) {
            ::close(fd);
            return -1;
          }
          header_block.clear();
          for (auto& h : hs) {
            if (h.name == "grpc-status") *grpc_status = std::atoi(h.value.c_str());
            if (h.name == "grpc-message") *grpc_message = h.value;
          }
        }
        break;
      }
      case h2::F_DATA: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (fh.flags & h2::FLAG_PADDED) {
          if (n < 1) break;
          uint8_t pad = p[0];
          p += 1;
          n -= 1;
          if (pad > n) break;  // malformed padding: drop the frame
          n -= pad;
        }
        body.append(reinterpret_cast<const char*>(p), n);
        if (fh.flags & h2::FLAG_END_STREAM) stream_done = true;
        break;
      }
      case h2::F_RST_STREAM:
      case h2::F_GOAWAY:
        stream_done = true;
        break;
      default:
        break;
    }
  }
  ::close(fd);
  if (*grpc_status < 0) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: no trailers\n");
    return -1;
  }
  if (body.size() >= 5) {
    uint32_t mlen = (static_cast<uint8_t>(body[1]) << 24) |
                    (static_cast<uint8_t>(body[2]) << 16) |
                    (static_cast<uint8_t>(body[3]) << 8) |
                    static_cast<uint8_t>(body[4]);
    if (body.size() >= 5 + mlen) *response_payload = body.substr(5, mlen);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// bench mode: persistent-connection load generator
// ---------------------------------------------------------------------------
//
// `me_client bench <addr> <clients> <per_client> [symbols]` — N worker
// threads, each holding ONE HTTP/2 connection and issuing sequential unary
// SubmitOrder calls on ascending stream ids; prints a single JSON line with
// sustained orders/sec and p50/p99 latency. This is the native counterpart
// of benchmarks/run_all.py config 4's Python thread workers: a GIL-free
// load source so an e2e comparison measures the SERVER edge, not the
// client.
class BenchConn {
 public:
  bool open(const std::string& addr) {
    authority_ = addr;
    fd_ = dial(addr);
    if (fd_ < 0) return false;
    std::string out(h2::kPreface, h2::kPrefaceLen);
    h2::write_frame_header(h2::F_SETTINGS, 0, 0, 0, &out);
    return send_all(fd_, out);
  }

  ~BenchConn() {
    if (fd_ >= 0) ::close(fd_);
  }

  // Sends one request on a fresh stream id (non-blocking wrt the
  // response); returns the stream id, or 0 on transport failure. Multiple
  // streams may be in flight — HTTP/2 multiplexing is the whole point.
  uint32_t issue(const std::string& path, const std::string& request_bytes) {
    uint32_t sid = next_stream_;
    next_stream_ += 2;
    std::string out;
    std::string block;
    h2::hpack_encode(":method", "POST", &block);
    h2::hpack_encode(":scheme", "http", &block);
    h2::hpack_encode(":path", path, &block);
    h2::hpack_encode(":authority", authority_, &block);  // grpc servers require it
    h2::hpack_encode("te", "trailers", &block);
    h2::hpack_encode("content-type", "application/grpc", &block);
    h2::write_frame_header(h2::F_HEADERS, h2::FLAG_END_HEADERS, sid,
                           block.size(), &out);
    out += block;
    std::string data;
    h2::grpc_frame(request_bytes, &data);
    h2::write_frame_header(h2::F_DATA, h2::FLAG_END_STREAM, sid, data.size(),
                           &out);
    out += data;
    if (!send_all(fd_, out)) return 0;
    inflight_.emplace(sid, StreamState{});
    return sid;
  }

  struct Completion {
    uint32_t sid = 0;
    int grpc_status = -1;
    std::string payload;
  };

  // Blocks until any in-flight stream completes. Returns false on
  // transport failure.
  bool reap(Completion* out) {
    for (;;) {
      for (auto it = inflight_.begin(); it != inflight_.end(); ++it) {
        if (it->second.ended) {
          fill_completion(it, out);
          return true;
        }
      }
      if (!pump()) return false;
    }
  }

  size_t inflight() const { return inflight_.size(); }

  // Server-streaming reader for stream `sid`: returns 1 and one gRPC
  // message as it arrives, 0 on end-of-stream (check stream_status()),
  // -1 on transport error. Unlike reap(), messages surface incrementally.
  int next_message(uint32_t sid, std::string* out) {
    for (;;) {
      auto it = inflight_.find(sid);
      if (it == inflight_.end()) return -1;
      std::string& body = it->second.body;
      if (body.size() >= 5) {
        uint32_t mlen = (static_cast<uint8_t>(body[1]) << 24) |
                        (static_cast<uint8_t>(body[2]) << 16) |
                        (static_cast<uint8_t>(body[3]) << 8) |
                        static_cast<uint8_t>(body[4]);
        if (body.size() >= 5 + mlen) {
          *out = body.substr(5, mlen);
          body.erase(0, 5 + static_cast<size_t>(mlen));
          return 1;
        }
      }
      if (it->second.ended) {
        stream_status_ = it->second.grpc_status;
        inflight_.erase(it);
        return 0;
      }
      if (!pump()) return -1;
    }
  }

  // Trailer grpc-status of the last stream next_message() finished
  // (0 = OK; >0 = server error the caller must surface).
  int stream_status() const { return stream_status_; }

  // Watch streams are legitimately idle for minutes: drop the 30s recv
  // deadline dial() installs for request/response commands.
  void clear_timeout() {
    timeval tv{0, 0};
    ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }

 private:
  struct StreamState {
    std::string body;
    int grpc_status = -1;
    bool ended = false;   // END_STREAM observed (possibly via trailers)
  };

  // Strips PADDED (+ PRIORITY for HEADERS) per RFC 7540; false = malformed.
  static bool strip_pad(const h2::FrameHeader& fh, const uint8_t*& p,
                        size_t& n, bool headers) {
    if (fh.flags & h2::FLAG_PADDED) {
      if (n < 1) return false;
      uint8_t pad = p[0];
      p += 1;
      n -= 1;
      if (pad > n) return false;
      n -= pad;
    }
    if (headers && (fh.flags & h2::FLAG_PRIORITY)) {
      if (n < 5) return false;
      p += 5;
      n -= 5;
    }
    return true;
  }

  bool credit_window(uint32_t sid, size_t nbytes) {
    // Replenish both receive windows for consumed DATA — without this a
    // long-lived connection stalls after 64KB of responses and the server
    // fail-fast-closes it as window-starved.
    if (nbytes == 0) return true;
    std::string wu;
    uint32_t incr = static_cast<uint32_t>(nbytes);
    for (uint32_t target : {0u, sid}) {
      h2::write_frame_header(h2::F_WINDOW_UPDATE, 0, target, 4, &wu);
      wu.push_back(static_cast<char>((incr >> 24) & 0xff));
      wu.push_back(static_cast<char>((incr >> 16) & 0xff));
      wu.push_back(static_cast<char>((incr >> 8) & 0xff));
      wu.push_back(static_cast<char>(incr & 0xff));
    }
    return send_all(fd_, wu);
  }

  // Reads and processes exactly ONE frame (the single demux both reap()
  // and next_message() drive). Returns false on transport error.
  bool pump() {
    uint8_t raw[9];
    if (!read_exact(fd_, raw, 9)) return false;
    h2::FrameHeader fh = h2::parse_frame_header(raw);
    if (fh.length > (1u << 24)) return false;
    std::vector<uint8_t> payload(fh.length);
    if (fh.length && !read_exact(fd_, payload.data(), fh.length)) return false;
    switch (fh.type) {
      case h2::F_SETTINGS:
        if (!(fh.flags & h2::FLAG_ACK)) {
          std::string ack;
          h2::write_frame_header(h2::F_SETTINGS, h2::FLAG_ACK, 0, 0, &ack);
          return send_all(fd_, ack);
        }
        return true;
      case h2::F_PING:
        if (!(fh.flags & h2::FLAG_ACK) && fh.length == 8) {
          std::string pong;
          h2::write_frame_header(h2::F_PING, h2::FLAG_ACK, 0, 8, &pong);
          pong.append(reinterpret_cast<char*>(payload.data()), 8);
          return send_all(fd_, pong);
        }
        return true;
      case h2::F_HEADERS:
      case h2::F_CONTINUATION: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (!strip_pad(fh, p, n, fh.type == h2::F_HEADERS)) return false;
        header_block_.append(reinterpret_cast<const char*>(p), n);
        if (fh.type == h2::F_HEADERS) {
          header_sid_ = fh.stream_id;
          // END_STREAM may ride a HEADERS whose block continues in
          // CONTINUATION frames — remember it until END_HEADERS.
          header_es_ = (fh.flags & h2::FLAG_END_STREAM) != 0;
        }
        if (fh.flags & h2::FLAG_END_HEADERS) {
          std::vector<h2::Header> hs;
          if (!hpack_.decode(
                  reinterpret_cast<const uint8_t*>(header_block_.data()),
                  header_block_.size(), &hs)) {
            return false;
          }
          header_block_.clear();
          auto it = inflight_.find(header_sid_);
          if (it != inflight_.end()) {
            for (auto& h : hs) {
              if (h.name == "grpc-status")
                it->second.grpc_status = std::atoi(h.value.c_str());
            }
            if (header_es_) it->second.ended = true;
          }
          header_es_ = false;
        }
        return true;
      }
      case h2::F_DATA: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (!strip_pad(fh, p, n, false)) return false;
        auto it = inflight_.find(fh.stream_id);
        if (it != inflight_.end()) {
          it->second.body.append(reinterpret_cast<const char*>(p), n);
          if (fh.flags & h2::FLAG_END_STREAM) it->second.ended = true;
        }
        return credit_window(fh.stream_id, payload.size());
      }
      case h2::F_RST_STREAM:
      case h2::F_GOAWAY:
        return false;
      default:
        return true;  // WINDOW_UPDATE / PRIORITY / unknown: ignore
    }
  }

  void fill_completion(std::unordered_map<uint32_t, StreamState>::iterator it,
                       Completion* out) {
    out->sid = it->first;
    out->grpc_status = it->second.grpc_status;
    const std::string& body = it->second.body;
    if (body.size() >= 5) {
      uint32_t mlen = (static_cast<uint8_t>(body[1]) << 24) |
                      (static_cast<uint8_t>(body[2]) << 16) |
                      (static_cast<uint8_t>(body[3]) << 8) |
                      static_cast<uint8_t>(body[4]);
      if (body.size() >= 5 + mlen) out->payload = body.substr(5, mlen);
    }
    inflight_.erase(it);
  }

  int fd_ = -1;
  uint32_t next_stream_ = 1;
  std::string authority_;
  std::string header_block_;
  uint32_t header_sid_ = 0;   // stream of the in-progress header block
  bool header_es_ = false;    // that block's HEADERS carried END_STREAM
  int stream_status_ = -1;
  h2::HpackDecoder hpack_;
  std::unordered_map<uint32_t, StreamState> inflight_;
};

int do_bench(const std::string& addr, int clients, int per_client,
             int symbols, int inflight, const std::string& sym_prefix) {
  const std::string path = "/matching_engine.v1.MatchingEngine/SubmitOrder";
  std::vector<std::vector<double>> lat(clients);
  std::vector<int> ok_count(clients, 0), rejected(clients, 0);
  std::atomic<int> transport_errors{0};

  // Warm the server's jit before timing.
  {
    BenchConn warm;
    if (!warm.open(addr)) {
      std::fprintf(stderr, "[bench] connect failed\n");
      return 2;
    }
    pb::OrderRequest req;
    req.set_client_id("warm");
    req.set_symbol(sym_prefix + "0");
    req.set_side(pb::BUY);
    req.set_order_type(pb::LIMIT);
    req.set_price(1);
    req.set_scale(0);
    req.set_quantity(1);
    std::string bytes;
    req.SerializeToString(&bytes);
    BenchConn::Completion c;
    if (!warm.issue(path, bytes) || !warm.reap(&c)) {
      std::fprintf(stderr, "[bench] warm call failed\n");
      return 2;
    }
  }

  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> threads;
  for (int w = 0; w < clients; ++w) {
    threads.emplace_back([&, w] {
      BenchConn conn;
      if (!conn.open(addr)) {
        transport_errors.fetch_add(per_client);
        return;
      }
      unsigned seed = 0x9e3779b9u * static_cast<unsigned>(w + 1);
      lat[w].reserve(per_client);
      std::unordered_map<uint32_t, std::chrono::steady_clock::time_point> t0s;
      int sent = 0;
      while (sent < per_client || !t0s.empty()) {
        // Keep up to `inflight` streams open on this connection.
        while (sent < per_client &&
               static_cast<int>(t0s.size()) < inflight) {
          pb::OrderRequest req;
          req.set_client_id("b" + std::to_string(w));
          req.set_symbol(sym_prefix +
                         std::to_string(rand_r(&seed) % symbols));
          req.set_side((rand_r(&seed) & 1) ? pb::BUY : pb::SELL);
          req.set_order_type(pb::LIMIT);
          req.set_price(10000 + static_cast<int>(rand_r(&seed) % 40) - 20);
          req.set_scale(4);
          req.set_quantity(1 + static_cast<int>(rand_r(&seed) % 49));
          std::string bytes;
          req.SerializeToString(&bytes);
          uint32_t sid = conn.issue(path, bytes);
          if (sid == 0) {
            transport_errors.fetch_add(per_client - sent);
            return;
          }
          t0s[sid] = std::chrono::steady_clock::now();
          ++sent;
        }
        BenchConn::Completion c;
        if (!conn.reap(&c)) {
          transport_errors.fetch_add(static_cast<int>(t0s.size()) +
                                     per_client - sent);
          return;
        }
        auto it = t0s.find(c.sid);
        if (it == t0s.end()) continue;
        lat[w].push_back(std::chrono::duration<double>(
            std::chrono::steady_clock::now() - it->second).count());
        t0s.erase(it);
        pb::OrderResponse resp;
        if (c.grpc_status == 0 && resp.ParseFromString(c.payload) &&
            resp.success()) {
          ++ok_count[w];
        } else {
          ++rejected[w];
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  double dt = std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                            t0).count();

  std::vector<double> all;
  int ok = 0, rej = 0;
  for (int w = 0; w < clients; ++w) {
    all.insert(all.end(), lat[w].begin(), lat[w].end());
    ok += ok_count[w];
    rej += rejected[w];
  }
  std::sort(all.begin(), all.end());
  double p50 = all.empty() ? 0 : all[all.size() / 2] * 1e3;
  double p99 = all.empty() ? 0 : all[static_cast<size_t>(all.size() * 0.99)] * 1e3;
  std::printf(
      "{\"metric\": \"native_client_e2e\", \"value\": %.1f, "
      "\"unit\": \"orders/sec\", \"clients\": %d, \"per_client\": %d, "
      "\"inflight\": %d, \"ok\": %d, \"rejected\": %d, "
      "\"transport_errors\": %d, \"p50_ms\": %.2f, \"p99_ms\": %.2f}\n",
      all.size() / dt, clients, per_client, inflight, ok, rej,
      transport_errors.load(), p50, p99);
  return transport_errors.load() ? 2 : 0;
}

int do_cancel(const std::string& addr, const std::string& client_id,
              const std::string& order_id) {
  pb::CancelRequest req;
  req.set_client_id(client_id);
  req.set_order_id(order_id);
  std::string bytes;
  req.SerializeToString(&bytes);
  std::string resp_bytes, grpc_message;
  int grpc_status = -1;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/CancelOrder",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0) {
    return 2;
  }
  if (grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::CancelResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  if (resp.success()) {
    std::printf("[client] canceled order_id=%s\n", resp.order_id().c_str());
    return 0;
  }
  std::printf("[client] cancel rejected: %s\n", resp.error_message().c_str());
  return 3;
}

int do_amend(const std::string& addr, const std::string& client_id,
             const std::string& order_id, long long new_qty) {
  pb::AmendRequest req;
  req.set_client_id(client_id);
  req.set_order_id(order_id);
  req.set_new_quantity(static_cast<int32_t>(new_qty));
  std::string bytes;
  req.SerializeToString(&bytes);
  std::string resp_bytes, grpc_message;
  int grpc_status = -1;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/AmendOrder",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0) {
    return 2;
  }
  if (grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::AmendResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  if (resp.success()) {
    std::printf("[client] amended order_id=%s remaining=%d\n",
                resp.order_id().c_str(), resp.remaining_quantity());
    return 0;
  }
  std::printf("[client] amend rejected: %s\n", resp.error_message().c_str());
  return 3;
}

}  // namespace

namespace {

// Output format parity with the Python CLI's `book` / `metrics`
// subcommands (matching_engine_tpu/client/cli.py).
int do_book(const std::string& addr, const std::string& symbol) {
  pb::OrderBookRequest req;
  req.set_symbol(symbol);
  std::string bytes, resp_bytes, grpc_message;
  req.SerializeToString(&bytes);
  int grpc_status = -1;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/GetOrderBook",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0 ||
      grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::OrderBookResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  std::printf("[client] book %s: %d bids / %d asks\n", symbol.c_str(),
              resp.bids_size(), resp.asks_size());
  for (const auto& o : resp.bids()) {
    std::printf("  bid %lld@Q%d x%lld %s (%s)\n",
                static_cast<long long>(o.price()), o.scale(),
                static_cast<long long>(o.quantity()), o.order_id().c_str(),
                o.client_id().c_str());
  }
  for (const auto& o : resp.asks()) {
    std::printf("  ask %lld@Q%d x%lld %s (%s)\n",
                static_cast<long long>(o.price()), o.scale(),
                static_cast<long long>(o.quantity()), o.order_id().c_str(),
                o.client_id().c_str());
  }
  if (resp.bid_levels_size() || resp.ask_levels_size()) {
    std::printf("  L2:\n");
    for (const auto& lv : resp.bid_levels()) {
      std::printf("    bid %lld@Q4 x%lld (%d order(s))\n",
                  static_cast<long long>(lv.price()),
                  static_cast<long long>(lv.quantity()), lv.order_count());
    }
    for (const auto& lv : resp.ask_levels()) {
      std::printf("    ask %lld@Q4 x%lld (%d order(s))\n",
                  static_cast<long long>(lv.price()),
                  static_cast<long long>(lv.quantity()), lv.order_count());
    }
  }
  return 0;
}

int do_auction(const std::string& addr, const std::string& symbol) {
  pb::AuctionRequest req;
  req.set_symbol(symbol);
  std::string bytes, resp_bytes, grpc_message;
  req.SerializeToString(&bytes);
  int grpc_status = -1;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/RunAuction",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0 ||
      grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::AuctionResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  if (!resp.success()) {
    std::printf("[client] auction rejected: %s\n",
                resp.error_message().c_str());
    return 3;
  }
  if (symbol.empty()) {
    std::printf("[client] auction: %d symbol(s) crossed, %lld executed\n",
                resp.symbols_crossed(),
                static_cast<long long>(resp.executed_quantity()));
  } else if (resp.symbols_crossed() == 0) {
    std::printf("[client] auction %s: did not cross\n", symbol.c_str());
  } else {
    std::printf("[client] auction %s: cleared %lld@Q4 x%lld\n",
                symbol.c_str(),
                static_cast<long long>(resp.clearing_price()),
                static_cast<long long>(resp.executed_quantity()));
  }
  if (!resp.error_message().empty()) {  // partial-abort warning channel
    std::printf("[client] warning: %s\n", resp.error_message().c_str());
  }
  return 0;
}

int do_metrics(const std::string& addr) {
  pb::MetricsRequest req;
  std::string bytes, resp_bytes, grpc_message;
  req.SerializeToString(&bytes);
  int grpc_status = -1;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/GetMetrics",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0 ||
      grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::MetricsResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  std::vector<std::pair<std::string, long long>> counters(
      resp.counters().begin(), resp.counters().end());
  std::sort(counters.begin(), counters.end());
  for (const auto& [k, v] : counters) {
    std::printf("counter %s %lld\n", k.c_str(), v);
  }
  std::vector<std::pair<std::string, double>> gauges(
      resp.gauges().begin(), resp.gauges().end());
  std::sort(gauges.begin(), gauges.end());
  for (const auto& [k, v] : gauges) {
    std::printf("gauge %s %.1f\n", k.c_str(), v);
  }
  return 0;
}

}  // namespace

namespace {

// Server-streaming watcher: prints one line per message until the server
// closes the stream, the connection drops, or max_events arrive
// (max_events <= 0 = unbounded). Output parity with the Python CLI's
// watch-md / watch-orders loops.
int do_watch(const std::string& addr, bool market_data,
             const std::string& key, long max_events) {
  std::string request_bytes;
  std::string path;
  if (market_data) {
    pb::MarketDataRequest req;
    req.set_symbol(key);
    req.SerializeToString(&request_bytes);
    path = "/matching_engine.v1.MatchingEngine/StreamMarketData";
  } else {
    pb::OrderUpdatesRequest req;
    req.set_client_id(key);
    req.SerializeToString(&request_bytes);
    path = "/matching_engine.v1.MatchingEngine/StreamOrderUpdates";
  }
  BenchConn conn;
  if (!conn.open(addr)) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: connect\n");
    return 2;
  }
  conn.clear_timeout();
  uint32_t sid = conn.issue(path, request_bytes);
  if (sid == 0) {
    std::fprintf(stderr, "[client] rpc failed: send\n");
    return 2;
  }
  long seen = 0;
  for (;;) {
    std::string msg;
    int rc = conn.next_message(sid, &msg);
    if (rc < 0) {
      std::fprintf(stderr, "[client] stream closed\n");
      return 2;
    }
    if (rc == 0) {
      if (conn.stream_status() > 0) {
        std::fprintf(stderr, "[client] rpc failed: grpc-status=%d\n",
                     conn.stream_status());
        return 2;
      }
      return 0;  // clean end of stream (trailers)
    }
    if (market_data) {
      pb::MarketDataUpdate u;
      if (u.ParseFromString(msg)) {
        std::printf("[md] %s bid=%lld x%lld ask=%lld x%lld (Q%d)\n",
                    u.symbol().c_str(),
                    static_cast<long long>(u.best_bid()),
                    static_cast<long long>(u.bid_size()),
                    static_cast<long long>(u.best_ask()),
                    static_cast<long long>(u.ask_size()), u.scale());
      }
    } else {
      pb::OrderUpdate u;
      if (u.ParseFromString(msg)) {
        std::printf("[order] %s status=%d fill=%lld@%lld remaining=%lld\n",
                    u.order_id().c_str(), u.status(),
                    static_cast<long long>(u.fill_quantity()),
                    static_cast<long long>(u.fill_price()),
                    static_cast<long long>(u.remaining_quantity()));
      }
    }
    std::fflush(stdout);
    if (max_events > 0 && ++seen >= max_events) return 0;
  }
}

}  // namespace

int main(int argc, char** argv) {
  GOOGLE_PROTOBUF_VERIFY_VERSION;
  if (argc == 5 && std::strcmp(argv[1], "cancel") == 0) {
    return do_cancel(argv[2], argv[3], argv[4]);
  }
  if (argc == 6 && std::strcmp(argv[1], "amend") == 0) {
    return do_amend(argv[2], argv[3], argv[4], std::atoll(argv[5]));
  }
  if (argc == 4 && std::strcmp(argv[1], "book") == 0) {
    return do_book(argv[2], argv[3]);
  }
  if (argc == 3 && std::strcmp(argv[1], "metrics") == 0) {
    return do_metrics(argv[2]);
  }
  if ((argc == 3 || argc == 4) && std::strcmp(argv[1], "auction") == 0) {
    return do_auction(argv[2], argc == 4 ? argv[3] : "");
  }
  if ((argc == 4 || argc == 5) &&
      (std::strcmp(argv[1], "watch-md") == 0 ||
       std::strcmp(argv[1], "watch-orders") == 0)) {
    return do_watch(argv[2], std::strcmp(argv[1], "watch-md") == 0, argv[3],
                    argc == 5 ? std::atol(argv[4]) : 0);
  }
  if ((argc >= 5 && argc <= 8) && std::strcmp(argv[1], "bench") == 0) {
    // Optional [prefix]: a disjoint symbol namespace per loadgen run,
    // so dual-edge captures against one server drive FRESH books on
    // each edge instead of the second edge inheriting the first
    // edge's resting depth (which inflated its book-full rejects).
    return do_bench(argv[2], std::atoi(argv[3]), std::atoi(argv[4]),
                    argc >= 6 ? std::atoi(argv[5]) : 64,
                    argc >= 7 ? std::atoi(argv[6]) : 1,
                    argc >= 8 ? argv[7] : "S");
  }
  if (argc != 9) {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  const std::string addr = argv[1];
  pb::OrderRequest req;
  req.set_client_id(argv[2]);
  req.set_symbol(argv[3]);
  std::string side = argv[4];
  std::string otype = argv[5];
  for (auto& c : side) c = static_cast<char>(::toupper(c));
  for (auto& c : otype) c = static_cast<char>(::toupper(c));
  if (side == "BUY") {
    req.set_side(pb::BUY);
  } else if (side == "SELL") {
    req.set_side(pb::SELL);
  } else {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  // Optional time-in-force suffix: LIMIT:IOC, LIMIT:FOK, MARKET:FOK
  // (MARKET:IOC is accepted — MARKET is inherently immediate-or-cancel).
  std::string tif;
  auto colon = otype.find(':');
  if (colon != std::string::npos) {
    tif = otype.substr(colon + 1);
    otype = otype.substr(0, colon);
  }
  if (otype == "LIMIT") {
    req.set_order_type(pb::LIMIT);
  } else if (otype == "MARKET") {
    req.set_order_type(pb::MARKET);
  } else {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  if (tif == "IOC") {
    req.set_tif(pb::TIF_IOC);
  } else if (tif == "FOK") {
    req.set_tif(pb::TIF_FOK);
  } else if (!tif.empty() && tif != "GTC") {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  req.set_price(std::atoll(argv[6]));
  req.set_scale(std::atoi(argv[7]));
  req.set_quantity(std::atoll(argv[8]));

  std::string bytes;
  req.SerializeToString(&bytes);
  std::string resp_bytes, grpc_message;
  int grpc_status = -1;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/SubmitOrder",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0) {
    return 2;
  }
  if (grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::OrderResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  if (resp.success()) {
    std::printf("[client] accepted order_id=%s\n", resp.order_id().c_str());
    return 0;
  }
  std::printf("[client] rejected: %s\n", resp.error_message().c_str());
  return 3;
}
