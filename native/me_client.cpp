// me_client: the native CLI order submitter.
//
// Argv/exit-code/output parity with the reference client
// (src/client/client.cpp:10-29,49-56) and with the Python CLI
// (matching_engine_tpu/client/cli.py): positional args
//   <addr> <client_id> <symbol> <BUY|SELL> <LIMIT|MARKET> <price> <scale> <qty>
// plus a `cancel <addr> <client_id> <order_id>` subcommand; prints
// `[client] accepted order_id=...` / `[client] rejected: ...`;
// exit codes: 0 accepted, 1 usage, 2 RPC failure, 3 rejected.
//
// The transport is the framework's own HTTP/2 client (native/h2.cpp) — this
// image has no grpc++ — speaking cleartext h2c with prior knowledge, which
// is what insecure-creds gRPC servers accept. Interop with grpcio servers is
// tested in tests/test_native_client.py.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "gen/matching_engine.pb.h"
#include "h2.h"

namespace pb = matching_engine::v1;

namespace {

const char kUsage[] =
    "usage: me_client <addr> <client_id> <symbol> <BUY|SELL> <LIMIT|MARKET> "
    "<price> <scale> <quantity>\n"
    "   or: me_client cancel <addr> <client_id> <order_id>";

int dial(const std::string& addr) {
  std::string host = addr;
  std::string port = "50051";
  auto colon = addr.rfind(':');
  if (colon != std::string::npos) {
    host = addr.substr(0, colon);
    port = addr.substr(colon + 1);
  }
  if (host.empty() || host == "0.0.0.0") host = "127.0.0.1";
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // Same 30s deadline the Python CLI passes per call — a silent server
    // must fail the RPC, not hang the client forever.
    timeval tv{30, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  return fd;
}

bool send_all(int fd, const std::string& buf) {
  const char* p = buf.data();
  size_t left = buf.size();
  while (left > 0) {
    ssize_t n = ::send(fd, p, left, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    left -= static_cast<size_t>(n);
  }
  return true;
}

bool read_exact(int fd, uint8_t* dst, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, dst + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

// One unary gRPC call over a fresh h2c connection. Returns 0 and fills
// `response_payload` on success (any grpc-status, including errors, is
// reported via *grpc_status/*grpc_message).
int unary_call(const std::string& addr, const std::string& path,
               const std::string& request_bytes, std::string* response_payload,
               int* grpc_status, std::string* grpc_message) {
  int fd = dial(addr);
  if (fd < 0) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: connect %s\n",
                 addr.c_str());
    return -1;
  }
  std::string out(h2::kPreface, h2::kPrefaceLen);
  h2::write_frame_header(h2::F_SETTINGS, 0, 0, 0, &out);  // empty SETTINGS
  // Request headers (stream 1).
  std::string block;
  h2::hpack_encode(":method", "POST", &block);
  h2::hpack_encode(":scheme", "http", &block);
  h2::hpack_encode(":path", path, &block);
  h2::hpack_encode(":authority", addr, &block);
  h2::hpack_encode("te", "trailers", &block);
  h2::hpack_encode("content-type", "application/grpc", &block);
  h2::write_frame_header(h2::F_HEADERS, h2::FLAG_END_HEADERS, 1, block.size(),
                         &out);
  out += block;
  std::string data;
  h2::grpc_frame(request_bytes, &data);
  h2::write_frame_header(h2::F_DATA, h2::FLAG_END_STREAM, 1, data.size(),
                         &out);
  out += data;
  if (!send_all(fd, out)) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: send\n");
    ::close(fd);
    return -1;
  }

  // Read until our stream ends.
  h2::HpackDecoder hpack;
  std::string body;
  std::string header_block;
  bool stream_done = false;
  *grpc_status = -1;
  std::vector<uint8_t> payload;
  while (!stream_done) {
    uint8_t raw[9];
    if (!read_exact(fd, raw, 9)) break;
    h2::FrameHeader fh = h2::parse_frame_header(raw);
    if (fh.length > (1u << 24)) break;
    payload.resize(fh.length);
    if (fh.length && !read_exact(fd, payload.data(), fh.length)) break;
    switch (fh.type) {
      case h2::F_SETTINGS:
        if (!(fh.flags & h2::FLAG_ACK)) {
          std::string ack;
          h2::write_frame_header(h2::F_SETTINGS, h2::FLAG_ACK, 0, 0, &ack);
          send_all(fd, ack);
        }
        break;
      case h2::F_PING:
        if (!(fh.flags & h2::FLAG_ACK) && fh.length == 8) {
          std::string pong;
          h2::write_frame_header(h2::F_PING, h2::FLAG_ACK, 0, 8, &pong);
          pong.append(reinterpret_cast<char*>(payload.data()), 8);
          send_all(fd, pong);
        }
        break;
      case h2::F_HEADERS: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (fh.flags & h2::FLAG_PADDED) {
          if (n < 1) break;
          uint8_t pad = p[0];
          p += 1;
          n -= 1;
          if (pad <= n) n -= pad;
        }
        if (fh.flags & h2::FLAG_PRIORITY) {
          if (n < 5) break;
          p += 5;
          n -= 5;
        }
        header_block.assign(reinterpret_cast<const char*>(p), n);
        if (fh.flags & h2::FLAG_END_HEADERS) {
          std::vector<h2::Header> hs;
          if (!hpack.decode(
                  reinterpret_cast<const uint8_t*>(header_block.data()),
                  header_block.size(), &hs)) {
            ::close(fd);
            std::fprintf(stderr, "[client] rpc failed: INTERNAL: hpack\n");
            return -1;
          }
          header_block.clear();
          for (auto& h : hs) {
            if (h.name == "grpc-status") *grpc_status = std::atoi(h.value.c_str());
            if (h.name == "grpc-message") *grpc_message = h.value;
          }
          if (fh.flags & h2::FLAG_END_STREAM) stream_done = true;
        }
        break;
      }
      case h2::F_CONTINUATION: {
        header_block.append(reinterpret_cast<const char*>(payload.data()),
                            payload.size());
        if (fh.flags & h2::FLAG_END_HEADERS) {
          std::vector<h2::Header> hs;
          if (!hpack.decode(
                  reinterpret_cast<const uint8_t*>(header_block.data()),
                  header_block.size(), &hs)) {
            ::close(fd);
            return -1;
          }
          header_block.clear();
          for (auto& h : hs) {
            if (h.name == "grpc-status") *grpc_status = std::atoi(h.value.c_str());
            if (h.name == "grpc-message") *grpc_message = h.value;
          }
        }
        break;
      }
      case h2::F_DATA: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (fh.flags & h2::FLAG_PADDED) {
          if (n < 1) break;
          uint8_t pad = p[0];
          p += 1;
          n -= 1;
          if (pad <= n) n -= pad;
        }
        body.append(reinterpret_cast<const char*>(p), n);
        if (fh.flags & h2::FLAG_END_STREAM) stream_done = true;
        break;
      }
      case h2::F_RST_STREAM:
      case h2::F_GOAWAY:
        stream_done = true;
        break;
      default:
        break;
    }
  }
  ::close(fd);
  if (*grpc_status < 0) {
    std::fprintf(stderr, "[client] rpc failed: UNAVAILABLE: no trailers\n");
    return -1;
  }
  if (body.size() >= 5) {
    uint32_t mlen = (static_cast<uint8_t>(body[1]) << 24) |
                    (static_cast<uint8_t>(body[2]) << 16) |
                    (static_cast<uint8_t>(body[3]) << 8) |
                    static_cast<uint8_t>(body[4]);
    if (body.size() >= 5 + mlen) *response_payload = body.substr(5, mlen);
  }
  return 0;
}

int do_cancel(const std::string& addr, const std::string& client_id,
              const std::string& order_id) {
  pb::CancelRequest req;
  req.set_client_id(client_id);
  req.set_order_id(order_id);
  std::string bytes;
  req.SerializeToString(&bytes);
  std::string resp_bytes, grpc_message;
  int grpc_status;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/CancelOrder",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0) {
    return 2;
  }
  if (grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::CancelResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  if (resp.success()) {
    std::printf("[client] canceled order_id=%s\n", resp.order_id().c_str());
    return 0;
  }
  std::printf("[client] cancel rejected: %s\n", resp.error_message().c_str());
  return 3;
}

}  // namespace

int main(int argc, char** argv) {
  GOOGLE_PROTOBUF_VERIFY_VERSION;
  if (argc == 5 && std::strcmp(argv[1], "cancel") == 0) {
    return do_cancel(argv[2], argv[3], argv[4]);
  }
  if (argc != 9) {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  const std::string addr = argv[1];
  pb::OrderRequest req;
  req.set_client_id(argv[2]);
  req.set_symbol(argv[3]);
  std::string side = argv[4];
  std::string otype = argv[5];
  for (auto& c : side) c = static_cast<char>(::toupper(c));
  for (auto& c : otype) c = static_cast<char>(::toupper(c));
  if (side == "BUY") {
    req.set_side(pb::BUY);
  } else if (side == "SELL") {
    req.set_side(pb::SELL);
  } else {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  if (otype == "LIMIT") {
    req.set_order_type(pb::LIMIT);
  } else if (otype == "MARKET") {
    req.set_order_type(pb::MARKET);
  } else {
    std::fprintf(stderr, "%s\n", kUsage);
    return 1;
  }
  req.set_price(std::atoll(argv[6]));
  req.set_scale(std::atoi(argv[7]));
  req.set_quantity(std::atoll(argv[8]));

  std::string bytes;
  req.SerializeToString(&bytes);
  std::string resp_bytes, grpc_message;
  int grpc_status;
  if (unary_call(addr, "/matching_engine.v1.MatchingEngine/SubmitOrder",
                 bytes, &resp_bytes, &grpc_status, &grpc_message) != 0) {
    return 2;
  }
  if (grpc_status != 0) {
    std::fprintf(stderr, "[client] rpc failed: grpc-status=%d: %s\n",
                 grpc_status, grpc_message.c_str());
    return 2;
  }
  pb::OrderResponse resp;
  if (!resp.ParseFromString(resp_bytes)) {
    std::fprintf(stderr, "[client] rpc failed: bad response\n");
    return 2;
  }
  if (resp.success()) {
    std::printf("[client] accepted order_id=%s\n", resp.order_id().c_str());
    return 0;
  }
  std::printf("[client] rejected: %s\n", resp.error_message().c_str());
  return 3;
}
