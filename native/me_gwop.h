// MeGwOp: the wide op record shared between the serving edge's ring
// (me_gateway.cpp), the native lane engine (me_lanes.cpp), and the ctypes
// mirror in matching_engine_tpu/native/__init__.py — keep all three layouts
// identical.
#ifndef ME_GWOP_H_
#define ME_GWOP_H_

#include <cstdint>

extern "C" {

struct MeGwOp {
  uint64_t tag;
  int32_t op;        // 1 = submit, 2 = cancel, 3 = amend (qty-down)
  int32_t side;      // BUY=1 / SELL=2
  // Collapsed (order_type, tif) device code — proto.collapse_otype:
  // LIMIT=0, MARKET=1, LIMIT_IOC=2, LIMIT_FOK=3, MARKET_FOK=4.
  int32_t otype;
  int32_t price_q4;  // normalized; 0 for MARKET
  int64_t quantity;
  // Explicit lengths: proto3 strings may contain embedded NULs, which must
  // round-trip identically to the grpcio edge (no c-string truncation).
  int32_t symbol_len;
  int32_t client_id_len;
  int32_t order_id_len;
  char symbol[68];      // MAX_SYMBOL_BYTES=64
  char client_id[260];  // MAX_CLIENT_ID_BYTES=256
  char order_id[36];    // cancel/amend target "OID-<n>"
};

}  // extern "C"

#endif  // ME_GWOP_H_
