// MeGwOp: the wide op record shared between the serving edge's ring
// (me_gateway.cpp), the native lane engine (me_lanes.cpp), and the ctypes
// mirror in matching_engine_tpu/native/__init__.py — keep all three layouts
// identical.
#ifndef ME_GWOP_H_
#define ME_GWOP_H_

#include <cstdint>

extern "C" {

struct MeGwOp {
  uint64_t tag;
  int32_t op;        // 1 = submit, 2 = cancel, 3 = amend (qty-down)
  int32_t side;      // BUY=1 / SELL=2
  // Collapsed (order_type, tif) device code — proto.collapse_otype:
  // LIMIT=0, MARKET=1, LIMIT_IOC=2, LIMIT_FOK=3, MARKET_FOK=4.
  int32_t otype;
  int32_t price_q4;  // normalized; 0 for MARKET
  int64_t quantity;
  // Explicit lengths: proto3 strings may contain embedded NULs, which must
  // round-trip identically to the grpcio edge (no c-string truncation).
  int32_t symbol_len;
  int32_t client_id_len;
  int32_t order_id_len;
  char symbol[68];      // MAX_SYMBOL_BYTES=64
  char client_id[260];  // MAX_CLIENT_ID_BYTES=256
  char order_id[36];    // cancel/amend target "OID-<n>"
};

// MeOpRec: the flat binary op-record — the batch-edge wire format shared
// with matching_engine_tpu/domain/oprec.py (OPREC_DTYPE mirrors this
// byte-for-byte; the codec fuzz test pins the round trip). A
// SubmitOrderBatch payload / recorded op file is the 8-byte "MEOPREC1"
// magic followed by N of these; me_oprec_to_gwop (me_lanes.cpp) converts
// a packed run straight into tagged MeGwOp ring records in one crossing.
// Natural alignment — no packing pragma needed (max member align 8,
// sizeof == 384).
struct MeOpRec {
  uint8_t op;         // 1 = submit, 2 = cancel, 3 = amend (MeGwOp.op)
  uint8_t side;       // BUY=1 / SELL=2
  uint8_t otype;      // collapsed device code (see MeGwOp.otype)
  uint8_t flags;      // reserved, must be 0
  int32_t price_q4;   // normalized; 0 for MARKET
  int64_t quantity;   // submit qty / amend new-quantity
  uint16_t symbol_len;
  uint16_t client_id_len;
  uint16_t order_id_len;
  // Shm multi-producer lane: me_shmring_commit stamps the committing
  // handle's writer id here (0 = the anonymous/legacy single writer), so
  // the poller can demux responses and meter per-writer flow. On every
  // other edge (opfiles, batch RPC payloads) the field rides as 0 — the
  // old reserved pad, renamed, byte-identical.
  uint16_t writer;
  char symbol[64];     // == MAX_SYMBOL_BYTES
  char client_id[256];  // == MAX_CLIENT_ID_BYTES
  char order_id[36];
  char pad2[4];
};

// MeShmResp: one positional response record on the shared-memory ingress
// ring (native/me_shmring.cpp) — fixed 48 bytes, mirrored by
// SHM_RESP_DTYPE in domain/oprec.py (the ABI cross-checker pins the
// layout). `seq` is the request record's ring sequence; `reason` is a
// MeIngressReason code (the shm edge carries codes, not free text — the
// python client maps them via oprec.REASON_MESSAGES).
struct MeShmResp {
  uint64_t seq;
  int64_t remaining;   // amend ack: post-amend remaining quantity
  char order_id[24];   // "OID-<n>" (i64 fits in 24 with the prefix)
  uint8_t ok;
  uint8_t kind;        // 0 submit / 1 cancel / 2 amend
  uint8_t reason;      // MeIngressReason (0 when ok)
  uint8_t oid_len;
  // Writer id echoed from the request record (MeOpRec.writer):
  // me_shmring_respond_n routes each response into THIS writer's private
  // response sub-ring, and the stamp lets a client self-check that it
  // only ever sees its own acks.
  uint8_t writer;
  char pad[3];
};

// Reject reason codes on the shm ingress edge — ONE vocabulary across
// the C++ structural screen (me_oprec_flaws), the vectorized admission
// pipeline (server/admission.py) and the client (oprec.REASON_MESSAGES).
enum MeIngressReason {
  ME_REASON_NONE = 0,
  ME_REASON_MALFORMED = 1,   // codec-structural (record_flaws vocabulary)
  ME_REASON_RATE = 2,        // per-client rate limit
  ME_REASON_QTY = 3,         // per-client max order size
  ME_REASON_BAND = 4,        // price band around the symbol anchor
  ME_REASON_STP = 5,         // self-trade prevention
  ME_REASON_RING_FULL = 6,   // lane ring backpressure
  ME_REASON_ENGINE = 7,      // server-side dispatch failure
  ME_REASON_REJECTED = 8,    // engine app-level reject (capacity, unknown id)
};

}  // extern "C"

static_assert(sizeof(MeOpRec) == 384, "MeOpRec must mirror oprec.py");
static_assert(sizeof(MeShmResp) == 48, "MeShmResp must mirror oprec.py");

#endif  // ME_GWOP_H_
