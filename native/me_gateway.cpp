// me_gateway: the native gRPC serving edge.
//
// The reference's front end is a C++ grpc++ server
// (src/server/main.cpp:34-38, src/server/matching_engine_service.cpp:41-120).
// This is its counterpart in the TPU-native architecture: a C++ HTTP/2
// gateway (transport in native/h2.cpp — no grpc++/nghttp2 dev files exist in
// this image) that terminates gRPC, parses + validates the hot-path RPCs
// with the generated protobuf classes, and pushes fixed-size op records into
// a wide MPSC ring. The Python/JAX side owns the engine: a bridge thread
// drains the ring in time/size-windowed batches, runs the device dispatch,
// and completes each op back through `me_gateway_complete_*`, which builds
// and writes the protobuf response frames — so an order's bytes touch Python
// only as part of a dense batch, never per-RPC.
//
// Non-hot RPCs (GetOrderBook, GetMetrics, the two server-streaming RPCs)
// are forwarded to a registered Python callback and answered through
// `me_gateway_respond`, keeping exactly one implementation of book
// snapshots/metrics/stream hubs.
//
// Threading: one acceptor thread + one reader thread per connection.
// Responses are written by whichever thread completes them (bridge thread on
// the hot path) under a per-connection write mutex.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "gen/matching_engine.pb.h"
#include "h2.h"

namespace pb = matching_engine::v1;

// Domain validation lives in libme_native.so (same directory; linked via
// -l:libme_native.so + rpath $ORIGIN).
extern "C" {
int me_normalize_to_q4(long long price, int raw_scale, long long* out);
int me_validate_submit(int symbol_len, int client_id_len, long long quantity,
                       int side, int order_type, long long price, int scale,
                       long long max_price_q4, long long max_quantity,
                       int max_symbol_len, int max_client_id_len);
}

namespace {

// Submit validation with byte-identical reject messages to the Python
// service's domain.validate_submit (matching_engine_tpu/domain/order.py:85-129
// — itself the reference's rules at matching_engine_service.cpp:66-83 plus
// this framework's device bounds). Parity is enforced by
// tests/test_gateway.py::test_validate_message_parity, which replays the
// same invalid requests through both edges.
bool validate_submit_msg(const matching_engine::v1::OrderRequest& req,
                         long long max_price_q4, long long max_quantity,
                         int max_symbol_len, int max_client_id_len,
                         long long* price_q4_out, int* otype_out,
                         std::string* msg) {
  char buf[192];
  if (req.symbol().empty()) {
    *msg = "symbol is required";
    return false;
  }
  if (static_cast<int>(req.symbol().size()) > max_symbol_len) {
    std::snprintf(buf, sizeof(buf), "symbol exceeds %d bytes", max_symbol_len);
    *msg = buf;
    return false;
  }
  if (static_cast<int>(req.client_id().size()) > max_client_id_len) {
    std::snprintf(buf, sizeof(buf), "client_id exceeds %d bytes",
                  max_client_id_len);
    *msg = buf;
    return false;
  }
  if (req.quantity() <= 0) {
    *msg = "quantity must be positive";
    return false;
  }
  if (req.quantity() > max_quantity) {
    std::snprintf(buf, sizeof(buf),
                  "quantity %lld exceeds the engine maximum %lld "
                  "(int32 book-sum safety bound)",
                  static_cast<long long>(req.quantity()), max_quantity);
    *msg = buf;
    return false;
  }
  if (req.side() != 1 && req.side() != 2) {
    *msg = "side must be BUY or SELL";
    return false;
  }
  int otype = static_cast<int>(req.order_type());
  if (otype != 0 && otype != 1) {
    *msg = "order_type must be LIMIT or MARKET";
    return false;
  }
  // Collapse (order_type, tif) into the device otype lane code — the
  // same mapping as matching_engine_tpu/proto/__init__.py collapse_otype
  // (LIMIT=0, MARKET=1, LIMIT_IOC=2, LIMIT_FOK=3, MARKET_FOK=4; MARKET
  // is inherently IOC so MARKET+TIF_IOC stays 1).
  int tif = static_cast<int>(req.tif());
  if (tif == 0) {
    *otype_out = otype;
  } else if (tif == 1) {
    *otype_out = (otype == 0) ? 2 : 1;
  } else if (tif == 2) {
    *otype_out = (otype == 0) ? 3 : 4;
  } else {
    *msg = "unsupported (order_type, tif) combination";
    return false;
  }
  *price_q4_out = 0;
  if (otype == 0) {  // LIMIT
    if (req.price() <= 0) {
      *msg = "limit orders require a positive price";
      return false;
    }
    long long q4 = 0;
    int rc = me_normalize_to_q4(req.price(), req.scale(), &q4);
    if (rc == 1) {
      std::snprintf(buf, sizeof(buf), "scale %d out of range [0, 18]",
                    req.scale());
      *msg = buf;
      return false;
    }
    if (rc == 2) {
      std::snprintf(buf, sizeof(buf),
                    "price %lld at scale %d overflows int64 when normalized "
                    "to Q4",
                    static_cast<long long>(req.price()), req.scale());
      *msg = buf;
      return false;
    }
    if (q4 <= 0) {
      *msg = "limit price normalizes to zero at Q4 resolution";
      return false;
    }
    if (q4 > max_price_q4) {
      std::snprintf(buf, sizeof(buf),
                    "normalized Q4 price %lld exceeds the engine's int32 "
                    "price lane (max %lld)",
                    q4, max_price_q4);
      *msg = buf;
      return false;
    }
    *price_q4_out = q4;
  } else {  // MARKET
    if (req.scale() < 0 || req.scale() > 18) {
      std::snprintf(buf, sizeof(buf), "scale %d out of range [0, 18]",
                    req.scale());
      *msg = buf;
      return false;
    }
  }
  return true;
}

enum Method {
  M_UNKNOWN = 0,
  M_SUBMIT = 1,
  M_CANCEL = 2,
  M_BOOK = 3,
  M_METRICS = 4,
  M_STREAM_MD = 5,
  M_STREAM_OU = 6,
  M_AUCTION = 7,
  M_AMEND = 8,
  M_BATCH = 9,
};

int route(const std::string& path) {
  static const char kPrefix[] = "/matching_engine.v1.MatchingEngine/";
  if (path.rfind(kPrefix, 0) != 0) return M_UNKNOWN;
  const std::string m = path.substr(sizeof(kPrefix) - 1);
  if (m == "SubmitOrder") return M_SUBMIT;
  if (m == "CancelOrder") return M_CANCEL;
  if (m == "AmendOrder") return M_AMEND;
  if (m == "GetOrderBook") return M_BOOK;
  if (m == "GetMetrics") return M_METRICS;
  if (m == "StreamMarketData") return M_STREAM_MD;
  if (m == "StreamOrderUpdates") return M_STREAM_OU;
  if (m == "RunAuction") return M_AUCTION;  // forwarded (service-side)
  // Forwarded too: the op-record payload is already a flat binary batch,
  // so the python bridge hands it straight to the shared service handler
  // — no per-op C++ proto parse to win by keeping it here.
  if (m == "SubmitOrderBatch") return M_BATCH;
  return M_UNKNOWN;
}

}  // namespace

// MeGwOp (the wide op record popped by the Python bridge) lives in
// me_gwop.h — ONE definition shared with the lane engine; the ctypes
// mirror in matching_engine_tpu/native/__init__.py copies it.
#include "me_gwop.h"

extern "C" {

typedef void (*MeGwCallback)(uint64_t tag, int method, const uint8_t* data,
                             uint64_t len);

// From libme_native.so (me_lanes.cpp — the gateway links against it):
// the one op-record -> ring-record converter and the structural screen
// shared with the python edge (record_flaws' native twin).
int me_oprec_to_gwop(const uint8_t* payload, long long len,
                     uint64_t tag_base, MeGwOp* out, uint32_t max_n);
int me_oprec_flaws(const uint8_t* payload, long long len,
                   long long max_price_q4, long long max_quantity,
                   int32_t* codes, uint32_t max_n);

}  // extern "C"

namespace {

class Gateway;

// ---------------------------------------------------------------------------
// Connection
// ---------------------------------------------------------------------------

struct Stream {
  int method = M_UNKNOWN;
  std::string path;
  std::string header_block;  // accumulating HEADERS+CONTINUATION fragments
  bool headers_done = false;
  std::string body;
  bool request_done = false;
  bool closed = false;  // final response written or client RST
};
// Stream lifecycle: created by HEADERS (reader thread). Responder threads
// only ever FLAG an entry closed — the READER is the sole thread that
// erases map entries (tombstone sweep in the HEADERS handler), so the
// `Stream&` the reader holds across a frame can never dangle while a
// responder completes the same stream concurrently.

class Conn : public std::enable_shared_from_this<Conn> {
 public:
  Conn(int fd, Gateway* gw) : fd_(fd), gw_(gw) {}
  ~Conn() {
    if (fd_ >= 0) ::close(fd_);
  }

  void run();  // reader loop (owns the thread)

  // Serialized frame write; false once the connection is dead.
  bool write_all(const std::string& buf) {
    std::lock_guard<std::mutex> lk(write_mu_);
    return write_locked(buf);
  }

  void hard_close() {
    dead_.store(true, std::memory_order_relaxed);
    ::shutdown(fd_, SHUT_RDWR);
    fc_cv_.notify_all();  // unblock senders waiting for window
  }

  bool dead() const { return dead_.load(std::memory_order_relaxed); }

  // Response writers ------------------------------------------------------

  // Unary: HEADERS + DATA + trailers.
  bool write_unary(uint32_t stream_id, const std::string& message,
                   int grpc_status, const char* grpc_message);
  // One unary completion for a batched write: frames appended to *out
  // (data window reserved here, same discipline as send_data); the caller
  // flushes the accumulated buffer with ONE locked write. Returns 1 on
  // success; 0 when the connection died (blocking mode also returns 0 on
  // a window-wait timeout, after hard_close); -1 ONLY in non-blocking
  // mode when the send window is exhausted — nothing appended, nothing
  // reserved, the caller should flush its buffer and take the blocking
  // slow path for this item so already-built responses are never held
  // hostage to one starved stream.
  int append_unary(uint32_t stream_id, const std::string& message,
                   int grpc_status, const char* grpc_message,
                   std::string* out, bool block_for_window = true);
  // Streaming: headers (once) + one DATA frame.
  bool write_message(uint32_t stream_id, const std::string& message,
                     bool* headers_sent);
  // Trailers only (ends the stream; also used for trailers-only errors).
  bool write_trailers(uint32_t stream_id, int grpc_status,
                      const char* grpc_message, bool headers_already_sent);

  // Marks a stream finished from the responder side (reader sweeps later).
  void mark_closed(uint32_t stream_id) {
    {
      std::lock_guard<std::mutex> lk(streams_mu);
      auto it = streams.find(stream_id);
      if (it != streams.end()) it->second.closed = true;
    }
    std::lock_guard<std::mutex> lk(fc_mu_);
    stream_send_wnd_.erase(stream_id);
  }

  std::mutex streams_mu;  // guards streams map (reader + responders)
  std::unordered_map<uint32_t, Stream> streams;

 private:
  bool write_locked(const std::string& buf) {
    if (dead()) return false;
    const char* p = buf.data();
    size_t left = buf.size();
    while (left > 0) {
      ssize_t n = ::send(fd_, p, left, MSG_NOSIGNAL);
      if (n <= 0) {
        dead_.store(true, std::memory_order_relaxed);
        return false;
      }
      p += n;
      left -= static_cast<size_t>(n);
    }
    return true;
  }

  bool read_exact(uint8_t* dst, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = ::recv(fd_, dst + got, n - got, 0);
      if (r <= 0) return false;
      got += static_cast<size_t>(r);
    }
    return true;
  }

  // -- send-side flow control (RFC 7540 §5.2) ----------------------------
  // DATA writes reserve window under fc_mu_ first (blocking, bounded),
  // then serialize bytes under write_mu_ — so a window-starved response
  // can't stall control frames (pings, acks) from the reader thread.

  int64_t stream_wnd_locked(uint32_t sid) {
    auto it = stream_send_wnd_.find(sid);
    if (it == stream_send_wnd_.end()) {
      it = stream_send_wnd_.emplace(sid, peer_initial_wnd_).first;
    }
    return it->second;
  }

  // Sends `data` as DATA frames honoring conn+stream windows. Responses
  // are tiny against the 64KB default window, so the fast path never
  // waits; a client that grants no window for 3s while responses pend is
  // effectively dead and gets the connection closed — the wait is bounded
  // SHORT because completions run on the shared bridge drain thread, and
  // one stalled client must not head-of-line-block every other
  // connection's completions (nor, on the reader-thread reject path,
  // deadlock against the thread that would process its WINDOW_UPDATE).
  bool send_data(uint32_t sid, const std::string& data) {
    size_t off = 0;
    while (off < data.size()) {
      size_t want = std::min(data.size() - off, size_t{h2::kMaxFrameSize});
      size_t grant = 0;
      {
        std::unique_lock<std::mutex> lk(fc_mu_);
        auto deadline =
            std::chrono::steady_clock::now() + std::chrono::seconds(3);
        for (;;) {
          if (dead()) return false;
          int64_t avail = std::min<int64_t>(conn_send_wnd_,
                                            stream_wnd_locked(sid));
          if (avail > 0) {
            grant = std::min<size_t>(want, static_cast<size_t>(avail));
            conn_send_wnd_ -= static_cast<int64_t>(grant);
            stream_send_wnd_[sid] -= static_cast<int64_t>(grant);
            break;
          }
          if (fc_cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
            lk.unlock();
            hard_close();  // window-starved peer: fail fast, free the thread
            return false;
          }
        }
      }
      std::string out;
      h2::write_frame_header(h2::F_DATA, 0, sid, grant, &out);
      out.append(data, off, grant);
      if (!write_all(out)) return false;
      off += grant;
    }
    return true;
  }

  void window_update(uint32_t sid, uint32_t incr) {
    // Only track windows for streams that still exist: a peer spraying
    // WINDOW_UPDATE across arbitrary ids must not grow stream_send_wnd_
    // without bound. streams_mu is HELD across the fc_mu_ update so a
    // responder's mark_closed (which erases the entry) cannot interleave
    // between the open-check and the re-materialization. Nesting order is
    // streams_mu -> fc_mu_ everywhere; nothing takes them reversed.
    std::unique_lock<std::mutex> slk(streams_mu, std::defer_lock);
    if (sid != 0) {
      slk.lock();
      auto it = streams.find(sid);
      if (it == streams.end() || it->second.closed) return;
    }
    std::lock_guard<std::mutex> lk(fc_mu_);
    if (sid == 0) {
      conn_send_wnd_ += incr;
    } else {
      stream_wnd_locked(sid);  // materialize at peer initial
      stream_send_wnd_[sid] += incr;
    }
    fc_cv_.notify_all();
  }

  void apply_peer_initial_window(int32_t new_initial) {
    std::lock_guard<std::mutex> lk(fc_mu_);
    int64_t delta = static_cast<int64_t>(new_initial) - peer_initial_wnd_;
    peer_initial_wnd_ = new_initial;
    for (auto& [sid, wnd] : stream_send_wnd_) wnd += delta;  // RFC §6.9.2
    fc_cv_.notify_all();
  }

  void run_frames();  // the frame loop; run() wraps it with hard_close()
  void handle_headers_complete(uint32_t stream_id, Stream& st, bool end_stream);
  void handle_request(uint32_t stream_id, Stream& st);
  void handle_submit(uint32_t stream_id, const std::string& payload);
  void handle_cancel(uint32_t stream_id, const std::string& payload);
  void handle_amend(uint32_t stream_id, const std::string& payload);
  void handle_batch(uint32_t stream_id, const std::string& payload);
  void reject_submit(uint32_t stream_id, const std::string& order_id,
                     const std::string& error);
  void reject_amend(uint32_t stream_id, const std::string& order_id,
                    const std::string& error);
  void reject_cancel(uint32_t stream_id, const std::string& order_id,
                     const std::string& error);

  int fd_;
  Gateway* gw_;
  std::mutex write_mu_;
  std::atomic<bool> dead_{false};
  h2::HpackDecoder hpack_;
  uint32_t continuation_stream_ = 0;  // nonzero while awaiting CONTINUATION

  std::mutex fc_mu_;
  std::condition_variable fc_cv_;
  int64_t conn_send_wnd_ = 65535;
  int32_t peer_initial_wnd_ = 65535;
  std::unordered_map<uint32_t, int64_t> stream_send_wnd_;
};

// ---------------------------------------------------------------------------
// Gateway
// ---------------------------------------------------------------------------

struct Pending {
  std::weak_ptr<Conn> conn;
  uint32_t stream_id = 0;
  bool streaming = false;
  bool headers_sent = false;
};

// One in-gateway SubmitOrderBatch in flight (the native M_BATCH path):
// n positional slots, a run of consecutive ring tags for the records
// that passed the structural screen (pos maps tag offset -> original
// position), answered as ONE OrderBatchResponse once every ring member
// completes. Slots for screened-out records are prefilled.
struct BatchCtx {
  std::weak_ptr<Conn> conn;
  uint32_t stream_id = 0;
  uint32_t ring_n = 0;     // records pushed to the ring (tag run length)
  uint32_t unresolved = 0;  // ring members still awaiting completion
  std::vector<int32_t> pos;  // tag offset -> original record position
  std::vector<uint8_t> ok;
  std::vector<std::string> oid, err;
  std::vector<long long> remaining;
};

class Gateway {
 public:
  Gateway(std::string addr, uint32_t ring_cap, long long max_price_q4,
          long long max_quantity, int max_symbol_len, int max_client_id_len)
      : addr_(std::move(addr)),
        ring_cap_(ring_cap),
        max_price_q4_(max_price_q4),
        max_quantity_(max_quantity),
        // Clamp to the MeGwOp record capacity: the validated lengths bound
        // the memcpy in handle_submit, so a caller passing larger limits
        // must not be able to turn that into a buffer overflow.
        max_symbol_len_(std::min<int>(max_symbol_len, sizeof(MeGwOp::symbol))),
        max_client_id_len_(
            std::min<int>(max_client_id_len, sizeof(MeGwOp::client_id))) {}

  ~Gateway() { shutdown(); }

  int start() {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    std::string host = addr_;
    int port = 0;
    auto colon = addr_.rfind(':');
    if (colon != std::string::npos) {
      host = addr_.substr(0, colon);
      port = std::atoi(addr_.c_str() + colon + 1);
    }
    if (host.empty() || host == "0.0.0.0" || host == "[::]") {
      sa.sin_addr.s_addr = INADDR_ANY;
    } else if (::inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1) {
      if (host == "localhost") {
        ::inet_pton(AF_INET, "127.0.0.1", &sa.sin_addr);
      } else {
        ::close(fd);
        return -1;
      }
    }
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
        ::listen(fd, 256) != 0) {
      ::close(fd);
      return -1;
    }
    socklen_t len = sizeof(sa);
    ::getsockname(fd, reinterpret_cast<sockaddr*>(&sa), &len);
    port_ = ntohs(sa.sin_port);
    listen_fd_ = fd;
    acceptor_ = std::thread([this] { accept_loop(); });
    return port_;
  }

  void shutdown() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (acceptor_.joinable()) acceptor_.join();
    if (listen_fd_ >= 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      for (auto& c : conns_) c->hard_close();
    }
    // Connection threads are detached; hard_close wakes their recv() and
    // they exit. Wait (bounded) for the last one before the ring closes.
    {
      std::unique_lock<std::mutex> lk(active_mu_);
      active_cv_.wait_for(lk, std::chrono::seconds(10),
                          [&] { return active_conns_ == 0; });
    }
    ring_close();
  }

  bool idle() {
    std::lock_guard<std::mutex> lk(active_mu_);
    return active_conns_ == 0;
  }

  void conn_started() {
    std::lock_guard<std::mutex> lk(active_mu_);
    ++active_conns_;
  }

  void conn_finished() {
    std::lock_guard<std::mutex> lk(active_mu_);
    --active_conns_;
    active_cv_.notify_all();
  }

  // -- op ring -----------------------------------------------------------

  bool ring_push(const MeGwOp& op) {
    std::unique_lock<std::mutex> lk(ring_mu_);
    if (ring_closed_ || ring_.size() >= ring_cap_) {
      ring_rejects_.fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    ring_.push_back(op);
    ring_cv_.notify_one();
    return true;
  }

  // first_wait_us < 0 waits indefinitely for the first op; >= 0 bounds it
  // (0 records = timeout) so the pipelined bridge can finish a staged
  // dispatch during idle lulls.
  int ring_pop_batch(MeGwOp* out, uint32_t max, uint64_t window_us,
                     int64_t first_wait_us = -1) {
    std::unique_lock<std::mutex> lk(ring_mu_);
    if (first_wait_us < 0) {
      ring_cv_.wait(lk, [&] { return ring_closed_ || !ring_.empty(); });
    } else if (!ring_cv_.wait_for(
                   lk, std::chrono::microseconds(first_wait_us),
                   [&] { return ring_closed_ || !ring_.empty(); })) {
      return 0;
    }
    if (ring_.empty()) return -1;
    uint32_t n = 0;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::microseconds(window_us);
    for (;;) {
      while (n < max && !ring_.empty()) {
        out[n++] = ring_.front();
        ring_.pop_front();
      }
      if (n >= max || ring_closed_) break;
      if (ring_cv_.wait_until(lk, deadline, [&] {
            return ring_closed_ || !ring_.empty();
          })) {
        if (ring_.empty()) break;
        continue;
      }
      break;
    }
    return static_cast<int>(n);
  }

  void ring_close() {
    std::lock_guard<std::mutex> lk(ring_mu_);
    ring_closed_ = true;
    ring_cv_.notify_all();
  }

  // -- pending tag registry ----------------------------------------------

  uint64_t register_pending(const std::shared_ptr<Conn>& c, uint32_t stream_id,
                            bool streaming) {
    uint64_t tag = next_tag_.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> lk(pending_mu_);
    pending_[tag] = Pending{c, stream_id, streaming, false};
    return tag;
  }

  bool take_pending(uint64_t tag, Pending* out) {
    std::lock_guard<std::mutex> lk(pending_mu_);
    auto it = pending_.find(tag);
    if (it == pending_.end()) return false;
    *out = it->second;
    pending_.erase(it);
    return true;
  }

  // Peek without erasing (streaming intermediate messages).
  bool peek_pending(uint64_t tag, Pending* out) {
    std::lock_guard<std::mutex> lk(pending_mu_);
    auto it = pending_.find(tag);
    if (it == pending_.end()) return false;
    *out = it->second;
    return true;
  }

  void mark_headers_sent(uint64_t tag) {
    std::lock_guard<std::mutex> lk(pending_mu_);
    auto it = pending_.find(tag);
    if (it != pending_.end()) it->second.headers_sent = true;
  }

  void drop_pending(uint64_t tag) {
    std::lock_guard<std::mutex> lk(pending_mu_);
    pending_.erase(tag);
  }

  // -- in-gateway batch registry (native M_BATCH path) -------------------

  // Bulk push for the batch path: all-or-nothing under one ring lock —
  // a batch the ring can't hold entirely is refused whole (every
  // position answers "server overloaded"), never split.
  bool ring_push_n(const MeGwOp* ops, uint32_t n) {
    std::unique_lock<std::mutex> lk(ring_mu_);
    if (ring_closed_ || ring_.size() + n > ring_cap_) {
      ring_rejects_.fetch_add(n, std::memory_order_relaxed);
      return false;
    }
    for (uint32_t i = 0; i < n; i++) ring_.push_back(ops[i]);
    ring_cv_.notify_one();
    return true;
  }

  // Reserve a run of ring_n consecutive tags for one batch and register
  // its context. The completion entry points route member tags here via
  // try_complete_batch_member.
  uint64_t register_batch(std::shared_ptr<BatchCtx> ctx) {
    uint64_t base = next_tag_.fetch_add(ctx->ring_n,
                                        std::memory_order_relaxed);
    std::lock_guard<std::mutex> lk(batch_mu_);
    batches_[base] = std::move(ctx);
    return base;
  }

  void drop_batch(uint64_t base) {
    std::lock_guard<std::mutex> lk(batch_mu_);
    batches_.erase(base);
  }

  // Fill one batch member's slot; when the last member resolves, pop
  // the context out for the caller to serialize + answer. Returns false
  // when the tag belongs to no batch (a plain per-op pending tag).
  bool complete_batch_member(uint64_t tag, int kind, bool ok,
                             const std::string& oid, const std::string& err,
                             long long remaining,
                             std::shared_ptr<BatchCtx>* done) {
    std::lock_guard<std::mutex> lk(batch_mu_);
    auto it = batches_.upper_bound(tag);
    if (it == batches_.begin()) return false;
    --it;
    BatchCtx& b = *it->second;
    uint64_t off = tag - it->first;
    if (off >= b.ring_n) return false;
    int32_t p = b.pos[off];
    (void)kind;
    b.ok[p] = ok ? 1 : 0;
    b.oid[p] = oid;
    b.err[p] = err;
    b.remaining[p] = remaining;
    if (--b.unresolved == 0) {
      *done = std::move(it->second);
      batches_.erase(it);
    }
    return true;
  }

  std::mutex batch_mu_;
  std::map<uint64_t, std::shared_ptr<BatchCtx>> batches_;  // by base tag

  // Truncation sweep companion: take every in-flight native-batch
  // context too — a batch whose member completions fell in a truncated
  // tail would otherwise never resolve (its client hangs to the RPC
  // deadline and the BatchCtx entry leaks in batches_ forever). A late
  // completion for a swept member is a no-op (the map entry is gone).
  std::vector<std::shared_ptr<BatchCtx>> sweep_batches() {
    std::lock_guard<std::mutex> lk(batch_mu_);
    std::vector<std::shared_ptr<BatchCtx>> out;
    out.reserve(batches_.size());
    for (auto& [base, ctx] : batches_) out.push_back(ctx);
    batches_.clear();
    return out;
  }

  // Truncation sweep (me_gateway_complete_batch): take EVERY non-streaming
  // pending entry. A malformed completion buffer leaves the unparsed
  // tail's tags unknown, and pending_ doesn't record dispatch membership,
  // so the sweep over-approximates "the current dispatch" with all
  // in-flight unary tags — each swept client gets an immediate INTERNAL
  // error instead of hanging to its RPC deadline, and any late completion
  // for a swept tag is a no-op (take_pending already removed it).
  std::vector<Pending> sweep_pending_unary() {
    std::lock_guard<std::mutex> lk(pending_mu_);
    std::vector<Pending> out;
    for (auto it = pending_.begin(); it != pending_.end();) {
      if (!it->second.streaming) {
        out.push_back(it->second);
        it = pending_.erase(it);
      } else {
        ++it;
      }
    }
    return out;
  }

  MeGwCallback callback() const { return callback_; }
  void set_callback(MeGwCallback cb) { callback_ = cb; }

  // M_BATCH routing: 0 (default) = the in-gateway native path; 1 =
  // forward through the python callback (the bridge sets this when the
  // vectorized admission screens are enabled — they run python-side).
  bool forward_batch() const {
    return forward_batch_.load(std::memory_order_relaxed) != 0;
  }
  void set_forward_batch(int v) {
    forward_batch_.store(v, std::memory_order_relaxed);
  }

  long long max_price_q4() const { return max_price_q4_; }
  long long max_quantity() const { return max_quantity_; }
  int max_symbol_len() const { return max_symbol_len_; }
  int max_client_id_len() const { return max_client_id_len_; }

  uint64_t requests() const { return requests_.load(); }
  uint64_t ring_rejects() const { return ring_rejects_.load(); }
  uint64_t conns_accepted() const { return conns_accepted_.load(); }
  void count_request() { requests_.fetch_add(1, std::memory_order_relaxed); }

  int port() const { return port_; }

 private:
  void accept_loop() {
    for (;;) {
      int cfd = ::accept(listen_fd_, nullptr, nullptr);
      if (cfd < 0) {
        if (stopping_.load()) return;
        continue;
      }
      int one = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto conn = std::make_shared<Conn>(cfd, this);
      conns_accepted_.fetch_add(1, std::memory_order_relaxed);
      {
        std::lock_guard<std::mutex> lk(conns_mu_);
        conns_.push_back(conn);
        // Opportunistic cleanup of finished connections.
        if (conns_.size() > 64) {
          std::vector<std::shared_ptr<Conn>> live;
          for (auto& c : conns_) {
            if (!c->dead()) live.push_back(c);
          }
          conns_.swap(live);
        }
      }
      conn_started();
      std::thread([this, conn] {
        conn->run();
        conn_finished();
      }).detach();
    }
  }

  std::string addr_;
  int listen_fd_ = -1;
  int port_ = -1;
  std::thread acceptor_;
  std::atomic<bool> stopping_{false};

  std::mutex conns_mu_;
  std::vector<std::shared_ptr<Conn>> conns_;
  std::mutex active_mu_;
  std::condition_variable active_cv_;
  int active_conns_ = 0;

  const uint32_t ring_cap_;
  std::mutex ring_mu_;
  std::condition_variable ring_cv_;
  std::deque<MeGwOp> ring_;
  bool ring_closed_ = false;

  std::mutex pending_mu_;
  std::unordered_map<uint64_t, Pending> pending_;
  std::atomic<uint64_t> next_tag_{1};

  MeGwCallback callback_ = nullptr;
  std::atomic<int> forward_batch_{0};

  const long long max_price_q4_;
  const long long max_quantity_;
  const int max_symbol_len_;
  const int max_client_id_len_;

  std::atomic<uint64_t> requests_{0};
  std::atomic<uint64_t> ring_rejects_{0};
  std::atomic<uint64_t> conns_accepted_{0};
};

// ---------------------------------------------------------------------------
// Conn implementation
// ---------------------------------------------------------------------------

int Conn::append_unary(uint32_t stream_id, const std::string& message,
                       int grpc_status, const char* grpc_message,
                       std::string* out, bool block_for_window) {
  const size_t rollback = out->size();
  // The response header block is constant (status 200 + grpc
  // content-type) and our HPACK encoder is stateless for these literals:
  // encode once, reuse for every completion.
  static const std::string kHdrBlock = [] {
    std::string b;
    h2::hpack_encode(":status", "200", &b);
    h2::hpack_encode("content-type", "application/grpc", &b);
    return b;
  }();
  h2::write_frame_header(h2::F_HEADERS, h2::FLAG_END_HEADERS, stream_id,
                         kHdrBlock.size(), out);
  *out += kHdrBlock;

  std::string data;
  h2::grpc_frame(message, &data);
  // Reserve send window for the DATA payload (same partial-grant
  // discipline as send_data) but APPEND frames instead of writing them.
  size_t off = 0;
  while (off < data.size()) {
    size_t want = std::min(data.size() - off, size_t{h2::kMaxFrameSize});
    size_t grant = 0;
    {
      std::unique_lock<std::mutex> lk(fc_mu_);
      auto deadline =
          std::chrono::steady_clock::now() + std::chrono::seconds(3);
      for (;;) {
        if (dead()) {
          out->resize(rollback);
          return 0;
        }
        int64_t avail = std::min<int64_t>(conn_send_wnd_,
                                          stream_wnd_locked(stream_id));
        if (avail > 0) {
          grant = std::min<size_t>(want, static_cast<size_t>(avail));
          conn_send_wnd_ -= static_cast<int64_t>(grant);
          stream_send_wnd_[stream_id] -= static_cast<int64_t>(grant);
          break;
        }
        if (!block_for_window) {
          // Nothing reserved for this item beyond prior iterations'
          // grants — give those back and undo the appended frames so the
          // caller can retry this item on the blocking slow path.
          conn_send_wnd_ += static_cast<int64_t>(off);
          stream_send_wnd_[stream_id] += static_cast<int64_t>(off);
          out->resize(rollback);
          return -1;
        }
        if (fc_cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
          lk.unlock();
          hard_close();
          out->resize(rollback);
          return 0;
        }
      }
    }
    h2::write_frame_header(h2::F_DATA, 0, stream_id, grant, out);
    out->append(data, off, grant);
    off += grant;
  }

  // grpc-status 0 with no message is the overwhelmingly common trailer:
  // cache its block too.
  static const std::string kOkTrailerBlock = [] {
    std::string b;
    h2::hpack_encode("grpc-status", "0", &b);
    return b;
  }();
  if (grpc_status == 0 && !(grpc_message && *grpc_message)) {
    h2::write_frame_header(
        h2::F_HEADERS, h2::FLAG_END_HEADERS | h2::FLAG_END_STREAM, stream_id,
        kOkTrailerBlock.size(), out);
    *out += kOkTrailerBlock;
  } else {
    std::string trailer_block;
    h2::hpack_encode("grpc-status", std::to_string(grpc_status),
                     &trailer_block);
    if (grpc_message && *grpc_message) {
      h2::hpack_encode("grpc-message", grpc_message, &trailer_block);
    }
    h2::write_frame_header(
        h2::F_HEADERS, h2::FLAG_END_HEADERS | h2::FLAG_END_STREAM, stream_id,
        trailer_block.size(), out);
    *out += trailer_block;
  }
  mark_closed(stream_id);
  return 1;
}

bool Conn::write_unary(uint32_t stream_id, const std::string& message,
                       int grpc_status, const char* grpc_message) {
  std::string out;
  if (append_unary(stream_id, message, grpc_status, grpc_message, &out) != 1) {
    mark_closed(stream_id);
    return false;
  }
  return write_all(out);
}

bool Conn::write_message(uint32_t stream_id, const std::string& message,
                         bool* headers_sent) {
  if (!*headers_sent) {
    std::string hdr_block;
    h2::hpack_encode(":status", "200", &hdr_block);
    h2::hpack_encode("content-type", "application/grpc", &hdr_block);
    std::string hdrs;
    h2::write_frame_header(h2::F_HEADERS, h2::FLAG_END_HEADERS, stream_id,
                           hdr_block.size(), &hdrs);
    hdrs += hdr_block;
    if (!write_all(hdrs)) return false;
    *headers_sent = true;
  }
  std::string data;
  h2::grpc_frame(message, &data);
  return send_data(stream_id, data);
}

bool Conn::write_trailers(uint32_t stream_id, int grpc_status,
                          const char* grpc_message,
                          bool headers_already_sent) {
  std::string out;
  std::string block;
  if (!headers_already_sent) {
    // Trailers-only response (gRPC over HTTP/2 spec allows it).
    h2::hpack_encode(":status", "200", &block);
    h2::hpack_encode("content-type", "application/grpc", &block);
  }
  h2::hpack_encode("grpc-status", std::to_string(grpc_status), &block);
  if (grpc_message && *grpc_message) {
    h2::hpack_encode("grpc-message", grpc_message, &block);
  }
  h2::write_frame_header(h2::F_HEADERS,
                         h2::FLAG_END_HEADERS | h2::FLAG_END_STREAM, stream_id,
                         block.size(), &out);
  out += block;
  bool ok = write_all(out);
  mark_closed(stream_id);
  return ok;
}

void Conn::run() {
  run_frames();
  // EVERY exit path must release the socket promptly — a malformed frame
  // that merely returned would otherwise leave the fd open (and the client
  // hanging) until shutdown.
  hard_close();
}

void Conn::run_frames() {
  // 1. Client preface.
  uint8_t preface[h2::kPrefaceLen];
  if (!read_exact(preface, sizeof(preface)) ||
      std::memcmp(preface, h2::kPreface, sizeof(preface)) != 0) {
    return;
  }
  // 2. Our SETTINGS + a large connection window.
  {
    std::string out;
    // SETTINGS: MAX_CONCURRENT_STREAMS=4096, INITIAL_WINDOW_SIZE=1MiB.
    std::string payload;
    auto put_setting = [&payload](uint16_t id, uint32_t val) {
      payload.push_back(static_cast<char>(id >> 8));
      payload.push_back(static_cast<char>(id & 0xff));
      payload.push_back(static_cast<char>((val >> 24) & 0xff));
      payload.push_back(static_cast<char>((val >> 16) & 0xff));
      payload.push_back(static_cast<char>((val >> 8) & 0xff));
      payload.push_back(static_cast<char>(val & 0xff));
    };
    put_setting(0x3, 4096);      // MAX_CONCURRENT_STREAMS
    put_setting(0x4, 1 << 20);   // INITIAL_WINDOW_SIZE
    h2::write_frame_header(h2::F_SETTINGS, 0, 0, payload.size(), &out);
    out += payload;
    // Grow the connection-level receive window by 16MiB.
    h2::write_frame_header(h2::F_WINDOW_UPDATE, 0, 0, 4, &out);
    uint32_t incr = (16u << 20);
    out.push_back(static_cast<char>((incr >> 24) & 0xff));
    out.push_back(static_cast<char>((incr >> 16) & 0xff));
    out.push_back(static_cast<char>((incr >> 8) & 0xff));
    out.push_back(static_cast<char>(incr & 0xff));
    if (!write_all(out)) return;
  }

  // 3. Frame loop.
  std::vector<uint8_t> payload;
  for (;;) {
    uint8_t raw[9];
    if (!read_exact(raw, 9)) return;
    h2::FrameHeader fh = h2::parse_frame_header(raw);
    if (fh.length > (1u << 24)) return;  // sanity cap
    payload.resize(fh.length);
    if (fh.length && !read_exact(payload.data(), fh.length)) return;

    // A CONTINUATION sequence must be contiguous on one stream.
    if (continuation_stream_ != 0 &&
        (fh.type != h2::F_CONTINUATION || fh.stream_id != continuation_stream_)) {
      return;  // connection error per RFC 7540 §6.10
    }

    switch (fh.type) {
      case h2::F_SETTINGS: {
        if (!(fh.flags & h2::FLAG_ACK)) {
          // Honor the peer's INITIAL_WINDOW_SIZE for our DATA sends.
          for (size_t off = 0; off + 6 <= payload.size(); off += 6) {
            uint16_t id = static_cast<uint16_t>((payload[off] << 8) |
                                                payload[off + 1]);
            uint32_t val = (static_cast<uint32_t>(payload[off + 2]) << 24) |
                           (static_cast<uint32_t>(payload[off + 3]) << 16) |
                           (static_cast<uint32_t>(payload[off + 4]) << 8) |
                           payload[off + 5];
            if (id == 0x4 && val <= 0x7fffffffu) {
              apply_peer_initial_window(static_cast<int32_t>(val));
            }
          }
          std::string ack;
          h2::write_frame_header(h2::F_SETTINGS, h2::FLAG_ACK, 0, 0, &ack);
          if (!write_all(ack)) return;
        }
        break;
      }
      case h2::F_PING: {
        if (!(fh.flags & h2::FLAG_ACK) && fh.length == 8) {
          std::string pong;
          h2::write_frame_header(h2::F_PING, h2::FLAG_ACK, 0, 8, &pong);
          pong.append(reinterpret_cast<char*>(payload.data()), 8);
          if (!write_all(pong)) return;
        }
        break;
      }
      case h2::F_WINDOW_UPDATE: {
        if (fh.length == 4) {
          uint32_t incr = ((static_cast<uint32_t>(payload[0]) & 0x7f) << 24) |
                          (static_cast<uint32_t>(payload[1]) << 16) |
                          (static_cast<uint32_t>(payload[2]) << 8) |
                          payload[3];
          if (incr) window_update(fh.stream_id, incr);
        }
        break;
      }
      case h2::F_PRIORITY:
        break;
      case h2::F_GOAWAY:
        return;
      case h2::F_RST_STREAM: {
        // Reader-side close: safe to erase directly (no live Stream& here).
        {
          std::lock_guard<std::mutex> lk(streams_mu);
          streams.erase(fh.stream_id);
        }
        std::lock_guard<std::mutex> lk(fc_mu_);
        stream_send_wnd_.erase(fh.stream_id);
        break;
      }
      case h2::F_HEADERS: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (fh.flags & h2::FLAG_PADDED) {
          if (n < 1) return;
          uint8_t pad = p[0];
          p += 1;
          n -= 1;
          if (pad > n) return;
          n -= pad;
        }
        if (fh.flags & h2::FLAG_PRIORITY) {
          if (n < 5) return;
          p += 5;
          n -= 5;
        }
        Stream* st;
        {
          std::lock_guard<std::mutex> lk(streams_mu);
          // Sweep tombstones (responder-closed streams) while no Stream&
          // is held — the reader is the only thread that erases, so
          // references it takes below stay valid across the request.
          if (streams.size() > 64) {
            for (auto it = streams.begin(); it != streams.end();) {
              it = it->second.closed ? streams.erase(it) : std::next(it);
            }
          }
          Stream& ref = streams[fh.stream_id];
          if (ref.closed) break;  // late frames on a finished stream: drop
          st = &ref;
        }
        st->header_block.append(reinterpret_cast<const char*>(p), n);
        bool end_stream = (fh.flags & h2::FLAG_END_STREAM) != 0;
        if (fh.flags & h2::FLAG_END_HEADERS) {
          continuation_stream_ = 0;
          handle_headers_complete(fh.stream_id, *st, end_stream);
        } else {
          continuation_stream_ = fh.stream_id;
          if (end_stream) st->request_done = true;  // applies when complete
        }
        break;
      }
      case h2::F_CONTINUATION: {
        Stream* st;
        {
          std::lock_guard<std::mutex> lk(streams_mu);
          auto it = streams.find(fh.stream_id);
          if (it == streams.end()) return;
          if (it->second.closed) break;
          st = &it->second;
        }
        st->header_block.append(reinterpret_cast<const char*>(payload.data()),
                                payload.size());
        if (fh.flags & h2::FLAG_END_HEADERS) {
          continuation_stream_ = 0;
          handle_headers_complete(fh.stream_id, *st, st->request_done);
        }
        break;
      }
      case h2::F_DATA: {
        const uint8_t* p = payload.data();
        size_t n = payload.size();
        if (fh.flags & h2::FLAG_PADDED) {
          if (n < 1) return;
          uint8_t pad = p[0];
          p += 1;
          n -= 1;
          if (pad > n) return;
          n -= pad;
        }
        Stream* st = nullptr;
        {
          std::lock_guard<std::mutex> lk(streams_mu);
          auto it = streams.find(fh.stream_id);
          if (it != streams.end() && !it->second.closed) st = &it->second;
        }
        if (st != nullptr) {
          st->body.append(reinterpret_cast<const char*>(p), n);
        }
        // Replenish both flow-control windows for what we just consumed
        // (even for dropped frames on closed streams — the bytes arrived).
        if (payload.size() > 0) {
          std::string wu;
          uint32_t incr = static_cast<uint32_t>(payload.size());
          for (uint32_t sid : {0u, fh.stream_id}) {
            h2::write_frame_header(h2::F_WINDOW_UPDATE, 0, sid, 4, &wu);
            wu.push_back(static_cast<char>((incr >> 24) & 0xff));
            wu.push_back(static_cast<char>((incr >> 16) & 0xff));
            wu.push_back(static_cast<char>((incr >> 8) & 0xff));
            wu.push_back(static_cast<char>(incr & 0xff));
          }
          if (!write_all(wu)) return;
        }
        if (st != nullptr && (fh.flags & h2::FLAG_END_STREAM)) {
          st->request_done = true;
          handle_request(fh.stream_id, *st);
        }
        break;
      }
      default:
        break;  // PUSH_PROMISE from a client is invalid; ignore others
    }
  }
}

void Conn::handle_headers_complete(uint32_t stream_id, Stream& st,
                                   bool end_stream) {
  if (st.headers_done) {
    // Trailers from the client: nothing to read in them for our methods.
    st.header_block.clear();
    if (end_stream && !st.request_done) {
      st.request_done = true;
      handle_request(stream_id, st);
    }
    return;
  }
  std::vector<h2::Header> headers;
  if (!hpack_.decode(
          reinterpret_cast<const uint8_t*>(st.header_block.data()),
          st.header_block.size(), &headers)) {
    hard_close();  // HPACK failure is a connection error
    return;
  }
  st.header_block.clear();
  st.headers_done = true;
  for (auto& h : headers) {
    if (h.name == ":path") st.path = h.value;
  }
  st.method = route(st.path);
  if (end_stream) {
    st.request_done = true;
    handle_request(stream_id, st);
  }
}

void Conn::handle_request(uint32_t stream_id, Stream& st) {
  gw_->count_request();
  if (st.method == M_UNKNOWN) {
    write_trailers(stream_id, 12, "unknown method", false);  // UNIMPLEMENTED
    return;
  }
  // Extract the first gRPC message from the body.
  if (st.body.size() < 5) {
    write_trailers(stream_id, 13, "malformed request body", false);  // INTERNAL
    return;
  }
  uint8_t compressed = static_cast<uint8_t>(st.body[0]);
  uint32_t mlen = (static_cast<uint8_t>(st.body[1]) << 24) |
                  (static_cast<uint8_t>(st.body[2]) << 16) |
                  (static_cast<uint8_t>(st.body[3]) << 8) |
                  static_cast<uint8_t>(st.body[4]);
  if (compressed != 0) {
    write_trailers(stream_id, 12, "compression not supported", false);
    return;
  }
  if (st.body.size() < 5 + static_cast<size_t>(mlen)) {
    write_trailers(stream_id, 13, "truncated request body", false);
    return;
  }
  std::string payload = st.body.substr(5, mlen);
  st.body.clear();

  switch (st.method) {
    case M_SUBMIT:
      handle_submit(stream_id, payload);
      return;
    case M_CANCEL:
      handle_cancel(stream_id, payload);
      return;
    case M_AMEND:
      handle_amend(stream_id, payload);
      return;
    case M_BATCH:
      // In-gateway native batch path: structural screen + record
      // conversion + one bulk ring push, all here — the python bridge
      // never sees the payload (it used to forward it whole through the
      // callback worker and back through the grpcio service handler).
      // With forward_batch set (the bridge runs vectorized admission
      // screens only python-side), fall through to the callback path.
      if (!gw_->forward_batch()) {
        handle_batch(stream_id, payload);
        return;
      }
      [[fallthrough]];  // forwarded like book/metrics/streams
    default: {
      // Forwarded methods (book/metrics/streams) go through the Python
      // callback; the response arrives via me_gateway_respond.
      MeGwCallback cb = gw_->callback();
      if (cb == nullptr) {
        write_trailers(stream_id, 14, "service not ready", false);  // UNAVAILABLE
        return;
      }
      bool streaming =
          st.method == M_STREAM_MD || st.method == M_STREAM_OU;
      uint64_t tag =
          gw_->register_pending(shared_from_this(), stream_id, streaming);
      cb(tag, st.method, reinterpret_cast<const uint8_t*>(payload.data()),
         payload.size());
      return;
    }
  }
}

void Conn::reject_submit(uint32_t stream_id, const std::string& order_id,
                         const std::string& error) {
  pb::OrderResponse resp;
  resp.set_order_id(order_id);
  resp.set_success(false);
  resp.set_error_message(error);
  std::string bytes;
  resp.SerializeToString(&bytes);
  write_unary(stream_id, bytes, 0, nullptr);
}

void Conn::reject_amend(uint32_t stream_id, const std::string& order_id,
                        const std::string& error) {
  pb::AmendResponse resp;
  resp.set_order_id(order_id);
  resp.set_success(false);
  resp.set_error_message(error);
  std::string bytes;
  resp.SerializeToString(&bytes);
  write_unary(stream_id, bytes, 0, nullptr);
}

void Conn::reject_cancel(uint32_t stream_id, const std::string& order_id,
                         const std::string& error) {
  pb::CancelResponse resp;
  resp.set_order_id(order_id);
  resp.set_success(false);
  resp.set_error_message(error);
  std::string bytes;
  resp.SerializeToString(&bytes);
  write_unary(stream_id, bytes, 0, nullptr);
}

std::string flaw_message(int32_t code, uint8_t op, long long max_qty,
                         long long max_price_q4);  // defined below handle_batch

// Native per-op admission screen (the PR 16 residual): run the SAME
// structural pass every bulk edge runs (me_oprec_flaws — record_flaws'
// native twin) over the single validated record, so per-op RPC traffic
// gets the identical screen vocabulary without a python hop. For
// submits the proto validation above is a superset and this is
// belt-and-braces; for cancels/amends it is where the per-op path picks
// up the record-box rules (empty target -> "unknown order id") and the
// engine quantity cap the batch edge already enforced on amends.
int32_t perop_flaw(const MeOpRec& rec, long long max_price_q4,
                   long long max_quantity) {
  int32_t code = 0;
  if (me_oprec_flaws(reinterpret_cast<const uint8_t*>(&rec),
                     static_cast<long long>(sizeof(MeOpRec)), max_price_q4,
                     max_quantity, &code, 1) != 1)
    return 0;  // a ragged single record can't happen for an in-stack rec
  return code;
}

void Conn::handle_submit(uint32_t stream_id, const std::string& payload) {
  pb::OrderRequest req;
  if (!req.ParseFromString(payload)) {
    write_trailers(stream_id, 13, "unparsable OrderRequest", false);
    return;
  }
  // Validation parity with the Python service: app-level reject, gRPC OK
  // (reference matching_engine_service.cpp:66-83 semantics).
  long long price_q4 = 0;
  int otype = 0;
  std::string err;
  if (!validate_submit_msg(req, gw_->max_price_q4(), gw_->max_quantity(),
                           gw_->max_symbol_len(), gw_->max_client_id_len(),
                           &price_q4, &otype, &err)) {
    reject_submit(stream_id, "", err);
    return;
  }
  {
    MeOpRec rec{};
    rec.op = 1;
    rec.side = static_cast<uint8_t>(req.side());
    rec.otype = static_cast<uint8_t>(otype);
    rec.price_q4 = static_cast<int32_t>(price_q4);
    rec.quantity = req.quantity();
    rec.symbol_len = static_cast<uint16_t>(req.symbol().size());
    std::memcpy(rec.symbol, req.symbol().data(),
                std::min(req.symbol().size(), sizeof(rec.symbol)));
    rec.client_id_len = static_cast<uint16_t>(
        std::min(req.client_id().size(), sizeof(rec.client_id)));
    std::memcpy(rec.client_id, req.client_id().data(), rec.client_id_len);
    int32_t code = perop_flaw(rec, gw_->max_price_q4(), gw_->max_quantity());
    if (code != 0) {
      reject_submit(stream_id, "",
                    flaw_message(code, rec.op, gw_->max_quantity(),
                                 gw_->max_price_q4()));
      return;
    }
  }
  MeGwOp op{};
  op.op = 1;
  op.side = req.side();
  op.otype = otype;
  op.price_q4 = static_cast<int32_t>(price_q4);
  op.quantity = req.quantity();
  // Length-prefixed copies: proto3 strings may hold embedded NULs and must
  // book identically to the grpcio edge (lengths were validated above).
  op.symbol_len = static_cast<int32_t>(req.symbol().size());
  std::memcpy(op.symbol, req.symbol().data(), req.symbol().size());
  op.client_id_len = static_cast<int32_t>(req.client_id().size());
  std::memcpy(op.client_id, req.client_id().data(), req.client_id().size());
  op.tag = gw_->register_pending(shared_from_this(), stream_id, false);
  if (!gw_->ring_push(op)) {
    gw_->drop_pending(op.tag);
    reject_submit(stream_id, "", "server overloaded");
    return;
  }
}

void Conn::handle_cancel(uint32_t stream_id, const std::string& payload) {
  pb::CancelRequest req;
  if (!req.ParseFromString(payload)) {
    write_trailers(stream_id, 13, "unparsable CancelRequest", false);
    return;
  }
  if (req.client_id().empty()) {
    reject_cancel(stream_id, req.order_id(), "client_id is required");
    return;
  }
  if (req.order_id().size() > sizeof(MeGwOp::order_id)) {
    reject_cancel(stream_id, req.order_id(), "unknown order id");
    return;
  }
  {
    // Screen rec lengths are CLAMPED to the record boxes (like the
    // MeGwOp copy below): an over-long requester id must keep resolving
    // as wrong-owner in the bridge, not trip the box rule here.
    MeOpRec rec{};
    rec.op = 2;
    rec.order_id_len = static_cast<uint16_t>(
        std::min(req.order_id().size(), sizeof(rec.order_id)));
    std::memcpy(rec.order_id, req.order_id().data(), rec.order_id_len);
    rec.client_id_len = static_cast<uint16_t>(
        std::min(req.client_id().size(), sizeof(rec.client_id)));
    std::memcpy(rec.client_id, req.client_id().data(), rec.client_id_len);
    int32_t code = perop_flaw(rec, gw_->max_price_q4(), gw_->max_quantity());
    if (code != 0) {
      reject_cancel(stream_id, req.order_id(),
                    flaw_message(code, rec.op, gw_->max_quantity(),
                                 gw_->max_price_q4()));
      return;
    }
  }
  MeGwOp op{};
  op.op = 2;
  op.order_id_len = static_cast<int32_t>(req.order_id().size());
  std::memcpy(op.order_id, req.order_id().data(), req.order_id().size());
  // An over-long requester id is clamped to the record capacity: every
  // real owner id is <= 256 bytes (submit validation), so the clamped
  // 260-byte value still compares unequal to all of them and the bridge
  // resolves unknown-order vs wrong-owner exactly as the grpcio edge does.
  size_t cid = std::min(req.client_id().size(), sizeof(MeGwOp::client_id));
  op.client_id_len = static_cast<int32_t>(cid);
  std::memcpy(op.client_id, req.client_id().data(), cid);
  op.tag = gw_->register_pending(shared_from_this(), stream_id, false);
  if (!gw_->ring_push(op)) {
    gw_->drop_pending(op.tag);
    reject_cancel(stream_id, req.order_id(), "server overloaded");
    return;
  }
}

void Conn::handle_amend(uint32_t stream_id, const std::string& payload) {
  // Validation parity with service.AmendOrder: client_id required,
  // new_quantity > 0; directory checks (unknown id / wrong client /
  // feasibility) happen in the bridge + kernel, as for cancels.
  pb::AmendRequest req;
  if (!req.ParseFromString(payload)) {
    write_trailers(stream_id, 13, "unparsable AmendRequest", false);
    return;
  }
  if (req.client_id().empty()) {
    reject_amend(stream_id, req.order_id(), "client_id is required");
    return;
  }
  if (req.new_quantity() <= 0) {
    reject_amend(stream_id, req.order_id(), "new_quantity must be positive");
    return;
  }
  if (req.order_id().size() > sizeof(MeGwOp::order_id)) {
    reject_amend(stream_id, req.order_id(), "unknown order id");
    return;
  }
  {
    MeOpRec rec{};
    rec.op = 3;
    rec.quantity = req.new_quantity();
    rec.order_id_len = static_cast<uint16_t>(
        std::min(req.order_id().size(), sizeof(rec.order_id)));
    std::memcpy(rec.order_id, req.order_id().data(), rec.order_id_len);
    rec.client_id_len = static_cast<uint16_t>(
        std::min(req.client_id().size(), sizeof(rec.client_id)));
    std::memcpy(rec.client_id, req.client_id().data(), rec.client_id_len);
    int32_t code = perop_flaw(rec, gw_->max_price_q4(), gw_->max_quantity());
    if (code != 0) {
      // The one per-op screen with real teeth: an amend new_quantity
      // over the engine cap (code 10) — the bulk edges always enforced
      // it; service.AmendOrder mirrors the check for edge parity.
      reject_amend(stream_id, req.order_id(),
                   flaw_message(code, rec.op, gw_->max_quantity(),
                                gw_->max_price_q4()));
      return;
    }
  }
  MeGwOp op{};
  op.op = 3;
  op.quantity = req.new_quantity();
  op.order_id_len = static_cast<int32_t>(req.order_id().size());
  std::memcpy(op.order_id, req.order_id().data(), req.order_id().size());
  size_t cid = std::min(req.client_id().size(), sizeof(MeGwOp::client_id));
  op.client_id_len = static_cast<int32_t>(cid);
  std::memcpy(op.client_id, req.client_id().data(), cid);
  op.tag = gw_->register_pending(shared_from_this(), stream_id, false);
  if (!gw_->ring_push(op)) {
    gw_->drop_pending(op.tag);
    reject_amend(stream_id, req.order_id(), "server overloaded");
    return;
  }
}

// Serialize a finished BatchCtx as ONE OrderBatchResponse and answer the
// RPC (positional parallel arrays — the grpcio edge's exact contract).
void send_batch_response(const std::shared_ptr<BatchCtx>& b) {
  auto conn = b->conn.lock();
  if (!conn || conn->dead()) return;
  pb::OrderBatchResponse resp;
  resp.set_success(true);
  for (size_t i = 0; i < b->ok.size(); i++) {
    resp.add_ok(b->ok[i] != 0);
    resp.add_order_id(b->oid[i]);
    resp.add_error(b->err[i]);
    resp.add_remaining(b->remaining[i]);
  }
  std::string bytes;
  resp.SerializeToString(&bytes);
  conn->write_unary(b->stream_id, bytes, 0, nullptr);
}

// me_oprec_flaws code -> the record_flaws message (domain/oprec.py
// flaw_message — keep the strings in lockstep; the skip-guarded gateway
// test compares against the python screen's wording).
std::string flaw_message(int32_t code, uint8_t op, long long max_qty,
                         long long max_price_q4) {
  switch (code) {
    case 1: return "invalid op code (1=submit, 2=cancel, 3=amend)";
    case 2: return "reserved flags must be 0";
    case 3: return "identifier length exceeds the record box";
    case 4: return "symbol is required";
    case 5: return "unknown order id";
    case 6: return "client_id is required";
    case 7: return "side must be BUY or SELL";
    case 8: return "unsupported (order_type, tif) combination";
    case 9: return op == 3 ? "new_quantity must be positive"
                           : "quantity must be positive";
    case 10:
      return "quantity exceeds the engine maximum " +
             std::to_string(max_qty) + " (int32 book-sum safety bound)";
    case 11:
      return "price_q4 out of the engine's int32 price lane (0, " +
             std::to_string(max_price_q4) + "]";
    case 12: return "MARKET records must carry price_q4=0";
    default: return "malformed record";
  }
}

// The in-gateway native batch path (ROADMAP Open item 3c): decode the
// OrderBatchRequest HERE, run the structural screen (me_oprec_flaws —
// record_flaws' native twin), convert the clean run straight into
// tagged ring records (me_oprec_to_gwop) and bulk-push them under one
// ring lock (ring_push_n) — the python bridge no longer sees batch
// payloads at all. Host checks / id assignment stay with the ring
// consumer (the native-lane dispatch or the bridge record loop), whose
// completions resolve the batch's positional slots by tag.
void Conn::handle_batch(uint32_t stream_id, const std::string& payload) {
  pb::OrderBatchRequest req;
  if (!req.ParseFromString(payload)) {
    write_trailers(stream_id, 13, "unparsable OrderBatchRequest", false);
    return;
  }
  auto fail_whole = [&](const std::string& msg) {
    // Payload-poisoning defects answer like the grpcio edge: an
    // app-level success=false, never a transport error.
    pb::OrderBatchResponse resp;
    resp.set_success(false);
    resp.set_error_message(msg);
    std::string bytes;
    resp.SerializeToString(&bytes);
    write_unary(stream_id, bytes, 0, nullptr);
  };
  const std::string& ops = req.ops();
  if (ops.size() < 8 || std::memcmp(ops.data(), "MEOPREC1", 8) != 0) {
    fail_whole("bad op-record magic (not an MEOPREC1 payload)");
    return;
  }
  const uint8_t* body = reinterpret_cast<const uint8_t*>(ops.data()) + 8;
  long long blen = static_cast<long long>(ops.size()) - 8;
  if (blen % static_cast<long long>(sizeof(MeOpRec)) != 0) {
    fail_whole("truncated op-record payload (" + std::to_string(blen) +
               " bytes is not a multiple of the " +
               std::to_string(sizeof(MeOpRec)) + "-byte record)");
    return;
  }
  long long n = blen / static_cast<long long>(sizeof(MeOpRec));
  constexpr long long kBatchCap = 1 << 16;  // service._BATCH_RECORD_CAP
  if (n > kBatchCap) {
    fail_whole("op-record batch of " + std::to_string(n) +
               " exceeds the per-request cap " + std::to_string(kBatchCap));
    return;
  }
  auto ctx = std::make_shared<BatchCtx>();
  ctx->conn = shared_from_this();
  ctx->stream_id = stream_id;
  ctx->ok.assign(n, 0);
  ctx->oid.assign(n, std::string());
  ctx->err.assign(n, std::string());
  ctx->remaining.assign(n, 0);
  if (n == 0) {
    send_batch_response(ctx);
    return;
  }
  std::vector<int32_t> codes(n, 0);
  if (me_oprec_flaws(body, blen, gw_->max_price_q4(), gw_->max_quantity(),
                     codes.data(), static_cast<uint32_t>(n)) != n) {
    fail_whole("malformed op-record payload");
    return;
  }
  const MeOpRec* recs = reinterpret_cast<const MeOpRec*>(body);
  std::vector<MeOpRec> clean;
  clean.reserve(n);
  for (long long i = 0; i < n; i++) {
    if (codes[i] != 0) {
      ctx->err[i] = flaw_message(codes[i], recs[i].op, gw_->max_quantity(),
                                 gw_->max_price_q4());
    } else {
      ctx->pos.push_back(static_cast<int32_t>(i));
      clean.push_back(recs[i]);
    }
  }
  if (clean.empty()) {
    send_batch_response(ctx);
    return;
  }
  ctx->ring_n = static_cast<uint32_t>(clean.size());
  ctx->unresolved = ctx->ring_n;
  std::shared_ptr<BatchCtx> local = ctx;  // keep alive past register
  uint64_t base = gw_->register_batch(std::move(ctx));
  std::vector<MeGwOp> gwops(clean.size());
  if (me_oprec_to_gwop(reinterpret_cast<const uint8_t*>(clean.data()),
                       static_cast<long long>(clean.size() *
                                              sizeof(MeOpRec)),
                       base, gwops.data(),
                       static_cast<uint32_t>(clean.size())) !=
      static_cast<int>(clean.size())) {
    // The screen already vetted structure — this is converter skew.
    gw_->drop_batch(base);
    fail_whole("op-record conversion failed (server-side skew)");
    return;
  }
  if (!gw_->ring_push_n(gwops.data(), static_cast<uint32_t>(gwops.size()))) {
    gw_->drop_batch(base);
    for (int32_t p : local->pos) local->err[p] = "server overloaded";
    send_batch_response(local);
    return;
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI (consumed by matching_engine_tpu/native via ctypes)
// ---------------------------------------------------------------------------

extern "C" {

void* me_gateway_create(const char* addr, uint32_t ring_capacity,
                        long long max_price_q4, long long max_quantity,
                        int max_symbol_len, int max_client_id_len) {
  return new Gateway(addr ? addr : "0.0.0.0:0", ring_capacity, max_price_q4,
                     max_quantity, max_symbol_len, max_client_id_len);
}

int me_gateway_start(void* g) { return static_cast<Gateway*>(g)->start(); }

int me_gateway_port(void* g) { return static_cast<Gateway*>(g)->port(); }

void me_gateway_set_callback(void* g, MeGwCallback cb) {
  static_cast<Gateway*>(g)->set_callback(cb);
}

void me_gateway_set_forward_batch(void* g, int v) {
  static_cast<Gateway*>(g)->set_forward_batch(v);
}

int me_gw_pop_batch(void* g, MeGwOp* out, uint32_t max, uint64_t window_us) {
  return static_cast<Gateway*>(g)->ring_pop_batch(out, max, window_us);
}

int me_gw_pop_batch_timed(void* g, MeGwOp* out, uint32_t max,
                          uint64_t window_us, int64_t first_wait_us) {
  return static_cast<Gateway*>(g)->ring_pop_batch(out, max, window_us,
                                                  first_wait_us);
}

// Hot-path completions: build the protobuf response and write all frames.
void me_gateway_complete_submit(void* g, uint64_t tag, int success,
                                const char* order_id, const char* error) {
  auto* gw = static_cast<Gateway*>(g);
  {
    std::shared_ptr<BatchCtx> done;
    if (gw->complete_batch_member(tag, 0, success != 0,
                                  order_id ? order_id : "",
                                  error ? error : "", 0, &done)) {
      if (done) send_batch_response(done);
      return;
    }
  }
  Pending p;
  if (!gw->take_pending(tag, &p)) return;
  auto conn = p.conn.lock();
  if (!conn || conn->dead()) return;
  pb::OrderResponse resp;
  resp.set_order_id(order_id ? order_id : "");
  resp.set_success(success != 0);
  if (error && *error) resp.set_error_message(error);
  std::string bytes;
  resp.SerializeToString(&bytes);
  conn->write_unary(p.stream_id, bytes, 0, nullptr);
}

void me_gateway_complete_cancel(void* g, uint64_t tag, int success,
                                const char* order_id, const char* error) {
  auto* gw = static_cast<Gateway*>(g);
  {
    std::shared_ptr<BatchCtx> done;
    if (gw->complete_batch_member(tag, 1, success != 0,
                                  order_id ? order_id : "",
                                  error ? error : "", 0, &done)) {
      if (done) send_batch_response(done);
      return;
    }
  }
  Pending p;
  if (!gw->take_pending(tag, &p)) return;
  auto conn = p.conn.lock();
  if (!conn || conn->dead()) return;
  pb::CancelResponse resp;
  resp.set_order_id(order_id ? order_id : "");
  resp.set_success(success != 0);
  if (error && *error) resp.set_error_message(error);
  std::string bytes;
  resp.SerializeToString(&bytes);
  conn->write_unary(p.stream_id, bytes, 0, nullptr);
}

// Amend completion: AmendResponse carries the post-amend remaining, so it
// has its own completion entry (amends are rare next to submits — the
// single-call path is fine; submits/cancels ride complete_batch).
void me_gateway_complete_amend(void* g, uint64_t tag, int success,
                               const char* order_id, long long remaining,
                               const char* error) {
  auto* gw = static_cast<Gateway*>(g);
  {
    std::shared_ptr<BatchCtx> done;
    if (gw->complete_batch_member(tag, 2, success != 0,
                                  order_id ? order_id : "",
                                  error ? error : "", success ? remaining : 0,
                                  &done)) {
      if (done) send_batch_response(done);
      return;
    }
  }
  Pending p;
  if (!gw->take_pending(tag, &p)) return;
  auto conn = p.conn.lock();
  if (!conn || conn->dead()) return;
  pb::AmendResponse resp;
  resp.set_order_id(order_id ? order_id : "");
  resp.set_success(success != 0);
  if (success) resp.set_remaining_quantity(static_cast<int32_t>(remaining));
  if (error && *error) resp.set_error_message(error);
  std::string bytes;
  resp.SerializeToString(&bytes);
  conn->write_unary(p.stream_id, bytes, 0, nullptr);
}

// Batched completions: ONE ctypes crossing and ONE locked socket write per
// connection per dispatch, instead of one of each per order. The bridge's
// per-op completion fan-out measured ~59us/op (3 locked sends + a pending
// lookup + a ctypes call each); this is the serving edge's dominant cost
// at saturation (docs/BENCH_METHOD.md). Wire format, little-endian:
//   u32 n, then n records of:
//   u64 tag | u8 kind (0=submit, 1=cancel) | u8 ok |
//   u16 oid_len | oid bytes | u16 err_len | err bytes
void me_gateway_complete_batch(void* g, const uint8_t* buf, uint64_t len) {
  auto* gw = static_cast<Gateway*>(g);
  if (!buf || len < 4) return;
  size_t off = 0;
  auto rd_u16 = [&](uint16_t* v) {
    if (off + 2 > len) return false;
    *v = static_cast<uint16_t>(buf[off] | (buf[off + 1] << 8));
    off += 2;
    return true;
  };
  uint32_t n = buf[0] | (buf[1] << 8) | (buf[2] << 16) |
               (static_cast<uint32_t>(buf[3]) << 24);
  off = 4;

  struct Item {
    uint32_t stream_id;
    std::string bytes;  // serialized OrderResponse/CancelResponse
  };
  // Group by connection so each conn gets one appended buffer + one write.
  std::vector<std::pair<std::shared_ptr<Conn>, std::vector<Item>>> groups;
  // A truncated/malformed buffer can only mean encoder/parser skew
  // (NativeGateway.complete_batch and the lane engine's comp_buf are the
  // in-repo producers): scream, then sweep-fail the in-flight unary tags
  // below — the unparsed tail's clients must get immediate errors, not
  // hang to their RPC deadline.
  bool skew = false;
  auto truncated = [&](uint32_t i) {
    skew = true;
    std::fprintf(stderr,
                 "[me_gw] complete_batch buffer truncated at record %u/%u "
                 "(off=%zu len=%llu) — encoder/parser skew, sweeping "
                 "pending unary tags\n",
                 i, n, off, static_cast<unsigned long long>(len));
  };
  for (uint32_t i = 0; i < n; i++) {
    if (off + 10 > len) { truncated(i); break; }
    uint64_t tag = 0;
    for (int b = 0; b < 8; b++)
      tag |= static_cast<uint64_t>(buf[off + b]) << (8 * b);
    off += 8;
    uint8_t kind = buf[off++];
    uint8_t ok = buf[off++];
    uint16_t oid_len = 0, err_len = 0;
    if (!rd_u16(&oid_len) || off + oid_len > len) { truncated(i); break; }
    std::string oid(reinterpret_cast<const char*>(buf + off), oid_len);
    off += oid_len;
    if (!rd_u16(&err_len) || off + err_len > len) { truncated(i); break; }
    std::string err(reinterpret_cast<const char*>(buf + off), err_len);
    off += err_len;

    {
      // A tag from an in-gateway native batch resolves its positional
      // slot instead of writing a per-op unary response.
      std::shared_ptr<BatchCtx> done;
      if (gw->complete_batch_member(tag, kind, ok != 0, oid, err, 0,
                                    &done)) {
        if (done) send_batch_response(done);
        continue;
      }
    }
    Pending p;
    if (!gw->take_pending(tag, &p)) continue;
    auto conn = p.conn.lock();
    if (!conn || conn->dead()) continue;

    std::string bytes;
    if (kind == 0) {
      pb::OrderResponse resp;
      resp.set_order_id(oid);
      resp.set_success(ok != 0);
      if (!err.empty()) resp.set_error_message(err);
      resp.SerializeToString(&bytes);
    } else {
      pb::CancelResponse resp;
      resp.set_order_id(oid);
      resp.set_success(ok != 0);
      if (!err.empty()) resp.set_error_message(err);
      resp.SerializeToString(&bytes);
    }
    std::vector<Item>* items = nullptr;
    for (auto& gr : groups) {
      if (gr.first.get() == conn.get()) {
        items = &gr.second;
        break;
      }
    }
    if (!items) {
      groups.emplace_back(std::move(conn), std::vector<Item>{});
      items = &groups.back().second;
    }
    items->push_back(Item{p.stream_id, std::move(bytes)});
  }

  for (auto& gr : groups) {
    auto& conn = gr.first;
    std::string out;
    for (auto& item : gr.second) {
      int rc = conn->append_unary(item.stream_id, item.bytes, 0, nullptr,
                                  &out, /*block_for_window=*/false);
      if (rc == 1) continue;
      if (rc == 0) break;  // conn died: the remaining items can't land
      // Window-starved stream: flush everything already built (earlier
      // responses must not wait behind this stream's window), then take
      // the blocking slow path for just this item.
      if (!out.empty()) {
        conn->write_all(out);
        out.clear();
      }
      conn->write_unary(item.stream_id, item.bytes, 0, nullptr);
    }
    if (!out.empty()) conn->write_all(out);
  }

  if (skew) {
    // The well-formed prefix was delivered above; everything still
    // pending (this dispatch's unparsed tail, possibly plus other
    // in-flight unary ops — membership isn't tracked, over-sweeping
    // trades a spurious INTERNAL for a guaranteed deadline hang) fails
    // now with a trailers-only INTERNAL error.
    for (const Pending& p : gw->sweep_pending_unary()) {
      auto conn = p.conn.lock();
      if (!conn || conn->dead()) continue;
      conn->write_trailers(p.stream_id, 13,
                           "completion batch truncated (encoder/parser skew)",
                           p.headers_sent);
    }
    // In-flight native batches suffer the same unknown-tail problem:
    // answer each whole (app-level, like every batch-poisoning defect)
    // instead of letting its client hang on unresolved members.
    for (const auto& b : gw->sweep_batches()) {
      auto conn = b->conn.lock();
      if (!conn || conn->dead()) continue;
      pb::OrderBatchResponse resp;
      resp.set_success(false);
      resp.set_error_message(
          "completion batch truncated (encoder/parser skew)");
      std::string bytes;
      resp.SerializeToString(&bytes);
      conn->write_unary(b->stream_id, bytes, 0, nullptr);
    }
  }
}

// Generic response path for forwarded methods. end_stream=1 finishes the
// RPC with trailers; msg may be NULL for a trailers-only finish.
// Returns 1 on success, 0 when the stream/connection is gone.
int me_gateway_respond(void* g, uint64_t tag, const uint8_t* msg,
                       uint64_t len, int end_stream, int grpc_status,
                       const char* grpc_message) {
  auto* gw = static_cast<Gateway*>(g);
  Pending p;
  if (end_stream) {
    if (!gw->take_pending(tag, &p)) return 0;
  } else {
    if (!gw->peek_pending(tag, &p)) return 0;
  }
  auto conn = p.conn.lock();
  if (!conn || conn->dead()) {
    if (!end_stream) gw->drop_pending(tag);
    return 0;
  }
  {
    // A client RST erases the stream entry; stop the producer.
    std::lock_guard<std::mutex> lk(conn->streams_mu);
    auto it = conn->streams.find(p.stream_id);
    if (it == conn->streams.end() || it->second.closed) {
      if (!end_stream) gw->drop_pending(tag);
      return 0;
    }
  }
  bool ok = true;
  bool headers_sent = p.headers_sent;
  if (msg != nullptr && len > 0) {
    std::string m(reinterpret_cast<const char*>(msg), len);
    ok = conn->write_message(p.stream_id, m, &headers_sent);
    if (ok && !p.headers_sent) gw->mark_headers_sent(tag);
  }
  if (ok && end_stream) {
    ok = conn->write_trailers(p.stream_id, grpc_status,
                              grpc_message ? grpc_message : "", headers_sent);
  }
  if (!ok && !end_stream) gw->drop_pending(tag);
  return ok ? 1 : 0;
}

// 1 while the stream can still accept messages (connection + stream alive).
int me_gateway_stream_alive(void* g, uint64_t tag) {
  auto* gw = static_cast<Gateway*>(g);
  Pending p;
  if (!gw->peek_pending(tag, &p)) return 0;
  auto conn = p.conn.lock();
  if (!conn || conn->dead()) return 0;
  std::lock_guard<std::mutex> lk(conn->streams_mu);
  auto it = conn->streams.find(p.stream_id);
  return (it == conn->streams.end() || it->second.closed) ? 0 : 1;
}

void me_gateway_stats(void* g, uint64_t* requests, uint64_t* ring_rejects,
                      uint64_t* conns) {
  auto* gw = static_cast<Gateway*>(g);
  if (requests) *requests = gw->requests();
  if (ring_rejects) *ring_rejects = gw->ring_rejects();
  if (conns) *conns = gw->conns_accepted();
}

void me_gateway_shutdown(void* g) { static_cast<Gateway*>(g)->shutdown(); }

void me_gateway_destroy(void* g) {
  auto* gw = static_cast<Gateway*>(g);
  gw->shutdown();
  if (!gw->idle()) {
    // A connection thread outlived the shutdown timeout (e.g. wedged in a
    // blocking send): leak the gateway rather than free memory under a
    // live thread. Same policy as NativeRingDispatcher.close.
    std::fprintf(stderr, "[gateway] destroy with live connections; leaking\n");
    return;
  }
  delete gw;
}

}  // extern "C"
