// me_native: the C++ runtime layer of the TPU-native matching engine.
//
// The reference (/root/reference) is an all-C++20 gRPC order gateway; this
// library is the native counterpart of its host-side runtime, redesigned for
// the batched-TPU architecture:
//
//   1. Domain arithmetic — Q4 price normalization with the exact semantics of
//      the reference's normalize_to_q4 (include/domain/price.hpp:15-29):
//      scale in [0,18], truncation toward zero on downscale, int64 overflow
//      detection on upscale — plus the submit-validation predicate of
//      src/server/matching_engine_service.cpp:66-83.
//
//   2. MeRing — a bounded MPSC ring that replaces the reference's global
//      `write_mu` serialization point (matching_engine_service.cpp:102).
//      Producer RPC threads enqueue fixed-size ops; one consumer drains
//      time/size-windowed batches destined for a dense [S, B] device
//      dispatch. The batching window logic (first-item deadline) lives here,
//      in C++, off the GIL.
//
//   3. MeSink — the asynchronous durable tail: a worker thread applying
//      whole engine dispatches to SQLite as single WAL transactions
//      (reference schema, src/storage/storage.cpp:28-68, with its dormant
//      bugs fixed — see SURVEY.md §2.9). Links directly against the system
//      libsqlite3; the header subset used is declared below (the SQLite C
//      ABI is stable and versioned).
//
// Exposed as a C ABI consumed by ctypes (matching_engine_tpu/native).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// SQLite C API subset (system header not installed in this image; these are
// the stable documented prototypes of libsqlite3.so.0).
// ---------------------------------------------------------------------------
extern "C" {
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
int sqlite3_open_v2(const char*, sqlite3**, int, const char*);
int sqlite3_close_v2(sqlite3*);
int sqlite3_exec(sqlite3*, const char*, int (*)(void*, int, char**, char**),
                 void*, char**);
int sqlite3_prepare_v2(sqlite3*, const char*, int, sqlite3_stmt**,
                       const char**);
int sqlite3_bind_int64(sqlite3_stmt*, int, long long);
int sqlite3_bind_null(sqlite3_stmt*, int);
int sqlite3_bind_text(sqlite3_stmt*, int, const char*, int, void (*)(void*));
int sqlite3_step(sqlite3_stmt*);
int sqlite3_reset(sqlite3_stmt*);
int sqlite3_finalize(sqlite3_stmt*);
int sqlite3_busy_timeout(sqlite3*, int);
const char* sqlite3_errmsg(sqlite3*);
void sqlite3_free(void*);
#define SQLITE_OK 0
#define SQLITE_ROW 100
#define SQLITE_DONE 101
#define SQLITE_OPEN_READWRITE 0x00000002
#define SQLITE_OPEN_CREATE 0x00000004
#define SQLITE_OPEN_FULLMUTEX 0x00010000
#define SQLITE_TRANSIENT ((void (*)(void*))-1)
}

// ===========================================================================
// 1. Domain: Q4 normalization + submit validation
// ===========================================================================

namespace {
constexpr int kTargetScale = 4;
constexpr long long kPow10[19] = {
    1LL,
    10LL,
    100LL,
    1000LL,
    10000LL,
    100000LL,
    1000000LL,
    10000000LL,
    100000000LL,
    1000000000LL,
    10000000000LL,
    100000000000LL,
    1000000000000LL,
    10000000000000LL,
    100000000000000LL,
    1000000000000000LL,
    10000000000000000LL,
    100000000000000000LL,
    1000000000000000000LL,
};
}  // namespace

extern "C" {

// Error codes shared with the Python binding.
enum MeErr {
  ME_OK = 0,
  ME_ERR_SCALE = 1,     // scale outside [0, 18]
  ME_ERR_OVERFLOW = 2,  // int64 overflow on upscale
};

// Reference include/domain/price.hpp:15-29: rescale `price` quoted with
// `raw_scale` decimals onto the Q4 grid. Downscale truncates toward zero
// (C++ integer division semantics — the reference relies on the same).
int me_normalize_to_q4(long long price, int raw_scale, long long* out) {
  if (raw_scale < 0 || raw_scale > 18) return ME_ERR_SCALE;
  if (raw_scale == kTargetScale) {
    *out = price;
    return ME_OK;
  }
  if (raw_scale < kTargetScale) {
    long long mul = kPow10[kTargetScale - raw_scale];
    long long scaled;
    if (__builtin_mul_overflow(price, mul, &scaled)) return ME_ERR_OVERFLOW;
    *out = scaled;
    return ME_OK;
  }
  *out = price / kPow10[raw_scale - kTargetScale];  // truncates toward zero
  return ME_OK;
}

// Submit validation predicate — full parity with domain/order.py's
// validate_submit (itself the reference's rules at
// matching_engine_service.cpp:66-83 plus this framework's device bounds).
enum MeValidate {
  ME_V_OK = 0,
  ME_V_EMPTY_SYMBOL = 1,
  ME_V_BAD_QTY = 2,
  ME_V_BAD_PRICE = 3,   // LIMIT with price <= 0 (or truncating to 0 at Q4)
  ME_V_BAD_SCALE = 4,
  ME_V_PRICE_OVERFLOW = 5,  // int64 on rescale, or > int32 device lane
  ME_V_QTY_TOO_LARGE = 6,   // > max_quantity (int32 book-sum safety bound)
  ME_V_BAD_SIDE = 7,        // not BUY(1)/SELL(2)
  ME_V_BAD_TYPE = 8,        // not LIMIT(0)/MARKET(1)
  ME_V_SYMBOL_TOO_LONG = 9,
  ME_V_CLIENT_ID_TOO_LONG = 10,
};

int me_validate_submit(int symbol_len, int client_id_len, long long quantity,
                       int side, int order_type, long long price, int scale,
                       long long max_price_q4, long long max_quantity,
                       int max_symbol_len, int max_client_id_len) {
  if (symbol_len <= 0) return ME_V_EMPTY_SYMBOL;
  if (symbol_len > max_symbol_len) return ME_V_SYMBOL_TOO_LONG;
  if (client_id_len > max_client_id_len) return ME_V_CLIENT_ID_TOO_LONG;
  if (quantity <= 0) return ME_V_BAD_QTY;
  if (quantity > max_quantity) return ME_V_QTY_TOO_LARGE;
  if (side != 1 && side != 2) return ME_V_BAD_SIDE;
  if (order_type != 0 && order_type != 1) return ME_V_BAD_TYPE;
  if (order_type == 0) {  // LIMIT
    if (price <= 0) return ME_V_BAD_PRICE;
    long long q4;
    int rc = me_normalize_to_q4(price, scale, &q4);
    if (rc == ME_ERR_SCALE) return ME_V_BAD_SCALE;
    if (rc == ME_ERR_OVERFLOW) return ME_V_PRICE_OVERFLOW;
    if (q4 > max_price_q4) return ME_V_PRICE_OVERFLOW;
    if (q4 <= 0) return ME_V_BAD_PRICE;  // truncated to zero at Q4
  } else {
    if (scale < 0 || scale > 18) return ME_V_BAD_SCALE;
  }
  return ME_V_OK;
}

}  // extern "C"

// ===========================================================================
// 2. MeRing: bounded MPSC op ring with timed batch drain
// ===========================================================================

extern "C" {

// Fixed-size op record; `tag` is an opaque producer cookie (the Python side
// maps it back to the op's future + host metadata).
struct MeOp {
  uint64_t tag;
  int32_t sym;
  int32_t op;     // 0 noop / 1 submit / 2 cancel (engine/kernel.py opcodes)
  int32_t side;   // BUY=1 / SELL=2
  int32_t otype;  // LIMIT=0 / MARKET=1
  int32_t price;  // Q4, int32 device lane
  int32_t qty;
  int32_t oid;
  int32_t pad;
};

}  // extern "C"

namespace {

class MeRing {
 public:
  explicit MeRing(uint32_t capacity) : cap_(capacity) {}

  bool push(const MeOp& op) {
    std::unique_lock<std::mutex> lk(mu_);
    if (closed_ || q_.size() >= cap_) {
      dropped_.fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    q_.push_back(op);
    cv_.notify_one();
    return true;
  }

  // Blocks until at least one op is available (or the ring closes), then
  // drains until `max` ops are taken or `window_us` elapses from the first
  // op — the dispatcher's latency/throughput knob, in native code.
  // first_wait_us < 0 waits indefinitely for the first op; >= 0 bounds
  // that wait (the pipelined drain loop polls so an idle lull finishes a
  // staged dispatch instead of stranding its clients). Returns the count
  // (0 = first-wait timeout), or -1 when closed and empty.
  int pop_batch(MeOp* out, uint32_t max, uint64_t window_us,
                int64_t first_wait_us = -1) {
    std::unique_lock<std::mutex> lk(mu_);
    if (first_wait_us < 0) {
      cv_.wait(lk, [&] { return closed_ || !q_.empty(); });
    } else if (!cv_.wait_for(lk, std::chrono::microseconds(first_wait_us),
                             [&] { return closed_ || !q_.empty(); })) {
      return 0;  // first-wait timeout, nothing arrived
    }
    if (q_.empty()) return -1;  // closed and drained
    uint32_t n = 0;
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::microseconds(window_us);
    for (;;) {
      while (n < max && !q_.empty()) {
        out[n++] = q_.front();
        q_.pop_front();
      }
      if (n >= max || closed_) break;
      if (cv_.wait_until(lk, deadline,
                         [&] { return closed_ || !q_.empty(); })) {
        if (q_.empty()) break;  // woke on close
        continue;
      }
      break;  // window elapsed
    }
    return static_cast<int>(n);
  }

  void close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    cv_.notify_all();
  }

  uint64_t dropped() const { return dropped_.load(std::memory_order_relaxed); }
  size_t size() {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  const uint32_t cap_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<MeOp> q_;
  bool closed_ = false;
  std::atomic<uint64_t> dropped_{0};
};

}  // namespace

extern "C" {

// All entry points tolerate a null handle (a destroyed ring behaves as
// closed) — a use-after-close from a binding must degrade, not segfault.
void* me_ring_create(uint32_t capacity) { return new MeRing(capacity); }
void me_ring_destroy(void* r) { delete static_cast<MeRing*>(r); }
int me_ring_push(void* r, const MeOp* op) {
  if (!r || !op) return 0;
  return static_cast<MeRing*>(r)->push(*op) ? 1 : 0;
}
int me_ring_pop_batch(void* r, MeOp* out, uint32_t max, uint64_t window_us) {
  if (!r || !out) return -1;
  return static_cast<MeRing*>(r)->pop_batch(out, max, window_us);
}
int me_ring_pop_batch_timed(void* r, MeOp* out, uint32_t max,
                            uint64_t window_us, int64_t first_wait_us) {
  if (!r || !out) return -1;
  return static_cast<MeRing*>(r)->pop_batch(out, max, window_us,
                                            first_wait_us);
}
void me_ring_close(void* r) {
  if (r) static_cast<MeRing*>(r)->close();
}
uint64_t me_ring_dropped(void* r) {
  return r ? static_cast<MeRing*>(r)->dropped() : 0;
}
uint64_t me_ring_size(void* r) {
  return r ? static_cast<MeRing*>(r)->size() : 0;
}

}  // extern "C"

// ===========================================================================
// 3. MeSink: async batched SQLite writer
// ===========================================================================
//
// Batch wire format (little-endian, packed by the Python binding):
//   u32 n_orders   then per order:
//     str order_id, str client_id, str symbol        (str = u16 len + bytes)
//     u8 side, u8 otype, u8 has_price, i64 price, i64 qty, i64 remaining,
//     u8 status
//   u32 n_updates  then per update: str order_id, u8 status, i64 remaining
//   u32 n_fills    then per fill:
//     str order_id, str counter_order_id, i64 price, i64 qty, i64 ts
//
// Schema matches matching_engine_tpu/storage/storage.py (which itself is the
// reference schema at src/storage/storage.cpp:28-68 with SURVEY §2.9 bug
// fixes); the two sinks are interchangeable and row-for-row identical.

namespace {

const char kSchema[] =
    "CREATE TABLE IF NOT EXISTS orders ("
    "  order_id            TEXT PRIMARY KEY,"
    "  client_id           TEXT NOT NULL,"
    "  symbol              TEXT NOT NULL,"
    "  side                INTEGER NOT NULL CHECK (side IN (1, 2)),"
    "  order_type          INTEGER NOT NULL CHECK (order_type IN (0, 1)),"
    "  price               INTEGER,"
    "  quantity            INTEGER NOT NULL CHECK (quantity > 0),"
    "  remaining_quantity  INTEGER NOT NULL CHECK (remaining_quantity >= 0),"
    "  status              INTEGER NOT NULL CHECK (status BETWEEN 0 AND 4),"
    "  created_ts          INTEGER NOT NULL,"
    "  updated_ts          INTEGER NOT NULL,"
    "  tif                 INTEGER NOT NULL DEFAULT 0 CHECK (tif IN (0, 1, 2)));"
    "CREATE INDEX IF NOT EXISTS idx_orders_symbol_status"
    "  ON orders (symbol, status);"
    "CREATE INDEX IF NOT EXISTS idx_orders_client ON orders (client_id);"
    "CREATE TABLE IF NOT EXISTS fills ("
    "  fill_id           INTEGER PRIMARY KEY AUTOINCREMENT,"
    "  order_id          TEXT NOT NULL REFERENCES orders (order_id),"
    "  counter_order_id  TEXT NOT NULL,"
    "  price             INTEGER NOT NULL,"
    "  quantity          INTEGER NOT NULL CHECK (quantity > 0),"
    "  ts                INTEGER NOT NULL);"
    "CREATE INDEX IF NOT EXISTS idx_fills_order ON fills (order_id);";

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), end_(p + n) {}
  bool u8(uint8_t* v) {
    if (p_ + 1 > end_) return false;
    *v = *p_++;
    return true;
  }
  bool u32(uint32_t* v) {
    if (p_ + 4 > end_) return false;
    std::memcpy(v, p_, 4);
    p_ += 4;
    return true;
  }
  bool i64(long long* v) {
    if (p_ + 8 > end_) return false;
    std::memcpy(v, p_, 8);
    p_ += 8;
    return true;
  }
  bool str(std::string* s) {
    uint16_t len;
    if (p_ + 2 > end_) return false;
    std::memcpy(&len, p_, 2);
    p_ += 2;
    if (p_ + len > end_) return false;
    s->assign(reinterpret_cast<const char*>(p_), len);
    p_ += len;
    return true;
  }

 private:
  const uint8_t* p_;
  const uint8_t* end_;
};

long long now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

class MeSink {
 public:
  // path_ must be fully constructed before worker_ launches run() — members
  // initialize in declaration order and worker_ is declared last.
  MeSink(const char* path, uint32_t max_queue)
      : path_(path), max_queue_(max_queue), worker_([this] { run(); }) {}

  ~MeSink() {
    close();
    if (worker_.joinable()) worker_.join();
  }

  bool open_ok() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_opened_.wait(lk, [&] { return opened_; });
    return open_ok_;
  }

  void flush() {
    std::unique_lock<std::mutex> lk(mu_);
    uint64_t target = seq_in_;
    cv_flushed_.wait(lk, [&] { return seq_done_ >= target || closed_; });
  }

  void close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (closed_) return;
      closing_ = true;
      cv_.notify_all();
    }
    // run() drains the queue, then observes closing_ and exits; it sets
    // closed_ last so flush()/submit() waiters wake correctly.
  }

  void stats(uint64_t* batches, uint64_t* rows, uint64_t* dropped,
             uint64_t* errors) {
    *batches = batches_.load(std::memory_order_relaxed);
    *rows = rows_.load(std::memory_order_relaxed);
    *dropped = dropped_.load(std::memory_order_relaxed);
    *errors = errors_.load(std::memory_order_relaxed);
  }

 private:
  void run() {
    // The worker owns the connection end to end (SQLite connections are not
    // meant to hop threads); open/schema happen here, open_ok() rendezvouses.
    bool ok = open_db();
    {
      std::lock_guard<std::mutex> lk(mu_);
      opened_ = true;
      open_ok_ = ok;
      cv_opened_.notify_all();
    }
    if (!ok) {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
      cv_flushed_.notify_all();
      cv_space_.notify_all();
      return;
    }
    for (;;) {
      std::vector<std::vector<uint8_t>> work;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return closing_ || !q_.empty(); });
        if (q_.empty() && closing_) break;
        // Coalesce everything queued into one transaction (async_sink.py
        // does the same): fewer fsyncs, same durability model.
        work.swap(q_);
        cv_space_.notify_all();
      }
      apply(work);
      {
        std::lock_guard<std::mutex> lk(mu_);
        seq_done_ += work.size();
        cv_flushed_.notify_all();
      }
    }
    if (db_) {
      for (auto* s : {ins_order_, upd_order_, upd_amend_, ins_fill_})
        if (s) sqlite3_finalize(s);
      sqlite3_close_v2(db_);
      db_ = nullptr;
    }
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    cv_flushed_.notify_all();
    cv_space_.notify_all();
  }

  bool open_db() {
    if (sqlite3_open_v2(path_.c_str(), &db_,
                        SQLITE_OPEN_READWRITE | SQLITE_OPEN_CREATE |
                            SQLITE_OPEN_FULLMUTEX,
                        nullptr) != SQLITE_OK)
      return false;
    sqlite3_busy_timeout(db_, 5000);  // reference storage.cpp:14
    // Reference storage.cpp:17-24 pragmas.
    if (sqlite3_exec(db_,
                     "PRAGMA journal_mode=WAL;"
                     "PRAGMA synchronous=NORMAL;"
                     "PRAGMA foreign_keys=ON;",
                     nullptr, nullptr, nullptr) != SQLITE_OK)
      return false;
    if (sqlite3_exec(db_, kSchema, nullptr, nullptr, nullptr) != SQLITE_OK)
      return false;
    // Migration twin of Storage.init(): a pre-tif database keeps its
    // original orders table; add the column in place (failure = column
    // already exists, which is the fine case — probe it afterwards).
    sqlite3_exec(db_,
                 "ALTER TABLE orders ADD COLUMN tif INTEGER NOT NULL "
                 "DEFAULT 0 CHECK (tif IN (0, 1, 2))",
                 nullptr, nullptr, nullptr);
    auto prep = [&](const char* sql, sqlite3_stmt** st) {
      return sqlite3_prepare_v2(db_, sql, -1, st, nullptr) == SQLITE_OK;
    };
    return prep(
               "INSERT INTO orders (order_id, client_id, symbol, side,"
               " order_type, price, quantity, remaining_quantity, status,"
               " created_ts, updated_ts, tif) VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
               &ins_order_) &&
           prep(
               "UPDATE orders SET status = ?, remaining_quantity = ?,"
               " updated_ts = ? WHERE order_id = ?",
               &upd_order_) &&
           prep(
               "UPDATE orders SET status = ?, remaining_quantity = ?,"
               " quantity = ?, updated_ts = ? WHERE order_id = ?",
               &upd_amend_) &&
           prep(
               "INSERT INTO fills (order_id, counter_order_id, price,"
               " quantity, ts) VALUES (?,?,?,?,?)",
               &ins_fill_);
  }

  void apply(const std::vector<std::vector<uint8_t>>& work) {
    long long ts = now_us();
    if (sqlite3_exec(db_, "BEGIN", nullptr, nullptr, nullptr) != SQLITE_OK) {
      std::fprintf(stderr, "[me_sink] BEGIN failed: %s\n",
                   sqlite3_errmsg(db_));
      errors_.fetch_add(1, std::memory_order_relaxed);
      return;
    }
    // Each queued batch lands in its own savepoint: one bad batch (the
    // failure mode the stress test hit — a whole coalesced transaction
    // rolled back, silently orphaning later fills/updates) costs exactly
    // that batch, loudly, never its neighbors.
    uint64_t nrows = 0, nbatches = 0;
    for (const auto& buf : work) {
      if (sqlite3_exec(db_, "SAVEPOINT b", nullptr, nullptr, nullptr) !=
          SQLITE_OK) {
        std::fprintf(stderr, "[me_sink] SAVEPOINT failed: %s\n",
                     sqlite3_errmsg(db_));
        errors_.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
      uint64_t batch_rows = 0;
      if (apply_one(buf, ts, &batch_rows)) {
        sqlite3_exec(db_, "RELEASE b", nullptr, nullptr, nullptr);
        nrows += batch_rows;
        nbatches++;
      } else {
        std::fprintf(stderr, "[me_sink] batch dropped (%s)\n",
                     sqlite3_errmsg(db_));
        sqlite3_exec(db_, "ROLLBACK TO b", nullptr, nullptr, nullptr);
        sqlite3_exec(db_, "RELEASE b", nullptr, nullptr, nullptr);
        errors_.fetch_add(1, std::memory_order_relaxed);
      }
    }
    if (sqlite3_exec(db_, "COMMIT", nullptr, nullptr, nullptr) == SQLITE_OK) {
      batches_.fetch_add(nbatches, std::memory_order_relaxed);
      rows_.fetch_add(nrows, std::memory_order_relaxed);
    } else {
      std::fprintf(stderr, "[me_sink] COMMIT failed: %s\n",
                   sqlite3_errmsg(db_));
      sqlite3_exec(db_, "ROLLBACK", nullptr, nullptr, nullptr);
      errors_.fetch_add(1, std::memory_order_relaxed);
    }
  }

  bool step_reset(sqlite3_stmt* st) {
    bool ok = sqlite3_step(st) == SQLITE_DONE;
    sqlite3_reset(st);
    return ok;
  }

  bool apply_one(const std::vector<uint8_t>& buf, long long ts,
                 uint64_t* nrows) {
    Reader r(buf.data(), buf.size());
    uint32_t n;
    if (!r.u32(&n)) return false;
    for (uint32_t i = 0; i < n; i++) {
      std::string oid, cid, sym;
      uint8_t side, otype, has_price, status;
      long long price, qty, remaining;
      if (!(r.str(&oid) && r.str(&cid) && r.str(&sym) && r.u8(&side) &&
            r.u8(&otype) && r.u8(&has_price) && r.i64(&price) &&
            r.i64(&qty) && r.i64(&remaining) && r.u8(&status)))
        return false;
      // The wire byte is the engine's collapsed (order_type, tif) lane
      // code (proto/__init__.py split_otype): 0/1 = LIMIT/MARKET GTC,
      // 2 = LIMIT IOC, 3 = LIMIT FOK, 4 = MARKET FOK. The order_type
      // column keeps the reference's 0/1 domain; tif gets its own column.
      int base_type = (otype == 1 || otype == 4) ? 1 : 0;
      int tif = (otype == 2) ? 1 : (otype == 3 || otype == 4) ? 2 : 0;
      sqlite3_bind_text(ins_order_, 1, oid.c_str(), -1, SQLITE_TRANSIENT);
      sqlite3_bind_text(ins_order_, 2, cid.c_str(), -1, SQLITE_TRANSIENT);
      sqlite3_bind_text(ins_order_, 3, sym.c_str(), -1, SQLITE_TRANSIENT);
      sqlite3_bind_int64(ins_order_, 4, side);
      sqlite3_bind_int64(ins_order_, 5, base_type);
      // MARKET orders persist NULL price — fixing the reference's dormant
      // bug of storing a bogus as-is price (SURVEY §2.9c).
      if (has_price)
        sqlite3_bind_int64(ins_order_, 6, price);
      else
        sqlite3_bind_null(ins_order_, 6);
      sqlite3_bind_int64(ins_order_, 7, qty);
      sqlite3_bind_int64(ins_order_, 8, remaining);
      sqlite3_bind_int64(ins_order_, 9, status);
      sqlite3_bind_int64(ins_order_, 10, ts);
      sqlite3_bind_int64(ins_order_, 11, ts);
      sqlite3_bind_int64(ins_order_, 12, tif);
      if (!step_reset(ins_order_)) {
        std::fprintf(stderr, "[me_sink] order insert %s: %s\n", oid.c_str(),
                     sqlite3_errmsg(db_));
        return false;
      }
      (*nrows)++;
    }
    if (!r.u32(&n)) return false;
    for (uint32_t i = 0; i < n; i++) {
      std::string oid;
      uint8_t status, has_qty;
      long long remaining, qty;
      if (!(r.str(&oid) && r.u8(&status) && r.i64(&remaining) &&
            r.u8(&has_qty) && r.i64(&qty)))
        return false;
      // has_qty marks a priority-preserving amend: quantity moves WITH
      // remaining so filled == quantity - remaining stays exact.
      sqlite3_stmt* st = has_qty ? upd_amend_ : upd_order_;
      sqlite3_bind_int64(st, 1, status);
      sqlite3_bind_int64(st, 2, remaining);
      if (has_qty) {
        sqlite3_bind_int64(st, 3, qty);
        sqlite3_bind_int64(st, 4, ts);
        sqlite3_bind_text(st, 5, oid.c_str(), -1, SQLITE_TRANSIENT);
      } else {
        sqlite3_bind_int64(st, 3, ts);
        sqlite3_bind_text(st, 4, oid.c_str(), -1, SQLITE_TRANSIENT);
      }
      if (!step_reset(st)) {
        std::fprintf(stderr, "[me_sink] order update %s: %s\n", oid.c_str(),
                     sqlite3_errmsg(db_));
        return false;
      }
      (*nrows)++;
    }
    if (!r.u32(&n)) return false;
    for (uint32_t i = 0; i < n; i++) {
      std::string oid, coid;
      long long price, qty, fts;
      if (!(r.str(&oid) && r.str(&coid) && r.i64(&price) && r.i64(&qty) &&
            r.i64(&fts)))
        return false;
      // All six placeholders bound — the reference's dormant add_fill binds
      // 5 of 6 and can never execute (SURVEY §2.9b).
      sqlite3_bind_text(ins_fill_, 1, oid.c_str(), -1, SQLITE_TRANSIENT);
      sqlite3_bind_text(ins_fill_, 2, coid.c_str(), -1, SQLITE_TRANSIENT);
      sqlite3_bind_int64(ins_fill_, 3, price);
      sqlite3_bind_int64(ins_fill_, 4, qty);
      sqlite3_bind_int64(ins_fill_, 5, fts ? fts : ts);
      if (!step_reset(ins_fill_)) {
        std::fprintf(stderr, "[me_sink] fill insert %s/%s: %s\n", oid.c_str(),
                     coid.c_str(), sqlite3_errmsg(db_));
        return false;
      }
      (*nrows)++;
    }
    return true;
  }

  std::string path_;
  const uint32_t max_queue_;
  sqlite3* db_ = nullptr;
  sqlite3_stmt* ins_order_ = nullptr;
  sqlite3_stmt* upd_order_ = nullptr;
  sqlite3_stmt* upd_amend_ = nullptr;
  sqlite3_stmt* ins_fill_ = nullptr;

  std::mutex mu_;
  std::condition_variable cv_, cv_space_, cv_flushed_, cv_opened_;
  std::vector<std::vector<uint8_t>> q_;
  bool closing_ = false;
  bool closed_ = false;
  bool opened_ = false;
  bool open_ok_ = false;
  uint64_t seq_in_ = 0;   // guarded by mu_ (incremented in me_sink_submit)
  uint64_t seq_done_ = 0;
  std::atomic<uint64_t> batches_{0}, rows_{0}, dropped_{0}, errors_{0};
  std::thread worker_;

  friend bool sink_submit_counted(MeSink*, const uint8_t*, size_t, bool);
};

bool sink_submit_counted(MeSink* s, const uint8_t* buf, size_t len,
                         bool block) {
  // seq_in_ must advance under mu_ together with the queue push so flush()
  // targets are exact; wrap submit to do both.
  std::vector<uint8_t> copy(buf, buf + len);
  std::unique_lock<std::mutex> lk(s->mu_);
  if (block) {
    s->cv_space_.wait(
        lk, [&] { return s->closed_ || s->closing_ ||
                         s->q_.size() < s->max_queue_; });
  }
  if (s->closed_ || s->closing_ || s->q_.size() >= s->max_queue_) {
    s->dropped_.fetch_add(1, std::memory_order_relaxed);
    return false;
  }
  s->q_.push_back(std::move(copy));
  s->seq_in_++;
  s->cv_.notify_one();
  return true;
}

}  // namespace

extern "C" {

void* me_sink_open(const char* path, uint32_t max_queue) {
  auto* s = new MeSink(path, max_queue ? max_queue : 4096);
  if (!s->open_ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int me_sink_submit(void* h, const uint8_t* buf, uint64_t len, int block) {
  if (!h || !buf) return 0;
  return sink_submit_counted(static_cast<MeSink*>(h), buf, len, block != 0)
             ? 1
             : 0;
}

void me_sink_flush(void* h) {
  if (h) static_cast<MeSink*>(h)->flush();
}

void me_sink_stats(void* h, uint64_t* batches, uint64_t* rows,
                   uint64_t* dropped, uint64_t* errors) {
  if (!h) {
    *batches = *rows = *dropped = *errors = 0;
    return;
  }
  static_cast<MeSink*>(h)->stats(batches, rows, dropped, errors);
}

void me_sink_close(void* h) { delete static_cast<MeSink*>(h); }

}  // extern "C"
