// HPACK codec self-test against RFC 7541 Appendix C vectors.
// Exit 0 on success; prints the first failing check otherwise.
// Run by tests/test_gateway.py::test_hpack_vectors.

#include <cassert>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "h2.h"

namespace {

std::string unhex(const std::string& s) {
  std::string out;
  for (size_t i = 0; i + 1 < s.size(); i += 2) {
    out.push_back(static_cast<char>(
        std::stoi(s.substr(i, 2), nullptr, 16)));
  }
  return out;
}

int failures = 0;

void expect(bool ok, const char* what) {
  if (!ok) {
    std::printf("FAIL: %s\n", what);
    ++failures;
  }
}

void expect_hdr(const std::vector<h2::Header>& hs, size_t i,
                const char* name, const char* value) {
  if (i >= hs.size()) {
    std::printf("FAIL: header %zu missing (got %zu)\n", i, hs.size());
    ++failures;
    return;
  }
  if (hs[i].name != name || hs[i].value != value) {
    std::printf("FAIL: header %zu = %s: %s (want %s: %s)\n", i,
                hs[i].name.c_str(), hs[i].value.c_str(), name, value);
    ++failures;
  }
}

}  // namespace

int main() {
  // --- RFC 7541 C.3: request examples without Huffman, one shared decoder
  // (exercises dynamic-table insertion and indexed reuse across blocks).
  {
    h2::HpackDecoder dec;
    std::vector<h2::Header> h1;
    expect(dec.decode(
               reinterpret_cast<const uint8_t*>(
                   unhex("828684410f7777772e6578616d706c652e636f6d").data()),
               20, &h1),
           "C.3.1 decode ok");
    expect_hdr(h1, 0, ":method", "GET");
    expect_hdr(h1, 1, ":scheme", "http");
    expect_hdr(h1, 2, ":path", "/");
    expect_hdr(h1, 3, ":authority", "www.example.com");

    std::vector<h2::Header> h2v;
    std::string b2 = unhex("828684be58086e6f2d6361636865");
    expect(dec.decode(reinterpret_cast<const uint8_t*>(b2.data()), b2.size(),
                      &h2v),
           "C.3.2 decode ok");
    expect_hdr(h2v, 3, ":authority", "www.example.com");  // dynamic index 62
    expect_hdr(h2v, 4, "cache-control", "no-cache");

    std::vector<h2::Header> h3;
    std::string b3 = unhex(
        "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565");
    expect(dec.decode(reinterpret_cast<const uint8_t*>(b3.data()), b3.size(),
                      &h3),
           "C.3.3 decode ok");
    expect_hdr(h3, 1, ":scheme", "https");
    expect_hdr(h3, 2, ":path", "/index.html");
    expect_hdr(h3, 3, ":authority", "www.example.com");
    expect_hdr(h3, 4, "custom-key", "custom-value");
  }

  // --- RFC 7541 C.4: the same requests Huffman-coded.
  {
    h2::HpackDecoder dec;
    std::vector<h2::Header> h1;
    std::string b1 = unhex("828684418cf1e3c2e5f23a6ba0ab90f4ff");
    expect(dec.decode(reinterpret_cast<const uint8_t*>(b1.data()), b1.size(),
                      &h1),
           "C.4.1 decode ok");
    expect_hdr(h1, 3, ":authority", "www.example.com");

    std::vector<h2::Header> h2v;
    std::string b2 = unhex("828684be5886a8eb10649cbf");
    expect(dec.decode(reinterpret_cast<const uint8_t*>(b2.data()), b2.size(),
                      &h2v),
           "C.4.2 decode ok");
    expect_hdr(h2v, 4, "cache-control", "no-cache");

    std::vector<h2::Header> h3;
    std::string b3 = unhex(
        "828785bf408825a849e95ba97d7f8925a849e95bb8e8b4bf");
    expect(dec.decode(reinterpret_cast<const uint8_t*>(b3.data()), b3.size(),
                      &h3),
           "C.4.3 decode ok");
    expect_hdr(h3, 4, "custom-key", "custom-value");
  }

  // --- Huffman: direct string checks.
  {
    std::string out;
    std::string in = unhex("f1e3c2e5f23a6ba0ab90f4ff");
    expect(h2::huffman_decode(reinterpret_cast<const uint8_t*>(in.data()),
                              in.size(), &out) &&
               out == "www.example.com",
           "huffman www.example.com");
    out.clear();
    in = unhex("a8eb10649cbf");
    expect(h2::huffman_decode(reinterpret_cast<const uint8_t*>(in.data()),
                              in.size(), &out) &&
               out == "no-cache",
           "huffman no-cache");
    // Invalid padding (zeros) must be rejected.
    out.clear();
    in = unhex("f1e3c2e5f23a6ba0ab90f400");
    expect(!h2::huffman_decode(reinterpret_cast<const uint8_t*>(in.data()),
                               in.size(), &out),
           "huffman bad padding rejected");
  }

  // --- Integer edge: multi-byte length (value 1337 with 5-bit prefix is the
  // RFC C.1.2 example but exercised here through a long raw string).
  {
    h2::HpackDecoder dec;
    std::string name(300, 'x');
    std::string block;
    block.push_back(0x00);  // literal w/o indexing, new name
    // length 300 with 7-bit prefix: 0x7f, then 300-127=173 -> 0xad 0x01
    block.push_back(0x7f);
    block.push_back(static_cast<char>(0xad));
    block.push_back(0x01);
    block += name;
    block.push_back(0x01);  // value "v"
    block += "v";
    std::vector<h2::Header> hs;
    expect(dec.decode(reinterpret_cast<const uint8_t*>(block.data()),
                      block.size(), &hs),
           "long literal decode ok");
    expect_hdr(hs, 0, name.c_str(), "v");
  }

  // --- Encoder output must round-trip through the decoder.
  {
    std::string block;
    h2::hpack_encode(":status", "200", &block);
    h2::hpack_encode("content-type", "application/grpc", &block);
    h2::HpackDecoder dec;
    std::vector<h2::Header> hs;
    expect(dec.decode(reinterpret_cast<const uint8_t*>(block.data()),
                      block.size(), &hs),
           "encode round-trip decode ok");
    expect_hdr(hs, 0, ":status", "200");
    expect_hdr(hs, 1, "content-type", "application/grpc");
  }

  // --- Frame header round-trip.
  {
    std::string hdr;
    h2::write_frame_header(h2::F_HEADERS, h2::FLAG_END_HEADERS, 5, 1234, &hdr);
    h2::FrameHeader fh =
        h2::parse_frame_header(reinterpret_cast<const uint8_t*>(hdr.data()));
    expect(fh.length == 1234 && fh.type == h2::F_HEADERS &&
               fh.flags == h2::FLAG_END_HEADERS && fh.stream_id == 5,
           "frame header round-trip");
  }

  if (failures == 0) std::printf("h2_test: all checks passed\n");
  return failures == 0 ? 0 : 1;
}
