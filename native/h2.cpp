// HTTP/2 + HPACK codec implementation. See h2.h for scope and rationale.

#include "h2.h"

#include <array>
#include <cstring>

namespace h2 {

// ---------------------------------------------------------------------------
// Huffman decoding (RFC 7541 §5.2, Appendix B)
// ---------------------------------------------------------------------------

#include "hpack_huffman.inc"

namespace {

// Binary decode tree built once from the canonical code table. Each node is a
// pair of child indices; leaves store the decoded symbol. ~500 internal nodes.
struct HuffTree {
  struct Node {
    int32_t child[2] = {-1, -1};
    int32_t sym = -1;
  };
  std::vector<Node> nodes;

  HuffTree() {
    nodes.emplace_back();  // root
    for (int s = 0; s < 257; ++s) {
      uint32_t code = kHuffTable[s].code;
      int bits = kHuffTable[s].bits;
      size_t at = 0;
      for (int b = bits - 1; b >= 0; --b) {
        int bit = (code >> b) & 1;
        if (nodes[at].child[bit] < 0) {
          nodes[at].child[bit] = static_cast<int32_t>(nodes.size());
          nodes.emplace_back();
        }
        at = static_cast<size_t>(nodes[at].child[bit]);
      }
      nodes[at].sym = s;
    }
  }
};

const HuffTree& huff_tree() {
  static const HuffTree tree;
  return tree;
}

}  // namespace

bool huffman_decode(const uint8_t* p, size_t n, std::string* out) {
  const HuffTree& t = huff_tree();
  size_t at = 0;
  int depth = 0;  // bits consumed since last emitted symbol
  for (size_t i = 0; i < n; ++i) {
    for (int b = 7; b >= 0; --b) {
      int bit = (p[i] >> b) & 1;
      int32_t next = t.nodes[at].child[bit];
      if (next < 0) return false;  // invalid code
      at = static_cast<size_t>(next);
      ++depth;
      int sym = t.nodes[at].sym;
      if (sym >= 0) {
        if (sym == 256) return false;  // EOS inside the string is an error
        out->push_back(static_cast<char>(sym));
        at = 0;
        depth = 0;
      }
    }
  }
  // Remaining bits must be a prefix of EOS (all 1s) and < 8 bits: verify by
  // checking every consumed-but-unfinished edge took the '1' branch. We track
  // this cheaply: walk from root along 1s `depth` steps and compare.
  if (depth >= 8) return false;
  size_t check = 0;
  for (int i = 0; i < depth; ++i) {
    int32_t next = t.nodes[check].child[1];
    if (next < 0) return false;
    check = static_cast<size_t>(next);
  }
  return check == at;
}

// ---------------------------------------------------------------------------
// HPACK static table (RFC 7541 Appendix A — canonical standard data)
// ---------------------------------------------------------------------------

namespace {
const std::array<Header, 62> kStaticTable = {{
    {"", ""},  // index 0 unused
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
}};
}  // namespace

// ---------------------------------------------------------------------------
// HPACK decoder
// ---------------------------------------------------------------------------

bool HpackDecoder::read_int(const uint8_t*& p, const uint8_t* end,
                            int prefix_bits, uint64_t* out) {
  if (p >= end) return false;
  uint64_t mask = (1u << prefix_bits) - 1;
  uint64_t v = *p++ & mask;
  if (v < mask) {
    *out = v;
    return true;
  }
  int shift = 0;
  for (;;) {
    if (p >= end || shift > 56) return false;
    uint8_t b = *p++;
    v += static_cast<uint64_t>(b & 0x7f) << shift;
    shift += 7;
    if (!(b & 0x80)) break;
  }
  *out = v;
  return true;
}

bool HpackDecoder::read_string(const uint8_t*& p, const uint8_t* end,
                               std::string* out) {
  if (p >= end) return false;
  bool huff = (*p & 0x80) != 0;
  uint64_t len;
  if (!read_int(p, end, 7, &len)) return false;
  if (len > static_cast<uint64_t>(end - p)) return false;
  if (huff) {
    if (!huffman_decode(p, len, out)) return false;
  } else {
    out->append(reinterpret_cast<const char*>(p), len);
  }
  p += len;
  return true;
}

bool HpackDecoder::table_lookup(uint64_t index, Header* out) const {
  if (index == 0) return false;
  if (index < kStaticTable.size()) {
    *out = kStaticTable[index];
    return true;
  }
  size_t di = index - kStaticTable.size();  // 0-based into dynamic table
  if (di >= dyn_.size()) return false;
  *out = dyn_[di];
  return true;
}

void HpackDecoder::table_insert(const Header& h) {
  size_t sz = h.name.size() + h.value.size() + 32;
  while (!dyn_.empty() && dyn_size_ + sz > cap_) {
    dyn_size_ -= dyn_.back().name.size() + dyn_.back().value.size() + 32;
    dyn_.pop_back();
  }
  if (sz <= cap_) {
    dyn_.push_front(h);
    dyn_size_ += sz;
  }
  // else: an entry larger than the table empties it (handled above) and is
  // itself not inserted — RFC 7541 §4.4.
}

bool HpackDecoder::decode(const uint8_t* p, size_t n,
                          std::vector<Header>* out) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint8_t b = *p;
    if (b & 0x80) {  // §6.1 indexed header field
      uint64_t idx;
      if (!read_int(p, end, 7, &idx)) return false;
      Header h;
      if (!table_lookup(idx, &h)) return false;
      out->push_back(std::move(h));
    } else if (b & 0x40) {  // §6.2.1 literal with incremental indexing
      uint64_t idx;
      if (!read_int(p, end, 6, &idx)) return false;
      Header h;
      if (idx) {
        if (!table_lookup(idx, &h)) return false;
        h.value.clear();
      } else if (!read_string(p, end, &h.name)) {
        return false;
      }
      if (!read_string(p, end, &h.value)) return false;
      table_insert(h);
      out->push_back(std::move(h));
    } else if (b & 0x20) {  // §6.3 dynamic table size update
      uint64_t cap;
      if (!read_int(p, end, 5, &cap)) return false;
      if (cap > cap_limit_) return false;
      cap_ = cap;
      while (dyn_size_ > cap_ && !dyn_.empty()) {
        dyn_size_ -= dyn_.back().name.size() + dyn_.back().value.size() + 32;
        dyn_.pop_back();
      }
    } else {  // §6.2.2/§6.2.3 literal without indexing / never indexed
      uint64_t idx;
      if (!read_int(p, end, 4, &idx)) return false;
      Header h;
      if (idx) {
        if (!table_lookup(idx, &h)) return false;
        h.value.clear();
      } else if (!read_string(p, end, &h.name)) {
        return false;
      }
      if (!read_string(p, end, &h.value)) return false;
      out->push_back(std::move(h));
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// HPACK encoder (literal-without-indexing, raw strings only)
// ---------------------------------------------------------------------------

namespace {
void encode_int(uint64_t v, int prefix_bits, uint8_t first_byte_flags,
                std::string* out) {
  uint64_t mask = (1u << prefix_bits) - 1;
  if (v < mask) {
    out->push_back(static_cast<char>(first_byte_flags | v));
    return;
  }
  out->push_back(static_cast<char>(first_byte_flags | mask));
  v -= mask;
  while (v >= 128) {
    out->push_back(static_cast<char>(0x80 | (v & 0x7f)));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}
}  // namespace

void hpack_encode(std::string_view name, std::string_view value,
                  std::string* out) {
  out->push_back(0x00);  // literal without indexing, new name
  encode_int(name.size(), 7, 0x00, out);  // H=0 (raw)
  out->append(name);
  encode_int(value.size(), 7, 0x00, out);
  out->append(value);
}

// ---------------------------------------------------------------------------
// Framing
// ---------------------------------------------------------------------------

void write_frame_header(uint8_t type, uint8_t flags, uint32_t stream_id,
                        size_t length, std::string* out) {
  out->push_back(static_cast<char>((length >> 16) & 0xff));
  out->push_back(static_cast<char>((length >> 8) & 0xff));
  out->push_back(static_cast<char>(length & 0xff));
  out->push_back(static_cast<char>(type));
  out->push_back(static_cast<char>(flags));
  out->push_back(static_cast<char>((stream_id >> 24) & 0x7f));
  out->push_back(static_cast<char>((stream_id >> 16) & 0xff));
  out->push_back(static_cast<char>((stream_id >> 8) & 0xff));
  out->push_back(static_cast<char>(stream_id & 0xff));
}

FrameHeader parse_frame_header(const uint8_t p[9]) {
  FrameHeader h;
  h.length = (static_cast<uint32_t>(p[0]) << 16) |
             (static_cast<uint32_t>(p[1]) << 8) | p[2];
  h.type = p[3];
  h.flags = p[4];
  h.stream_id = ((static_cast<uint32_t>(p[5]) & 0x7f) << 24) |
                (static_cast<uint32_t>(p[6]) << 16) |
                (static_cast<uint32_t>(p[7]) << 8) | p[8];
  return h;
}

void grpc_frame(std::string_view message, std::string* out) {
  out->push_back(0);  // uncompressed
  uint32_t n = static_cast<uint32_t>(message.size());
  out->push_back(static_cast<char>((n >> 24) & 0xff));
  out->push_back(static_cast<char>((n >> 16) & 0xff));
  out->push_back(static_cast<char>((n >> 8) & 0xff));
  out->push_back(static_cast<char>(n & 0xff));
  out->append(message);
}

}  // namespace h2
