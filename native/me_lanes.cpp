// me_lanes: the native serving fast path — lane build + completion decode.
//
// The r5 bottleneck (VERDICT weak #1): the device kernel matches ~2.0B
// orders/s but the serving path feeding it tops out at ~10.6k orders/s,
// because the bridge/runner hot loops run per-OP Python: ring-record
// tuple conversion, OrderInfo/EngineOp construction, directory dict
// mutation, numpy lane scatter, per-result decode, storage-tuple packing,
// completion-list building. This file moves all of that per-op work into
// C++, leaving Python control-plane work per DISPATCH:
//
//   build  — consume a popped MeGwOp batch straight from the gateway ring
//            buffer: validate encodings, run the host directory checks
//            (unknown id / wrong client / auction mode / symbol capacity),
//            assign oids + recycled device handles + symbol slots, place
//            ops into sparse [K, 9] or dense [S, B, 7] lane waves.
//   wave   — materialize one wave's ready-to-device_put int32 lane buffer.
//   decode — consume one wave's packed small-vector readback (the SAME
//            layout engine/sparse.py and engine/harness.py read): update
//            the directory, apply maker decrements from the fill log,
//            accumulate storage rows in the MeSink wire format and
//            completion records in the gateway batch wire format.
//   finish — evict terminal orders (recycling handles/slots), assemble the
//            completion + storage + aux buffers for one ctypes take().
//
// Parity: the Python path (gateway_bridge._drain_batch +
// engine_runner._stage_locked/_decode_batch/_evict_terminal) stays the
// oracle — tests/test_native_lanes.py replays lifecycle-fuzz streams
// through both and asserts identical lanes, outcomes, and storage bytes.
// Every ordering choice here (slot/oid/handle assignment order, decode in
// device (slot, row) order, eviction in op order then ASCENDING maker
// handle order, LIFO free lists) mirrors the Python code lines; change
// either side only in lockstep.
//
// Compiled into libme_native.so (no protobuf dependency — the gateway's
// protobuf edge stays in libme_gateway.so).

#include <algorithm>
#include <array>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "me_gwop.h"

// zlib's crc32 (system libz; the stable documented prototype) — the same
// function behind Python's zlib.crc32, so owner_hash is bit-identical to
// domain/order.py.
extern "C" {
unsigned long crc32(unsigned long crc, const unsigned char* buf,
                    unsigned int len);
}

namespace {

// engine/kernel.py opcodes + statuses (pinned there; test_native_lanes.py
// asserts this module and the kernel agree through the parity streams).
constexpr int kOpSubmit = 1, kOpCancel = 2, kOpRest = 3, kOpAmend = 4;
constexpr int kNew = 0, kPartiallyFilled = 1, kFilled = 2, kCanceled = 3,
              kRejected = 4;
constexpr int kMarket = 1, kMarketFok = 4;  // price column is NULL for these

constexpr long long kOwnerRegistryCap = 1'000'000;
constexpr int kBucketFloor = 64;  // sparse.bucket floor

int bucket(int n) {
  int k = kBucketFloor;
  while (k < n) k <<= 1;
  return k;
}

// Strict UTF-8 validation (RFC 3629): rejects overlongs, surrogates and
// > U+10FFFF — the same inputs CPython's bytes.decode() rejects, so the
// fast path rejects exactly the records the Python bridge rejects.
bool utf8_valid(const char* s, int len) {
  const unsigned char* p = reinterpret_cast<const unsigned char*>(s);
  const unsigned char* end = p + len;
  while (p < end) {
    unsigned char c = *p;
    if (c < 0x80) {
      p += 1;
    } else if ((c & 0xE0) == 0xC0) {
      if (end - p < 2 || (p[1] & 0xC0) != 0x80 || c < 0xC2) return false;
      p += 2;
    } else if ((c & 0xF0) == 0xE0) {
      if (end - p < 3 || (p[1] & 0xC0) != 0x80 || (p[2] & 0xC0) != 0x80)
        return false;
      if (c == 0xE0 && p[1] < 0xA0) return false;            // overlong
      if (c == 0xED && p[1] >= 0xA0) return false;           // surrogate
      p += 3;
    } else if ((c & 0xF8) == 0xF0) {
      if (end - p < 4 || (p[1] & 0xC0) != 0x80 || (p[2] & 0xC0) != 0x80 ||
          (p[3] & 0xC0) != 0x80)
        return false;
      if (c == 0xF0 && p[1] < 0x90) return false;            // overlong
      if (c > 0xF4 || (c == 0xF4 && p[1] >= 0x90)) return false;  // >10FFFF
      p += 4;
    } else {
      return false;
    }
  }
  return true;
}

// -- little-endian append helpers (the MeSink / gateway wire formats) ------

void put_u8(std::string* out, uint8_t v) { out->push_back(static_cast<char>(v)); }
void put_u16(std::string* out, uint16_t v) {
  out->push_back(static_cast<char>(v & 0xff));
  out->push_back(static_cast<char>(v >> 8));
}
void put_u32(std::string* out, uint32_t v) {
  for (int i = 0; i < 4; i++) out->push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}
void put_u64(std::string* out, uint64_t v) {
  for (int i = 0; i < 8; i++) out->push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}
void put_i32(std::string* out, int32_t v) { put_u32(out, static_cast<uint32_t>(v)); }
void put_i64(std::string* out, long long v) { put_u64(out, static_cast<uint64_t>(v)); }
void put_str(std::string* out, const std::string& s) {
  put_u16(out, static_cast<uint16_t>(s.size()));
  out->append(s);
}

std::string render_oid(long long n) { return "OID-" + std::to_string(n); }

// Canonical "OID-<n>" parse: only the exact string Python's dict key path
// would match (no leading zeros, digits only) resolves. Returns -1 on
// non-canonical input (== unknown order id).
long long parse_oid(const std::string& s) {
  if (s.size() < 5 || s.size() > 4 + 19 || s.compare(0, 4, "OID-") != 0)
    return -1;
  if (s[4] == '0') return -1;  // oids start at 1; canonical has no zeros
  long long v = 0;
  for (size_t i = 4; i < s.size(); i++) {
    char c = s[i];
    if (c < '0' || c > '9') return -1;
    if (v > (9223372036854775807LL - (c - '0')) / 10) return -1;
    v = v * 10 + (c - '0');
  }
  return v;
}

// -- directory entry --------------------------------------------------------

struct LaneOrder {
  long long oid = 0;      // "OID-<oid>"
  std::string client_id;  // raw bytes (validated UTF-8)
  std::string symbol;
  int32_t side = 0;
  int32_t otype = 0;
  int32_t price_q4 = 0;
  int32_t handle = 0;
  long long quantity = 0;
  long long remaining = 0;
  int32_t status = 0;
};
using OrderPtr = std::shared_ptr<LaneOrder>;

// -- per-dispatch context ---------------------------------------------------

struct CtxOp {
  uint64_t tag = 0;
  int op = 0;  // engine op: kOpSubmit / kOpCancel / kOpAmend
  OrderPtr target;
  // Frozen lane payload (HostOrder fields, engine_runner._stage_locked):
  int32_t dev_op = 0, side = 0, otype = 0, price = 0;
  long long qty = 0;
  int32_t owner = 0;
  int32_t slot = -1, row = -1, wave = -1;  // wave < 0: not device-bound
  // Outcome (stage reject or device result):
  bool has_outcome = false;
  int32_t status = 0;
  long long filled = 0, remaining = 0;
  std::string error;
};

struct ImmReject {  // host reject completed before any device work
  uint64_t tag = 0;
  int kind = 0;  // 0 submit / 1 cancel / 2 amend
  std::string order_id, error;
};

struct Ctx {
  std::vector<CtxOp> ops;        // device-bound EngineOps, record order
  std::vector<int> outcome_order;  // op indices in res.outcomes order
  std::vector<ImmReject> imm;
  bool build_ou = false, build_md = false;
  int shape = 1;  // 0 sparse / 1 dense
  int n_waves = 0;
  int n_lanes = 0;  // host_orders length (device lanes)
  std::vector<int> wave_n, wave_k;
  std::vector<std::vector<int>> wave_order;  // per wave, op idx by (slot,row)
  int decode_cursor = 0;

  // Accumulated outputs (storage sections in MeSink wire order):
  std::string store_orders, store_updates, store_fills;
  uint32_t n_store_orders = 0, n_updates = 0, n_fills = 0;
  std::string aux_ou;
  uint32_t n_ou = 0;
  std::vector<std::pair<std::string, int32_t>> new_owners;
  std::vector<std::pair<std::string, long long>> recon;
  std::set<int32_t> terminal_makers;  // ascending == Python sorted()
  // Market data: sparse = first-touch insertion order; dense = sorted set
  // + the LAST wave's [4, S] top-of-book block.
  std::vector<int32_t> md_slots;
  std::unordered_map<int32_t, std::array<int32_t, 4>> md_tob;
  std::set<int32_t> dense_touched;
  std::vector<int32_t> dense_tob;  // [4 * S] from the last decoded wave
  // Slot-directory deltas for the Python mirror:
  std::vector<std::pair<int32_t, std::string>> slot_allocs;
  std::vector<int32_t> slot_releases;
  // Counters (aux layout; indices documented in native/__init__.py):
  long long fill_count = 0, overflow_waves = 0;
  long long accepted = 0, rejected = 0, canceled = 0, amended = 0;
  long long owner_overflow = 0, owner_collisions = 0;
  // Assembled at finish, copied at take:
  std::string comp_buf, store_buf, aux_buf;
  bool finished = false;
};

// ---------------------------------------------------------------------------
// MeLanes engine
// ---------------------------------------------------------------------------

class MeLanes {
 public:
  MeLanes(int32_t num_symbols, int32_t batch, int32_t fill_inline,
          int32_t max_fills)
      : S_(num_symbols), B_(batch), L_(fill_inline), max_fills_(max_fills) {
    slot_symbols_.resize(S_);
    slot_live_.assign(S_, 0);
  }

  // -- allocators (mirror EngineRunner._id_lock state) ---------------------

  int32_t alloc_handle() {
    if (!free_handles_.empty()) {
      int32_t h = free_handles_.back();
      free_handles_.pop_back();
      return h;
    }
    if (next_handle_ >= 2147483647) return -1;  // runner raises; build fails
    return next_handle_++;
  }

  // symbol_slot + live-count acquire (EngineRunner.slot_acquire); records
  // a fresh allocation into ctx for the Python slot-map mirror.
  int32_t slot_acquire(const std::string& sym, Ctx* ctx) {
    auto it = symbols_.find(sym);
    int32_t slot;
    if (it != symbols_.end()) {
      slot = it->second;
    } else {
      if (!free_slots_.empty()) {
        slot = free_slots_.back();
        free_slots_.pop_back();
      } else if (next_slot_ < S_) {
        slot = next_slot_++;
      } else {
        return -1;
      }
      symbols_[sym] = slot;
      slot_symbols_[slot] = sym;
      if (ctx) ctx->slot_allocs.emplace_back(slot, sym);
    }
    slot_live_[slot] += 1;
    return slot;
  }

  void slot_release(int32_t slot, Ctx* ctx, int32_t* released) {
    slot_live_[slot] -= 1;
    if (slot_live_[slot] == 0) {
      const std::string& sym = slot_symbols_[slot];
      if (!sym.empty()) {
        symbols_.erase(sym);
        slot_symbols_[slot].clear();
        free_slots_.push_back(slot);
        if (ctx) ctx->slot_releases.push_back(slot);
        if (released) *released = slot;
      }
    }
  }

  // EngineRunner._owner_for: crc32 first candidate, linear probe past
  // claimed ids, registry cap with unregistered probing.
  int32_t owner_for(const std::string& cid, Ctx* ctx) {
    if (cid.empty()) return 0;
    auto it = owner_by_client_.find(cid);
    if (it != owner_by_client_.end()) return it->second;
    uint32_t h = static_cast<uint32_t>(
        crc32(0, reinterpret_cast<const unsigned char*>(cid.data()),
              static_cast<unsigned int>(cid.size())));
    int32_t owner = static_cast<int32_t>(h & 0x7FFFFFFF);
    if (owner == 0) owner = 1;
    if (static_cast<long long>(owner_by_client_.size()) >= kOwnerRegistryCap) {
      ctx->owner_overflow++;
      while (owner_claimed_.count(owner) || owner == 0)
        owner = (owner + 1) & 0x7FFFFFFF;
      return owner;  // unregistered past the cap (counted residual risk)
    }
    if (owner_claimed_.count(owner)) {
      ctx->owner_collisions++;
      const std::string& first = owner_claimed_[owner];
      int32_t orig = owner;
      while (owner_claimed_.count(owner) || owner == 0)
        owner = (owner + 1) & 0x7FFFFFFF;
      std::fprintf(stderr,
                   "[me_lanes] owner_hash collision: %.64s vs %.64s; "
                   "remapped %d -> %d\n",
                   cid.c_str(), first.c_str(), orig, owner);
    }
    owner_by_client_[cid] = owner;
    owner_claimed_[owner] = cid;
    ctx->new_owners.emplace_back(cid, owner);
    return owner;
  }

  // -- build ---------------------------------------------------------------

  // Returns n_waves (>= 0) and stages a dispatch context, or -1 on a
  // malformed record / allocator exhaustion (caller fails the batch).
  int build(const MeGwOp* recs, uint32_t n, int build_ou, int build_md,
            int32_t* flags, int32_t* wave_n_out, int32_t* wave_k_out,
            uint32_t max_waves) {
    std::lock_guard<std::mutex> lk(mu_);
    auto ctx = std::make_unique<Ctx>();
    ctx->build_ou = build_ou != 0;
    ctx->build_md = build_md != 0;

    // Pass 1 — the bridge record loop (gateway_bridge._drain_batch):
    // host checks + id/slot/handle assignment against the PRE-BATCH
    // directory (a cancel naming a submit from the same drained batch is
    // "unknown order id", exactly as in Python, where registration
    // happens after the whole record loop).
    struct Planned {
      int op;
      uint64_t tag;
      OrderPtr target;
      long long amend_qty = 0;
      int32_t slot = -1;  // submit: acquired in this pass
    };
    std::vector<Planned> planned;
    planned.reserve(n);
    std::vector<OrderPtr> fresh;  // registered in pass 2

    for (uint32_t i = 0; i < n; i++) {
      const MeGwOp& r = recs[i];
      if (r.symbol_len < 0 || r.symbol_len > (int)sizeof(r.symbol) ||
          r.client_id_len < 0 || r.client_id_len > (int)sizeof(r.client_id) ||
          r.order_id_len < 0 || r.order_id_len > (int)sizeof(r.order_id))
        return -1;
      int kind = r.op == 1 ? 0 : (r.op == 3 ? 2 : 1);
      if (!utf8_valid(r.symbol, r.symbol_len) ||
          !utf8_valid(r.client_id, r.client_id_len) ||
          !utf8_valid(r.order_id, r.order_id_len)) {
        ctx->rejected++;
        ctx->imm.push_back({r.tag, kind, "", "invalid request encoding"});
        continue;
      }
      std::string client_id(r.client_id, r.client_id_len);
      if (r.op == 1) {  // submit (already validated at the edge)
        std::string symbol(r.symbol, r.symbol_len);
        if (auction_mode_ && r.otype != 0) {
          ctx->rejected++;
          ctx->imm.push_back(
              {r.tag, 0, "",
               "only GTC LIMIT orders are accepted during an auction call "
               "period"});
          continue;
        }
        int32_t slot = slot_acquire(symbol, ctx.get());
        if (slot < 0) {
          ctx->rejected++;
          ctx->imm.push_back(
              {r.tag, 0, "",
               "symbol capacity exhausted (engine symbol axis is full)"});
          continue;
        }
        long long oidn = next_oid_;
        next_oid_ += oid_stride_;
        int32_t h = alloc_handle();
        if (h < 0) return -1;
        auto info = std::make_shared<LaneOrder>();
        info->oid = oidn;
        info->client_id = std::move(client_id);
        info->symbol = std::move(symbol);
        info->side = r.side;
        info->otype = r.otype;
        info->price_q4 = r.price_q4;
        info->handle = h;
        info->quantity = r.quantity;
        info->remaining = r.quantity;
        info->status = kNew;
        fresh.push_back(info);
        planned.push_back({kOpSubmit, r.tag, std::move(info), 0, slot});
      } else {  // cancel / amend: directory checks as the bridge does
        std::string order_id(r.order_id, r.order_id_len);
        const char* which = r.op == 3 ? "amend" : "cancel";
        (void)which;
        long long oidn = parse_oid(order_id);
        auto dit = oidn >= 0 ? by_oid_.find(oidn) : by_oid_.end();
        if (dit == by_oid_.end()) {
          ctx->imm.push_back({r.tag, r.op == 3 ? 2 : 1, order_id,
                              "unknown order id"});
          continue;
        }
        OrderPtr target = dit->second;
        if (target->client_id != client_id) {
          ctx->imm.push_back({r.tag, r.op == 3 ? 2 : 1, order_id,
                              "order belongs to a different client"});
          continue;
        }
        if (r.op == 3) {
          planned.push_back({kOpAmend, r.tag, std::move(target), r.quantity, -1});
        } else {
          planned.push_back({kOpCancel, r.tag, std::move(target), 0, -1});
        }
      }
    }

    // Pass 2 — the runner stage loop (engine_runner._stage_locked): the
    // terminal-target guard, auction-mode classification, lane placement,
    // owner assignment, eager registration. A mid-pass failure unwinds the
    // eager registrations (the _rollback_registrations policy: directory
    // entries go, consumed handles/oids stay unrecycled).
    auto fail_build = [&]() {
      for (const OrderPtr& f : fresh) {
        by_handle_.erase(f->handle);
        by_oid_.erase(f->oid);
      }
      return -1;
    };
    std::vector<int64_t> counts(S_, 0);
    int n_waves = 0;
    for (auto& p : planned) {
      CtxOp op;
      op.tag = p.tag;
      op.op = p.op;
      op.target = p.target;
      LaneOrder& info = *p.target;
      if ((p.op == kOpCancel || p.op == kOpAmend) &&
          (info.status == kFilled || info.status == kCanceled ||
           info.status == kRejected)) {
        // Target went terminal after this op was enqueued: reject on the
        // host, the device never sees a stale handle.
        op.has_outcome = true;
        op.status = kRejected;
        op.error = "order not open";
        ctx->ops.push_back(std::move(op));
        ctx->outcome_order.push_back(static_cast<int>(ctx->ops.size()) - 1);
        continue;
      }
      int32_t slot = p.slot;
      if (slot < 0) {
        auto sit = symbols_.find(info.symbol);
        if (sit == symbols_.end()) return fail_build();  // caller bug
        slot = sit->second;
      }
      op.dev_op = (p.op == kOpSubmit && auction_mode_) ? kOpRest : p.op;
      op.side = info.side;
      op.otype = info.otype;
      op.price = info.price_q4;
      long long qty = p.op == kOpAmend ? p.amend_qty
                      : p.op == kOpCancel ? 0
                                          : info.remaining;
      if (qty < INT32_MIN || qty > INT32_MAX) return fail_build();  // i32 lane
      op.qty = qty;
      op.owner = owner_for(info.client_id, ctx.get());
      op.slot = slot;
      op.wave = static_cast<int>(counts[slot] / B_);
      op.row = static_cast<int>(counts[slot] % B_);
      counts[slot] += 1;
      if (op.wave + 1 > n_waves) n_waves = op.wave + 1;
      ctx->n_lanes += 1;
      if (p.op == kOpSubmit) {
        by_handle_[info.handle] = p.target;
        by_oid_[info.oid] = p.target;
      }
      ctx->ops.push_back(std::move(op));
    }

    if (static_cast<uint32_t>(n_waves) > max_waves) return fail_build();
    ctx->n_waves = n_waves;
    ctx->shape =
        (ctx->n_lanes > 0 && ctx->n_lanes * 4 <= S_ * B_) ? 0 : 1;
    ctx->wave_n.assign(n_waves, 0);
    ctx->wave_order.assign(n_waves, {});
    for (size_t i = 0; i < ctx->ops.size(); i++) {
      const CtxOp& op = ctx->ops[i];
      if (op.wave < 0) continue;
      ctx->wave_n[op.wave] += 1;
      ctx->wave_order[op.wave].push_back(static_cast<int>(i));
    }
    ctx->wave_k.assign(n_waves, 0);
    for (int w = 0; w < n_waves; w++) {
      auto& order = ctx->wave_order[w];
      std::sort(order.begin(), order.end(), [&](int a, int b) {
        const CtxOp& x = ctx->ops[a];
        const CtxOp& y = ctx->ops[b];
        return x.slot != y.slot ? x.slot < y.slot : x.row < y.row;
      });
      ctx->wave_k[w] = bucket(ctx->wave_n[w]);
      wave_n_out[w] = ctx->wave_n[w];
      wave_k_out[w] = ctx->wave_k[w];
    }
    flags[0] = ctx->shape;
    flags[1] = n_waves;
    flags[2] = ctx->n_lanes;
    flags[3] = static_cast<int32_t>(ctx->ops.size());
    ctxs_.push_back(std::move(ctx));
    return n_waves;
  }

  // Materialize one wave's lane buffer (sparse [K, 9] / dense [S, B, 7]).
  int wave(uint32_t w, int32_t* out) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty()) return -1;
    Ctx& ctx = *ctxs_.back();  // waves fetched right after build
    if (w >= static_cast<uint32_t>(ctx.n_waves)) return -1;
    if (ctx.shape == 0) {
      int k = ctx.wave_k[w];
      std::memset(out, 0, sizeof(int32_t) * k * 9);
      int i = 0;
      for (int idx : ctx.wave_order[w]) {
        const CtxOp& op = ctx.ops[idx];
        int32_t* lane = out + i * 9;
        lane[0] = op.slot;
        lane[1] = op.row;
        lane[2] = op.dev_op;
        lane[3] = op.side;
        lane[4] = op.otype;
        lane[5] = op.price;
        lane[6] = static_cast<int32_t>(op.qty);
        lane[7] = op.target->handle;
        lane[8] = op.owner;
        i++;
      }
      for (; i < k; i++) out[i * 9 + 0] = S_;  // padding: scatter-drop slot
    } else {
      std::memset(out, 0, sizeof(int32_t) * S_ * B_ * 7);
      for (int idx : ctx.wave_order[w]) {
        const CtxOp& op = ctx.ops[idx];
        int32_t* lane = out + (op.slot * B_ + op.row) * 7;
        lane[0] = op.dev_op;
        lane[1] = op.side;
        lane[2] = op.otype;
        lane[3] = op.price;
        lane[4] = static_cast<int32_t>(op.qty);
        lane[5] = op.target->handle;
        lane[6] = op.owner;
      }
    }
    return 0;
  }

  // Materialize ONE stacked [m, S, B, 7] megadispatch buffer covering
  // waves [w0, w0+m) of the newest staged dispatch — the native twin of
  // np.stack over _prepare_mega's per-wave arrays, built in one crossing
  // instead of m wave() calls + a host-side stack copy. Dense only.
  int wave_mega(uint32_t w0, uint32_t m, int32_t* out) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty() || m == 0) return -1;
    Ctx& ctx = *ctxs_.back();  // waves fetched right after build
    if (ctx.shape != 1) return -1;
    if (w0 + m > static_cast<uint32_t>(ctx.n_waves)) return -1;
    const long long plane = static_cast<long long>(S_) * B_ * 7;
    std::memset(out, 0, sizeof(int32_t) * plane * m);
    for (uint32_t j = 0; j < m; j++) {
      int32_t* base = out + plane * j;
      for (int idx : ctx.wave_order[w0 + j]) {
        const CtxOp& op = ctx.ops[idx];
        int32_t* lane = base + (op.slot * B_ + op.row) * 7;
        lane[0] = op.dev_op;
        lane[1] = op.side;
        lane[2] = op.otype;
        lane[3] = op.price;
        lane[4] = static_cast<int32_t>(op.qty);
        lane[5] = op.target->handle;
        lane[6] = op.owner;
      }
    }
    return 0;
  }

  // -- decode --------------------------------------------------------------

  // Consumes the OLDEST staged dispatch's next wave. Returns the wave's
  // fill count, -2 when the fill log exceeded the inline segment and the
  // caller must re-call with the full [5, max_fills] buffer, -1 on error.
  long long decode_wave(const int32_t* small, long long small_len,
                        const int32_t* fills, long long fills_len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty()) return -1;
    Ctx& ctx = *ctxs_.front();
    if (ctx.decode_cursor >= ctx.n_waves) return -1;
    int w = ctx.decode_cursor;
    int k = ctx.shape == 0 ? ctx.wave_k[w] : 0;
    long long expect = ctx.shape == 0
                           ? 7LL * k + 2 + 5LL * L_
                           : 3LL * S_ * B_ + 4LL * S_ + 2 + 5LL * L_;
    if (small_len != expect) return -1;
    long long meta = ctx.shape == 0 ? 7LL * k : 3LL * S_ * B_ + 4LL * S_;
    long long fc = small[meta];
    bool overflow = small[meta + 1] != 0;
    const int32_t* frows[5];
    long long fstride;
    if (fc <= L_) {
      for (int r = 0; r < 5; r++) frows[r] = small + meta + 2 + r * L_;
      fstride = 1;  // rows are contiguous [5, L]
      (void)fstride;
    } else {
      if (fills == nullptr) return -2;  // caller fetches the full buffer
      if (fills_len != 5LL * max_fills_) return -1;
      for (int r = 0; r < 5; r++) frows[r] = fills + r * max_fills_;
    }
    if (fc < 0 || fc > max_fills_) return -1;
    if (overflow) ctx.overflow_waves += 1;

    const int32_t* p_status;
    const int32_t* p_filled;
    const int32_t* p_remaining;
    if (ctx.shape == 0) {
      p_status = small;
      p_filled = small + k;
      p_remaining = small + 2 * k;
    } else {
      p_status = small;
      p_filled = small + S_ * B_;
      p_remaining = small + 2 * S_ * B_;
    }
    if (apply_wave(ctx, w, p_status, p_filled, p_remaining,
                   /*by_rank=*/ctx.shape == 0, /*p_handle=*/nullptr, frows,
                   fc) != 0)
      return -1;

    // Market data accumulation.
    if (ctx.build_md) {
      if (ctx.shape == 0) {
        int i = 0;
        for (int idx : ctx.wave_order[w]) {
          const CtxOp& e = ctx.ops[idx];
          std::array<int32_t, 4> tob = {small[3 * k + i], small[4 * k + i],
                                        small[5 * k + i], small[6 * k + i]};
          auto it = ctx.md_tob.find(e.slot);
          if (it == ctx.md_tob.end()) {
            ctx.md_slots.push_back(e.slot);  // first-touch insertion order
            ctx.md_tob[e.slot] = tob;
          } else {
            it->second = tob;  // later waves overwrite
          }
          i++;
        }
      } else {
        for (int idx : ctx.wave_order[w])
          ctx.dense_touched.insert(ctx.ops[idx].slot);
        const int32_t* base = small + 3 * S_ * B_;
        ctx.dense_tob.assign(base, base + 4 * S_);  // last wave wins
      }
    }
    ctx.fill_count += fc;
    ctx.decode_cursor += 1;
    return fc;
  }

  // Decode M waves of the OLDEST staged dispatch from ONE megadispatch
  // readback (kernel.MegaStepOutput.small layout; the native twin of
  // harness.decode_step_mega): per-wave compacted completions + inline
  // fill segments, final-book top-of-book in the header. `lo` is the
  // inline fill rows per wave (kernel.mega_fill_inline). Returns the
  // stack's total fill count, -2 when some wave's fill log exceeded the
  // inline segment and the caller must re-call with the full
  // [M, 5, max_fills] buffer, -1 on error. Dense dispatches only (the
  // runner never stacks sparse waves — mirroring _prepare_mega).
  long long decode_mega(const int32_t* small, long long small_len,
                        int32_t m, int32_t rcap, int32_t lo,
                        const int32_t* fills, long long fills_len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty() || m <= 0 || rcap <= 0 || lo <= 0) return -1;
    Ctx& ctx = *ctxs_.front();
    if (ctx.shape != 1) return -1;
    if (ctx.decode_cursor + m > ctx.n_waves) return -1;
    long long expect = 3LL * m + 4LL * S_ + 5LL * m * rcap + 5LL * m * lo;
    if (small_len != expect) return -1;
    const int32_t* res_counts = small;
    const int32_t* fill_counts = small + m;
    const int32_t* overflows = small + 2 * m;
    const int32_t* tob = small + 3 * m;                   // [4, S]
    const int32_t* res = tob + 4 * S_;                    // [m, 5, rcap]
    const int32_t* finline = res + 5LL * m * rcap;        // [m, 5, lo]
    for (int j = 0; j < m; j++) {
      if (fill_counts[j] < 0 || fill_counts[j] > max_fills_) return -1;
      if (fill_counts[j] > lo && fills == nullptr) return -2;
    }
    if (fills != nullptr && fills_len != 5LL * m * max_fills_) return -1;
    long long total_fc = 0;
    for (int j = 0; j < m; j++) {
      int w = ctx.decode_cursor;
      const int32_t* r = res + 5LL * j * rcap;
      // Every lane placed in a wave is a real op, so the compacted count
      // must equal the wave's op count — anything else is a readback/
      // schedule mismatch and must fail loudly, never misattribute.
      if (res_counts[j] !=
          static_cast<int32_t>(ctx.wave_order[w].size()))
        return -1;
      long long fc = fill_counts[j];
      const int32_t* frows[5];
      if (fc <= lo) {
        for (int row = 0; row < 5; row++)
          frows[row] = finline + 5LL * j * lo + static_cast<long long>(row) * lo;
      } else {
        for (int row = 0; row < 5; row++)
          frows[row] = fills + 5LL * j * max_fills_ +
                       static_cast<long long>(row) * max_fills_;
      }
      if (overflows[j]) ctx.overflow_waves += 1;
      // Compacted rows: oid | sym | status | filled | remaining, packed
      // in device row-major order == wave_order's (slot, row) sort, so
      // rank indexing lines up exactly; row 0 verifies handle identity.
      if (apply_wave(ctx, w, r + 2LL * rcap, r + 3LL * rcap,
                     r + 4LL * rcap, /*by_rank=*/true, /*p_handle=*/r,
                     frows, fc) != 0)
        return -1;
      if (ctx.build_md)
        for (int idx : ctx.wave_order[w])
          ctx.dense_touched.insert(ctx.ops[idx].slot);
      ctx.fill_count += fc;
      total_fc += fc;
      ctx.decode_cursor += 1;
    }
    if (ctx.build_md) {
      // Final-book top-of-book == the last stacked wave's — identical to
      // the serial schedule's last-wave overwrite.
      ctx.dense_tob.assign(tob, tob + 4 * S_);
    }
    return total_fc;
  }

 private:
  // The per-wave op decode shared by the serial full-plane readback and
  // the mega compacted readback: apply statuses and fills to the
  // directory, accumulate storage rows, outcomes, and maker bookkeeping.
  // by_rank=false: p_* are full [S, B] planes indexed slot*B+row (dense
  // serial). by_rank=true: p_* are indexed by the op's RANK in wave
  // order (sparse lanes, and mega compacted rows — whose packing order
  // is exactly wave_order's (slot, row) sort; p_handle, when non-null,
  // verifies rank identity against the compacted oid column).
  int apply_wave(Ctx& ctx, int w, const int32_t* p_status,
                 const int32_t* p_filled, const int32_t* p_remaining,
                 bool by_rank, const int32_t* p_handle,
                 const int32_t* const frows[5], long long fc) {
    // Group fills by taker handle, preserving order (fills_by_taker).
    std::unordered_map<int32_t, std::vector<int>> fills_by_taker;
    for (long long j = 0; j < fc; j++)
      fills_by_taker[frows[1][j]].push_back(static_cast<int>(j));

    int lane_i = 0;
    for (int idx : ctx.wave_order[w]) {
      CtxOp& e = ctx.ops[idx];
      long long pos = by_rank ? lane_i : e.slot * B_ + e.row;
      if (p_handle != nullptr && p_handle[pos] != e.target->handle)
        return -1;  // compacted row order diverged from the schedule
      lane_i++;
      int32_t status = p_status[pos];
      long long filled = p_filled[pos];
      long long remaining = p_remaining[pos];
      LaneOrder& info = *e.target;
      if (e.op == kOpSubmit) {
        info.status = status;
        info.remaining = remaining;
        e.has_outcome = true;
        e.status = status;
        e.filled = filled;
        e.remaining = remaining;
        if (status == kRejected) {
          e.error = filled == 0
                        ? "book side at capacity"
                        : "partially filled; remainder rejected (book side "
                          "at capacity)";
        }
        ctx.outcome_order.push_back(idx);
        // Storage order row (engine_runner storage_orders tuple order).
        std::string oid_s = render_oid(info.oid);
        put_str(&ctx.store_orders, oid_s);
        put_str(&ctx.store_orders, info.client_id);
        put_str(&ctx.store_orders, info.symbol);
        bool has_price = !(info.otype == kMarket || info.otype == kMarketFok);
        put_u8(&ctx.store_orders, static_cast<uint8_t>(info.side));
        put_u8(&ctx.store_orders, static_cast<uint8_t>(info.otype));
        put_u8(&ctx.store_orders, has_price ? 1 : 0);
        put_i64(&ctx.store_orders, has_price ? info.price_q4 : 0);
        put_i64(&ctx.store_orders, info.quantity);
        put_i64(&ctx.store_orders, info.remaining);
        put_u8(&ctx.store_orders, static_cast<uint8_t>(info.status));
        ctx.n_store_orders++;
        // Taker fills + maker bookkeeping, in priority order.
        auto fbt = fills_by_taker.find(info.handle);
        long long decoded_qty = 0;
        if (fbt != fills_by_taker.end())
          for (int j : fbt->second) decoded_qty += frows[4][j];
        if (decoded_qty < filled)
          ctx.recon.emplace_back(oid_s, filled - decoded_qty);
        long long rem = info.quantity;
        if (fbt != fills_by_taker.end()) {
          for (int j : fbt->second) {
            int32_t fprice = frows[3][j];
            long long fqty = frows[4][j];
            rem -= fqty;
            if (ctx.build_ou) {
              int32_t st = (rem == 0 && info.remaining == 0)
                               ? kFilled
                               : kPartiallyFilled;
              emit_ou(&ctx, info, st, fprice, fqty, rem);
            }
            auto mit = by_handle_.find(frows[2][j]);
            if (mit == by_handle_.end()) continue;
            LaneOrder& maker = *mit->second;
            maker.remaining -= fqty;
            maker.status =
                maker.remaining == 0 ? kFilled : kPartiallyFilled;
            if (maker.remaining == 0)
              ctx.terminal_makers.insert(maker.handle);
            std::string moid = render_oid(maker.oid);
            put_str(&ctx.store_fills, oid_s);
            put_str(&ctx.store_fills, moid);
            put_i64(&ctx.store_fills, fprice);
            put_i64(&ctx.store_fills, fqty);
            put_i64(&ctx.store_fills, 0);  // ts: FillRow default
            ctx.n_fills++;
            put_str(&ctx.store_updates, moid);
            put_u8(&ctx.store_updates, static_cast<uint8_t>(maker.status));
            put_i64(&ctx.store_updates, maker.remaining);
            put_u8(&ctx.store_updates, 0);
            put_i64(&ctx.store_updates, 0);
            ctx.n_updates++;
            if (ctx.build_ou)
              emit_ou(&ctx, maker, maker.status, fprice, fqty,
                      maker.remaining);
          }
        }
        if (ctx.build_ou &&
            (status == kNew || status == kCanceled || status == kRejected))
          emit_ou(&ctx, info, status, 0, 0, remaining);
      } else if (e.op == kOpAmend) {
        e.has_outcome = true;
        if (status == kNew) {
          long long filled_so_far = info.quantity - info.remaining;
          info.remaining = remaining;
          info.quantity = filled_so_far + remaining;
          e.status = kNew;
          e.filled = 0;
          e.remaining = remaining;
          ctx.outcome_order.push_back(idx);
          std::string oid_s = render_oid(info.oid);
          put_str(&ctx.store_updates, oid_s);
          put_u8(&ctx.store_updates, static_cast<uint8_t>(info.status));
          put_i64(&ctx.store_updates, info.remaining);
          put_u8(&ctx.store_updates, 1);  // amend: quantity moves too
          put_i64(&ctx.store_updates, info.quantity);
          ctx.n_updates++;
          if (ctx.build_ou)
            emit_ou(&ctx, info, info.status, 0, 0, remaining);
        } else {
          e.status = kRejected;
          e.filled = 0;
          e.remaining = 0;
          e.error =
              "amend rejected (must strictly reduce an open order's "
              "quantity)";
          ctx.outcome_order.push_back(idx);
        }
      } else {  // cancel
        e.has_outcome = true;
        if (status == kCanceled) {
          info.status = kCanceled;
          info.remaining = 0;
          e.status = kCanceled;
          e.filled = 0;
          e.remaining = remaining;
          ctx.outcome_order.push_back(idx);
          std::string oid_s = render_oid(info.oid);
          put_str(&ctx.store_updates, oid_s);
          put_u8(&ctx.store_updates, static_cast<uint8_t>(kCanceled));
          put_i64(&ctx.store_updates, 0);
          put_u8(&ctx.store_updates, 0);
          put_i64(&ctx.store_updates, 0);
          ctx.n_updates++;
          if (ctx.build_ou) emit_ou(&ctx, info, kCanceled, 0, 0, 0);
        } else {
          e.status = kRejected;
          e.filled = 0;
          e.remaining = 0;
          e.error = "order not open";
          ctx.outcome_order.push_back(idx);
        }
      }
    }
    return 0;
  }

 public:
  // -- finish / take -------------------------------------------------------

  int finish(long long* comp_len, long long* store_len, long long* aux_len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty()) return -1;
    Ctx& ctx = *ctxs_.front();
    if (ctx.decode_cursor != ctx.n_waves || ctx.finished) return -1;

    // Aux: market data FIRST (built pre-eviction, like finalize_fn running
    // before _evict_terminal), then slot deltas etc.
    std::string md;
    uint32_t n_md = 0;
    if (ctx.build_md) {
      if (ctx.shape == 0) {
        for (int32_t slot : ctx.md_slots) {
          const auto& t = ctx.md_tob[slot];
          put_i32(&md, slot);
          for (int v = 0; v < 4; v++) put_i32(&md, t[v]);
          n_md++;
        }
      } else if (!ctx.dense_tob.empty()) {
        for (int32_t slot : ctx.dense_touched) {  // ascending == sorted()
          put_i32(&md, slot);
          put_i32(&md, ctx.dense_tob[slot]);            // best_bid
          put_i32(&md, ctx.dense_tob[S_ + slot]);       // bid_size
          put_i32(&md, ctx.dense_tob[2 * S_ + slot]);   // best_ask
          put_i32(&md, ctx.dense_tob[3 * S_ + slot]);   // ask_size
          n_md++;
        }
      }
    }

    // Eviction (engine_runner._evict_terminal): ops in record order, then
    // terminal makers in ascending handle order.
    for (const CtxOp& e : ctx.ops) {
      const LaneOrder& info = *e.target;
      if (e.op == kOpSubmit &&
          (info.status == kFilled || info.status == kCanceled ||
           info.status == kRejected)) {
        evict_locked(info.handle, &ctx);
      } else if (e.op == kOpCancel && info.status == kCanceled) {
        evict_locked(info.handle, &ctx);
      }
    }
    for (int32_t h : ctx.terminal_makers) {
      auto it = by_handle_.find(h);
      if (it != by_handle_.end() &&
          (it->second->status == kFilled || it->second->status == kCanceled ||
           it->second->status == kRejected))
        evict_locked(h, &ctx);
    }

    // Completion buffers. The gateway batch (kinds 0/1, low tags) uses the
    // me_gateway_complete_batch wire format; amend and local (bit-63 tag)
    // completions ride aux sections the bridge resolves itself.
    std::string comp, aux_amend, aux_local;
    uint32_t n_comp = 0, n_amend = 0, n_local = 0;
    auto emit_comp = [&](uint64_t tag, int kind, bool ok,
                         const std::string& oid, const std::string& err,
                         long long remaining) {
      if (tag & (1ULL << 63)) {
        put_u64(&aux_local, tag);
        put_u8(&aux_local, static_cast<uint8_t>(kind));
        put_u8(&aux_local, ok ? 1 : 0);
        put_i64(&aux_local, remaining);
        put_str(&aux_local, oid);
        put_str(&aux_local, err);
        n_local++;
      } else if (kind == 2) {
        put_u64(&aux_amend, tag);
        put_u8(&aux_amend, ok ? 1 : 0);
        put_i64(&aux_amend, remaining);
        put_str(&aux_amend, oid);
        put_str(&aux_amend, err);
        n_amend++;
      } else {
        put_u64(&comp, tag);
        put_u8(&comp, static_cast<uint8_t>(kind));
        put_u8(&comp, ok ? 1 : 0);
        put_str(&comp, oid);
        put_str(&comp, err);
        n_comp++;
      }
    };
    for (const ImmReject& r : ctx.imm)
      emit_comp(r.tag, r.kind, false, r.order_id, r.error, 0);
    for (int idx : ctx.outcome_order) {
      CtxOp& e = ctx.ops[idx];
      std::string oid = render_oid(e.target->oid);
      if (e.op == kOpAmend) {
        bool ok = e.status == kNew;
        if (ok) ctx.amended++;
        emit_comp(e.tag, 2, ok, oid,
                  ok ? "" : (e.error.empty() ? "amend rejected" : e.error),
                  e.remaining);
      } else if (e.op != kOpCancel) {
        if (e.status == kRejected && !e.error.empty()) {
          ctx.rejected++;
          emit_comp(e.tag, 0, false, oid, e.error, 0);
        } else {
          ctx.accepted++;
          emit_comp(e.tag, 0, true, oid, "", 0);
        }
      } else {
        if (e.status == kCanceled) {
          ctx.canceled++;
          emit_comp(e.tag, 1, true, oid, "", 0);
        } else {
          emit_comp(e.tag, 1, false, oid,
                    e.error.empty() ? "order not open" : e.error, 0);
        }
      }
      e.has_outcome = true;
    }
    for (CtxOp& e : ctx.ops) {  // ops the decode missed: fail loudly
      if (e.has_outcome) continue;
      std::string oid = render_oid(e.target->oid);
      if (e.op == kOpAmend)
        emit_comp(e.tag, 2, false, oid, "op produced no outcome", 0);
      else
        emit_comp(e.tag, e.op == kOpCancel ? 1 : 0, false, oid,
                  "op produced no outcome", 0);
    }

    ctx.comp_buf.clear();
    put_u32(&ctx.comp_buf, n_comp);
    ctx.comp_buf += comp;

    ctx.store_buf.clear();
    put_u32(&ctx.store_buf, ctx.n_store_orders);
    ctx.store_buf += ctx.store_orders;
    put_u32(&ctx.store_buf, ctx.n_updates);
    ctx.store_buf += ctx.store_updates;
    put_u32(&ctx.store_buf, ctx.n_fills);
    ctx.store_buf += ctx.store_fills;

    // Aux assembly (layout mirrored by native.__init__.parse_lane_aux).
    std::string& aux = ctx.aux_buf;
    aux.clear();
    const long long counters[13] = {
        static_cast<long long>(ctx.ops.size()),  // engine_ops
        ctx.accepted, ctx.rejected, ctx.canceled, ctx.amended,
        ctx.fill_count, ctx.overflow_waves,
        ctx.shape, ctx.n_lanes, ctx.n_waves,
        ctx.owner_overflow, ctx.owner_collisions,
        static_cast<long long>(ctx.recon.size())};
    put_u32(&aux, 13);
    for (long long c : counters) put_i64(&aux, c);
    put_u32(&aux, static_cast<uint32_t>(ctx.slot_allocs.size()));
    for (auto& [slot, sym] : ctx.slot_allocs) {
      put_i32(&aux, slot);
      put_str(&aux, sym);
    }
    put_u32(&aux, static_cast<uint32_t>(ctx.slot_releases.size()));
    for (int32_t slot : ctx.slot_releases) put_i32(&aux, slot);
    put_u32(&aux, static_cast<uint32_t>(ctx.new_owners.size()));
    for (auto& [cid, owner] : ctx.new_owners) {
      put_str(&aux, cid);
      put_i32(&aux, owner);
    }
    put_u32(&aux, static_cast<uint32_t>(ctx.recon.size()));
    for (auto& [oid, qty] : ctx.recon) {
      put_str(&aux, oid);
      put_i64(&aux, qty);
    }
    put_u32(&aux, n_md);
    aux += md;
    put_u32(&aux, n_amend);
    aux += aux_amend;
    put_u32(&aux, n_local);
    aux += aux_local;
    put_u32(&aux, ctx.n_ou);
    aux += ctx.aux_ou;

    ctx.finished = true;
    *comp_len = static_cast<long long>(ctx.comp_buf.size());
    *store_len = static_cast<long long>(ctx.store_buf.size());
    *aux_len = static_cast<long long>(ctx.aux_buf.size());
    return 0;
  }

  int take(uint8_t* comp, uint8_t* store, uint8_t* aux) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty() || !ctxs_.front()->finished) return -1;
    Ctx& ctx = *ctxs_.front();
    std::memcpy(comp, ctx.comp_buf.data(), ctx.comp_buf.size());
    std::memcpy(store, ctx.store_buf.data(), ctx.store_buf.size());
    std::memcpy(aux, ctx.aux_buf.data(), ctx.aux_buf.size());
    ctxs_.pop_front();
    return 0;
  }

  // Rollback for a failed dispatch (mirror of _rollback_registrations):
  // drop directory entries for submits with no outcome; handles/slots are
  // NOT recycled (maybe-applied on device). newest=1 pops the just-built
  // context (stage failure), 0 the oldest (decode failure).
  int abort(int newest) {
    std::lock_guard<std::mutex> lk(mu_);
    if (ctxs_.empty()) return -1;
    Ctx& ctx = newest ? *ctxs_.back() : *ctxs_.front();
    for (const CtxOp& e : ctx.ops) {
      if (e.op == kOpSubmit && !e.has_outcome) {
        by_handle_.erase(e.target->handle);
        by_oid_.erase(e.target->oid);
      }
    }
    if (newest)
      ctxs_.pop_back();
    else
      ctxs_.pop_front();
    return 0;
  }

  // -- out-of-dispatch directory access (snapshots, auctions, adopt) -------

  int get_order(int32_t handle, long long* oid, int32_t* i32s /* [5] */,
                long long* i64s /* [2] */, char* symbol, int32_t* sym_len,
                char* client_id, int32_t* cid_len) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = by_handle_.find(handle);
    if (it == by_handle_.end()) return 0;
    const LaneOrder& o = *it->second;
    *oid = o.oid;
    i32s[0] = o.side;
    i32s[1] = o.otype;
    i32s[2] = o.price_q4;
    i32s[3] = o.status;
    i32s[4] = o.handle;
    i64s[0] = o.quantity;
    i64s[1] = o.remaining;
    std::memcpy(symbol, o.symbol.data(), o.symbol.size());
    *sym_len = static_cast<int32_t>(o.symbol.size());
    std::memcpy(client_id, o.client_id.data(), o.client_id.size());
    *cid_len = static_cast<int32_t>(o.client_id.size());
    return 1;
  }

  int32_t lookup(const char* order_id, int32_t len) {
    std::lock_guard<std::mutex> lk(mu_);
    long long oidn = parse_oid(std::string(order_id, len));
    if (oidn < 0) return 0;
    auto it = by_oid_.find(oidn);
    return it == by_oid_.end() ? 0 : it->second->handle;
  }

  int adjust(int32_t handle, long long remaining, int32_t status) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = by_handle_.find(handle);
    if (it == by_handle_.end()) return 0;
    it->second->remaining = remaining;
    it->second->status = status;
    return 1;
  }

  int evict(int32_t handle, int32_t* released_slot) {
    std::lock_guard<std::mutex> lk(mu_);
    *released_slot = -1;
    auto it = by_handle_.find(handle);
    if (it == by_handle_.end()) return 0;
    OrderPtr o = it->second;
    by_handle_.erase(it);
    by_oid_.erase(o->oid);
    free_handles_.push_back(handle);
    auto sit = symbols_.find(o->symbol);
    if (sit != symbols_.end()) slot_release(sit->second, nullptr, released_slot);
    return 1;
  }

  void set_auction_mode(int v) {
    std::lock_guard<std::mutex> lk(mu_);
    auction_mode_ = v != 0;
  }

  void set_oid_stride(long long stride) {
    std::lock_guard<std::mutex> lk(mu_);
    if (stride > 0) oid_stride_ = stride;
  }

  // Install the Python runner's state (boot migration, and the resync
  // after a Python-side control-plane mutation such as an auction).
  // Blob layout built by native.__init__.pack_lane_state; REPLACES all
  // directory/allocator state (refuses mid-dispatch: staged ctxs hold
  // OrderPtrs into the directory being replaced).
  int adopt(const uint8_t* buf, long long len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!ctxs_.empty()) return -2;
    by_handle_.clear();
    by_oid_.clear();
    free_handles_.clear();
    symbols_.clear();
    slot_symbols_.assign(S_, std::string());
    slot_live_.assign(S_, 0);
    free_slots_.clear();
    owner_by_client_.clear();
    owner_claimed_.clear();
    const uint8_t* p = buf;
    const uint8_t* end = buf + len;
    auto rd_u16 = [&](uint16_t* v) {
      if (p + 2 > end) return false;
      std::memcpy(v, p, 2);
      p += 2;
      return true;
    };
    auto rd_u32 = [&](uint32_t* v) {
      if (p + 4 > end) return false;
      std::memcpy(v, p, 4);
      p += 4;
      return true;
    };
    auto rd_i32 = [&](int32_t* v) { return rd_u32(reinterpret_cast<uint32_t*>(v)); };
    auto rd_i64 = [&](long long* v) {
      if (p + 8 > end) return false;
      std::memcpy(v, p, 8);
      p += 8;
      return true;
    };
    auto rd_str = [&](std::string* s) {
      uint16_t n;
      if (!rd_u16(&n) || p + n > end) return false;
      s->assign(reinterpret_cast<const char*>(p), n);
      p += n;
      return true;
    };
    uint32_t version, count;
    if (!rd_u32(&version) || version != 1) return -1;
    if (!rd_i64(&next_oid_) || !rd_i32(&next_handle_)) return -1;
    if (!rd_u32(&count)) return -1;
    free_handles_.assign(count, 0);
    for (uint32_t i = 0; i < count; i++)
      if (!rd_i32(&free_handles_[i])) return -1;
    if (!rd_i32(&next_slot_) || !rd_u32(&count)) return -1;
    free_slots_.assign(count, 0);
    for (uint32_t i = 0; i < count; i++)
      if (!rd_i32(&free_slots_[i])) return -1;
    if (!rd_u32(&count)) return -1;
    for (uint32_t i = 0; i < count; i++) {
      int32_t slot;
      long long live;
      std::string sym;
      if (!rd_i32(&slot) || !rd_i64(&live) || !rd_str(&sym)) return -1;
      if (slot < 0 || slot >= S_) return -1;
      symbols_[sym] = slot;
      slot_symbols_[slot] = sym;
      slot_live_[slot] = live;
    }
    if (!rd_u32(&count)) return -1;
    for (uint32_t i = 0; i < count; i++) {
      std::string cid;
      int32_t owner;
      if (!rd_str(&cid) || !rd_i32(&owner)) return -1;
      owner_by_client_[cid] = owner;
      owner_claimed_[owner] = cid;
    }
    if (!rd_u32(&count)) return -1;
    for (uint32_t i = 0; i < count; i++) {
      auto o = std::make_shared<LaneOrder>();
      if (!rd_i32(&o->handle) || !rd_i64(&o->oid) || !rd_str(&o->client_id) ||
          !rd_str(&o->symbol) || !rd_i32(&o->side) || !rd_i32(&o->otype) ||
          !rd_i32(&o->price_q4) || !rd_i64(&o->quantity) ||
          !rd_i64(&o->remaining) || !rd_i32(&o->status))
        return -1;
      by_handle_[o->handle] = o;
      by_oid_[o->oid] = o;
    }
    int32_t amode;
    if (!rd_i32(&amode)) return -1;
    auction_mode_ = amode != 0;
    return 0;
  }

  // Full state dump in the adopt() blob format (dump -> adopt round-trips
  // bit-identically; the Python mirror refresh before a control-plane
  // mutation parses the same layout). Two-call protocol like dump_slots:
  // nullptr/short cap returns the needed size. Deterministic: orders by
  // ascending handle, symbols by ascending slot; free lists keep their
  // LIFO stack order (future handle/slot assignment depends on it).
  long long dump_state(uint8_t* out, long long cap) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string buf;
    put_u32(&buf, 1);  // version
    put_i64(&buf, next_oid_);
    put_i32(&buf, next_handle_);
    put_u32(&buf, static_cast<uint32_t>(free_handles_.size()));
    for (int32_t h : free_handles_) put_i32(&buf, h);
    put_i32(&buf, next_slot_);
    put_u32(&buf, static_cast<uint32_t>(free_slots_.size()));
    for (int32_t s : free_slots_) put_i32(&buf, s);
    put_u32(&buf, static_cast<uint32_t>(symbols_.size()));
    for (int32_t slot = 0; slot < S_; slot++) {
      if (slot_symbols_[slot].empty()) continue;
      put_i32(&buf, slot);
      put_i64(&buf, slot_live_[slot]);
      put_str(&buf, slot_symbols_[slot]);
    }
    put_u32(&buf, static_cast<uint32_t>(owner_by_client_.size()));
    {
      std::vector<const std::string*> cids;
      cids.reserve(owner_by_client_.size());
      for (auto it = owner_by_client_.begin(); it != owner_by_client_.end();
           ++it)
        cids.push_back(&it->first);
      std::sort(cids.begin(), cids.end(),
                [](const std::string* a, const std::string* b) {
                  return *a < *b;
                });
      for (const std::string* cid : cids) {
        put_str(&buf, *cid);
        put_i32(&buf, owner_by_client_.at(*cid));
      }
    }
    put_u32(&buf, static_cast<uint32_t>(by_handle_.size()));
    {
      std::vector<int32_t> handles;
      handles.reserve(by_handle_.size());
      for (auto it = by_handle_.begin(); it != by_handle_.end(); ++it)
        handles.push_back(it->first);
      std::sort(handles.begin(), handles.end());
      for (int32_t h : handles) {
        const LaneOrder& o = *by_handle_.at(h);
        put_i32(&buf, o.handle);
        put_i64(&buf, o.oid);
        put_str(&buf, o.client_id);
        put_str(&buf, o.symbol);
        put_i32(&buf, o.side);
        put_i32(&buf, o.otype);
        put_i32(&buf, o.price_q4);
        put_i64(&buf, o.quantity);
        put_i64(&buf, o.remaining);
        put_i32(&buf, o.status);
      }
    }
    put_i32(&buf, auction_mode_ ? 1 : 0);
    if (out == nullptr || static_cast<long long>(buf.size()) > cap)
      return static_cast<long long>(buf.size());
    std::memcpy(out, buf.data(), buf.size());
    return static_cast<long long>(buf.size());
  }

  // Full slot-table dump (Python mirror refresh after an abort).
  long long dump_slots(uint8_t* out, long long cap) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string buf;
    put_u32(&buf, static_cast<uint32_t>(symbols_.size()));
    for (int32_t slot = 0; slot < S_; slot++) {
      if (slot_symbols_[slot].empty()) continue;
      put_i32(&buf, slot);
      put_str(&buf, slot_symbols_[slot]);
    }
    if (out == nullptr || static_cast<long long>(buf.size()) > cap)
      return static_cast<long long>(buf.size());
    std::memcpy(out, buf.data(), buf.size());
    return static_cast<long long>(buf.size());
  }

  void stats(long long* live, long long* next_oid, long long* staged) {
    std::lock_guard<std::mutex> lk(mu_);
    *live = static_cast<long long>(by_handle_.size());
    *next_oid = next_oid_;
    *staged = static_cast<long long>(ctxs_.size());
  }

 private:
  void emit_ou(Ctx* ctx, const LaneOrder& o, int32_t status,
               long long fill_price, long long fill_qty, long long remaining) {
    std::string& b = ctx->aux_ou;
    put_i32(&b, status);
    put_i64(&b, fill_price);
    put_i64(&b, fill_qty);
    put_i64(&b, remaining);
    put_str(&b, render_oid(o.oid));
    put_str(&b, o.client_id);
    put_str(&b, o.symbol);
    ctx->n_ou++;
  }

  // EngineRunner._evict: idempotent; handle freed BEFORE the slot check.
  void evict_locked(int32_t handle, Ctx* ctx) {
    auto it = by_handle_.find(handle);
    if (it == by_handle_.end()) return;
    OrderPtr o = it->second;
    by_handle_.erase(it);
    by_oid_.erase(o->oid);
    free_handles_.push_back(handle);
    auto sit = symbols_.find(o->symbol);
    if (sit != symbols_.end()) slot_release(sit->second, ctx, nullptr);
  }

  const int32_t S_, B_, L_, max_fills_;
  std::mutex mu_;
  bool auction_mode_ = false;

  // Directory + allocators (the native twin of EngineRunner's _id_lock
  // state; LIFO free lists, same as the Python list pop/append).
  std::unordered_map<int32_t, OrderPtr> by_handle_;
  std::unordered_map<long long, OrderPtr> by_oid_;
  long long next_oid_ = 1;
  // Partitioned serving: lane i of K allocates the strided residue class
  // (adopt() seeds next_oid_ onto it; this keeps it there). Default 1 ==
  // the dense single-lane line.
  long long oid_stride_ = 1;
  int32_t next_handle_ = 1;
  std::vector<int32_t> free_handles_;
  std::map<std::string, int32_t> symbols_;
  std::vector<std::string> slot_symbols_;
  std::vector<long long> slot_live_;
  std::vector<int32_t> free_slots_;
  int32_t next_slot_ = 0;
  std::unordered_map<std::string, int32_t> owner_by_client_;
  std::unordered_map<int32_t, std::string> owner_claimed_;

  std::deque<std::unique_ptr<Ctx>> ctxs_;  // staged dispatches, FIFO
};

// ---------------------------------------------------------------------------
// GwRing: a standalone MeGwOp ring for the grpcio edge's record dispatcher
// (same batching-window semantics as the gateway's internal ring).
// ---------------------------------------------------------------------------

class GwRing {
 public:
  explicit GwRing(uint32_t capacity) : cap_(capacity) {}

  bool push(const MeGwOp& op) {
    std::unique_lock<std::mutex> lk(mu_);
    if (closed_ || q_.size() >= cap_) {
      dropped_++;
      return false;
    }
    q_.push_back(op);
    cv_.notify_one();
    return true;
  }

  // Bulk push for the batch edge: all-or-nothing under ONE lock
  // acquisition — a batch the ring can't hold entirely is refused whole
  // (per-op "server overloaded" at the RPC, positionally), never split
  // across an overload boundary.
  bool push_n(const MeGwOp* ops, uint32_t n) {
    std::unique_lock<std::mutex> lk(mu_);
    if (closed_ || q_.size() + n > cap_) {
      dropped_ += n;
      return false;
    }
    for (uint32_t i = 0; i < n; i++) q_.push_back(ops[i]);
    cv_.notify_one();
    return true;
  }

  int pop_batch(MeGwOp* out, uint32_t max, uint64_t window_us,
                int64_t first_wait_us) {
    std::unique_lock<std::mutex> lk(mu_);
    if (first_wait_us < 0) {
      cv_.wait(lk, [&] { return closed_ || !q_.empty(); });
    } else if (!cv_.wait_for(lk, std::chrono::microseconds(first_wait_us),
                             [&] { return closed_ || !q_.empty(); })) {
      return 0;
    }
    if (q_.empty()) return -1;
    uint32_t n = 0;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::microseconds(window_us);
    for (;;) {
      while (n < max && !q_.empty()) {
        out[n++] = q_.front();
        q_.pop_front();
      }
      if (n >= max || closed_) break;
      if (cv_.wait_until(lk, deadline,
                         [&] { return closed_ || !q_.empty(); })) {
        if (q_.empty()) break;
        continue;
      }
      break;
    }
    return static_cast<int>(n);
  }

  void close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    cv_.notify_all();
  }

  uint64_t dropped() {
    std::lock_guard<std::mutex> lk(mu_);
    return dropped_;
  }

 private:
  const uint32_t cap_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<MeGwOp> q_;
  bool closed_ = false;
  uint64_t dropped_ = 0;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI (consumed by matching_engine_tpu/native via ctypes)
// ---------------------------------------------------------------------------

extern "C" {

void* me_lanes_create(int32_t num_symbols, int32_t batch, int32_t fill_inline,
                      int32_t max_fills) {
  return new MeLanes(num_symbols, batch, fill_inline, max_fills);
}

void me_lanes_destroy(void* h) { delete static_cast<MeLanes*>(h); }

int me_lanes_build(void* h, const MeGwOp* recs, uint32_t n, int build_ou,
                   int build_md, int32_t* flags, int32_t* wave_n,
                   int32_t* wave_k, uint32_t max_waves) {
  if (!h || (!recs && n)) return -1;
  return static_cast<MeLanes*>(h)->build(recs, n, build_ou, build_md, flags,
                                         wave_n, wave_k, max_waves);
}

int me_lanes_wave(void* h, uint32_t wave, int32_t* out) {
  if (!h || !out) return -1;
  return static_cast<MeLanes*>(h)->wave(wave, out);
}

int me_lanes_wave_mega(void* h, uint32_t w0, uint32_t m, int32_t* out) {
  if (!h || !out) return -1;
  return static_cast<MeLanes*>(h)->wave_mega(w0, m, out);
}

long long me_lanes_decode_wave(void* h, const int32_t* small,
                               long long small_len, const int32_t* fills,
                               long long fills_len) {
  if (!h || !small) return -1;
  return static_cast<MeLanes*>(h)->decode_wave(small, small_len, fills,
                                               fills_len);
}

long long me_lanes_decode_mega(void* h, const int32_t* small,
                               long long small_len, int32_t m, int32_t rcap,
                               int32_t lo, const int32_t* fills,
                               long long fills_len) {
  if (!h || !small) return -1;
  return static_cast<MeLanes*>(h)->decode_mega(small, small_len, m, rcap, lo,
                                               fills, fills_len);
}

int me_lanes_finish(void* h, long long* comp_len, long long* store_len,
                    long long* aux_len) {
  if (!h) return -1;
  return static_cast<MeLanes*>(h)->finish(comp_len, store_len, aux_len);
}

int me_lanes_take(void* h, uint8_t* comp, uint8_t* store, uint8_t* aux) {
  if (!h) return -1;
  return static_cast<MeLanes*>(h)->take(comp, store, aux);
}

int me_lanes_abort(void* h, int newest) {
  if (!h) return -1;
  return static_cast<MeLanes*>(h)->abort(newest);
}

int me_lanes_get_order(void* h, int32_t handle, long long* oid, int32_t* i32s,
                       long long* i64s, char* symbol, int32_t* sym_len,
                       char* client_id, int32_t* cid_len) {
  if (!h) return 0;
  return static_cast<MeLanes*>(h)->get_order(handle, oid, i32s, i64s, symbol,
                                             sym_len, client_id, cid_len);
}

int32_t me_lanes_lookup(void* h, const char* order_id, int32_t len) {
  if (!h || !order_id) return 0;
  return static_cast<MeLanes*>(h)->lookup(order_id, len);
}

int me_lanes_adjust(void* h, int32_t handle, long long remaining,
                    int32_t status) {
  if (!h) return 0;
  return static_cast<MeLanes*>(h)->adjust(handle, remaining, status);
}

int me_lanes_evict(void* h, int32_t handle, int32_t* released_slot) {
  if (!h) return 0;
  return static_cast<MeLanes*>(h)->evict(handle, released_slot);
}

void me_lanes_set_auction_mode(void* h, int v) {
  if (h) static_cast<MeLanes*>(h)->set_auction_mode(v);
}

void me_lanes_set_oid_stride(void* h, long long stride) {
  if (h) static_cast<MeLanes*>(h)->set_oid_stride(stride);
}

int me_lanes_adopt(void* h, const uint8_t* buf, long long len) {
  if (!h || !buf) return -1;
  return static_cast<MeLanes*>(h)->adopt(buf, len);
}

long long me_lanes_dump_slots(void* h, uint8_t* out, long long cap) {
  if (!h) return -1;
  return static_cast<MeLanes*>(h)->dump_slots(out, cap);
}

long long me_lanes_dump_state(void* h, uint8_t* out, long long cap) {
  if (!h) return -1;
  return static_cast<MeLanes*>(h)->dump_state(out, cap);
}

void me_lanes_stats(void* h, long long* live, long long* next_oid,
                    long long* staged) {
  if (!h) {
    *live = *next_oid = *staged = 0;
    return;
  }
  static_cast<MeLanes*>(h)->stats(live, next_oid, staged);
}

// -- GwRing ----------------------------------------------------------------

void* me_gwring_create(uint32_t capacity) { return new GwRing(capacity); }
void me_gwring_destroy(void* r) { delete static_cast<GwRing*>(r); }
int me_gwring_push(void* r, const MeGwOp* op) {
  if (!r || !op) return 0;
  return static_cast<GwRing*>(r)->push(*op) ? 1 : 0;
}
int me_gwring_push_n(void* r, const MeGwOp* ops, uint32_t n) {
  if (!r || (!ops && n)) return 0;
  if (n == 0) return 1;
  return static_cast<GwRing*>(r)->push_n(ops, n) ? 1 : 0;
}

// -- the flat op-record codec (me_gwop.h MeOpRec / domain/oprec.py) --------
//
// Convert a packed run of op-records (a SubmitOrderBatch payload body /
// recorded-flow slice, WITHOUT the 8-byte magic — the caller validated
// it) into tagged MeGwOp ring records in ONE crossing: record i gets tag
// tag_base + i, so positional responses map back by subtraction. Returns
// n, or -1 on a structurally invalid record (length over its box /
// nonzero reserved flags) — the python edge pre-screens those
// positionally, so -1 here means caller skew, never client input.
int me_oprec_to_gwop(const uint8_t* payload, long long len,
                     uint64_t tag_base, MeGwOp* out, uint32_t max_n) {
  if ((!payload && len) || !out) return -1;
  if (len % static_cast<long long>(sizeof(MeOpRec)) != 0) return -1;
  long long n = len / static_cast<long long>(sizeof(MeOpRec));
  if (n > static_cast<long long>(max_n)) return -1;
  const MeOpRec* recs = reinterpret_cast<const MeOpRec*>(payload);
  for (long long i = 0; i < n; i++) {
    const MeOpRec& r = recs[i];
    if (r.symbol_len > sizeof(r.symbol) ||
        r.client_id_len > sizeof(r.client_id) ||
        r.order_id_len > sizeof(r.order_id) || r.flags != 0 ||
        r.op < 1 || r.op > 3)
      return -1;
    MeGwOp& o = out[i];
    o.tag = tag_base + static_cast<uint64_t>(i);
    o.op = r.op;
    o.side = r.side;
    o.otype = r.otype;
    o.price_q4 = r.price_q4;
    o.quantity = r.quantity;
    o.symbol_len = r.symbol_len;
    o.client_id_len = r.client_id_len;
    o.order_id_len = r.order_id_len;
    std::memcpy(o.symbol, r.symbol, r.symbol_len);
    std::memcpy(o.client_id, r.client_id, r.client_id_len);
    std::memcpy(o.order_id, r.order_id, r.order_id_len);
  }
  return static_cast<int>(n);
}
// Native twin of domain/oprec.record_flaws: per-record EDGE validation
// over a packed run (no magic), emitting one flaw code per record into
// `codes` (0 = clean; the codes map positionally onto record_flaws'
// message branches — tests/test_shm_ingress.py pins code<->message
// parity against the python screen). Used by the C++ gateway's native
// M_BATCH path and available to any native ingress that must screen
// without python. Returns n, or -1 on a ragged payload.
int me_oprec_flaws(const uint8_t* payload, long long len,
                   long long max_price_q4, long long max_quantity,
                   int32_t* codes, uint32_t max_n) {
  if ((!payload && len) || !codes) return -1;
  if (len % static_cast<long long>(sizeof(MeOpRec)) != 0) return -1;
  long long n = len / static_cast<long long>(sizeof(MeOpRec));
  if (n > static_cast<long long>(max_n)) return -1;
  const MeOpRec* recs = reinterpret_cast<const MeOpRec*>(payload);
  for (long long i = 0; i < n; i++) {
    const MeOpRec& r = recs[i];
    bool is_submit = r.op == 1;
    bool is_target = r.op == 2 || r.op == 3;
    bool priced = is_submit && (r.otype == 0 || r.otype == 2 || r.otype == 3);
    bool market = is_submit && (r.otype == 1 || r.otype == 4);
    int32_t c = 0;  // branch order mirrors record_flaws exactly
    if (r.op < 1 || r.op > 3)
      c = 1;   // invalid op code
    else if (r.flags != 0)
      c = 2;   // reserved flags
    else if (r.symbol_len > sizeof(r.symbol) ||
             r.client_id_len > sizeof(r.client_id) ||
             r.order_id_len > sizeof(r.order_id))
      c = 3;   // identifier length over the record box
    else if (is_submit && r.symbol_len == 0)
      c = 4;   // symbol required
    else if (is_target && r.order_id_len == 0)
      c = 5;   // unknown order id
    else if (is_target && r.client_id_len == 0)
      c = 6;   // client_id required
    else if (is_submit && r.side != 1 && r.side != 2)
      c = 7;   // side
    else if (is_submit && r.otype > 4)
      c = 8;   // otype
    else if ((is_submit || r.op == 3) && r.quantity <= 0)
      c = 9;   // non-positive quantity
    else if ((is_submit || r.op == 3) && r.quantity > max_quantity)
      c = 10;  // over the engine cap
    else if (priced && (r.price_q4 <= 0 || r.price_q4 > max_price_q4))
      c = 11;  // price out of the device lane
    else if (market && r.price_q4 != 0)
      c = 12;  // MARKET must carry price 0
    codes[i] = c;
  }
  return static_cast<int>(n);
}
int me_gwring_pop_batch(void* r, MeGwOp* out, uint32_t max,
                        uint64_t window_us, int64_t first_wait_us) {
  if (!r || !out) return -1;
  return static_cast<GwRing*>(r)->pop_batch(out, max, window_us,
                                            first_wait_us);
}
void me_gwring_close(void* r) {
  if (r) static_cast<GwRing*>(r)->close();
}
uint64_t me_gwring_dropped(void* r) {
  return r ? static_cast<GwRing*>(r)->dropped() : 0;
}

}  // extern "C"
