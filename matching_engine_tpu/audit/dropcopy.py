"""Drop-copy stream: one compact lifecycle record per order event.

Real venues run a drop-copy feed — an independent, sequenced copy of
every order's lifecycle — precisely because post-hoc database audits are
too late (CoinTossX, arXiv:2102.10925, ships per-order event logging as
a first-class engine output; arXiv:2402.09527 makes online
reconciliation the precondition for replication). Here the drop-copy is
derived from the dispatch's STORAGE EVENT ROWS at the decode boundary:

- the storage rows are produced by the decode on BOTH serving paths
  (DispatchResult.storage_* on the Python path, the unpacked MeSink
  buffer on --native-lanes) and the lane parity suite already pins them
  byte-identical — so the drop-copy reflects what the device actually
  did, with bit-identical payloads whichever path decoded it;
- every record carries the dispatch envelope (trace_id, shape, waves,
  oldest-op edge-ingress wall clock) so one record correlates with the
  flight recorder and the trace export;
- records publish on the sequenced `audit` channel (ONE venue-wide seq
  domain) through the StreamHub, so they replay/resume/gap-detect like
  any sequenced feed channel and the in-process InvariantAuditor can
  treat a seq hole as evidence of loss between decode and publish.

Record vocabulary (OrderUpdate with audit_kind set — scripts/audit.py
and the auditor share it):

  kind 1 ORDER   submit decoded: order_id/client_id/symbol, final-of-
                 dispatch status + remaining, original quantity in
                 audit_quantity, side/otype, limit price in fill_price
  kind 2 UPDATE  status row: order_id, status, remaining (amends carry
                 the reduced quantity in audit_quantity)
  kind 3 FILL    execution: order_id = aggressor, counter_order_id =
                 maker, fill_price/fill_quantity

Fault injection (tests + the soak's corruption round): ME_AUDIT_FAULT
mutates/drops exactly one record between decode and publish, emulating
the corruption classes the auditor must catch — see _FaultInjector.
"""

from __future__ import annotations

import os
import threading
import time

from matching_engine_tpu.proto import pb2

# Reserved StreamOrderUpdates client_id that subscribes the caller to the
# drop-copy audit channel instead of a per-client update stream.
AUDIT_CLIENT = "__dropcopy__"
# Same channel, but cursor 0 means "from the epoch start" (a full
# retained-window replay) instead of the legacy live-only attach — the
# standby attestor's contract: it must pair the primary's audit records
# for the SAME replayed range its applier consumed from the op log.
AUDIT_CLIENT_FULL = "__dropcopy_all__"

KIND_ORDER, KIND_UPDATE, KIND_FILL = 1, 2, 3


def dropcopy_events(orders, updates, fills, trace_id: int = 0,
                    shape: str = "", waves: int = 0,
                    ingress_ts_us: int = 0) -> list[pb2.OrderUpdate]:
    """Encode one dispatch's storage rows as drop-copy records.

    Emission order is ORDER rows, then FILL rows, then UPDATE rows: a
    taker's registration precedes its executions, and maker status
    transitions reflect post-fill state — the order the auditor's shadow
    state machine applies them in.

    The dispatch envelope is splatted only for non-default values: this
    builder runs per storage row on the drain loops' publish path, and
    proto3 never serializes scalar defaults anyway — the wire bytes are
    identical, the setter calls are not."""
    env: dict = {}
    if trace_id:
        env["trace_id"] = trace_id
    if shape:
        env["dispatch_shape"] = shape
    if waves:
        env["dispatch_waves"] = waves
    if ingress_ts_us:
        env["ingress_ts_us"] = ingress_ts_us
    OU = pb2.OrderUpdate
    out: list[pb2.OrderUpdate] = []
    for (oid, cid, sym, side, otype, price, qty, remaining, status) in orders:
        out.append(OU(
            audit_kind=KIND_ORDER, order_id=oid, client_id=cid, symbol=sym,
            status=status, remaining_quantity=remaining, scale=4,
            fill_price=price if price is not None else 0,
            audit_side=side, audit_otype=otype, audit_quantity=qty, **env))
    for f in fills:
        out.append(OU(
            audit_kind=KIND_FILL, order_id=f.order_id,
            counter_order_id=f.counter_order_id, fill_price=f.price_q4,
            fill_quantity=f.quantity, scale=4, **env))
    for row in updates:
        if len(row) > 3:  # amend row: the reduced quantity rides along
            out.append(OU(
                audit_kind=KIND_UPDATE, order_id=row[0], status=row[1],
                remaining_quantity=row[2], audit_quantity=row[3], **env))
        else:
            out.append(OU(
                audit_kind=KIND_UPDATE, order_id=row[0], status=row[1],
                remaining_quantity=row[2], **env))
    return out


def materialize_chunk(rows, env, first_seq: int = 0, epoch: int = 0,
                      skip: int | None = None, lo: int | None = None,
                      hi: int | None = None) -> list[pb2.OrderUpdate]:
    """Build the wire events for one retained dispatch chunk, stamped
    with its seq run — the ONE copy-on-replay materializer shared by the
    hub's live fan-out (`skip` = fault-dropped flat index) and the
    sequencer's replay path (`lo`/`hi` = requested seq range). One
    definition is what makes replayed bytes == live bytes a structural
    guarantee rather than a parallel-implementation promise. `rows` is
    the (orders, updates, fills) triple — the publisher unpacks native
    store buffers ONCE in _process, and the sequencer retains that same
    tuple."""
    orders, updates, fills = rows
    events = dropcopy_events(orders, updates, fills, *env)
    out = []
    for i, e in enumerate(events):
        if i == skip:
            continue
        seq = first_seq + i if first_seq else 0
        if lo is not None and not (lo <= seq <= hi):
            continue
        if seq:
            e.seq = seq
            e.feed_epoch = epoch
        out.append(e)
    return out


class _FaultInjector:
    """Single-shot corruption injector for the decode→publish seam
    (ME_AUDIT_FAULT env; tests and the soak's corruption-injection round).
    Faults apply to the decode-boundary ROWS before encoding, so the
    external drop-copy subscribers and the in-process auditor observe
    the identical corruption:

      fill_qty    mutate one fill row's quantity (+1): the corrupt-
                  decode class — quantity conservation must fire
      transition  rewrite one terminal status row to PARTIALLY_FILLED:
                  the skipped/illegal-transition class
      gap         drop one record AFTER it is stamped: the lost-between-
                  decode-and-publish class — seq continuity must fire

    ME_AUDIT_FAULT_AFTER=k skips the first k eligible records (default
    0). The fault fires once per injector, then disarms. Mutations copy
    the row lists — the async sink already holds references to the
    originals, and the fault models FEED corruption, not store
    corruption.
    """

    def __init__(self, kind: str | None = None, after: int | None = None):
        if kind is None:
            kind = os.environ.get("ME_AUDIT_FAULT", "") or None
        self.kind = kind
        self.after = (int(os.environ.get("ME_AUDIT_FAULT_AFTER", "0"))
                      if after is None else after)
        self.fired = False

    @property
    def armed(self) -> bool:
        return self.kind is not None and not self.fired

    def apply_rows(self, orders, fills, updates):
        """(orders, fills, updates, drop_flat_index | None); flat index
        counts across the orders → fills → updates emission order."""
        if self.kind == "fill_qty":
            for i, f in enumerate(fills):
                if self.after > 0:
                    self.after -= 1
                    continue
                from matching_engine_tpu.storage.storage import FillRow

                fills = list(fills)
                fills[i] = FillRow(f.order_id, f.counter_order_id,
                                   f.price_q4, f.quantity + 1, f.ts)
                self.fired = True
                return orders, fills, updates, None
            return orders, fills, updates, None
        if self.kind == "transition":
            for i, row in enumerate(updates):
                # FILLED/CANCELED rows with remaining 0 only: the
                # PARTIAL rewrite then provably violates the status/
                # remaining machine — a row where the rewrite could
                # pass every invariant must not consume the single shot.
                if row[1] not in (2, 3) or row[2] != 0:
                    continue
                if self.after > 0:
                    self.after -= 1
                    continue
                updates = list(updates)
                updates[i] = (row[0], 1) + tuple(row[2:])  # -> PARTIAL
                self.fired = True
                return orders, fills, updates, None
            return orders, fills, updates, None
        if self.kind == "gap":
            n = len(orders) + len(fills) + len(updates)
            for i in range(n):
                if self.after > 0:
                    self.after -= 1
                    continue
                self.fired = True
                return orders, fills, updates, i
            return orders, fills, updates, None
        raise ValueError(f"unknown ME_AUDIT_FAULT kind {self.kind!r}")


class AuditPump:
    """Out-of-band surveillance worker (the async-sink pattern): the
    drain loops enqueue ONE compact item per dispatch — O(1) on the
    dispatch path, never per record — and this thread builds the
    drop-copy records, stamps + fans them out on the hub, and feeds the
    InvariantAuditor. Real venues run drop-copy out of band for exactly
    this reason: surveillance must not tax the matching path.

    Ordering: the FIFO queue's enqueue order (each lane enqueues from
    its own decode callback, in decode order) IS the audit channel's
    stamp order; one consumer thread makes stamp order == feed order by
    construction.

    Backpressure: a full queue BLOCKS the publisher (counted as
    audit_pump_stalls) instead of dropping — an UNSTAMPED loss would be
    invisible to the very seq-continuity invariant the auditor exists
    to enforce. The queue bounds memory at maxsize dispatches."""

    def __init__(self, metrics, maxsize: int = 4096):
        import queue

        self.metrics = metrics
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        # Pre-register so a healthy server exports zeros, not absence.
        metrics.inc("audit_pump_stalls", 0)
        metrics.inc("audit_pump_errors", 0)
        self._thread = threading.Thread(target=self._run, name="audit-pump",
                                        daemon=True)
        self._thread.start()

    def submit(self, publisher, item) -> None:
        import queue

        try:
            self._q.put_nowait((publisher, item))
        except queue.Full:
            self.metrics.inc("audit_pump_stalls")
            self._q.put((publisher, item))

    def flush(self) -> None:
        """Barrier: returns once everything enqueued so far is audited
        (tests, soak verdicts, shutdown)."""
        done = threading.Event()
        self._q.put(("FLUSH", done))
        done.wait()

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=10)

    def _run(self) -> None:
        from matching_engine_tpu.utils.obs import warn_rate_limited

        while True:
            item = self._q.get()
            if item is None:
                return
            pub, work = item
            if pub == "FLUSH":
                work.set()
                continue
            try:
                pub._process(work)
            except Exception as e:  # noqa: BLE001 — surveillance must
                # degrade (counted + rate-limited), never kill the pump:
                # a dead pump would silently blind the auditor.
                self.metrics.inc("audit_pump_errors")
                warn_rate_limited(
                    "audit-pump",
                    f"[audit] pump error: {type(e).__name__}: {e}")


class DropCopyPublisher:
    """Per-lane drop-copy publisher: `publish()` is called by the lane's
    drain loop on_finish (under that lane's dispatch lock, right where
    the sink/hub publish happens) and SNAPSHOTS the dispatch's rows +
    envelope — the row lists must be captured before the async sink's
    coalescing can extend them, and auction_mode read at dispatch time.
    With an AuditPump the heavy half (record build, stamp, fan-out,
    invariant pass — and on the native path the store-buffer unpack)
    runs out of band on the pump thread; without one it runs inline
    (tests, the client-side checker)."""

    def __init__(self, hub, metrics, auditor=None, runner=None,
                 fault: _FaultInjector | None = None, pump=None):
        self.hub = hub
        self.metrics = metrics
        self.auditor = auditor
        self.runner = runner  # auction_mode: crossed books are legal then
        self.fault = fault if fault is not None else _FaultInjector()
        self.pump = pump

    def publish(self, result, timeline=None, shape: str = "") -> None:
        store_buf = getattr(result, "store_buf", None)
        if store_buf is not None:  # native path: immutable MeSink wire
            rows = store_buf if len(store_buf) > 12 else None
        else:
            # Tuple snapshots: the sink's coalescing thread EXTENDS the
            # first queued batch's lists in place — reading them later
            # (or even concurrently) would replay another dispatch's
            # rows into this dispatch's drop-copy.
            rows = (tuple(result.storage_orders),
                    tuple(result.storage_updates),
                    tuple(result.storage_fills))
            if not (rows[0] or rows[1] or rows[2]):
                rows = None
        md = getattr(result, "market_data", None)
        if rows is None and not md:
            return
        trace_id, waves, ingress_us = 0, 0, 0
        if timeline is not None:
            trace_id = timeline.trace_id
            shape = timeline.shape or shape
            waves = timeline.waves
            if timeline.t_ingress is not None:
                # perf_counter stamp -> wall clock µs (the envelope is
                # normalized away in parity comparisons).
                ingress_us = int((time.time() - (time.perf_counter()
                                 - timeline.t_ingress)) * 1e6)
        in_auction = self.runner is not None and self.runner.auction_mode
        item = (rows, md, (trace_id, shape, waves, ingress_us), in_auction)
        if self.pump is not None:
            self.pump.submit(self, item)
        else:
            self._process(item)

    def _process(self, item) -> None:
        rows, md, env, in_auction = item
        if rows is None:
            orders, updates, fills = (), (), ()
        elif isinstance(rows, (bytes, bytearray)):
            from matching_engine_tpu import native as me_native

            orders, updates, fills = me_native.unpack_store_buf(rows)
        else:
            orders, updates, fills = rows
        drop = None
        if self.fault.armed:
            orders, fills, updates, drop = self.fault.apply_rows(
                orders, fills, updates)
        n = len(orders) + len(fills) + len(updates)
        observer = None
        if self.auditor is not None:
            a_orders, a_fills, a_updates = orders, fills, updates
            if drop is not None:
                # Keep the auditor's row feed aligned with what was
                # actually delivered (the dropped record is exactly what
                # its seq-continuity invariant must notice is missing).
                a_orders, a_fills, a_updates = \
                    list(orders), list(fills), list(updates)
                no, nf = len(orders), len(fills)
                if drop < no:
                    del a_orders[drop]
                elif drop < no + nf:
                    del a_fills[drop - no]
                else:
                    del a_updates[drop - no - nf]

            # Runs under the hub lock: the auditor must see batches in
            # stamp order. Content feeds as the decode-boundary ROWS;
            # seq continuity checks the delivered stamp list. Uncross
            # batches (shape "auction") relax the maker-price equality
            # rule — they execute at the clearing price.
            is_auction = env[1] == "auction"

            def observer(seqs):
                self.auditor.observe_rows(
                    a_orders, a_fills, a_updates, seqs=seqs,
                    market_data=md, crossed_ok=in_auction,
                    auction=is_auction)

        if n or observer is not None:
            delivered = self.hub.publish_audit_rows(
                (orders, updates, fills), env, n, drop=drop,
                observer=observer)
            if delivered:
                self.metrics.inc("audit_records", len(delivered))
        if self.auditor is not None:
            # Store probes that came due during the observe run NOW —
            # outside the hub lock, on this (pump/caller) thread.
            self.auditor.maybe_store_check()
