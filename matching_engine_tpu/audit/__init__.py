"""Online surveillance: per-order drop-copy stream + invariant auditor.

The serving stack maintains three independent truth surfaces — the
device book, the SQLite store, the sequenced feed — and until this
package the only correctness check was an OFFLINE audit of the store
after shutdown. Here:

- `dropcopy` publishes one compact lifecycle record per order event on
  the sequenced `audit` feed channel, derived from the dispatch's
  storage rows at the decode boundary on BOTH serving paths (so the
  records are bit-identical whichever path decoded the dispatch), and
- `auditor.InvariantAuditor` consumes those records in-process and
  asserts, continuously, that the surfaces agree — first violation
  flight-dumps with the offending record inlined and /auditz turns red.

Consume externally via StreamOrderUpdates with the reserved
`AUDIT_CLIENT` client_id (resume/gap-fill like any sequenced channel),
`client/cli.py audit`, or `scripts/audit.py --dropcopy` offline.
"""

from matching_engine_tpu.audit.auditor import VIOLATION_KINDS, InvariantAuditor
from matching_engine_tpu.audit.dropcopy import (
    AUDIT_CLIENT,
    KIND_FILL,
    KIND_ORDER,
    KIND_UPDATE,
    AuditPump,
    DropCopyPublisher,
    dropcopy_events,
)

__all__ = ["AUDIT_CLIENT", "AuditPump", "DropCopyPublisher",
           "InvariantAuditor", "KIND_FILL", "KIND_ORDER", "KIND_UPDATE",
           "VIOLATION_KINDS", "dropcopy_events"]
