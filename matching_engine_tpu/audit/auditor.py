"""InvariantAuditor: the continuous online proof that the three truth
surfaces — device book, durable store, sequenced feed — agree.

A shadow per-order state machine fed from the drop-copy records (plus
lazy read-only probes of the durable store), asserting ONLINE what
scripts/audit.py could previously only prove after the server was dead:

  transition      legal status transitions only (NEW -> PARTIALLY_FILLED
                  -> {FILLED, CANCELED}; REJECTED terminal; FILLED <=>
                  remaining == 0, PARTIAL/NEW => remaining > 0)
  conservation    Σ fills <= original quantity; remaining monotone
                  non-increasing; fills == quantity - remaining at every
                  dispatch boundary (REJECTED included; CANCELED holds
                  no remainder liability — scripts/audit.py's rules)
  fill_symmetry   every fill references a live maker (and a registered
                  aggressor) with matching symbol, opposite side, and
                  the maker's limit price
  seq_gap         the audit channel's venue-wide seq line is dense — a
                  hole is an event lost between decode and publish
  crossed_book    best_bid < best_ask after every dispatch (call-auction
                  accumulation excepted, where crossed books are legal)
  store_mismatch  sampled terminal orders' durable rows (status,
                  remaining, Σ fills) equal the shadow once committed
  malformed       structurally impossible records (non-positive fill
                  quantity, negative remaining, self-crossed ids)

Two feeding surfaces share one core:

- `observe_rows(orders, fills, updates, seqs)` — the in-process hot
  path: the DispatchResult's storage row TUPLES straight from the
  decode (no proto attribute reads; this runs on the drain loops'
  publish path under the hub lock), with seq continuity checked from
  the delivered wire events' seq list;
- `observe(events)` — wire-shaped drop-copy protos (the client-side
  checker behind `client audit`), converted to rows and delegated.

Cost model (--audit-sample N): the cheap record-shape, seq, and
crossed-book invariants run for EVERY record; the full shadow state
machine (and the store probes) track a deterministic 1-in-N order
subset (multiplicative hash of the OID number — a plain modulus would
miss strided shard lanes' residue classes entirely), so overhead is
bounded and the subset is
identical across runs/replicas — the determinism-audit substrate the HA
replica (ROADMAP Open item 3) will reuse to assert primary/standby
bit-identity. N=1 shadows everything (tests, corruption soaks).

The first violation flight-records the offending record inline and
schedules a post-mortem dump (rate-limited thereafter);
me_audit_violations_total{_<kind>} count every one; /readyz stays up but
/auditz turns red (utils/obs.ObsServer).
"""

from __future__ import annotations

import sqlite3
import threading
import time
from collections import deque

from matching_engine_tpu.audit.dropcopy import KIND_FILL, KIND_ORDER, KIND_UPDATE
from matching_engine_tpu.utils.obs import warn_rate_limited

NEW, PARTIALLY_FILLED, FILLED, CANCELED, REJECTED = range(5)
_TERMINAL = (FILLED, CANCELED, REJECTED)
_LEGAL = {
    NEW: (NEW, PARTIALLY_FILLED, FILLED, CANCELED),
    PARTIALLY_FILLED: (PARTIALLY_FILLED, FILLED, CANCELED),
    FILLED: (),
    CANCELED: (),
    REJECTED: (),
}

VIOLATION_KINDS = ("transition", "conservation", "fill_symmetry",
                   "seq_gap", "crossed_book", "store_mismatch", "malformed")


class _Shadow:
    __slots__ = ("qty", "remaining", "status", "side", "symbol",
                 "price_q4", "filled")

    def __init__(self, qty, remaining, status, side, symbol, price_q4):
        self.qty = qty
        self.remaining = remaining
        self.status = status
        self.side = side
        self.symbol = symbol
        self.price_q4 = price_q4
        self.filled = 0


def _oid_num(order_id: str) -> int | None:
    if order_id.startswith("OID-"):
        try:
            return int(order_id[4:])
        except ValueError:
            return None
    return None


class InvariantAuditor:
    """Thread-safe (one lock; every serving lane's drain loop feeds it,
    serialized through the StreamHub's publish lock)."""

    def __init__(self, metrics=None, sample: int = 8,
                 db_path: str | None = None, store_check_every: int = 32,
                 max_tracked: int = 1 << 20, max_pending: int = 8192,
                 strict: bool = True):
        if metrics is None:
            from matching_engine_tpu.utils.metrics import Metrics

            metrics = Metrics()
        self.metrics = metrics
        self.sample = max(1, int(sample))
        # strict=True: the in-process mode — attached from boot, so a
        # fill/update referencing an unregistered order IS corruption.
        # strict=False: a client-side checker that may have attached
        # mid-stream — unknown references are skipped (only references
        # to orders it SAW go terminal still violate).
        self.strict = strict
        self.db_path = db_path
        self.store_check_every = max(1, int(store_check_every))
        self.max_tracked = max_tracked
        self._lock = threading.Lock()
        self._shadows: dict[str, _Shadow] = {}
        self._last_seq = 0
        self._dispatches = 0
        self._auction_batch = False  # current batch is an uncross
        self.violations = 0
        self.by_kind: dict[str, int] = {k: 0 for k in VIOLATION_KINDS}
        self.records_seen = 0
        self.store_checks = 0
        self.max_pending = max(1, int(max_pending))
        # Sampled terminal orders awaiting their durable-store probe:
        # (order_id, status, remaining, filled, attempts) — plus a
        # parallel id set so _retired() stays O(1) (a linear deque scan
        # per registered order would ride the publish path).
        self._store_pending: deque = deque()
        self._store_pending_ids: set[str] = set()
        self._probe_due = False
        # Serializes PROBERS only (sink-commit hook vs pump cadence);
        # the SQL itself runs outside the main auditor lock — the
        # hub-lock → auditor-lock publish path must never wait on
        # SQLite.
        self._probe_lock = threading.Lock()
        self._recent: deque = deque(maxlen=32)
        self._conn: sqlite3.Connection | None = None
        # Orders born before the auditor attached (boot recovery replay
        # publishes no drop-copy): ids below the floor are exempt from
        # shadow tracking — a fill referencing one is pre-boot state,
        # not corruption. Strided lanes recover unequal counts, so the
        # floor is per OID residue class (set_oid_floors) — one global
        # max would exempt the other lanes' genuinely new ids.
        self.oid_floor = 0
        self._oid_floors: dict[int, int] = {}  # n % stride -> floor
        self._oid_stride = 1
        # Pre-register the exported series so a clean server still
        # exposes zeros (scrapers see names, not absence); the per-kind
        # registrations stay literal for the OPERATIONS.md doc-lint.
        m = metrics
        m.inc("audit_records", 0)
        m.inc("audit_violations", 0)
        m.inc("audit_violations_transition", 0)
        m.inc("audit_violations_conservation", 0)
        m.inc("audit_violations_fill_symmetry", 0)
        m.inc("audit_violations_seq_gap", 0)
        m.inc("audit_violations_crossed_book", 0)
        m.inc("audit_violations_store_mismatch", 0)
        m.inc("audit_violations_malformed", 0)
        m.inc("audit_store_checks", 0)
        m.set_gauge("audit_tracked_orders", 0)
        m.set_gauge("audit_store_pending", 0)

    # -- violation plumbing ------------------------------------------------

    def _violation(self, kind: str, detail: str, record=None) -> None:
        self.violations += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        self.metrics.inc("audit_violations")
        self.metrics.inc("audit_violations_" + kind)
        entry = {
            "kind": "audit_violation", "violation": kind, "detail": detail,
            "wall_ts": time.time(),
        }
        if record is not None:
            entry["record"] = record
        self._recent.append(entry)
        recorder = getattr(self.metrics, "recorder", None)
        if recorder is not None:
            # The offending record rides the flight ring inline; the dump
            # (rate-limited, background thread) is the operator's
            # post-mortem with the dispatch context around it.
            recorder.record(entry)
            recorder.dump_on_error()
        warn_rate_limited(
            "auditor-" + kind,
            f"[audit] INVARIANT VIOLATION ({kind}): {detail}")

    # -- sampling ----------------------------------------------------------

    def _tracked_id(self, order_id: str) -> bool:
        n = _oid_num(order_id)
        if n is None:
            return False
        floor = (self._oid_floors.get(n % self._oid_stride, self.oid_floor)
                 if self._oid_floors else self.oid_floor)
        if n < floor:
            return False
        if self.sample == 1:
            return True
        # Multiplicative hash with a high-bit fold, NOT n % sample:
        # strided shard lanes allocate one residue class each, and a
        # plain modulus would leave whole lanes with zero shadow
        # coverage (no odd n has n % 8 == 0; an odd multiplier alone
        # preserves parity, hence the fold). Still a pure deterministic
        # function of the OID — identical subset across runs/replicas.
        h = (n * 2654435761) & 0xFFFFFFFF
        return ((h ^ (h >> 16)) % self.sample) == 0

    def set_oid_floors(self, lanes) -> None:
        """Per-residue-class pre-boot floors: lanes is
        [(next_oid, oid_offset, oid_stride)] over the serving runners
        after recovery replay."""
        for next_oid, offset, stride in lanes:
            if stride <= 1:
                self.oid_floor = max(self.oid_floor, next_oid)
            else:
                self._oid_stride = stride
                self._oid_floors[(offset + 1) % stride] = next_oid

    def _retired(self, order_id: str) -> bool:
        return order_id in self._store_pending_ids

    def _pending_add_locked(self, ent) -> None:
        if len(self._store_pending) >= self.max_pending:
            evicted = self._store_pending.popleft()
            self._store_pending_ids.discard(evicted[0])
        self._store_pending.append(ent)
        self._store_pending_ids.add(ent[0])

    def seed_seq(self, last_seq: int) -> None:
        """Set the expected seq cursor (a client-side checker attaching
        mid-stream seeds from its first event; the in-process auditor
        keeps the boot default of 0 = expect the line to start at 1)."""
        with self._lock:
            self._last_seq = max(self._last_seq, last_seq)

    # -- the per-dispatch feed --------------------------------------------

    def observe_rows(self, orders, fills, updates, seqs=None,
                     market_data=None, crossed_ok: bool = False,
                     auction: bool = False) -> None:
        """Consume one dispatch's delivered drop-copy content as the
        decode-boundary ROW tuples (orders: storage order rows, fills:
        FillRows, updates: status rows) plus the delivered wire events'
        seq list. The in-process hot path — plain tuple/int work, called
        under the publishing hub lock so concurrent lanes feed in stamp
        order. `auction` marks an uncross batch: its fills execute at
        the CLEARING price, which may legitimately improve on a maker's
        limit — the maker-price equality check is continuous-matching
        law only."""
        with self._lock:
            self._auction_batch = auction
            self._observe_locked(orders, fills, updates, seqs,
                                 market_data, crossed_ok)

    def observe(self, events, market_data=None,
                crossed_ok: bool = False) -> None:
        """Wire-shaped feed (drop-copy OrderUpdate protos): convert to
        rows and delegate — the client-side checker's surface."""
        from matching_engine_tpu.storage.storage import FillRow

        orders, fills, updates, seqs = [], [], [], []
        for e in events:
            seqs.append(e.seq)
            k = e.audit_kind
            if k == KIND_ORDER:
                orders.append((e.order_id, e.client_id, e.symbol,
                               e.audit_side, e.audit_otype, e.fill_price,
                               e.audit_quantity, e.remaining_quantity,
                               e.status))
            elif k == KIND_FILL:
                fills.append(FillRow(e.order_id, e.counter_order_id,
                                     e.fill_price, e.fill_quantity))
            elif k == KIND_UPDATE:
                if e.audit_quantity:
                    updates.append((e.order_id, e.status,
                                    e.remaining_quantity, e.audit_quantity))
                else:
                    updates.append((e.order_id, e.status,
                                    e.remaining_quantity))
            else:
                with self._lock:
                    self._violation("malformed",
                                    f"unknown audit_kind {k}",
                                    {"order_id": e.order_id, "seq": e.seq})
        self.observe_rows(
            orders, fills, updates, seqs, market_data, crossed_ok,
            auction=bool(events) and events[0].dispatch_shape == "auction")

    def _observe_locked(self, orders, fills, updates, seqs,
                        market_data, crossed_ok) -> None:
        self.records_seen += len(orders) + len(fills) + len(updates)
        if seqs:
            last = self._last_seq
            for seq in seqs:
                if seq:
                    # Attached from boot, the audit line is known to
                    # start at 1: a hole BEFORE the first observed
                    # record is as much a loss as one in the middle.
                    # (Client-side checkers attaching mid-stream seed
                    # the cursor via seed_seq.)
                    if seq != last + 1:
                        self._violation(
                            "seq_gap",
                            f"audit seq hole: {last} -> {seq} "
                            f"({seq - last - 1} record(s) lost between "
                            f"decode and publish)")
                    if seq > last:
                        last = seq
            self._last_seq = last
        touched: dict[str, _Shadow] = {}
        for row in orders:
            self._apply_order(row, touched)
        for f in fills:
            self._apply_fill(f, touched)
        for row in updates:
            self._apply_update(row, touched)
        # Dispatch-boundary conservation: every touched shadow's books
        # must balance NOW — corruption is caught within one dispatch.
        for oid, s in touched.items():
            self._check_balance(oid, s)
        # Terminal shadows retire to the store-probe queue (bounds the
        # live set at open + in-flight sampled orders).
        for oid, s in touched.items():
            if s.status in _TERMINAL and oid in self._shadows:
                del self._shadows[oid]
                self._pending_add_locked(
                    [oid, s.status, s.remaining, s.filled, 0])
        if market_data:
            for u in market_data:
                if (not crossed_ok and u.bid_size > 0 and u.ask_size > 0
                        and u.best_bid >= u.best_ask):
                    self._violation(
                        "crossed_book",
                        f"{u.symbol}: crossed top-of-book after dispatch "
                        f"(bid {u.best_bid}x{u.bid_size} >= ask "
                        f"{u.best_ask}x{u.ask_size})")
        self._dispatches += 1
        if self._dispatches % 16 == 0:  # gauge refresh, not per dispatch
            self.metrics.set_gauge("audit_tracked_orders",
                                   len(self._shadows))
            self.metrics.set_gauge("audit_store_pending",
                                   len(self._store_pending))
        if (self.db_path is not None and self._store_pending
                and self._dispatches % self.store_check_every == 0):
            # NEVER probe here: observe_rows runs under the publishing
            # hub lock — the caller (pump/client) probes after release.
            self._probe_due = True

    def _apply_order(self, row, touched) -> None:
        (oid, _cid, sym, side, _otype, price, qty, rem, status) = row
        if qty <= 0 or rem < 0 or rem > qty:
            self._violation(
                "malformed",
                f"{oid}: impossible order row qty={qty} remaining={rem}",
                {"order_id": oid, "row": list(row)})
            return
        self._check_status_remaining(oid, status, rem, qty)
        if not self._tracked_id(oid):
            return
        if oid in self._shadows or self._retired(oid):
            self._violation(
                "transition", f"{oid}: re-registered (duplicate order row)",
                {"order_id": oid, "row": list(row)})
            return
        if len(self._shadows) >= self.max_tracked:
            return  # bounded memory: stop adopting, keep existing checks
        s = _Shadow(qty, rem, status, side, sym,
                    price if price is not None else 0)
        self._shadows[oid] = s
        touched[oid] = s

    def _apply_fill(self, f, touched) -> None:
        fq = f.quantity
        oid, coid = f.order_id, f.counter_order_id
        if fq <= 0:
            self._violation(
                "malformed",
                f"non-positive fill quantity {fq} ({oid}/{coid})",
                {"order_id": oid, "counter_order_id": coid})
            return
        if not coid:
            self._violation("malformed", f"{oid}: fill without a maker",
                            {"order_id": oid})
            return
        if oid == coid:
            self._violation(
                "fill_symmetry", f"{oid}: fill pairs an order with itself",
                {"order_id": oid})
            return
        taker = maker = None
        if self._tracked_id(oid):
            taker = self._shadows.get(oid)
            if taker is None:
                if self.strict or self._retired(oid):
                    self._violation(
                        "fill_symmetry",
                        f"fill references unregistered or dead aggressor "
                        f"{oid}",
                        {"order_id": oid, "counter_order_id": coid,
                         "fill_quantity": fq, "fill_price": f.price_q4})
            else:
                taker.filled += fq
                touched[oid] = taker
        if self._tracked_id(coid):
            maker = self._shadows.get(coid)
            if maker is None:
                # Live-maker invariant: terminal shadows retired at the
                # previous dispatch boundary, so a lookup miss IS a fill
                # against a dead (or, in strict mode, never-registered)
                # maker.
                if self.strict or self._retired(coid):
                    self._violation(
                        "fill_symmetry",
                        f"fill references dead or unknown maker {coid} "
                        f"(taker {oid})",
                        {"order_id": oid, "counter_order_id": coid,
                         "fill_quantity": fq, "fill_price": f.price_q4})
            else:
                maker.filled += fq
                touched[coid] = maker
                # Continuous matching executes AT the maker's limit; an
                # auction uncross executes at the clearing price, which
                # may improve on it — strict equality there would flag
                # every price-improved auction fill.
                if f.price_q4 != maker.price_q4 and not self._auction_batch:
                    self._violation(
                        "fill_symmetry",
                        f"fill at {f.price_q4} but maker {coid} rests at "
                        f"{maker.price_q4}",
                        {"order_id": oid, "counter_order_id": coid,
                         "fill_price": f.price_q4})
        if taker is not None and maker is not None:
            if taker.side == maker.side:
                self._violation(
                    "fill_symmetry",
                    f"fill pairs same-side orders {oid}/{coid}",
                    {"order_id": oid, "counter_order_id": coid})
            if taker.symbol != maker.symbol:
                self._violation(
                    "fill_symmetry",
                    f"fill crosses symbols {oid}/{coid}",
                    {"order_id": oid, "counter_order_id": coid})

    def _apply_update(self, row, touched) -> None:
        oid, status, rem = row[0], row[1], row[2]
        if rem < 0:
            self._violation("malformed",
                            f"{oid}: negative remaining {rem}",
                            {"order_id": oid, "row": list(row)})
            return
        if not self._tracked_id(oid):
            return
        s = self._shadows.get(oid)
        if s is None:
            # Update for an untracked/retired order: a status row after
            # terminal retirement is itself an illegal transition.
            if self._retired(oid):
                self._violation(
                    "transition",
                    f"{oid}: status row after terminal state",
                    {"order_id": oid, "row": list(row)})
            return
        if status not in _LEGAL.get(s.status, ()):
            self._violation(
                "transition",
                f"{oid}: illegal transition {s.status} -> {status}",
                {"order_id": oid, "row": list(row)})
        if rem > s.remaining:
            self._violation(
                "conservation",
                f"{oid}: remaining increased {s.remaining} -> {rem}",
                {"order_id": oid, "row": list(row)})
        if len(row) > 3:  # amend row: quantity reduces with remaining
            if row[3] > s.qty:
                self._violation(
                    "conservation",
                    f"{oid}: amend RAISED quantity {s.qty} -> {row[3]}",
                    {"order_id": oid, "row": list(row)})
            s.qty = row[3]
        self._check_status_remaining(oid, status, rem, s.qty)
        s.status = status
        s.remaining = rem
        touched[oid] = s

    def _check_status_remaining(self, oid, status, rem, qty) -> None:
        """Per-record status/remaining machine (kind: transition)."""
        if status == FILLED:
            if rem != 0:
                self._violation(
                    "transition", f"{oid}: FILLED with remaining={rem}",
                    {"order_id": oid})
        elif status == NEW:
            if rem != qty:
                self._violation(
                    "transition",
                    f"{oid}: NEW with remaining {rem} != quantity {qty}",
                    {"order_id": oid})
        elif status == PARTIALLY_FILLED and not (0 < rem < qty):
            self._violation(
                "transition",
                f"{oid}: PARTIALLY_FILLED with remaining={rem} of {qty}",
                {"order_id": oid})

    def _check_balance(self, oid: str, s: _Shadow) -> None:
        """scripts/audit.py's per-order arithmetic, held at EVERY
        dispatch boundary (acknowledged fill-record loss — the
        me_fill_buffer_overflows_total regime — surfaces here by design:
        the drop-copy is missing exactly what the fills table is)."""
        if s.status == CANCELED:
            if s.filled > s.qty:
                self._violation(
                    "conservation",
                    f"{oid}: overfilled ({s.filled} > {s.qty})")
            return
        if s.filled != s.qty - s.remaining:
            self._violation(
                "conservation",
                f"{oid}: fills {s.filled} != quantity {s.qty} - "
                f"remaining {s.remaining} (status {s.status})")

    # -- durable-store probes ----------------------------------------------

    def _db(self) -> sqlite3.Connection | None:
        if self._conn is None and self.db_path is not None:
            try:
                self._conn = sqlite3.connect(
                    f"file:{self.db_path}?mode=ro", uri=True,
                    check_same_thread=False, timeout=1.0)
            except sqlite3.Error:
                return None  # store not initialized yet: probes wait
        return self._conn

    def _store_probe(self, limit: int, strict: bool = False) -> None:
        """Probe up to `limit` pending entries against the durable
        store. The SQL runs OUTSIDE the main auditor lock (only
        _probe_lock serializes concurrent probers — the sink-commit hook
        vs the pump cadence): the hub-lock → auditor-lock publish path
        must never wait on SQLite."""
        with self._probe_lock:
            # Connect (and memoize) OUTSIDE the auditor lock: _conn is a
            # probers-only resource and sqlite3.connect can block on the
            # filesystem — under _lock it would stall the hub-locked
            # publish path (the lock-order analyzer pins this).
            conn = self._db()
            if conn is None:
                return
            with self._lock:
                n = min(limit, len(self._store_pending))
                entries = []
                for _ in range(n):
                    ent = self._store_pending.popleft()
                    self._store_pending_ids.discard(ent[0])
                    entries.append(ent)
            requeue: list = []
            findings: list[str] = []
            checked = 0
            for ent in entries:
                oid, status, remaining, filled, attempts = ent
                try:
                    row = conn.execute(
                        "SELECT status, remaining_quantity FROM orders "
                        "WHERE order_id = ?", (oid,)).fetchone()
                    if row is None or row[0] not in _TERMINAL:
                        # The async sink hasn't committed this far yet:
                        # not a contradiction, re-probe later. Strict
                        # mode (the caller flushed the sink first) makes
                        # absence a finding.
                        if strict:
                            findings.append(
                                f"{oid}: terminal on the feed (status "
                                f"{status}) but store row is "
                                f"{'absent' if row is None else 'non-terminal'}"
                                f" after flush")
                        else:
                            ent[4] = attempts + 1
                            requeue.append(ent)
                        continue
                    checked += 1
                    db_fills = conn.execute(
                        "SELECT COALESCE(SUM(quantity), 0) FROM fills "
                        "WHERE order_id = ? OR counter_order_id = ?",
                        (oid, oid)).fetchone()[0]
                    if row[0] != status or row[1] != remaining:
                        findings.append(
                            f"{oid}: store row (status {row[0]}, "
                            f"remaining {row[1]}) contradicts the feed "
                            f"(status {status}, remaining {remaining})")
                    elif db_fills != filled:
                        findings.append(
                            f"{oid}: store fills {db_fills} != feed "
                            f"fills {filled}")
                except sqlite3.Error:
                    # Mid-write contention/corrupt file: retry later; a
                    # persistent failure leaves entries pending, visible
                    # in audit_store_pending.
                    ent[4] = attempts + 1
                    requeue.append(ent)
            with self._lock:
                for ent in requeue:
                    self._pending_add_locked(ent)
                self.store_checks += checked
                if checked:
                    self.metrics.inc("audit_store_checks", checked)
                for detail in findings:
                    self._violation("store_mismatch", detail)
                self.metrics.set_gauge("audit_store_pending",
                                       len(self._store_pending))

    def maybe_store_check(self) -> None:
        """Run a bounded probe pass if one came due during observe_rows
        — called by the pump AFTER the hub lock is released (the cadence
        fallback for sinks without the commit hook)."""
        if self._probe_due:
            self._probe_due = False
            self._store_probe(limit=8)

    def notify_commit(self) -> None:
        """Sink-commit notification (wired to AsyncStorageSink.on_commit
        by build_server): a storage batch just landed, so pending probes
        have their best chance of resolving — run a bounded pass HERE on
        the sink's own thread, off every dispatch path."""
        if self.db_path is None or not self._store_pending:
            return
        self._store_probe(limit=8)

    def final_store_check(self) -> None:
        """Strict pass over every pending probe — call after the caller
        flushed the sink (tests, shutdown, soak verdicts)."""
        self._store_probe(limit=len(self._store_pending), strict=True)

    # -- reporting (/auditz) -----------------------------------------------

    @property
    def red(self) -> bool:
        return self.violations > 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ok": self.violations == 0,
                "violations": self.violations,
                "by_kind": {k: v for k, v in self.by_kind.items() if v},
                "records": self.records_seen,
                "dispatches": self._dispatches,
                "tracked_orders": len(self._shadows),
                "sample": self.sample,
                "last_seq": self._last_seq,
                "store": {"checks": self.store_checks,
                          "pending": len(self._store_pending)},
                "recent": list(self._recent),
            }

    def close(self) -> None:
        # _conn is probers-only state: serialize on the probe lock, not
        # the auditor lock (SQLite teardown never blocks observe_rows).
        with self._probe_lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except sqlite3.Error:
                    pass
                self._conn = None
