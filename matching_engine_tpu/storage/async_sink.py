"""Asynchronous storage sink: the durable tail of the TPU fill stream.

The reference's single biggest structural flaw is that its only hot path is a
synchronous SQLite INSERT inside the RPC handler under a global mutex
(SURVEY.md §3.2). Here persistence is decoupled: the engine runner emits
(order-insert, status-update, fill) events per dispatch onto a queue; one
background thread drains the queue and writes each dispatch as a single WAL
transaction (`Storage.apply_batch`). The match path never blocks on disk.

Durability model: same as the reference (WAL + synchronous=NORMAL) but
batched — on crash, the tail of the fill stream since the last drained batch
is lost from SQLite while the device book retains it; recovery reconciles
from the book checkpoint (utils/checkpoint.py). `flush()` gives callers a
barrier when they need read-your-writes (tests, shutdown drain).
"""

from __future__ import annotations

import queue
import threading
import time

from matching_engine_tpu.storage.storage import FillRow, Storage


class SpillingSink:
    """Order-preserving overflow buffer in front of any sink.

    VERDICT r2 weak #7: a non-blocking `submit` on a full sink queue used to
    DROP the whole storage batch, leaving SQLite permanently behind the book
    with only a counter. This adapter converts that drop into a deferred
    write: rejected batches land in a bounded spill deque, and every later
    submit first re-offers the spill head (FIFO across the spill boundary,
    so SQLite never sees reordered writes). The checkpoint flush barrier
    drains the spill BLOCKING before flushing the inner sink — a checkpoint
    therefore always captures a storage state >= its snapshot, which is the
    invariant utils/checkpoint.py's restore reconciliation assumes.

    Only a spill overflow (inner sink stalled for >max_spill batches) still
    drops, and that is counted separately as a true loss
    (`storage_batches_lost`).
    """

    def __init__(self, inner, metrics=None, max_spill: int = 4096):
        import collections

        self._inner = inner
        self._metrics = metrics
        self._max_spill = max_spill
        self._spill: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self.spilled = 0   # batches that took the spill detour (recovered)
        self.lost = 0      # batches truly dropped (spill overflow)

    def _offer_spill_locked(self) -> bool:
        """Re-offer spilled batches to the inner sink; True when drained."""
        while self._spill:
            orders, updates, fills = self._spill[0]
            if not self._inner.submit(
                orders=orders, updates=updates, fills=fills, block=False
            ):
                return False
            self._spill.popleft()
        return True

    def submit(self, orders=None, updates=None, fills=None, block=True) -> bool:
        item = (orders or [], updates or [], fills or [])
        if not any(item):
            return True
        with self._lock:
            # FIFO: while a spill exists, new batches must queue behind it.
            if self._offer_spill_locked():
                if self._inner.submit(
                    orders=item[0], updates=item[1], fills=item[2], block=block
                ):
                    return True
            if len(self._spill) >= self._max_spill:
                self.lost += 1
                if self._metrics is not None:
                    self._metrics.inc("storage_batches_lost")
                return False
            self._spill.append(item)
            self.spilled += 1
            if self._metrics is not None:
                self._metrics.inc("storage_batches_spilled")
            return True

    def submit_packed(self, buf: bytes, block: bool = True) -> bool:
        """Packed fast path (native lane dispatches): forwarded straight to
        a packed-capable inner sink while no spill is queued; otherwise
        unpacked onto the spill so writes stay FIFO across the spill
        boundary. The whole offer-or-spill decision holds ONE lock
        acquisition — dropping it between the failed direct attempt and
        the fallback would let a concurrent submit() overtake this batch."""
        from matching_engine_tpu.native import unpack_store_buf

        if not hasattr(self._inner, "submit_packed"):
            orders, updates, fills = unpack_store_buf(buf)
            return self.submit(orders=orders, updates=updates, fills=fills,
                               block=block)
        with self._lock:
            if self._offer_spill_locked():
                if self._inner.submit_packed(buf, block=block):
                    return True
            if len(self._spill) >= self._max_spill:
                self.lost += 1
                if self._metrics is not None:
                    self._metrics.inc("storage_batches_lost")
                return False
            self._spill.append(unpack_store_buf(buf))
            self.spilled += 1
            if self._metrics is not None:
                self._metrics.inc("storage_batches_spilled")
            return True

    def flush(self) -> None:
        """Barrier: drains the spill (blocking) then the inner sink."""
        with self._lock:
            while self._spill:
                orders, updates, fills = self._spill.popleft()
                self._inner.submit(
                    orders=orders, updates=updates, fills=fills, block=True
                )
        self._inner.flush()

    def close(self) -> None:
        self.flush()
        self._inner.close()

    def stats(self) -> dict:
        inner = self._inner.stats() if hasattr(self._inner, "stats") else {}
        inner.update({"spilled": self.spilled, "lost": self.lost})
        return inner

    @property
    def dropped(self) -> int:
        return self.lost


class AsyncStorageSink:
    def __init__(self, storage: Storage, max_queue: int = 4096,
                 metrics=None, on_commit=None):
        self._storage = storage
        self._metrics = metrics  # stage_sink_commit_us + sink_queue_depth
        # Commit notification (--audit): fired after each batch's WAL txn
        # lands, ON THIS SINK THREAD — the InvariantAuditor runs its
        # store<->feed probes here, where rows are freshest and the
        # probe's SQLite read can never sit on a dispatch path.
        self._on_commit = on_commit
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="storage-sink", daemon=True)
        self.dropped = 0  # batches dropped on a full queue (backpressure signal)
        # `dropped += 1` is a read-modify-write: K serving lanes share one
        # sink and can hit queue.Full together, so the count takes a lock
        # (cold path — it only runs when the queue is already full;
        # lockset analyzer finding).
        self._drop_lock = threading.Lock()
        self._thread.start()

    def submit(
        self,
        orders: list[tuple] | None = None,
        updates: list[tuple] | None = None,
        fills: list[FillRow] | None = None,
        block: bool = True,
    ) -> bool:
        """Enqueue one dispatch's worth of writes. With block=False, a full
        queue drops the batch and counts it (callers that prefer losing log
        tail over stalling the match loop)."""
        item = (orders or [], updates or [], fills or [])
        if not any(item):
            return True
        try:
            self._q.put(item, block=block, timeout=None if block else 0)
            return True
        except queue.Full:
            with self._drop_lock:
                self.dropped += 1
            return False

    def flush(self) -> None:
        """Barrier: returns once everything enqueued so far is in SQLite."""
        done = threading.Event()
        self._q.put(("FLUSH", done))
        done.wait()

    def close(self) -> None:
        self.flush()
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=10)

    def _commit(self, orders, updates, fills) -> None:
        """One WAL transaction — the stage ledger's sink-commit figure
        (time actually spent in SQLite per batch, off the match path)."""
        from matching_engine_tpu.utils.obs import STAGE_SINK_COMMIT

        t0 = time.perf_counter()
        self._storage.apply_batch(orders, updates, fills)
        if self._on_commit is not None:
            try:
                self._on_commit()
            except Exception as e:  # noqa: BLE001 — surveillance must
                # never take the durable writer down with it.
                print(f"[sink] on_commit hook failed: "
                      f"{type(e).__name__}: {e}")
        if self._metrics is not None:
            t1 = time.perf_counter()
            self._metrics.observe(STAGE_SINK_COMMIT, (t1 - t0) * 1e6)
            self._metrics.set_gauge("sink_queue_depth", self._q.qsize())
            tracer = getattr(self._metrics, "tracer", None)
            if tracer is not None:
                # The seventh pipeline stage in the --trace-dir file: the
                # sink runs async to dispatches, so its commits trace on
                # their own thread track rather than nested per dispatch.
                tracer.emit_span("sink_commit", t0, t1, thread_label="sink")

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] == "FLUSH":
                item[1].set()
                continue
            orders, updates, fills = item
            # Coalesce whatever else is already queued into the same txn.
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._commit(orders, updates, fills)
                    return
                if isinstance(nxt, tuple) and len(nxt) == 2 and nxt[0] == "FLUSH":
                    self._commit(orders, updates, fills)
                    orders, updates, fills = [], [], []
                    nxt[1].set()
                    continue
                orders.extend(nxt[0])
                updates.extend(nxt[1])
                fills.extend(nxt[2])
            if orders or updates or fills:
                self._commit(orders, updates, fills)
