"""Asynchronous storage sink: the durable tail of the TPU fill stream.

The reference's single biggest structural flaw is that its only hot path is a
synchronous SQLite INSERT inside the RPC handler under a global mutex
(SURVEY.md §3.2). Here persistence is decoupled: the engine runner emits
(order-insert, status-update, fill) events per dispatch onto a queue; one
background thread drains the queue and writes each dispatch as a single WAL
transaction (`Storage.apply_batch`). The match path never blocks on disk.

Durability model: same as the reference (WAL + synchronous=NORMAL) but
batched — on crash, the tail of the fill stream since the last drained batch
is lost from SQLite while the device book retains it; recovery reconciles
from the book checkpoint (utils/checkpoint.py). `flush()` gives callers a
barrier when they need read-your-writes (tests, shutdown drain).
"""

from __future__ import annotations

import queue
import threading

from matching_engine_tpu.storage.storage import FillRow, Storage


class AsyncStorageSink:
    def __init__(self, storage: Storage, max_queue: int = 4096):
        self._storage = storage
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="storage-sink", daemon=True)
        self.dropped = 0  # batches dropped on a full queue (backpressure signal)
        self._thread.start()

    def submit(
        self,
        orders: list[tuple] | None = None,
        updates: list[tuple] | None = None,
        fills: list[FillRow] | None = None,
        block: bool = True,
    ) -> bool:
        """Enqueue one dispatch's worth of writes. With block=False, a full
        queue drops the batch and counts it (callers that prefer losing log
        tail over stalling the match loop)."""
        item = (orders or [], updates or [], fills or [])
        if not any(item):
            return True
        try:
            self._q.put(item, block=block, timeout=None if block else 0)
            return True
        except queue.Full:
            self.dropped += 1
            return False

    def flush(self) -> None:
        """Barrier: returns once everything enqueued so far is in SQLite."""
        done = threading.Event()
        self._q.put(("FLUSH", done))
        done.wait()

    def close(self) -> None:
        self.flush()
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=10)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] == "FLUSH":
                item[1].set()
                continue
            orders, updates, fills = item
            # Coalesce whatever else is already queued into the same txn.
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._storage.apply_batch(orders, updates, fills)
                    return
                if isinstance(nxt, tuple) and len(nxt) == 2 and nxt[0] == "FLUSH":
                    self._storage.apply_batch(orders, updates, fills)
                    orders, updates, fills = [], [], []
                    nxt[1].set()
                    continue
                orders.extend(nxt[0])
                updates.extend(nxt[1])
                fills.extend(nxt[2])
            if orders or updates or fills:
                self._storage.apply_batch(orders, updates, fills)
