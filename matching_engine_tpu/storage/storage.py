"""Durable SQLite store for orders and fills.

Mirrors the reference storage layer's contract (include/storage/storage.hpp,
src/storage/storage.cpp): WAL journal, synchronous=NORMAL, foreign keys, 5s
busy timeout, an `orders` table carrying the full status lifecycle plus
`remaining_quantity`, a `fills` table FK'd to orders, the same indexes, a
never-throw bool-returning method surface, and order-id sequence recovery
(MAX over `OID-<n>`).

The reference's dormant-code bugs are fixed, not inherited (SURVEY.md §2.9):
(a) best_bid/best_ask filter on side=1/2 (the stored encoding), not 0/1;
(b) add_fill binds every placeholder;
(c) insert_new_order stores the order's actual type, and MARKET orders store
    a NULL price (the column is nullable for exactly this reason).

Unlike the reference — where a synchronous insert under the service's global
mutex IS the engine hot path (SURVEY.md §3.2) — this store sits behind
AsyncStorageSink off the match path; the device never waits on SQLite.
"""

from __future__ import annotations

import dataclasses
import os
import sqlite3
import threading
import time

# proto OrderUpdate.Status values (side.py pins the enum layout).
STATUS_NEW = 0
STATUS_PARTIALLY_FILLED = 1
STATUS_FILLED = 2
STATUS_CANCELED = 3
STATUS_REJECTED = 4

_SCHEMA = """
CREATE TABLE IF NOT EXISTS orders (
    order_id            TEXT PRIMARY KEY,
    client_id           TEXT NOT NULL,
    symbol              TEXT NOT NULL,
    side                INTEGER NOT NULL CHECK (side IN (1, 2)),
    order_type          INTEGER NOT NULL CHECK (order_type IN (0, 1)),
    price               INTEGER,            -- Q4; NULL for MARKET orders
    quantity            INTEGER NOT NULL CHECK (quantity > 0),
    remaining_quantity  INTEGER NOT NULL CHECK (remaining_quantity >= 0),
    status              INTEGER NOT NULL CHECK (status BETWEEN 0 AND 4),
    created_ts          INTEGER NOT NULL,
    updated_ts          INTEGER NOT NULL,
    -- Time-in-force (wire TimeInForce: GTC=0/IOC=1/FOK=2). order_type keeps
    -- the reference's 0/1 domain; IOC/FOK rows never rest so recovery's
    -- resting-order replay needs no tif awareness.
    tif                 INTEGER NOT NULL DEFAULT 0 CHECK (tif IN (0, 1, 2))
);
CREATE INDEX IF NOT EXISTS idx_orders_symbol_status ON orders (symbol, status);
CREATE INDEX IF NOT EXISTS idx_orders_client ON orders (client_id);
CREATE TABLE IF NOT EXISTS fills (
    fill_id           INTEGER PRIMARY KEY AUTOINCREMENT,
    order_id          TEXT NOT NULL REFERENCES orders (order_id),
    counter_order_id  TEXT NOT NULL,
    price             INTEGER NOT NULL,   -- Q4 execution (maker) price
    quantity          INTEGER NOT NULL CHECK (quantity > 0),
    ts                INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_fills_order ON fills (order_id);
-- Durability-gap ledger: explicit, quantified acknowledgements of data the
-- durable log is known to be missing (fill records lost to kernel
-- max_fills overflow, zombie rows closed after a spill overflow). The
-- audit (scripts/audit.py) uses these to keep EXACT per-order arithmetic
-- across an acknowledged loss; unexplained mismatches stay violations.
CREATE TABLE IF NOT EXISTS server_meta (
    key   TEXT PRIMARY KEY,
    value TEXT NOT NULL
);

CREATE TABLE IF NOT EXISTS recon (
    recon_id   INTEGER PRIMARY KEY AUTOINCREMENT,
    order_id   TEXT NOT NULL,
    kind       TEXT NOT NULL,
    lost_quantity INTEGER NOT NULL,
    ts         INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_recon_order ON recon (order_id);
-- Self-trade-prevention identity registry: every client id's assigned
-- int32 owner id, persisted at first sight so the assignment is stable
-- across restarts (collision-free by the UNIQUE constraint — a crc32
-- hash collision gets a probed, remapped id; ADVICE r3). The device book
-- lanes and checkpoints carry these ints.
CREATE TABLE IF NOT EXISTS owner_ids (
    client_id TEXT PRIMARY KEY,
    owner     INTEGER NOT NULL UNIQUE CHECK (owner > 0)
);
"""


@dataclasses.dataclass(frozen=True)
class FillRow:
    order_id: str
    counter_order_id: str
    price_q4: int
    quantity: int
    ts: int = 0


def _now_us() -> int:
    return time.time_ns() // 1_000


class Storage:
    """Thread-safe (single connection + lock) durable store.

    Write methods catch everything and return bool — a storage failure must
    degrade to an order reject upstream, never a crash (reference
    storage.hpp:22 contract).
    """

    def __init__(self, db_path: str):
        self.db_path = db_path
        self._lock = threading.Lock()
        self._conn = None
        try:
            d = os.path.dirname(db_path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._conn = sqlite3.connect(
                db_path, timeout=5.0, check_same_thread=False, isolation_level=None
            )
        except Exception as e:  # noqa: BLE001 — never-throw surface; init()
            # reports False and the server exits with the storage code (1),
            # mirroring the reference's ctor-throw -> exit-1 path (main.cpp:63-69).
            print(f"[storage] open failed: {e}")

    def get_meta(self, key: str) -> str | None:
        """server_meta lookup (e.g. the persisted auction_mode). Never
        throws (the storage contract)."""
        if self._conn is None:
            return None
        try:
            with self._lock:
                row = self._conn.execute(
                    "SELECT value FROM server_meta WHERE key = ?", (key,)
                ).fetchone()
            return row[0] if row else None
        except Exception as e:  # noqa: BLE001
            print(f"[storage] get_meta failed: {e}")
            return None

    def load_owner_ids(self) -> list[tuple[str, int]] | None:
        """All persisted (client_id, owner) STP assignments. Never throws;
        a read FAILURE returns None (distinct from an empty registry) so
        the caller can warn that identities will re-derive."""
        if self._conn is None:
            return None
        try:
            with self._lock:
                rows = self._conn.execute(
                    "SELECT client_id, owner FROM owner_ids").fetchall()
            return [(r[0], int(r[1])) for r in rows]
        except Exception as e:  # noqa: BLE001
            print(f"[storage] load_owner_ids failed: {e}")
            return None

    def insert_owner_ids(self, rows: list[tuple[str, int]]) -> bool:
        """Persist first-sight STP assignments (one txn). OR IGNORE makes
        a replayed assignment after crash-and-restore a no-op, but each
        row is then READ BACK: an ignored insert that left a DIFFERENT
        owner for the client (or the owner claimed by another client —
        UNIQUE(owner)) is in-memory/durable divergence, warned loudly.
        Returns True when every row landed or already matched (divergence
        warns but returns True — a retry cannot heal it); False only on a
        write failure worth retrying."""
        if self._conn is None or not rows:
            return self._conn is not None
        conflicts = []
        try:
            with self._lock:
                self._conn.execute("BEGIN")
                for client_id, owner in rows:
                    self._conn.execute(
                        "INSERT OR IGNORE INTO owner_ids(client_id, owner) "
                        "VALUES(?, ?)", (client_id, owner))
                    got = self._conn.execute(
                        "SELECT owner FROM owner_ids WHERE client_id = ?",
                        (client_id,)).fetchone()
                    if got is None or int(got[0]) != owner:
                        conflicts.append(
                            (client_id, owner,
                             None if got is None else int(got[0])))
                self._conn.commit()
        except Exception as e:  # noqa: BLE001
            try:
                self._conn.rollback()
            except Exception:  # noqa: BLE001
                pass
            print(f"[storage] insert_owner_ids failed: {e}")
            return False
        for client_id, owner, durable in conflicts:
            print(f"[storage] WARNING: owner_ids divergence for "
                  f"{client_id!r}: in-memory {owner} vs durable {durable} "
                  f"— restart will use the durable id")
        return True

    def set_meta(self, key: str, value: str) -> bool:
        if self._conn is None:
            return False
        try:
            with self._lock:
                self._conn.execute(
                    "INSERT INTO server_meta(key, value) VALUES(?, ?) "
                    "ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                    (key, value),
                )
                self._conn.commit()
            return True
        except Exception as e:  # noqa: BLE001
            print(f"[storage] set_meta failed: {e}")
            return False

    def init(self) -> bool:
        if self._conn is None:
            return False
        try:
            with self._lock:
                cur = self._conn
                cur.execute("PRAGMA journal_mode=WAL")
                cur.execute("PRAGMA synchronous=NORMAL")
                cur.execute("PRAGMA foreign_keys=ON")
                cur.executescript(_SCHEMA)
                # Migration: a database created before the tif column
                # existed keeps its original orders table (CREATE TABLE IF
                # NOT EXISTS is a no-op there) — add the column in place.
                cols = {r[1] for r in cur.execute(
                    "PRAGMA table_info(orders)").fetchall()}
                if "tif" not in cols:
                    cur.execute(
                        "ALTER TABLE orders ADD COLUMN tif INTEGER NOT NULL "
                        "DEFAULT 0 CHECK (tif IN (0, 1, 2))")
            return True
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] init failed: {e}")
            return False

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()

    # -- writes ------------------------------------------------------------

    def insert_new_order(
        self,
        order_id: str,
        client_id: str,
        symbol: str,
        side: int,
        order_type: int,
        price_q4: int | None,
        quantity: int,
        status: int = STATUS_NEW,
        remaining: int | None = None,
        tif: int = 0,
    ) -> bool:
        """Insert an accepted order. MARKET orders pass price_q4=None."""
        ts = _now_us()
        rem = quantity if remaining is None else remaining
        try:
            with self._lock:
                self._conn.execute(
                    "INSERT INTO orders (order_id, client_id, symbol, side, "
                    "order_type, price, quantity, remaining_quantity, status, "
                    "created_ts, updated_ts, tif) VALUES "
                    "(?,?,?,?,?,?,?,?,?,?,?,?)",
                    (order_id, client_id, symbol, side, order_type, price_q4,
                     quantity, rem, status, ts, ts, tif),
                )
            return True
        except Exception as e:  # noqa: BLE001
            print(f"[storage] insert_new_order({order_id}) failed: {e}")
            return False

    def update_order_status(self, order_id: str, status: int, remaining: int) -> bool:
        try:
            with self._lock:
                self._conn.execute(
                    "UPDATE orders SET status = ?, remaining_quantity = ?, "
                    "updated_ts = ? WHERE order_id = ?",
                    (status, remaining, _now_us(), order_id),
                )
            return True
        except Exception as e:  # noqa: BLE001
            print(f"[storage] update_order_status({order_id}) failed: {e}")
            return False

    def add_fill(self, fill: FillRow) -> bool:
        try:
            with self._lock:
                self._conn.execute(
                    "INSERT INTO fills (order_id, counter_order_id, price, "
                    "quantity, ts) VALUES (?,?,?,?,?)",
                    (fill.order_id, fill.counter_order_id, fill.price_q4,
                     fill.quantity, fill.ts or _now_us()),
                )
            return True
        except Exception as e:  # noqa: BLE001
            print(f"[storage] add_fill({fill.order_id}) failed: {e}")
            return False

    def apply_batch(self, orders: list[tuple], updates: list[tuple], fills: list[FillRow]) -> bool:
        """One transaction for a whole engine dispatch (the async sink's unit).

        orders: (order_id, client_id, symbol, side, collapsed_otype,
        price|None, quantity, remaining, status) tuples — the otype is the
        engine's collapsed (order_type, tif) lane code, split here so the
        order_type column keeps the reference's 0/1 domain; updates:
        (order_id, status, remaining) tuples; fills: FillRows.
        """
        from matching_engine_tpu.proto import split_otype

        ts = _now_us()
        try:
            # Inside the try: a malformed tuple or unknown collapsed code
            # must honor this module's never-throw bool contract (the async
            # sink thread calls with no guard of its own).
            order_rows = []
            for (oid, cid, sym, side, code, price, qty, rem, status) in orders:
                otype, tif = split_otype(code)
                order_rows.append((oid, cid, sym, side, otype, price, qty,
                                   rem, status, ts, ts, tif))
            with self._lock:
                self._conn.execute("BEGIN")
                try:
                    self._conn.executemany(
                        "INSERT INTO orders (order_id, client_id, symbol, side, "
                        "order_type, price, quantity, remaining_quantity, status, "
                        "created_ts, updated_ts, tif) VALUES "
                        "(?,?,?,?,?,?,?,?,?,?,?,?)",
                        order_rows,
                    )
                    # 3-tuples update status/remaining (fills, cancels);
                    # 4-tuples are priority-preserving amends and move
                    # quantity WITH remaining so filled == quantity -
                    # remaining stays exact. ONE order-preserving pass —
                    # an amend and a later fill of the same order can
                    # share a batch, and the later event must win (the
                    # native sink applies in stream order too).
                    for u in updates:
                        if len(u) == 3:
                            self._conn.execute(
                                "UPDATE orders SET status = ?, "
                                "remaining_quantity = ?, updated_ts = ? "
                                "WHERE order_id = ?",
                                (u[1], u[2], ts, u[0]),
                            )
                        else:
                            self._conn.execute(
                                "UPDATE orders SET status = ?, "
                                "remaining_quantity = ?, quantity = ?, "
                                "updated_ts = ? WHERE order_id = ?",
                                (u[1], u[2], u[3], ts, u[0]),
                            )
                    self._conn.executemany(
                        "INSERT INTO fills (order_id, counter_order_id, price, "
                        "quantity, ts) VALUES (?,?,?,?,?)",
                        [(f.order_id, f.counter_order_id, f.price_q4, f.quantity,
                          f.ts or ts) for f in fills],
                    )
                    self._conn.execute("COMMIT")
                except Exception:
                    self._conn.execute("ROLLBACK")
                    raise
            return True
        except Exception as e:  # noqa: BLE001
            print(f"[storage] apply_batch failed: {e}")
            return False

    # -- reads -------------------------------------------------------------

    def apply_repairs(self, repairs: list[tuple],
                      recon: list[tuple[str, str, int]]) -> bool:
        """One transaction applying checkpoint-time durability repairs.

        repairs: (order_id, remaining, status, lost_qty) — adopt the device
        book's remaining/status for orders whose fill records were lost.
        recon:   (order_id, kind, lost_qty) ledger rows (see _SCHEMA).
        """
        if not repairs and not recon:
            return True
        ts = _now_us()
        try:
            with self._lock, self._conn:
                for (order_id, remaining, status, _lost) in repairs:
                    self._conn.execute(
                        "UPDATE orders SET status = ?, remaining_quantity = ?, "
                        "updated_ts = ? WHERE order_id = ?",
                        (status, remaining, ts, order_id),
                    )
                self._conn.executemany(
                    "INSERT INTO recon (order_id, kind, lost_quantity, ts) "
                    "VALUES (?,?,?,?)",
                    [(oid, kind, lost, ts) for (oid, kind, lost) in recon],
                )
            return True
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] apply_repairs failed: {e}")
            return False

    def get_order(self, order_id: str):
        try:
            with self._lock:
                row = self._conn.execute(
                    "SELECT order_id, client_id, symbol, side, order_type, price, "
                    "quantity, remaining_quantity, status, created_ts, "
                    "updated_ts, tif FROM orders WHERE order_id = ?",
                    (order_id,),
                ).fetchone()
            return row
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] get_order failed: {e}")
            return None

    def open_orders(self, symbol: str | None = None):
        """Orders with live book presence (NEW / PARTIALLY_FILLED) — the
        recovery set for book reconstruction after restart."""
        q = (
            "SELECT order_id, client_id, symbol, side, order_type, price, "
            "quantity, remaining_quantity, status FROM orders "
            "WHERE status IN (?, ?) AND order_type = 0"
        )
        args: list = [STATUS_NEW, STATUS_PARTIALLY_FILLED]
        if symbol is not None:
            q += " AND symbol = ?"
            args.append(symbol)
        # Numeric tiebreak on the OID sequence: ids are TEXT, and coalesced
        # sink transactions stamp one created_ts for a whole dispatch, so a
        # lexicographic tiebreak would replay OID-10 before OID-9 and invert
        # time priority after restart.
        q += " ORDER BY created_ts, CAST(SUBSTR(order_id, 5) AS INTEGER)"
        try:
            with self._lock:
                return self._conn.execute(q, args).fetchall()
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] open_orders failed: {e}")
            return []

    def best_bid(self, symbol: str):
        """(price_q4, total remaining) of the best bid, or None.

        side=1 (BUY) — the stored encoding, fixing the reference's
        side=0 filter bug (storage.cpp:218)."""
        try:
            with self._lock:
                row = self._conn.execute(
                    "SELECT price, SUM(remaining_quantity) FROM orders "
                    "WHERE symbol = ? AND side = 1 AND status IN (0, 1) "
                    "AND price IS NOT NULL GROUP BY price "
                    "ORDER BY price DESC LIMIT 1",
                    (symbol,),
                ).fetchone()
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] best_bid failed: {e}")
            return None
        return None if row is None or row[0] is None else (row[0], row[1])

    def best_ask(self, symbol: str):
        try:
            with self._lock:
                row = self._conn.execute(
                    "SELECT price, SUM(remaining_quantity) FROM orders "
                    "WHERE symbol = ? AND side = 2 AND status IN (0, 1) "
                    "AND price IS NOT NULL GROUP BY price "
                    "ORDER BY price ASC LIMIT 1",
                    (symbol,),
                ).fetchone()
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] best_ask failed: {e}")
            return None
        return None if row is None or row[0] is None else (row[0], row[1])

    def load_next_oid_seq(self) -> int:
        """Resume the OID-<n> sequence: 1 + MAX(n) over stored ids
        (reference storage.cpp:254-268)."""
        try:
            with self._lock:
                row = self._conn.execute(
                    "SELECT MAX(CAST(SUBSTR(order_id, 5) AS INTEGER)) "
                    "FROM orders WHERE order_id LIKE 'OID-%'"
                ).fetchone()
            return 1 if row is None or row[0] is None else int(row[0]) + 1
        except Exception as e:  # noqa: BLE001
            print(f"[storage] load_next_oid_seq failed: {e}")
            return 1

    def fills_for_order(self, order_id: str):
        try:
            with self._lock:
                return self._conn.execute(
                    "SELECT order_id, counter_order_id, price, quantity, ts "
                    "FROM fills WHERE order_id = ? ORDER BY fill_id",
                    (order_id,),
                ).fetchall()
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] fills_for_order failed: {e}")
            return []

    def count(self, table: str) -> int:
        assert table in ("orders", "fills")
        try:
            with self._lock:
                return self._conn.execute(f"SELECT COUNT(*) FROM {table}").fetchone()[0]
        except Exception as e:  # noqa: BLE001 — never-throw surface
            print(f"[storage] count failed: {e}")
            return 0
