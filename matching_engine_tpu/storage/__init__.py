from matching_engine_tpu.storage.storage import FillRow, Storage
from matching_engine_tpu.storage.async_sink import AsyncStorageSink

__all__ = ["FillRow", "Storage", "AsyncStorageSink"]
