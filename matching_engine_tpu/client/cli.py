"""CLI client, argv-compatible with the reference's one-shot submitter.

Reference contract (src/client/client.cpp:10-29,49-56): positional args
`<addr> <client_id> <symbol> <BUY|SELL> <LIMIT|MARKET> <price> <scale>
<quantity>`, prints `[client] accepted order_id=...` on success or the
rejection reason, exit codes: 1 usage, 2 RPC failure, 3 rejected.

Extended subcommands (new surface): `book`, `cancel`, `watch-md`,
`watch-orders`, `metrics`, `auction` — invoked as
`python -m matching_engine_tpu.client.cli <sub> ...`; the bare 8-arg form
stays the submit path.
"""

from __future__ import annotations

import sys

import grpc

from matching_engine_tpu.proto import pb2
from matching_engine_tpu.proto.rpc import MatchingEngineStub

USAGE = (
    "usage: client <addr> <client_id> <symbol> <BUY|SELL> "
    "<LIMIT|MARKET[:IOC|:FOK]> <price> <scale> <quantity>\n"
    "   or: client book <addr> <symbol>\n"
    "   or: client cancel <addr> <client_id> <order_id>\n"
    "   or: client amend <addr> <client_id> <order_id> <new_qty>\n"
    "   or: client watch-md <addr> <symbol>\n"
    "   or: client watch-orders <addr> <client_id>\n"
    "   or: client subscribe <addr> md <symbol> | orders <client_id>\n"
    "                 [--from-seq N] [--epoch N] [--conflate]\n"
    "                 [--no-gap-fill] [--max-events N]\n"
    "                 [--idle-exit SECS] [--summary-json FILE] [--quiet]\n"
    "   or: client submit-batch <addr> <opfile> [--batch-size N]\n"
    "                 [--summary-json FILE] [--quiet]\n"
    "   or: client submit-stream <addr> <opfile> [--chunk N]\n"
    "                 [--summary-json FILE] [--quiet]\n"
    "   or: client submit-shm <segment> <opfile> [--chunk N]\n"
    "                 [--timeout SECS] [--offset N] [--count N]\n"
    "                 [--summary-json FILE] [--quiet]\n"
    "   or: client audit <addr> [--from-seq N] [--epoch N]\n"
    "                 [--no-gap-fill] [--max-events N] [--idle-exit SECS]\n"
    "                 [--capture FILE] [--summary-json FILE] [--quiet]\n"
    "   or: client metrics <addr>\n"
    "   or: client auction <addr> [symbol | --open]\n"
    "   or: client simulate --scenario NAME --out FILE [--steps N]\n"
    "                 [--seed N] [--symbols N] [--serve-shards K]\n"
    "                 [--summary-json FILE]\n"
    "   or: client gym-rollout --venues V --scenario NAME[,NAME...]\n"
    "                 [--steps N] [--seed N] [--symbols N] [--kernel K]\n"
    "                 [--freeze VENUE --out FILE] [--summary-json FILE]\n"
    "   or: client promote <addr>"
)


def _stub(addr: str) -> MatchingEngineStub:
    return MatchingEngineStub(grpc.insecure_channel(addr))


def _submit(argv: list[str]) -> int:
    addr, client_id, symbol, side_s, type_s, price_s, scale_s, qty_s = argv
    side = {"BUY": pb2.BUY, "SELL": pb2.SELL}.get(side_s.upper())
    # Optional time-in-force suffix: LIMIT:IOC / LIMIT:FOK / MARKET:FOK
    # (MARKET:IOC accepted; MARKET is inherently immediate-or-cancel).
    type_u, _, tif_s = type_s.upper().partition(":")
    otype = {"LIMIT": pb2.LIMIT, "MARKET": pb2.MARKET}.get(type_u)
    tif = {"": pb2.TIF_GTC, "GTC": pb2.TIF_GTC, "IOC": pb2.TIF_IOC,
           "FOK": pb2.TIF_FOK}.get(tif_s)
    if side is None or otype is None or tif is None:
        print(USAGE, file=sys.stderr)
        return 1
    req = pb2.OrderRequest(
        client_id=client_id, symbol=symbol, order_type=otype, side=side,
        price=int(price_s), scale=int(scale_s), quantity=int(qty_s),
        tif=tif,
    )
    try:
        resp = _stub(addr).SubmitOrder(req, timeout=30)
    except grpc.RpcError as e:
        print(f"[client] rpc failed: {e.code().name}: {e.details()}", file=sys.stderr)
        return 2
    if resp.success:
        print(f"[client] accepted order_id={resp.order_id}")
        return 0
    print(f"[client] rejected: {resp.error_message}")
    return 3


def _book(addr: str, symbol: str) -> int:
    try:
        resp = _stub(addr).GetOrderBook(pb2.OrderBookRequest(symbol=symbol), timeout=10)
    except grpc.RpcError as e:
        print(f"[client] rpc failed: {e.code().name}", file=sys.stderr)
        return 2
    print(f"[client] book {symbol}: {len(resp.bids)} bids / {len(resp.asks)} asks")
    for label, side in (("bid", resp.bids), ("ask", resp.asks)):
        for o in side:
            print(f"  {label} {o.price}@Q{o.scale} x{o.quantity} {o.order_id} ({o.client_id})")
    if resp.bid_levels or resp.ask_levels:
        print("  L2:")
        for label, side in (("bid", resp.bid_levels),
                            ("ask", resp.ask_levels)):
            for lv in side:
                print(f"    {label} {lv.price}@Q4 x{lv.quantity} "
                      f"({lv.order_count} order(s))")
    return 0


def _auction(addr: str, symbol: str) -> int:
    if symbol == "--open":
        # (Re)open the venue-wide call period without uncrossing — the
        # workload replay driver's phase hook (sim/scenarios.py).
        resp = _stub(addr).RunAuction(
            pb2.AuctionRequest(open_call=True), timeout=60)
        if not resp.success:
            print(f"[client] auction open rejected: {resp.error_message}")
            return 3
        print("[client] auction call period OPEN (submits rest until the "
              "next all-symbols auction)")
        return 0
    resp = _stub(addr).RunAuction(pb2.AuctionRequest(symbol=symbol),
                                  timeout=60)
    if not resp.success:
        print(f"[client] auction rejected: {resp.error_message}")
        return 3
    if symbol:
        if resp.symbols_crossed == 0:
            print(f"[client] auction {symbol}: did not cross")
        else:
            print(f"[client] auction {symbol}: cleared "
                  f"{resp.clearing_price}@Q4 x{resp.executed_quantity}")
    else:
        print(f"[client] auction: {resp.symbols_crossed} symbol(s) crossed, "
              f"{resp.executed_quantity} executed")
    if resp.error_message:  # partial-abort warning (success=true channel)
        print(f"[client] warning: {resp.error_message}")
    return 0


def _cancel(addr: str, client_id: str, order_id: str) -> int:
    try:
        resp = _stub(addr).CancelOrder(
            pb2.CancelRequest(client_id=client_id, order_id=order_id), timeout=10
        )
    except grpc.RpcError as e:
        print(f"[client] rpc failed: {e.code().name}", file=sys.stderr)
        return 2
    if resp.success:
        print(f"[client] canceled order_id={resp.order_id}")
        return 0
    print(f"[client] cancel rejected: {resp.error_message}")
    return 3


def _amend(addr: str, client_id: str, order_id: str, new_qty: str) -> int:
    try:
        resp = _stub(addr).AmendOrder(
            pb2.AmendRequest(client_id=client_id, order_id=order_id,
                             new_quantity=int(new_qty)), timeout=10
        )
    except grpc.RpcError as e:
        print(f"[client] rpc failed: {e.code().name}", file=sys.stderr)
        return 2
    if resp.success:
        print(f"[client] amended order_id={resp.order_id} "
              f"remaining={resp.remaining_quantity}")
        return 0
    print(f"[client] amend rejected: {resp.error_message}")
    return 3


def _watch_md(addr: str, symbol: str) -> int:
    # flush per event: watchers are typically piped/redirected, and buffered
    # stream output looks like silence.
    for u in _stub(addr).StreamMarketData(pb2.MarketDataRequest(symbol=symbol)):
        print(f"[client] md {u.symbol} bid={u.best_bid}x{u.bid_size} "
              f"ask={u.best_ask}x{u.ask_size} (Q{u.scale})", flush=True)
    return 0


def _watch_orders(addr: str, client_id: str) -> int:
    for u in _stub(addr).StreamOrderUpdates(pb2.OrderUpdatesRequest(client_id=client_id)):
        print(f"[client] update {u.order_id} {pb2.OrderUpdate.Status.Name(u.status)} "
              f"fill={u.fill_quantity}@{u.fill_price} remaining={u.remaining_quantity}",
              flush=True)
    return 0


def _subscribe(argv: list[str]) -> int:
    """Sequenced-feed subscriber (feed/client.py): prints events, detects
    sequence gaps LOUDLY on stderr, auto-gap-fills them from the server's
    retransmission store, and exits non-zero (4) on any unrecovered gap —
    the soak/CI feed-integrity assertion. `watch-md`/`watch-orders` stay
    the raw unsequenced taps."""
    import json
    import signal
    import threading
    import time

    from matching_engine_tpu.feed.client import SequencedSubscriber
    from matching_engine_tpu.feed.sequencer import CHANNEL_MD, CHANNEL_OU

    addr, kind, key = argv[0], argv[1], argv[2]
    channel = {"md": CHANNEL_MD, "orders": CHANNEL_OU}.get(kind)
    if channel is None:
        print(USAGE, file=sys.stderr)
        return 1
    from_seq, epoch, max_events, idle_exit = 0, 0, 0, 0.0
    conflate, gap_fill, quiet, summary_json = False, True, False, None
    it = iter(argv[3:])
    try:
        for a in it:
            if a == "--from-seq":
                from_seq = int(next(it))
            elif a == "--epoch":
                epoch = int(next(it))
            elif a == "--conflate":
                conflate = True
            elif a == "--no-gap-fill":
                gap_fill = False
            elif a == "--max-events":
                max_events = int(next(it))
            elif a == "--idle-exit":
                idle_exit = float(next(it))
            elif a == "--summary-json":
                summary_json = next(it)
            elif a == "--quiet":
                quiet = True
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except StopIteration:
        print(USAGE, file=sys.stderr)
        return 1

    def on_gap(start, end, filled, missing):
        print(f"[client] FEED GAP {channel}/{key}: seq {start + 1}.."
              f"{end - 1} missed upstream; {filled} gap-filled, "
              f"{missing} UNRECOVERED", file=sys.stderr, flush=True)

    def on_rebase(cursor, seq):
        print(f"[client] FEED EPOCH REBASE {channel}/{key}: server "
              f"restarted (cursor {cursor} -> live seq {seq}); the old "
              f"epoch's tail is unknowable", file=sys.stderr, flush=True)

    feed = SequencedSubscriber(
        _stub(addr), channel, key, from_seq=from_seq, conflate=conflate,
        gap_fill=gap_fill, on_gap=on_gap, on_rebase=on_rebase, epoch=epoch)
    last_event = [time.monotonic()]
    stop_reason: list[str] = []

    def _stop(why: str) -> None:
        if not stop_reason:
            stop_reason.append(why)
        feed.cancel()

    for s in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(s, lambda *_: _stop("signal"))
        except ValueError:
            pass  # not the main thread (tests drive main() directly)
    if idle_exit > 0:
        # Watchdog instead of an RPC deadline: an idle FEED is healthy,
        # an idle SUBSCRIBER PROCESS in a soak round is done — cancel
        # from the side so the stream itself carries no deadline.
        def watchdog():
            while not stop_reason:
                if time.monotonic() - last_event[0] > idle_exit:
                    _stop("idle")
                    return
                time.sleep(min(0.25, idle_exit / 4))

        threading.Thread(target=watchdog, daemon=True).start()

    rc = 0
    try:
        for e in feed:
            last_event[0] = time.monotonic()
            if not quiet:
                if channel == CHANNEL_MD:
                    print(f"[client] md #{e.seq} {e.symbol} "
                          f"bid={e.best_bid}x{e.bid_size} "
                          f"ask={e.best_ask}x{e.ask_size} (Q{e.scale})",
                          flush=True)
                else:
                    print(f"[client] update #{e.seq} {e.order_id} "
                          f"{pb2.OrderUpdate.Status.Name(e.status)} "
                          f"fill={e.fill_quantity}@{e.fill_price} "
                          f"remaining={e.remaining_quantity}", flush=True)
            if max_events and feed.events >= max_events:
                _stop("max-events")
                break
    except grpc.RpcError as err:
        print(f"[client] rpc failed: {err.code().name}: {err.details()}",
              file=sys.stderr)
        rc = 2
    summary = feed.summary()
    summary["stop_reason"] = stop_reason[0] if stop_reason else "stream-end"
    print(f"[client] feed summary: events={summary['events']} "
          f"last_seq={summary['last_seq']} gaps={summary['gaps_detected']} "
          f"filled={summary['gap_filled_events']} "
          f"unrecovered={summary['unrecovered_events']} "
          f"conflated_jumps={summary['conflated_jumps']} "
          f"rebases={summary['epoch_rebases']}",
          file=sys.stderr, flush=True)
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f)
    if feed.unrecovered_events:
        print(f"[client] FEED INTEGRITY FAILURE: "
              f"{feed.unrecovered_events} event(s) unrecoverable",
              file=sys.stderr, flush=True)
        return 4
    return rc


def _audit(argv: list[str]) -> int:
    """Drop-copy surveillance tap: subscribe to the sequenced audit
    channel, run the CLIENT-SIDE invariant checker over the lifecycle
    records (grouped per dispatch by trace_id), optionally capture them
    as JSON lines for scripts/audit.py --dropcopy, and exit 4 on any
    violation the checker (or the feed's gap accounting) can see —
    mirrors the `subscribe` verb's signal/summary contract."""
    import json
    import signal
    import threading
    import time

    from matching_engine_tpu.audit import InvariantAuditor
    from matching_engine_tpu.feed.client import SequencedSubscriber
    from matching_engine_tpu.feed.sequencer import CHANNEL_AUDIT

    addr = argv[0]
    from_seq, epoch, max_events, idle_exit = 0, 0, 0, 0.0
    gap_fill, quiet = True, False
    summary_json = capture = None
    it = iter(argv[1:])
    try:
        for a in it:
            if a == "--from-seq":
                from_seq = int(next(it))
            elif a == "--epoch":
                epoch = int(next(it))
            elif a == "--no-gap-fill":
                gap_fill = False
            elif a == "--max-events":
                max_events = int(next(it))
            elif a == "--idle-exit":
                idle_exit = float(next(it))
            elif a == "--summary-json":
                summary_json = next(it)
            elif a == "--capture":
                capture = next(it)
            elif a == "--quiet":
                quiet = True
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except StopIteration:
        print(USAGE, file=sys.stderr)
        return 1

    def on_gap(start, end, filled, missing):
        print(f"[client] AUDIT FEED GAP: seq {start + 1}..{end - 1} "
              f"missed upstream; {filled} gap-filled, {missing} "
              f"UNRECOVERED", file=sys.stderr, flush=True)

    def on_rebase(cursor, seq):
        print(f"[client] AUDIT FEED EPOCH REBASE: server restarted "
              f"(cursor {cursor} -> live seq {seq})", file=sys.stderr,
              flush=True)

    feed = SequencedSubscriber(
        _stub(addr), CHANNEL_AUDIT, from_seq=from_seq, gap_fill=gap_fill,
        on_gap=on_gap, on_rebase=on_rebase, epoch=epoch)
    # Client-side checker: non-strict (a tap may attach mid-stream and
    # see fills for orders born before it), shadow-everything, no store
    # access. Seq holes are the SUBSCRIBER's job (it gap-fills; its
    # unrecovered count feeds the exit code), so the checker's cursor is
    # seeded per event.
    checker = InvariantAuditor(sample=1, strict=False)
    last_event = [time.monotonic()]
    stop_reason: list[str] = []

    def _stop(why: str) -> None:
        if not stop_reason:
            stop_reason.append(why)
        feed.cancel()

    for s in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(s, lambda *_: _stop("signal"))
        except ValueError:
            pass  # not the main thread (tests drive main() directly)
    if idle_exit > 0:
        def watchdog():
            while not stop_reason:
                if time.monotonic() - last_event[0] > idle_exit:
                    _stop("idle")
                    return
                time.sleep(min(0.25, idle_exit / 4))

        threading.Thread(target=watchdog, daemon=True).start()

    cap_f = open(capture, "w") if capture else None
    _KINDS = {1: "order", 2: "update", 3: "fill"}

    def cap_line(e) -> dict:
        return {
            "kind": _KINDS.get(e.audit_kind, e.audit_kind),
            "seq": e.seq, "order_id": e.order_id,
            "counter_order_id": e.counter_order_id,
            "client_id": e.client_id, "symbol": e.symbol,
            "status": e.status, "remaining": e.remaining_quantity,
            "quantity": e.audit_quantity, "side": e.audit_side,
            "otype": e.audit_otype,
            "price": e.fill_price if e.audit_kind == 1 else 0,
            "fill_price": e.fill_price if e.audit_kind == 3 else 0,
            "fill_quantity": e.fill_quantity,
            "trace_id": e.trace_id, "shape": e.dispatch_shape,
            "waves": e.dispatch_waves, "ingress_ts_us": e.ingress_ts_us,
        }

    rc = 0
    batch: list = []
    batch_trace = [None]

    def flush_batch() -> None:
        if batch:
            checker.observe(batch)
            batch.clear()

    try:
        first = True
        for e in feed:
            last_event[0] = time.monotonic()
            if first and e.seq:
                checker.seed_seq(e.seq - 1)
                first = False
            # One observe() per dispatch: the balance invariants hold at
            # dispatch boundaries, and every record of a dispatch shares
            # its trace_id.
            if batch and e.trace_id != batch_trace[0]:
                flush_batch()
            batch_trace[0] = e.trace_id
            batch.append(e)
            if cap_f is not None:
                cap_f.write(json.dumps(cap_line(e)) + "\n")
            if not quiet:
                k = _KINDS.get(e.audit_kind, "?")
                print(f"[client] audit #{e.seq} {k} {e.order_id} "
                      f"st={e.status} rem={e.remaining_quantity} "
                      f"fill={e.fill_quantity}@{e.fill_price} "
                      f"ctr={e.counter_order_id} trace={e.trace_id}",
                      flush=True)
            if max_events and feed.events >= max_events:
                _stop("max-events")
                break
    except grpc.RpcError as err:
        print(f"[client] rpc failed: {err.code().name}: {err.details()}",
              file=sys.stderr)
        rc = 2
    tail_reason = stop_reason[0] if stop_reason else "stream-end"
    unchecked_tail = 0
    if rc == 0 and tail_reason in ("idle", "stream-end"):
        # The stream drained to a dispatch boundary (a dispatch's
        # records arrive in one burst): the tail group is complete.
        flush_batch()
    else:
        # Signal / --max-events / RPC error can stop ITERATION mid-
        # dispatch — between an order row and its fills. Balance-
        # checking that truncated group would report a healthy venue as
        # corrupt (spurious exit 4); it is unverifiable, not wrong.
        unchecked_tail = len(batch)
        batch.clear()
    if cap_f is not None:
        cap_f.close()
    snap = checker.snapshot()
    summary = feed.summary()
    summary["stop_reason"] = tail_reason
    summary["unchecked_tail_records"] = unchecked_tail
    summary["violations"] = snap["violations"]
    summary["violations_by_kind"] = snap["by_kind"]
    summary["tracked_orders"] = snap["tracked_orders"]
    print(f"[client] audit summary: events={summary['events']} "
          f"last_seq={summary['last_seq']} violations={snap['violations']} "
          f"by_kind={snap['by_kind']} gaps={summary['gaps_detected']} "
          f"unrecovered={summary['unrecovered_events']} "
          f"rebases={summary['epoch_rebases']}",
          file=sys.stderr, flush=True)
    for v in snap["recent"]:
        print(f"[client] AUDIT VIOLATION ({v['violation']}): {v['detail']}",
              file=sys.stderr, flush=True)
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f)
    if snap["violations"] or feed.unrecovered_events:
        print(f"[client] AUDIT INTEGRITY FAILURE: "
              f"{snap['violations']} violation(s), "
              f"{feed.unrecovered_events} unrecoverable event(s)",
              file=sys.stderr, flush=True)
        return 4
    return rc


def _submit_batch(argv: list[str]) -> int:
    """Replay a recorded op file through SubmitOrderBatch: the file is the
    flat binary op-record wire (domain/oprec.py — the SAME codec reader
    the bench replay uses), sliced into --batch-size requests. Per-op
    statuses come back positionally; the summary counts them. Exit 3 when
    nothing was accepted, 2 on RPC failure."""
    import json
    import time

    from matching_engine_tpu.domain import oprec

    addr, path = argv[0], argv[1]
    batch_size, summary_json, quiet = 512, None, False
    it = iter(argv[2:])
    try:
        for a in it:
            if a == "--batch-size":
                batch_size = int(next(it))
            elif a == "--summary-json":
                summary_json = next(it)
            elif a == "--quiet":
                quiet = True
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except StopIteration:
        print(USAGE, file=sys.stderr)
        return 1
    if batch_size < 1:
        print(USAGE, file=sys.stderr)
        return 1
    try:
        arr = oprec.read_opfile(path)
    except (OSError, oprec.OpRecError) as e:
        print(f"[client] cannot read op file: {e}", file=sys.stderr)
        return 1
    stub = _stub(addr)
    total = len(arr)
    accepted = rejected = batches = 0
    errors: dict[str, int] = {}
    t0 = time.perf_counter()
    for start in range(0, total, batch_size):
        payload = oprec.slice_payload(arr, start, batch_size)
        try:
            resp = stub.SubmitOrderBatch(
                pb2.OrderBatchRequest(ops=payload), timeout=60)
        except grpc.RpcError as e:
            print(f"[client] rpc failed: {e.code().name}: {e.details()}",
                  file=sys.stderr)
            return 2
        batches += 1
        if not resp.success:
            print(f"[client] batch rejected: {resp.error_message}",
                  file=sys.stderr)
            return 3
        for i, ok in enumerate(resp.ok):
            if ok:
                accepted += 1
            else:
                rejected += 1
                err = resp.error[i]
                errors[err] = errors.get(err, 0) + 1
                if not quiet:
                    print(f"[client] op {start + i} rejected: {err}")
    dt = time.perf_counter() - t0
    rate = accepted / dt if dt > 0 else 0.0
    summary = {"ops": total, "batches": batches, "batch_size": batch_size,
               "accepted": accepted, "rejected": rejected,
               "wall_s": round(dt, 3), "accepted_per_s": round(rate, 1),
               "reject_reasons": errors}
    print(f"[client] batch replay: {accepted}/{total} accepted in "
          f"{batches} batch(es), {dt:.3f}s ({rate:.0f} accepted/s)",
          file=sys.stderr, flush=True)
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f)
    return 0 if accepted > 0 or total == 0 else 3


def _submit_stream(argv: list[str]) -> int:
    """Replay a recorded op file through the client-streaming
    SubmitOrderStream RPC: the file slices into --chunk payloads sent as
    one stream; ONE positional response spans the whole stream. Exit 3
    when nothing was accepted, 2 on RPC failure."""
    import json
    import time

    from matching_engine_tpu.domain import oprec

    addr, path = argv[0], argv[1]
    chunk, summary_json, quiet = 64, None, False
    it = iter(argv[2:])
    try:
        for a in it:
            if a == "--chunk":
                chunk = int(next(it))
            elif a == "--summary-json":
                summary_json = next(it)
            elif a == "--quiet":
                quiet = True
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except StopIteration:
        print(USAGE, file=sys.stderr)
        return 1
    if chunk < 1:
        print(USAGE, file=sys.stderr)
        return 1
    try:
        arr = oprec.read_opfile(path)
    except (OSError, oprec.OpRecError) as e:
        print(f"[client] cannot read op file: {e}", file=sys.stderr)
        return 1
    stub = _stub(addr)
    total = len(arr)

    def chunks():
        for start in range(0, total, chunk):
            yield pb2.OrderBatchRequest(
                ops=oprec.slice_payload(arr, start, chunk))

    t0 = time.perf_counter()
    try:
        resp = stub.SubmitOrderStream(chunks(), timeout=300)
    except grpc.RpcError as e:
        print(f"[client] rpc failed: {e.code().name}: {e.details()}",
              file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0
    if not resp.success:
        print(f"[client] stream rejected: {resp.error_message}",
              file=sys.stderr)
        return 3
    accepted = sum(1 for ok in resp.ok if ok)
    rejected = len(resp.ok) - accepted
    errors: dict[str, int] = {}
    for i, ok in enumerate(resp.ok):
        if not ok:
            err = resp.error[i]
            errors[err] = errors.get(err, 0) + 1
            if not quiet:
                print(f"[client] op {i} rejected: {err}")
    rate = accepted / dt if dt > 0 else 0.0
    summary = {"ops": total, "chunk": chunk, "accepted": accepted,
               "rejected": rejected, "wall_s": round(dt, 3),
               "accepted_per_s": round(rate, 1), "reject_reasons": errors}
    print(f"[client] stream replay: {accepted}/{total} accepted, "
          f"{dt:.3f}s ({rate:.0f} accepted/s)", file=sys.stderr, flush=True)
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f)
    return 0 if accepted > 0 or total == 0 else 3


def _submit_shm(argv: list[str]) -> int:
    """Replay a recorded op file through a server's shared-memory
    ingress segment (--shm-ingress PATH on the server): attach, write
    records straight into the mapped ring in --chunk claims, and collect
    positional responses (by ring sequence) from the response ring.
    Backpressure (a full ring) retries until --timeout. Exit 3 when
    nothing was accepted, 2 when the segment is unavailable or responses
    go missing."""
    import json
    import time

    from matching_engine_tpu import native as me_native
    from matching_engine_tpu.domain import oprec

    seg, path = argv[0], argv[1]
    chunk, timeout_s, summary_json, quiet = 256, 60.0, None, False
    max_inflight = 1 << 30
    offset, count = 0, -1
    ready_file = start_barrier = None
    it = iter(argv[2:])
    try:
        for a in it:
            if a == "--chunk":
                chunk = int(next(it))
            elif a == "--timeout":
                timeout_s = float(next(it))
            elif a == "--max-inflight":
                # Cancel-gap flow control for recorded scenarios: keep
                # the un-acked backlog below the manifest's
                # min_cancel_gap so the poller can never dispatch a
                # cancel in the same batch as its target submit.
                max_inflight = int(next(it))
            elif a == "--offset":
                # Multi-writer partitioning: N concurrent submit-shm
                # processes each replay a disjoint [offset, offset+count)
                # slice of one op file through the same segment.
                offset = int(next(it))
            elif a == "--count":
                count = int(next(it))
            elif a == "--ready-file":
                # Multi-writer start synchronization (the bench and the
                # soak): touch ready-file once attached + registered,
                # then hold at the barrier so every writer's measured
                # window starts together (python startup excluded).
                ready_file = next(it)
            elif a == "--start-barrier":
                start_barrier = next(it)
            elif a == "--summary-json":
                summary_json = next(it)
            elif a == "--quiet":
                quiet = True
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except StopIteration:
        print(USAGE, file=sys.stderr)
        return 1
    if chunk < 1 or offset < 0:
        print(USAGE, file=sys.stderr)
        return 1
    try:
        arr = oprec.read_opfile(path)
    except (OSError, oprec.OpRecError) as e:
        print(f"[client] cannot read op file: {e}", file=sys.stderr)
        return 1
    if offset or count >= 0:
        end = len(arr) if count < 0 else min(len(arr), offset + count)
        arr = arr[offset:end]
    try:
        ring = me_native.ShmRing(seg)
    except RuntimeError as e:
        print(f"[client] cannot attach shm segment: {e}", file=sys.stderr)
        return 2
    # Claim a writer lane: responses come back on this lane's private
    # sub-ring, so N concurrent clients each see exactly their own acks.
    # A full registry (>15 writers) falls back to the shared anonymous
    # lane 0 — correct, but acks are then interleaved with other
    # anonymous writers'.
    writer_id = ring.register_writer()
    if ready_file:
        with open(ready_file, "w") as f:
            f.write(str(writer_id))
    if start_barrier:
        import os as _os
        barrier_deadline = time.perf_counter() + timeout_s
        while not _os.path.exists(start_barrier):
            if time.perf_counter() > barrier_deadline:
                print("[client] start barrier never released",
                      file=sys.stderr)
                ring.close()
                return 2
            time.sleep(0.002)
    total = len(arr)
    deadline = time.perf_counter() + timeout_s
    accepted = rejected = accepted_submits = 0
    reasons: dict[str, int] = {}
    pending = 0
    pushed = 0
    t0 = time.perf_counter()

    import numpy as np

    def drain(wait_us: int) -> bool:
        """Vectorized response drain: decode the raw MeShmResp run as
        ONE numpy array — the client stays per-batch python like the
        server's poller."""
        nonlocal pending, accepted, rejected, accepted_submits
        raw = ring.resp_poll_raw(4096, wait_us)
        if raw is None:
            return False  # server shut the segment down
        if not raw:
            return True
        rs = np.frombuffer(raw, dtype=oprec.SHM_RESP_DTYPE)
        pending -= len(rs)
        okv = rs["ok"] != 0
        accepted += int(np.count_nonzero(okv))
        accepted_submits += int(np.count_nonzero(okv & (rs["kind"] == 0)))
        nbad = len(rs) - int(np.count_nonzero(okv))
        rejected += nbad
        if nbad:
            for code, cnt in zip(*np.unique(rs["reason"][~okv],
                                            return_counts=True)):
                msg = oprec.REASON_MESSAGES.get(int(code),
                                                f"reason {code}")
                reasons[msg] = reasons.get(msg, 0) + int(cnt)
            if not quiet:
                for r in rs[~okv]:
                    msg = oprec.REASON_MESSAGES.get(int(r["reason"]),
                                                    "?")
                    print(f"[client] seq {int(r['seq'])} rejected: "
                          f"{msg}")
        return True

    alive = True
    while pushed < total and alive:
        n = min(chunk, total - pushed)
        if pending + n > max_inflight:
            alive = drain(2_000)
            if time.perf_counter() > deadline:
                print("[client] responses stalled past --timeout",
                      file=sys.stderr)
                break
            continue
        body = arr[pushed:pushed + n].tobytes()
        base = ring.push_payload(body, n)
        if base == -2:
            alive = False
            break
        if base < 0:
            # Ring full: drain responses (frees nothing here, but keeps
            # the response ring moving) and retry until the deadline.
            alive = drain(10_000)
            if time.perf_counter() > deadline:
                print("[client] shm ring full past --timeout",
                      file=sys.stderr)
                break
            continue
        pushed += n
        pending += n
        alive = drain(0)
    while pending > 0 and alive and time.perf_counter() < deadline:
        alive = drain(100_000)
    dt = time.perf_counter() - t0
    ring.close()
    if pending > 0:
        print(f"[client] {pending} response(s) missing after "
              f"{timeout_s:.0f}s", file=sys.stderr)
        return 2
    rate = accepted / dt if dt > 0 else 0.0
    summary = {"ops": total, "pushed": pushed, "chunk": chunk,
               "accepted": accepted, "accepted_submits": accepted_submits,
               "rejected": rejected, "writer_id": writer_id,
               "wall_s": round(dt, 3), "accepted_per_s": round(rate, 1),
               "reject_reasons": reasons}
    print(f"[client] shm replay: {accepted}/{total} accepted, "
          f"{dt:.3f}s ({rate:.0f} accepted/s)", file=sys.stderr, flush=True)
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f)
    return 0 if accepted > 0 or total == 0 else 3


def _simulate(argv: list[str]) -> int:
    """Record a named scenario to a workload opfile WITHOUT any server or
    bench harness: run the on-device agent market (sim/scenarios.py),
    decode the generated flow into oprec records (sim/record.py), and
    write `--out` plus its manifest. The artifact replays through
    `client submit-batch`, `runner_bench --workload`, the soak's
    flash-crash round, and CI's smoke — all through the same codec
    reader. Exit 1 on usage, 3 on a scenario that produced no ops."""
    import json

    scenario_name = out = summary_json = None
    steps = seed = None
    symbols, serve_shards = 16, 1
    it = iter(argv)
    try:
        for a in it:
            if a == "--scenario":
                scenario_name = next(it)
            elif a == "--out":
                out = next(it)
            elif a == "--steps":
                steps = int(next(it))
            elif a == "--seed":
                seed = int(next(it))
            elif a == "--symbols":
                symbols = int(next(it))
            elif a == "--serve-shards":
                serve_shards = int(next(it))
            elif a == "--summary-json":
                summary_json = next(it)
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except (StopIteration, ValueError):
        print(USAGE, file=sys.stderr)
        return 1
    if not scenario_name or not out or symbols < 1 or serve_shards < 1:
        print(USAGE, file=sys.stderr)
        return 1

    # Heavy imports gated behind the verb: the other subcommands must not
    # pay jax startup.
    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.sim.record import record_scenario
    from matching_engine_tpu.sim.scenarios import (
        default_mix,
        make_scenario,
        recording_capacity,
        recording_kernel,
    )
    from matching_engine_tpu.utils.metrics import Metrics

    try:
        scenario = make_scenario(scenario_name, steps=steps)
    except ValueError as e:
        print(f"[client] {e}", file=sys.stderr)
        return 1
    mix = default_mix(scenario_name)
    rcap = recording_capacity(mix, scenario_name)
    cfg = EngineConfig(num_symbols=symbols, capacity=rcap,
                       batch=mix.batch_for(), max_fills=1 << 15,
                       kernel=recording_kernel(rcap))
    metrics = Metrics()
    try:
        manifest = record_scenario(cfg, mix, scenario, seed=seed or 0,
                                   out_path=out, serve_shards=serve_shards,
                                   metrics=metrics)
    except (RuntimeError, OSError) as e:
        # Scenario too big for the fixed recording config (uncross fill-
        # log overflow), recorder/codec skew, or an unwritable --out: the
        # verb's contract is a reason + exit 3, never a traceback.
        print(f"[client] simulate failed: {e}", file=sys.stderr)
        return 3
    summary = {
        "scenario": manifest["name"], "seed": manifest["seed"],
        "ops": manifest["ops"], "steps": manifest["steps"],
        "symbols": manifest["symbols"],
        "per_class_ops": manifest["per_class_ops"],
        # Per-phase ground truth (fills/volume/uncross) rides along so a
        # replay driver can reconcile phase by phase, not just end-state.
        "phases": [{k: p[k] for k in ("kind", "steps", "start_record",
                                      "end_record", "fills", "volume",
                                      "uncross", "uncross_executed")}
                   for p in manifest["phases"]],
        "min_cancel_gap": manifest["min_cancel_gap"],
        "sim_fills": manifest["sim_fills"],
        "sim_volume": manifest["sim_volume"],
        "out": out,
    }
    print(f"[client] simulate {manifest['name']}: {manifest['ops']} ops "
          f"over {manifest['steps']} steps x {manifest['symbols']} symbols "
          f"-> {out}", file=sys.stderr, flush=True)
    print(json.dumps(summary))
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if manifest["ops"] > 0 else 3


def _gym_rollout(argv: list[str]) -> int:
    """Roll the many-venue gym (gym/env.py) serverless: V venues in one
    jit'd scan, scenario programs cycling over the venue axis, per-venue
    seeds `--seed + v`. `--steps` defaults to one full episode of the
    longest scenario (auto-reset covers shorter venues). `--freeze V
    --out FILE` additionally freezes venue V's first episode into a
    replayable workload artifact (gym/episode.py) — the same opfile +
    manifest pair `client simulate` writes, replayable through
    `submit-batch` with exact fill reconciliation. Exit 1 on usage, 3 on
    a rollout that produced no ops."""
    import json

    scenario_arg = out = summary_json = None
    steps = freeze = None
    venues, seed, symbols, kernel = 4, 0, 16, None
    it = iter(argv)
    try:
        for a in it:
            if a == "--venues":
                venues = int(next(it))
            elif a == "--scenario":
                scenario_arg = next(it)
            elif a == "--steps":
                steps = int(next(it))
            elif a == "--seed":
                seed = int(next(it))
            elif a == "--symbols":
                symbols = int(next(it))
            elif a == "--kernel":
                kernel = next(it)
            elif a == "--freeze":
                freeze = int(next(it))
            elif a == "--out":
                out = next(it)
            elif a == "--summary-json":
                summary_json = next(it)
            else:
                print(USAGE, file=sys.stderr)
                return 1
    except (StopIteration, ValueError):
        print(USAGE, file=sys.stderr)
        return 1
    if not scenario_arg or venues < 1 or symbols < 1:
        print(USAGE, file=sys.stderr)
        return 1
    if (freeze is None) != (out is None) \
            or (freeze is not None and not 0 <= freeze < venues):
        print(USAGE, file=sys.stderr)
        return 1

    import numpy as np

    from matching_engine_tpu.engine.book import EngineConfig
    from matching_engine_tpu.gym import VenueGym, freeze_episode
    from matching_engine_tpu.sim.scenarios import (
        default_mix,
        make_scenario,
        recording_capacity,
        recording_kernel,
    )
    from matching_engine_tpu.utils.metrics import Metrics

    names = [n for n in scenario_arg.split(",") if n]
    try:
        scens = [make_scenario(n, steps=steps) for n in names]
    except ValueError as e:
        print(f"[client] {e}", file=sys.stderr)
        return 1
    # One engine config for all venues: the recording sizing of the
    # heaviest scenario in the cycle (venues differ by program/seed/
    # population, not capacity — capacity is jit-static).
    mix = default_mix(names[0])
    rcap = max(recording_capacity(mix, n) for n in names)
    cfg = EngineConfig(num_symbols=symbols, capacity=rcap,
                       batch=mix.batch_for(), max_fills=1 << 15,
                       kernel=kernel or recording_kernel(rcap))
    metrics = Metrics()
    record = (freeze,) if freeze is not None else ()
    try:
        env = VenueGym.from_scenarios(cfg, mix, venues, scens,
                                      record=record)
        state, _obs = env.reset([seed + v for v in range(venues)])
        ep_len = np.asarray(env.controls.ep_len)
        run_steps = steps if steps is not None else int(ep_len.max())
        state, stats, rec, _obs = env.rollout(state, run_steps,
                                              metrics=metrics)
    except (RuntimeError, ValueError) as e:
        print(f"[client] gym-rollout failed: {e}", file=sys.stderr)
        return 3
    ops = int(np.asarray(stats.real_ops).sum())
    summary = {
        "venues": venues, "steps": run_steps,
        "scenarios": names, "kernel": cfg.kernel, "seed": seed,
        "symbols": symbols, "ops": ops,
        "venue_steps": venues * run_steps,
        "episodes_done": int(np.asarray(stats.done).sum()),
        "fills": [int(x) for x in np.asarray(stats.fills).sum(axis=0)],
        "volume": [int(x) for x in np.asarray(stats.volume).sum(axis=0)],
        "uncrossed": int(np.asarray(stats.uncrossed).sum()),
    }
    if freeze is not None:
        scen_v = scens[freeze % len(scens)]
        if run_steps < int(ep_len[freeze]):
            print(f"[client] gym-rollout failed: --steps {run_steps} < "
                  f"venue {freeze} episode length {int(ep_len[freeze])} "
                  f"(cannot freeze a partial episode)", file=sys.stderr)
            return 3
        try:
            man = freeze_episode(env.spec, scen_v, freeze, rec, stats,
                                 out, seed=seed + freeze, metrics=metrics)
        except (RuntimeError, ValueError, OSError) as e:
            print(f"[client] gym-rollout freeze failed: {e}",
                  file=sys.stderr)
            return 3
        summary["frozen"] = {
            "out": out, "venue": freeze, "ops": man["ops"],
            "sim_fills": man["sim_fills"],
            "sim_volume": man["sim_volume"],
            "min_cancel_gap": man["min_cancel_gap"],
            "phases": [{k: p[k] for k in ("kind", "steps", "fills",
                                          "volume", "uncross",
                                          "uncross_executed")}
                       for p in man["phases"]],
        }
    print(f"[client] gym-rollout: {venues} venue(s) x {run_steps} steps "
          f"({cfg.kernel}), {ops} ops, "
          f"{summary['episodes_done']} episode(s) done"
          + (f", froze venue {freeze} -> {out}" if freeze is not None
             else ""),
          file=sys.stderr, flush=True)
    print(json.dumps(summary))
    if summary_json:
        with open(summary_json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if ops > 0 else 3


def _promote(addr: str) -> int:
    """Failover verb: flip the --standby replica at `addr` into the
    serving primary (replication/standby.py promote — feed-epoch bump,
    OID floor re-seed, mutation RPCs open). Exit 3 when the target is not
    a standby, matching the submit-reject convention; connected
    subscribers observe one epoch rebase and resume with their cursors."""
    try:
        resp = _stub(addr).Promote(pb2.PromoteRequest(), timeout=60)
    except grpc.RpcError as e:
        print(f"[client] rpc failed: {e.code().name}: {e.details()}",
              file=sys.stderr)
        return 2
    if not resp.success:
        print(f"[client] promote rejected: {resp.error_message}",
              file=sys.stderr)
        return 3
    print(f"[client] promoted: feed_epoch={resp.feed_epoch}")
    return 0


def _metrics(addr: str) -> int:
    resp = _stub(addr).GetMetrics(pb2.MetricsRequest(), timeout=10)
    for k in sorted(resp.counters):
        print(f"[client] counter {k} = {resp.counters[k]}")
    for k in sorted(resp.gauges):
        print(f"[client] gauge {k} = {resp.gauges[k]:.1f}")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        return _dispatch(argv)
    except grpc.RpcError as e:
        # Streams/metrics surface RPC failures here; unary subcommands catch
        # their own. Same message/exit contract either way.
        print(f"[client] rpc failed: {e.code().name}: {e.details()}", file=sys.stderr)
        return 2
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) went away; not an error.
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        return 0
    except KeyboardInterrupt:
        return 0


def _dispatch(argv: list[str]) -> int:
    try:
        # Before the bare 8-arg submit form: subscribe takes a variable
        # option tail, and e.g. `subscribe <addr> md SYM --idle-exit 60
        # --summary-json f` is ALSO 8 args.
        if len(argv) >= 4 and argv[0] == "subscribe":
            return _subscribe(argv[1:])
        if len(argv) >= 3 and argv[0] == "submit-batch":
            return _submit_batch(argv[1:])
        if len(argv) >= 3 and argv[0] == "submit-stream":
            return _submit_stream(argv[1:])
        if len(argv) >= 3 and argv[0] == "submit-shm":
            return _submit_shm(argv[1:])
        if len(argv) >= 3 and argv[0] == "simulate":
            return _simulate(argv[1:])
        if len(argv) >= 3 and argv[0] == "gym-rollout":
            return _gym_rollout(argv[1:])
        if len(argv) >= 2 and argv[0] == "audit":
            return _audit(argv[1:])
        if len(argv) == 8:
            return _submit(argv)
        if len(argv) == 3 and argv[0] == "book":
            return _book(argv[1], argv[2])
        if len(argv) == 4 and argv[0] == "cancel":
            return _cancel(argv[1], argv[2], argv[3])
        if len(argv) == 5 and argv[0] == "amend":
            return _amend(argv[1], argv[2], argv[3], argv[4])
        if len(argv) in (2, 3) and argv[0] == "auction":
            return _auction(argv[1], argv[2] if len(argv) == 3 else "")
        if len(argv) == 3 and argv[0] == "watch-md":
            return _watch_md(argv[1], argv[2])
        if len(argv) == 3 and argv[0] == "watch-orders":
            return _watch_orders(argv[1], argv[2])
        if len(argv) == 2 and argv[0] == "metrics":
            return _metrics(argv[1])
        if len(argv) == 2 and argv[0] == "promote":
            return _promote(argv[1])
    except (ValueError, IndexError):
        pass
    print(USAGE, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
