"""Sparse dispatch: O(actual ops) host<->device transfer per engine step.

The dense serving step ships a full [S, B] OrderBatch (6 int32 planes) and
reads back [S, B] result planes even when a dispatch carries a handful of
orders — at 4096 symbols x batch 32 that is ~3MB up and ~1.5MB down per
step, pure overhead on the host<->device boundary SURVEY.md §7 calls the
latency-critical one (and doubly so over the tunneled single-chip setup,
where that transfer dominates serving latency).

This path ships only the K real ops: [K] coordinate + payload lanes are
scattered onto the zero [S, B] grid ON DEVICE (padding rows target slot=S
and are dropped by the scatter), the unchanged dense kernel runs, and the
per-op results plus each op's symbol top-of-book are GATHERED back at the
same [K] coordinates. Fills were already compact. K is bucketed to powers
of two so the jit cache holds ~log2(S*B) programs instead of one per batch
size.

Semantics are identical to the dense path by construction (same
engine_step_impl); tests/test_sparse.py asserts bit-equal books, results,
and fills on randomized streams. The EngineRunner uses this path for
single-device serving whenever a dispatch is sparse enough to profit
(engine_runner._run_dispatch_locked); the mesh path keeps dense batches
(a sharded scatter would need per-shard coordinate routing for no win —
multi-chip serving amortizes transfers over much larger dispatches).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
)
from matching_engine_tpu.engine.kernel import engine_step_impl


class SparseBatch(NamedTuple):
    """[K] lanes; padding entries carry slot == num_symbols (scatter-drop)."""

    slot: jax.Array
    row: jax.Array
    op: jax.Array
    side: jax.Array
    otype: jax.Array
    price: jax.Array
    qty: jax.Array
    oid: jax.Array


class SparseStepOutput(NamedTuple):
    """Per-op results gathered at the op coordinates, [K] each; fills and
    top-of-book as in StepOutput (fills are already compact). tob_* are the
    post-step top-of-book of each op's OWN symbol (duplicates when several
    ops share a symbol — the decoder dedups by slot)."""

    status: jax.Array
    filled: jax.Array
    remaining: jax.Array
    fill_sym: jax.Array
    fill_taker_oid: jax.Array
    fill_maker_oid: jax.Array
    fill_price: jax.Array
    fill_qty: jax.Array
    fill_count: jax.Array
    fill_overflow: jax.Array
    tob_best_bid: jax.Array
    tob_bid_size: jax.Array
    tob_best_ask: jax.Array
    tob_ask_size: jax.Array


def bucket(n: int, floor: int = 64) -> int:
    """Smallest power-of-two >= n (>= floor) — the static K of the jit."""
    k = floor
    while k < n:
        k <<= 1
    return k


@partial(jax.jit, static_argnums=0, donate_argnums=1)
def engine_step_sparse(cfg: EngineConfig, book: BookBatch,
                       sparse: SparseBatch):
    s, b = cfg.num_symbols, cfg.batch
    zeros = jnp.zeros((s, b), I32)

    def scatter(vals):
        # Padding lanes carry slot == s: out-of-bounds -> dropped.
        return zeros.at[sparse.slot, sparse.row].set(vals, mode="drop")

    dense = OrderBatch(
        op=scatter(sparse.op), side=scatter(sparse.side),
        otype=scatter(sparse.otype), price=scatter(sparse.price),
        qty=scatter(sparse.qty), oid=scatter(sparse.oid),
    )
    new_book, out = engine_step_impl(cfg, book, dense)

    gslot = jnp.clip(sparse.slot, 0, s - 1)
    grow = jnp.clip(sparse.row, 0, b - 1)
    real = sparse.op != 0

    def gather(plane, pad):
        return jnp.where(real, plane[gslot, grow], pad)

    def gather_sym(vec):
        return jnp.where(real, vec[gslot], 0)

    return new_book, SparseStepOutput(
        status=gather(out.status, -1),
        filled=gather(out.filled, 0),
        remaining=gather(out.remaining, 0),
        fill_sym=out.fill_sym,
        fill_taker_oid=out.fill_taker_oid,
        fill_maker_oid=out.fill_maker_oid,
        fill_price=out.fill_price,
        fill_qty=out.fill_qty,
        fill_count=out.fill_count,
        fill_overflow=out.fill_overflow,
        tob_best_bid=gather_sym(out.best_bid),
        tob_bid_size=gather_sym(out.bid_size),
        tob_best_ask=gather_sym(out.best_ask),
        tob_ask_size=gather_sym(out.ask_size),
    )


def decode_sparse_step(sparse: SparseBatch, n: int, out: SparseStepOutput):
    """(results, fills, overflow) — mirror of harness.decode_step, but from
    [K] lanes: results come back in lane order, which build_sparse already
    emitted as device (symbol, row) event order."""
    from matching_engine_tpu.engine.harness import HostResult, decode_fills

    results = [
        HostResult(*t)
        for t in zip(
            np.asarray(sparse.oid[:n]).tolist(),
            np.asarray(sparse.slot[:n]).tolist(),
            np.asarray(out.status[:n]).tolist(),
            np.asarray(out.filled[:n]).tolist(),
            np.asarray(out.remaining[:n]).tolist(),
        )
    ]
    fills = decode_fills(
        out.fill_sym, out.fill_taker_oid, out.fill_maker_oid,
        out.fill_price, out.fill_qty, int(out.fill_count),
    )
    return results, fills, bool(out.fill_overflow)


def build_sparse(cfg: EngineConfig, orders) -> list[tuple[SparseBatch, int]]:
    """Group a chronological HostOrder list into [K]-lane sparse dispatches.

    Same wave semantics as harness.build_batches: orders of one symbol keep
    arrival order in ascending rows; a symbol's (B+1)-th op overflows into
    the next wave. Lanes within a wave are emitted in (slot, row) order —
    the device event order the runner's decode replays — so the gathered
    results line up 1:1 with the lane index. Returns [(batch, n_real)].
    """
    s, b = cfg.num_symbols, cfg.batch
    waves: list[list] = []
    counts = np.zeros((s,), dtype=np.int64)
    for o in orders:
        if not (-(1 << 31) <= o.oid < (1 << 31)):
            raise ValueError(f"oid {o.oid} exceeds the int32 device lane")
        i, row = divmod(int(counts[o.sym]), b)
        while i >= len(waves):
            waves.append([])
        waves[i].append((o.sym, row, o.op, o.side, o.otype, o.price, o.qty,
                         o.oid))
        counts[o.sym] += 1

    out = []
    for wave in waves:
        wave.sort(key=lambda t: (t[0], t[1]))  # device (symbol, row) order
        n = len(wave)
        k = bucket(n)
        arr = np.zeros((k, 8), dtype=np.int32)
        arr[:n] = np.asarray(wave, dtype=np.int32)
        arr[n:, 0] = s  # padding -> scatter-drop coordinate
        out.append((SparseBatch(
            slot=arr[:, 0], row=arr[:, 1], op=arr[:, 2], side=arr[:, 3],
            otype=arr[:, 4], price=arr[:, 5], qty=arr[:, 6], oid=arr[:, 7],
        ), n))
    return out
