"""Sparse dispatch: O(actual ops) host<->device transfer per engine step.

The dense serving step ships a full [S, B] OrderBatch (7 int32 planes) and
reads back [S, B] result planes even when a dispatch carries a handful of
orders — at 4096 symbols x batch 32 that is ~3MB up and ~1.5MB down per
step, pure overhead on the host<->device boundary SURVEY.md §7 calls the
latency-critical one (and doubly so over the tunneled single-chip setup,
where that transfer dominates serving latency).

This path ships only the K real ops, and in as few transfers as possible —
on the tunneled TPU every host<->device hop is a round trip, so transfer
COUNT matters as much as bytes:

- up: ONE [K, 9] int32 lane array (coordinates + payload + STP owner).
  The jit unpacks columns on device and scatters them onto the zero
  [S, B] grid (padding rows target slot=S and are dropped by the
  scatter).
- down: ONE packed [7K+2+5L] int32 vector (per-op status/filled/
  remaining, each op's symbol top-of-book, fill_count, fill_overflow,
  and the leading L=fill_inline_count fill rows), plus ONE full-buffer
  [5, max_fills] fetch only when the fill count exceeds the inline
  segment (fetched whole and sliced on host — a device-side dynamic
  slice is a fresh program per count).

The unchanged dense kernel runs in between, so semantics are identical to
the dense path by construction; tests/test_sparse.py asserts bit-equal
books, results, and fills on randomized streams. K is bucketed to powers
of two so the jit cache holds ~log2(S*B) programs instead of one per batch
size. The EngineRunner uses this path for single-device serving whenever a
dispatch is sparse enough to profit (engine_runner._run_dispatch_locked);
the mesh path keeps dense batches (a sharded scatter would need per-shard
coordinate routing for no win — multi-chip serving amortizes transfers
over much larger dispatches).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
)
from matching_engine_tpu.engine.kernel import (
    engine_step_impl,
    fill_inline_count,
)

# Column layout of the [K, 9] lane array (the ONE upload per sparse step).
LANE_SLOT, LANE_ROW, LANE_OP, LANE_SIDE = 0, 1, 2, 3
LANE_OTYPE, LANE_PRICE, LANE_QTY, LANE_OID, LANE_OWNER = 4, 5, 6, 7, 8
LANE_COLS = 9


class SparseBatch(NamedTuple):
    """One sparse dispatch: `lanes` is the packed [K, 9] int32 array;
    padding rows carry slot == num_symbols (scatter-drop coordinate).
    Column views are host-side numpy (free — `lanes` is built on host)."""

    lanes: np.ndarray

    @property
    def slot(self) -> np.ndarray:
        return self.lanes[:, LANE_SLOT]

    @property
    def row(self) -> np.ndarray:
        return self.lanes[:, LANE_ROW]

    @property
    def op(self) -> np.ndarray:
        return self.lanes[:, LANE_OP]

    @property
    def side(self) -> np.ndarray:
        return self.lanes[:, LANE_SIDE]

    @property
    def otype(self) -> np.ndarray:
        return self.lanes[:, LANE_OTYPE]

    @property
    def price(self) -> np.ndarray:
        return self.lanes[:, LANE_PRICE]

    @property
    def qty(self) -> np.ndarray:
        return self.lanes[:, LANE_QTY]

    @property
    def oid(self) -> np.ndarray:
        return self.lanes[:, LANE_OID]

    @property
    def owner(self) -> np.ndarray:
        return self.lanes[:, LANE_OWNER]


class SparseStepOutput(NamedTuple):
    """Device-side packed step output — ONE read round-trip per step for
    any dispatch whose fill count fits the inline segment, two otherwise:

    small: [7K + 2 + 5L] int32 (L = fill_inline_count(cfg)) = status |
           filled | remaining | tob_best_bid | tob_bid_size |
           tob_best_ask | tob_ask_size (each [K], gathered at the op
           coordinates; tob_* duplicate when ops share a symbol) ++
           [fill_count, fill_overflow] ++ fills[:, :L] ravelled.
    fills: [5, max_fills] int32, rows in decode_fills column order
           (sym, taker_oid, maker_oid, price, qty) — fetched only when
           fill_count > L.
    """

    small: jax.Array
    fills: jax.Array


class SparseDecoded(NamedTuple):
    """Host view of one sparse step (all numpy, no further transfers)."""

    status: np.ndarray
    filled: np.ndarray
    remaining: np.ndarray
    tob_best_bid: np.ndarray
    tob_bid_size: np.ndarray
    tob_best_ask: np.ndarray
    tob_ask_size: np.ndarray
    fill_count: int
    fill_overflow: bool
    fills_inline: np.ndarray  # [5, L]


def bucket(n: int, floor: int = 64) -> int:
    """Smallest power-of-two >= n (>= floor) — the static K of the jit."""
    k = floor
    while k < n:
        k <<= 1
    return k


@partial(jax.jit, static_argnums=0, donate_argnums=1)
def _step_sparse_jit(cfg: EngineConfig, book: BookBatch, lanes: jax.Array):
    s, b = cfg.num_symbols, cfg.batch
    slot = lanes[:, LANE_SLOT]
    row = lanes[:, LANE_ROW]
    op = lanes[:, LANE_OP]
    zeros = jnp.zeros((s, b), I32)

    def scatter(vals):
        # Padding lanes carry slot == s: out-of-bounds -> dropped.
        return zeros.at[slot, row].set(vals, mode="drop")

    dense = OrderBatch(
        op=scatter(op),
        side=scatter(lanes[:, LANE_SIDE]),
        otype=scatter(lanes[:, LANE_OTYPE]),
        price=scatter(lanes[:, LANE_PRICE]),
        qty=scatter(lanes[:, LANE_QTY]),
        oid=scatter(lanes[:, LANE_OID]),
        owner=scatter(lanes[:, LANE_OWNER]),
    )
    new_book, out = engine_step_impl(cfg, book, dense)

    gslot = jnp.clip(slot, 0, s - 1)
    grow = jnp.clip(row, 0, b - 1)
    real = op != 0

    def gather(plane, pad):
        return jnp.where(real, plane[gslot, grow], pad)

    def gather_sym(vec):
        return jnp.where(real, vec[gslot], 0)

    fills = jnp.stack([
        out.fill_sym, out.fill_taker_oid, out.fill_maker_oid,
        out.fill_price, out.fill_qty,
    ])
    small = jnp.concatenate([
        gather(out.status, -1),
        gather(out.filled, 0),
        gather(out.remaining, 0),
        gather_sym(out.best_bid),
        gather_sym(out.bid_size),
        gather_sym(out.best_ask),
        gather_sym(out.ask_size),
        jnp.stack([
            out.fill_count.astype(I32),
            out.fill_overflow.astype(I32),
        ]),
        fills[:, :fill_inline_count(cfg)].reshape(-1),  # static slice
    ])
    return new_book, SparseStepOutput(small=small, fills=fills)


def engine_step_sparse(cfg: EngineConfig, book: BookBatch,
                       sparse: SparseBatch):
    return _step_sparse_jit(cfg, book, sparse.lanes)


def unpack_sparse_output(out: SparseStepOutput, k: int) -> SparseDecoded:
    """ONE device->host transfer for everything except an over-inline
    fill log."""
    small = np.asarray(out.small)
    lo = (small.shape[0] - 7 * k - 2) // 5
    tail = 7 * k + 2
    return SparseDecoded(
        status=small[0:k],
        filled=small[k:2 * k],
        remaining=small[2 * k:3 * k],
        tob_best_bid=small[3 * k:4 * k],
        tob_bid_size=small[4 * k:5 * k],
        tob_best_ask=small[5 * k:6 * k],
        tob_ask_size=small[6 * k:7 * k],
        fill_count=int(small[7 * k]),
        fill_overflow=bool(small[7 * k + 1]),
        fills_inline=small[tail:tail + 5 * lo].reshape(5, lo),
    )


def decode_sparse_step(sparse: SparseBatch, n: int, out: SparseStepOutput):
    """(results, fills, overflow, decoded) — mirror of harness.decode_step,
    but from [K] lanes: results come back in lane order, which build_sparse
    already emitted as device (symbol, row) event order. Two transfers max:
    the packed small vector, and (only when fills occurred) the [5, :n]
    fill slice."""
    from matching_engine_tpu.engine.harness import HostResult, decode_fills

    k = sparse.lanes.shape[0]
    dec = unpack_sparse_output(out, k)
    results = [
        HostResult(*t)
        for t in zip(
            sparse.oid[:n].tolist(),
            sparse.slot[:n].tolist(),
            dec.status[:n].tolist(),
            dec.filled[:n].tolist(),
            dec.remaining[:n].tolist(),
        )
    ]
    fn = dec.fill_count
    if fn == 0:
        fills = []
    else:
        # Common case: fills fit the inline segment of the one small-vector
        # readback. Otherwise fetch the WHOLE fill buffer and slice on
        # host — a device-side `fills[:, :fn]` would be a fresh XLA
        # program per distinct fn (a compile + execution round trip per
        # dispatch over a tunneled chip).
        packed = (dec.fills_inline if fn <= dec.fills_inline.shape[1]
                  else np.asarray(out.fills))
        fills = decode_fills(packed[0], packed[1], packed[2], packed[3],
                             packed[4], fn)
    return results, fills, dec.fill_overflow, dec


def build_sparse(cfg: EngineConfig, orders) -> list[tuple[SparseBatch, int]]:
    """Group a chronological HostOrder list into [K]-lane sparse dispatches.

    Same wave semantics as harness.build_batches: orders of one symbol keep
    arrival order in ascending rows; a symbol's (B+1)-th op overflows into
    the next wave. Lanes within a wave are emitted in (slot, row) order —
    the device event order the runner's decode replays — so the gathered
    results line up 1:1 with the lane index. Returns [(batch, n_real)].
    """
    s, b = cfg.num_symbols, cfg.batch
    waves: list[list] = []
    counts = np.zeros((s,), dtype=np.int64)
    for o in orders:
        if not (-(1 << 31) <= o.oid < (1 << 31)):
            raise ValueError(f"oid {o.oid} exceeds the int32 device lane")
        i, row = divmod(int(counts[o.sym]), b)
        while i >= len(waves):
            waves.append([])
        waves[i].append((o.sym, row, o.op, o.side, o.otype, o.price, o.qty,
                         o.oid, o.owner))
        counts[o.sym] += 1

    out = []
    for wave in waves:
        wave.sort(key=lambda t: (t[0], t[1]))  # device (symbol, row) order
        n = len(wave)
        k = bucket(n)
        arr = np.zeros((k, LANE_COLS), dtype=np.int32)
        arr[:n] = np.asarray(wave, dtype=np.int32)
        arr[n:, LANE_SLOT] = s  # padding -> scatter-drop coordinate
        out.append((SparseBatch(lanes=arr), n))
    return out
