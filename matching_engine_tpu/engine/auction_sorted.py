"""Venue-depth call-auction uncross: O(CAP log CAP), exact past int32.

The matrix-formulation uncross (engine/auction.py `_uncross_one` /
`_records_one`) evaluates demand/supply with [2C, C] masked matvecs and
pairs bilateral records with a [C, C] interval-overlap matrix — quadratic
intermediates AND int32 volume sums, both of which break at venue depth
(VERDICT r4 missing #4: capacity 8192 books supported continuous matching
but not auctions, because `capacity * MAX_QUANTITY` wraps int32 and the
clearing price needs EXACT sums, so the sorted kernel's saturating-sum
trick is not applicable).

This module is the sorted-book answer, used for `EngineConfig.kernel ==
"sorted"` books at any capacity up to 8192:

- Each side is priority-sorted once (`jnp.lexsort`; the sorted kernel's
  dense-prefix invariant makes this nearly a no-op, but the sort is kept
  so the formulation is correct for ANY lane order).
- demand(p) / supply(p) over the 2C candidate prices collapse to
  `searchsorted` into the sorted price lanes + a prefix-sum lookup —
  O(C log C) total, no [2C, C] matrix.
- Every cumulative volume is a **wide pair**: two int32 lanes holding a
  base-2^15 limb decomposition (value = hi * 2^15 + lo, 0 <= lo < 2^15).
  Limb-wise `cumsum` cannot wrap (lo-limb sum <= 8192 * 32767 < 2^28;
  hi-limb <= 8192 * (MAX_QUANTITY >> 15) < 2^20) and one carry
  normalization restores canonical form, so demand, supply, imbalance
  and the clearing-price argmax are EXACT to 2^46 — no clamping anywhere
  near the comparison that picks p* (the VERDICT's requirement).
- Bilateral records come from a sorted MERGE of the two sides' fill
  interval boundaries on the executed-volume line instead of the [C, C]
  overlap matrix: consecutive merged boundaries delimit one record; the
  bid/ask identity of record k is a running count of completed intervals.
  Record order (bid-major, ask-ascending within) is identical to the
  matrix path and the oracle.

Parity: engine/oracle.py `OracleBook.auction` (exact Python ints) pins
both formulations; tests/test_auction.py fuzzes capacity-8192 books with
near-MAX_QUANTITY volumes through this path.

Reference scope anchor: the auction status machine this feeds is declared
at /root/reference/proto/matching_engine.proto:79-85; the reference never
implemented an engine behind it (its engine file is 0 bytes).
"""

from __future__ import annotations

import jax.numpy as jnp

from matching_engine_tpu.engine.book import I32

IMAX = jnp.iinfo(jnp.int32).max
_SH = 15
_LMASK = (1 << _SH) - 1


# -- wide-pair (base-2^15 two-limb int32) helpers ---------------------------
# Canonical form: value = hi * 2^15 + lo with 0 <= lo < 2^15 (hi carries
# the sign). Lexicographic (hi, lo) comparison == value comparison.

def _w_norm(hi, lo):
    """Carry-normalize (arithmetic >> floors, so negatives work too)."""
    return hi + (lo >> _SH), lo & _LMASK


def _w_split(q):
    """int32 (non-negative, < 2^30) -> canonical wide pair."""
    return q >> _SH, q & _LMASK


def _w_cumsum(q, axis=-1):
    """EXACT inclusive cumsum of int32 quantities as a wide pair: each
    limb's running sum stays far inside int32 (see module docstring)."""
    hi, lo = _w_split(q)
    return _w_norm(jnp.cumsum(hi, axis=axis), jnp.cumsum(lo, axis=axis))


def _w_sub(ahi, alo, bhi, blo):
    return _w_norm(ahi - bhi, alo - blo)


def _w_abs(hi, lo):
    neg = hi < 0
    nhi, nlo = _w_norm(-hi, -lo)
    return jnp.where(neg, nhi, hi), jnp.where(neg, nlo, lo)


def _w_le(ahi, alo, bhi, blo):
    return (ahi < bhi) | ((ahi == bhi) & (alo <= blo))


def _w_to_i32(hi, lo):
    """Narrow a wide value KNOWN to fit int32 (caller guarantees)."""
    return (hi << _SH) + lo


# -- the per-symbol uncross (vmapped by the caller) -------------------------

def _uncross_records_one(bid_price, bid_qty, bid_oid, bid_seq,
                         ask_price, ask_qty, ask_oid, ask_seq, mask):
    """One symbol's uncross + bilateral records, sorted formulation.

    Returns (fill_b[C], fill_a[C], p_star, exec_hi, exec_lo,
    rec_taker[2C], rec_maker[2C], rec_qty[2C], rec_count) — fills in
    ORIGINAL lane order (scatter through the sort permutation), executed
    volume as a wide pair, records bid-major like the matrix path."""
    cap = bid_qty.shape[0]
    live_b = bid_qty > 0
    live_a = ask_qty > 0

    # Priority sort: key ascending = (-price for bids / price for asks,
    # then seq); dead lanes key IMAX -> sorted last.
    ord_b = jnp.lexsort((bid_seq, jnp.where(live_b, -bid_price, IMAX)))
    ord_a = jnp.lexsort((ask_seq, jnp.where(live_a, ask_price, IMAX)))
    sq_b = jnp.where(live_b, bid_qty, 0)[ord_b]
    sq_a = jnp.where(live_a, ask_qty, 0)[ord_a]
    key_b = jnp.where(live_b, -bid_price, IMAX)[ord_b]   # ascending
    key_a = jnp.where(live_a, ask_price, IMAX)[ord_a]    # ascending

    # Exclusive prefix volumes, [C+1] wide: Dx[i] = qty of the i highest-
    # priority bids (demand down the sorted order), Sx likewise.
    zero = jnp.zeros((1,), I32)

    def _excl(hi, lo):
        return (jnp.concatenate([zero, hi]), jnp.concatenate([zero, lo]))

    d_hi_c, d_lo_c = _w_cumsum(sq_b)
    s_hi_c, s_lo_c = _w_cumsum(sq_a)
    dx_hi, dx_lo = _excl(d_hi_c, d_lo_c)
    sx_hi, sx_lo = _excl(s_hi_c, s_lo_c)

    # Candidate clearing prices: every live resting price, [2C].
    cand = jnp.concatenate([bid_price, ask_price])
    valid = jnp.concatenate([live_b, live_a]) & mask

    # demand(p) = volume of bids with price >= p  = Dx[#keys <= -p];
    # supply(p) = volume of asks with price <= p  = Sx[#keys <=  p].
    nb = jnp.searchsorted(key_b, -cand, side="right")
    na = jnp.searchsorted(key_a, cand, side="right")
    d_hi, d_lo = dx_hi[nb], dx_lo[nb]
    s_hi, s_lo = sx_hi[na], sx_lo[na]

    # executable = min(demand, supply); invalid candidates -> (-1, 0)
    # (below every canonical non-negative value).
    d_min = _w_le(d_hi, d_lo, s_hi, s_lo)
    ex_hi = jnp.where(valid, jnp.where(d_min, d_hi, s_hi), -1)
    ex_lo = jnp.where(valid, jnp.where(d_min, d_lo, s_lo), 0)

    # Lexicographic max executable: limb-at-a-time (canonical form).
    m_hi = jnp.max(ex_hi)
    m_lo = jnp.max(jnp.where(ex_hi == m_hi, ex_lo, -1))
    c1 = valid & (ex_hi == m_hi) & (ex_lo == m_lo)

    # Tie 1: min |demand - supply|; tie 2: lowest price.
    i_hi, i_lo = _w_abs(*_w_sub(d_hi, d_lo, s_hi, s_lo))
    m2_hi = jnp.min(jnp.where(c1, i_hi, IMAX))
    m2_lo = jnp.min(jnp.where(c1 & (i_hi == m2_hi), i_lo, IMAX))
    c2 = c1 & (i_hi == m2_hi) & (i_lo == m2_lo)
    p_star = jnp.min(jnp.where(c2, cand, IMAX))

    crossed = mask & ((m_hi > 0) | ((m_hi == 0) & (m_lo > 0))) \
        & (p_star < IMAX)
    q_hi = jnp.where(crossed, m_hi, 0)
    q_lo = jnp.where(crossed, m_lo, 0)

    # Fills in sorted space. Eligible lanes are a PREFIX of the sorted
    # order (every lane before an eligible lane has >= its price), so
    # ahead-of-me is just the exclusive prefix volume Dx/Sx again.
    def _side_fills(keys, neg_p, sq, dx_h, dx_l):
        elig = crossed & (keys <= (-p_star if neg_p else p_star)) \
            & (sq > 0)
        a_hi, a_lo = dx_h[:cap], dx_l[:cap]           # ahead-of-lane-i
        r_hi, r_lo = _w_sub(q_hi, q_lo, a_hi, a_lo)   # remaining at i
        pos = (r_hi > 0) | ((r_hi == 0) & (r_lo > 0))
        take_all = _w_le(*_w_split(sq), r_hi, r_lo)
        # r < sq <= MAX_QUANTITY in the partial branch -> narrowing safe.
        fill = jnp.where(take_all, sq, _w_to_i32(r_hi, r_lo))
        return jnp.where(elig & pos, fill, 0).astype(I32)

    fill_sb = _side_fills(key_b, True, sq_b, dx_hi, dx_lo)
    fill_sa = _side_fills(key_a, False, sq_a, sx_hi, sx_lo)

    # Bilateral records: merge the two sides' interval boundaries on the
    # executed-volume line. Boundary of lane i = inclusive fill cumsum;
    # zero-fill lanes park at (IMAX, IMAX) and sort last.
    b_hi, b_lo = _w_cumsum(fill_sb)
    a_hi, a_lo = _w_cumsum(fill_sa)
    real_b = fill_sb > 0
    real_a = fill_sa > 0
    e_hi = jnp.concatenate([jnp.where(real_b, b_hi, IMAX),
                            jnp.where(real_a, a_hi, IMAX)])
    e_lo = jnp.concatenate([jnp.where(real_b, b_lo, IMAX),
                            jnp.where(real_a, a_lo, IMAX)])
    is_bid = jnp.concatenate([real_b, jnp.zeros((cap,), bool)])
    is_ask = jnp.concatenate([jnp.zeros((cap,), bool), real_a])
    ord_e = jnp.lexsort((e_lo, e_hi))
    e_hi, e_lo = e_hi[ord_e], e_lo[ord_e]
    real = (is_bid | is_ask)[ord_e]

    # Record k spans [E[k-1], E[k]) (E[-1] = 0). Its bid/ask = how many
    # of that side's intervals completed strictly before it starts =
    # exclusive running count of that side's sorted boundaries.
    prev_hi = jnp.concatenate([zero, e_hi[:-1]])
    prev_lo = jnp.concatenate([zero, e_lo[:-1]])
    nonempty = real & ~_w_le(e_hi, e_lo, prev_hi, prev_lo)
    # Width fits int32: a record lies inside ONE bid interval (<= its
    # fill <= MAX_QUANTITY).
    rec_qty = jnp.where(
        nonempty, _w_to_i32(*_w_sub(e_hi, e_lo, prev_hi, prev_lo)), 0)
    cum_b = jnp.cumsum(is_bid[ord_e].astype(I32))
    cum_a = jnp.cumsum(is_ask[ord_e].astype(I32))
    i_b = jnp.concatenate([zero, cum_b[:-1]])
    i_a = jnp.concatenate([zero, cum_a[:-1]])
    s_bid_oid = bid_oid[ord_b]
    s_ask_oid = ask_oid[ord_a]
    rec_taker = jnp.where(
        nonempty, s_bid_oid[jnp.clip(i_b, 0, cap - 1)], 0)
    rec_maker = jnp.where(
        nonempty, s_ask_oid[jnp.clip(i_a, 0, cap - 1)], 0)

    # Scatter fills back to original lane order for apply_uncross.
    fill_b = jnp.zeros((cap,), I32).at[ord_b].set(fill_sb)
    fill_a = jnp.zeros((cap,), I32).at[ord_a].set(fill_sa)

    return (fill_b, fill_a, jnp.where(crossed, p_star, 0).astype(I32),
            q_hi.astype(I32), q_lo.astype(I32),
            rec_taker.astype(I32), rec_maker.astype(I32),
            rec_qty.astype(I32), jnp.sum(nonempty).astype(I32))
