"""Sorted-book match kernel: O(CAP) per order instead of O(CAP^2).

The production kernel (engine/kernel.py) allocates fills with a [CAP, CAP]
priority comparison matrix — per-order work and intermediates quadratic in
book capacity, which is exactly where a venue-depth book (VERDICT r3 weak
#3 / next-step 4) gets expensive. This module is the alternative
formulation that answers it: maintain each book side as a **dense sorted
prefix** — live entries (qty > 0) occupy slots [0, n) ordered by
price-time priority (key ascending; key = price for asks, -price for
bids; ties impossible: seqs are unique and insertion places equal-price
orders behind existing ones) — and the whole matrix collapses to vector
ops:

- quantity resting ahead of maker j  = exclusive cumsum of eligible qty,
- fill_j = clip(Q - ahead_j, 0, qty_j)   (identical allocation),
- priority rank = exclusive cumsum of the eligibility mask,
- resting inserts by shift (one O(CAP) gather), cancels compact the side
  (one cumsum-scatter), matched-out makers compact the same way.

Everything else — eligibility, self-trade prevention, statuses, MARKET
IOC, OP_REST auction accumulation, the fill-log contract, finalize_step —
is shared with or identical to kernel.py, and bit-parity with the host
oracle AND the matrix kernel is pinned by tests/test_kernel_sorted.py.

Books produced by the two kernels are NOT interchangeable mid-stream (the
matrix kernel leaves holes and arbitrary slot order); pick one kernel per
book lifetime. `bench_child.py --kernel sorted` benches this one; the
capacity sweep decides which formulation serves at which CAP
(docs/BENCH_METHOD.md round-4: capacity sweep).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
)
from matching_engine_tpu.engine.kernel import (
    BUY,
    CANCELED,
    FILLED,
    LIMIT_FOK,
    LIMIT_IOC,
    MARKET,
    MARKET_FOK,
    NEW,
    NOOP_STATUS,
    OP_AMEND,
    OP_CANCEL,
    OP_REST,
    OP_SUBMIT,
    PARTIALLY_FILLED,
    REJECTED,
    _SymBook,
    finalize_step,
)


def _compact(qty, *arrays):
    """Pack live entries (qty > 0) into a dense prefix, preserving order;
    freed tail slots zero. Returns (new_qty, *new_arrays)."""
    cap = qty.shape[0]
    keep = qty > 0
    dest = jnp.where(keep, jnp.cumsum(keep) - 1, cap)  # cap = trash slot

    def scatter(x):
        return jnp.zeros((cap + 1,), I32).at[dest].set(
            jnp.where(keep, x, 0))[:cap]

    return (scatter(qty), *(scatter(x) for x in arrays))


def _match_one_sorted(book: _SymBook, order):
    """Apply one order to one SORTED book (see module docstring invariant).
    Same return contract as kernel._match_one."""
    op, side, otype, price, qty, oid, owner = (
        order.op, order.side, order.otype, order.price, order.qty,
        order.oid, order.owner,
    )
    is_submit = op == OP_SUBMIT
    is_cancel = op == OP_CANCEL
    is_rest = op == OP_REST
    is_amend = op == OP_AMEND        # qty-down in place: priority kept
    is_submit_like = is_submit | is_rest
    is_buy = side == BUY
    # Same tif collapse as kernel._match_one: px_any = price-indifferent
    # sweep, is_fok = all-or-nothing, never_rests = cancels remainder.
    px_any = (otype == MARKET) | (otype == MARKET_FOK)
    is_fok = (otype == LIMIT_FOK) | (otype == MARKET_FOK)
    never_rests = px_any | (otype == LIMIT_IOC) | (otype == LIMIT_FOK)
    cap = book.bid_qty.shape[0]
    idx = jnp.arange(cap)

    # ---- opposite side (maker candidates), sorted best-first -------------
    opp_price = jnp.where(is_buy, book.ask_price, book.bid_price)
    opp_qty = jnp.where(is_buy, book.ask_qty, book.bid_qty)
    opp_oid = jnp.where(is_buy, book.ask_oid, book.bid_oid)
    opp_seq = jnp.where(is_buy, book.ask_seq, book.bid_seq)
    opp_owner = jnp.where(is_buy, book.ask_owner, book.bid_owner)

    live = opp_qty > 0
    price_ok = jnp.where(is_buy, opp_price <= price, opp_price >= price)
    not_self = (owner == 0) | (opp_owner != owner)
    elig = live & (px_any | price_ok) & is_submit & not_self
    self_blocked = is_submit & (~never_rests) & jnp.any(
        live & price_ok & (owner != 0) & (opp_owner == owner))

    # Priority order IS slot order: ahead-of-j is an exclusive prefix sum.
    # Venue-depth books (capacity * MAX_QUANTITY >= 2^31) switch to a
    # SATURATING prefix sum: min(a+b, SAT) over non-negative ints is
    # associative, SAT = 2^30-1 keeps a+b inside int32, and saturation
    # is reached only past take_q (<= MAX_QUANTITY << SAT), where the
    # fill is zero regardless — so the allocation stays EXACT while the
    # running sum can no longer wrap. (int64 is x64-gated in jax; this
    # stays in native int32 lanes.) Every other sum (filled_total <= qty,
    # cancel_qty <= qty, lane counts <= cap) is int32-safe as is. Static
    # branch: `cap` is a trace-time shape.
    from matching_engine_tpu.engine.book import MAX_QUANTITY

    elig_qty = jnp.where(elig, opp_qty, 0)
    if cap * MAX_QUANTITY >= 2**31:
        sat = jnp.int32((1 << 30) - 1)
        cum = jax.lax.associative_scan(
            lambda a, b: jnp.minimum(a + b, sat), elig_qty)
    else:
        cum = jnp.cumsum(elig_qty)
    ahead = cum - elig_qty

    # Fill-or-kill gate: the inclusive cumsum's last element is the total
    # eligible liquidity. Under the saturating venue-depth scan it clamps
    # at 2^30-1 > MAX_QUANTITY >= qty, so `avail < qty` is exact whether
    # or not the running sum saturated.
    avail = cum[-1] if cap > 0 else jnp.int32(0)
    fok_fail = is_fok & (avail < qty)

    take_q = jnp.where(is_submit_like & ~fok_fail, qty, 0)
    fill = jnp.where(elig, jnp.clip(take_q - ahead, 0, opp_qty), 0)
    filled_total = jnp.sum(fill)
    remaining = jnp.where(is_submit_like, qty, 0) - filled_total

    # Rank among eligible makers = exclusive prefix count (same slots the
    # matrix kernel's pairwise rank produces — sorted order is priority
    # order).
    rank = jnp.cumsum(elig.astype(I32)) - elig.astype(I32)
    has_fill = fill > 0
    slot = jnp.where(has_fill, rank, cap)
    fill_oid = jnp.zeros((cap + 1,), I32).at[slot].set(
        jnp.where(has_fill, opp_oid, 0))[:cap]
    fill_qty_out = jnp.zeros((cap + 1,), I32).at[slot].set(fill)[:cap]
    fill_price = jnp.zeros((cap + 1,), I32).at[slot].set(
        jnp.where(has_fill, opp_price, 0))[:cap]

    # Matched-out makers leave holes: re-pack the prefix.
    new_opp_qty, opp_price, opp_oid, opp_seq, opp_owner = _compact(
        opp_qty - fill, opp_price, opp_oid, opp_seq, opp_owner)

    # ---- own side: sorted insert of a LIMIT remainder, or cancel ---------
    own_price = jnp.where(is_buy, book.bid_price, book.ask_price)
    own_qty = jnp.where(is_buy, book.bid_qty, book.ask_qty)
    own_oid = jnp.where(is_buy, book.bid_oid, book.ask_oid)
    own_seq = jnp.where(is_buy, book.bid_seq, book.ask_seq)
    own_owner = jnp.where(is_buy, book.bid_owner, book.ask_owner)

    own_live = own_qty > 0
    n_live = jnp.sum(own_live.astype(I32))
    do_rest = is_submit_like & (~never_rests) & (remaining > 0) & ~self_blocked
    rested = do_rest & (n_live < cap)

    # Insertion position: behind every live entry with key <= new key
    # (equal price = earlier seq = higher priority than the newcomer).
    own_key = jnp.where(is_buy, -own_price, own_price)
    new_key = jnp.where(is_buy, -price, price)
    pos = jnp.sum((own_live & (own_key <= new_key)).astype(I32))

    gather_src = jnp.clip(idx - 1, 0, cap - 1)

    def insert(x, new_val):
        shifted = jnp.where(idx > pos, x[gather_src], x)
        return jnp.where(rested & (idx == pos), new_val,
                         jnp.where(rested, shifted, x))

    ins_price = insert(own_price, price)
    ins_qty = insert(own_qty, remaining)
    ins_oid = insert(own_oid, oid)
    ins_seq = insert(own_seq, book.next_seq)
    ins_owner = insert(own_owner, owner)
    next_seq = book.next_seq + jnp.where(rested, 1, 0).astype(I32)

    cancel_mask = is_cancel & (own_oid == oid) & own_live
    cancel_qty = jnp.sum(jnp.where(cancel_mask, own_qty, 0))
    cancel_ok = jnp.any(cancel_mask)
    # Amend down in place: quantity drops, price/seq (and the dense
    # sorted-prefix position they define) stay put — new qty > 0 keeps
    # density, so the compact below is still an identity for amends.
    amend_mask = is_amend & (own_oid == oid) & own_live
    amend_feasible = amend_mask & (qty > 0) & (qty < own_qty)
    amend_ok = jnp.any(amend_feasible)
    # Cancel zeroes its slot; the unconditional compact below re-packs
    # (identity when nothing was zeroed — inserts keep density).
    c_qty = jnp.where(cancel_mask, 0,
                      jnp.where(amend_feasible, qty, ins_qty))
    own_qty2, own_price2, own_oid2, own_seq2, own_owner2 = _compact(
        c_qty, ins_price, ins_oid, ins_seq, ins_owner)

    new_book = _SymBook(
        bid_price=jnp.where(is_buy, own_price2, opp_price),
        bid_qty=jnp.where(is_buy, own_qty2, new_opp_qty),
        bid_oid=jnp.where(is_buy, own_oid2, opp_oid),
        bid_seq=jnp.where(is_buy, own_seq2, opp_seq),
        bid_owner=jnp.where(is_buy, own_owner2, opp_owner),
        ask_price=jnp.where(is_buy, opp_price, own_price2),
        ask_qty=jnp.where(is_buy, new_opp_qty, own_qty2),
        ask_oid=jnp.where(is_buy, opp_oid, own_oid2),
        ask_seq=jnp.where(is_buy, opp_seq, own_seq2),
        ask_owner=jnp.where(is_buy, opp_owner, own_owner2),
        next_seq=next_seq,
    )

    # ---- status (identical decision tree to kernel._match_one) -----------
    submit_status = jnp.where(
        remaining == 0,
        FILLED,
        jnp.where(
            never_rests | self_blocked,
            CANCELED,
            jnp.where(
                rested,
                jnp.where(filled_total > 0, PARTIALLY_FILLED, NEW),
                REJECTED,
            ),
        ),
    )
    cancel_status = jnp.where(cancel_ok, CANCELED, REJECTED)
    amend_status = jnp.where(amend_ok, NEW, REJECTED)
    status = jnp.where(
        is_submit_like,
        submit_status,
        jnp.where(
            is_cancel, cancel_status,
            jnp.where(is_amend, amend_status, NOOP_STATUS)),
    ).astype(I32)
    out_remaining = jnp.where(
        is_submit_like, remaining,
        jnp.where(is_cancel, cancel_qty,
                  jnp.where(is_amend & amend_ok, qty, 0))
    ).astype(I32)

    return new_book, (
        status,
        filled_total.astype(I32),
        out_remaining,
        fill_oid,
        fill_qty_out,
        fill_price,
    )


def _sym_scan_sorted(book: _SymBook, orders):
    return jax.lax.scan(lambda b, o: _match_one_sorted(b, o), book, orders)


def engine_step_sorted_core(cfg: EngineConfig, book: BookBatch,
                            orders: OrderBatch):
    """Raw sorted-formulation match pass (same contract as
    kernel.engine_step_core): no finalize epilogue, so the megadispatch
    scan can compact per wave instead."""
    sym_book = _SymBook(*book[:-1], next_seq=book.next_seq)
    new_sym_book, raw = jax.vmap(_sym_scan_sorted)(sym_book, orders)
    return BookBatch(*new_sym_book[:-1], next_seq=new_sym_book.next_seq), raw


def engine_step_sorted_impl(cfg: EngineConfig, book: BookBatch,
                            orders: OrderBatch):
    """Un-jitted sorted-formulation step (same contract as
    kernel.engine_step_impl; shares finalize_step)."""
    new_book, (status, filled, remaining, f_oid, f_qty, f_price) = (
        engine_step_sorted_core(cfg, book, orders))
    return new_book, finalize_step(
        cfg, new_book, orders, status, filled, remaining, f_oid, f_qty,
        f_price)


engine_step_sorted = jax.jit(engine_step_sorted_impl, static_argnums=0,
                             donate_argnums=1)
