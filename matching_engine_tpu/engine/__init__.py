from matching_engine_tpu.engine.book import BookBatch, EngineConfig
from matching_engine_tpu.engine.oracle import Fill, OracleBook, OrderResult
from matching_engine_tpu.engine import kernel

__all__ = ["BookBatch", "EngineConfig", "Fill", "OracleBook", "OrderResult", "kernel"]
