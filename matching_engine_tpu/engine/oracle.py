"""Host oracle: an obviously-correct price-time-priority CLOB.

This is the fill-parity referee for the TPU kernel (SURVEY.md §4: replay the
same order stream through this and through the jit'd kernel, assert identical
fills). It is deliberately simple Python — integer math only, linear scans,
no cleverness. The reference left its engine file empty
(include/engine/model.hpp, 0 bytes); these are the matching semantics this
framework defines (SURVEY.md §7 "Matching semantics"):

- Price-time priority: best price first (lowest ask / highest bid), FIFO by
  arrival sequence within a price level.
- LIMIT: crosses while the opposite best satisfies the limit; any remainder
  rests in the book.
- MARKET: sweeps the opposite side without a price bound; any remainder is
  canceled (immediate-or-cancel remainder — market orders never rest).
- LIMIT_IOC: matches at the limit like LIMIT, then cancels any remainder
  instead of resting it.
- LIMIT_FOK / MARKET_FOK: all-or-nothing — if the eligible liquidity
  (price-crossing, live, not self-owned) cannot cover the full quantity,
  the order cancels untouched; otherwise it fills completely.
- Fills execute at the resting (maker) price.
- CANCEL removes a resting order by id.
- Each book side has a fixed capacity (the device kernel's static shape); a
  LIMIT remainder that finds the side full is rejected after its fills are
  honored (status REJECTED, rested=False).

Statuses use the proto enum (OrderUpdate.Status): a fully filled taker is
FILLED; partially filled LIMIT that rests is PARTIALLY_FILLED; partially
filled MARKET ends CANCELED; an untouched resting LIMIT is NEW.
"""

from __future__ import annotations

import dataclasses

from matching_engine_tpu.proto import pb2

NEW = pb2.OrderUpdate.Status.NEW
PARTIALLY_FILLED = pb2.OrderUpdate.Status.PARTIALLY_FILLED
FILLED = pb2.OrderUpdate.Status.FILLED
CANCELED = pb2.OrderUpdate.Status.CANCELED
REJECTED = pb2.OrderUpdate.Status.REJECTED

# Collapsed (order_type, tif) codes — MUST match kernel.py's lane encoding
# (pinned by tests/test_tif.py); defined here too so the oracle stays
# importable without jax.
LIMIT_IOC, LIMIT_FOK, MARKET_FOK = 2, 3, 4


@dataclasses.dataclass(frozen=True)
class Fill:
    taker_oid: int
    maker_oid: int
    price_q4: int
    quantity: int


@dataclasses.dataclass(frozen=True)
class OrderResult:
    oid: int
    status: int  # pb2.OrderUpdate.Status value
    filled: int
    remaining: int
    rested: bool
    fills: tuple[Fill, ...]


@dataclasses.dataclass
class _Resting:
    oid: int
    price_q4: int
    qty: int
    seq: int
    owner: int = 0  # self-trade-prevention identity (0 = none)


class OracleBook:
    """Single-symbol CLOB with fixed per-side capacity.

    With `levels`/`level_fifo` set, capacity is LEVEL-STRUCTURED (the
    kernel_levels.py contract): a side holds at most `levels` distinct
    live prices, each with at most `level_fifo` resting orders; a rest at
    a new price with the level directory full, or at an existing price
    whose FIFO is full, REJECTS even below total capacity. Matching
    semantics are identical either way."""

    def __init__(self, capacity: int = 256, levels: int | None = None,
                 level_fifo: int | None = None):
        self.capacity = capacity
        self.levels = levels
        self.level_fifo = level_fifo
        assert (levels is None) == (level_fifo is None)
        self.bids: list[_Resting] = []
        self.asks: list[_Resting] = []
        self.next_seq = 0

    # -- internals ---------------------------------------------------------

    def _opposite(self, side: int) -> list[_Resting]:
        return self.asks if side == pb2.BUY else self.bids

    def _own(self, side: int) -> list[_Resting]:
        return self.bids if side == pb2.BUY else self.asks

    def _side_full(self, own: list[_Resting], price_q4: int) -> bool:
        """Would a rest at `price_q4` exceed this side's capacity?"""
        if self.levels is None:
            return len(own) >= self.capacity
        at_level = sum(1 for r in own if r.price_q4 == price_q4)
        if at_level:
            return at_level >= self.level_fifo
        return len({r.price_q4 for r in own}) >= self.levels

    def _priority_sorted(self, side_of_resting: int, resting: list[_Resting]):
        # Lowest ask first / highest bid first; FIFO (seq) within a level.
        if side_of_resting == pb2.SELL:
            return sorted(resting, key=lambda r: (r.price_q4, r.seq))
        return sorted(resting, key=lambda r: (-r.price_q4, r.seq))

    # -- operations --------------------------------------------------------

    def submit(
        self, oid: int, side: int, order_type: int, price_q4: int, qty: int,
        owner: int = 0,
    ) -> OrderResult:
        assert qty > 0
        opp_side = pb2.SELL if side == pb2.BUY else pb2.BUY
        opp = self._opposite(side)
        px_any = order_type in (pb2.MARKET, MARKET_FOK)
        is_fok = order_type in (LIMIT_FOK, MARKET_FOK)
        never_rests = order_type != pb2.LIMIT
        remaining = qty
        fills: list[Fill] = []

        def crosses(maker: _Resting) -> bool:
            if px_any:
                return True
            if side == pb2.BUY:
                return maker.price_q4 <= price_q4
            return maker.price_q4 >= price_q4

        # Fill-or-kill: all-or-nothing against the liquidity this taker is
        # actually eligible for (price-crossing, live, not self-owned).
        if is_fok:
            avail = sum(
                m.qty for m in opp
                if m.qty > 0 and crosses(m)
                and not (owner and m.owner == owner))
            if avail < qty:
                return OrderResult(oid, CANCELED, 0, qty, False, ())

        for maker in self._priority_sorted(opp_side, opp):
            if remaining == 0:
                break
            if maker.qty == 0:
                continue
            if owner and maker.owner == owner:
                continue  # self-trade prevention: skip own resting orders
            if not crosses(maker):
                break  # priority-sorted: nothing further can cross
            take = min(remaining, maker.qty)
            maker.qty -= take
            remaining -= take
            fills.append(Fill(oid, maker.oid, maker.price_q4, take))

        # Drop emptied makers.
        self.asks = [r for r in self.asks if r.qty > 0]
        self.bids = [r for r in self.bids if r.qty > 0]

        filled = qty - remaining
        if remaining == 0:
            return OrderResult(oid, FILLED, filled, 0, False, tuple(fills))

        if never_rests:
            # MARKET and IOC remainders cancel; a FOK that passed the
            # all-or-nothing gate cannot reach here.
            return OrderResult(oid, CANCELED, filled, remaining, False, tuple(fills))

        # STP skip-then-cancel: a remainder whose rest would cross the
        # client's OWN opposite order cancels instead of standing the
        # book crossed (kernel._match_one's self_blocked twin).
        if owner:
            crosses_self = any(
                r.owner == owner and (
                    r.price_q4 <= price_q4 if side == pb2.BUY
                    else r.price_q4 >= price_q4)
                for r in self._opposite(side))
            if crosses_self:
                return OrderResult(oid, CANCELED, filled, remaining, False,
                                   tuple(fills))

        own = self._own(side)
        if self._side_full(own, price_q4):
            return OrderResult(oid, REJECTED, filled, remaining, False, tuple(fills))
        own.append(_Resting(oid, price_q4, remaining, self.next_seq, owner))
        self.next_seq += 1
        status = PARTIALLY_FILLED if filled > 0 else NEW
        return OrderResult(oid, status, filled, remaining, True, tuple(fills))

    def rest(self, oid: int, side: int, price_q4: int, qty: int,
             owner: int = 0) -> OrderResult:
        """OP_REST twin: rest without matching (auction accumulation —
        the book may stand crossed afterwards). NEW on success, REJECTED
        when the side is at capacity."""
        assert qty > 0
        own = self._own(side)
        if self._side_full(own, price_q4):
            return OrderResult(oid, REJECTED, 0, qty, False, ())
        own.append(_Resting(oid, price_q4, qty, self.next_seq, owner))
        self.next_seq += 1
        return OrderResult(oid, NEW, 0, qty, True, ())

    def auction(self) -> tuple[int, int, list[Fill]]:
        """Call-auction uncross (oracle twin of engine/auction.py).

        Returns (clearing_price_q4, executed_qty, fills); (0, 0, []) when
        the book cannot cross. Rules: p* maximizes executable volume
        min(demand, supply) over the resting prices, ties minimize the
        imbalance |demand - supply|, remaining ties take the LOWEST price;
        each side allocates in price-time priority up to the executed
        volume; bilateral records pair the two sides' fill intervals on
        the executed-volume line (taker = bid, maker = ask, price = p*)."""
        cands = sorted({r.price_q4 for r in self.bids}
                       | {r.price_q4 for r in self.asks})
        best = None  # (executed, imbalance, price)
        for p in cands:
            d = sum(r.qty for r in self.bids if r.price_q4 >= p)
            s = sum(r.qty for r in self.asks if r.price_q4 <= p)
            key = (-min(d, s), abs(d - s), p)
            if best is None or key < best:
                best = key
        if best is None or -best[0] <= 0:
            return 0, 0, []
        q, p_star = -best[0], best[2]

        def allocate(resting, sorted_side):
            out, taken = [], 0
            for r in self._priority_sorted(sorted_side, resting):
                if taken >= q:
                    break
                take = min(r.qty, q - taken)
                out.append((r, taken, take))  # (order, interval start, qty)
                taken += take
            return out

        bid_alloc = allocate(
            [r for r in self.bids if r.price_q4 >= p_star], pb2.BUY)
        ask_alloc = allocate(
            [r for r in self.asks if r.price_q4 <= p_star], pb2.SELL)

        fills: list[Fill] = []
        for b, b_lo, b_q in bid_alloc:
            for a, a_lo, a_q in ask_alloc:
                ov = min(b_lo + b_q, a_lo + a_q) - max(b_lo, a_lo)
                if ov > 0:
                    fills.append(Fill(b.oid, a.oid, p_star, ov))
        for r, _, take in bid_alloc + ask_alloc:
            r.qty -= take
        self.bids = [r for r in self.bids if r.qty > 0]
        self.asks = [r for r in self.asks if r.qty > 0]
        return p_star, q, fills

    def cancel(self, oid: int) -> OrderResult:
        for side_list in (self.bids, self.asks):
            for r in side_list:
                if r.oid == oid:
                    side_list.remove(r)
                    return OrderResult(oid, CANCELED, 0, r.qty, False, ())
        return OrderResult(oid, REJECTED, 0, 0, False, ())

    def amend(self, oid: int, new_qty: int) -> OrderResult:
        """Priority-preserving quantity reduction (kernel OP_AMEND twin):
        only a strict reduction to a positive quantity succeeds; the
        order keeps its seq (time priority) and price. Returns NEW with
        the new remaining on success, REJECTED otherwise."""
        for side_list in (self.bids, self.asks):
            for r in side_list:
                if r.oid == oid:
                    if 0 < new_qty < r.qty:
                        r.qty = new_qty
                        return OrderResult(oid, NEW, 0, new_qty, True, ())
                    return OrderResult(oid, REJECTED, 0, 0, False, ())
        return OrderResult(oid, REJECTED, 0, 0, False, ())

    # -- views -------------------------------------------------------------

    def best_bid(self) -> tuple[int, int] | None:
        """(price_q4, total size at that price) or None."""
        if not self.bids:
            return None
        p = max(r.price_q4 for r in self.bids)
        return p, sum(r.qty for r in self.bids if r.price_q4 == p)

    def best_ask(self) -> tuple[int, int] | None:
        if not self.asks:
            return None
        p = min(r.price_q4 for r in self.asks)
        return p, sum(r.qty for r in self.asks if r.price_q4 == p)

    def snapshot(self):
        """Canonical book state: priority-sorted (oid, price, qty, seq) per side.

        Used by parity tests to compare against the device book.
        """
        bids = [
            (r.oid, r.price_q4, r.qty, r.seq)
            for r in self._priority_sorted(pb2.BUY, self.bids)
        ]
        asks = [
            (r.oid, r.price_q4, r.qty, r.seq)
            for r in self._priority_sorted(pb2.SELL, self.asks)
        ]
        return bids, asks
