"""Venue axis: every engine primitive, vmapped over V independent books.

The kernels in this package step ONE venue — a [S, CAP] book batch per
formulation. The many-venue gym (gym/env.py, ROADMAP Open item 5) steps
V independent venues `[V, S, CAP]` in one jit'd scan, JAX-LOB style
(arXiv:2308.13289): same compiled program, a leading venue axis on every
buffer. This module is the engine-side seam — thin `jax.vmap` wrappers
over the existing single-venue primitives, so the venue axis can never
drift from the single-venue semantics (the gym's parity oracle is
literally "V-venue run == V single-venue runs, bit for bit", pinned by
tests/test_gym.py on all three kernel formulations).

Everything here is pure jnp/vmap — safe inside jit/scan bodies, no jit
roots of its own (the gym owns the jit boundary and its donation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.auction import (
    apply_uncross,
    uncross_and_records,
)
from matching_engine_tpu.engine.book import BookBatch, EngineConfig, OrderBatch
from matching_engine_tpu.engine.kernel import _top_of_book, engine_step_core

I32 = jnp.int32


def venue_step_core(cfg: EngineConfig, books: BookBatch,
                    orders: OrderBatch):
    """One match pass for every venue: engine_step_core vmapped over the
    leading venue axis. `books` fields are [V, S, CAP] ([V, S] for
    next_seq), `orders` fields [V, S, B]. Returns (new_books, raw) with
    raw = (status, filled, remaining, f_oid, f_qty, f_price), each
    carrying the [V] axis in front of the single-venue shapes. Dispatches
    on cfg.kernel exactly like the single-venue entry — all three
    formulations are venue-vmappable (pure jnp inside)."""
    return jax.vmap(lambda b, o: engine_step_core(cfg, b, o))(books, orders)


def venue_top_of_book(books: BookBatch):
    """Per-venue TOB: (best_bid, bid_size, best_ask, ask_size), [V, S]
    each (0 where the side is empty — the single-venue masking rule)."""
    bb, bs = jax.vmap(lambda p, q: _top_of_book(p, q, True))(
        books.bid_price, books.bid_qty)
    ba, az = jax.vmap(lambda p, q: _top_of_book(p, q, False))(
        books.ask_price, books.ask_qty)
    return bb, bs, ba, az


def venue_uncross(cfg: EngineConfig, books: BookBatch, mask: jax.Array):
    """Call-auction uncross, one venue at a time under vmap: `mask` is
    [V, S] bool (which symbols of which venues uncross this step — the
    gym raises a whole venue's row at its call phases' closing steps).

    Returns (new_books, p_star [V, S], exec_hi [V, S], exec_lo [V, S],
    aborted [V]). The abort rule is PER VENUE and matches
    auction.auction_step exactly: if a venue's bilateral record count
    would overflow cfg.max_fills, that venue applies NOTHING (books
    stand, exec/p_star zeroed) while the other venues uncross normally —
    bit-identical to running auction_step per venue. Executed volume
    comes back as base-2^15 limbs (exec_hi << 15) + exec_lo like the
    single-venue AuctionOutput; recombine on host at int64."""
    (fill_b, fill_a, p_star, exec_hi, exec_lo, _rt, _rm, _rq,
     rec_counts) = jax.vmap(
        lambda b, m: uncross_and_records(cfg, b, m))(books, mask)
    total = jnp.sum(rec_counts, axis=1)
    aborted = total > cfg.max_fills
    apply = mask & jnp.logical_not(aborted)[:, None]
    new_books = jax.vmap(
        lambda b, fb, fa, ap: apply_uncross(
            b, fb, fa, ap, kernel=cfg.kernel, levels=cfg.levels))(
        books, fill_b, fill_a, apply)
    ok = jnp.logical_not(aborted)[:, None]
    zero = jnp.zeros((), I32)
    return (new_books,
            jnp.where(ok, p_star, zero),
            jnp.where(ok, exec_hi, zero),
            jnp.where(ok, exec_lo, zero),
            aborted)
