"""The jit'd match kernel: price-time-priority CLOB matching in fixed shapes.

This is the TPU-first replacement for the hot path the reference never built
(its entire "engine" is one SQLite INSERT under a global mutex —
src/server/matching_engine_service.cpp:100-104, SURVEY.md §3.2). Design:

- **No sorting, no data-dependent loops.** For one incoming order, fills are
  allocated with a masked priority comparison matrix: `better[k, j]` says
  resting order k has strictly higher price-time priority than j (better
  price, or same price and earlier seq). The quantity resting *ahead* of j is
  a masked matvec `ahead_j = sum_k better[k,j] * elig_k * qty_k`, and
  `fill_j = clip(Q - ahead_j, 0, qty_j)` — exactly the allocation a
  sequential sweep produces, but as dense [CAP, CAP] int32 vector ops the
  VPU eats whole. (seqs are unique per book, so priority is a strict total
  order and filled slots form a priority prefix.)
- **Sequential within a symbol, parallel across symbols.** Orders for one
  symbol apply in batch order via `lax.scan` (a later order can match an
  earlier one's resting remainder); `vmap` runs every symbol's scan in
  parallel (SURVEY.md §7 "Hard parts": sequential dependence within a batch).
- **Compact fill log.** Each step scatters its fills to priority-rank slots
  (rank = count of eligible makers ahead — unique, prefix-dense, so no sort
  is needed there either); after the scan a global cumsum-compaction packs
  all [S, B, CAP] potential fill records into one bounded [max_fills] buffer
  so the device->host transfer is O(actual fills), not O(S*B*CAP).
- **Integer-only.** All match math is int32; results are bit-identical to
  the host oracle (engine/oracle.py) — enforced by tests/test_kernel_parity.

Matching semantics are the ones this framework defines (see oracle.py
docstring); statuses use proto OrderUpdate.Status values.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    batch_from_lanes,
    OrderBatch,
    StepOutput,
)

# proto OrderUpdate.Status values (pinned; side.py asserts the enum layout).
NEW, PARTIALLY_FILLED, FILLED, CANCELED, REJECTED = 0, 1, 2, 3, 4
NOOP_STATUS = -1

# OP_REST: rest WITHOUT matching — the call-auction accumulation op
# (engine/auction.py): books may stand crossed until an uncross clears
# them. Identical to OP_SUBMIT except the maker scan never runs.
OP_NOOP, OP_SUBMIT, OP_CANCEL, OP_REST = 0, 1, 2, 3
# Priority-preserving quantity reduction (venue "amend down"): the qty
# lane carries the NEW remaining quantity; the resting order keeps its
# price, seq, and therefore its place in the time-priority queue. Any
# other modification (qty up, price change) re-prices priority and is a
# cancel+submit at the service layer, never an in-place edit.
OP_AMEND = 4
# Device otype lane: the wire's (order_type, time_in_force) pair collapses
# to one small code so the dispatch layout stays [S, B, 7] (no extra lane).
# LIMIT = GTC limit (the only code that RESTS); MARKET is inherently IOC.
# LIMIT_IOC matches at the limit then cancels the remainder; LIMIT_FOK /
# MARKET_FOK are all-or-nothing (fill the full quantity immediately or
# cancel untouched). The service edge maps proto tif -> these codes
# (server/service.py); the reference's wire contract has no tif field —
# this is an additive extension (proto field 8).
LIMIT, MARKET, LIMIT_IOC, LIMIT_FOK, MARKET_FOK = 0, 1, 2, 3, 4
BUY, SELL = 1, 2


class _SymBook(NamedTuple):
    """One symbol's book slices inside the vmap'd scan body (field order
    mirrors BookBatch so `_SymBook(*book[:-1], ...)` stays valid)."""

    bid_price: jax.Array
    bid_qty: jax.Array
    bid_oid: jax.Array
    bid_seq: jax.Array
    bid_owner: jax.Array
    ask_price: jax.Array
    ask_qty: jax.Array
    ask_oid: jax.Array
    ask_seq: jax.Array
    ask_owner: jax.Array
    next_seq: jax.Array


def _match_one(book: _SymBook, order):
    """Apply one order to one book. All inputs per-symbol (no S axis).

    Returns (book', (status, filled, remaining, fill_oid[CAP], fill_qty[CAP],
    fill_price[CAP])) where fill arrays are priority-rank-indexed (slot r =
    r-th best maker touched; zeros past the last fill).
    """
    op, side, otype, price, qty, oid, owner = (
        order.op, order.side, order.otype, order.price, order.qty,
        order.oid, order.owner,
    )
    is_submit = op == OP_SUBMIT
    is_cancel = op == OP_CANCEL
    is_rest = op == OP_REST          # auction accumulation: never matches
    is_amend = op == OP_AMEND        # qty-down in place: priority kept
    is_submit_like = is_submit | is_rest
    is_buy = side == BUY
    # px_any: price-indifferent sweep (MARKET-style eligibility); is_fok:
    # all-or-nothing; never_rests: every code but plain LIMIT cancels its
    # remainder instead of resting.
    px_any = (otype == MARKET) | (otype == MARKET_FOK)
    is_fok = (otype == LIMIT_FOK) | (otype == MARKET_FOK)
    never_rests = px_any | (otype == LIMIT_IOC) | (otype == LIMIT_FOK)

    # ---- opposite side (maker candidates), via where-selects -------------
    opp_price = jnp.where(is_buy, book.ask_price, book.bid_price)
    opp_qty = jnp.where(is_buy, book.ask_qty, book.bid_qty)
    opp_oid = jnp.where(is_buy, book.ask_oid, book.bid_oid)
    opp_seq = jnp.where(is_buy, book.ask_seq, book.bid_seq)
    opp_owner = jnp.where(is_buy, book.ask_owner, book.bid_owner)

    # Direction-normalized price key: smaller = better priority for the
    # maker. Buying consumes asks (low price good); selling consumes bids
    # (high price good, so negate).
    key = jnp.where(is_buy, opp_price, -opp_price)

    price_ok = jnp.where(is_buy, opp_price <= price, opp_price >= price)
    # Self-trade prevention (skip-then-cancel): a taker never crosses a
    # maker of the same nonzero owner — the skipped maker keeps its place
    # for other takers — and a LIMIT remainder that would REST crossing
    # the client's own opposite order is canceled instead (resting it
    # would stand the book crossed in continuous trading, which the
    # recovery safety net relies on never happening). OP_REST bypasses
    # both (auction accumulation crosses deliberately).
    not_self = (owner == 0) | (opp_owner != owner)
    elig = (opp_qty > 0) & (px_any | price_ok) & is_submit & not_self
    self_blocked = is_submit & (~never_rests) & jnp.any(
        (opp_qty > 0) & price_ok & (owner != 0) & (opp_owner == owner))

    # better[k, j]: maker k strictly ahead of maker j in price-time priority.
    better = (key[:, None] < key[None, :]) | (
        (key[:, None] == key[None, :]) & (opp_seq[:, None] < opp_seq[None, :])
    )
    elig_qty = jnp.where(elig, opp_qty, 0)
    ahead = jnp.sum(jnp.where(better, elig_qty[:, None], 0), axis=0)

    # Fill-or-kill gate: all-or-nothing — if the eligible liquidity can't
    # cover the full quantity, no fill happens at all. The sum is exact:
    # matrix books are capacity <= 1024 < 2^31 / MAX_QUANTITY (book.py).
    fok_fail = is_fok & (jnp.sum(elig_qty) < qty)

    take_q = jnp.where(is_submit_like & ~fok_fail, qty, 0)
    fill = jnp.where(elig, jnp.clip(take_q - ahead, 0, opp_qty), 0)
    filled_total = jnp.sum(fill)
    remaining = jnp.where(is_submit_like, qty, 0) - filled_total

    new_opp_qty = opp_qty - fill

    # Priority rank of each eligible maker (unique: seqs are unique). Filled
    # slots are a priority prefix, so rank doubles as the output slot.
    rank = jnp.sum(jnp.where(better & elig[:, None] & elig[None, :], 1, 0), axis=0)
    has_fill = fill > 0
    cap = fill.shape[0]
    slot = jnp.where(has_fill, rank, cap)  # cap = trash slot
    fill_oid = jnp.zeros((cap + 1,), I32).at[slot].set(jnp.where(has_fill, opp_oid, 0))[:cap]
    fill_qty_out = jnp.zeros((cap + 1,), I32).at[slot].set(fill)[:cap]
    fill_price = jnp.zeros((cap + 1,), I32).at[slot].set(jnp.where(has_fill, opp_price, 0))[:cap]

    # ---- own side: rest a LIMIT remainder, or cancel a resting order -----
    own_price = jnp.where(is_buy, book.bid_price, book.ask_price)
    own_qty = jnp.where(is_buy, book.bid_qty, book.ask_qty)
    own_oid = jnp.where(is_buy, book.bid_oid, book.ask_oid)
    own_seq = jnp.where(is_buy, book.bid_seq, book.ask_seq)
    own_owner = jnp.where(is_buy, book.bid_owner, book.ask_owner)

    do_rest = is_submit_like & (~never_rests) & (remaining > 0) & ~self_blocked
    free = own_qty == 0
    has_free = jnp.any(free)
    slot_idx = jnp.argmax(free)  # first free slot
    rested = do_rest & has_free

    idx = jnp.arange(cap)
    at_slot = rested & (idx == slot_idx)
    own_price = jnp.where(at_slot, price, own_price)
    own_qty = jnp.where(at_slot, remaining, own_qty)
    own_oid = jnp.where(at_slot, oid, own_oid)
    own_seq = jnp.where(at_slot, book.next_seq, own_seq)
    own_owner = jnp.where(at_slot, owner, own_owner)
    next_seq = book.next_seq + jnp.where(rested, 1, 0).astype(I32)

    cancel_mask = is_cancel & (own_oid == oid) & (own_qty > 0)
    cancel_qty = jnp.sum(jnp.where(cancel_mask, own_qty, 0))
    cancel_ok = jnp.any(cancel_mask)
    own_qty = jnp.where(cancel_mask, 0, own_qty)

    # Amend down: reduce the target's quantity in place (price/seq — and
    # with them time priority — untouched). Only a strict reduction to a
    # positive quantity is valid; anything else REJECTs (qty up or price
    # moves lose priority and belong to cancel+submit).
    amend_mask = is_amend & (own_oid == oid) & (own_qty > 0)
    amend_feasible = amend_mask & (qty > 0) & (qty < own_qty)
    amend_ok = jnp.any(amend_feasible)
    own_qty = jnp.where(amend_feasible, qty, own_qty)

    # ---- write back (buy: opp=asks/own=bids; sell: the reverse) ----------
    new_book = _SymBook(
        bid_price=jnp.where(is_buy, own_price, opp_price),
        bid_qty=jnp.where(is_buy, own_qty, new_opp_qty),
        bid_oid=jnp.where(is_buy, own_oid, opp_oid),
        bid_seq=jnp.where(is_buy, own_seq, opp_seq),
        bid_owner=jnp.where(is_buy, own_owner, opp_owner),
        ask_price=jnp.where(is_buy, opp_price, own_price),
        ask_qty=jnp.where(is_buy, new_opp_qty, own_qty),
        ask_oid=jnp.where(is_buy, opp_oid, own_oid),
        ask_seq=jnp.where(is_buy, opp_seq, own_seq),
        ask_owner=jnp.where(is_buy, opp_owner, own_owner),
        next_seq=next_seq,
    )

    # ---- status ----------------------------------------------------------
    submit_status = jnp.where(
        remaining == 0,
        FILLED,
        jnp.where(
            # Immediate-or-cancel remainders: MARKET/IOC/FOK always (none
            # of them rest — a failed FOK cancels untouched); a LIMIT
            # whose rest would self-cross (STP skip-then-cancel).
            never_rests | self_blocked,
            CANCELED,
            jnp.where(
                rested,
                jnp.where(filled_total > 0, PARTIALLY_FILLED, NEW),
                REJECTED,  # limit remainder but book side full
            ),
        ),
    )
    cancel_status = jnp.where(cancel_ok, CANCELED, REJECTED)
    amend_status = jnp.where(amend_ok, NEW, REJECTED)
    status = jnp.where(
        is_submit_like,
        submit_status,
        jnp.where(
            is_cancel, cancel_status,
            jnp.where(is_amend, amend_status, NOOP_STATUS)),
    ).astype(I32)
    out_remaining = jnp.where(
        is_submit_like, remaining,
        jnp.where(is_cancel, cancel_qty,
                  jnp.where(is_amend & amend_ok, qty, 0))
    ).astype(I32)

    return new_book, (
        status,
        filled_total.astype(I32),
        out_remaining,
        fill_oid,
        fill_qty_out,
        fill_price,
    )


def _sym_scan(book: _SymBook, orders):
    """Scan one symbol's B orders through its book, in batch order."""

    def step(b, o):
        return _match_one(b, o)

    return jax.lax.scan(step, book, orders)


def _top_of_book(price, qty, best_is_max):
    """[S] best price + size at best, masked on qty>0; zeros when empty.

    At venue-depth capacities (capacity * MAX_QUANTITY >= 2^31, sorted
    kernel only) the size sum SATURATES at 2^30-1 instead of wrapping —
    a price level deeper than a billion units reports the clamp, never a
    negative size (documented in DESIGN.md 6d)."""
    from matching_engine_tpu.domain.order import MAX_QUANTITY

    live = qty > 0
    any_live = jnp.any(live, axis=1)
    if best_is_max:
        best = jnp.max(jnp.where(live, price, jnp.iinfo(I32).min), axis=1)
    else:
        best = jnp.min(jnp.where(live, price, jnp.iinfo(I32).max), axis=1)
    best = jnp.where(any_live, best, 0)
    at_best = jnp.where(live & (price == best[:, None]), qty, 0)
    if qty.shape[1] * MAX_QUANTITY >= 2**31:
        sat = jnp.int32((1 << 30) - 1)
        size = jax.lax.associative_scan(
            lambda a, b: jnp.minimum(a + b, sat), at_best, axis=1)[:, -1]
    else:
        size = jnp.sum(at_best, axis=1)
    size = jnp.where(any_live, size, 0)
    return best.astype(I32), size.astype(I32)


def apply_halt_mask(orders: OrderBatch, halted) -> OrderBatch:
    """Trading-halt hook: suppress every op of the halted symbols
    (`halted` is a [S] bool mask — or [V, S] when the orders carry a
    leading venue axis, engine/venues.py) to OP_NOOP. The kernel ignores
    NOOP rows, so a halted symbol's book stands frozen — no submits, no
    cancels, no fills — while the other symbols keep trading in the same
    dispatch. This is the per-symbol halt primitive the scenario sim
    (sim/scenarios.py) drives for halt phases, hot-symbol gating, and
    burst off-periods; pure jnp, safe inside jit/scan bodies."""
    return orders._replace(
        op=jnp.where(halted[..., None], OP_NOOP, orders.op))


def engine_step_core(cfg: EngineConfig, book: BookBatch, orders: OrderBatch):
    """The raw match pass, WITHOUT the finalize epilogue: (new_book,
    (status, filled, remaining, f_oid, f_qty, f_price)), fill arrays still
    the [S, B, CAP] priority-rank tensor. Shared by the single-step entry
    (which finalizes into a StepOutput) and the megadispatch scan body
    (which compacts per wave instead — engine_step_mega). Dispatches on
    cfg.kernel like engine_step_impl."""
    if cfg.kernel == "sorted":
        from matching_engine_tpu.engine.kernel_sorted import (
            engine_step_sorted_core,
        )

        return engine_step_sorted_core(cfg, book, orders)
    if cfg.kernel == "levels":
        from matching_engine_tpu.engine.kernel_levels import (
            engine_step_levels_core,
        )

        return engine_step_levels_core(cfg, book, orders)
    sym_book = _SymBook(*book[:-1], next_seq=book.next_seq)
    # vmap over the symbol axis; scan over the batch axis inside.
    new_sym_book, raw = jax.vmap(_sym_scan)(sym_book, orders)
    return BookBatch(*new_sym_book[:-1], next_seq=new_sym_book.next_seq), raw


def engine_step_impl(cfg: EngineConfig, book: BookBatch, orders: OrderBatch):
    """Un-jitted engine step body (shared by the jit'd single-device entry
    point below and the shard_map-wrapped multi-chip step in
    parallel/sharding.py, where each shard runs this on its symbol slice).

    A hand-written Pallas variant of the match loop was built, proven
    bit-identical, measured ~700x SLOWER than this XLA formulation, and
    retired — see docs/DESIGN.md §6 for the analysis (integer control-flow
    over VPU lanes is exactly what XLA already schedules well; the
    priority-matrix broadcasts relayout poorly under Mosaic).

    cfg.kernel selects the formulation at trace time: "matrix" (this
    file's [CAP, CAP] priority matrix), "sorted" (kernel_sorted.py's
    O(CAP) dense-sorted-prefix variant) or "levels" (kernel_levels.py's
    price-level [L, F] FIFO-row variant) — every serving path (packed
    dense, sparse, shard_map mesh) dispatches through here, so the
    config knob covers them all."""
    new_book, (status, filled, remaining, f_oid, f_qty, f_price) = (
        engine_step_core(cfg, book, orders))
    return new_book, finalize_step(
        cfg, new_book, orders, status, filled, remaining, f_oid, f_qty, f_price
    )


def finalize_step(
    cfg: EngineConfig,
    new_book: BookBatch,
    orders: OrderBatch,
    status,
    filled,
    remaining,
    f_oid,
    f_qty,
    f_price,
) -> StepOutput:
    """Shared epilogue: compact the [S, B, CAP] potential-fill tensor into
    the bounded global fill log and compute post-step top-of-book."""
    # [S, B, CAP] -> flat, ordered (symbol, batch position, priority rank).
    # ONE compaction definition (compact_rows, shared with the mega scan's
    # per-wave fill logs) so the serial and stacked fill logs can't drift.
    s, b, cap = f_qty.shape
    flat_qty = f_qty.reshape(-1)
    mask = flat_qty > 0
    total = jnp.sum(mask)
    n = cfg.max_fills
    sym_ids = jnp.broadcast_to(jnp.arange(s, dtype=I32)[:, None, None], (s, b, cap))
    taker = jnp.broadcast_to(orders.oid[:, :, None], (s, b, cap))
    (fill_sym, fill_taker, fill_maker, fill_price, fill_qty), fill_count = (
        compact_rows(
            mask,
            (sym_ids.reshape(-1), taker.reshape(-1), f_oid.reshape(-1),
             f_price.reshape(-1), flat_qty),
            n,
        ))
    best_bid, bid_size = _top_of_book(new_book.bid_price, new_book.bid_qty, True)
    best_ask, ask_size = _top_of_book(new_book.ask_price, new_book.ask_qty, False)
    return StepOutput(
        status=status,
        filled=filled,
        remaining=remaining,
        fill_sym=fill_sym,
        fill_taker_oid=fill_taker,
        fill_maker_oid=fill_maker,
        fill_price=fill_price,
        fill_qty=fill_qty,
        fill_count=fill_count,
        fill_overflow=total > n,
        best_bid=best_bid,
        bid_size=bid_size,
        best_ask=best_ask,
        ask_size=ask_size,
    )


# Single-device entry point. The book argument is donated: the update is
# in-place in HBM, the book never round-trips to host (SURVEY.md §7
# "Host<->device pipeline").
engine_step = jax.jit(engine_step_impl, static_argnums=0, donate_argnums=1)


# Leading fill rows inlined into the packed small vector: a dispatch whose
# fill count fits is decoded from ONE readback (the second, full fill-log
# fetch costs another network round trip on a tunneled chip — ~64ms
# measured, independent of size).
FILL_INLINE = 256


def fill_inline_count(cfg: EngineConfig) -> int:
    return min(cfg.max_fills, FILL_INLINE)


class PackedStepOutput(NamedTuple):
    """StepOutput packed for minimal host readback round-trips (the dense
    analog of sparse.SparseStepOutput — on a tunneled chip every transfer
    is a network round trip, so reading ~14 arrays per step costs ~14 RTTs
    where these cost ONE for any dispatch with <= FILL_INLINE fills, two
    otherwise):

    small: [3*S*B + 4*S + 2 + 5*L] int32 (L = fill_inline_count(cfg)) =
           status | filled | remaining (each [S, B], ravelled) ++
           best_bid | bid_size | best_ask | ask_size (each [S]) ++
           [fill_count, fill_overflow] ++ fills[:, :L] ravelled.
    fills: [5, max_fills] int32, rows in harness.decode_fills column order
           (sym, taker_oid, maker_oid, price, qty) — fetched only when
           fill_count > L.
    """

    small: jax.Array
    fills: jax.Array


def compact_rows(mask, cols, out_len: int):
    """Prefix-sum gather compaction: pack the masked entries of the 1-D
    `cols` arrays to the front of [out_len] buffers (device order
    preserved; zeros past the packed prefix). Returns (packed_cols,
    count) with count = min(popcount(mask), out_len); entries past
    out_len land in the trash slot exactly like the fill-log compaction.
    Pure jnp — safe under vmap and inside scan bodies (the megadispatch
    wave body uses it for both completions and fills)."""
    pos = jnp.cumsum(mask) - 1
    dest = jnp.where(mask & (pos < out_len), pos, out_len)
    packed = tuple(
        jnp.zeros((out_len + 1,), I32).at[dest].set(
            jnp.where(mask, c, 0))[:out_len]
        for c in cols
    )
    return packed, jnp.minimum(jnp.sum(mask), out_len).astype(I32)


def mega_result_cap(cfg: EngineConfig, max_ops: int) -> int:
    """Static compacted-completion capacity (rows per wave) for one mega
    dispatch: smallest power-of-two >= the deepest wave's real-op count,
    clamped to the full grid. The host KNOWS every wave's op count (it
    built the lane arrays), so the buffer never truncates; bucketing
    keeps the jit cache at ~log2(S*B) programs instead of one per count."""
    cap = cfg.num_symbols * cfg.batch
    r = 64
    while r < max_ops:
        r <<= 1
    return min(r, cap)


def mega_fill_inline(cfg: EngineConfig, rcap: int) -> int:
    """Inline fill rows per WAVE in the mega readback. Sized with the
    dispatch (>= the compacted-result bucket, floor 64) instead of the
    flat FILL_INLINE: M waves each carry an inline segment, so a fixed
    256 would dominate the packed vector at small shapes — exactly the
    padding the compaction exists to cut. A wave filling more than this
    pays the one full-buffer fetch, same policy as the packed step."""
    return min(fill_inline_count(cfg), max(64, rcap))


class MegaStepOutput(NamedTuple):
    """One megadispatch scan's packed readback (M waves amortized over a
    single XLA dispatch). Decode with harness.decode_step_mega.

    small: [3M + 4S + M*5*R + M*5*L] int32 (R = mega_result_cap bucket,
           L = mega_fill_inline(cfg, R)) =
           res_counts[M] | fill_counts[M] | fill_overflows[M] ++
           best_bid | bid_size | best_ask | ask_size (each [S], FINAL
           book — identical to the last wave's top-of-book) ++
           compacted completions [M, 5, R] ravelled (rows oid | sym |
           status | filled | remaining, packed device-order per wave) ++
           inline fill segments [M, 5, L] ravelled.
    fills: [M, 5, max_fills] int32 per-wave full fill logs (decode_fills
           column order) — fetched only when some wave's fill count
           exceeds the inline segment.

    The completion compaction is the readback-bytes win: the serial
    packed step reads 3*S*B result planes per wave even when a handful
    of rows carry real ops; this reads 5*R per wave plus a fixed header.
    """

    small: jax.Array
    fills: jax.Array


@partial(jax.jit, static_argnums=(0, 3), donate_argnums=1)
def engine_step_mega(cfg: EngineConfig, book: BookBatch, lanes: jax.Array,
                     rcap: int):
    """Megadispatch: ONE jit'd lax.scan over M stacked [S, B, 7] dispatch
    waves (`lanes` is [M, S, B, 7]) on the donated book — one XLA
    dispatch (and one host->device upload) amortized over all M waves,
    with device-side completion compaction so the readback is O(real
    ops), not O(M*S*B). Wave semantics are engine_step_packed applied M
    times in order, bit-identical by construction (same engine_step_core
    body; tests/test_megadispatch.py pins it on both kernels)."""
    n = cfg.max_fills
    lo = mega_fill_inline(cfg, rcap)
    s, b = cfg.num_symbols, cfg.batch

    def wave(bk, wl):
        orders = batch_from_lanes(wl)
        new_bk, (status, filled, remaining, f_oid, f_qty, f_price) = (
            engine_step_core(cfg, bk, orders))
        # Completion compaction: pack the real (non-NOOP) rows to the
        # front in device row-major order — exactly the row order
        # harness.decode_results emits from the full planes.
        mask = orders.op.reshape(-1) != OP_NOOP
        sym_ids = jnp.broadcast_to(
            jnp.arange(s, dtype=I32)[:, None], (s, b)).reshape(-1)
        res_cols, res_count = compact_rows(
            mask,
            (orders.oid.reshape(-1), sym_ids, status.reshape(-1),
             filled.reshape(-1), remaining.reshape(-1)),
            rcap,
        )
        # Fill-log compaction: same contract as finalize_step's global
        # cumsum (flat order = (symbol, batch position, priority rank)).
        cap = f_qty.shape[2]
        flat_qty = f_qty.reshape(-1)
        fmask = flat_qty > 0
        fsym = jnp.broadcast_to(
            jnp.arange(s, dtype=I32)[:, None, None], (s, b, cap)).reshape(-1)
        taker = jnp.broadcast_to(
            orders.oid[:, :, None], (s, b, cap)).reshape(-1)
        fill_cols, _ = compact_rows(
            fmask,
            (fsym, taker, f_oid.reshape(-1), f_price.reshape(-1), flat_qty),
            n,
        )
        total = jnp.sum(fmask)
        return new_bk, (
            jnp.stack(res_cols),            # [5, rcap]
            res_count,
            jnp.stack(fill_cols),           # [5, max_fills]
            jnp.minimum(total, n).astype(I32),
            (total > n).astype(I32),
        )

    new_book, (res, res_counts, fills, fill_counts, overflows) = jax.lax.scan(
        wave, book, lanes)
    # Top-of-book once, on the FINAL book — identical to the serial
    # schedule, whose market data publishes from the last wave's output.
    best_bid, bid_size = _top_of_book(new_book.bid_price, new_book.bid_qty,
                                      True)
    best_ask, ask_size = _top_of_book(new_book.ask_price, new_book.ask_qty,
                                      False)
    small = jnp.concatenate([
        res_counts,
        fill_counts,
        overflows,
        best_bid,
        bid_size,
        best_ask,
        ask_size,
        res.reshape(-1),
        fills[:, :, :lo].reshape(-1),  # static slice
    ])
    return new_book, MegaStepOutput(small=small, fills=fills)


@partial(jax.jit, static_argnums=0, donate_argnums=1)
def engine_step_packed(cfg: EngineConfig, book: BookBatch, lanes: jax.Array):
    """engine_step with ONE [S, B, 7] upload (harness.build_batch_arrays
    layout, unpacked on device) and the output packed into two arrays;
    decode with harness.decode_step_packed. Semantics identical by
    construction (same engine_step_impl)."""
    orders = batch_from_lanes(lanes)
    new_book, out = engine_step_impl(cfg, book, orders)
    fills = jnp.stack([
        out.fill_sym, out.fill_taker_oid, out.fill_maker_oid,
        out.fill_price, out.fill_qty,
    ])
    small = jnp.concatenate([
        out.status.reshape(-1),
        out.filled.reshape(-1),
        out.remaining.reshape(-1),
        out.best_bid,
        out.bid_size,
        out.best_ask,
        out.ask_size,
        jnp.stack([
            out.fill_count.astype(I32),
            out.fill_overflow.astype(I32),
        ]),
        fills[:, :fill_inline_count(cfg)].reshape(-1),  # static slice
    ])
    return new_book, PackedStepOutput(small=small, fills=fills)
