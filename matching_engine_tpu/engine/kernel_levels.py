"""Price-level book kernel: O(levels) match sweep over [L, F] FIFO rows.

The third match formulation (beside kernel.py's [CAP, CAP] priority matrix
and kernel_sorted.py's dense sorted prefix), and the classic design real
venues use ("The World's Fastest Matching Engine Algorithm",
arXiv:2606.01183; KineticSim, arXiv:2606.21784): the book is **price
levels with per-level FIFO queues**, so the hot-path match decision runs
at level granularity — O(L) price comparisons and one [L] prefix sum —
instead of per-resting-order work that grows with raw capacity. At venue
depth (capacity 8192) the matrix kernel is inadmissible ([C, C]
intermediates, int32 sum wrap) and the sorted kernel's per-order
shift/compact sweeps pay O(C) lanes per op whether the book is deep or
empty; here the per-op work concentrates in [L]- and [F]-width vectors
(L, F ~ sqrt-ish factors of C), with only cheap elementwise masks left at
full [L, F] = [C] width.

Layout: the standard BookBatch [S, C] lane planes, with each side's [C]
plane viewed as [L, F] (L = cfg.levels rows, F = C // L FIFO slots per
row). Invariant per side:

- a row is either EMPTY (all qty 0) or carries one price level: its live
  slots form a dense prefix along F, all share one price, in seq (FIFO =
  price-time) order;
- distinct live rows carry distinct prices; row ORDER is arbitrary (no
  shifting level directory — a freed row is simply reused).

Because "qty == 0 marks a free slot and every read masks on qty > 0"
still holds (the book.py core invariant), everything layout-agnostic
composes untouched: init_book, checkpoint encode/restore, snapshot_books,
book_snapshot joins, _top_of_book, crossed_symbols, seq rebasing
(position-preserving), and the wide-sum auction uncross (auction_sorted
priority-sorts its input lanes, so the levels layout needs no special
casing there — only apply_uncross re-packs the row prefixes afterwards).

Capacity semantics (the metered-backpressure contract): a LIMIT remainder
rests iff its price level has FIFO room — an existing row with a free
slot, or a free row for a new price. A full row (F orders at one price)
or a full level directory (L live prices) REJECTS the rest even below
total capacity; the oracle (engine/oracle.py, levels/level_fifo params)
models the identical rule, and the serving layer meters every such
reject as book-capacity backpressure (me_book_capacity_rejects_total).

Everything else — eligibility, STP, FOK, statuses, fill-log rank
contract, finalize_step — is shared with or identical to the sibling
kernels; bit-parity with the level-aware oracle is pinned by
tests/test_kernel_levels.py and the lifecycle-fuzz/megadispatch legs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
    level_shape,
)
from matching_engine_tpu.engine.kernel import (
    BUY,
    CANCELED,
    FILLED,
    LIMIT_FOK,
    LIMIT_IOC,
    MARKET,
    MARKET_FOK,
    NEW,
    NOOP_STATUS,
    OP_AMEND,
    OP_CANCEL,
    OP_REST,
    OP_SUBMIT,
    PARTIALLY_FILLED,
    REJECTED,
    _SymBook,
    finalize_step,
)
IMAX = jnp.iinfo(jnp.int32).max
# Plain Python int, cast at trace time: a module-level jnp constant would
# be created inside whatever jit trace first imports this module (the
# engine_step_core dispatch imports lazily) and leak as a tracer.
_SAT = (1 << 30) - 1


def _cumsum_sat(x, axis, saturate: bool):
    """Inclusive cumsum; saturating min(a+b, 2^30-1) when quantity sums
    could wrap int32 (same exactness argument as kernel_sorted: saturation
    is only reached far past any take quantity, where the fill is zero
    regardless, so the allocation stays exact)."""
    if saturate:
        sat = jnp.int32(_SAT)
        return jax.lax.associative_scan(
            lambda a, b: jnp.minimum(a + b, sat), x, axis=axis)
    return jnp.cumsum(x, axis=axis)


def _compact_rows(qty, *arrays):
    """Re-pack every row's live slots into a dense FIFO prefix (order
    preserved; freed tail slots zero).

    GATHER formulation, not kernel_sorted's cumsum-scatter: output slot
    f of row l reads the (f+1)-th live slot (searchsorted into the
    row's inclusive live-count cumsum). XLA-CPU scatters cost ~40x a
    same-size gather (measured; docs/BENCH_METHOD.md §capacity-sweep),
    and this repack runs twice per op — it is the levels kernel's
    hottest fixed cost at depth."""
    fifo = qty.shape[1]
    keep = (qty > 0).astype(I32)
    cnt = jnp.cumsum(keep, axis=1)                      # inclusive
    j = jnp.arange(1, fifo + 1, dtype=I32)
    src = jax.vmap(lambda c: jnp.searchsorted(c, j, side="left"))(cnt)
    valid = j[None, :] <= cnt[:, -1:]
    src = jnp.clip(src, 0, fifo - 1)

    def g(x):
        return jnp.where(valid, jnp.take_along_axis(x, src, axis=1), 0)

    return (g(qty), *(g(x) for x in arrays))


def _match_one_levels(book: _SymBook, order, lvl: int, fifo: int,
                      saturate: bool):
    """Apply one order to one LEVELS book (see module docstring invariant).
    Same return contract as kernel._match_one; `lvl`/`fifo`/`saturate`
    are trace-time statics."""
    op, side, otype, price, qty, oid, owner = (
        order.op, order.side, order.otype, order.price, order.qty,
        order.oid, order.owner,
    )
    is_submit = op == OP_SUBMIT
    is_cancel = op == OP_CANCEL
    is_rest = op == OP_REST
    is_amend = op == OP_AMEND
    is_submit_like = is_submit | is_rest
    is_buy = side == BUY
    # Same tif collapse as kernel._match_one.
    px_any = (otype == MARKET) | (otype == MARKET_FOK)
    is_fok = (otype == LIMIT_FOK) | (otype == MARKET_FOK)
    never_rests = px_any | (otype == LIMIT_IOC) | (otype == LIMIT_FOK)
    cap = lvl * fifo

    def rows(x):
        return x.reshape(lvl, fifo)

    # ---- opposite side (maker candidates), [L, F] rows -------------------
    opp_price = rows(jnp.where(is_buy, book.ask_price, book.bid_price))
    opp_qty = rows(jnp.where(is_buy, book.ask_qty, book.bid_qty))
    opp_oid = rows(jnp.where(is_buy, book.ask_oid, book.bid_oid))
    opp_seq = rows(jnp.where(is_buy, book.ask_seq, book.bid_seq))
    opp_owner = rows(jnp.where(is_buy, book.ask_owner, book.bid_owner))

    live = opp_qty > 0
    row_live = live[:, 0]          # dense prefix: row live iff slot 0 live
    row_price = opp_price[:, 0]    # the level price (shared across the row)
    # Direction-normalized level key: smaller = better maker priority.
    key = jnp.where(is_buy, row_price, -row_price)
    price_ok_row = jnp.where(is_buy, row_price <= price, row_price >= price)
    not_self = (owner == 0) | (opp_owner != owner)
    elig = live & (px_any | price_ok_row[:, None]) & is_submit & not_self
    self_blocked = is_submit & (~never_rests) & jnp.any(
        live & price_ok_row[:, None] & (owner != 0) & (opp_owner == owner))

    # The O(L) sweep: per-level eligible volume, cumulated in level
    # priority order (argsort of the level keys; dead rows sort last, and
    # live rows carry distinct prices so live keys never tie).
    elig_qty = jnp.where(elig, opp_qty, 0)
    in_cum = _cumsum_sat(elig_qty, 1, saturate)   # within-row inclusive
    row_elig_qty = in_cum[:, -1]
    order_ix = jnp.argsort(jnp.where(row_live, key, IMAX))
    sorted_q = row_elig_qty[order_ix]
    cum = _cumsum_sat(sorted_q, 0, saturate)
    row_ahead = jnp.zeros((lvl,), I32).at[order_ix].set(cum - sorted_q)

    # Per-slot ahead = level ahead + within-row exclusive FIFO cumsum.
    # Both terms saturate at 2^30-1, so their sum fits int32; either one
    # at/"past" saturation already exceeds any take quantity (fill 0).
    ahead = row_ahead[:, None] + (in_cum - elig_qty)

    # Fill-or-kill gate: the level cumsum's last element is the total
    # eligible liquidity (saturates far above MAX_QUANTITY >= qty, so the
    # comparison is exact either way).
    avail = cum[-1]
    fok_fail = is_fok & (avail < qty)

    take_q = jnp.where(is_submit_like & ~fok_fail, qty, 0)
    fill = jnp.where(elig, jnp.clip(take_q - ahead, 0, opp_qty), 0)
    filled_total = jnp.sum(fill)
    remaining = jnp.where(is_submit_like, qty, 0) - filled_total

    # Priority rank among eligible makers = level rank base (exclusive
    # count of eligible makers on better levels) + within-row exclusive
    # eligibility count — the same unique prefix-dense ranks the sibling
    # kernels scatter the fill log by.
    elig_i = elig.astype(I32)
    row_cnt = jnp.sum(elig_i, axis=1)
    sorted_cnt = row_cnt[order_ix]
    cnt_cum = jnp.cumsum(sorted_cnt)
    rank_base = jnp.zeros((lvl,), I32).at[order_ix].set(cnt_cum - sorted_cnt)
    rank = rank_base[:, None] + (jnp.cumsum(elig_i, axis=1) - elig_i)
    has_fill = fill > 0
    slot = jnp.where(has_fill, rank, cap).reshape(-1)
    fill_oid = jnp.zeros((cap + 1,), I32).at[slot].set(
        jnp.where(has_fill, opp_oid, 0).reshape(-1))[:cap]
    fill_qty_out = jnp.zeros((cap + 1,), I32).at[slot].set(
        fill.reshape(-1))[:cap]
    fill_price = jnp.zeros((cap + 1,), I32).at[slot].set(
        jnp.where(has_fill, opp_price, 0).reshape(-1))[:cap]

    # Consumed makers leave holes in their rows' FIFO prefixes (a skipped
    # self-owned maker can sit ahead of a consumed one): re-pack per row.
    new_opp_qty, opp_price, opp_oid, opp_seq, opp_owner = _compact_rows(
        opp_qty - fill, opp_price, opp_oid, opp_seq, opp_owner)

    # ---- own side: FIFO-append a LIMIT remainder, or cancel/amend --------
    own_price = rows(jnp.where(is_buy, book.bid_price, book.ask_price))
    own_qty = rows(jnp.where(is_buy, book.bid_qty, book.ask_qty))
    own_oid = rows(jnp.where(is_buy, book.bid_oid, book.ask_oid))
    own_seq = rows(jnp.where(is_buy, book.bid_seq, book.ask_seq))
    own_owner = rows(jnp.where(is_buy, book.bid_owner, book.ask_owner))

    own_live = own_qty > 0
    orow_live = own_live[:, 0]
    orow_price = own_price[:, 0]
    orow_cnt = jnp.sum(own_live.astype(I32), axis=1)

    match_row = orow_live & (orow_price == price)
    has_row = jnp.any(match_row)
    row_i = jnp.argmax(match_row)
    free_rows = ~orow_live
    has_free_row = jnp.any(free_rows)
    new_row_i = jnp.argmax(free_rows)
    target_row = jnp.where(has_row, row_i, new_row_i)
    cnt_t = orow_cnt[target_row]
    target_slot = jnp.where(has_row, cnt_t, 0)
    # Level-structured capacity: an existing level rests at its FIFO tail
    # (if the row has room), a new price claims a free row (if the level
    # directory has one). No room either way = capacity REJECT.
    room = jnp.where(has_row, cnt_t < fifo, has_free_row)

    do_rest = is_submit_like & (~never_rests) & (remaining > 0) & ~self_blocked
    rested = do_rest & room

    li = jnp.arange(lvl)[:, None]
    fi = jnp.arange(fifo)[None, :]
    at_slot = rested & (li == target_row) & (fi == target_slot)
    own_price = jnp.where(at_slot, price, own_price)
    own_qty = jnp.where(at_slot, remaining, own_qty)
    own_oid = jnp.where(at_slot, oid, own_oid)
    own_seq = jnp.where(at_slot, book.next_seq, own_seq)
    own_owner = jnp.where(at_slot, owner, own_owner)
    next_seq = book.next_seq + jnp.where(rested, 1, 0).astype(I32)

    cancel_mask = is_cancel & (own_oid == oid) & own_live
    cancel_qty = jnp.sum(jnp.where(cancel_mask, own_qty, 0))
    cancel_ok = jnp.any(cancel_mask)
    # Amend down in place: qty drops but stays > 0 — row density and FIFO
    # position untouched, so the compact below is an identity for amends.
    amend_mask = is_amend & (own_oid == oid) & own_live
    amend_feasible = amend_mask & (qty > 0) & (qty < own_qty)
    amend_ok = jnp.any(amend_feasible)
    c_qty = jnp.where(cancel_mask, 0,
                      jnp.where(amend_feasible, qty, own_qty))
    own_qty2, own_price2, own_oid2, own_seq2, own_owner2 = _compact_rows(
        c_qty, own_price, own_oid, own_seq, own_owner)

    def flat(x):
        return x.reshape(cap)

    new_book = _SymBook(
        bid_price=flat(jnp.where(is_buy, own_price2, opp_price)),
        bid_qty=flat(jnp.where(is_buy, own_qty2, new_opp_qty)),
        bid_oid=flat(jnp.where(is_buy, own_oid2, opp_oid)),
        bid_seq=flat(jnp.where(is_buy, own_seq2, opp_seq)),
        bid_owner=flat(jnp.where(is_buy, own_owner2, opp_owner)),
        ask_price=flat(jnp.where(is_buy, opp_price, own_price2)),
        ask_qty=flat(jnp.where(is_buy, new_opp_qty, own_qty2)),
        ask_oid=flat(jnp.where(is_buy, opp_oid, own_oid2)),
        ask_seq=flat(jnp.where(is_buy, opp_seq, own_seq2)),
        ask_owner=flat(jnp.where(is_buy, opp_owner, own_owner2)),
        next_seq=next_seq,
    )

    # ---- status (identical decision tree to kernel._match_one) -----------
    submit_status = jnp.where(
        remaining == 0,
        FILLED,
        jnp.where(
            never_rests | self_blocked,
            CANCELED,
            jnp.where(
                rested,
                jnp.where(filled_total > 0, PARTIALLY_FILLED, NEW),
                REJECTED,  # level row full / level directory full
            ),
        ),
    )
    cancel_status = jnp.where(cancel_ok, CANCELED, REJECTED)
    amend_status = jnp.where(amend_ok, NEW, REJECTED)
    status = jnp.where(
        is_submit_like,
        submit_status,
        jnp.where(
            is_cancel, cancel_status,
            jnp.where(is_amend, amend_status, NOOP_STATUS)),
    ).astype(I32)
    out_remaining = jnp.where(
        is_submit_like, remaining,
        jnp.where(is_cancel, cancel_qty,
                  jnp.where(is_amend & amend_ok, qty, 0))
    ).astype(I32)

    return new_book, (
        status,
        filled_total.astype(I32),
        out_remaining,
        fill_oid,
        fill_qty_out,
        fill_price,
    )


def _sym_scan_levels(lvl, fifo, saturate, book: _SymBook, orders):
    return jax.lax.scan(
        lambda b, o: _match_one_levels(b, o, lvl, fifo, saturate),
        book, orders)


def engine_step_levels_core(cfg: EngineConfig, book: BookBatch,
                            orders: OrderBatch):
    """Raw levels-formulation match pass (same contract as
    kernel.engine_step_core): no finalize epilogue, so the megadispatch
    scan can compact per wave instead."""
    from functools import partial

    from matching_engine_tpu.engine.book import MAX_QUANTITY

    lvl, fifo = level_shape(cfg)
    saturate = cfg.capacity * MAX_QUANTITY >= 2**31
    sym_book = _SymBook(*book[:-1], next_seq=book.next_seq)
    new_sym_book, raw = jax.vmap(
        partial(_sym_scan_levels, lvl, fifo, saturate))(sym_book, orders)
    return BookBatch(*new_sym_book[:-1], next_seq=new_sym_book.next_seq), raw


def engine_step_levels_impl(cfg: EngineConfig, book: BookBatch,
                            orders: OrderBatch):
    """Un-jitted levels-formulation step (same contract as
    kernel.engine_step_impl; shares finalize_step)."""
    new_book, (status, filled, remaining, f_oid, f_qty, f_price) = (
        engine_step_levels_core(cfg, book, orders))
    return new_book, finalize_step(
        cfg, new_book, orders, status, filled, remaining, f_oid, f_qty,
        f_price)


engine_step_levels = jax.jit(engine_step_levels_impl, static_argnums=0,
                             donate_argnums=1)
