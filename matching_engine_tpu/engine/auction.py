"""Call-auction (batch uncross) kernel: clear every book at one price.

A second market mechanism beside the continuous price-time-priority match
(engine/kernel.py): collect the resting limit orders of each book, find
the single clearing price that maximizes executable volume, and execute
both sides at that price — the open/close/volatility-auction mechanism of
real exchanges. The reference has no analog (its engine file is empty;
SURVEY.md §2 row 5); this is a framework extension the TPU design makes
nearly free: one `vmap` uncrosses every symbol's book in a single
fixed-shape device step.

Mechanism (per symbol, all int32):

1. Candidate prices are the live resting prices (both sides, [2C] lanes).
   demand(p) = total bid quantity with limit >= p; supply(p) = total ask
   quantity with limit <= p; executable(p) = min(demand, supply).
2. The clearing price p* maximizes executable volume; ties minimize the
   order imbalance |demand - supply|; remaining ties take the LOWEST such
   price (deterministic; documented).
3. Allocation at p*: the eligible orders of each side fill in price-time
   priority (better price first, then earlier seq) up to the executed
   volume Q — exactly the `ahead_of_me` prefix-sum rule the continuous
   kernel uses, so the marginal order is partially filled and everything
   with strictly better priority fills fully.
4. Trade records are bilateral: each bid's fill occupies the interval
   [ahead_b, ahead_b + fill_b) of the executed-volume line, each ask's
   likewise; every overlapping (bid, ask) interval pair is one trade of
   the overlap length at p*. Both sides' records sum to Q, and record
   count per symbol is at most (#bid fills + #ask fills - 1).
5. All symbols' records compact into one [max_fills] log (the continuous
   kernel's cumsum-scatter). If the total would overflow the buffer the
   WHOLE auction aborts untouched (overflow flag set, books unchanged) —
   an uncross must be all-or-nothing per invocation, never half-logged.

Parity: engine/oracle.py `OracleBook.auction` implements the same rules
on Python lists; tests/test_auction.py fuzzes book states through both.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.book import I32, BookBatch, EngineConfig
from matching_engine_tpu.engine.kernel import _top_of_book

IMAX = jnp.iinfo(jnp.int32).max


class AuctionOutput(NamedTuple):
    """Packed device output — ONE small readback + the fill log:

    small: [7S + 2] int32 = clear_price | exec_lo | exec_hi (each [S];
           executed volume = exec_hi * 2^15 + exec_lo, split because a
           venue-depth uncross can exceed int32; 0 when the symbol did
           not cross) ++ best_bid | bid_size | best_ask | ask_size
           (each [S], POST-auction) ++ [fill_count, aborted].
    fills: [5, max_fills] int32, harness.decode_fills column order —
           (sym, taker_oid = bid, maker_oid = ask, price = p*, qty).
    """

    small: jax.Array
    fills: jax.Array


def _uncross_one(bid_price, bid_qty, bid_oid, bid_seq,
                 ask_price, ask_qty, ask_oid, ask_seq, mask):
    """One symbol's uncross: returns (fill_b[C], fill_a[C], p_star, q_exec,
    start_b[C], start_a[C]) — fills are the per-lane executed quantities,
    start_* the interval offsets used for bilateral record pairing."""
    live_b = bid_qty > 0
    live_a = ask_qty > 0

    cand = jnp.concatenate([bid_price, ask_price])          # [2C]
    cand_valid = jnp.concatenate([live_b, live_a]) & mask

    # demand/supply at every candidate price: [2C, C] masked matvecs.
    d = jnp.sum(jnp.where(live_b[None, :] & (bid_price[None, :] >= cand[:, None]),
                          bid_qty[None, :], 0), axis=1)
    s = jnp.sum(jnp.where(live_a[None, :] & (ask_price[None, :] <= cand[:, None]),
                          ask_qty[None, :], 0), axis=1)
    ex = jnp.where(cand_valid, jnp.minimum(d, s), -1)
    imb = jnp.abs(d - s)

    # Lexicographic pick: max executable, then min imbalance, then min price.
    m1 = jnp.max(ex)
    c1 = cand_valid & (ex == m1)
    m2 = jnp.min(jnp.where(c1, imb, IMAX))
    c2 = c1 & (imb == m2)
    p_star = jnp.min(jnp.where(c2, cand, IMAX))
    q_exec = jnp.maximum(m1, 0)

    crossed = mask & (q_exec > 0) & (p_star < IMAX)
    q = jnp.where(crossed, q_exec, 0)

    elig_b = live_b & (bid_price >= p_star) & crossed
    elig_a = live_a & (ask_price <= p_star) & crossed

    # Price-time priority prefix sums (the continuous kernel's ahead rule).
    better_b = (bid_price[:, None] > bid_price[None, :]) | (
        (bid_price[:, None] == bid_price[None, :])
        & (bid_seq[:, None] < bid_seq[None, :])
    )
    ahead_b = jnp.sum(
        jnp.where(better_b & elig_b[:, None], bid_qty[:, None], 0), axis=0)
    fill_b = jnp.where(elig_b, jnp.clip(q - ahead_b, 0, bid_qty), 0)

    better_a = (ask_price[:, None] < ask_price[None, :]) | (
        (ask_price[:, None] == ask_price[None, :])
        & (ask_seq[:, None] < ask_seq[None, :])
    )
    ahead_a = jnp.sum(
        jnp.where(better_a & elig_a[:, None], ask_qty[:, None], 0), axis=0)
    fill_a = jnp.where(elig_a, jnp.clip(q - ahead_a, 0, ask_qty), 0)

    return (fill_b, fill_a, jnp.where(crossed, p_star, 0).astype(I32),
            q.astype(I32), ahead_b.astype(I32), ahead_a.astype(I32))


def _records_one(fill_b, fill_a, start_b, start_a, bid_oid, ask_oid):
    """One symbol's bilateral records, compacted to [2C-1] lanes.

    Record count per symbol is bounded by (#bid fills + #ask fills - 1)
    <= 2C-1, so compacting PER SYMBOL first keeps the later global
    compaction at [S, 2C-1] instead of [S, C, C] — a 64x smaller scatter
    at the 4k x 128 configuration.
    """
    cap = fill_b.shape[0]
    r = 2 * cap - 1
    b_lo = start_b[:, None]
    b_hi = (start_b + fill_b)[:, None]
    a_lo = start_a[None, :]
    a_hi = (start_a + fill_a)[None, :]
    ov = jnp.clip(jnp.minimum(b_hi, a_hi) - jnp.maximum(b_lo, a_lo), 0, None)
    ov = jnp.where((fill_b[:, None] > 0) & (fill_a[None, :] > 0), ov, 0)
    flat = ov.reshape(-1).astype(I32)
    m = flat > 0
    pos = jnp.cumsum(m) - 1
    dest = jnp.where(m, pos, r)  # count <= r by construction; r = trash
    taker = jnp.broadcast_to(bid_oid[:, None], (cap, cap)).reshape(-1)
    maker = jnp.broadcast_to(ask_oid[None, :], (cap, cap)).reshape(-1)

    def compact(vals):
        return jnp.zeros((r + 1,), I32).at[dest].set(vals)[:r]

    return compact(taker), compact(maker), compact(flat), jnp.sum(m)


def apply_uncross(book: BookBatch, fill_b, fill_a, apply,
                  kernel: str = "matrix", levels: int = 0) -> BookBatch:
    """Decrement both sides' executed quantities where `apply` ([S] bool)
    holds — THE one book-update rule for single-device and mesh uncross.

    Under the sorted-book kernel (EngineConfig.kernel == "sorted") the
    fully-filled makers' holes are re-packed so the dense-sorted-prefix
    invariant survives the auction: decrements never change relative
    priority order, so an order-preserving compact restores it exactly.
    Under the levels kernel the same repack runs PER FIFO ROW (each side's
    [C] plane viewed as [levels, C // levels]) so every level keeps its
    dense FIFO prefix."""
    out = book._replace(
        bid_qty=book.bid_qty - jnp.where(apply[:, None], fill_b, 0),
        ask_qty=book.ask_qty - jnp.where(apply[:, None], fill_a, 0),
    )
    if kernel == "levels":
        from matching_engine_tpu.engine.kernel_sorted import _compact

        s, cap = out.bid_qty.shape
        fifo = cap // levels

        def repack(qty, price, oid, seq, owner):
            def r(x):
                return x.reshape(s * levels, fifo)

            q2, p2, o2, sq2, w2 = jax.vmap(_compact)(
                r(qty), r(price), r(oid), r(seq), r(owner))
            return tuple(x.reshape(s, cap) for x in (q2, p2, o2, sq2, w2))

        bq, bp, bo, bs, bw = repack(out.bid_qty, out.bid_price, out.bid_oid,
                                    out.bid_seq, out.bid_owner)
        aq, ap, ao, as_, aw = repack(out.ask_qty, out.ask_price, out.ask_oid,
                                     out.ask_seq, out.ask_owner)
        return out._replace(
            bid_qty=bq, bid_price=bp, bid_oid=bo, bid_seq=bs, bid_owner=bw,
            ask_qty=aq, ask_price=ap, ask_oid=ao, ask_seq=as_, ask_owner=aw,
        )
    if kernel != "sorted":
        return out
    from matching_engine_tpu.engine.kernel_sorted import _compact

    bq, bp, bo, bs, bw = jax.vmap(_compact)(
        out.bid_qty, out.bid_price, out.bid_oid, out.bid_seq, out.bid_owner)
    aq, ap, ao, as_, aw = jax.vmap(_compact)(
        out.ask_qty, out.ask_price, out.ask_oid, out.ask_seq, out.ask_owner)
    return out._replace(
        bid_qty=bq, bid_price=bp, bid_oid=bo, bid_seq=bs, bid_owner=bw,
        ask_qty=aq, ask_price=ap, ask_oid=ao, ask_seq=as_, ask_owner=aw,
    )


def compact_records(sym_ids, rec_taker, rec_maker, price, rec_qty, n,
                    aborted):
    """Stage-2 global compaction of the per-symbol record lanes into one
    [n] log (5 columns) — shared by the single-device and shard-local
    paths; `aborted` routes every record to the trash lane."""
    flat_qty = rec_qty.reshape(-1)
    m = flat_qty > 0
    pos = jnp.cumsum(m) - 1
    dest = jnp.where(m & (pos < n) & ~aborted, pos, n)  # n = trash

    def compact(vals):
        return jnp.zeros((n + 1,), I32).at[dest].set(vals.reshape(-1))[:n]

    return (compact(sym_ids), compact(rec_taker), compact(rec_maker),
            compact(price), compact(flat_qty))


def zero_unless(x, ok):
    """x where ok else 0 (the aborted-output masking rule)."""
    return x * jnp.where(ok, 1, 0).astype(I32)


def uncross_and_records(cfg: EngineConfig, book: BookBatch, mask):
    """Formulation dispatch shared by the single-device and sharded
    paths: returns (fill_b, fill_a [S, C] in lane order, p_star [S],
    exec_hi, exec_lo [S] — executed volume as base-2^15 limbs,
    rec_taker, rec_maker, rec_qty [S, R], rec_counts [S]) where R is the
    formulation's per-symbol record-lane count.

    Matrix-kernel books use the [C, C] formulation above (its int32
    volume sums are exact at matrix capacities — EngineConfig pins
    capacity <= 1024 < 2^31 / MAX_QUANTITY); sorted- and levels-kernel
    books use the O(C log C) wide-sum formulation
    (engine/auction_sorted.py — it priority-sorts its input lanes first,
    so any lane layout is admissible), exact at any supported depth."""
    if cfg.kernel in ("sorted", "levels"):
        from matching_engine_tpu.engine.auction_sorted import (
            _uncross_records_one,
        )

        (fill_b, fill_a, p_star, exec_hi, exec_lo, rec_taker, rec_maker,
         rec_qty, rec_counts) = jax.vmap(_uncross_records_one)(
            book.bid_price, book.bid_qty, book.bid_oid, book.bid_seq,
            book.ask_price, book.ask_qty, book.ask_oid, book.ask_seq,
            mask,
        )
    else:
        fill_b, fill_a, p_star, q_exec, start_b, start_a = jax.vmap(
            _uncross_one)(
            book.bid_price, book.bid_qty, book.bid_oid, book.bid_seq,
            book.ask_price, book.ask_qty, book.ask_oid, book.ask_seq,
            mask,
        )
        rec_taker, rec_maker, rec_qty, rec_counts = jax.vmap(_records_one)(
            fill_b, fill_a, start_b, start_a, book.bid_oid, book.ask_oid)
        exec_hi, exec_lo = q_exec >> 15, q_exec & 0x7FFF
    return (fill_b, fill_a, p_star, exec_hi, exec_lo,
            rec_taker, rec_maker, rec_qty, rec_counts)


@partial(jax.jit, static_argnums=0, donate_argnums=1)
def auction_step(cfg: EngineConfig, book: BookBatch, mask: jax.Array):
    """Uncross every masked symbol's book at its clearing price.

    mask: [S] bool — which symbols participate. Returns (new_book,
    AuctionOutput). All-or-nothing: if the bilateral record log would
    overflow cfg.max_fills, NOTHING is applied and `aborted` is set.
    """
    s_dim = cfg.num_symbols
    (fill_b, fill_a, p_star, exec_hi, exec_lo, rec_taker, rec_maker,
     rec_qty, rec_counts) = uncross_and_records(cfg, book, mask)

    total = jnp.sum(rec_counts)
    n = cfg.max_fills
    aborted = total > n

    # All-or-nothing: an overflow leaves every book untouched.
    new_book = apply_uncross(book, fill_b, fill_a, mask & ~aborted,
                             kernel=cfg.kernel, levels=cfg.levels)

    # Stage 2: global compaction over the per-symbol record lanes
    # (row-major, so records stay symbol-major in per-symbol rank order).
    r = rec_qty.shape[1]
    sym_ids = jnp.broadcast_to(
        jnp.arange(s_dim, dtype=I32)[:, None], (s_dim, r))
    price = jnp.broadcast_to(p_star[:, None], (s_dim, r))
    fills = jnp.stack(list(compact_records(
        sym_ids, rec_taker, rec_maker, price, rec_qty, n, aborted)))

    best_bid, bid_size = _top_of_book(new_book.bid_price, new_book.bid_qty, True)
    best_ask, ask_size = _top_of_book(new_book.ask_price, new_book.ask_qty, False)
    small = jnp.concatenate([
        zero_unless(p_star, ~aborted),
        zero_unless(exec_lo, ~aborted),
        zero_unless(exec_hi, ~aborted),
        best_bid, bid_size, best_ask, ask_size,
        jnp.stack([
            jnp.where(aborted, 0, jnp.minimum(total, n)).astype(I32),
            aborted.astype(I32),
        ]),
    ])
    return new_book, AuctionOutput(small=small, fills=fills)


class AuctionDecoded(NamedTuple):
    """Host view (numpy, from the one small readback)."""

    clear_price: object
    executed: object
    best_bid: object
    bid_size: object
    best_ask: object
    ask_size: object
    fill_count: int
    aborted: bool


def decode_auction(cfg: EngineConfig, out: AuctionOutput):
    """(decoded, fills) — one readback + the fill slice (host-sliced from
    the whole fixed-shape buffer; see decode_step_packed's rationale)."""
    import numpy as np

    from matching_engine_tpu.engine.harness import decode_fills

    small = np.asarray(out.small)
    s = cfg.num_symbols
    executed = (small[2 * s:3 * s].astype(np.int64) << 15) \
        + small[s:2 * s]
    dec = AuctionDecoded(
        clear_price=small[0:s],
        executed=executed,
        best_bid=small[3 * s:4 * s],
        bid_size=small[4 * s:5 * s],
        best_ask=small[5 * s:6 * s],
        ask_size=small[6 * s:7 * s],
        fill_count=int(small[7 * s]),
        aborted=bool(small[7 * s + 1]),
    )
    if dec.fill_count:
        packed = np.asarray(out.fills)
        fills = decode_fills(packed[0], packed[1], packed[2], packed[3],
                             packed[4], dec.fill_count)
    else:
        fills = []
    return dec, fills
