"""Device book state: fixed-shape struct-of-arrays limit order books.

The reference declared an engine and left the file empty
(include/engine/model.hpp, 0 bytes; SURVEY.md §2 row 5). This is the
TPU-native book it implied: one pytree holding `num_symbols` books, each side
a fixed-capacity set of (price, qty, oid, seq) int32 lanes. Static shapes
everywhere — XLA compiles the match step once; `qty == 0` marks a free slot
and every read masks on `qty > 0` (that masking is the core invariant; stale
price/oid values in freed slots are never observed).

All book math is int32:
- prices are Q4 scaled ints (domain/price.py bounds them to int32 at
  validation),
- quantities are bounded by MAX_QUANTITY so a full side's quantity sum stays
  below 2**31 (the priority prefix-sum in the kernel accumulates at lane
  width; see kernel.py),
- `seq` is a per-book arrival counter giving FIFO within a price level.

Integer-only math is what makes bit-exact fill parity with the host oracle
possible (SURVEY.md §7 "Hard parts").
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from matching_engine_tpu.domain.order import MAX_QUANTITY  # noqa: F401  (re-export)

I32 = jnp.int32


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static kernel configuration (hashable; closed over at jit time)."""

    num_symbols: int = 64
    capacity: int = 128          # resting orders per side per book
    batch: int = 8               # orders per symbol per engine step
    max_fills: int = 1 << 15     # global fill-buffer slots per engine step
    # Match formulation: "matrix" = the [CAP, CAP] priority-matrix kernel
    # (engine/kernel.py), "sorted" = the O(CAP) dense-sorted-prefix kernel
    # (engine/kernel_sorted.py), "levels" = the price-level [L, F] kernel
    # (engine/kernel_levels.py: L level rows x F FIFO slots per side, match
    # sweep over levels instead of orders). All bit-match the oracle
    # (kernel="levels" against the level-capacity-aware oracle); books are
    # NOT interchangeable between kernels mid-lifetime (each layout has its
    # own invariant), so the choice is part of semantic_key and a
    # checkpoint from another kernel restores via full replay.
    kernel: str = "matrix"
    # kernel="levels" only: price-level rows per book side (the book's [C]
    # lane plane is viewed as [levels, capacity // levels]). 0 = derive a
    # default from capacity at construction (normalized in __post_init__,
    # so two configs spelling the same choice compare equal). Must divide
    # capacity. A submit at a NEW price when all `levels` rows are live, or
    # at an EXISTING price whose FIFO row is full, is a (metered) capacity
    # reject even below total capacity — the oracle models the same rule.
    levels: int = 0
    # Tiered capacity classes (server/tiered_runner.py): a static partition
    # of the symbol axis into contiguous groups, each with its own book
    # capacity — ((count, capacity), ...), sum of counts == num_symbols.
    # The jit'd kernels never see a tiered config (the tiered runner steps
    # one per-tier sub-config each); `capacity` must equal the deepest
    # tier. Part of semantic_key: a checkpoint written under one tier spec
    # refuses to restore under another (full-replay fallback).
    tiers: tuple = ()

    def __post_init__(self):
        assert self.kernel in ("matrix", "sorted", "levels"), self.kernel
        if self.kernel == "matrix":
            # The matrix kernel accumulates qty sums at int32 lane width
            # (capacity * MAX_QUANTITY must not wrap) and materializes
            # [S, CAP, CAP] intermediates — 1024 is both bounds.
            assert self.capacity <= 1024, \
                "matrix kernel: capacity beyond 1024 breaks int32 qty sums"
        else:
            # The sorted/levels kernels switch their ahead-of-maker
            # accumulators to SATURATING int32 prefix sums when capacity *
            # MAX_QUANTITY could wrap (venue-depth books; exact below
            # saturation, clamped far past any take quantity above it —
            # kernel_sorted.py / kernel_levels.py); 8192 bounds the
            # shift/scatter shapes.
            assert self.capacity <= 8192, \
                f"{self.kernel} kernel: capacity beyond 8192 unsupported"
        if self.kernel == "levels":
            if self.levels == 0:
                object.__setattr__(
                    self, "levels", default_levels(self.capacity))
            assert 1 <= self.levels <= self.capacity, self.levels
            assert self.capacity % self.levels == 0, \
                f"levels {self.levels} must divide capacity {self.capacity}"
        else:
            assert self.levels == 0, \
                "levels is only meaningful for kernel='levels'"
        if self.tiers:
            # Normalize to a tuple of int pairs: checkpoint meta round-
            # trips through JSON (lists of lists), and semantic_key /
            # equality must not depend on the container spelling.
            object.__setattr__(
                self, "tiers",
                tuple((int(n), int(c)) for n, c in self.tiers))
            counts = [t[0] for t in self.tiers]
            caps = [t[1] for t in self.tiers]
            # ValueError, not assert: these validate OPERATOR input
            # (--book-tiers) and must survive `python -O`.
            if not all(c > 0 for c in counts) or not all(
                    c >= 1 for c in caps):
                raise ValueError(f"non-positive tier in {self.tiers}")
            if sum(counts) != self.num_symbols:
                raise ValueError(
                    f"tier symbol counts {counts} must sum to "
                    f"num_symbols {self.num_symbols}")
            if self.capacity != max(caps):
                raise ValueError(
                    "capacity must equal the deepest tier's capacity")
            if self.kernel == "matrix" and max(caps) > 1024:
                raise ValueError(
                    "matrix kernel: tier capacity beyond 1024")

    def semantic_key(self) -> tuple:
        """The fields that define book/kernel SEMANTICS (shapes, buffer
        sizes, book-layout invariants) as opposed to any execution-strategy
        knobs that may be added later. Checkpoint compatibility compares
        this."""
        return (self.num_symbols, self.capacity, self.batch, self.max_fills,
                self.kernel, self.levels, tuple(self.tiers))

    def tier_configs(self) -> list:
        """The per-tier sub-configs the tiered runner steps (empty when
        untiered). Each is a plain single-capacity EngineConfig over the
        tier's contiguous symbol rows; kernel='levels' re-derives its
        per-tier level count from the tier's own capacity."""
        import dataclasses as _dc

        return [
            _dc.replace(self, num_symbols=n, capacity=cap, tiers=(),
                        levels=0)
            for n, cap in self.tiers
        ]


def default_levels(capacity: int) -> int:
    """Default price-level row count for kernel='levels': aim for 16 rows
    on shallow books and 64-slot FIFO rows on deep ones, then settle on
    the largest divisor of `capacity` at or under that target (levels must
    tile the lane plane exactly)."""
    if capacity <= 64:
        target = max(2, capacity // 4)
    else:
        target = max(16, capacity // 64)
    target = min(target, 256, capacity)
    for cand in range(target, 0, -1):
        if capacity % cand == 0:
            return cand
    return 1


def level_shape(cfg: EngineConfig) -> tuple[int, int]:
    """(L, F) of a levels-kernel config: L price-level rows of F FIFO
    slots each; L * F == capacity."""
    assert cfg.kernel == "levels", cfg.kernel
    return cfg.levels, cfg.capacity // cfg.levels


def auction_capacity_max(kernel: str = "matrix") -> int:
    """Largest book capacity the call-auction uncross supports for this
    kernel. Matrix books use the [C, C] formulation whose int32
    demand/supply sums are exact up to 2^31 / MAX_QUANTITY (= 1073 —
    above the matrix kernel's own 1024 capacity bound, so every matrix
    config can auction). Sorted and levels books use the O(C log C)
    wide-sum formulation (engine/auction_sorted.py — it priority-sorts its
    input, so it is correct for ANY lane order, the levels layout
    included), exact at every capacity those kernels themselves support —
    both market mechanisms cover the full venue-depth range (VERDICT r4
    missing #4 closed)."""
    if kernel in ("sorted", "levels"):
        return 8192
    return (2**31 - 1) // MAX_QUANTITY


class BookBatch(NamedTuple):
    """All books, batched on the leading symbol axis. Shapes [S, CAP] / [S].

    `*_owner` is the resting order's self-trade-prevention identity: a
    stable int32 hash of the submitting client_id (0 = none). The
    continuous match kernel never crosses a taker with a maker of the
    same nonzero owner (see kernel._match_one)."""

    bid_price: jax.Array
    bid_qty: jax.Array
    bid_oid: jax.Array
    bid_seq: jax.Array
    bid_owner: jax.Array
    ask_price: jax.Array
    ask_qty: jax.Array
    ask_oid: jax.Array
    ask_seq: jax.Array
    ask_owner: jax.Array
    next_seq: jax.Array  # [S] per-book arrival counter


class OrderBatch(NamedTuple):
    """One dispatch of orders, grouped by symbol. Shapes [S, B], int32.

    op: 0 = no-op padding, 1 = submit, 2 = cancel.
    side: proto Side (BUY=1 / SELL=2); for cancels, the side the target
          rests on (the host order directory knows it).
    otype: proto OrderType (LIMIT=0 / MARKET=1); ignored for cancels.
    price: Q4 limit price (0 for MARKET).
    qty: order quantity (submit) / unused (cancel).
    oid: numeric order id (submit) / target order id (cancel).
    """

    op: jax.Array
    side: jax.Array
    otype: jax.Array
    price: jax.Array
    qty: jax.Array
    oid: jax.Array
    owner: jax.Array  # self-trade-prevention identity (0 = none)


# Columns of the packed [..., 7] dispatch lane array.
BATCH_COLS = 7


def batch_from_lanes(lanes) -> OrderBatch:
    """THE [..., 7] lane-column layout, shared by the host batch builder
    (harness.build_batch_arrays writes it), host-side column views
    (harness.batch_view), and the device-side unpack inside
    kernel.engine_step_packed — one definition so the three can't drift.
    Works on numpy (views) and traced jax arrays alike."""
    return OrderBatch(
        op=lanes[..., 0], side=lanes[..., 1], otype=lanes[..., 2],
        price=lanes[..., 3], qty=lanes[..., 4], oid=lanes[..., 5],
        owner=lanes[..., 6],
    )


class StepOutput(NamedTuple):
    """Engine-step results, sized for a cheap device->host transfer.

    status/filled/remaining: [S, B] per-order outcomes (proto
        OrderUpdate.Status values; -1 for no-op padding rows).
    fill_*: the global compacted fill log, [max_fills] each, valid rows
        [0, fill_count). Within a symbol, rows appear in chronological
        (batch position) then price-time priority order — the exact order
        the oracle emits fills.
    fill_count: scalar count of valid fill rows.
    fill_overflow: True if more fills occurred than buffer slots; the book
        state is still correct, only the excess fill *records* were dropped.
    best_bid/bid_size/best_ask/ask_size: [S] top-of-book after the step
        (0 where the side is empty).
    """

    status: jax.Array
    filled: jax.Array
    remaining: jax.Array
    fill_sym: jax.Array
    fill_taker_oid: jax.Array
    fill_maker_oid: jax.Array
    fill_price: jax.Array
    fill_qty: jax.Array
    fill_count: jax.Array
    fill_overflow: jax.Array
    best_bid: jax.Array
    bid_size: jax.Array
    best_ask: jax.Array
    ask_size: jax.Array


def init_book(cfg: EngineConfig) -> BookBatch:
    s, c = cfg.num_symbols, cfg.capacity

    # Distinct buffers per field: the engine step donates the book, and
    # aliased buffers cannot be donated twice.
    def z():
        return jnp.zeros((s, c), dtype=I32)

    return BookBatch(
        bid_price=z(), bid_qty=z(), bid_oid=z(), bid_seq=z(), bid_owner=z(),
        ask_price=z(), ask_qty=z(), ask_oid=z(), ask_seq=z(), ask_owner=z(),
        next_seq=jnp.zeros((s,), dtype=I32),
    )


def noop_orders(cfg: EngineConfig) -> OrderBatch:
    z = jnp.zeros((cfg.num_symbols, cfg.batch), dtype=I32)
    return OrderBatch(op=z, side=z, otype=z, price=z, qty=z, oid=z, owner=z)
