"""Host driver around the device kernel: batch building, result decoding.

This is the glue between host order streams and the [S, B] device dispatch
format — used by the parity tests, the benchmark, and the server's engine
runner. It owns no policy: grouping/padding here, matching on device.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from matching_engine_tpu.engine.book import (
    BATCH_COLS,
    BookBatch,
    EngineConfig,
    batch_from_lanes,
    OrderBatch,
    StepOutput,
)
from matching_engine_tpu.engine.kernel import (
    OP_CANCEL,
    OP_NOOP,
    OP_SUBMIT,
    engine_step_packed,
    fill_inline_count,
)


@dataclasses.dataclass(frozen=True)
class HostOrder:
    """One host-side engine op (already validated + Q4-normalized)."""

    sym: int          # symbol slot in [0, num_symbols)
    op: int           # OP_SUBMIT / OP_REST / OP_CANCEL
    side: int         # BUY / SELL (for cancel: side the target rests on)
    otype: int = 0    # LIMIT / MARKET
    price: int = 0    # Q4
    qty: int = 0
    oid: int = 0
    owner: int = 0    # self-trade-prevention identity (0 = none)


@dataclasses.dataclass(frozen=True)
class HostFill:
    sym: int
    taker_oid: int
    maker_oid: int
    price_q4: int
    quantity: int


@dataclasses.dataclass(frozen=True)
class HostResult:
    oid: int
    sym: int
    status: int
    filled: int
    remaining: int


def build_batch_arrays(cfg: EngineConfig,
                       orders: list[HostOrder]) -> list[np.ndarray]:
    """Group a chronological order list into dense [S, B, 7] dispatch
    arrays (the packed single-upload form engine_step_packed consumes).

    Orders for the same symbol keep their relative order (placed in
    successive batch rows of the same dispatch, overflowing into further
    dispatches); unused rows are OP_NOOP padding the kernel ignores.
    """
    s, b = cfg.num_symbols, cfg.batch
    batches: list[np.ndarray] = []  # each [S, B, BATCH_COLS]
    counts = np.zeros((s,), dtype=np.int64)  # orders seen per symbol so far

    for o in orders:
        if not (-(1 << 31) <= o.oid < (1 << 31)):
            # Device oid lanes are int32 by design; unbounded host OIDs map
            # onto recycled int32 handles in the EngineRunner. Reaching here
            # with a wider value is a caller bug — fail, never wrap.
            raise ValueError(f"oid {o.oid} exceeds the int32 device lane")
        i, row = divmod(int(counts[o.sym]), b)
        while i >= len(batches):
            batches.append(np.zeros((s, b, BATCH_COLS), dtype=np.int32))
        batches[i][o.sym, row] = (o.op, o.side, o.otype, o.price, o.qty,
                                  o.oid, o.owner)
        counts[o.sym] += 1
    return batches


def batch_view(arr: np.ndarray) -> OrderBatch:
    """Host-side OrderBatch column views of one [S, B, 7] dispatch array
    (free — numpy views; decode reads op/oid from these)."""
    return batch_from_lanes(arr)


def build_batches(cfg: EngineConfig, orders: list[HostOrder]) -> list[OrderBatch]:
    """build_batch_arrays, as OrderBatch views (the 6-plane dispatch form
    engine_step and the sharded path consume)."""
    return [batch_view(arr) for arr in build_batch_arrays(cfg, orders)]


def decode_results(batch: OrderBatch, status, filled, remaining,
                   sym_offset: int = 0) -> list[HostResult]:
    """Per-order outcomes for the real (non-padding) rows of one dispatch.

    `sym_offset` globalizes symbol indices when `batch` is a process-local
    row block of a sharded dispatch (parallel/hostlocal.py)."""
    status = np.asarray(status)
    filled = np.asarray(filled)
    remaining = np.asarray(remaining)
    op = np.asarray(batch.op)
    oid = np.asarray(batch.oid)

    # np.nonzero is row-major, so results keep (symbol, batch-row) device
    # order — engine_runner's decode relies on that to replay the scan's
    # event order. Bulk fancy-index + tolist: no per-element boxing.
    sym_idx, row_idx = np.nonzero(op != OP_NOOP)
    return [
        HostResult(*t)
        for t in zip(
            oid[sym_idx, row_idx].tolist(),
            (sym_idx + sym_offset).tolist(),
            status[sym_idx, row_idx].tolist(),
            filled[sym_idx, row_idx].tolist(),
            remaining[sym_idx, row_idx].tolist(),
        )
    ]


def decode_fills(sym, taker, maker, price, qty, n: int) -> list[HostFill]:
    """Bulk fill decode: one device->host transfer per column, one tolist()
    each — per-element indexing would cost a device gather (jax) or boxed
    scalar conversion (numpy) per int. THE fill-column order lives here
    (and only here; the sharded decoder shares this helper)."""
    return [
        HostFill(*t)
        for t in zip(
            np.asarray(sym[:n]).tolist(),
            np.asarray(taker[:n]).tolist(),
            np.asarray(maker[:n]).tolist(),
            np.asarray(price[:n]).tolist(),
            np.asarray(qty[:n]).tolist(),
        )
    ]


def decode_step(
    cfg: EngineConfig, batch: OrderBatch, out: StepOutput
) -> tuple[list[HostResult], list[HostFill], bool]:
    """Decode one StepOutput into per-order results + the fill log."""
    results = decode_results(batch, out.status, out.filled, out.remaining)
    fills = decode_fills(
        out.fill_sym, out.fill_taker_oid, out.fill_maker_oid,
        out.fill_price, out.fill_qty, int(out.fill_count),
    )
    return results, fills, bool(out.fill_overflow)


class DenseDecoded:
    """Host view of one packed dense step (all numpy, decoded from the ONE
    small-vector readback). Attribute names mirror StepOutput."""

    __slots__ = ("status", "filled", "remaining", "best_bid", "bid_size",
                 "best_ask", "ask_size", "fill_count", "fill_overflow",
                 "fills_inline")

    def __init__(self, cfg: EngineConfig, small: np.ndarray):
        s, b = cfg.num_symbols, cfg.batch
        sb = s * b
        self.status = small[0:sb].reshape(s, b)
        self.filled = small[sb:2 * sb].reshape(s, b)
        self.remaining = small[2 * sb:3 * sb].reshape(s, b)
        base = 3 * sb
        self.best_bid = small[base:base + s]
        self.bid_size = small[base + s:base + 2 * s]
        self.best_ask = small[base + 2 * s:base + 3 * s]
        self.ask_size = small[base + 3 * s:base + 4 * s]
        self.fill_count = int(small[base + 4 * s])
        self.fill_overflow = bool(small[base + 4 * s + 1])
        lo = fill_inline_count(cfg)
        tail = base + 4 * s + 2
        self.fills_inline = small[tail:tail + 5 * lo].reshape(5, lo)


def decode_step_packed(cfg: EngineConfig, batch: OrderBatch, pout):
    """decode_step for a PackedStepOutput: at most two device->host
    transfers, both of ALREADY-COMPUTED fixed-shape buffers. Never slice
    the fill log on device: `fills[:, :n]` is a fresh XLA program per
    distinct n — on a tunneled chip that is a compile plus an execution
    round trip per step, ~1000x the cost of fetching the whole buffer and
    slicing on host."""
    dec = DenseDecoded(cfg, np.asarray(pout.small))
    results = decode_results(batch, dec.status, dec.filled, dec.remaining)
    if dec.fill_count == 0:
        fills = []
    else:
        # Common case: the fill log fit the inline segment — decoded from
        # the same readback. Only an over-FILL_INLINE dispatch pays the
        # second (whole-buffer, fixed-shape) fetch.
        packed = (dec.fills_inline
                  if dec.fill_count <= dec.fills_inline.shape[1]
                  else np.asarray(pout.fills))
        fills = decode_fills(packed[0], packed[1], packed[2], packed[3],
                             packed[4], dec.fill_count)
    return results, fills, dec.fill_overflow, dec


class MegaDecoded:
    """Host view of one megadispatch readback (kernel.MegaStepOutput.small
    layout; all numpy views of the ONE transferred vector). Exposes the
    final-book top-of-book under the StepOutput attribute names so the
    runner's market-data publisher reads it like any dense output."""

    __slots__ = ("res_counts", "fill_counts", "overflows", "best_bid",
                 "bid_size", "best_ask", "ask_size", "res", "fills_inline")

    def __init__(self, cfg: EngineConfig, m: int, rcap: int,
                 small: np.ndarray):
        from matching_engine_tpu.engine.kernel import mega_fill_inline

        s = cfg.num_symbols
        lo = mega_fill_inline(cfg, rcap)
        self.res_counts = small[0:m]
        self.fill_counts = small[m:2 * m]
        self.overflows = small[2 * m:3 * m]
        base = 3 * m
        self.best_bid = small[base:base + s]
        self.bid_size = small[base + s:base + 2 * s]
        self.best_ask = small[base + 2 * s:base + 3 * s]
        self.ask_size = small[base + 3 * s:base + 4 * s]
        base += 4 * s
        self.res = small[base:base + m * 5 * rcap].reshape(m, 5, rcap)
        base += m * 5 * rcap
        self.fills_inline = small[base:base + m * 5 * lo].reshape(m, 5, lo)


def decode_step_mega(cfg: EngineConfig, mout, m: int, rcap: int):
    """Decode one megadispatch output into per-wave (results, fills,
    overflow) triples — the same triples the serial schedule's per-wave
    decode_step_packed produces, in the same order, from ONE packed
    readback. Returns (waves, decoded, fetched_full): a second
    (whole-buffer, fixed-shape) fills fetch happens only when some wave's
    fill count exceeds the inline segment, same policy as the packed
    single step (never a device-side dynamic slice).

    Results decode straight off the compacted rows: the device packed
    real ops in row-major (symbol, batch-row) order, which is exactly
    np.nonzero's order over the full planes — so HostResult lists are
    bit-identical to decode_results on the uncompacted output."""
    small = np.asarray(mout.small)
    dec = MegaDecoded(cfg, m, rcap, small)
    full = None
    waves = []
    for i in range(m):
        rc = int(dec.res_counts[i])
        r = dec.res[i]
        results = [
            HostResult(*t)
            for t in zip(r[0, :rc].tolist(), r[1, :rc].tolist(),
                         r[2, :rc].tolist(), r[3, :rc].tolist(),
                         r[4, :rc].tolist())
        ]
        fn = int(dec.fill_counts[i])
        if fn == 0:
            fills = []
        else:
            if fn <= dec.fills_inline.shape[2]:
                packed = dec.fills_inline[i]
            else:
                if full is None:
                    full = np.asarray(mout.fills)
                packed = full[i]
            fills = decode_fills(packed[0], packed[1], packed[2], packed[3],
                                 packed[4], fn)
        waves.append((results, fills, bool(dec.overflows[i])))
    return waves, dec, full is not None


# Max dispatched-but-undecoded steps held in flight. Enough to hide the
# per-step sync round trip behind the device pipeline (a tunneled chip
# bills ~64ms per synchronization), small enough that staged outputs
# (each pinning a [5, max_fills] fill buffer + result vector in HBM)
# stay O(1), not O(waves).
PIPELINE_DEPTH = 8


def run_pipelined(dispatched, decode, depth: int = PIPELINE_DEPTH) -> None:
    """THE bounded dispatch-ahead window (one definition for the serving
    runner's three dispatch shapes and apply_orders): pull from the
    `dispatched` iterator (whose body enqueues async device steps) keeping
    at most `depth` undecoded outputs staged, then drain. Decode order is
    FIFO — identical to decoding inline, minus the per-step sync."""
    staged: deque = deque()
    for item in dispatched:
        staged.append(item)
        if len(staged) >= depth:
            decode(staged.popleft())
    while staged:
        decode(staged.popleft())


def apply_orders(
    cfg: EngineConfig, book: BookBatch, orders: list[HostOrder]
) -> tuple[BookBatch, list[HostResult], list[HostFill]]:
    """Run a chronological order list through the kernel; decode everything.

    Dispatch-then-decode with a bounded window: up to PIPELINE_DEPTH steps
    are enqueued ahead of the decode cursor (async jit dispatch; the
    donated book chains them on device), so the host never synchronizes on
    the step it just dispatched — over a tunneled chip a per-step sync
    costs a full network round trip (~64ms measured), which would
    otherwise dominate this loop ~100x over the actual compute."""
    results: list[HostResult] = []
    fills: list[HostFill] = []

    def dispatch():
        nonlocal book
        for arr in build_batch_arrays(cfg, orders):
            book, pout = engine_step_packed(cfg, book, arr)
            yield arr, pout

    def decode_one(item):
        arr, pout = item
        r, f, overflow, _ = decode_step_packed(cfg, batch_view(arr), pout)
        assert not overflow, "fill buffer overflow in test harness"
        results.extend(r)
        fills.extend(f)

    run_pipelined(dispatch(), decode_one)
    return book, results, fills


def random_order_stream(
    num_symbols: int,
    n_ops: int,
    seed: int = 0,
    *,
    cancel_p: float = 0.15,
    market_p: float = 0.2,
    price_base: int = 10_000,
    price_levels: int = 12,
    price_step: int = 100,
    qty_max: int = 20,
    tif_p: float = 0.0,
) -> list[HostOrder]:
    """Deterministic mixed op stream (limit/market submits + cancels).

    tif_p > 0 additionally converts that fraction of submits to a
    time-in-force variant (LIMIT -> LIMIT_IOC or LIMIT_FOK, MARKET ->
    MARKET_FOK), exercising the collapsed otype codes end to end.

    The one generator behind the parity tests, the sharding tests, and the
    benchmark, so they all exercise the same op mix. Cancels target
    previously submitted LIMIT orders (which may or may not still rest —
    canceling a filled order is a REJECTED cancel on both sides of every
    parity check). Oids are 1-based and assigned to submits only.
    """
    import random

    from matching_engine_tpu.engine.kernel import (
        BUY,
        LIMIT,
        LIMIT_FOK,
        LIMIT_IOC,
        MARKET,
        MARKET_FOK,
        OP_CANCEL,
        OP_SUBMIT,
        SELL,
    )

    rng = random.Random(seed)
    orders: list[HostOrder] = []
    live_by_sym: list[dict[int, int]] = [dict() for _ in range(num_symbols)]
    oid = 0
    for _ in range(n_ops):
        sym = rng.randrange(num_symbols)
        if live_by_sym[sym] and rng.random() < cancel_p:
            target = rng.choice(list(live_by_sym[sym]))
            side = live_by_sym[sym].pop(target)
            orders.append(HostOrder(sym, OP_CANCEL, side, oid=target))
            continue
        oid += 1
        side = rng.choice((BUY, SELL))
        otype = MARKET if rng.random() < market_p else LIMIT
        if tif_p and rng.random() < tif_p:
            if otype == MARKET:
                otype = MARKET_FOK
            else:
                otype = rng.choice((LIMIT_IOC, LIMIT_FOK))
        price = (
            0 if otype in (MARKET, MARKET_FOK)
            else price_base + price_step * rng.randrange(price_levels)
        )
        qty = rng.randrange(1, qty_max)
        orders.append(HostOrder(sym, OP_SUBMIT, side, otype, price, qty, oid=oid))
        if otype == LIMIT:
            live_by_sym[sym][oid] = side
    return orders


def snapshot_books(book: BookBatch):
    """Decode device books to the oracle's snapshot format.

    Returns per symbol: (bids, asks), each a priority-sorted list of
    (oid, price_q4, qty, seq).
    """
    bp, bq = np.asarray(book.bid_price), np.asarray(book.bid_qty)
    bo, bs = np.asarray(book.bid_oid), np.asarray(book.bid_seq)
    ap, aq = np.asarray(book.ask_price), np.asarray(book.ask_qty)
    ao, as_ = np.asarray(book.ask_oid), np.asarray(book.ask_seq)

    snaps = []
    for i in range(bp.shape[0]):
        bids = [
            (int(bo[i, j]), int(bp[i, j]), int(bq[i, j]), int(bs[i, j]))
            for j in np.nonzero(bq[i] > 0)[0]
        ]
        asks = [
            (int(ao[i, j]), int(ap[i, j]), int(aq[i, j]), int(as_[i, j]))
            for j in np.nonzero(aq[i] > 0)[0]
        ]
        bids.sort(key=lambda r: (-r[1], r[3]))
        asks.sort(key=lambda r: (r[1], r[3]))
        snaps.append((bids, asks))
    return snaps
