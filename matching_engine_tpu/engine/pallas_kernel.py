"""Pallas TPU kernel for the match loop: books pinned in VMEM per batch.

The XLA path (engine/kernel.py) expresses the per-symbol order scan as
`vmap(lax.scan)`; XLA schedules each scan step as its own fused loop body
with the book carried through HBM-visible buffers. This kernel instead
grids over symbol blocks and runs the whole B-order loop inside one
program, with the block's book slices resident in VMEM end to end — one
HBM read and one HBM write per book field per engine step, regardless of B
(SURVEY.md §7 step 5: "Pallas kernel for the match inner loop").

Algorithm parity: this is the same masked priority-matrix allocation as
kernel._match_one, vectorized over a [SB] symbol-block axis, with the two
scatter sites (fill-by-rank, global compaction) replaced by one-hot
reductions and left to the shared epilogue respectively. All math is int32;
outputs are bit-identical to the XLA path and the host oracle
(tests/test_pallas.py asserts both, in interpret mode; the compiled kernel
was verified bit-identical on TPU hardware as well).

STATUS — correct but not yet competitive. Measured on a single TPU chip at
the bench config (S=1024, CAP=128, B=16): XLA scan path ~215M orders/s,
this kernel ~0.3M orders/s. The [SB, CAP, CAP] priority-matrix broadcasts
(`key[:, :, None]` — a lane->sublane transpose per order per field) relayout
poorly under Mosaic, and per-symbol 2D blocks are not an option (block
sublane dims must be multiples of 8). The XLA formulation is HBM-bound on
the scan carry and already 20x the north-star target, so this path stays
flag-gated (EngineConfig.pallas=False by default) as the seed for future
kernel work, not the production path.

TPU notes (per /opt/skills/guides/pallas_guide.md):
- iota is 2D (`broadcasted_iota`); all blocks carry an [SB, ...] leading
  axis so every intermediate is >= 2D.
- Mosaic rejects vector i1/i8 masks (arith.trunci to i1 fails to lower),
  so all masks are int32 0/1 tensors and selection is arithmetic (_sel).
- book blocks are [SB, CAP] int32 — CAP is the lane dim (128-friendly);
  the [SB, CAP, CAP] priority matrix at SB=8, CAP=128 is 512 KiB of VMEM.
- input_output_aliases donate the nine book buffers in place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
)
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    FILLED,
    MARKET,
    NEW,
    NOOP_STATUS,
    OP_CANCEL,
    OP_SUBMIT,
    PARTIALLY_FILLED,
    REJECTED,
    BUY,
)


def _symbol_block(num_symbols: int) -> int:
    """Largest power-of-two block <= 8 dividing the symbol axis."""
    for sb in (8, 4, 2, 1):
        if num_symbols % sb == 0:
            return sb
    return 1


def _match_kernel(
    # book refs [SB, CAP] (+ next_seq [SB, 1])
    bid_price_ref, bid_qty_ref, bid_oid_ref, bid_seq_ref,
    ask_price_ref, ask_qty_ref, ask_oid_ref, ask_seq_ref, next_seq_ref,
    # order refs [SB, B]
    op_ref, side_ref, otype_ref, price_ref, qty_ref, oid_ref,
    # outputs: aliased book refs, then per-order outputs
    o_bid_price_ref, o_bid_qty_ref, o_bid_oid_ref, o_bid_seq_ref,
    o_ask_price_ref, o_ask_qty_ref, o_ask_oid_ref, o_ask_seq_ref,
    o_next_seq_ref,
    status_ref, filled_ref, remaining_ref,      # [SB, B]
    f_oid_ref, f_qty_ref, f_price_ref,          # [SB, B, CAP]
    *, batch: int,
):
    cap = bid_price_ref.shape[1]
    sb = bid_price_ref.shape[0]
    idx = jax.lax.broadcasted_iota(I32, (sb, cap), 1)

    # Mosaic note: boolean vectors (i1/i8) do not lower reliably on TPU, so
    # every mask here is an int32 0/1 tensor (comparisons are cast
    # immediately) and selection is arithmetic. `_sel` is exact even when
    # (a - b) wraps: int32 is two's-complement mod-2^32, so b + (a-b)*1 == a
    # regardless of intermediate overflow.
    def m(cond):
        return cond.astype(I32)

    def _sel(mask, a, b):
        return b + (a - b) * mask

    book0 = (
        bid_price_ref[:], bid_qty_ref[:], bid_oid_ref[:], bid_seq_ref[:],
        ask_price_ref[:], ask_qty_ref[:], ask_oid_ref[:], ask_seq_ref[:],
        next_seq_ref[:, 0],
    )

    def body(b, book):
        (bid_price, bid_qty, bid_oid, bid_seq,
         ask_price, ask_qty, ask_oid, ask_seq, next_seq) = book
        op = op_ref[:, b]
        side = side_ref[:, b]
        otype = otype_ref[:, b]
        price = price_ref[:, b]
        qty = qty_ref[:, b]
        oid = oid_ref[:, b]

        m_submit = m(op == OP_SUBMIT)           # [SB]
        m_cancel = m(op == OP_CANCEL)
        m_buy = m(side == BUY)[:, None]         # [SB, 1]
        m_market = m(otype == MARKET)

        # ---- opposite side (maker candidates) ---------------------------
        opp_price = _sel(m_buy, ask_price, bid_price)
        opp_qty = _sel(m_buy, ask_qty, bid_qty)
        opp_oid = _sel(m_buy, ask_oid, bid_oid)
        opp_seq = _sel(m_buy, ask_seq, bid_seq)

        key = _sel(m_buy, opp_price, -opp_price)
        m_price_ok = _sel(
            m_buy,
            m(opp_price <= price[:, None]),
            m(opp_price >= price[:, None]),
        )
        m_elig = (
            m(opp_qty > 0)
            * jnp.maximum(m_market[:, None], m_price_ok)
            * m_submit[:, None]
        )

        # better[s, k, j]: maker k strictly ahead of maker j.
        m_better = jnp.maximum(
            m(key[:, :, None] < key[:, None, :]),
            m(key[:, :, None] == key[:, None, :])
            * m(opp_seq[:, :, None] < opp_seq[:, None, :]),
        )
        elig_qty = m_elig * opp_qty
        ahead = jnp.sum(m_better * elig_qty[:, :, None], axis=1)

        take_q = m_submit * qty
        fill = m_elig * jnp.clip(take_q[:, None] - ahead, 0, opp_qty)
        filled_total = jnp.sum(fill, axis=1)
        remaining = take_q - filled_total
        new_opp_qty = opp_qty - fill

        # Priority rank of each eligible maker; filled slots are a priority
        # prefix, so rank doubles as the fill-log slot. The XLA path
        # scatters by rank; here a one-hot reduction produces the same
        # rank-indexed rows without a scatter.
        rank = jnp.sum(
            m_better * m_elig[:, :, None] * m_elig[:, None, :], axis=1
        )
        m_has_fill = m(fill > 0)
        onehot = m_has_fill[:, :, None] * m(rank[:, :, None] == idx[:, None, :])
        f_oid_b = jnp.sum(onehot * opp_oid[:, :, None], axis=1)
        f_qty_b = jnp.sum(onehot * fill[:, :, None], axis=1)
        f_price_b = jnp.sum(onehot * opp_price[:, :, None], axis=1)

        # ---- own side: rest a LIMIT remainder / cancel ------------------
        own_price = _sel(m_buy, bid_price, ask_price)
        own_qty = _sel(m_buy, bid_qty, ask_qty)
        own_oid = _sel(m_buy, bid_oid, ask_oid)
        own_seq = _sel(m_buy, bid_seq, ask_seq)

        m_do_rest = m_submit * (1 - m_market) * m(remaining > 0)
        m_free = m(own_qty == 0)
        m_has_free = jnp.max(m_free, axis=1)
        slot_idx = jnp.min(_sel(m_free, idx, cap), axis=1)
        m_rested = m_do_rest * m_has_free

        at_slot = m_rested[:, None] * m(idx == slot_idx[:, None])
        own_price = _sel(at_slot, jnp.broadcast_to(price[:, None], own_price.shape), own_price)
        own_qty = _sel(at_slot, jnp.broadcast_to(remaining[:, None], own_qty.shape), own_qty)
        own_oid = _sel(at_slot, jnp.broadcast_to(oid[:, None], own_oid.shape), own_oid)
        own_seq = _sel(at_slot, jnp.broadcast_to(next_seq[:, None], own_seq.shape), own_seq)
        next_seq = next_seq + m_rested

        cancel_mask = (
            m_cancel[:, None] * m(own_oid == oid[:, None]) * m(own_qty > 0)
        )
        cancel_qty = jnp.sum(cancel_mask * own_qty, axis=1)
        m_cancel_ok = jnp.max(cancel_mask, axis=1)
        own_qty = own_qty * (1 - cancel_mask)

        # ---- write back -------------------------------------------------
        new_book = (
            _sel(m_buy, own_price, opp_price),
            _sel(m_buy, own_qty, new_opp_qty),
            _sel(m_buy, own_oid, opp_oid),
            _sel(m_buy, own_seq, opp_seq),
            _sel(m_buy, opp_price, own_price),
            _sel(m_buy, new_opp_qty, own_qty),
            _sel(m_buy, opp_oid, own_oid),
            _sel(m_buy, opp_seq, own_seq),
            next_seq,
        )

        # ---- status -----------------------------------------------------
        submit_status = _sel(
            m(remaining == 0),
            jnp.full_like(op, FILLED),
            _sel(
                m_market,
                jnp.full_like(op, CANCELED),
                _sel(
                    m_rested,
                    _sel(m(filled_total > 0),
                         jnp.full_like(op, PARTIALLY_FILLED),
                         jnp.full_like(op, NEW)),
                    jnp.full_like(op, REJECTED),
                ),
            ),
        )
        cancel_status = _sel(
            m_cancel_ok, jnp.full_like(op, CANCELED), jnp.full_like(op, REJECTED)
        )
        status = _sel(
            m_submit,
            submit_status,
            _sel(m_cancel, cancel_status, jnp.full_like(op, NOOP_STATUS)),
        ).astype(I32)
        out_remaining = _sel(
            m_submit, remaining, m_cancel * cancel_qty
        ).astype(I32)

        status_ref[:, pl.ds(b, 1)] = status[:, None]
        filled_ref[:, pl.ds(b, 1)] = filled_total.astype(I32)[:, None]
        remaining_ref[:, pl.ds(b, 1)] = out_remaining[:, None]
        f_oid_ref[:, pl.ds(b, 1), :] = f_oid_b.astype(I32)[:, None, :]
        f_qty_ref[:, pl.ds(b, 1), :] = f_qty_b.astype(I32)[:, None, :]
        f_price_ref[:, pl.ds(b, 1), :] = f_price_b.astype(I32)[:, None, :]
        return new_book

    # B is static — a Python loop fully unrolls the order sequence (no
    # data-dependent trip count; the scheduler pipelines across iterations).
    book = book0
    for b in range(batch):
        book = body(b, book)
    (o_bid_price_ref[:], o_bid_qty_ref[:], o_bid_oid_ref[:],
     o_bid_seq_ref[:], o_ask_price_ref[:], o_ask_qty_ref[:],
     o_ask_oid_ref[:], o_ask_seq_ref[:]) = book[:8]
    o_next_seq_ref[:, 0] = book[8]


@functools.partial(jax.jit, static_argnums=0)
def match_batch_pallas(cfg: EngineConfig, book: BookBatch, orders: OrderBatch):
    """Run the match loop as a Pallas kernel.

    Returns (new_book, (status, filled, remaining, f_oid, f_qty, f_price))
    with the same shapes/semantics as the XLA scan path; callers feed the
    per-order tuple to kernel.finalize_step.
    """
    s, cap, b = cfg.num_symbols, cfg.capacity, cfg.batch
    sb = _symbol_block(s)
    grid = (s // sb,)

    interpret = cfg.pallas_interpret
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    if not interpret and sb != 8:
        # Mosaic requires sublane-dim blocks in multiples of 8 on real TPU
        # (module docstring); sub-8 blocks only exist when the symbol axis
        # isn't divisible by 8. Reject loudly rather than fail inside Mosaic.
        raise ValueError(
            f"pallas=True on a TPU backend needs num_symbols % 8 == 0 "
            f"(got {s}); pad the symbol axis or use the XLA path"
        )

    def row_spec():
        return pl.BlockSpec((sb, cap), lambda i: (i, 0), memory_space=pltpu.VMEM)

    def seq_spec():
        return pl.BlockSpec((sb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    def ord_spec():
        return pl.BlockSpec((sb, b), lambda i: (i, 0), memory_space=pltpu.VMEM)

    def fill_spec():
        return pl.BlockSpec(
            (sb, b, cap), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        )

    sds = jax.ShapeDtypeStruct
    out_shape = (
        *(sds((s, cap), I32) for _ in range(8)),   # book sides
        sds((s, 1), I32),                          # next_seq
        sds((s, b), I32), sds((s, b), I32), sds((s, b), I32),
        sds((s, b, cap), I32), sds((s, b, cap), I32), sds((s, b, cap), I32),
    )
    out_specs = (
        *(row_spec() for _ in range(8)),
        seq_spec(),
        ord_spec(), ord_spec(), ord_spec(),
        fill_spec(), fill_spec(), fill_spec(),
    )
    in_specs = [
        *(row_spec() for _ in range(8)),
        seq_spec(),
        *(ord_spec() for _ in range(6)),
    ]

    outs = pl.pallas_call(
        functools.partial(_match_kernel, batch=b),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        # Donate the nine book buffers in place (input i -> output i).
        input_output_aliases={i: i for i in range(9)},
        interpret=interpret,
    )(
        book.bid_price, book.bid_qty, book.bid_oid, book.bid_seq,
        book.ask_price, book.ask_qty, book.ask_oid, book.ask_seq,
        book.next_seq[:, None],
        orders.op, orders.side, orders.otype, orders.price, orders.qty,
        orders.oid,
    )
    new_book = BookBatch(*outs[:8], next_seq=outs[8][:, 0])
    status, filled, remaining, f_oid, f_qty, f_price = outs[9:]
    return new_book, (status, filled, remaining, f_oid, f_qty, f_price)
