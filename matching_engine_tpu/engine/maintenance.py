"""Book maintenance ops that run OFF the hot path: seq rebasing.

Each book's `next_seq` is a per-symbol int32 arrival counter; price-time
priority ties break on it, and the sorted kernel's dense-prefix invariant
is (price, seq)-ordered. Nothing in the hot path bounds it — after 2^31
arrivals on ONE symbol the counter wraps and new orders silently jump the
time-priority queue (and a sorted-kernel book's invariant corrupts with
it). The reference never faced this (its engine file is empty and its one
counter is the 64-bit OID sequence); a venue-grade engine must.

`rebase_seqs` renumbers every book's live seqs to [0, live_count) in
priority order — (price, seq) ordering is exactly preserved, so matching
behavior is bit-identical before/after — and resets `next_seq` to the
max live count per book. It is a rare, fixed-shape, jitted device op
(O(C log C) lexsort per side) intended for quiesce points: the
CheckpointDaemon runs it under the dispatch lock whenever any book's
counter crosses REBASE_THRESHOLD (headroom of 2^30 before the cliff).

For sorted-kernel books the renumbering is the identity permutation by
construction (lanes already sit in priority order), so the invariant is
preserved trivially; for matrix books lanes are unordered and the rank
comes from the lexsort.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.book import I32, BookBatch, EngineConfig

IMAX = jnp.iinfo(jnp.int32).max

# Trigger with plenty of headroom: 2^30 arrivals on one book leaves
# another 2^30 before the wrap even if every check is missed once.
REBASE_THRESHOLD = 1 << 30


def _rank_side(price, qty, seq, best_is_max):
    """New seq per lane: the lane's price-time priority rank among live
    lanes (dead lanes keep seq 0 — they are never read, qty==0 masks).

    Liveness is the PRIMARY sort key (lexsort's last key), so dead lanes
    sort strictly after every live lane no matter what stale price/seq
    they hold — a sentinel-in-the-key scheme would collide with a legal
    live ask at price 2^31-1 (validation admits it) and hand it a rank
    past the live count."""
    live = qty > 0
    key = -price if best_is_max else price
    order = jnp.lexsort((seq, key, (~live).astype(I32)))
    cap = price.shape[0]
    rank = jnp.zeros((cap,), I32).at[order].set(jnp.arange(cap, dtype=I32))
    return jnp.where(live, rank, 0).astype(I32), jnp.sum(live).astype(I32)


@partial(jax.jit, static_argnums=0, donate_argnums=1)
def rebase_seqs(cfg: EngineConfig, book: BookBatch) -> BookBatch:
    """Renumber all books' seqs to dense priority ranks; next_seq becomes
    the max live count per book (strictly above every assigned seq)."""
    bid_seq, nb = jax.vmap(partial(_rank_side, best_is_max=True))(
        book.bid_price, book.bid_qty, book.bid_seq)
    ask_seq, na = jax.vmap(partial(_rank_side, best_is_max=False))(
        book.ask_price, book.ask_qty, book.ask_seq)
    return book._replace(
        bid_seq=bid_seq, ask_seq=ask_seq,
        next_seq=jnp.maximum(nb, na).astype(I32))
