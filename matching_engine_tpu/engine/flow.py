"""Realistic L3 flow generation (VERDICT r3 next-step 6).

`random_order_stream` (engine/harness.py) is uniform-ish synthetic flow:
every symbol equally active, shallow 100-level ladders, no bursts — the
regime that flatters the O(CAP^2) priority matrix (sparse books = cheap
rows). This module generates the flow shapes real venues see, so the
config-3b benchmark row measures the engine where it is EXPENSIVE:

- **Power-law symbol activity** (Zipf, alpha ~1.1): a few symbols take
  most of the flow — their books and scan rows stay hot and deep while
  the tail stays sparse (real-venue concentration).
- **Bursts**: Poisson-triggered flurries where a handful of hot symbols
  receive a correlated run of orders (news/sweep events) — stresses the
  per-symbol sequential scan, since one symbol's orders can't parallelize
  across the batch axis.
- **Deep-book regimes**: a configurable fraction of symbols runs
  maker-heavy flow over a wide ladder with low cancel rates, driving
  resting depth toward book capacity — where the [CAP, CAP] matrix does
  maximal work and side-full REJECTEDs appear (reported by the bench).
- **Mid-price random walk** per symbol: limit prices cluster around a
  drifting touch (geometric offsets), as L3 data does, instead of
  resampling a fixed ladder.

Deterministic per seed; integer Q4 prices; oids 1-based on submits only —
the same contract as random_order_stream, so the parity oracle and
measure_device_throughput consume it unchanged (tests/test_flow.py).
"""

from __future__ import annotations

import bisect
import itertools
import random

from matching_engine_tpu.engine.harness import HostOrder
from matching_engine_tpu.engine.kernel import (
    BUY,
    LIMIT,
    LIMIT_FOK,
    LIMIT_IOC,
    MARKET,
    MARKET_FOK,
    OP_CANCEL,
    OP_SUBMIT,
    SELL,
)


def realistic_order_stream(
    num_symbols: int,
    n_ops: int,
    seed: int = 0,
    *,
    alpha: float = 1.1,          # Zipf exponent over symbol activity
    deep_fraction: float = 0.1,  # symbols running the deep-book regime
    burst_p: float = 0.004,      # per-op chance a burst starts
    burst_len: int = 150,        # ops per burst
    burst_symbols: int = 4,      # hot symbols sharing one burst
    cancel_p: float = 0.08,
    market_p: float = 0.10,
    tif_p: float = 0.05,         # fraction of submits carrying IOC/FOK
    price_base: int = 10_000,
    qty_max: int = 100,
) -> list[HostOrder]:
    """One chronological mixed-op stream with the regimes above."""
    rng = random.Random(seed)

    # Zipf activity over a shuffled symbol permutation (hot symbols must
    # not correlate with slot order — slot order is a device layout).
    perm = list(range(num_symbols))
    rng.shuffle(perm)
    weights = [(i + 1) ** -alpha for i in range(num_symbols)]
    # Deep-regime membership rides the HOT end (real concentration:
    # the busiest names also carry the most resting depth).
    n_deep = max(1, int(num_symbols * deep_fraction))
    deep = {perm[i] for i in range(n_deep)}

    mid = [price_base + rng.randrange(-500, 501) for _ in range(num_symbols)]
    live: list[dict[int, int]] = [dict() for _ in range(num_symbols)]

    orders: list[HostOrder] = []
    oid = 0
    burst_left = 0
    burst_pool: list[int] = []

    # Inverse-CDF sampling: O(log S) per draw via bisect on the
    # cumulative weights, computed ONCE — rng.choices re-accumulates its
    # weight list on every call, which made stream generation
    # O(n_ops * num_symbols) and dominated bench setup at S=4096
    # (ADVICE r4 low / VERDICT r4 next-step 7).
    cum_w = list(itertools.accumulate(weights))
    total_w = cum_w[-1]

    def pick_symbol() -> int:
        if burst_left > 0:
            return rng.choice(burst_pool)
        return perm[bisect.bisect_right(cum_w, rng.random() * total_w)]

    while len(orders) < n_ops:
        if burst_left > 0:
            burst_left -= 1
        elif rng.random() < burst_p:
            burst_left = burst_len
            # Bursts hit hot names (the Zipf head) plus one random tail.
            burst_pool = [perm[i] for i in
                          rng.sample(range(min(16, num_symbols)),
                                     k=min(burst_symbols - 1, 16,
                                           num_symbols))]
            if num_symbols > 16:  # one tail name, distinct from the head
                burst_pool.append(perm[rng.randrange(16, num_symbols)])
            if not burst_pool:  # burst_symbols=1 at tiny S: never empty
                burst_pool.append(perm[rng.randrange(num_symbols)])
        sym = pick_symbol()

        is_deep = sym in deep
        # Deep regime: maker-heavy, wide ladder, sticky resting orders.
        c_p = cancel_p * (0.3 if is_deep else 1.0)
        m_p = market_p * (0.5 if is_deep else 1.0)
        if live[sym] and rng.random() < c_p:
            target = rng.choice(list(live[sym]))
            side = live[sym].pop(target)
            orders.append(HostOrder(sym, OP_CANCEL, side, oid=target))
            continue
        # Mid-price random walk (lazy: only when the symbol trades).
        if rng.random() < 0.2:
            mid[sym] += rng.choice((-1, 0, 0, 1))
        oid += 1
        side = rng.choice((BUY, SELL))
        otype = MARKET if rng.random() < m_p else LIMIT
        # A slice of real flow is IOC/FOK (aggressive participants who
        # never rest) — exercises the tif codes under venue-shaped load.
        is_tif = bool(tif_p) and rng.random() < tif_p
        if is_tif:
            if otype == MARKET:
                otype = MARKET_FOK
            else:
                otype = rng.choice((LIMIT_IOC, LIMIT_FOK))
        if otype in (MARKET, MARKET_FOK):
            price = 0
        else:
            # Geometric offset from the touch: most orders near the mid,
            # a long tail of passive depth. Deep symbols ladder wider.
            spread = 2 if not is_deep else 1
            off = 0
            step_p = 0.55 if is_deep else 0.35
            while rng.random() < step_p and off < 500:
                off += 1
            # Passive flow prices on its OWN side of the touch; the
            # IOC/FOK slice prices THROUGH it (aggressors cross or they
            # are pointless) — reaching the partial-fill-remainder-cancel
            # and FOK all-or-nothing paths, not just zero-fill cancels.
            aggress = -1 if is_tif else 1
            price = mid[sym] + aggress * (spread + off) * (
                1 if side == SELL else -1)
            if price < 1:
                price = 1
        qty = rng.randrange(1, qty_max)
        orders.append(HostOrder(sym, OP_SUBMIT, side, otype, price, qty,
                                oid=oid))
        if otype == LIMIT:
            live[sym][oid] = side
    return orders
