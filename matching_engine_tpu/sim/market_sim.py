"""Agent-based market simulation, closed-loop on device.

BASELINE.json config 5 ("agent-based market sim: 4k symbols x 256
market-maker agents"): a population of market-maker agents per symbol quotes
around a random-walking fair value; their order flow feeds straight into the
match kernel *inside the same jit'd scan* — order generation, matching, and
agent-state updates never leave the device. The reference has no simulation
subsystem at all (SURVEY.md §6: it publishes no benchmarks and its engine
file is empty); this module is the TPU-native load generator its intended
capability surface implies.

Per step and symbol (batch layout, `4*refresh + markets` slots):
  [cancel old bid]*K  [cancel old ask]*K  [new bid]*K  [new ask]*K  [market]*M
Agents are refreshed round-robin (step-rotated), so every agent's quotes are
re-priced every `agents/refresh` steps. Cancels precede the replacement
quotes in batch order, and the kernel applies batch positions sequentially
per symbol, so a refresh is atomic within a step.

Everything is int32 and PRNG-driven (`jax.random` with a threaded key): the
same seed reproduces the same market bit-for-bit, and the generated flow can
be replayed through the host oracle for parity (tests/test_sim.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax

from matching_engine_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp

from matching_engine_tpu.engine.book import BookBatch, EngineConfig, OrderBatch, init_book
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_SUBMIT, engine_step_impl
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL

I32 = jnp.int32


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static sim configuration. `batch_for()` gives the EngineConfig.batch
    the order layout requires."""

    agents: int = 256          # market makers per symbol
    refresh: int = 8           # agents re-quoted per step (round-robin)
    markets: int = 4           # noise market orders per symbol per step
    half_spread: int = 5       # Q4 ticks each side of fair value
    spread_jitter: int = 8     # extra per-quote price noise in [0, jitter)
    qty_max: int = 100         # quote/market size drawn from [1, qty_max]
    fair_vol: int = 3          # fair-value random-walk step in [-vol, vol]
    fair_init: int = 10_000    # initial Q4 fair value, all symbols
    fair_min: int = 100        # random-walk clamp (keeps prices positive)
    fair_max: int = 1 << 24

    def batch_for(self) -> int:
        return 4 * self.refresh + self.markets

    def __post_init__(self):
        assert 0 < self.refresh <= self.agents
        assert self.half_spread >= 1, "quotes must not self-cross"


class SimState(NamedTuple):
    """Device-resident agent state. Shapes [S] / [S, A].

    The PRNG key is PER SYMBOL ([S, 2]): each symbol's market is an
    independent stochastic process, which makes the whole sim pure SPMD —
    symbol-sharding it over a mesh changes nothing about any symbol's
    stream (tests/test_sim.py asserts sharded == single-device)."""

    keys: jax.Array       # [S, 2] per-symbol PRNG keys
    step: jax.Array       # scalar int32 step counter (drives round-robin)
    fair: jax.Array       # [S] fair-value random walk (Q4)
    mm_bid_oid: jax.Array  # [S, A] each agent's resting bid oid (0 = none)
    mm_ask_oid: jax.Array  # [S, A]
    next_oid: jax.Array   # [S] per-symbol oid counter (oids unique per symbol)


class StepStats(NamedTuple):
    """Per-step scalars, cheap to stack over a scan."""

    real_ops: jax.Array   # non-padding ops dispatched (cancel slots with no
                          # resting quote are OP_NOOP; throughput counts real)
    fills: jax.Array      # number of fill records
    volume: jax.Array     # total traded quantity
    spread: jax.Array     # mean top-of-book spread over two-sided symbols
    resting: jax.Array    # live resting orders across all books


def init_sim(cfg: EngineConfig, scfg: SimConfig, seed: int = 0) -> SimState:
    s, a = cfg.num_symbols, scfg.agents
    base = jax.random.PRNGKey(seed)
    # Per-symbol independent streams, derived from the GLOBAL symbol index —
    # a sharded run folds in the same indices, so symbol i's market is
    # identical at any mesh size.
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(s))
    return SimState(
        keys=keys,
        step=jnp.zeros((), I32),
        fair=jnp.full((s,), scfg.fair_init, I32),
        mm_bid_oid=jnp.zeros((s, a), I32),
        mm_ask_oid=jnp.zeros((s, a), I32),
        next_oid=jnp.ones((s,), I32),
    )


def _gen_orders(cfg: EngineConfig, scfg: SimConfig, state: SimState):
    """One step of agent decisions -> (new_state, OrderBatch)."""
    s, k, m = cfg.num_symbols, scfg.refresh, scfg.markets

    # Per-symbol key fan-out: 7 subkeys per symbol, all draws vmapped.
    subs = jax.vmap(lambda kk: jax.random.split(kk, 7))(state.keys)  # [S, 7, 2]
    keys = subs[:, 0]

    def draw(col, fn):
        return jax.vmap(fn)(subs[:, col])

    # Fair value random walk, clamped.
    fair = jnp.clip(
        state.fair + draw(1, lambda kk: jax.random.randint(
            kk, (), -scfg.fair_vol, scfg.fair_vol + 1, I32)),
        scfg.fair_min, scfg.fair_max,
    )

    # Round-robin refresh set (same agent indices across symbols).
    idx = (state.step * k + jnp.arange(k, dtype=I32)) % scfg.agents  # [K]

    old_bid = state.mm_bid_oid[:, idx]  # [S, K]
    old_ask = state.mm_ask_oid[:, idx]

    # New quotes around fair value.
    jb = draw(2, lambda kk: jax.random.randint(kk, (k,), 0, scfg.spread_jitter, I32))
    ja = draw(3, lambda kk: jax.random.randint(kk, (k,), 0, scfg.spread_jitter, I32))
    bid_px = jnp.maximum(fair[:, None] - scfg.half_spread - jb, 1)
    ask_px = fair[:, None] + scfg.half_spread + ja
    qty = draw(4, lambda kk: jax.random.randint(kk, (2 * k,), 1, scfg.qty_max + 1, I32))

    # Oid assignment: submits in batch order get consecutive per-symbol oids.
    base = state.next_oid[:, None]  # [S, 1]
    bid_oid = base + jnp.arange(k, dtype=I32)[None, :]
    ask_oid = base + k + jnp.arange(k, dtype=I32)[None, :]
    mkt_oid = base + 2 * k + jnp.arange(m, dtype=I32)[None, :]

    # Noise market orders.
    mside = draw(5, lambda kk: jax.random.randint(kk, (m,), 0, 2, I32)) + BUY
    mqty = draw(6, lambda kk: jax.random.randint(kk, (m,), 1, scfg.qty_max + 1, I32))

    def seg(op, side, otype, price, q, oid):
        # owner 0: sim agents opt out of self-trade prevention (makers
        # cancel-then-requote, so self-crossing is already structural).
        return (op, side, otype, price, q, oid, jnp.zeros_like(op))

    zeros_k = jnp.zeros((s, k), I32)
    zeros_m = jnp.zeros((s, m), I32)
    segs = [
        # Cancel the refreshed agents' old quotes (no-op where none rests).
        seg(jnp.where(old_bid > 0, OP_CANCEL, 0), jnp.full((s, k), BUY, I32),
            zeros_k, zeros_k, zeros_k, old_bid),
        seg(jnp.where(old_ask > 0, OP_CANCEL, 0), jnp.full((s, k), SELL, I32),
            zeros_k, zeros_k, zeros_k, old_ask),
        # Replacement quotes.
        seg(jnp.full((s, k), OP_SUBMIT, I32), jnp.full((s, k), BUY, I32),
            jnp.full((s, k), LIMIT, I32), bid_px, qty[:, :k], bid_oid),
        seg(jnp.full((s, k), OP_SUBMIT, I32), jnp.full((s, k), SELL, I32),
            jnp.full((s, k), LIMIT, I32), ask_px, qty[:, k:], ask_oid),
        # Noise takers.
        seg(jnp.full((s, m), OP_SUBMIT, I32), mside,
            jnp.full((s, m), MARKET, I32), zeros_m, mqty, mkt_oid),
    ]
    orders = OrderBatch(*(jnp.concatenate(parts, axis=1) for parts in zip(*segs)))

    new_state = SimState(
        keys=keys,
        step=state.step + 1,
        fair=fair,
        mm_bid_oid=state.mm_bid_oid.at[:, idx].set(bid_oid),
        mm_ask_oid=state.mm_ask_oid.at[:, idx].set(ask_oid),
        next_oid=state.next_oid + 2 * k + m,
    )
    return new_state, orders


def sim_step_impl(cfg: EngineConfig, scfg: SimConfig, book: BookBatch, state: SimState,
                  axis: str | None = None):
    """One closed-loop step: agents -> orders -> match -> stats.

    Returns (book, state, orders, stats); compose under jit/scan. With
    `axis` set (inside shard_map over that mesh axis), stats are psum'd so
    every shard reports the GLOBAL market totals.
    """
    state, orders = _gen_orders(cfg, scfg, state)
    book, out = engine_step_impl(cfg, book, orders)

    both = (out.best_bid > 0) & (out.best_ask > 0)
    sums = dict(
        real_ops=jnp.sum(orders.op != 0),
        fills=out.fill_count,
        volume=jnp.sum(out.fill_qty),
        spread_sum=jnp.sum(jnp.where(both, out.best_ask - out.best_bid, 0)),
        both_n=jnp.sum(both),
        resting=jnp.sum(book.bid_qty > 0) + jnp.sum(book.ask_qty > 0),
    )
    if axis is not None:
        sums = {name: jax.lax.psum(v, axis) for name, v in sums.items()}
    stats = StepStats(
        real_ops=sums["real_ops"].astype(I32),
        fills=sums["fills"].astype(I32),
        volume=sums["volume"].astype(I32),
        spread=jnp.where(
            sums["both_n"] > 0,
            sums["spread_sum"] // jnp.maximum(sums["both_n"], 1),
            0,
        ).astype(I32),
        resting=sums["resting"].astype(I32),
    )
    return book, state, orders, stats


def _run_impl(cfg: EngineConfig, scfg: SimConfig, steps: int, collect_orders: bool,
              book: BookBatch, state: SimState, axis: str | None = None):
    def scan_body(carry, _):
        book, state = carry
        book, state, orders, stats = sim_step_impl(cfg, scfg, book, state, axis=axis)
        return (book, state), (stats, orders if collect_orders else None)

    (book, state), (stats, orders) = jax.lax.scan(
        scan_body, (book, state), None, length=steps
    )
    return book, state, stats, orders


# Module-level jit so repeated run_sim calls with the same static config hit
# the compile cache (a per-call @jax.jit closure would re-trace every time).
_run_jit = jax.jit(_run_impl, static_argnums=(0, 1, 2, 3))


def run_sim(
    cfg: EngineConfig,
    scfg: SimConfig,
    steps: int,
    seed: int = 0,
    collect_orders: bool = False,
):
    """Run `steps` closed-loop steps under one jit'd lax.scan.

    Returns (book, state, stats[T], orders[T] | None). With
    collect_orders=True the per-step OrderBatches are stacked and returned
    (host replay / parity testing; memory scales with T*S*B — keep small).
    """
    assert cfg.batch == scfg.batch_for(), (
        f"EngineConfig.batch must be {scfg.batch_for()} for this SimConfig"
    )
    book = init_book(cfg)
    state = init_sim(cfg, scfg, seed)
    return _run_jit(cfg, scfg, steps, collect_orders, book, state)


def run_sim_sharded(
    cfg: EngineConfig,
    scfg: SimConfig,
    mesh,
    steps: int,
    seed: int = 0,
):
    """run_sim over a symbol-sharded mesh (BASELINE config 5's "pmap'd
    across v4-8" form).

    Pure SPMD: each shard runs its symbol slice's independent markets; the
    only collectives are the per-step stat psums. Because PRNG streams are
    per-symbol (folded from GLOBAL symbol indices), results are bit-identical
    to the single-device run at any mesh size (tests/test_sim.py).

    Returns (book, state, stats[T]) — book/state remain device-sharded.
    """
    import dataclasses

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from matching_engine_tpu.parallel.sharding import AXIS, _book_specs

    assert cfg.batch == scfg.batch_for(), (
        f"EngineConfig.batch must be {scfg.batch_for()} for this SimConfig"
    )
    n = mesh.devices.size
    if cfg.num_symbols % n != 0:
        raise ValueError(f"num_symbols={cfg.num_symbols} not divisible by mesh size {n}")
    local_cfg = dataclasses.replace(cfg, num_symbols=cfg.num_symbols // n)

    state_specs = SimState(
        keys=P(AXIS, None), step=P(), fair=P(AXIS),
        mm_bid_oid=P(AXIS, None), mm_ask_oid=P(AXIS, None), next_oid=P(AXIS),
    )
    stats_specs = StepStats(*(P(),) * len(StepStats._fields))  # psum'd -> replicated

    def local_run(book, state):
        book, state, stats, _ = _run_impl(
            local_cfg, scfg, steps, False, book, state, axis=AXIS)
        return book, state, stats

    mapped = jax.jit(shard_map(
        local_run,
        mesh=mesh,
        in_specs=(_book_specs(), state_specs),
        out_specs=(_book_specs(), state_specs, stats_specs),
    ))

    book = jax.device_put(
        init_book(cfg), jax.tree.map(lambda s: NamedSharding(mesh, s), _book_specs()))
    state = jax.device_put(
        init_sim(cfg, scfg, seed),
        jax.tree.map(lambda s: NamedSharding(mesh, s), state_specs))
    return mapped(book, state)
