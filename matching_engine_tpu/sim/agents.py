"""Heterogeneous on-device agent populations (ROADMAP Open item 4).

market_sim.py drives one agent type (market makers). Real venues face
*mixed* flow — passive quoting, trend-chasing, heavy-tailed retail noise,
aggressive liquidity taking — whose correlations produce the stress
shapes uniform fuzz never does (JAX-LOB, arXiv:2308.13289, runs exactly
such populations vmapped on device; CoinTossX, arXiv:2102.10925,
catalogues the resulting scenarios). This module generalizes the sim to
four agent classes, all generated *inside the same jit'd scan* as the
match kernel, all int32, all `jax.random`-keyed per symbol — one seed
reproduces the whole market bit-for-bit, and the generated flow replays
through the host oracle (tests/test_scenarios.py).

Per step and symbol the batch layout is STATIC (shape-stable under jit):

    [mm cancel bid]*K [mm cancel ask]*K [mm bid]*K [mm ask]*K
    [momentum]*Mo [noise]*Nz [taker]*Tk          (B = 4K+Mo+Nz+Tk)

- **Market makers** (class 0): the market_sim design — K agents refreshed
  round-robin per step cancel their old quotes and re-quote around the
  fair-value random walk.
- **Momentum / trend followers** (class 1): react to the TOP-OF-BOOK
  return. An integer EMA of mid-price changes (`mom_sig`) accumulates per
  symbol; when it exceeds a threshold, momentum lanes fire MARKET orders
  *in the direction of the move*, sized by signal strength — the
  amplification loop that turns an injected shock into a cascade
  (scenarios.flash_crash).
- **Noise traders** (class 2): random-side LIMIT orders priced around
  fair value with HEAVY-TAILED sizes — an integer Pareto draw
  (`qty ~ scale // uniform`, P(q >= x) ~ 1/x) clipped to a cap, so a
  small fraction of orders are book-sweeping blocks.
- **Aggressive takers** (class 3): probabilistic MARKET orders; under a
  scenario's sell-bias window (the shock) they all hit bids at double
  size.

Per-symbol gating (Zipf hot-symbol skew, burst on/off, halts) suppresses
whole symbols via kernel.apply_halt_mask — gated symbols advance no agent
state, so their quotes simply stand.

Everything here must stay pure under trace: the jit-purity analyzer
walks this module as part of the sim jit roots' closure.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from matching_engine_tpu.engine.book import EngineConfig, OrderBatch
from matching_engine_tpu.engine.kernel import (
    OP_CANCEL,
    OP_SUBMIT,
    apply_halt_mask,
)
from matching_engine_tpu.proto import BUY, LIMIT, MARKET, SELL

I32 = jnp.int32

# Agent-class ids, positional in the batch layout (column_roles). The
# recorder derives per-op client identities from these + the static
# layout, so the opfile knows which class produced every record.
CLASS_MM, CLASS_MOMENTUM, CLASS_NOISE, CLASS_TAKER = 0, 1, 2, 3
CLASS_TAGS = ("mm", "mom", "nz", "tk")


@dataclasses.dataclass(frozen=True)
class AgentMix:
    """Static population configuration (hashable; jit-static). Counts are
    LANES per symbol per step; the market-maker population additionally
    has `mm_agents` resting identities refreshed `mm_refresh` at a time
    (round-robin, the market_sim contract)."""

    mm_agents: int = 64
    mm_refresh: int = 4
    momentum: int = 2          # momentum lanes per symbol per step
    noise: int = 4             # noise-trader lanes
    takers: int = 2            # aggressive-taker lanes
    half_spread: int = 5       # Q4 ticks each side of fair value
    spread_jitter: int = 8     # extra per-quote price noise in [0, jitter)
    qty_max: int = 100         # mm quote size in [1, qty_max]
    fair_vol: int = 3          # fair-value random-walk step in [-vol, vol]
    fair_init: int = 10_000
    fair_min: int = 100
    fair_max: int = 1 << 24
    noise_scale: int = 1 << 11  # Pareto numerator: qty ~ scale // u
    noise_qty_cap: int = 500    # heavy-tail clamp (<< MAX_QUANTITY)
    noise_p: int = 70           # percent chance a noise lane fires
    mom_threshold: int = 4      # |mid-return EMA| (Q4) before momentum acts
    mom_p: int = 60             # percent chance an eligible momentum lane fires
    mom_qty: int = 25           # momentum base size (scaled by signal)
    taker_p: int = 35           # percent chance a taker lane fires
    taker_qty: int = 40

    def batch_for(self) -> int:
        return 4 * self.mm_refresh + self.momentum + self.noise + self.takers

    def __post_init__(self):
        assert 0 < self.mm_refresh <= self.mm_agents
        assert self.half_spread >= 1, "quotes must not self-cross"
        assert self.mom_threshold >= 1 and self.noise_scale >= 2


class AgentState(NamedTuple):
    """Device-resident state for the whole population. Shapes [S]/[S, A].

    PRNG keys are PER SYMBOL (market_sim's SPMD contract: every symbol is
    an independent stochastic process). `prev_mid`/`mom_sig` carry the
    top-of-book memory the momentum class trades on — updated from the
    engine step's own output inside the scan (observe_market), so the
    trend loop is fully closed on device."""

    keys: jax.Array        # [S, 2]
    step: jax.Array        # scalar int32 global step
    fair: jax.Array        # [S] fair-value random walk (Q4)
    mm_bid_oid: jax.Array  # [S, A]
    mm_ask_oid: jax.Array  # [S, A]
    next_oid: jax.Array    # [S] per-symbol oid counter
    prev_mid: jax.Array    # [S] last step's TOB mid (0 = none yet)
    mom_sig: jax.Array     # [S] integer EMA of mid returns


def init_agents(cfg: EngineConfig, mix: AgentMix, seed: int = 0) -> AgentState:
    s, a = cfg.num_symbols, mix.mm_agents
    base = jax.random.PRNGKey(seed)
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(s))
    return AgentState(
        keys=keys,
        step=jnp.zeros((), I32),
        fair=jnp.full((s,), mix.fair_init, I32),
        mm_bid_oid=jnp.zeros((s, a), I32),
        mm_ask_oid=jnp.zeros((s, a), I32),
        next_oid=jnp.ones((s,), I32),
        prev_mid=jnp.zeros((s,), I32),
        mom_sig=jnp.zeros((s,), I32),
    )


def column_roles(mix: AgentMix) -> list[tuple[int, str, int]]:
    """Static batch-column layout: per column (class_id, role, lane).
    role in {"cancel_bid", "cancel_ask", "bid", "ask", "flow"}. The
    recorder (sim/record.py) uses this to attribute every generated op to
    its agent class/lane without any extra device lanes."""
    k = mix.mm_refresh
    out: list[tuple[int, str, int]] = []
    out += [(CLASS_MM, "cancel_bid", j) for j in range(k)]
    out += [(CLASS_MM, "cancel_ask", j) for j in range(k)]
    out += [(CLASS_MM, "bid", j) for j in range(k)]
    out += [(CLASS_MM, "ask", j) for j in range(k)]
    out += [(CLASS_MOMENTUM, "flow", j) for j in range(mix.momentum)]
    out += [(CLASS_NOISE, "flow", j) for j in range(mix.noise)]
    out += [(CLASS_TAKER, "flow", j) for j in range(mix.takers)]
    return out


def mm_agent_index(mix: AgentMix, step: int, lane: int) -> int:
    """The resting-identity index a market-maker column refreshes at a
    given global step — the round-robin formula the device uses, exposed
    for the recorder's client-id attribution."""
    return (step * mix.mm_refresh + lane) % mix.mm_agents


class ClassGates(NamedTuple):
    """Per-population fire-probability overrides (percent, int32). The
    defaults mirror AgentMix's static constants; the many-venue gym
    (gym/env.py) passes TRACED per-venue values instead, so one compiled
    step serves V venues with genuinely different populations while a
    venue whose gates equal the mix constants stays bit-identical to the
    single-venue scenario run (the parity oracle)."""

    noise_p: jax.Array | int
    mom_p: jax.Array | int
    taker_p: jax.Array | int


def default_gates(mix: AgentMix) -> ClassGates:
    return ClassGates(noise_p=mix.noise_p, mom_p=mix.mom_p,
                      taker_p=mix.taker_p)


def agent_orders(
    cfg: EngineConfig,
    mix: AgentMix,
    state: AgentState,
    zipf_w: jax.Array,
    *,
    call_mode,
    halt,
    burst_on,
    shock,
    sell_bias,
    gates: ClassGates | None = None,
):
    """One step of population decisions -> (new_state, OrderBatch).

    Flags accept python bools (constant-folded, the scenario runner's
    static per-phase jit) OR traced bool scalars (the many-venue gym,
    where phase programs differ per venue inside one vmapped step):
    `call_mode` (auction call period: LIMIT flow rests via the serving
    layer's OP_REST mapping — here we keep OP_SUBMIT and let the caller
    map it, see scenarios._phase_step — and market-type classes are
    gated off), `halt` (every symbol suppressed), `burst_on` (off-period
    suppresses all symbols), `shock` (int32 — per-step fair-value
    decrement while a scenario shock is active), `sell_bias` (bool —
    takers all SELL at double size). `zipf_w` is the [S] per-symbol
    activity weight in Q15 (32768 = always active). `gates` optionally
    overrides the class fire probabilities with traced per-venue values
    (defaults to the mix constants — bit-identical)."""
    s = cfg.num_symbols
    k, mo, nz, tk = mix.mm_refresh, mix.momentum, mix.noise, mix.takers
    if gates is None:
        gates = default_gates(mix)

    subs = jax.vmap(lambda kk: jax.random.split(kk, 13))(state.keys)
    keys = subs[:, 0]

    def draw(col, fn):
        return jax.vmap(fn)(subs[:, col])

    # Fair-value random walk, minus the scenario shock while active.
    fair = jnp.clip(
        state.fair
        + draw(1, lambda kk: jax.random.randint(
            kk, (), -mix.fair_vol, mix.fair_vol + 1, I32))
        - shock,
        mix.fair_min, mix.fair_max,
    )

    # Per-symbol activity gate: Zipf weight x burst window x halt.
    gate_draw = draw(2, lambda kk: jax.random.randint(kk, (), 0, 1 << 15, I32))
    active = (gate_draw < zipf_w) & burst_on & jnp.logical_not(halt)

    # ---- market makers (market_sim's round-robin refresh) ----------------
    idx = (state.step * k + jnp.arange(k, dtype=I32)) % mix.mm_agents
    old_bid = state.mm_bid_oid[:, idx]
    old_ask = state.mm_ask_oid[:, idx]
    jb = draw(3, lambda kk: jax.random.randint(kk, (k,), 0, mix.spread_jitter, I32))
    ja = draw(4, lambda kk: jax.random.randint(kk, (k,), 0, mix.spread_jitter, I32))
    bid_px = jnp.maximum(fair[:, None] - mix.half_spread - jb, 1)
    ask_px = fair[:, None] + mix.half_spread + ja
    mm_qty = draw(5, lambda kk: jax.random.randint(kk, (2 * k,), 1,
                                                   mix.qty_max + 1, I32))

    base = state.next_oid[:, None]
    bid_oid = base + jnp.arange(k, dtype=I32)[None, :]
    ask_oid = base + k + jnp.arange(k, dtype=I32)[None, :]
    mom_oid = base + 2 * k + jnp.arange(mo, dtype=I32)[None, :]
    nz_oid = base + 2 * k + mo + jnp.arange(nz, dtype=I32)[None, :]
    tk_oid = base + 2 * k + mo + nz + jnp.arange(tk, dtype=I32)[None, :]

    # ---- momentum: trade the TOB-return signal ---------------------------
    sig = state.mom_sig
    amp = jnp.clip(jnp.abs(sig) // mix.mom_threshold, 1, 4)
    mom_pct = draw(6, lambda kk: jax.random.randint(kk, (mo,), 0, 100, I32))
    mom_fire = (jnp.abs(sig)[:, None] >= mix.mom_threshold) & (
        mom_pct < gates.mom_p)
    mom_side = jnp.broadcast_to(jnp.where(sig[:, None] < 0, SELL, BUY),
                                (s, mo)).astype(I32)
    mom_qty = jnp.broadcast_to((mix.mom_qty * amp)[:, None], (s, mo))

    # ---- noise: heavy-tailed sizes around fair ---------------------------
    nz_pct = draw(7, lambda kk: jax.random.randint(kk, (nz,), 0, 100, I32))
    nz_fire = nz_pct < gates.noise_p
    nz_side = draw(8, lambda kk: jax.random.randint(kk, (nz,), 0, 2, I32)) + BUY
    span = 3 * mix.half_spread
    nz_off = draw(9, lambda kk: jax.random.randint(kk, (nz,), -span,
                                                   span + 1, I32))
    # Price on the order's own side of fair plus jitter: mostly passive,
    # occasionally crossing (the jitter can step through the spread).
    nz_px = jnp.maximum(
        fair[:, None] + jnp.where(nz_side == BUY, -1, 1) * mix.half_spread
        + nz_off, 1)
    # Integer Pareto: u ~ U[1, scale), qty = clip(scale // u, 1, cap)
    # gives P(qty >= q) ~ 1/q — a genuine heavy tail in pure int32.
    nz_u = draw(10, lambda kk: jax.random.randint(kk, (nz,), 1,
                                                  mix.noise_scale, I32))
    nz_qty = jnp.clip(mix.noise_scale // nz_u, 1, mix.noise_qty_cap)

    # ---- takers: aggressive MARKET flow ----------------------------------
    tk_pct = draw(11, lambda kk: jax.random.randint(kk, (tk,), 0, 100, I32))
    tk_fire = (tk_pct < gates.taker_p) | sell_bias
    tk_rand_side = draw(12, lambda kk: jax.random.randint(kk, (tk,), 0, 2,
                                                          I32)) + BUY
    tk_side = jnp.where(sell_bias, SELL, tk_rand_side)
    tk_qty = jnp.broadcast_to(
        jnp.where(sell_bias, 2 * mix.taker_qty, mix.taker_qty).astype(I32),
        (s, tk))

    def seg(op, side, otype, price, q, oid):
        # owner 0: sim agents opt out of device self-trade prevention
        # (the recorder assigns per-agent client ids instead, so server
        # replay can never STP-diverge either — sim/record.py).
        return (op, side, otype, price, q, oid, jnp.zeros_like(op))

    zeros_k = jnp.zeros((s, k), I32)
    # Market-type classes are off in a call period. logical_not keeps
    # this correct for BOTH python-bool call_mode (folded to a constant)
    # and traced per-venue scalars under the gym's venue vmap.
    market_gate = jnp.logical_not(call_mode)
    segs = [
        seg(jnp.where(old_bid > 0, OP_CANCEL, 0), jnp.full((s, k), BUY, I32),
            zeros_k, zeros_k, zeros_k, old_bid),
        seg(jnp.where(old_ask > 0, OP_CANCEL, 0), jnp.full((s, k), SELL, I32),
            zeros_k, zeros_k, zeros_k, old_ask),
        seg(jnp.full((s, k), OP_SUBMIT, I32), jnp.full((s, k), BUY, I32),
            jnp.full((s, k), LIMIT, I32), bid_px, mm_qty[:, :k], bid_oid),
        seg(jnp.full((s, k), OP_SUBMIT, I32), jnp.full((s, k), SELL, I32),
            jnp.full((s, k), LIMIT, I32), ask_px, mm_qty[:, k:], ask_oid),
        seg(jnp.where(mom_fire & market_gate, OP_SUBMIT, 0), mom_side,
            jnp.full((s, mo), MARKET, I32), jnp.zeros((s, mo), I32),
            mom_qty, mom_oid),
        seg(jnp.where(nz_fire, OP_SUBMIT, 0), nz_side,
            jnp.full((s, nz), LIMIT, I32), nz_px, nz_qty, nz_oid),
        seg(jnp.where(tk_fire & market_gate, OP_SUBMIT, 0), tk_side,
            jnp.full((s, tk), MARKET, I32), jnp.zeros((s, tk), I32),
            tk_qty, tk_oid),
    ]
    orders = OrderBatch(*(jnp.concatenate(parts, axis=1)
                          for parts in zip(*segs)))
    # Gated symbols emit nothing this step (the engine halt hook).
    orders = apply_halt_mask(orders, ~active)

    adv = jnp.where(active, 1, 0).astype(I32)
    new_state = AgentState(
        keys=keys,
        step=state.step + 1,
        fair=jnp.where(active, fair, state.fair),
        mm_bid_oid=state.mm_bid_oid.at[:, idx].set(
            jnp.where(active[:, None], bid_oid, old_bid)),
        mm_ask_oid=state.mm_ask_oid.at[:, idx].set(
            jnp.where(active[:, None], ask_oid, old_ask)),
        next_oid=state.next_oid + adv * (2 * k + mo + nz + tk),
        prev_mid=state.prev_mid,   # updated post-match (observe_market)
        mom_sig=state.mom_sig,
    )
    return new_state, orders


def observe_market(mix: AgentMix, state: AgentState, best_bid, best_ask
                   ) -> AgentState:
    """Close the trend loop: fold the engine step's post-match top of book
    into the momentum signal. `mom_sig` is a decaying integer EMA of mid
    returns (half-decay per step plus the fresh return), clamped so one
    wild print cannot saturate the signal forever."""
    both = (best_bid > 0) & (best_ask > 0)
    mid = jnp.where(both, (best_bid + best_ask) // 2, state.fair)
    ret = jnp.where(state.prev_mid > 0, mid - state.prev_mid, 0)
    lim = 16 * mix.mom_threshold
    sig = jnp.clip(state.mom_sig - state.mom_sig // 2 + ret, -lim, lim)
    return state._replace(prev_mid=mid, mom_sig=sig.astype(I32))
