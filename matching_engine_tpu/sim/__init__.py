from matching_engine_tpu.sim.market_sim import (
    SimConfig,
    SimState,
    init_sim,
    run_sim,
    run_sim_sharded,
    sim_step_impl,
)

__all__ = ["SimConfig", "SimState", "init_sim", "run_sim", "run_sim_sharded",
           "sim_step_impl", "AgentMix", "Scenario", "Phase", "make_scenario",
           "run_scenario", "record_scenario"]


def __getattr__(name):
    # The scenario subsystem imports lazily: sim/__init__ is imported by
    # light-weight consumers (the CLI) that must not pay the agents/
    # scenarios module graph unless a scenario is actually used.
    if name in ("AgentMix", "init_agents", "agent_orders", "column_roles"):
        from matching_engine_tpu.sim import agents

        return getattr(agents, name)
    if name in ("Scenario", "Phase", "make_scenario", "run_scenario",
                "SCENARIO_NAMES", "zipf_weights_q15"):
        from matching_engine_tpu.sim import scenarios

        return getattr(scenarios, name)
    if name in ("record_scenario", "read_manifest", "manifest_path_for"):
        from matching_engine_tpu.sim import record

        return getattr(record, name)
    raise AttributeError(name)
