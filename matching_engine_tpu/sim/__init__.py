from matching_engine_tpu.sim.market_sim import (
    SimConfig,
    SimState,
    init_sim,
    run_sim,
    run_sim_sharded,
    sim_step_impl,
)

__all__ = ["SimConfig", "SimState", "init_sim", "run_sim", "run_sim_sharded",
           "sim_step_impl"]
