"""Decode generated device flow into replayable oprec workload opfiles.

The bridge between the on-device agent market (sim/agents.py +
sim/scenarios.py) and the serving stack: a recorded scenario becomes a
flat binary op-record file (domain/oprec.py — the PR 7 MAGIC framing)
plus a JSON manifest, landing under benchmarks/workloads/ as a
versioned, language-neutral workload artifact. `client submit-batch`,
`runner_bench --workload`, `latency_bench --workload`, the soak's
flash-crash round, and CI's smoke all replay the SAME file through the
SAME codec reader.

The one non-trivial mapping is order-id renumbering. The sim assigns
per-symbol int32 oids; the server assigns its own global "OID-<n>"
sequence at admission (strided per lane under --serve-shards). Because a
fresh server assigns ids deterministically in record order (the
tests/test_batch_edge.py `_script` contract), the recorder can PREDICT
every submit's server id — lane = the shard router's crc32 symbol home,
id = lane + 1 + n_lane * K for the lane's n-th recorded submit — and
rewrite every cancel's target to the id the server will actually assign.
Cancels also carry the owning agent's client id (the server enforces
client/order ownership). Replay therefore must be IN ORDER on one
connection, with the batch size below the manifest's `min_cancel_gap`
(intra-batch targets resolve against the pre-batch directory; the gap
for market-maker flow is many steps of records, so the default 512 is
far inside it).

Every byte of the opfile is a pure function of (config, mix, scenario,
seed): the determinism-taint analyzer walks this module as part of the
replay closure (write_opfile is a declared replay sink), and
tests/test_scenarios.py byte-compares two recordings of one seed.
"""

from __future__ import annotations

import json

import numpy as np

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.book import EngineConfig
from matching_engine_tpu.engine.kernel import OP_CANCEL, OP_REST, OP_SUBMIT
from matching_engine_tpu.parallel.multihost import symbol_home
from matching_engine_tpu.sim.agents import (
    CLASS_MM,
    CLASS_TAGS,
    AgentMix,
    column_roles,
    mm_agent_index,
)
from matching_engine_tpu.sim.scenarios import Scenario, run_scenario

MANIFEST_FORMAT = 1

# Injected gym-action flow records under its own class tag: the gym's
# action lanes (gym/env.py) are no agent class, but their ops must ride
# the same opfile/manifest schema — column role (ACTION_CLASS, "flow",
# slot) appended after column_roles(mix).
ACTION_CLASS = len(CLASS_TAGS)
ACTION_TAG = "act"


def _class_tag(cls: int) -> str:
    return CLASS_TAGS[cls] if cls < len(CLASS_TAGS) else ACTION_TAG


def manifest_path_for(opfile_path: str) -> str:
    """<name>.opfile[.gz] -> <name>.manifest.json (same directory)."""
    base = opfile_path
    if base.endswith(".gz"):
        base = base[:-3]
    if base.endswith(".opfile"):
        base = base[:-len(".opfile")]
    return base + ".manifest.json"


def _client_id(cls: int, role: str, lane: int, sym: int, step: int,
               mix: AgentMix) -> str:
    """Per-op client identity. Market makers keep a STABLE id per resting
    identity (cancels must present the submitting client); the
    taker-style classes get a step-unique id so server-side self-trade
    prevention can never fire between a client's own orders — the device
    sim runs owner=0 (STP opted out), and replay must not diverge."""
    tag = _class_tag(cls)
    if cls == CLASS_MM:
        return f"{tag}{sym}-{mm_agent_index(mix, step, lane)}"
    return f"{tag}{sym}-{lane}-{step}"


class OpfileBuilder:
    """THE device-lanes -> oprec-records decode, shared by the scenario
    recorder below and the gym episode freezer (gym/episode.py) so the
    two artifact producers cannot drift: one OID-renumbering rule, one
    client-identity rule, one set of replay constraints, one manifest
    accounting. Feed one step at a time (add_step, [S, B] int arrays in
    batch-column order per `roles`); iteration order (step, symbol,
    column) IS the record order the server will see — byte-stable."""

    def __init__(self, num_symbols: int, mix: AgentMix, roles,
                 serve_shards: int = 1, symbol_prefix: str = "S"):
        self.mix = mix
        self.roles = roles
        self.serve_shards = serve_shards
        self.symbols = [f"{symbol_prefix}{s}" for s in range(num_symbols)]
        self.lanes = ([symbol_home(sym, serve_shards)
                       for sym in self.symbols]
                      if serve_shards > 1 else [0] * num_symbols)
        self.records: list[tuple] = []
        # (sym, sim_oid) -> (server "OID-<n>", client_id, record index)
        self.oid_map: dict[tuple[int, int], tuple[str, str, int]] = {}
        self.lane_counts = [0] * max(1, serve_shards)
        tags = list(CLASS_TAGS)
        if any(cls == ACTION_CLASS for cls, _r, _l in roles):
            tags.append(ACTION_TAG)
        self.per_class = {tag: {"submits": 0, "cancels": 0}
                          for tag in tags}
        self.per_symbol = [0] * num_symbols
        self.skipped_cancels = 0
        self.min_cancel_gap: int | None = None
        # Per-symbol resting-depth UPPER BOUND over the recording: live
        # GTC LIMIT count ignoring fills (a fill only ever lowers true
        # depth). Replay uses it to assert a --book-tiers spec is deep
        # enough BEFORE driving a server (check_tier_depth below).
        self.live_limits = [0] * num_symbols
        self.max_resting_depth = [0] * num_symbols
        # sim oid -> symbol of a still-live recorded LIMIT
        self.limit_sym: dict[tuple[int, int], int] = {}

    def add_step(self, g_step: int, op, side, otype, price, qty,
                 oid) -> None:
        """Decode one step's [S, B] lanes into records (in place)."""
        s_syms, b_cols = op.shape
        for s in range(s_syms):
            row_op = op[s]
            if not row_op.any():
                continue
            for b in range(b_cols):
                o = int(row_op[b])
                if o == 0:
                    continue
                cls, role, lane_idx = self.roles[b]
                if o in (OP_SUBMIT, OP_REST):
                    lane = self.lanes[s]
                    n = self.lane_counts[lane]
                    self.lane_counts[lane] += 1
                    srv_oid = (
                        f"OID-{lane + 1 + n * self.serve_shards}"
                        if self.serve_shards > 1 else f"OID-{n + 1}")
                    cid = _client_id(cls, role, lane_idx, s, g_step,
                                     self.mix)
                    self.oid_map[(s, int(oid[s, b]))] = (
                        srv_oid, cid, len(self.records))
                    self.records.append((
                        oprec.OPREC_SUBMIT, int(side[s, b]),
                        int(otype[s, b]), int(price[s, b]),
                        int(qty[s, b]), self.symbols[s], cid, ""))
                    self.per_class[_class_tag(cls)]["submits"] += 1
                    self.per_symbol[s] += 1
                    if int(otype[s, b]) == 0:  # GTC LIMIT rests
                        self.live_limits[s] += 1
                        self.max_resting_depth[s] = max(
                            self.max_resting_depth[s],
                            self.live_limits[s])
                        self.limit_sym[(s, int(oid[s, b]))] = s
                elif o == OP_CANCEL:
                    hit = self.oid_map.get((s, int(oid[s, b])))
                    if hit is None:
                        # A cancel of flow that was never recorded
                        # (cannot happen for the shipped mixes; kept
                        # as a counted guard, never silent).
                        self.skipped_cancels += 1
                        continue
                    srv_oid, cid, born_at = hit
                    gap = len(self.records) - born_at
                    if self.min_cancel_gap is None \
                            or gap < self.min_cancel_gap:
                        self.min_cancel_gap = gap
                    self.records.append((
                        oprec.OPREC_CANCEL, 0, 0, 0, 0, "", cid,
                        srv_oid))
                    self.per_class[_class_tag(cls)]["cancels"] += 1
                    self.per_symbol[s] += 1
                    if self.limit_sym.pop((s, int(oid[s, b])),
                                          None) is not None:
                        self.live_limits[s] -= 1

    def write(self, out_path: str):
        """Validate with the codec's own edge rules and write the
        opfile. Returns the packed record array."""
        arr = oprec.pack_records(self.records)
        flaws = [m for m in oprec.record_flaws(arr) if m is not None]
        if flaws:
            raise RuntimeError(
                f"recorded flow failed edge validation ({len(flaws)} "
                f"flawed records; first: {flaws[0]}) — recorder/codec "
                f"skew")
        oprec.write_opfile(out_path, arr)
        return arr

    def manifest_accounting(self) -> dict:
        """The builder-owned manifest fields (shared schema slice)."""
        return {
            "ops": len(self.records),
            "per_class_ops": self.per_class,
            "per_symbol_ops": self.per_symbol,
            "min_cancel_gap": self.min_cancel_gap,
            "max_resting_depth": self.max_resting_depth,
            "skipped_cancels": self.skipped_cancels,
        }


def record_scenario(
    cfg: EngineConfig,
    mix: AgentMix,
    scenario: Scenario,
    seed: int,
    out_path: str,
    serve_shards: int = 1,
    metrics=None,
    symbol_prefix: str = "S",
) -> dict:
    """Run + record one scenario; write the opfile and its manifest.

    Returns the manifest dict (phases with record ranges AND the sim's
    per-phase fill/volume/uncross ground truth — one schema with the
    gym's frozen-episode manifests, so every replay reconciler reads
    the same shape — plus per-class/per-symbol op counts and the replay
    constraints)."""
    book, state, phases = run_scenario(cfg, mix, scenario, seed=seed,
                                       collect_orders=True)
    bld = OpfileBuilder(cfg.num_symbols, mix, column_roles(mix),
                        serve_shards=serve_shards,
                        symbol_prefix=symbol_prefix)

    manifest_phases = []
    step0 = 0
    for pr in phases:
        start_rec = len(bld.records)
        op = np.asarray(pr.orders.op)
        side = np.asarray(pr.orders.side)
        otype = np.asarray(pr.orders.otype)
        price = np.asarray(pr.orders.price)
        qty = np.asarray(pr.orders.qty)
        oid = np.asarray(pr.orders.oid)
        for t in range(op.shape[0]):
            bld.add_step(step0 + t, op[t], side[t], otype[t], price[t],
                         qty[t], oid[t])
        manifest_phases.append({
            "kind": pr.phase.kind,
            "steps": pr.phase.steps,
            "start_record": start_rec,
            "end_record": len(bld.records),
            # Per-phase ground truth: continuous fills/volume from the
            # sim's own step stats, call executions separately — the
            # per-phase slice of the totals below, so a phase-aware
            # replay can reconcile each phase, not just the end state.
            "fills": int(np.sum(np.asarray(pr.stats.fills))),
            "volume": int(np.sum(np.asarray(pr.stats.volume))),
            "uncross": pr.phase.kind == "auction",
            "uncross_executed": (int(np.sum(pr.uncross.executed))
                                 if pr.uncross is not None else 0),
        })
        step0 += pr.phase.steps

    arr = bld.write(out_path)

    sim_fills = sum(p["fills"] for p in manifest_phases)
    sim_volume = sum(p["volume"] for p in manifest_phases)
    manifest = {
        "format": MANIFEST_FORMAT,
        "name": scenario.name,
        "seed": seed,
        "symbols": cfg.num_symbols,
        "capacity": cfg.capacity,
        "batch": cfg.batch,
        "kernel": cfg.kernel,
        "max_fills": cfg.max_fills,
        "serve_shards": serve_shards,
        "zipf_alpha_q8": scenario.zipf_alpha_q8,
        "steps": scenario.total_steps(),
        "phases": manifest_phases,
        **bld.manifest_accounting(),
        "sim_fills": sim_fills,
        "sim_volume": sim_volume,
        "agent_mix": {
            "mm_agents": mix.mm_agents, "mm_refresh": mix.mm_refresh,
            "momentum": mix.momentum, "noise": mix.noise,
            "takers": mix.takers,
        },
    }
    with open(manifest_path_for(out_path), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)

    if metrics is not None:
        metrics.inc("sim_record_ops", len(bld.records))
        metrics.inc("sim_record_steps", scenario.total_steps())
        metrics.inc("sim_record_phases", len(manifest_phases))
        metrics.inc("sim_record_bytes", len(arr) * oprec.RECORD_SIZE)
    return manifest


def check_tier_depth(manifest: dict, tiers, pins=None,
                     symbol_prefix: str = "S") -> list[str]:
    """Assert a --book-tiers spec is deep enough for a recorded workload
    BEFORE driving a server with it: every symbol's recorded
    `max_resting_depth` (a fill-ignoring upper bound) must fit the
    capacity of the tier group the symbol would land in — its pinned
    group, else the SHALLOWEST group of the spec (unpinned allocation
    starts at the last group and may spill into any other, and which one
    a given symbol lands in depends on arrival order — so the sound
    static judgment is the worst case); spill into deeper groups is
    deliberately NOT credited, so passing this check means the replay
    cannot depend on borrowed deep slots. Returns a list of
    human-readable violations (empty = spec is deep enough)."""
    depths = manifest.get("max_resting_depth")
    if not depths:
        return [
            "manifest has no max_resting_depth (recorded before the "
            "tier-aware format) — re-record with client simulate"]
    pins = pins or {}
    shallowest = min(range(len(tiers)), key=lambda g: tiers[g][1])
    out = []
    for s, depth in enumerate(depths):
        sym = f"{symbol_prefix}{s}"
        g = pins.get(sym, shallowest)
        cap = tiers[g][1]
        if depth > cap:
            out.append(
                f"{sym}: recorded resting depth {depth} exceeds tier "
                f"group {g} capacity {cap} (pin it to a deeper group or "
                f"deepen the spec)")
    return out


def read_manifest(opfile_path: str) -> dict:
    with open(manifest_path_for(opfile_path)) as f:
        m = json.load(f)
    if m.get("format") != MANIFEST_FORMAT:
        raise ValueError(
            f"unsupported workload manifest format {m.get('format')!r} "
            f"for {opfile_path}")
    return m
