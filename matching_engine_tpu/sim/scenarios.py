"""Declarative scenario programs over the heterogeneous agent market.

A Scenario is a sequence of timed PHASES over the agent mix — the
CoinTossX stress catalogue (arXiv:2102.10925) as data, not prose:

- ``continuous``: normal trading; optional burst gating (on/off arrival
  waves) and a shock window (per-step fair-value decrements + all-sell
  takers — the flash-crash injection the momentum class then amplifies
  through the top-of-book return loop).
- ``auction``: a call period. LIMIT flow RESTS without matching
  (kernel OP_REST — the books may stand crossed), market-type classes
  are gated off, and the phase ends with a call-auction uncross
  (engine/auction.py auction_step) clearing every book at one price.
  This is exactly the serving stack's auction-mode plumbing: on replay,
  the workload driver opens the call period (RunAuction open_call) and
  uncrosses at the phase end (RunAuction), so recorded auction-day flow
  exercises the live server's call machinery.
- ``halt``: a trading halt — every symbol suppressed via the engine's
  halt hook (kernel.apply_halt_mask); books stand frozen, zero ops and
  zero fills admitted (tests pin it).

Hot-symbol skew rides the whole scenario: ``zipf_alpha_q8 > 0`` gates
each symbol's per-step activity by a Zipf weight, so a few symbols carry
most of the flow while the tail idles (engine/flow.py's power-law
regime, now closed-loop).

Each phase runs as ONE jit'd lax.scan (static phase config => the
compile cache holds one program per distinct phase shape); state and
book carry across phases, so a scenario is bit-reproducible from (config,
mix, program, seed).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from matching_engine_tpu.engine.auction import auction_step, decode_auction
from matching_engine_tpu.engine.book import BookBatch, EngineConfig, init_book
from matching_engine_tpu.engine.kernel import (
    LIMIT,
    OP_REST,
    OP_SUBMIT,
    engine_step_impl,
)
from matching_engine_tpu.sim.agents import (
    AgentMix,
    AgentState,
    agent_orders,
    init_agents,
    observe_market,
)
from matching_engine_tpu.sim.market_sim import StepStats

I32 = jnp.int32


@dataclasses.dataclass(frozen=True)
class Phase:
    """One timed phase (hashable; jit-static)."""

    kind: str                 # "continuous" | "auction" | "halt"
    steps: int
    burst_period: int = 0     # 0 = no burst gating
    burst_on: int = 0         # active steps per period
    shock_bp: int = 0         # per-step fair decrement while shocked (Q4)
    shock_start: int = 0      # step offset within the phase
    shock_len: int = 0

    def __post_init__(self):
        assert self.kind in ("continuous", "auction", "halt"), self.kind
        assert self.steps > 0
        if self.burst_period:
            assert 0 < self.burst_on <= self.burst_period


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    phases: tuple[Phase, ...]
    zipf_alpha_q8: int = 0    # Zipf exponent * 256 over symbol activity

    def total_steps(self) -> int:
        return sum(p.steps for p in self.phases)


def zipf_weights_q15(num_symbols: int, alpha_q8: int) -> np.ndarray:
    """[S] per-symbol activity weights in Q15 (32768 = always active).
    Slot 0 is the hottest symbol — deterministic, no RNG, so the weights
    are part of the scenario's reproducible identity. alpha_q8 == 0 =>
    uniform full activity."""
    if alpha_q8 <= 0:
        return np.full(num_symbols, 1 << 15, dtype=np.int32)
    alpha = alpha_q8 / 256.0
    w = np.array([(1.0 / (i + 1) ** alpha) for i in range(num_symbols)])
    return np.maximum((w * (1 << 15)).astype(np.int32), 1)


class PhaseResult:
    """Host-side per-phase outcome."""

    __slots__ = ("phase", "stats", "orders", "uncross", "uncross_fills")

    def __init__(self, phase, stats, orders, uncross=None, uncross_fills=None):
        self.phase = phase
        self.stats = stats            # StepStats, stacked [steps]
        self.orders = orders          # OrderBatch [steps, S, B] | None
        self.uncross = uncross        # AuctionDecoded | None
        self.uncross_fills = uncross_fills


def _phase_impl(cfg: EngineConfig, mix: AgentMix, phase: Phase,
                phase_start: int, collect: bool,
                book: BookBatch, state: AgentState, zipf_w: jax.Array):
    call_mode = phase.kind == "auction"
    halt = phase.kind == "halt"

    def scan_body(carry, _):
        book, state = carry
        t = state.step - phase_start
        if phase.burst_period:
            burst_on = (t % phase.burst_period) < phase.burst_on
        else:
            burst_on = jnp.ones((), bool)
        if phase.shock_len:
            in_shock = (t >= phase.shock_start) & (
                t < phase.shock_start + phase.shock_len)
        else:
            in_shock = jnp.zeros((), bool)
        shock = jnp.where(in_shock, phase.shock_bp, 0).astype(I32)
        state, orders = agent_orders(
            cfg, mix, state, zipf_w, call_mode=call_mode, halt=halt,
            burst_on=burst_on, shock=shock, sell_bias=in_shock)
        if call_mode:
            # Call period: LIMIT flow accumulates without matching — the
            # serving stack's auction-mode mapping (engine_runner turns
            # admitted submits into OP_REST while the call is open).
            orders = orders._replace(op=jnp.where(
                (orders.op == OP_SUBMIT) & (orders.otype == LIMIT),
                OP_REST, orders.op))
        book, out = engine_step_impl(cfg, book, orders)
        state = observe_market(mix, state, out.best_bid, out.best_ask)

        both = (out.best_bid > 0) & (out.best_ask > 0)
        n_both = jnp.sum(both)
        stats = StepStats(
            real_ops=jnp.sum(orders.op != 0).astype(I32),
            fills=out.fill_count.astype(I32),
            volume=jnp.sum(out.fill_qty).astype(I32),
            spread=jnp.where(
                n_both > 0,
                jnp.sum(jnp.where(both, out.best_ask - out.best_bid, 0))
                // jnp.maximum(n_both, 1), 0).astype(I32),
            resting=(jnp.sum(book.bid_qty > 0)
                     + jnp.sum(book.ask_qty > 0)).astype(I32),
        )
        return (book, state), (stats, orders if collect else None)

    (book, state), (stats, orders) = jax.lax.scan(
        scan_body, (book, state), None, length=phase.steps)
    return book, state, stats, orders


# Module-level jit: repeated phases with the same static config hit the
# compile cache (the market_sim convention).
_phase_run = jax.jit(_phase_impl, static_argnums=(0, 1, 2, 3, 4))


def run_scenario(
    cfg: EngineConfig,
    mix: AgentMix,
    scenario: Scenario,
    seed: int = 0,
    collect_orders: bool = False,
):
    """Run a scenario program end to end on device.

    Returns (book, state, [PhaseResult...]). Auction phases end with an
    all-symbols uncross whose decoded summary + bilateral fills ride the
    PhaseResult (the oracle parity test replays them; the recorder maps
    them onto the replay driver's RunAuction calls)."""
    assert cfg.batch == mix.batch_for(), (
        f"EngineConfig.batch must be {mix.batch_for()} for this AgentMix")
    book = init_book(cfg)
    state = init_agents(cfg, mix, seed)
    zipf_w = jnp.asarray(zipf_weights_q15(cfg.num_symbols,
                                          scenario.zipf_alpha_q8))
    results: list[PhaseResult] = []
    start = 0
    for phase in scenario.phases:
        book, state, stats, orders = _phase_run(
            cfg, mix, phase, start, collect_orders, book, state, zipf_w)
        uncross = uncross_fills = None
        if phase.kind == "auction":
            mask = jnp.ones((cfg.num_symbols,), bool)
            book, aout = auction_step(cfg, book, mask)
            uncross, uncross_fills = decode_auction(cfg, aout)
            if uncross.aborted:
                raise RuntimeError(
                    "scenario uncross aborted: fill log overflow — raise "
                    "EngineConfig.max_fills for this population")
        results.append(PhaseResult(phase, stats, orders, uncross,
                                   uncross_fills))
        start += phase.steps
    return book, state, results


# -- the scenario catalogue ---------------------------------------------------

def _scaled(phases: list[Phase], steps: int | None) -> tuple[Phase, ...]:
    """Proportionally rescale a program to ~`steps` total (each phase
    keeps at least one step, so the program's structure survives any
    scale)."""
    if steps is None:
        return tuple(phases)
    base = sum(p.steps for p in phases)
    out = []
    for p in phases:
        n = max(1, round(p.steps * steps / base))
        f = {fld.name: getattr(p, fld.name)
             for fld in dataclasses.fields(Phase)}
        # Keep shock/burst windows inside the rescaled phase.
        f["steps"] = n
        if f["shock_len"]:
            f["shock_start"] = min(f["shock_start"], max(0, n - 2))
            f["shock_len"] = max(1, min(f["shock_len"],
                                        n - f["shock_start"]))
        out.append(Phase(**f))
    return tuple(out)


def make_scenario(name: str, steps: int | None = None) -> Scenario:
    """The named stress catalogue. `steps` proportionally rescales the
    program's total length (CLI `simulate --steps`)."""
    if name == "auction_day":
        # Open call -> continuous -> halt -> reopen call -> continuous ->
        # closing call: the full exchange trading day.
        phases = [
            Phase("auction", 12),
            Phase("continuous", 60),
            Phase("halt", 10),
            Phase("auction", 12),
            Phase("continuous", 46),
            Phase("auction", 12),
        ]
        return Scenario("auction_day", _scaled(phases, steps))
    if name == "flash_crash":
        # Warm-up, then an injected sell shock the momentum population
        # amplifies, then the recovery tail.
        phases = [
            Phase("continuous", 40),
            Phase("continuous", 50, shock_bp=60, shock_start=8,
                  shock_len=12),
            Phase("continuous", 40),
        ]
        return Scenario("flash_crash", _scaled(phases, steps))
    if name == "hot_symbols":
        # Zipf(1.2) activity skew: slot 0 runs hot, the tail idles.
        return Scenario("hot_symbols",
                        _scaled([Phase("continuous", 130)], steps),
                        zipf_alpha_q8=int(1.2 * 256))
    if name == "bursts":
        # On/off arrival waves: 6 active steps in every 20.
        return Scenario("bursts",
                        _scaled([Phase("continuous", 130, burst_period=20,
                                       burst_on=6)], steps))
    if name == "deep_books":
        # Zipf-hot flow under an OVERSIZED market-maker ladder population
        # (default_mix below: 192 resting identities per symbol): the
        # head symbols accumulate resting depth far past the legacy
        # 128-order book — the workload that motivates --book-tiers deep
        # groups and the levels kernel, and the one whose replay meters
        # capacity backpressure on an under-tiered server.
        return Scenario("deep_books",
                        _scaled([Phase("continuous", 130)], steps),
                        zipf_alpha_q8=int(1.2 * 256))
    raise ValueError(
        f"unknown scenario {name!r} (have: {', '.join(SCENARIO_NAMES)})")


SCENARIO_NAMES = ("auction_day", "flash_crash", "hot_symbols", "bursts",
                  "deep_books")


def default_mix(name: str):
    """The agent mix a named scenario records with (client simulate).
    Everything runs the stock AgentMix except deep_books, whose point is
    an ungated market-maker LADDER deeper than the legacy capacity: 192
    resting identities per symbol, refreshed 8 at a time."""
    from matching_engine_tpu.sim.agents import AgentMix

    if name == "deep_books":
        return AgentMix(mm_agents=192, mm_refresh=8, qty_max=40)
    return AgentMix()


def recording_capacity(mix, name: str = "") -> int:
    """Book capacity for RECORDING a scenario (the sim's own engine run):
    headroom over the deepest population a mix can rest. The stock mixes
    keep the legacy 128; deep_books records at 1024 — uncanceled noise
    residue accumulates on the Zipf-hot head far past the
    market-makers' own 192-quote ladder, and a recording that hit its
    own capacity wall would bake rejects into the artifact that a
    deeper replay server then legitimately fills (fill_drift)."""
    if name == "deep_books":
        return 1024
    cap = 128
    while cap < mix.mm_agents + 64:
        cap <<= 1
    return cap


def recording_kernel(capacity: int) -> str:
    """Kernel for the recording run: matrix at the legacy depth (the
    committed pre-deep_books artifacts' exact configuration — their
    regeneration commands must keep reproducing identical bytes), sorted
    past it (matrix [C, C] intermediates are quadratic; all kernels are
    bit-identical on the flow the recorder captures, so the artifact
    bytes do not depend on this choice except through capacity)."""
    return "sorted" if capacity > 256 else "matrix"
