"""Observability subsystem: stage latency ledger, Prometheus exposition,
and a crash flight recorder.

Before this module the only visibility was the in-process Metrics registry
behind one gRPC call — and the --native-lanes fast path moved per-op work
into C++ where those hooks no longer fire, so the fastest configuration
was the blindest one. Three layers fix that:

1. **Stage latency ledger** (`DispatchTimeline`): every serving dispatch
   carries monotonic stamps at the pipeline boundaries

       edge ingress -> queue enqueue -> lane build -> device dispatch
       -> completion decode -> stream publish -> sink commit

   and the deltas land in `stage_<name>_us` sliding-window histograms
   (p50/p99 via Metrics.snapshot). Stamps are per DISPATCH, not per op —
   the native-lanes path regains per-stage visibility without re-adding
   per-op Python work. Queue-depth and in-flight gauges ride along.

2. **Prometheus exposition** (`render_prometheus` + `ObsServer`): a
   stdlib-only HTTP thread serving `/metrics` (text format 0.0.4),
   `/healthz`, `/readyz`, and `/flightrecorder` (JSON ring snapshot).
   Counters export as `me_<name>_total`, gauges as `me_<name>`.

3. **Flight recorder** (`FlightRecorder`): a bounded ring of recent
   dispatch summaries (shape, counters, per-stage latencies, errors)
   that dumps JSON on SIGUSR2, fatal dispatch error, and clean shutdown
   — a soak/e2e failure leaves a post-mortem artifact instead of "it
   got slow".
"""

from __future__ import annotations

import http.server
import itertools
import json
import os
import signal
import threading
import time
from collections import deque

# Stage histogram names, in pipeline order. Each is a Metrics.observe
# histogram in microseconds, exported with _p50/_p99 derived gauges.
STAGE_EDGE_INGRESS = "stage_edge_ingress_us"       # RPC entry -> ring/queue push
STAGE_QUEUE_WAIT = "stage_queue_wait_us"           # enqueue -> drain pop
STAGE_LANE_BUILD = "stage_lane_build_us"           # pop -> device buffers built
STAGE_DEVICE_DISPATCH = "stage_device_dispatch_us" # buffers built -> waves issued
STAGE_COMPLETION_DECODE = "stage_completion_decode_us"  # issue -> decoded (incl. pipeline residency + device wait)
STAGE_STREAM_PUBLISH = "stage_stream_publish_us"   # decode -> sink/hub enqueued
STAGE_SINK_COMMIT = "stage_sink_commit_us"         # one storage batch's SQLite txn

STAGES = (
    STAGE_EDGE_INGRESS, STAGE_QUEUE_WAIT, STAGE_LANE_BUILD,
    STAGE_DEVICE_DISPATCH, STAGE_COMPLETION_DECODE, STAGE_STREAM_PUBLISH,
    STAGE_SINK_COMMIT,
)


class DispatchTimeline:
    """Monotonic stamps for ONE dispatch crossing the serving pipeline.

    Created by a drain loop when it pops a batch (`path` names the edge:
    "python", "native-lanes", "gateway", "gateway-lanes"); the runner
    stamps the batch as it crosses each boundary; `finish()` folds the
    deltas into the stage histograms and appends one flight-recorder
    entry (when the registry carries one). All stamps are optional —
    a boundary never crossed simply records nothing.
    """

    __slots__ = ("path", "n_ops", "t_ingress", "t_enqueue", "t_pop",
                 "t_build", "t_issue", "t_decode", "t_publish", "shape",
                 "waves", "mega_m", "counters", "trace_id")

    # Process-wide dispatch trace ids (GIL-atomic); every timeline gets
    # one so a sampled trace export names exactly which dispatch it is
    # and the flight-recorder entry for the same dispatch correlates.
    _trace_ids = itertools.count(1)

    def __init__(self, path: str, n_ops: int, t_enqueue: float | None = None,
                 t_pop: float | None = None, t_ingress: float | None = None):
        self.path = path
        self.n_ops = n_ops
        self.t_ingress = t_ingress   # oldest op's RPC entry (edge ingress)
        self.t_enqueue = t_enqueue   # earliest op enqueue (queue-wait origin)
        self.t_pop = time.perf_counter() if t_pop is None else t_pop
        self.t_build = None
        self.t_issue = None
        self.t_decode = None
        self.t_publish = None
        self.shape = ""              # "sparse" | "dense" | "mesh" | "mega"
        self.waves = 0
        self.mega_m = 1              # waves stacked per device call (mega)
        self.counters: dict = {}
        self.trace_id = next(self._trace_ids)

    def stamp_build(self) -> None:
        self.t_build = time.perf_counter()

    def stamp_issue(self) -> None:
        self.t_issue = time.perf_counter()

    def stamp_decode(self) -> None:
        self.t_decode = time.perf_counter()

    def stamp_publish(self) -> None:
        self.t_publish = time.perf_counter()

    def _stages_us(self) -> dict[str, float]:
        out: dict[str, float] = {}

        def delta(name, a, b):
            if a is not None and b is not None and b >= a:
                out[name] = (b - a) * 1e6

        # t_ingress is deliberately NOT folded here: the service layer
        # already observes STAGE_EDGE_INGRESS per op (RPC entry -> push);
        # folding the per-dispatch oldest-op delta too would double-count
        # the histogram. The stamp exists for the trace exporter's
        # edge-ingress span.
        delta(STAGE_QUEUE_WAIT, self.t_enqueue, self.t_pop)
        delta(STAGE_LANE_BUILD, self.t_pop, self.t_build)
        delta(STAGE_DEVICE_DISPATCH, self.t_build, self.t_issue)
        # Decode is stamped when THIS batch's results are decoded, which
        # under pipelining includes up to pipeline_inflight batches of
        # residency — the client-felt figure, same convention as
        # dispatch_us.
        delta(STAGE_COMPLETION_DECODE, self.t_issue or self.t_build,
              self.t_decode)
        delta(STAGE_STREAM_PUBLISH, self.t_decode, self.t_publish)
        return out

    def finish(self, metrics, error: Exception | None = None) -> None:
        """Fold the stamped deltas into the stage histograms and the
        flight-recorder ring. Call exactly once, from the edge's
        on_finish callback (dispatch lock held there is fine — observe()
        is the hot-path-safe registry call)."""
        stages = self._stages_us()
        for name, us in stages.items():
            metrics.observe(name, us)
        e2e = self.e2e_us()
        if e2e is not None and error is None:
            # Per-dispatch end-to-end (oldest op's first stamp -> last
            # stamp): the tail the trace sampler's slow threshold rolls
            # over, and the p99/p50 ratio latency_bench gates on.
            # Successful dispatches only — an errored dispatch's span is
            # truncated at whatever stamp it died on, and a burst of
            # those would deflate the rolling p99 into tagging ordinary
            # dispatches as slow.
            metrics.observe("dispatch_e2e_us", e2e)
        tracer = getattr(metrics, "tracer", None)
        if tracer is not None and error is None:
            tracer.offer_dispatch(self, e2e)
        recorder = getattr(metrics, "recorder", None)
        if recorder is None:
            return
        entry = {
            "kind": "dispatch" if error is None else "dispatch_error",
            "path": self.path,
            "trace_id": self.trace_id,
            "ops": self.n_ops,
            "shape": self.shape,
            "waves": self.waves,
            "mega_m": self.mega_m,
            "stages_us": {k: round(v, 1) for k, v in stages.items()},
            "counters": dict(self.counters),
        }
        if error is not None:
            entry["error"] = f"{type(error).__name__}: {error}"
        recorder.record(entry)
        if error is not None:
            recorder.dump_on_error()

    def e2e_us(self) -> float | None:
        """Oldest-stamp -> newest-stamp span of this dispatch in µs (the
        client-felt figure minus the RPC transport), None before any
        pair of stamps exists."""
        first = next((t for t in (self.t_ingress, self.t_enqueue,
                                  self.t_pop) if t is not None), None)
        last = next((t for t in (self.t_publish, self.t_decode,
                                 self.t_issue, self.t_build, self.t_pop)
                     if t is not None), None)
        if first is None or last is None or last < first:
            return None
        return (last - first) * 1e6


_warn_lock = threading.Lock()
_warn_last: dict[str, float] = {}
_warn_suppressed: dict[str, int] = {}
_warn_span: dict[str, tuple[int, int]] = {}


def warn_rate_limited(key: str, msg: str, interval_s: float = 5.0,
                      oid_span: tuple[int, int] | None = None) -> None:
    """Print `msg` at most once per `interval_s` per `key`, with a count
    of the lines suppressed in between. A flapping sink/hub fails at
    BATCH rate — per-failure print() would melt stdout exactly when the
    operator needs it; the paired `me_` counter carries the true rate.

    `oid_span` (lo, hi order-id numbers touched by this failure) is
    ACCUMULATED across suppressed calls and printed with the next
    emitted line, so a post-mortem can bound the blast radius of the
    whole suppressed window — not just the one batch that happened to
    print."""
    now = time.monotonic()
    with _warn_lock:
        if oid_span is not None:
            prev = _warn_span.get(key)
            _warn_span[key] = (oid_span if prev is None else
                               (min(prev[0], oid_span[0]),
                                max(prev[1], oid_span[1])))
        last = _warn_last.get(key, 0.0)
        if now - last < interval_s:
            _warn_suppressed[key] = _warn_suppressed.get(key, 0) + 1
            return
        suppressed = _warn_suppressed.pop(key, 0)
        span = _warn_span.pop(key, None)
        _warn_last[key] = now
    tail = f" (+{suppressed} suppressed)" if suppressed else ""
    if span is not None:
        tail += f" (orders OID-{span[0]}..OID-{span[1]} affected)"
    print(f"{msg}{tail}")


def record_dispatch_error(metrics, where: str, error: Exception) -> None:
    """Flight-record a drain-loop failure that never made it to a
    timeline (pop/stage machinery raised) and dump a post-mortem."""
    recorder = getattr(metrics, "recorder", None)
    if recorder is None:
        return
    recorder.record({
        "kind": "error", "where": where,
        "error": f"{type(error).__name__}: {error}",
    })
    recorder.dump_on_error()


class FlightRecorder:
    """Bounded ring of recent dispatch summaries with JSON dumps.

    Recording is cheap (one dict append under a lock, per DISPATCH);
    the ring overwrites oldest-first. Dumps go to `dump_dir` as
    `flight_<utc>_<reason>.json`; with no dump_dir the ring still
    records (snapshot() serves /flightrecorder) but dump() is a no-op
    returning None. Error-triggered dumps are rate-limited so a
    persistent fault can't fill the disk with identical post-mortems.
    """

    def __init__(self, capacity: int = 512, dump_dir: str | None = None,
                 error_dump_interval_s: float = 30.0):
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._last_error_dump = 0.0
        self._prev_sigusr2 = None
        self.dump_dir = dump_dir
        self.error_dump_interval_s = error_dump_interval_s
        # Attached by build_server: lets dump() capture the controller/
        # balance context (me_megadispatch_*, me_lane_*) that per-entry
        # stage deltas alone can't explain a tail spike with.
        self.metrics = None

    def record(self, entry: dict) -> None:
        with self._lock:
            self._seq += 1
            e = dict(entry)
            e["seq"] = self._seq
            e["wall_ts"] = time.time()
            self._ring.append(e)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, reason: str) -> str | None:
        """Write the ring to a timestamped JSON file; returns the path
        (None when no dump_dir is configured or the write failed — a
        post-mortem must never take the server down with it)."""
        if not self.dump_dir:
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            path = os.path.join(
                self.dump_dir, f"flight_{ts}_{os.getpid()}_{reason}.json")
            doc = {
                "reason": reason,
                "wall_ts": time.time(),
                "pid": os.getpid(),
                "context": self._dump_context(),
                "entries": self.snapshot(),
            }
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
            print(f"[obs] flight recorder dumped {len(doc['entries'])} "
                  f"entries to {path} ({reason})")
            return path
        except OSError as e:
            print(f"[obs] flight recorder dump failed: "
                  f"{type(e).__name__}: {e}")
            return None

    def _dump_context(self) -> dict:
        """The megadispatch-controller and lane-balance state at dump
        time: a SIGUSR2 snapshot must carry the M / imbalance context a
        tail spike happened under, not just per-dispatch stage deltas."""
        if self.metrics is None:
            return {}
        try:
            counters, gauges = self.metrics.snapshot()
        except Exception:  # noqa: BLE001 — a post-mortem never raises
            return {}
        keep = ("megadispatch", "lane")
        return {
            "gauges": {k: v for k, v in sorted(gauges.items())
                       if k.startswith(keep)},
            "counters": {k: v for k, v in sorted(counters.items())
                         if k.startswith(keep)},
        }

    def dump_on_error(self) -> bool:
        """Rate-limited dump for fatal dispatch errors. The write runs on
        a background daemon thread: callers sit on serving-critical paths
        (timeline.finish runs under the dispatch lock), and a slow disk
        must never stall dispatches for a post-mortem. Returns whether a
        dump was scheduled."""
        if not self.dump_dir:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._last_error_dump < self.error_dump_interval_s:
                return False
            self._last_error_dump = now
        threading.Thread(target=self.dump, args=("dispatch-error",),
                         name="flight-dump", daemon=True).start()
        return True

    def install_sigusr2(self) -> bool:
        """SIGUSR2 -> dump("sigusr2") on a BACKGROUND daemon thread
        (same pattern as dump_on_error): the handler runs on the main
        thread between bytecodes, and dump() acquires the recorder and
        registry locks — a synchronous dump while the main thread itself
        held either would self-deadlock on the non-reentrant lock.
        Install from the main thread only (signal module restriction);
        returns False where unavailable (e.g. Windows)."""
        if not hasattr(signal, "SIGUSR2"):
            return False

        def _handler(*_):
            threading.Thread(target=self.dump, args=("sigusr2",),
                             name="flight-dump", daemon=True).start()

        try:
            self._prev_sigusr2 = signal.signal(signal.SIGUSR2, _handler)
            return True
        except ValueError:  # not the main thread
            return False

    def uninstall_sigusr2(self) -> None:
        if self._prev_sigusr2 is not None:
            signal.signal(signal.SIGUSR2, self._prev_sigusr2)
            self._prev_sigusr2 = None


# -- per-dispatch trace export (--trace-dir) ---------------------------------


class TraceExporter:
    """Bounded sampler exporting dispatches as Chrome `trace_event` JSON.

    Rides the registry as `metrics.tracer` (the recorder pattern):
    DispatchTimeline.finish offers every completed dispatch; the sampler
    keeps (a) every `sample_every`-th dispatch and (b) every dispatch
    whose end-to-end latency exceeds the ROLLING p99 of `dispatch_e2e_us`
    (threshold cached, refreshed at most once per second) — the tail is
    exactly what a uniform sample misses. A kept dispatch becomes one
    parent slice with nested child slices for the pipeline stages
    (edge-ingress → queue-wait → lane-build → device-dispatch →
    completion-decode → stream-publish), args carrying the trace id,
    shape, and aux counters (the flight-recorder entry's content, folded
    into the trace). Host spans from utils/tracing.span (native lane
    build/decode) and the async sink's commit txns land in the same file
    on their own threads, so one file opened in Perfetto /
    chrome://tracing shows the whole seven-stage pipeline.

    Hot-path cost when not sampling: one counter bump and one float
    compare. Kept events go to a bounded in-memory queue (overflow
    counted as trace_dropped_events) drained by a background writer —
    a full disk surfaces as a rate-limited warning plus the
    trace_write_errors counter, never a stalled dispatch or a log storm.

    The file is a streamed JSON array (the Chrome trace array form):
    finalized with `]` on close() so it json-parses; Perfetto loads the
    unterminated prefix too if the process dies mid-run.
    """

    def __init__(self, trace_dir: str, metrics=None, sample_every: int = 64,
                 queue_cap: int = 8192, flush_interval_s: float = 0.25):
        self.trace_dir = trace_dir
        self.metrics = metrics
        self.sample_every = max(1, int(sample_every))
        self._queue_cap = queue_cap
        self._t0 = time.perf_counter()   # ts origin (µs since start)
        self._n = 0                      # dispatches offered
        self._span_seen: dict[str, int] = {}
        self._slow_p99_us: float | None = None
        self._slow_refresh = 0.0
        self._ev_lock = threading.Lock()
        self._events: list[dict] = []
        self._tids: dict[str, int] = {}
        self._tid_seq = 0
        self._file = None
        self.path: str | None = None
        self._wrote_any = False
        # Serializes whole flushes: the background writer and direct
        # flush() callers (tests, close) would otherwise race the lazy
        # file open and interleave writes into the same path.
        self._flush_lock = threading.Lock()
        self._stop = threading.Event()
        self._flush_interval_s = flush_interval_s
        self._thread = threading.Thread(target=self._run, name="trace-writer",
                                        daemon=True)
        self._thread.start()

    # -- sampling (hot path) ----------------------------------------------

    def offer_dispatch(self, tl, e2e_us: float | None) -> None:
        """Called by DispatchTimeline.finish for EVERY dispatch — must
        stay O(1) when not sampling. Under --serve-shards K lane drain
        threads call in concurrently (each under its OWN dispatch lock),
        so the _n / _span_seen counters race deliberately unlocked: a
        lost increment only drifts the uniform sampling phase, and a
        lock here would serialize the lanes the partition decouples.
        Nothing correctness-bearing may ever ride these counters."""
        self._n += 1
        sampled = (self._n % self.sample_every) == 0
        slow = False
        if not sampled and e2e_us is not None:
            thr = self._slow_threshold()
            slow = thr is not None and e2e_us > thr
        if not (sampled or slow):
            return
        self._export_dispatch(tl, e2e_us, "interval" if sampled else "slow")

    def _slow_threshold(self) -> float | None:
        """Rolling p99 of dispatch end-to-end latency, refreshed at most
        once per second (percentile() walks the bucket grid — fine per
        second, not per dispatch)."""
        if self.metrics is None:
            return None
        now = time.monotonic()
        if now - self._slow_refresh >= 1.0:
            self._slow_refresh = now
            self._slow_p99_us = self.metrics.percentile(
                "dispatch_e2e_us", 0.99)
        return self._slow_p99_us

    # -- event construction -------------------------------------------------

    def _rel_us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    def _tid(self, label: str, events: list[dict]) -> int:
        with self._ev_lock:  # spans race in from sink/lane threads
            tid = self._tids.get(label)
            if tid is None:
                self._tid_seq += 1  # a seq, not len(): drops unregister
                tid = self._tids[label] = self._tid_seq
                events.append({"ph": "M", "pid": os.getpid(), "tid": tid,
                               "name": "thread_name",
                               "args": {"name": label}})
        return tid

    def _unregister_meta(self, events: list[dict]) -> None:
        """A batch carrying a track's one-time thread_name metadata was
        dropped (queue overflow) or lost (failed write): forget the
        label so the NEXT event on that track re-emits it — otherwise
        the whole track renders anonymous for the rest of the file."""
        with self._ev_lock:
            for e in events:
                if e.get("ph") == "M":
                    self._tids.pop(e["args"]["name"], None)

    def _export_dispatch(self, tl, e2e_us, why: str) -> None:
        events: list[dict] = []
        # Track identity includes the DRAIN THREAD, not just the path:
        # under --serve-shards K lanes share one path string, and
        # time-overlapping slices on one tid would nest lane B's stages
        # inside lane A's dispatch in Perfetto. (Thread names collide
        # too — every lane's drain is "dispatcher" — so use the ident.)
        tid = self._tid(
            f"dispatch:{tl.path}@{threading.get_ident()}", events)
        pid = os.getpid()
        stamps = [("edge_ingress", tl.t_ingress, tl.t_enqueue),
                  ("queue_wait", tl.t_enqueue, tl.t_pop),
                  ("lane_build", tl.t_pop, tl.t_build),
                  ("device_dispatch", tl.t_build, tl.t_issue),
                  ("completion_decode", tl.t_issue or tl.t_build,
                   tl.t_decode),
                  ("stream_publish", tl.t_decode, tl.t_publish)]
        present = [(n, a, b) for n, a, b in stamps
                   if a is not None and b is not None and b >= a]
        if not present:
            return
        first = min(a for _, a, _ in present)
        last = max(b for _, _, b in present)
        events.append({
            "name": f"dispatch#{tl.trace_id}", "cat": "dispatch",
            "ph": "X", "pid": pid, "tid": tid,
            "ts": round(self._rel_us(first), 3),
            "dur": round((last - first) * 1e6, 3),
            "args": {
                "trace_id": tl.trace_id, "path": tl.path, "why": why,
                "ops": tl.n_ops, "shape": tl.shape, "waves": tl.waves,
                "mega_m": tl.mega_m,
                "e2e_us": round(e2e_us, 1) if e2e_us is not None else None,
                "counters": dict(tl.counters),
            },
        })
        for name, a, b in present:
            events.append({
                "name": name, "cat": "stage", "ph": "X", "pid": pid,
                "tid": tid, "ts": round(self._rel_us(a), 3),
                "dur": round((b - a) * 1e6, 3),
                "args": {"trace_id": tl.trace_id},
            })
        self._enqueue(events)
        if self.metrics is not None:
            self.metrics.inc("trace_exported_dispatches")

    def emit_span(self, name: str, t_start: float, t_end: float,
                  thread_label: str | None = None) -> None:
        """A host-side span (tracing.span / sink commit) on its own
        thread track, sampled at the same 1-in-N rate per span name (a
        span fires per dispatch — unsampled export would swamp the file
        at exactly the rates worth tracing)."""
        seen = self._span_seen.get(name, 0) + 1
        self._span_seen[name] = seen
        if seen % self.sample_every:
            return
        events: list[dict] = []
        label = thread_label or f"span:{threading.current_thread().name}"
        tid = self._tid(label, events)
        events.append({
            "name": name, "cat": "span", "ph": "X", "pid": os.getpid(),
            "tid": tid, "ts": round(self._rel_us(t_start), 3),
            "dur": round((t_end - t_start) * 1e6, 3),
        })
        self._enqueue(events)

    def _enqueue(self, events: list[dict]) -> None:
        with self._ev_lock:
            dropped = len(self._events) + len(events) > self._queue_cap
            if not dropped:
                self._events.extend(events)
        if dropped:
            if self.metrics is not None:
                self.metrics.inc("trace_dropped_events", len(events))
            self._unregister_meta(events)

    # -- the writer thread --------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self._flush_interval_s):
            self.flush()

    def flush(self) -> None:
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        with self._ev_lock:
            batch, self._events = self._events, []
        if not batch:
            return
        try:
            if self._file is None:
                os.makedirs(self.trace_dir, exist_ok=True)
                ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
                self.path = os.path.join(
                    self.trace_dir, f"trace_{ts}_{os.getpid()}.json")
                self._file = open(self.path, "w")
                self._file.write("[\n")
            chunks = []
            for e in batch:
                if self._wrote_any:
                    chunks.append(",\n")
                self._wrote_any = True
                chunks.append(json.dumps(e, separators=(",", ":")))
            self._file.write("".join(chunks))
            self._file.flush()
        except (OSError, ValueError) as e:
            # ValueError: write on a file closed by a racing close().
            # The batch is dropped (bounded memory beats a retry queue on
            # a full disk); the counter carries the true loss rate and
            # the log line stays at human rate however fast dispatches
            # sample. Track metadata in the lost batch unregisters so the
            # track re-labels itself on its next event.
            if self.metrics is not None:
                self.metrics.inc("trace_write_errors")
            self._unregister_meta(batch)
            warn_rate_limited(
                "trace-writer",
                f"[obs] trace write failed: {type(e).__name__}: {e}")

    def close(self) -> None:
        """Final flush + JSON finalize. The array closes with `]` so the
        file json-parses; an uncleanly-killed run leaves the
        unterminated array, which Perfetto still loads."""
        self._stop.set()
        self._thread.join(timeout=5)
        with self._flush_lock:
            self._flush_locked()
            if self._file is not None:
                try:
                    self._file.write("\n]\n")
                    self._file.close()
                except OSError as e:
                    warn_rate_limited(
                        "trace-writer",
                        f"[obs] trace finalize failed: "
                        f"{type(e).__name__}: {e}")
                self._file = None


# -- Prometheus text exposition ---------------------------------------------

_PROM_PREFIX = "me_"


def _prom_name(name: str) -> str:
    """Registry key -> Prometheus metric name (charset is already
    [a-z0-9_] by construction; prefix namespaces the exporter)."""
    return _PROM_PREFIX + name


def render_prometheus(metrics) -> str:
    """Render the full registry in Prometheus text format 0.0.4.

    Counters -> `me_<name>_total` (counter); gauges -> `me_<name>`
    (gauge). Histograms export BOTH ways: the derived
    `<name>_p50`/`<name>_p99`/`<name>_p999` gauges (quantiles computed
    server-side over the time window — stable names, no PromQL needed)
    AND native `me_<name>_bucket{le="..."}` series with `_sum`/`_count`,
    so histogram_quantile() and cross-instance aggregation work. The
    bucket/_sum/_count series are LIFETIME-cumulative (never shrink —
    proper Prometheus counter semantics for rate()); only the derived
    quantile gauges describe the `me_stage_window_seconds` time window.
    """
    counters, gauges = metrics.snapshot()
    lines: list[str] = []
    for name in sorted(counters):
        p = _prom_name(name) + "_total"
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {int(counters[name])}")
    for name in sorted(gauges):
        p = _prom_name(name)
        lines.append(f"# TYPE {p} gauge")
        v = float(gauges[name])
        lines.append(f"{p} {v:.6g}")
    hist_fn = getattr(metrics, "hist_snapshot", None)
    if hist_fn is not None:
        hists = hist_fn()
        for name in sorted(hists):
            h = hists[name]
            p = _prom_name(name)
            lines.append(f"# TYPE {p} histogram")
            for ub, cum in h["buckets"]:
                lines.append(f'{p}_bucket{{le="{ub:.6g}"}} {cum}')
            lines.append(f'{p}_bucket{{le="+Inf"}} {h["count"]}')
            lines.append(f"{p}_sum {h['sum']:.6g}")
            lines.append(f"{p}_count {h['count']}")
    return "\n".join(lines) + "\n"


class ObsServer:
    """The `--metrics-port` endpoint: a stdlib-only ThreadingHTTPServer
    on its own daemon thread.

      GET /metrics         Prometheus text format (full registry)
      GET /healthz         200 while the process serves requests
      GET /readyz          200 once serving, 503 during shutdown
      GET /flightrecorder  JSON snapshot of the flight-recorder ring
      GET /auditz          online-surveillance verdict (--audit): 200 +
                           JSON while every invariant holds, 500 + the
                           violation summary once any fired — /readyz
                           deliberately stays green (a red audit means
                           INVESTIGATE, not drop traffic), 404 with the
                           auditor off
      GET /replz           replication verdict (--standby / --oplog-ship):
                           200 + the role/lag/attestation JSON while the
                           replica provably mirrors the primary, 500 once
                           an attestation divergence or an unrecoverable
                           op-log gap poisoned it (same investigate-not-
                           drop contract as /auditz), 404 with
                           replication off

    No third-party exporter dependency: the container must not need a
    pip install to be scrapable.
    """

    def __init__(self, metrics, recorder: FlightRecorder | None = None,
                 ready_fn=None, port: int = 0, host: str = "127.0.0.1",
                 auditor=None, repl=None):
        # Loopback by default: /flightrecorder exposes internal dispatch
        # detail — exporting to a scrape network is an explicit choice
        # (--metrics-host 0.0.0.0), not a side effect of enabling metrics.
        self.metrics = metrics
        self.recorder = recorder
        self.ready_fn = ready_fn or (lambda: True)
        self.auditor = auditor  # audit.InvariantAuditor | None
        # replication.StandbyReplica | replication.OpLogShipper | None —
        # anything with a snapshot() carrying an "ok" verdict.
        self.repl = repl
        obs = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # no per-scrape stderr spam
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200, render_prometheus(obs.metrics).encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/healthz":
                        self._send(200, b"ok\n", "text/plain")
                    elif path == "/readyz":
                        if obs.ready_fn():
                            self._send(200, b"ready\n", "text/plain")
                        else:
                            self._send(503, b"shutting down\n", "text/plain")
                    elif path == "/flightrecorder":
                        entries = (obs.recorder.snapshot()
                                   if obs.recorder is not None else [])
                        self._send(200, json.dumps(entries).encode(),
                                   "application/json")
                    elif path == "/auditz":
                        if obs.auditor is None:
                            self._send(404, b"auditor disabled\n",
                                       "text/plain")
                        else:
                            snap = obs.auditor.snapshot()
                            self._send(
                                200 if snap["ok"] else 500,
                                json.dumps(snap).encode(),
                                "application/json")
                    elif path == "/replz":
                        if obs.repl is None:
                            self._send(404, b"replication disabled\n",
                                       "text/plain")
                        else:
                            snap = obs.repl.snapshot()
                            self._send(
                                200 if snap["ok"] else 500,
                                json.dumps(snap).encode(),
                                "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper hung up mid-response

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http", daemon=True)

    def start(self) -> int:
        self._thread.start()
        return self.port

    def close(self) -> None:
        # shutdown() blocks on a flag only serve_forever sets; calling it
        # on a never-started server would wait forever.
        if self._thread.is_alive():
            self._httpd.shutdown()
            self._thread.join(timeout=5)
        self._httpd.server_close()
