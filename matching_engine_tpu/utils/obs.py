"""Observability subsystem: stage latency ledger, Prometheus exposition,
and a crash flight recorder.

Before this module the only visibility was the in-process Metrics registry
behind one gRPC call — and the --native-lanes fast path moved per-op work
into C++ where those hooks no longer fire, so the fastest configuration
was the blindest one. Three layers fix that:

1. **Stage latency ledger** (`DispatchTimeline`): every serving dispatch
   carries monotonic stamps at the pipeline boundaries

       edge ingress -> queue enqueue -> lane build -> device dispatch
       -> completion decode -> stream publish -> sink commit

   and the deltas land in `stage_<name>_us` sliding-window histograms
   (p50/p99 via Metrics.snapshot). Stamps are per DISPATCH, not per op —
   the native-lanes path regains per-stage visibility without re-adding
   per-op Python work. Queue-depth and in-flight gauges ride along.

2. **Prometheus exposition** (`render_prometheus` + `ObsServer`): a
   stdlib-only HTTP thread serving `/metrics` (text format 0.0.4),
   `/healthz`, `/readyz`, and `/flightrecorder` (JSON ring snapshot).
   Counters export as `me_<name>_total`, gauges as `me_<name>`.

3. **Flight recorder** (`FlightRecorder`): a bounded ring of recent
   dispatch summaries (shape, counters, per-stage latencies, errors)
   that dumps JSON on SIGUSR2, fatal dispatch error, and clean shutdown
   — a soak/e2e failure leaves a post-mortem artifact instead of "it
   got slow".
"""

from __future__ import annotations

import http.server
import json
import os
import signal
import threading
import time
from collections import deque

# Stage histogram names, in pipeline order. Each is a Metrics.observe
# histogram in microseconds, exported with _p50/_p99 derived gauges.
STAGE_EDGE_INGRESS = "stage_edge_ingress_us"       # RPC entry -> ring/queue push
STAGE_QUEUE_WAIT = "stage_queue_wait_us"           # enqueue -> drain pop
STAGE_LANE_BUILD = "stage_lane_build_us"           # pop -> device buffers built
STAGE_DEVICE_DISPATCH = "stage_device_dispatch_us" # buffers built -> waves issued
STAGE_COMPLETION_DECODE = "stage_completion_decode_us"  # issue -> decoded (incl. pipeline residency + device wait)
STAGE_STREAM_PUBLISH = "stage_stream_publish_us"   # decode -> sink/hub enqueued
STAGE_SINK_COMMIT = "stage_sink_commit_us"         # one storage batch's SQLite txn

STAGES = (
    STAGE_EDGE_INGRESS, STAGE_QUEUE_WAIT, STAGE_LANE_BUILD,
    STAGE_DEVICE_DISPATCH, STAGE_COMPLETION_DECODE, STAGE_STREAM_PUBLISH,
    STAGE_SINK_COMMIT,
)


class DispatchTimeline:
    """Monotonic stamps for ONE dispatch crossing the serving pipeline.

    Created by a drain loop when it pops a batch (`path` names the edge:
    "python", "native-lanes", "gateway", "gateway-lanes"); the runner
    stamps the batch as it crosses each boundary; `finish()` folds the
    deltas into the stage histograms and appends one flight-recorder
    entry (when the registry carries one). All stamps are optional —
    a boundary never crossed simply records nothing.
    """

    __slots__ = ("path", "n_ops", "t_enqueue", "t_pop", "t_build",
                 "t_issue", "t_decode", "t_publish", "shape", "waves",
                 "mega_m", "counters")

    def __init__(self, path: str, n_ops: int, t_enqueue: float | None = None,
                 t_pop: float | None = None):
        self.path = path
        self.n_ops = n_ops
        self.t_enqueue = t_enqueue   # earliest op enqueue (queue-wait origin)
        self.t_pop = time.perf_counter() if t_pop is None else t_pop
        self.t_build = None
        self.t_issue = None
        self.t_decode = None
        self.t_publish = None
        self.shape = ""              # "sparse" | "dense" | "mesh" | "mega"
        self.waves = 0
        self.mega_m = 1              # waves stacked per device call (mega)
        self.counters: dict = {}

    def stamp_build(self) -> None:
        self.t_build = time.perf_counter()

    def stamp_issue(self) -> None:
        self.t_issue = time.perf_counter()

    def stamp_decode(self) -> None:
        self.t_decode = time.perf_counter()

    def stamp_publish(self) -> None:
        self.t_publish = time.perf_counter()

    def _stages_us(self) -> dict[str, float]:
        out: dict[str, float] = {}

        def delta(name, a, b):
            if a is not None and b is not None and b >= a:
                out[name] = (b - a) * 1e6

        delta(STAGE_QUEUE_WAIT, self.t_enqueue, self.t_pop)
        delta(STAGE_LANE_BUILD, self.t_pop, self.t_build)
        delta(STAGE_DEVICE_DISPATCH, self.t_build, self.t_issue)
        # Decode is stamped when THIS batch's results are decoded, which
        # under pipelining includes up to pipeline_inflight batches of
        # residency — the client-felt figure, same convention as
        # dispatch_us.
        delta(STAGE_COMPLETION_DECODE, self.t_issue or self.t_build,
              self.t_decode)
        delta(STAGE_STREAM_PUBLISH, self.t_decode, self.t_publish)
        return out

    def finish(self, metrics, error: Exception | None = None) -> None:
        """Fold the stamped deltas into the stage histograms and the
        flight-recorder ring. Call exactly once, from the edge's
        on_finish callback (dispatch lock held there is fine — observe()
        is the hot-path-safe registry call)."""
        stages = self._stages_us()
        for name, us in stages.items():
            metrics.observe(name, us)
        recorder = getattr(metrics, "recorder", None)
        if recorder is None:
            return
        entry = {
            "kind": "dispatch" if error is None else "dispatch_error",
            "path": self.path,
            "ops": self.n_ops,
            "shape": self.shape,
            "waves": self.waves,
            "mega_m": self.mega_m,
            "stages_us": {k: round(v, 1) for k, v in stages.items()},
            "counters": dict(self.counters),
        }
        if error is not None:
            entry["error"] = f"{type(error).__name__}: {error}"
        recorder.record(entry)
        if error is not None:
            recorder.dump_on_error()


_warn_lock = threading.Lock()
_warn_last: dict[str, float] = {}
_warn_suppressed: dict[str, int] = {}


def warn_rate_limited(key: str, msg: str, interval_s: float = 5.0) -> None:
    """Print `msg` at most once per `interval_s` per `key`, with a count
    of the lines suppressed in between. A flapping sink/hub fails at
    BATCH rate — per-failure print() would melt stdout exactly when the
    operator needs it; the paired `me_` counter carries the true rate."""
    now = time.monotonic()
    with _warn_lock:
        last = _warn_last.get(key, 0.0)
        if now - last < interval_s:
            _warn_suppressed[key] = _warn_suppressed.get(key, 0) + 1
            return
        suppressed = _warn_suppressed.pop(key, 0)
        _warn_last[key] = now
    tail = f" (+{suppressed} suppressed)" if suppressed else ""
    print(f"{msg}{tail}")


def record_dispatch_error(metrics, where: str, error: Exception) -> None:
    """Flight-record a drain-loop failure that never made it to a
    timeline (pop/stage machinery raised) and dump a post-mortem."""
    recorder = getattr(metrics, "recorder", None)
    if recorder is None:
        return
    recorder.record({
        "kind": "error", "where": where,
        "error": f"{type(error).__name__}: {error}",
    })
    recorder.dump_on_error()


class FlightRecorder:
    """Bounded ring of recent dispatch summaries with JSON dumps.

    Recording is cheap (one dict append under a lock, per DISPATCH);
    the ring overwrites oldest-first. Dumps go to `dump_dir` as
    `flight_<utc>_<reason>.json`; with no dump_dir the ring still
    records (snapshot() serves /flightrecorder) but dump() is a no-op
    returning None. Error-triggered dumps are rate-limited so a
    persistent fault can't fill the disk with identical post-mortems.
    """

    def __init__(self, capacity: int = 512, dump_dir: str | None = None,
                 error_dump_interval_s: float = 30.0):
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._last_error_dump = 0.0
        self._prev_sigusr2 = None
        self.dump_dir = dump_dir
        self.error_dump_interval_s = error_dump_interval_s

    def record(self, entry: dict) -> None:
        with self._lock:
            self._seq += 1
            e = dict(entry)
            e["seq"] = self._seq
            e["wall_ts"] = time.time()
            self._ring.append(e)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, reason: str) -> str | None:
        """Write the ring to a timestamped JSON file; returns the path
        (None when no dump_dir is configured or the write failed — a
        post-mortem must never take the server down with it)."""
        if not self.dump_dir:
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            path = os.path.join(
                self.dump_dir, f"flight_{ts}_{os.getpid()}_{reason}.json")
            doc = {
                "reason": reason,
                "wall_ts": time.time(),
                "pid": os.getpid(),
                "entries": self.snapshot(),
            }
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
            print(f"[obs] flight recorder dumped {len(doc['entries'])} "
                  f"entries to {path} ({reason})")
            return path
        except OSError as e:
            print(f"[obs] flight recorder dump failed: "
                  f"{type(e).__name__}: {e}")
            return None

    def dump_on_error(self) -> bool:
        """Rate-limited dump for fatal dispatch errors. The write runs on
        a background daemon thread: callers sit on serving-critical paths
        (timeline.finish runs under the dispatch lock), and a slow disk
        must never stall dispatches for a post-mortem. Returns whether a
        dump was scheduled."""
        if not self.dump_dir:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._last_error_dump < self.error_dump_interval_s:
                return False
            self._last_error_dump = now
        threading.Thread(target=self.dump, args=("dispatch-error",),
                         name="flight-dump", daemon=True).start()
        return True

    def install_sigusr2(self) -> bool:
        """SIGUSR2 -> dump("sigusr2"). Main thread only (signal module
        restriction); returns False where unavailable (e.g. Windows)."""
        if not hasattr(signal, "SIGUSR2"):
            return False
        try:
            self._prev_sigusr2 = signal.signal(
                signal.SIGUSR2, lambda *_: self.dump("sigusr2"))
            return True
        except ValueError:  # not the main thread
            return False

    def uninstall_sigusr2(self) -> None:
        if self._prev_sigusr2 is not None:
            signal.signal(signal.SIGUSR2, self._prev_sigusr2)
            self._prev_sigusr2 = None


# -- Prometheus text exposition ---------------------------------------------

_PROM_PREFIX = "me_"


def _prom_name(name: str) -> str:
    """Registry key -> Prometheus metric name (charset is already
    [a-z0-9_] by construction; prefix namespaces the exporter)."""
    return _PROM_PREFIX + name


def render_prometheus(metrics) -> str:
    """Render the full registry in Prometheus text format 0.0.4.

    Counters -> `me_<name>_total` (counter); gauges -> `me_<name>`
    (gauge). Histogram windows surface through snapshot() as the
    derived `<name>_p50`/`<name>_p99` gauges — quantiles computed
    server-side over the sliding window, exported as plain gauges
    (the scraper gets stable names without native histogram buckets).
    """
    counters, gauges = metrics.snapshot()
    lines: list[str] = []
    for name in sorted(counters):
        p = _prom_name(name) + "_total"
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {int(counters[name])}")
    for name in sorted(gauges):
        p = _prom_name(name)
        lines.append(f"# TYPE {p} gauge")
        v = float(gauges[name])
        lines.append(f"{p} {v:.6g}")
    return "\n".join(lines) + "\n"


class ObsServer:
    """The `--metrics-port` endpoint: a stdlib-only ThreadingHTTPServer
    on its own daemon thread.

      GET /metrics         Prometheus text format (full registry)
      GET /healthz         200 while the process serves requests
      GET /readyz          200 once serving, 503 during shutdown
      GET /flightrecorder  JSON snapshot of the flight-recorder ring

    No third-party exporter dependency: the container must not need a
    pip install to be scrapable.
    """

    def __init__(self, metrics, recorder: FlightRecorder | None = None,
                 ready_fn=None, port: int = 0, host: str = "127.0.0.1"):
        # Loopback by default: /flightrecorder exposes internal dispatch
        # detail — exporting to a scrape network is an explicit choice
        # (--metrics-host 0.0.0.0), not a side effect of enabling metrics.
        self.metrics = metrics
        self.recorder = recorder
        self.ready_fn = ready_fn or (lambda: True)
        obs = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # no per-scrape stderr spam
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200, render_prometheus(obs.metrics).encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/healthz":
                        self._send(200, b"ok\n", "text/plain")
                    elif path == "/readyz":
                        if obs.ready_fn():
                            self._send(200, b"ready\n", "text/plain")
                        else:
                            self._send(503, b"shutting down\n", "text/plain")
                    elif path == "/flightrecorder":
                        entries = (obs.recorder.snapshot()
                                   if obs.recorder is not None else [])
                        self._send(200, json.dumps(entries).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper hung up mid-response

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http", daemon=True)

    def start(self) -> int:
        self._thread.start()
        return self.port

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()
