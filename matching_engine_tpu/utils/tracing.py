"""Profiling/tracing: jax.profiler integration + per-step annotations.

The reference's entire observability story is a per-RPC microsecond print
(src/server/matching_engine_service.cpp:46,116-118; SURVEY.md §5.1). The
TPU equivalent this module provides:

- `trace(dir)`: capture a full XLA device trace (TensorBoard-loadable) of
  everything dispatched inside the block;
- `step_annotation(name, n)`: label each engine dispatch so device traces
  show per-batch boundaries;
- `span(name)`: label an arbitrary host-side section — in the device
  trace (--profile-dir) AND, when a host trace exporter is installed
  (--trace-dir, utils/obs.TraceExporter via set_host_tracer), as a
  sampled Chrome trace_event slice in the same file as the per-dispatch
  pipeline slices, so one Perfetto view holds both.

Host-side wall-clock timing of arbitrary sections feeds the GetMetrics
registry via utils/metrics.py's Timer. The server enables tracing with
--profile-dir; bench/benchmark runs can wrap their loops directly.
"""

from __future__ import annotations

import contextlib
import time

import jax

# The process-wide host-span sink (utils/obs.TraceExporter | None).
# Installed by build_server when --trace-dir is set; module-global so the
# native-lanes loop's span() call sites need no plumbing.
_host_tracer = None


def set_host_tracer(tracer) -> None:
    """Install (or clear, with None) the host trace exporter span()
    mirrors into."""
    global _host_tracer
    _host_tracer = tracer


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler device trace into `log_dir`."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_annotation(name: str, step: int):
    """Annotate one engine dispatch in the device trace."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


@contextlib.contextmanager
def _span_both(name: str, tracer):
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        try:
            yield
        finally:
            tracer.emit_span(name, t0, time.perf_counter())


def span(name: str):
    """Label an arbitrary host-side section in the device trace (the
    non-step sibling of step_annotation). The native-lanes dispatch loop
    wraps its C++ lane build and completion decode in these so a
    --profile-dir trace shows per-batch boundaries in BOTH serving modes
    — before this, only EngineRunner's device steps were annotated and
    the native path's host sections were anonymous gaps. With a host
    tracer installed the same section additionally lands (sampled) in
    the --trace-dir Chrome trace."""
    tracer = _host_tracer
    if tracer is None:
        return jax.profiler.TraceAnnotation(name)
    return _span_both(name, tracer)
