"""Profiling/tracing: jax.profiler integration + per-step annotations.

The reference's entire observability story is a per-RPC microsecond print
(src/server/matching_engine_service.cpp:46,116-118; SURVEY.md §5.1). The
TPU equivalent this module provides:

- `trace(dir)`: capture a full XLA device trace (TensorBoard-loadable) of
  everything dispatched inside the block;
- `step_annotation(name, n)`: label each engine dispatch so device traces
  show per-batch boundaries;
Host-side wall-clock timing of arbitrary sections feeds the GetMetrics
registry via utils/metrics.py's Timer. The server enables tracing with
--profile-dir; bench/benchmark runs can wrap their loops directly.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler device trace into `log_dir`."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_annotation(name: str, step: int):
    """Annotate one engine dispatch in the device trace."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def span(name: str):
    """Label an arbitrary host-side section in the device trace (the
    non-step sibling of step_annotation). The native-lanes dispatch loop
    wraps its C++ lane build and completion decode in these so a
    --profile-dir trace shows per-batch boundaries in BOTH serving modes
    — before this, only EngineRunner's device steps were annotated and
    the native path's host sections were anonymous gaps."""
    return jax.profiler.TraceAnnotation(name)
