"""Checkpoint/restore of device book state + host directories.

The reference's only "checkpoint" is SQLite itself: restart reseeds the OID
sequence and (in intent, never in code) the book would be rebuilt from
`orders WHERE status IN (0,1)` (SURVEY.md §5.4). This framework keeps that
full-replay recovery path (server/main.py:recover_books) and adds what the
survey's TPU plan specifies on top: periodic snapshots of the device book so
restart cost is O(book size), not O(order history).

Format: one directory per checkpoint, written atomically (tmp dir + rename):
    book.npz   — the BookBatch arrays (host copies)
    meta.json  — engine config, symbol directory, open-order directory,
                 next OID, wall timestamp

Consistency: `snapshot()` must be called at a dispatch boundary with the
storage sink flushed (CheckpointDaemon does both), so the snapshot and
SQLite describe the same engine time. On restore, `reconcile` replays
anything SQLite knows that the snapshot predates:

- DB-open orders missing from the snapshot -> submitted (they arrived after
  the snapshot; back-of-queue priority is their true priority),
- snapshot orders the DB has since closed or partially filled -> canceled on
  device, and resubmitted with the DB's remaining quantity when still open.
  A post-snapshot partial fill therefore costs that order its queue position
  on recovery — documented recovery semantics, bounded by checkpoint cadence.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
import time

import jax
import numpy as np

from matching_engine_tpu.engine.book import BookBatch, EngineConfig
from matching_engine_tpu.engine.kernel import (
    CANCELED,
    FILLED,
    NEW,
    OP_CANCEL,
    OP_REST,
    PARTIALLY_FILLED,
    REJECTED,
)

_BOOK_FIELDS = BookBatch._fields


def _cfg_from_meta(meta: dict) -> EngineConfig:
    """EngineConfig from checkpoint meta, dropping keys of retired fields.

    Snapshots written before an execution-strategy knob was removed (e.g.
    the round-1 `pallas`/`pallas_interpret` flags retired in round 3) must
    keep loading: semantic compatibility is judged by semantic_key(), never
    by the config dataclass's full field list."""
    import dataclasses as _dc

    known = {f.name for f in _dc.fields(EngineConfig)}
    return EngineConfig(**{k: v for k, v in meta["cfg"].items() if k in known})


def _atomic_checkpoint_write(final: str, blocks: dict, meta: dict) -> None:
    """Write {book.npz, meta.json} into `final` via tmp dir + rename swap.

    The single atomic-swap implementation for both layouts (flat and
    per-host shard dirs) — a durability fix here covers both."""
    parent = os.path.dirname(os.path.abspath(final)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".ckpt-tmp-", dir=parent)
    try:
        np.savez(os.path.join(tmp, "book.npz"), **blocks)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.isdir(final):
            old = final + ".old"
            os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def save_checkpoint(path: str, runner) -> None:
    """Atomically write one checkpoint of `runner` (an EngineRunner).

    Caller is responsible for quiescence (no concurrent dispatch) — use
    CheckpointDaemon or hold the runner's snapshot lock externally.

    Multi-process: each host writes `path/host-<pid>/` atomically with ITS
    addressable book rows and ITS order directory (a host only ever books
    the symbols it owns); a whole-array read does not exist on a
    multi-process mesh. Single-process keeps the flat layout.
    """
    if jax.process_count() > 1:
        _save_checkpoint_hostlocal(path, runner)
        return
    if runner.cfg.tiers:
        # Tiered runner: one block set per tier group (shapes differ per
        # tier, so they cannot share one array). The tier spec rides
        # semantic_key, so a spec change refuses the restore loudly.
        book_host = {
            f"t{i}_{f}": np.asarray(getattr(b, f))
            for i, b in enumerate(runner.tier_books)
            for f in _BOOK_FIELDS
        }
    else:
        book_host = {
            f: np.asarray(getattr(runner.book, f)) for f in _BOOK_FIELDS}
    # The dispatch lock (held by the caller) quiesces the book and order
    # directories, but RPC threads allocate symbols/OIDs outside it — copy
    # those under the id lock so json.dump never walks a mutating dict.
    with runner._id_lock:
        symbols = dict(runner.symbols)
        next_oid_num = runner.next_oid_num
    meta = {
        "version": 2,  # v2: orders carry device handles (recycled int32 ids)
        "ts": time.time(),
        "cfg": dataclasses.asdict(runner.cfg),
        "symbols": symbols,
        "next_oid_num": next_oid_num,
        "orders": [dataclasses.asdict(i) for i in list(runner.orders_by_handle.values())],
    }
    _atomic_checkpoint_write(path, book_host, meta)


def _save_checkpoint_hostlocal(path: str, runner) -> None:
    from matching_engine_tpu.parallel import hostlocal

    blocks = {}
    lo = hi = 0
    for f in _BOOK_FIELDS:
        data, lo, hi = hostlocal.local_block(getattr(runner.book, f))
        blocks[f] = data
    with runner._id_lock:
        symbols = dict(runner.symbols)
        next_oid_num = runner.next_oid_num
    meta = {
        "version": 2,
        "ts": time.time(),
        "cfg": dataclasses.asdict(runner.cfg),
        "symbols": symbols,
        "next_oid_num": next_oid_num,
        "orders": [dataclasses.asdict(i)
                   for i in list(runner.orders_by_handle.values())],
        "slice": [lo, hi],
        "process": jax.process_index(),
        "num_processes": jax.process_count(),
    }
    _atomic_checkpoint_write(
        os.path.join(path, f"host-{jax.process_index():04d}"), blocks, meta)


def load_checkpoint(path: str) -> tuple[EngineConfig, BookBatch, dict]:
    """Read a checkpoint directory -> (cfg, host-side book, meta).

    For a multi-host checkpoint (host-<pid>/ layout), loads THIS process's
    shard and zero-pads the remote symbol rows: place_book reassembles the
    global array from every host's local rows, so the padding never lands
    on a device. meta carries the ["slice"] this host owns.
    """
    mine = os.path.join(path, f"host-{jax.process_index():04d}")
    if os.path.isdir(mine):
        with open(os.path.join(mine, "meta.json")) as f:
            meta = json.load(f)
        nproc = int(meta.get("num_processes", 1))
        # Every rank's shard must exist as a live (not .old leftover) dir
        # with its meta — a crash mid-rename must read as partial, loudly.
        missing = [
            r for r in range(nproc)
            if not os.path.isfile(
                os.path.join(path, f"host-{r:04d}", "meta.json"))
        ]
        if missing:
            raise ValueError(
                f"partial multi-host checkpoint: missing shard(s) for "
                f"rank(s) {missing} of {nproc}"
            )
        if int(meta.get("num_processes", 1)) != jax.process_count():
            raise ValueError(
                f"checkpoint written by {meta['num_processes']} processes, "
                f"restoring with {jax.process_count()}"
            )
        cfg = _cfg_from_meta(meta)
        lo, hi = meta["slice"]
        fields = {}
        with np.load(os.path.join(mine, "book.npz")) as z:
            for f in _BOOK_FIELDS:
                block = _field_or_default(z, f, cfg)
                full = np.zeros((cfg.num_symbols,) + block.shape[1:],
                                dtype=block.dtype)
                full[lo:hi] = block[lo:hi] if block.shape[0] == cfg.num_symbols else block
                fields[f] = full
        return cfg, BookBatch(**fields), meta
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    cfg = _cfg_from_meta(meta)
    with np.load(os.path.join(path, "book.npz")) as z:
        if cfg.tiers:
            # Tiered checkpoint: one BookBatch per tier group (the tiered
            # format postdates every BookBatch field, so no
            # forward-compat zero-fill is needed).
            book = [
                BookBatch(**{f: z[f"t{i}_{f}"] for f in _BOOK_FIELDS})
                for i in range(len(cfg.tiers))
            ]
        else:
            book = BookBatch(
                **{f: _field_or_default(z, f, cfg) for f in _BOOK_FIELDS})
    return cfg, book, meta


def _field_or_default(z, field: str, cfg: EngineConfig):
    """Forward compatibility for fields added to BookBatch after a
    snapshot was written (e.g. the round-3 self-trade-prevention owner
    lanes): a missing array loads as zeros of the field's shape;
    restore_runner rebuilds owner lanes from the order directory so old
    snapshots keep full STP semantics."""
    if field in z.files:
        return z[field]
    shape = ((cfg.num_symbols,) if field == "next_seq"
             else (cfg.num_symbols, cfg.capacity))
    return np.zeros(shape, dtype=np.int32)


def _rebuild_owner_lanes(runner) -> None:
    """Rebuild the owner lanes of a pre-owner snapshot from the order
    directory (handle -> client-id hash). Single-process only: on a
    multi-process mesh this RAISES before touching anything, and the
    caller (build_server) falls back to full SQLite replay — which
    reconstructs owners naturally from the persisted client ids."""
    import jax

    from matching_engine_tpu.parallel import hostlocal

    if runner.cfg.tiers:
        # The tiered checkpoint format postdates the owner lanes: every
        # tiered snapshot already carries them.
        return
    book = runner.book
    has_owners = (np.asarray(hostlocal.local_block(book.bid_owner)[0]).any()
                  or np.asarray(
                      hostlocal.local_block(book.ask_owner)[0]).any())
    if has_owners:
        return  # snapshot already carried owners
    # Identities via the runner's registry, NOT raw owner_hash: a
    # hash-collision-remapped client must get its persisted id here too,
    # or its rebuilt lane would alias the colliding client's STP identity
    # (the registry loads before restore — build_server ordering).
    owners = {h: runner._owner_for(i.client_id)
              for h, i in runner.orders_by_handle.items()}
    if not owners:
        return
    if jax.process_count() > 1:
        raise ValueError(
            "pre-owner-lane snapshot on a multi-process mesh: restore via "
            "full replay (owners rebuild from the persisted client ids)")
    bid_owner = np.asarray(book.bid_owner).copy()
    ask_owner = np.asarray(book.ask_owner).copy()
    bid_oid = np.asarray(book.bid_oid)
    bid_qty = np.asarray(book.bid_qty)
    ask_oid = np.asarray(book.ask_oid)
    ask_qty = np.asarray(book.ask_qty)
    for oid_arr, qty_arr, owner_arr in ((bid_oid, bid_qty, bid_owner),
                                        (ask_oid, ask_qty, ask_owner)):
        live = qty_arr > 0
        for r, c in zip(*np.nonzero(live)):
            owner_arr[r, c] = owners.get(int(oid_arr[r, c]), 0)
    host_book = BookBatch(*(np.asarray(x) for x in book))._replace(
        bid_owner=bid_owner, ask_owner=ask_owner)
    runner.place_book(host_book)


def restore_runner(runner, path: str, storage=None) -> int:
    """Load a checkpoint into `runner`, then reconcile against storage.

    Returns the number of reconciliation ops replayed (0 when the snapshot
    was already current). Raises ValueError on config mismatch.
    """
    from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo

    cfg, host_book, meta = load_checkpoint(path)
    if meta.get("version") != 2:
        raise ValueError(
            f"unsupported checkpoint version {meta.get('version')} "
            "(pre-handle formats restore via full replay)"
        )
    if tuple(cfg.tiers) != tuple(runner.cfg.tiers):
        # Its own clear error, distinct from generic config skew: a tier
        # re-spec changes which rows hold which books, so restoring the
        # old blocks would silently misplace depth. Callers fall back to
        # full replay, which re-rests open orders into the NEW layout.
        raise ValueError(
            f"checkpoint written under book-tier spec {tuple(cfg.tiers)} "
            f"but this server boots with {tuple(runner.cfg.tiers)} — "
            "restore refused; recover via full replay")
    if cfg.semantic_key() != runner.cfg.semantic_key():
        raise ValueError(
            f"checkpoint config {cfg} does not match runner config {runner.cfg}"
        )
    if "slice" in meta and list(meta["slice"]) != [runner._slot_lo,
                                                  runner._slot_hi]:
        # Same process count, different per-host device split: this rank's
        # shard no longer covers the rows it saved — restoring would
        # silently zero the difference. Fail loudly; callers fall back to
        # full replay from SQLite.
        raise ValueError(
            f"checkpoint shard covers symbols {meta['slice']} but this "
            f"rank now owns [{runner._slot_lo}, {runner._slot_hi})"
        )
    runner.place_book(host_book)
    runner.symbols = dict(meta["symbols"])
    runner.slot_symbols = [None] * cfg.num_symbols
    for sym, slot in runner.symbols.items():
        runner.slot_symbols[slot] = sym
    runner.orders_by_handle = {}
    runner.orders_by_id = {}
    for d in meta["orders"]:
        info = OrderInfo(**d)
        runner.orders_by_handle[info.handle] = info
        runner.orders_by_id[info.order_id] = info
    runner.seed_oid_sequence(int(meta["next_oid_num"]))
    # Snapshots written before the owner lanes existed load them as zeros;
    # rebuild from the directory (handle -> client hash) so restored books
    # keep self-trade prevention for their resting orders.
    _rebuild_owner_lanes(runner)
    # Rebuild allocator + slot-liveness state from the restored directory.
    # Handles of orders that died between this snapshot's birth process and
    # now are simply never reissued (next_handle continues past the max).
    runner._next_handle = 1 + max(
        (i.handle for i in runner.orders_by_handle.values()), default=0
    )
    runner._free_handles = []
    runner._slot_live = [0] * cfg.num_symbols
    for info in runner.orders_by_handle.values():
        runner._slot_live[runner.symbols[info.symbol]] += 1
    # Symbols snapshotted with zero live orders (their submits were queued
    # but never dispatched in the dead process) have no claim on a slot.
    for sym, slot in list(runner.symbols.items()):
        if runner._slot_live[slot] == 0:
            del runner.symbols[sym]
            runner.slot_symbols[slot] = None
    runner.rebuild_slot_allocator()

    if storage is None:
        return 0

    runner.seed_oid_sequence(storage.load_next_oid_seq())

    # --- reconcile: replay what SQLite saw after the snapshot -------------
    db_open: dict[str, tuple] = {}
    for row in storage.open_orders():
        # (order_id, client_id, symbol, side, otype, price, qty, remaining, status)
        db_open[row[0]] = row

    ops: list[EngineOp] = []
    # 1) snapshot orders the DB has since closed or changed: cancel stale
    #    device entries (and resubmit below with the DB remaining). The
    #    cancel dispatch itself evicts them — recycling handle and slot —
    #    so nothing is deleted from the directories by hand here.
    resubmit: list[OrderInfo] = []
    stale_ids: set[str] = set()
    for order_id, info in list(runner.orders_by_id.items()):
        row = db_open.get(order_id)
        if row is not None and row[7] == info.remaining:
            continue  # snapshot is current for this order
        ops.append(EngineOp(OP_CANCEL, info, cancel_requester="__recovery__"))
        stale_ids.add(order_id)
        if row is not None and row[7] > 0:
            resubmit.append(OrderInfo(
                oid=info.oid, order_id=order_id, client_id=row[1],
                symbol=row[2], side=row[3], otype=row[4], price_q4=row[5],
                quantity=row[6], remaining=row[7], status=row[8],
            ))
    # 2) DB-open orders the snapshot has never seen: submit them.
    resubmit_ids = {i.order_id for i in resubmit}
    for order_id, row in db_open.items():
        if order_id in runner.orders_by_id and order_id not in stale_ids:
            continue
        if order_id in resubmit_ids or order_id in stale_ids:
            continue
        num = int(order_id.split("-", 1)[1]) if order_id.startswith("OID-") else 0
        resubmit.append(OrderInfo(
            oid=num, order_id=order_id, client_id=row[1], symbol=row[2],
            side=row[3], otype=row[4], price_q4=row[5], quantity=row[6],
            remaining=row[7], status=row[8],
        ))

    if ops:
        runner.run_dispatch(ops)  # cancels first: frees capacity + removes stale
    # Handles/slots are assigned only now, after the cancel dispatch has
    # recycled the stale entries' — the allocator can't collide with a
    # handle that is still live on the device.
    sub_ops = []
    for info in sorted(resubmit, key=lambda i: i.oid):
        if not runner.owns_symbol(info.symbol):
            continue  # re-homed by a resize; rows stay in SQLite (main.py)
        if runner.slot_acquire(info.symbol) is None:
            continue  # symbol axis full; mirrors recover_books' drop policy
        info.handle = runner.assign_handle()
        sub_ops.append(EngineOp(OP_REST, info))
    if sub_ops:
        runner.run_dispatch(sub_ops)
    return len(ops) + len(sub_ops)


def latest_checkpoint(root: str) -> str | None:
    """Newest COMPLETE checkpoint directory under `root`.

    Multi-host layout: daemons tick independently (the engine step has no
    collectives to pace them), so the newest ckpt-N may hold only the
    faster hosts' shards at any instant — such partials are skipped here,
    and restore falls back to the newest checkpoint every rank finished.
    """
    if not os.path.isdir(root):
        return None
    best, best_ts = None, -1.0
    for name in os.listdir(root):
        p = os.path.join(root, name)
        mp = os.path.join(p, "meta.json")
        if not os.path.isfile(mp):
            # Multi-host layout: meta lives in the per-process subdirs.
            mp = os.path.join(p, f"host-{jax.process_index():04d}", "meta.json")
            if not os.path.isfile(mp):
                continue
        try:
            with open(mp) as f:
                meta = json.load(f)
            ts = float(meta.get("ts", 0))
            nproc = int(meta.get("num_processes", 1))
            if nproc > 1 and any(
                not os.path.isfile(
                    os.path.join(p, f"host-{r:04d}", "meta.json"))
                for r in range(nproc)
            ):
                continue  # partial (a rank hasn't written this one yet)
        except (ValueError, OSError):
            continue
        if ts > best_ts:
            best, best_ts = p, ts
    return best


class CheckpointDaemon:
    """Periodic checkpointer: flush the sink, quiesce the runner, snapshot.

    `keep` bounds retained checkpoints (oldest pruned). The flush barrier
    before the snapshot is what makes snapshot time == SQLite time (see
    module docstring).
    """

    def __init__(self, runner, sink, root: str, interval_s: float = 30.0,
                 keep: int = 3, storage=None):
        import threading

        self.runner = runner
        self.sink = sink
        self.root = root
        self.interval_s = interval_s
        self.keep = keep
        self.storage = storage  # enables checkpoint-time durability repairs
        self._overflows_seen = 0
        # Repairs/ledger rows that failed to persist (e.g. SQLITE_BUSY):
        # carried to the next checkpoint rather than lost — the host
        # directory was already mutated, so dropping them would leave
        # SQLite diverged with no acknowledgement.
        self._carry_repairs: list[tuple] = []
        self._carry_recon: list[tuple] = []
        # Resume numbering past any checkpoints a previous process left, so
        # _prune's name-sort never deletes a fresh snapshot as "oldest".
        self.saved = 1 + max(
            (int(n[5:]) for n in self._existing()), default=-1
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="checkpointer", daemon=True
        )

    def _existing(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            n for n in os.listdir(self.root)
            if n.startswith("ckpt-") and n[5:].isdigit()
            and os.path.isdir(os.path.join(self.root, n))
        )

    def start(self):
        self._thread.start()
        return self

    def checkpoint_now(self) -> str:
        path = os.path.join(self.root, f"ckpt-{self.saved:08d}")
        # Quiesce: no dispatch may run between the sink flush (which equalizes
        # SQLite with engine time) and the snapshot, and the book+directories
        # must not be mid-mutation (torn snapshots could double-apply orders
        # on restore). A pipelined dispatch staged-but-undecoded is part of
        # that invariant: its device waves are applied to the book, so it
        # MUST be decoded + published before the flush barrier, or the
        # snapshot would be ahead of SQLite.
        posts: list = []
        with self.runner._dispatch_lock:
            self.runner._finish_pending_locked(posts)
            self.sink.flush()
            # Owner registry joins the durability barrier: the snapshot's
            # book lanes carry assigned owner ints, so any assignment still
            # queued (e.g. an earlier sqlite-busy flush failure) must be
            # durable BEFORE the snapshot that freezes those ints — a
            # restore would otherwise re-derive different ids.
            self.runner.flush_owner_ids()
            self._reconcile_durability_locked()
            # Rare maintenance at the quiesce point: renumber seqs before
            # they can wrap int32 (the snapshot then freezes the rebased
            # lanes, so a restore inherits the headroom).
            self.runner.maybe_rebase_seqs()
            # Native lane mode keeps the hot-path directory in C++; pull
            # it into the Python mirror the snapshot reads (no-op on the
            # Python path).
            self.runner.sync_directory_for_snapshot_locked()
            save_checkpoint(path, self.runner)
        for p in posts:  # client completions, outside the engine lock
            p()
        self.saved += 1
        self._prune()
        return path

    def _reconcile_durability_locked(self) -> None:
        """Repair SQLite from the (authoritative) device book when fill
        records were lost to kernel max_fills overflow (VERDICT r2 weak #7).
        Runs under the dispatch lock, after the flush barrier, BEFORE the
        snapshot — so the snapshot captures the repaired directory and the
        recon ledger explains the missing fill rows to scripts/audit.py."""
        if self.storage is None:
            return
        overflows = self.runner.metrics.snapshot()[0].get(
            "fill_buffer_overflows", 0)
        repairs = self._carry_repairs
        recon = self._carry_recon
        self._carry_repairs, self._carry_recon = [], []
        if overflows > self._overflows_seen:
            self._overflows_seen = overflows
            repairs = repairs + self.runner.reconcile_fill_overflow()
        recon = recon + self.runner.drain_recon()
        if repairs or recon:
            if self.storage.apply_repairs(repairs, recon):
                print(f"[checkpoint] durability repair: {len(repairs)} "
                      f"orders, {len(recon)} recon rows")
            else:
                self._carry_repairs = repairs
                self._carry_recon = recon
                print(f"[checkpoint] durability repair failed; carrying "
                      f"{len(repairs)}/{len(recon)} rows to next checkpoint")

    def _prune(self):
        # Never delete the newest COMPLETE checkpoint (or anything newer):
        # with independently-ticking multi-host daemons, a stalled rank can
        # leave the only restorable state several names behind the fastest
        # rank's latest — pruning is bounded by restorability, not count.
        cks = self._existing()
        newest_complete = latest_checkpoint(self.root)
        protect_from = (
            os.path.basename(newest_complete) if newest_complete else None
        )
        for name in cks[: max(0, len(cks) - self.keep)]:
            if protect_from is not None and name >= protect_from:
                break
            shutil.rmtree(os.path.join(self.root, name), ignore_errors=True)

    def close(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.checkpoint_now()
            except Exception as e:  # keep the daemon alive; surface the error
                print(f"[checkpoint] snapshot failed: {type(e).__name__}: {e}")
