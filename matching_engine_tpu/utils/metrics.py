"""Minimal thread-safe metrics registry.

The reference's only observability is per-RPC wall-clock prints
(matching_engine_service.cpp:46,116-118; SURVEY.md §5.1/5.5). This registry
backs the GetMetrics RPC and periodic log lines: monotonic counters
(orders_accepted, fills, ...) and gauges (batch latency EMA, queue depth).
"""

from __future__ import annotations

import threading
import time


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def ema_gauge(self, name: str, value: float, alpha: float = 0.1) -> None:
        with self._lock:
            prev = self._gauges.get(name)
            self._gauges[name] = value if prev is None else alpha * value + (1 - alpha) * prev

    def snapshot(self) -> tuple[dict[str, int], dict[str, float]]:
        with self._lock:
            return dict(self._counters), dict(self._gauges)


class Timer:
    """Context manager feeding a microsecond EMA gauge."""

    def __init__(self, metrics: Metrics, gauge: str):
        self._m = metrics
        self._g = gauge

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._m.ema_gauge(self._g, (time.perf_counter() - self._t0) * 1e6)
        return False
