"""Minimal thread-safe metrics registry.

The reference's only observability is per-RPC wall-clock prints
(matching_engine_service.cpp:46,116-118; SURVEY.md §5.1/5.5). This registry
backs the GetMetrics RPC and periodic log lines: monotonic counters
(orders_accepted, fills, ...) and gauges (batch latency EMA, queue depth).
"""

from __future__ import annotations

import threading
import time


_HIST_CAP = 4096  # ring-buffer samples per histogram


def _rank(sorted_ring: list, q: float) -> float:
    """Nearest-rank percentile over a sorted, non-empty sample list."""
    return sorted_ring[min(int(q * len(sorted_ring)), len(sorted_ring) - 1)]


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        # name -> (ring list, next write index)
        self._hists: dict[str, tuple[list, int]] = {}
        # Optional utils/obs.py FlightRecorder, attached by build_server.
        # Riding on the registry keeps the recorder reachable from every
        # layer that already holds `metrics`, without constructor churn.
        self.recorder = None

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def ema_gauge(self, name: str, value: float, alpha: float = 0.1) -> None:
        """Exponential moving average, stored under `<name>_ema`.

        The suffix is applied HERE so an EMA can never collide with the
        same-named histogram's derived percentiles: Timer feeds both
        `x_us` observe() and `x_us` ema_gauge(), which used to surface
        as an indistinguishable bare `x_us` gauge next to `x_us_p50`
        (the submit_rpc_us collision).
        """
        name = f"{name}_ema"
        with self._lock:
            prev = self._gauges.get(name)
            self._gauges[name] = value if prev is None else alpha * value + (1 - alpha) * prev

    def observe(self, name: str, value: float) -> None:
        """Record one sample into `name`'s sliding-window histogram.

        The BASELINE metric is "orders/sec + p99 match latency": percentiles
        need a sample window, not an EMA. A fixed ring bounds memory; the
        window covers the last _HIST_CAP dispatches.
        """
        with self._lock:
            ring, idx = self._hists.get(name, ([], 0))
            if len(ring) < _HIST_CAP:
                ring.append(float(value))
            else:
                ring[idx] = float(value)
            self._hists[name] = (ring, (idx + 1) % _HIST_CAP)

    def percentile(self, name: str, q: float) -> float | None:
        """q in [0, 1] over the sliding window; None with no samples."""
        with self._lock:
            ring, _ = self._hists.get(name, ([], 0))
            ring = list(ring)  # sort OUTSIDE the lock: observe() is hot-path
        if not ring:
            return None
        ring.sort()
        return _rank(ring, q)

    def snapshot(self) -> tuple[dict[str, int], dict[str, float]]:
        """Counters + gauges, with p50/p99 derived gauges per histogram."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            rings = {n: list(r) for n, (r, _) in self._hists.items()}
        for name, ring in rings.items():
            ring.sort()
            if ring:
                gauges[f"{name}_p50"] = _rank(ring, 0.50)
                gauges[f"{name}_p99"] = _rank(ring, 0.99)
        return counters, gauges


class Timer:
    """Context manager feeding a microsecond EMA gauge (<name>_ema) plus
    the sliding-window histogram (surfaced as <name>_p50/_p99 in
    snapshot())."""

    def __init__(self, metrics: Metrics, gauge: str):
        self._m = metrics
        self._g = gauge

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        us = (time.perf_counter() - self._t0) * 1e6
        self._m.ema_gauge(self._g, us)
        self._m.observe(self._g, us)
        return False
