"""Minimal thread-safe metrics registry with three-nines histograms.

The reference's only observability is per-RPC wall-clock prints
(matching_engine_service.cpp:46,116-118; SURVEY.md §5.1/5.5). This registry
backs the GetMetrics RPC and periodic log lines: monotonic counters
(orders_accepted, fills, ...) and gauges (batch latency EMA, queue depth).

Histograms are HDR-style **log-bucketed** and **time-windowed**:

- Buckets are geometric with ratio 2^(1/8) (~9% relative width), covering
  sub-microsecond to ~10^9 µs in a fixed int array — observe() is O(1)
  with no per-sample storage, so a histogram's cost no longer depends on
  traffic rate, and the tail (p99.9) is as cheap as the median.
- The window is TIME-bounded (default 60 s, in `window_s` rotating
  slices), not last-N: under megadispatch the per-dispatch sample rate
  collapses and a last-4096 ring silently spanned minutes, making "p99"
  gauges stale snapshots of old load. A scrape now always describes the
  last `stage_window_seconds` (exported gauge), whatever the rate.
- Quantiles report the bucket UPPER bound (the HDR convention): the true
  sample is never above the reported value's bucket, so latency SLO
  checks err conservative. Exact-sample assertions belong to the raw
  recorder in benchmarks/latency_bench.py, not the registry.

snapshot() derives `<name>_p50/_p99/_p999` gauges per histogram;
hist_snapshot() exposes the raw cumulative buckets for native Prometheus
`le` exposition (utils/obs.render_prometheus).
"""

from __future__ import annotations

import math
import threading
import time

# Geometric bucket grid: index = floor(log2(v) * _LOG_SUB) + _IDX_OFF.
# _LOG_SUB sub-buckets per octave => relative width 2^(1/_LOG_SUB) ~ 9%.
_LOG_SUB = 8
_IDX_OFF = 10 * _LOG_SUB          # values down to 2^-10 (sub-µs deltas)
_N_BUCKETS = 40 * _LOG_SUB        # values up to 2^30 µs (~18 minutes)

_WINDOW_S = 60.0                  # default histogram window
_N_SLICES = 6                     # rotation granularity (window/6 per slice)
# The ring holds one EXTRA slice beyond the window's worth: merging N
# full slices + the current partial one guarantees coverage of at least
# window_s (never less, as an N-slice ring would right after each
# rotation) — the stage_window_seconds gauge promises a floor.
_N_RING = _N_SLICES + 1


def bucket_index(value: float) -> int:
    """Clamped log-bucket index for one sample."""
    if value <= 0.0:
        return 0
    i = int(math.floor(math.log2(value) * _LOG_SUB)) + _IDX_OFF
    return min(max(i, 0), _N_BUCKETS - 1)


def bucket_upper(i: int) -> float:
    """Upper bound of bucket i (the value quantiles report)."""
    return 2.0 ** ((i + 1 - _IDX_OFF) / _LOG_SUB)


class _WindowedHist:
    """One metric's log-bucketed counts over a rotating time window.

    `slices` is a ring of per-slice bucket arrays — one more slice than
    the window's worth, so the merged view (N full slices + the current
    partial one) always covers at least window_s and at most
    window_s + slice_s of history; advancing time zeroes the slices the
    clock skipped. All methods are called with the registry lock held.
    """

    __slots__ = ("slices", "epoch", "slice_s",
                 "life_counts", "life_sum", "life_count")

    def __init__(self, slice_s: float, now: float):
        self.slices = [[0] * _N_BUCKETS for _ in range(_N_RING)]
        self.slice_s = slice_s
        self.epoch = int(now / slice_s)
        # Lifetime (never-reset) view backing the Prometheus native
        # histogram series: rate()/histogram_quantile() need cumulative-
        # forever counts — a windowed count shrinks at slice rotation,
        # which Prometheus reads as a counter reset and double-counts.
        self.life_counts = [0] * _N_BUCKETS
        self.life_sum = 0.0
        self.life_count = 0

    def _advance(self, now: float) -> None:
        epoch = int(now / self.slice_s)
        # `now` is captured BEFORE the registry lock, so a thread
        # preempted at a slice boundary can arrive with a STALE
        # timestamp after a newer one already advanced the ring. Never
        # step backwards: doing so would re-zero the newer thread's
        # live slice on the next advance (a stale sample lands in the
        # current slice instead — off by at most one slice).
        if epoch <= self.epoch:
            return
        step = min(epoch - self.epoch, _N_RING)
        for k in range(1, step + 1):
            j = (self.epoch + k) % _N_RING
            s = self.slices[j]
            for i in range(_N_BUCKETS):
                s[i] = 0
        self.epoch = epoch

    def observe(self, value: float, now: float) -> None:
        self._advance(now)
        i = bucket_index(value)
        self.slices[self.epoch % _N_RING][i] += 1
        self.life_counts[i] += 1
        self.life_sum += value
        self.life_count += 1

    def merged(self, now: float) -> list[int]:
        self._advance(now)
        out = [0] * _N_BUCKETS
        for s in self.slices:
            for i in range(_N_BUCKETS):
                out[i] += s[i]
        return out


def _quantiles(counts: list[int], qs: tuple[float, ...]) -> list[float] | None:
    """Bucket-upper-bound quantiles over merged window counts (nearest
    rank). None when the window holds no samples."""
    total = sum(counts)
    if total == 0:
        return None
    out = []
    for q in qs:
        rank = min(int(q * total), total - 1)  # 0-based nearest rank
        run = 0
        for i, c in enumerate(counts):
            run += c
            if run > rank:
                out.append(bucket_upper(i))
                break
    return out


class Metrics:
    def __init__(self, window_s: float = _WINDOW_S):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _WindowedHist] = {}
        self.window_s = float(window_s)
        self._slice_s = self.window_s / _N_SLICES
        # The window every *_p50/_p99/_p999 gauge is computed over — a
        # scrape is only interpretable knowing how much history it spans.
        self.set_gauge("stage_window_seconds", self.window_s)
        # Injectable clock (tests advance it to prove window expiry).
        self._now = time.monotonic
        # Optional utils/obs.py FlightRecorder, attached by build_server.
        # Riding on the registry keeps the recorder reachable from every
        # layer that already holds `metrics`, without constructor churn.
        self.recorder = None
        # Optional utils/obs.py TraceExporter (--trace-dir), same pattern:
        # DispatchTimeline.finish offers each dispatch to the sampler.
        self.tracer = None

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def ema_gauge(self, name: str, value: float, alpha: float = 0.1) -> None:
        """Exponential moving average, stored under `<name>_ema`.

        The suffix is applied HERE so an EMA can never collide with the
        same-named histogram's derived percentiles: Timer feeds both
        `x_us` observe() and `x_us` ema_gauge(), which used to surface
        as an indistinguishable bare `x_us` gauge next to `x_us_p50`
        (the submit_rpc_us collision).
        """
        name = f"{name}_ema"
        with self._lock:
            prev = self._gauges.get(name)
            self._gauges[name] = value if prev is None else alpha * value + (1 - alpha) * prev

    def observe(self, name: str, value: float) -> None:
        """Record one sample into `name`'s windowed log-bucket histogram.

        O(1), no per-sample storage: one bucket increment in the current
        time slice. The window covers the last `window_s` seconds
        (stage_window_seconds gauge), however many samples arrived.
        """
        now = self._now()
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _WindowedHist(self._slice_s, now)
            h.observe(float(value), now)

    def percentile(self, name: str, q: float) -> float | None:
        """q in [0, 1] over the time window; None with no samples.
        Reports the sample's bucket upper bound (≤ ~9% above the true
        value, never below it)."""
        now = self._now()
        with self._lock:
            h = self._hists.get(name)
            counts = h.merged(now) if h is not None else None
        if counts is None:
            return None
        out = _quantiles(counts, (q,))
        return None if out is None else out[0]

    def snapshot(self) -> tuple[dict[str, int], dict[str, float]]:
        """Counters + gauges, with p50/p99/p999 derived gauges per
        histogram (empty windows surface no derived gauges — absent is
        distinguishable from zero)."""
        now = self._now()
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            merged = {n: h.merged(now) for n, h in self._hists.items()}
        for name, counts in merged.items():
            qv = _quantiles(counts, (0.50, 0.99, 0.999))
            if qv is not None:
                gauges[f"{name}_p50"] = qv[0]
                gauges[f"{name}_p99"] = qv[1]
                gauges[f"{name}_p999"] = qv[2]
        return counters, gauges

    def hist_snapshot(self) -> dict[str, dict]:
        """Raw histogram state for native Prometheus exposition: per name
        {"buckets": [(upper_bound, cumulative_count)], "sum", "count"} —
        all LIFETIME-cumulative (proper Prometheus histogram semantics:
        rate()/increase()/histogram_quantile() need counts that never
        shrink; the TIME-WINDOWED view lives in the derived
        _p50/_p99/_p999 gauges instead). A bucket once seen stays listed,
        so the le label set only grows; only boundaries where the
        cumulative count changes are listed — the full 320-bucket grid
        would bloat scrapes."""
        with self._lock:
            merged = {n: (list(h.life_counts), h.life_sum, h.life_count)
                      for n, h in self._hists.items()}
        out: dict[str, dict] = {}
        for name, (counts, lsum, lcount) in merged.items():
            cum = 0
            buckets = []
            for i, c in enumerate(counts):
                if c:
                    cum += c
                    buckets.append((bucket_upper(i), cum))
            out[name] = {"buckets": buckets, "sum": lsum, "count": lcount}
        return out


class Timer:
    """Context manager feeding a microsecond EMA gauge (<name>_ema) plus
    the windowed histogram (surfaced as <name>_p50/_p99/_p999 in
    snapshot())."""

    def __init__(self, metrics: Metrics, gauge: str):
        self._m = metrics
        self._g = gauge

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        us = (time.perf_counter() - self._t0) * 1e6
        self._m.ema_gauge(self._g, us)
        self._m.observe(self._g, us)
        return False
