"""Version-skew shims for the JAX APIs this repo relies on.

The mesh path was written against the promoted `jax.shard_map` (jax >=
0.5, `check_vma=` keyword). Older runtimes (0.4.x, like this
environment's 0.4.37) only ship `jax.experimental.shard_map.shard_map`
with the pre-rename `check_rep=` keyword — same semantics, different
spelling. Every shard_map call site goes through this wrapper so the
mesh/sharding stack (ShardedEngine, the sharded sim, the multi-process
servers) runs identically on both families instead of dying with
AttributeError at ShardedEngine construction.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """`jax.shard_map` with the 0.4.x experimental fallback.

    `check_vma` follows the new spelling; on old JAX it maps onto
    `check_rep` (the same replication/varying-manual-axes check under its
    pre-promotion name). None = each version's default.
    """
    kwargs = {}
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
    from jax.experimental.shard_map import shard_map as esm

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
