"""Shared device-throughput measurement methodology.

Used by the headline bench (bench.py) and the benchmark suite
(benchmarks/run_all.py) so the two can't silently diverge. Contract:

- real ops are counted from the HOST-side batches before device_put —
  reading a device array back mid-measurement collapses the axon tunnel's
  async dispatch pipeline and slows every subsequent step ~1000x;
- one un-timed warm pass compiles and primes the pipeline;
- several independent fully-synced windows are timed; the first is
  discarded (ramp) and the median of the rest is the sustained figure.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import build_batches
from matching_engine_tpu.engine.kernel import engine_step


def headline_streams(cfg: EngineConfig, n_streams: int = 4):
    """THE headline-bench flow (bench_child, the resident, and the watch
    captures all call this): L3-style mixed op stream at the config's
    shape. One definition so the resident's phase-0 figure and the child's
    figure stay comparable rows of the same metric."""
    from matching_engine_tpu.engine.harness import random_order_stream

    return [
        random_order_stream(
            cfg.num_symbols, 4 * cfg.num_symbols * cfg.batch, seed=w,
            cancel_p=0.10, market_p=0.15, price_base=9_950,
            price_levels=100, price_step=1, qty_max=100,
        )
        for w in range(n_streams)
    ]


def result_row(cfg: EngineConfig, value: float, lat_us: float, *,
               platform: str, n_devices: int, backend_init_s: float,
               git_rev: str) -> dict:
    """The benchmark artifact row shape (shared by bench_child and the
    resident so a schema tweak can't silently fork the two). The kernel
    label comes from cfg itself — the one thing that actually selected
    the formulation — so a row can never be mislabeled."""
    return {
        "value": value,
        "platform": platform,
        "n_devices": n_devices,
        "symbols": cfg.num_symbols,
        "capacity": cfg.capacity,
        "batch": cfg.batch,
        "backend_init_s": round(backend_init_s, 1),
        "mean_dispatch_latency_us": round(lat_us, 1),
        "kernel": cfg.kernel,
        "git_rev": git_rev,
    }


def prepare_waves(cfg: EngineConfig, streams, waves_per_stream: int = 2):
    """Device-put the leading `waves_per_stream` dispatches of each stream.
    Returns (waves, wave_ops) — the reusable device-resident inputs for
    measure_windows (the warm resident keeps these alive across requests
    so a measurement request costs windows, not stream building)."""
    waves, wave_ops = [], []
    for stream in streams:
        for b in build_batches(cfg, stream)[:waves_per_stream]:
            wave_ops.append(int(np.count_nonzero(np.asarray(b.op))))
            waves.append(jax.device_put(b))
    return waves, wave_ops


def measure_windows(cfg: EngineConfig, book, waves, wave_ops, *,
                    windows: int = 5, iters: int = 20):
    """The timed core: `windows` fully-synced windows of `iters` steps over
    pre-device-put waves; first window discarded (ramp). Returns
    (sustained orders/sec, mean step latency µs, book') — book' so a
    long-lived caller (benchmarks/resident.py) can thread state through
    repeated measurements without re-initializing. The match formulation
    is cfg.kernel (engine_step_impl dispatches on it at trace time)."""
    step = engine_step
    real_ops = sum(wave_ops[i % len(waves)] for i in range(iters))
    rates, lats = [], []
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(iters):
            book, out = step(cfg, book, waves[i % len(waves)])
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rates.append(real_ops / dt)
        lats.append(dt / iters * 1e6)

    # Report BOTH stats from the same (median-by-rate) window: sorting the
    # two lists independently can pair a fast window's rate with a slow
    # window's latency when inter-window variance is high (observed on the
    # axon tunnel: adjacent windows 3x apart), yielding a self-inconsistent
    # (rate, latency) pair — rate * latency must equal ops-per-step.
    pairs = sorted(zip(rates[1:], lats[1:]))
    mid_rate, mid_lat = pairs[len(pairs) // 2]
    return mid_rate, mid_lat, book


def measure_device_throughput(
    cfg: EngineConfig,
    streams,
    *,
    windows: int = 5,
    iters: int = 20,
    waves_per_stream: int = 2,
):
    """Returns (sustained orders/sec, mean dispatch latency in µs — the
    median across windows of each window's MEAN step latency dt/iters; a
    mean, not a percentile — real p50/p99 come from the serving-stack
    benchmark, see docs/BENCH_METHOD.md).

    `streams` is a list of HostOrder lists; the leading `waves_per_stream`
    dispatches of each are cycled during the timed loop.
    """
    waves, wave_ops = prepare_waves(cfg, streams, waves_per_stream)

    book = init_book(cfg)
    book, out = engine_step(cfg, book, waves[0])
    jax.block_until_ready(out)

    rate, lat, _ = measure_windows(
        cfg, book, waves, wave_ops, windows=windows, iters=iters)
    return rate, lat
