"""Shared device-throughput measurement methodology.

Used by the headline bench (bench.py) and the benchmark suite
(benchmarks/run_all.py) so the two can't silently diverge. Contract:

- real ops are counted from the HOST-side batches before device_put —
  reading a device array back mid-measurement collapses the axon tunnel's
  async dispatch pipeline and slows every subsequent step ~1000x;
- one un-timed warm pass compiles and primes the pipeline;
- several independent fully-synced windows are timed; the first is
  discarded (ramp) and the median of the rest is the sustained figure.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from matching_engine_tpu.engine.book import EngineConfig, init_book
from matching_engine_tpu.engine.harness import build_batches
from matching_engine_tpu.engine.kernel import engine_step


def measure_device_throughput(
    cfg: EngineConfig,
    streams,
    *,
    windows: int = 5,
    iters: int = 20,
    waves_per_stream: int = 2,
):
    """Returns (sustained orders/sec, mean dispatch latency in µs — the
    median across windows of each window's MEAN step latency dt/iters; a
    mean, not a percentile — real p50/p99 come from the serving-stack
    benchmark, see docs/BENCH_METHOD.md).

    `streams` is a list of HostOrder lists; the leading `waves_per_stream`
    dispatches of each are cycled during the timed loop.
    """
    waves, wave_ops = [], []
    for stream in streams:
        for b in build_batches(cfg, stream)[:waves_per_stream]:
            wave_ops.append(int(np.count_nonzero(np.asarray(b.op))))
            waves.append(jax.device_put(b))

    book = init_book(cfg)
    book, out = engine_step(cfg, book, waves[0])
    jax.block_until_ready(out)

    real_ops = sum(wave_ops[i % len(waves)] for i in range(iters))
    rates, lats = [], []
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(iters):
            book, out = engine_step(cfg, book, waves[i % len(waves)])
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rates.append(real_ops / dt)
        lats.append(dt / iters * 1e6)

    # Report BOTH stats from the same (median-by-rate) window: sorting the
    # two lists independently can pair a fast window's rate with a slow
    # window's latency when inter-window variance is high (observed on the
    # axon tunnel: adjacent windows 3x apart), yielding a self-inconsistent
    # (rate, latency) pair — rate * latency must equal ops-per-step.
    pairs = sorted(zip(rates[1:], lats[1:]))
    mid_rate, mid_lat = pairs[len(pairs) // 2]
    return mid_rate, mid_lat
