"""Warm-standby replication (ROADMAP item 2, arXiv:2402.09527's design).

The subsystem composes substrate every prior PR machine-checked into
hot/warm high availability:

- `oplog.OpLogShipper` — the primary republishes every admitted
  dispatch's op records (the flat oprec codec, PR 7 — submits carry
  their primary-assigned order ids) as a new sequenced `oplog` feed
  channel, so a standby inherits resume/gap-fill/epoch-rebase from the
  feed layer for free;
- `standby.StandbyReplica` — a second server process boots
  `--standby <primary addr>`, applies the op log deterministically
  through its own runner + SQLite sink (bit-identical replay is the
  megadispatch-parity + determinism-taint contract, PR 10), serves
  read-only, and continuously ATTESTS: its locally produced storage
  rows must be byte-identical to the primary's drop-copy audit records
  per dispatch — divergence flight-dumps both sides and turns `/replz`
  red, making the determinism contract observed in production;
- promotion — on primary loss (heartbeat lapse with
  `--standby-auto-promote-s`, or the explicit `Promote` RPC /
  `client promote` verb) the standby bumps its feed epoch, re-seeds the
  per-residue-class OID floors from its durable store, and opens the
  mutation RPCs; existing sequenced-feed clients rebase.

Replication is ASYNCHRONOUS: acks do not wait for the standby, so a
SIGKILLed primary can lose the in-flight tail (bounded by one
publish->receive window) — the same bound the async SQLite sink already
accepts. The kill-the-primary soak round and tests/test_replication.py
pin what IS guaranteed: the applied prefix is bit-identical, gap-free,
and a promoted replica serves on from it with no order-id collisions.
"""

from matching_engine_tpu.replication.oplog import (
    OPLOG_CLIENT,
    OPLOG_DISPATCH,
    OPLOG_HEARTBEAT,
    OpLogShipper,
    ops_from_oprec,
    ops_to_oprec,
)
from matching_engine_tpu.replication.standby import StandbyReplica

__all__ = ["OPLOG_CLIENT", "OPLOG_DISPATCH", "OPLOG_HEARTBEAT",
           "OpLogShipper", "StandbyReplica", "ops_from_oprec",
           "ops_to_oprec"]
