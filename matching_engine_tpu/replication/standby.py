"""The deterministic standby replica + continuous attestation + promotion.

A server booted `--standby <primary addr>` builds its normal serving
stack (runner(s), dispatcher(s), sink, hub, feed) but keeps the mutation
RPCs closed (service.read_only) and instead drives the engine from the
primary's sequenced op log:

- **rx** (`_rx_loop`): a `SequencedSubscriber` on the `oplog` channel —
  full replay from seq 1 on first attach (`from_start`), resume +
  gap-fill on reconnect. Received events land in a bounded queue; the
  split from apply is what makes replication lag measurable (rx cursor
  vs applied cursor, in seqs and bytes) instead of hidden in gRPC flow
  control. An UNRECOVERABLE oplog gap (evicted past the primary's
  retransmission window) poisons the replica — `/replz` goes red; the
  operator re-bootstraps rather than serving a state with a hole in it.

- **apply** (`_applier_loop`): each oplog dispatch event is applied as
  ONE engine dispatch on its mirror lane — dispatch boundaries are part
  of the determinism contract (an ORDER row carries final-of-dispatch
  status, so merging or splitting primary dispatches would change rows
  even with identical op order). Submits register with the PRIMARY's
  order id (the log is authoritative for identity); the engine replay
  produces everything else, and the standby's own sink/hub/drop-copy
  publish exactly as a primary's drain loop would.

- **attest** (`_attestor_loop`): subscribes to the primary's drop-copy
  audit channel and pairs each primary dispatch's records with the
  locally produced rows by the dispatch trace id (shipped in the oplog
  envelope; stamped on every audit record). The comparison surface is
  the normalized drop-copy tuple — every storage-row field, with the
  declared wall-clock envelope excluded — so "replica == primary" is
  *observed per dispatch in production*, not just statically proven.
  First divergence flight-dumps both sides and turns `/replz` red.
  Requires the primary to run `--audit` (the drop-copy IS the
  attestation substrate); without it the standby still replicates,
  with `attested == 0` visible on `/replz`.

- **promotion** (`promote`): on heartbeat lapse (opt-in
  `--standby-auto-promote-s`) or the explicit `Promote` RPC — quiesce
  rx/apply (draining every received event), re-seed the
  per-residue-class OID floors, bump the feed epoch (purging the old
  line's spill segments), and open the mutation RPCs. Clients rebase on
  the epoch change; sub-second kill-to-first-accept is measured by
  benchmarks/failover_bench.py.

Fault injection: ME_REPL_FAULT=row corrupts exactly one standby-side
row before attestation — the detection path's own proof, mirrored from
ME_AUDIT_FAULT (tests + the soak's kill round boot the standby with it
to assert `/replz` CAN go red).
"""

from __future__ import annotations

import os
import queue
import threading
import time

import grpc

from matching_engine_tpu.audit.dropcopy import dropcopy_events
from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.kernel import OP_AMEND, OP_CANCEL, OP_SUBMIT
from matching_engine_tpu.feed.client import SequencedSubscriber
from matching_engine_tpu.feed.sequencer import CHANNEL_AUDIT, CHANNEL_OPLOG
from matching_engine_tpu.proto.rpc import MatchingEngineStub
from matching_engine_tpu.replication.oplog import OPLOG_DISPATCH, ops_from_oprec
from matching_engine_tpu.server.dispatcher import publish_result
from matching_engine_tpu.server.engine_runner import EngineOp, OrderInfo
from matching_engine_tpu.utils.obs import warn_rate_limited

_STOP = object()


def normalize_audit_event(e) -> tuple:
    """One drop-copy record -> the attestation tuple: every storage-row
    field, none of the dispatch envelope (trace/shape/waves/ingress are
    the DECLARED wall-clock surface — hierarchy.DETERMINISM_WAIVERS) and
    none of the feed stamps (seq/epoch are per-line by design)."""
    return (e.audit_kind, e.order_id, e.client_id, e.symbol, e.status,
            e.remaining_quantity, e.audit_quantity, e.audit_side,
            e.audit_otype, e.fill_price, e.fill_quantity, e.scale,
            e.counter_order_id)


def normalize_rows(orders, updates, fills) -> list[tuple]:
    """Storage rows -> attestation tuples through the SAME record
    builder the primary's drop-copy uses (dropcopy_events) — one mapping
    definition is what makes 'byte-identical rows' a structural
    comparison, not a parallel re-implementation."""
    return [normalize_audit_event(e)
            for e in dropcopy_events(orders, updates, fills)]


class _ReplFault:
    """Single-shot standby-side corruption (ME_REPL_FAULT=row): bump one
    local row tuple's quantity field before attestation, once."""

    def __init__(self, kind: str | None = None):
        self.kind = kind if kind is not None \
            else (os.environ.get("ME_REPL_FAULT", "") or None)
        self.fired = False

    def apply(self, rows: list[tuple]) -> list[tuple]:
        if self.kind != "row" or self.fired or not rows:
            return rows
        self.fired = True
        r = rows[0]
        # Index 5 is remaining_quantity — any field works; the attestor
        # compares whole tuples.
        return [r[:5] + (r[5] + 1,) + r[6:]] + rows[1:]


class StandbyReplica:
    """Wires the standby threads over an already-built serving stack
    (server/main.build_server constructs one, then hands it here)."""

    # Bounded pairing stores: a side that runs ahead parks groups here
    # until the other side's record for the same trace id arrives.
    _ATTEST_PENDING_MAX = 8192

    def __init__(self, primary_addr: str, *, runners, shards, sink, hub,
                 sequencer, storage, metrics, service,
                 auto_promote_s: float = 0.0, attest: bool = True,
                 rx_queue: int = 1024, fault: _ReplFault | None = None):
        self.primary_addr = primary_addr
        self.runners = runners
        self.shards = shards  # server/shards.ServingShards | None
        self.sink = sink
        self.hub = hub
        self.sequencer = sequencer
        self.storage = storage
        self.metrics = metrics
        self.service = service
        self.auto_promote_s = auto_promote_s
        self.attest = attest
        self.fault = fault if fault is not None else _ReplFault()
        # Pre-register every exported me_repl_* series.
        m = metrics
        m.inc("repl_applied_dispatches", 0)
        m.inc("repl_applied_ops", 0)
        m.inc("repl_apply_errors", 0)
        m.inc("repl_attested_dispatches", 0)
        m.inc("repl_divergences", 0)
        m.inc("repl_attest_unmatched", 0)
        m.inc("repl_oplog_lost_records", 0)
        m.inc("repl_promotions", 0)
        m.inc("repl_epoch_rebases_seen", 0)
        m.set_gauge("repl_is_standby", 1)
        m.set_gauge("repl_rx_seq", 0)
        m.set_gauge("repl_applied_seq", 0)
        m.set_gauge("repl_lag_seqs", 0)
        m.set_gauge("repl_lag_bytes", 0)
        m.set_gauge("repl_heartbeat_age_s", 0)
        self._q: queue.Queue = queue.Queue(maxsize=rx_queue)
        self._lock = threading.Lock()          # promote state transition
        self._attest_lock = threading.Lock()   # pairing stores + rx group
        self._attest_local: dict[int, list] = {}
        self._attest_primary: dict[int, list] = {}
        self._att_group: list = []             # primary records, current run
        self._att_trace = 0
        self._att_stamp = 0.0
        self._stop = threading.Event()
        self._promote_started = False
        self._promote_done = threading.Event()
        self.promoted_epoch = 0
        self.diverged = False          # attestation mismatch observed
        self.poisoned: str | None = None  # unrecoverable state (gap/rebase)
        self._last_rx = time.monotonic()
        # Auto-promotion arms only after the rx loop has received at
        # least one event from the primary: a standby that NEVER heard
        # from it (wrong --standby address, primary not yet up) must not
        # self-promote an empty replica into a second writable server.
        self._ever_rx = False
        self._rx_seq = 0
        # Seq of the newest received DISPATCH event — the lag baseline.
        # (Heartbeats arrive unsequenced, seq 0; the split from _rx_seq
        # guards any future sequenced non-dispatch kind from reading as
        # phantom lag.)
        self._rx_dispatch_seq = 0
        self._rx_bytes = 0
        self._applied_seq = 0
        self._applied_bytes = 0
        self._max_oid = 0
        self._rx_sub = None
        self._attest_sub = None
        self._rx_thread = threading.Thread(target=self._rx_loop,
                                           name="repl-rx", daemon=True)
        self._apply_thread = threading.Thread(target=self._applier_loop,
                                              name="repl-apply", daemon=True)
        self._threads = [
            self._rx_thread,
            self._apply_thread,
            threading.Thread(target=self._watcher_loop, name="repl-watch",
                             daemon=True),
        ]
        if attest:
            self._threads.append(
                threading.Thread(target=self._attestor_loop,
                                 name="repl-attest", daemon=True))
        for t in self._threads:
            t.start()

    # -- plumbing ----------------------------------------------------------

    def _stub(self) -> tuple[MatchingEngineStub, grpc.Channel]:
        """One channel per connection attempt; the CALLER owns it and
        closes it when its subscriber finishes. During an outage the
        rx/attestor retry loops reconnect ~5x/s each — an accumulating
        channel list would exhaust fds on exactly the box that must
        stay healthy to be promoted."""
        ch = grpc.insecure_channel(self.primary_addr)
        return MatchingEngineStub(ch), ch

    def _runner_for_lane(self, lane: int):
        if self.shards is None:
            return self.runners[0]
        if lane >= len(self.shards.lanes):
            return None
        return self.shards.lanes[lane].runner

    def _poison(self, why: str) -> None:
        if self.poisoned is None:
            self.poisoned = why
        warn_rate_limited("repl-poison", f"[repl] replica POISONED: {why}")

    # -- rx ----------------------------------------------------------------

    def _rx_loop(self) -> None:
        epoch = 0
        first = True
        while not self._stop.is_set():
            def on_gap(start, end, filled, missing):
                if missing:
                    self.metrics.inc("repl_oplog_lost_records", missing)
                    self._poison(
                        f"oplog seqs {start + 1}..{end - 1} unrecoverable "
                        f"({missing} lost past the primary's window)")

            def on_rebase(cursor, seq):
                # The primary restarted under us: its new op log does not
                # continue the state we hold.
                self.metrics.inc("repl_epoch_rebases_seen")
                self._poison(f"primary feed epoch rebased (cursor {cursor} "
                             f"-> seq {seq}); re-bootstrap this standby")

            stub, ch = self._stub()
            sub = SequencedSubscriber(
                stub, CHANNEL_OPLOG, from_seq=self._rx_seq,
                epoch=epoch, from_start=first, on_gap=on_gap,
                on_rebase=on_rebase)
            self._rx_sub = sub
            if self._stop.is_set():
                sub.cancel()
            try:
                for e in sub:
                    self._last_rx = time.monotonic()
                    self._ever_rx = True
                    if e.seq:
                        self._rx_seq = e.seq
                        self.metrics.set_gauge("repl_rx_seq", e.seq)
                    if e.oplog_kind == OPLOG_DISPATCH:
                        if self.poisoned is not None:
                            # A poisoned replica STOPS applying: past a
                            # hole (or a primary rebase) the log is no
                            # longer a continuation of the state we
                            # hold, and applying it anyway would serve
                            # (and durably store) a merged fantasy
                            # history — keep serving the last provably
                            # consistent state instead.
                            continue
                        self._rx_dispatch_seq = e.seq
                        self._rx_bytes += len(e.oplog_ops)
                        first = False
                        # Timed puts, refreshing liveness while blocked:
                        # a full queue means WE are behind (apply-side
                        # stall), not that the primary died — letting
                        # _last_rx freeze here would read backpressure
                        # as a heartbeat lapse and auto-promote against
                        # a live primary. Auto-promotion during a deep
                        # backlog is wrong anyway (promotion must drain
                        # it first); real heartbeats resume the moment
                        # the backlog clears. The received event is
                        # never dropped (promote's drain contract): we
                        # keep trying while the applier is alive to
                        # drain — even during the promote quiesce.
                        while True:
                            try:
                                self._q.put(e, timeout=0.2)
                                break
                            except queue.Full:
                                self._last_rx = time.monotonic()
                                if self._stop.is_set() \
                                        and not self._apply_thread.is_alive():
                                    break  # nothing left to drain it
                    # Heartbeats (and unknown kinds) only refresh liveness.
            except grpc.RpcError:
                pass  # connection loss: retried below, promotion-aware
            finally:
                ch.close()
            epoch = sub.epoch or epoch
            if not self._stop.is_set():
                time.sleep(0.2)  # primary briefly unreachable: retry

    # -- apply -------------------------------------------------------------

    def _applier_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            try:
                self._apply_dispatch(item)
            except Exception as e:  # noqa: BLE001 — one bad dispatch must
                # not kill the applier silently; it DOES poison the
                # replica (state no longer provably mirrors the log).
                self.metrics.inc("repl_apply_errors")
                self._poison(f"apply failed at oplog seq {item.seq}: "
                             f"{type(e).__name__}: {e}")

    def _apply_dispatch(self, e) -> None:
        runner = self._runner_for_lane(e.oplog_lane)
        if runner is None:
            self.metrics.inc("repl_apply_errors")
            self._poison(f"oplog lane {e.oplog_lane} has no mirror lane "
                         f"(standby --serve-shards must match the primary)")
            return
        recs = ops_from_oprec(e.oplog_ops)
        ops: list[EngineOp] = []
        skipped = 0
        for (op, side, otype, price_q4, qty, sym, cid, oid) in recs:
            if op == oprec.OPREC_SUBMIT:
                num = int(oid[4:]) if oid.startswith("OID-") else 0
                if runner.slot_acquire(sym) is None:
                    # Capacity the primary had but we lack = config skew.
                    # Abandon the WHOLE dispatch (like the no-mirror-lane
                    # case): applying the partial remainder would write
                    # knowingly-wrong rows to the store and publish them
                    # to live read clients, not just trip the attestor.
                    self.metrics.inc("repl_apply_errors")
                    self._poison(f"symbol axis full for {sym} (standby "
                                 f"config must mirror the primary)")
                    return
                info = OrderInfo(
                    oid=num, order_id=oid, client_id=cid, symbol=sym,
                    side=side, otype=otype, price_q4=price_q4, quantity=qty,
                    remaining=qty, status=0, handle=runner.assign_handle())
                if num > self._max_oid:
                    self._max_oid = num
                ops.append(EngineOp(OP_SUBMIT, info))
            else:
                info = runner.orders_by_id.get(oid)
                if info is None:
                    # The primary dispatched against a stale directory
                    # entry that we already evicted in an earlier applied
                    # dispatch — its host reject produced no rows, and
                    # neither do we by skipping.
                    skipped += 1
                    continue
                ops.append(EngineOp(OP_CANCEL, info, cancel_requester=cid)
                           if op == oprec.OPREC_CANCEL
                           else EngineOp(OP_AMEND, info, amend_qty=qty))
        result = runner.run_dispatch(ops) if ops else None
        rows = ((), (), ())
        if result is not None:
            # Snapshot BEFORE the sink sees the lists (its coalescing
            # thread extends them in place — the drop-copy rule).
            rows = (tuple(result.storage_orders),
                    tuple(result.storage_updates),
                    tuple(result.storage_fills))
            dropcopy = getattr(runner, "dropcopy", None)
            if dropcopy is not None:
                dropcopy.publish(result)
            publish_result(result, self.sink, self.hub, self.metrics)
            self.metrics.inc("repl_applied_ops", len(ops))
        if self.attest:
            local = self.fault.apply(normalize_rows(*rows))
            if local or skipped:
                # Park even an EMPTY local group when any op was
                # skipped: a skip is the one case where our rows can
                # legitimately differ from the primary's, so a primary
                # that DID produce rows for this dispatch must pair
                # against our emptiness and report the divergence —
                # not age out as "unmatched" with /replz green. (When
                # the primary's reject also produced no rows, our empty
                # group ages out as repl_attest_unmatched — documented
                # as not-proof-of-divergence.)
                self._pair(e.trace_id, local, primary_side=False)
            else:
                # Nothing skipped and no rows on either side by
                # determinism (a row-less dispatch emits no drop-copy
                # records, so there is no primary group to pair with).
                self.metrics.inc("repl_attested_dispatches")
        self.metrics.inc("repl_applied_dispatches")
        self._applied_seq = max(self._applied_seq, e.seq)
        self._applied_bytes += len(e.oplog_ops)
        m = self.metrics
        m.set_gauge("repl_applied_seq", self._applied_seq)
        m.set_gauge("repl_lag_seqs",
                    max(0, self._rx_dispatch_seq - self._applied_seq))
        m.set_gauge("repl_lag_bytes",
                    max(0, self._rx_bytes - self._applied_bytes))

    # -- attest ------------------------------------------------------------

    # A dispatch's audit records arrive in one burst; a group idle this
    # long is complete (the watcher flushes it so the LAST dispatch
    # before an idle lull still attests — detection "within one
    # dispatch" even with nothing following it).
    _GROUP_IDLE_S = 1.0

    def _attestor_loop(self) -> None:
        # from_start on first attach, like the rx loop: the applier
        # full-replays the op log from the epoch start, so the audit
        # subscription must replay the same range — attaching live-only
        # would leave the whole replayed prefix unattested while its
        # local groups churn the pairing store as "unmatched".
        from_seq, epoch = 0, 0
        while not self._stop.is_set():
            stub, ch = self._stub()
            # from_start whenever the cursor is 0, not just on the first
            # attach: a reconnect after discarding an all-of-it tail
            # group rewinds from_seq to 0, and without the from-start
            # grant the re-fetch the tail-regroup below promises would
            # silently attach live-only (an unattested coverage hole).
            sub = SequencedSubscriber(stub, CHANNEL_AUDIT,
                                      from_seq=from_seq, epoch=epoch,
                                      from_start=from_seq == 0)
            self._attest_sub = sub
            if self._stop.is_set():
                sub.cancel()
            lost = 0
            skip_trace = None
            try:
                for e in sub:
                    if sub.unrecovered_events > lost:
                        # Audit records evicted past the primary's window:
                        # the dispatch group straddling the hole is
                        # truncated on BOTH of its edges — comparing either
                        # part would report a healthy replica as diverged.
                        # Discard what was built and skip the rest of the
                        # hole-adjacent trace; its local counterpart ages
                        # out as repl_attest_unmatched.
                        lost = sub.unrecovered_events
                        skip_trace = e.trace_id
                        with self._attest_lock:
                            if self._att_group:
                                self._att_group = []
                                self.metrics.inc("repl_attest_unmatched")
                    if e.audit_kind == 0:
                        continue
                    if skip_trace is not None:
                        if e.trace_id == skip_trace:
                            continue
                        skip_trace = None
                    with self._attest_lock:
                        if self._att_group and e.trace_id != self._att_trace:
                            trace, group = self._att_trace, self._att_group
                            self._att_group = []
                        else:
                            trace = group = None
                        self._att_trace = e.trace_id
                        self._att_group.append(normalize_audit_event(e))
                        self._att_stamp = time.monotonic()
                    if group:
                        self._pair(trace, group, primary_side=True)
            except grpc.RpcError:
                pass
            finally:
                ch.close()
            # A truncated tail group is re-fetched on reconnect rather
            # than compared half-received (records of one dispatch arrive
            # in one burst; a mid-burst cut would compare a partial
            # primary side against a full local one).
            with self._attest_lock:
                tail = len(self._att_group)
                self._att_group = []
            from_seq = max(from_seq, sub.last_seq - tail)
            epoch = sub.epoch or epoch
            if not self._stop.is_set():
                time.sleep(0.2)

    def _flush_idle_group(self) -> None:
        """Watcher-cadence flush of a complete-but-unfollowed audit
        group (see _GROUP_IDLE_S)."""
        sub = self._attest_sub
        if sub is not None and getattr(sub, "filling", False):
            # A gap-fill is in flight: the group may be truncated
            # MID-dispatch (the missing records are being refetched
            # right now) — flushing it would pair a partial primary
            # side against the full local rows and latch a false
            # permanent divergence out of a transient feed hiccup.
            return
        with self._attest_lock:
            if not self._att_group or \
                    time.monotonic() - self._att_stamp < self._GROUP_IDLE_S:
                return
            trace, group = self._att_trace, self._att_group
            self._att_group = []
        self._pair(trace, group, primary_side=True)

    def _pair(self, trace_id: int, rows: list, primary_side: bool) -> None:
        """Meet-in-the-middle pairing by primary dispatch trace id: park
        under the attest lock, compare outside it."""
        if not trace_id:
            self.metrics.inc("repl_attest_unmatched")
            return
        mine, theirs = ((self._attest_primary, self._attest_local)
                        if primary_side
                        else (self._attest_local, self._attest_primary))
        with self._attest_lock:
            other = theirs.pop(trace_id, None)
            if other is None:
                mine[trace_id] = rows
                while len(mine) > self._ATTEST_PENDING_MAX:
                    mine.pop(next(iter(mine)), None)
                    self.metrics.inc("repl_attest_unmatched")
                return
        local, primary = (other, rows) if primary_side else (rows, other)
        self._compare(trace_id, local, primary)

    def _compare(self, trace_id: int, local: list, primary: list) -> None:
        if local == primary:
            self.metrics.inc("repl_attested_dispatches")
            return
        self.diverged = True
        self.metrics.inc("repl_divergences")
        detail = (f"dispatch trace={trace_id}: standby rows != primary "
                  f"drop-copy ({len(local)} vs {len(primary)} records)")
        entry = {"kind": "repl_divergence", "detail": detail,
                 "trace_id": trace_id, "wall_ts": time.time(),
                 "local": [list(r) for r in local[:16]],
                 "primary": [list(r) for r in primary[:16]]}
        recorder = getattr(self.metrics, "recorder", None)
        if recorder is not None:
            recorder.record(entry)
            recorder.dump_on_error()
        warn_rate_limited("repl-diverge",
                          f"[repl] ATTESTATION DIVERGENCE: {detail}")

    # -- watcher / heartbeat ------------------------------------------------

    def _watcher_loop(self) -> None:
        while not self._stop.wait(0.2):
            age = time.monotonic() - self._last_rx
            self.metrics.set_gauge("repl_heartbeat_age_s", age)
            if self.attest:
                self._flush_idle_group()
            if (self.auto_promote_s > 0 and age > self.auto_promote_s
                    and not self._promote_started):
                if not self._ever_rx:
                    warn_rate_limited(
                        "repl-no-auto-promote",
                        f"[repl] heartbeat lapsed ({age:.2f}s) but this "
                        f"standby NEVER received anything from "
                        f"{self.primary_addr}: refusing auto-promotion "
                        f"(check the --standby address; promoting an "
                        f"empty replica while the real primary serves "
                        f"would split-brain)")
                    continue
                if self.poisoned is not None or self.diverged:
                    # A replica with a known hole (unrecoverable gap,
                    # primary rebase) or an attestation mismatch must
                    # never SELF-promote into the serving primary; the
                    # operator can still force it with the explicit
                    # Promote RPC, eyes open on a red /replz.
                    warn_rate_limited(
                        "repl-no-auto-promote",
                        f"[repl] heartbeat lapsed ({age:.2f}s) but "
                        f"auto-promotion refused: "
                        f"{self.poisoned or 'attestation divergence'}")
                    continue
                print(f"[repl] primary heartbeat lapsed "
                      f"({age:.2f}s > {self.auto_promote_s:.2f}s): "
                      f"auto-promoting")
                self.promote("heartbeat-lapse")

    # -- promotion ----------------------------------------------------------

    def promote(self, reason: str) -> int:
        """Standby -> primary. Idempotent; concurrent callers wait for
        the one transition. Returns the post-promotion feed epoch, or 0
        when the transition is still in flight after the wait budget —
        callers (service.Promote) must treat 0 as NOT promoted, never as
        success (the winner's quiesce joins can take minutes when rx is
        wedged in a dead gRPC read)."""
        with self._lock:
            started, self._promote_started = self._promote_started, True
        if started:
            if not self._promote_done.wait(timeout=300):
                return 0
            return self.promoted_epoch
        t0 = time.perf_counter()
        # 1. Quiesce intake: stop rx, drain every event already received
        #    (zero loss of received log), stop the attestor. The rx
        #    thread is joined BEFORE the stop sentinel is enqueued — rx
        #    may hold a received-but-unqueued event, and a sentinel
        #    racing ahead of its put() would strand that event behind
        #    _STOP forever (the applier is still draining, so the
        #    sentinel put cannot deadlock on a full queue).
        self._stop.set()
        for sub in (self._rx_sub, self._attest_sub):
            if sub is not None:
                sub.cancel()
        if self._rx_thread is not threading.current_thread():
            self._rx_thread.join(timeout=30)
        # The sentinel put must not block forever: the queue can be
        # FULL when an applier wedge is exactly what backed it up —
        # blocking here would leave the Promote RPC hung and, with
        # _promote_started latched, the standby permanently
        # unpromotable. Timed puts with the same progress test as the
        # join below: wait while the applier drains, abort if wedged.
        last_applied = -1
        while True:
            try:
                self._q.put(_STOP, timeout=30)
                break
            except queue.Full:
                if self._applied_seq == last_applied:
                    self._poison("promotion aborted: applier wedged at "
                                 f"oplog seq {self._applied_seq} with a "
                                 "full rx queue")
                    with self._lock:
                        self._promote_started = False
                    return 0
                last_applied = self._applied_seq
        # The applier's remaining work is bounded (rx is joined, the
        # queue is bounded) but can legitimately outlast any fixed
        # budget on a slow box with a full backlog — and opening the
        # mutation RPCs before it drains would interleave fresh submits
        # with old log events (stale OID floor, a history that is no
        # longer a prefix of the primary's). Wait while it makes
        # progress; abort the promotion only when it is wedged.
        last_applied = -1
        while self._apply_thread.is_alive():
            self._apply_thread.join(timeout=30)
            if not self._apply_thread.is_alive():
                break
            if self._applied_seq == last_applied:
                self._poison("promotion aborted: applier wedged at "
                             f"oplog seq {self._applied_seq}")
                with self._lock:
                    self._promote_started = False
                return 0
            last_applied = self._applied_seq
        for t in self._threads:
            if t is not threading.current_thread() \
                    and t not in (self._rx_thread, self._apply_thread):
                t.join(timeout=30)
        # 2. Decode anything still staged, flush the durable log.
        for r in self.runners:
            r.finish_pending()
        flush = getattr(self.sink, "flush", None)
        if flush is not None:
            flush()
        # 3. Re-seed the per-residue-class OID floors: every future id
        #    must clear both the durable store's max and the applied
        #    log's max (the sink tail could still be in flight).
        next_oid = max(self.storage.load_next_oid_seq(), self._max_oid + 1)
        for r in self.runners:
            r.seed_oid_sequence(next_oid)
        # 4. New feed epoch (old line's spill purged): clients rebase.
        if self.sequencer is not None:
            self.promoted_epoch = self.sequencer.rebase_epoch()
        # 5. Open the mutation RPCs.
        self.service.read_only = False
        self.metrics.inc("repl_promotions")
        self.metrics.set_gauge("repl_is_standby", 0)
        dt_ms = (time.perf_counter() - t0) * 1e3
        print(f"[repl] PROMOTED ({reason}) in {dt_ms:.1f}ms: "
              f"feed_epoch={self.promoted_epoch} next_oid={next_oid} "
              f"applied_seq={self._applied_seq}")
        self._promote_done.set()
        return self.promoted_epoch

    # -- reporting (/replz) --------------------------------------------------

    def snapshot(self) -> dict:
        c, g = self.metrics.snapshot()
        ok = not self.diverged and self.poisoned is None
        # "promoted" means the transition COMPLETED (mutations open) —
        # service.Promote tells operators to poll /replz for the
        # verdict, and the quiesce window can take minutes; reporting
        # the started flag here would call a still-read-only server
        # promoted.
        promoted = self._promote_done.is_set()
        return {
            "role": "primary (promoted)" if promoted
            else "standby (promoting)" if self._promote_started
            else "standby",
            "ok": ok,
            "primary": self.primary_addr,
            "promoted": promoted,
            "feed_epoch": self.promoted_epoch or (
                self.sequencer.epoch if self.sequencer else 0),
            "rx_seq": self._rx_seq,
            "applied_seq": self._applied_seq,
            "lag_seqs": max(0, self._rx_dispatch_seq - self._applied_seq),
            "lag_bytes": max(0, self._rx_bytes - self._applied_bytes),
            "applied_dispatches": c.get("repl_applied_dispatches", 0),
            "applied_ops": c.get("repl_applied_ops", 0),
            "apply_errors": c.get("repl_apply_errors", 0),
            "attested": c.get("repl_attested_dispatches", 0),
            "divergences": c.get("repl_divergences", 0),
            "oplog_lost_records": c.get("repl_oplog_lost_records", 0),
            "heartbeat_age_s": round(g.get("repl_heartbeat_age_s", 0.0), 3),
            "promotions": c.get("repl_promotions", 0),
            "diverged": self.diverged,
            "poisoned": self.poisoned,
        }

    def close(self) -> None:
        self._stop.set()
        for sub in (self._rx_sub, self._attest_sub):
            if sub is not None:
                sub.cancel()
        # Same rx-first join order as promote(): no event may land
        # behind the stop sentinel. (Each loop closes its own channel.)
        if self._rx_thread is not threading.current_thread():
            self._rx_thread.join(timeout=10)
        try:
            self._q.put_nowait(_STOP)
        except queue.Full:
            pass  # shutdown only: the daemon applier dies with us
        for t in self._threads:
            if t is not threading.current_thread() \
                    and t is not self._rx_thread:
                t.join(timeout=10)
