"""Op-log shipping: the primary's side of warm-standby replication.

Every dispatch the serving drain loops admit is re-encoded as ONE
sequenced `oplog` event carrying the dispatch's ops in the flat binary
op-record codec (domain/oprec.py — already the language-neutral replay
unit) plus the envelope a standby needs:

- submits ship WITH their primary-assigned "OID-<n>" in the record's
  order_id box: order-id assignment happens at the RPC edge in arrival
  order, which can differ from dispatch order under concurrent handlers,
  so a replica re-assigning ids in dispatch order would diverge — the
  log is authoritative for identity, the replica's engine only for
  consequences;
- the event's `trace_id` is the PRIMARY dispatch's trace id — the same
  id every drop-copy audit record of that dispatch carries, which is
  what lets the standby's attestor pair "what I produced" with "what
  the primary produced" exactly, even when K lanes interleave;
- `oplog_lane` names the serving lane so a sharded standby routes the
  whole dispatch to its mirror lane.

Shipping rides the drain loop's on_finish (under the dispatch lock,
next to the drop-copy publish): the proto and payload are built BEFORE
`StreamHub.publish_oplog` so nothing materializes under the hub lock,
and the ship strictly precedes the dispatch's client completions — an
acked op is always already in the retransmission store. Heartbeats
publish from a dedicated shipper thread so an idle primary still proves
liveness (the standby's heartbeat-lapse trigger).
"""

from __future__ import annotations

import threading

from matching_engine_tpu.domain import oprec
from matching_engine_tpu.engine.kernel import OP_AMEND, OP_CANCEL, OP_SUBMIT
from matching_engine_tpu.feed.sequencer import (  # noqa: F401 — re-export
    OPLOG_DISPATCH,
    OPLOG_HEARTBEAT,
)
from matching_engine_tpu.proto import pb2
from matching_engine_tpu.utils.obs import warn_rate_limited

# Reserved StreamOrderUpdates client_id that subscribes the caller to the
# sequenced op-log channel (the audit channel's AUDIT_CLIENT pattern).
OPLOG_CLIENT = "__oplog__"


def ops_to_oprec(ops) -> tuple[bytes, int]:
    """One dispatch's EngineOps -> (oprec payload, count).

    The record is the engine-facing tuple the batch edge already speaks;
    the one replication-specific convention is that SUBMIT records carry
    the assigned order id (the batch edge leaves it empty — ids are
    assigned server-side there, log-side here)."""
    rows = []
    for e in ops:
        i = e.info
        if e.op == OP_SUBMIT:
            rows.append((oprec.OPREC_SUBMIT, i.side, i.otype, i.price_q4,
                         i.quantity, i.symbol, i.client_id, i.order_id))
        elif e.op == OP_CANCEL:
            rows.append((oprec.OPREC_CANCEL, 0, 0, 0, 0, "",
                         e.cancel_requester, i.order_id))
        elif e.op == OP_AMEND:
            rows.append((oprec.OPREC_AMEND, 0, 0, 0, e.amend_qty, "",
                         i.client_id, i.order_id))
        # OP_REST never ships: it exists only on boot-recovery replays,
        # which run before any dispatcher (and before the shipper) exists.
    return oprec.encode_payload(oprec.pack_records(rows)), len(rows)


def ops_from_oprec(payload: bytes):
    """Op-log payload -> [(op, side, otype, price_q4, qty, symbol,
    client_id, order_id) ...] with str identifiers — the standby
    applier's input (identifiers were validated UTF-8 at the primary's
    edge, so decode errors here are transport corruption and raise)."""
    arr = oprec.decode_payload(payload)
    out = []
    for r in arr:
        op, side, otype, price_q4, qty, sym, cid, oid = oprec.record_fields(r)
        out.append((op, side, otype, price_q4, qty, sym.decode(),
                    cid.decode(), oid.decode()))
    return out


class OpLogShipper:
    """Per-server op-log publisher. `ship()` is called by each lane's
    drain loop on_finish (under that lane's dispatch lock); the heartbeat
    loop is this subsystem's own thread. One shipper serves every lane —
    the hub lock already serializes cross-lane stamping."""

    def __init__(self, hub, metrics, heartbeat_s: float = 0.25):
        self.hub = hub
        self.metrics = metrics
        self.heartbeat_s = heartbeat_s
        # Pre-register the exported series (zeros, not absence).
        metrics.inc("repl_oplog_dispatches", 0)
        metrics.inc("repl_oplog_records", 0)
        metrics.inc("repl_oplog_bytes", 0)
        metrics.set_gauge("repl_oplog_head_seq", 0)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._heartbeat_loop,
                                        name="oplog-shipper", daemon=True)
        self._thread.start()

    def ship(self, ops, timeline=None, lane: int = 0) -> None:
        """Publish one admitted dispatch's ops. Build-then-publish: the
        proto/payload work happens on the calling drain thread OUTSIDE
        the hub lock; publish_oplog stamps + fans out inside it."""
        if not ops:
            return
        payload, n = ops_to_oprec(ops)
        if n == 0:
            return
        ev = pb2.OrderUpdate(
            oplog_kind=OPLOG_DISPATCH, oplog_ops=payload, oplog_count=n,
            oplog_lane=lane,
            trace_id=timeline.trace_id if timeline is not None else 0)
        self.hub.publish_oplog([ev])
        self.metrics.inc("repl_oplog_dispatches")
        self.metrics.inc("repl_oplog_records", n)
        self.metrics.inc("repl_oplog_bytes", len(payload))

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.hub.publish_oplog([pb2.OrderUpdate(
                    oplog_kind=OPLOG_HEARTBEAT)])
            except Exception as e:  # noqa: BLE001 — a dead heartbeat
                # thread reads as primary loss downstream (phantom
                # auto-promotions); log and keep beating.
                warn_rate_limited(
                    "oplog-heartbeat",
                    f"[repl] heartbeat publish failed: "
                    f"{type(e).__name__}: {e}")

    def snapshot(self) -> dict:
        c, g = self.metrics.snapshot()
        return {
            "role": "primary", "ok": True,
            "oplog_dispatches": c.get("repl_oplog_dispatches", 0),
            "oplog_records": c.get("repl_oplog_records", 0),
            "oplog_bytes": c.get("repl_oplog_bytes", 0),
            "oplog_head_seq": int(g.get("repl_oplog_head_seq", 0)),
        }

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
