"""Store bit-identity verifier for the kill-the-primary contract.

Replication is asynchronous and BOTH durable stores are async-sink cuts
of the same totally-ordered dispatch sequence (the primary's SQLite sink
is a dispatch-order prefix; the replica's applied log is another), so
after a SIGKILL the two databases are cuts A = dispatches 1..M and
B = 1..N of one deterministic history. The checkable contract is exactly
prefix-consistency:

- every order present in BOTH stores must be byte-identical on the
  immutable columns (client_id, symbol, side, order_type, price,
  quantity, tif) — ANY difference there is corruption;
- the mutable columns (status, remaining_quantity) must be equal or
  strictly advanced on ONE consistent side — order X ahead in A while
  order Y is ahead in B cannot happen on two cuts of one history;
- orders present in only one store must all be on the AHEAD side (the
  tail the other cut hasn't reached);
- for identical order rows, the fill multisets must be identical; for
  advanced rows, the behind side's fills must be a sub-multiset of the
  ahead side's.

Wall-clock columns (created_ts/updated_ts, fills.ts) are the DECLARED
nondeterministic surface (analysis/hierarchy.DETERMINISM_WAIVERS) and
are excluded.

Library use: `compare_stores(db_a, db_b)` -> report dict with
`identical_prefix` (bool) and the offending rows. CLI use (the soak's
kill round): `python -m matching_engine_tpu.replication.verify A.db
B.db` — exit 0 on prefix identity, 1 with a printed report otherwise.
"""

from __future__ import annotations

import sqlite3
import sys
from collections import Counter

# status ranks for the legal-advance check: NEW(0) -> PARTIAL(1) ->
# terminal {FILLED(2), CANCELED(3), REJECTED(4)}.
_RANK = {0: 0, 1: 1, 2: 2, 3: 2, 4: 2}


def _orders(db: str) -> dict[str, tuple]:
    con = sqlite3.connect(f"file:{db}?mode=ro", uri=True)
    try:
        rows = con.execute(
            "SELECT order_id, client_id, symbol, side, order_type, price, "
            "quantity, remaining_quantity, status, tif FROM orders"
        ).fetchall()
    finally:
        con.close()
    return {r[0]: r[1:] for r in rows}


def _fills(db: str) -> dict[str, Counter]:
    con = sqlite3.connect(f"file:{db}?mode=ro", uri=True)
    try:
        rows = con.execute(
            "SELECT order_id, counter_order_id, price, quantity FROM fills"
        ).fetchall()
    finally:
        con.close()
    out: dict[str, Counter] = {}
    for oid, ctr, price, qty in rows:
        out.setdefault(oid, Counter())[(ctr, price, qty)] += 1
    return out


def _advanced(behind: tuple, ahead: tuple) -> bool:
    """True when `ahead` is a legal later state of the same order row:
    identical immutable columns, remaining non-increasing, status rank
    non-decreasing (and actually different)."""
    if behind[:6] != ahead[:6] or behind[8] != ahead[8]:  # immutables + tif
        return False
    rem_b, st_b = behind[6], behind[7]
    rem_a, st_a = ahead[6], ahead[7]
    if (rem_b, st_b) == (rem_a, st_a):
        return False
    if _RANK.get(st_b, 2) >= 2:
        # Terminal statuses are absorbing: once a cut recorded
        # FILLED/CANCELED/REJECTED, no later cut of the SAME history can
        # hold anything else for that order — a terminal-to-terminal
        # flip (CANCELED here, FILLED there) is divergence, never an
        # async-cut artifact.
        return False
    return rem_a <= rem_b and _RANK.get(st_a, 2) >= _RANK.get(st_b, 2)


def compare_stores(db_a: str, db_b: str, allow_fork: bool = False) -> dict:
    """Prefix-consistency verdict over two cuts of one deterministic
    history. allow_fork=True is the POST-PROMOTION contract: the dead
    primary may hold a durable tail that never shipped (only_a /
    a_ahead) while the promoted replica accepted fresh flow (only_b,
    and fresh fills advancing common resting orders = b_ahead), so the
    two stores legally fork at the promotion point — only disagreement
    on COMMON rows (mismatched, conflicting fills) is divergence.
    Without it (two cuts of ONE line) a simultaneous two-sided
    advance/exclusive is itself corruption and fails."""
    a_orders, b_orders = _orders(db_a), _orders(db_b)
    a_fills, b_fills = _fills(db_a), _fills(db_b)
    mismatched: list[str] = []      # corruption: neither equal nor advanced
    a_ahead: list[str] = []
    b_ahead: list[str] = []
    fill_mismatch: list[str] = []
    equal = 0
    for oid, ra in a_orders.items():
        rb = b_orders.get(oid)
        if rb is None:
            continue
        fa = a_fills.get(oid, Counter())
        fb = b_fills.get(oid, Counter())
        if ra == rb:
            equal += 1
            if fa != fb:
                # Same row state, different executions — but an async cut
                # can land BETWEEN a fill insert and its status update
                # only per dispatch, and both ride one sink batch; still,
                # tolerate the subset direction and flag true conflicts.
                if not (fa <= fb or fb <= fa):
                    fill_mismatch.append(oid)
                elif fa < fb:
                    b_ahead.append(oid)
                else:
                    a_ahead.append(oid)
        elif _advanced(ra, rb):
            b_ahead.append(oid)
            if not fa <= fb:
                fill_mismatch.append(oid)
        elif _advanced(rb, ra):
            a_ahead.append(oid)
            if not fb <= fa:
                fill_mismatch.append(oid)
        else:
            mismatched.append(oid)
    only_a = sorted(set(a_orders) - set(b_orders))
    only_b = sorted(set(b_orders) - set(a_orders))
    # Direction consistency: at most one side may be ahead anywhere, and
    # only-in-X orders are legal only when X is the (weakly) ahead side.
    mixed = bool(a_ahead) and bool(b_ahead)
    tail_ok = not (only_a and (b_ahead or (only_b and not a_ahead))) \
        and not (only_b and (a_ahead or (only_a and not b_ahead)))
    ok = not mismatched and not fill_mismatch \
        and (allow_fork or (not mixed and tail_ok))
    return {
        "identical_prefix": ok,
        "orders_a": len(a_orders), "orders_b": len(b_orders),
        "common": equal + len(a_ahead) + len(b_ahead) + len(mismatched),
        "equal": equal,
        "a_ahead": len(a_ahead), "b_ahead": len(b_ahead),
        "only_a": len(only_a), "only_b": len(only_b),
        "mixed_direction": mixed,
        "mismatched_orders": mismatched[:20],
        "fill_mismatches": fill_mismatch[:20],
    }


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    allow_fork = "--promoted" in argv
    argv = [a for a in argv if a != "--promoted"]
    if len(argv) != 2:
        print("usage: python -m matching_engine_tpu.replication.verify "
              "[--promoted] <primary.db> <replica.db>", file=sys.stderr)
        return 2
    rep = compare_stores(argv[0], argv[1], allow_fork=allow_fork)
    import json

    print(json.dumps(rep, indent=2, sort_keys=True))
    return 0 if rep["identical_prefix"] else 1


if __name__ == "__main__":
    sys.exit(main())
