"""Process-local views of globally-sharded arrays (the multi-host enabler).

On a multi-process mesh, `np.asarray(global_array)` raises for any array
with non-addressable shards — a single-controller read of the whole value
does not exist. Everything the serving stack reads back from the device
(fill segments, per-op results, top-of-book, book rows for snapshots and
checkpoints) must instead be assembled from THIS process's addressable
shards, and everything it feeds in (order batches, restored books) must be
constructed per-process with `jax.make_array_from_callback`.

These helpers are the single implementation of that discipline, used by
ShardedEngine.decode, EngineRunner's snapshot/market-data paths, and
utils/checkpoint.py. They are exact no-op-equivalents on a single process
(every shard is addressable, the local block is the whole array), so one
code path serves dev, CI's virtual 8-device CPU mesh, and a real multi-host
deployment. VERDICT r2 weak #3 is this module's reason to exist.
"""

from __future__ import annotations

import numpy as np


def _start(shard) -> int:
    sl = shard.index[0] if shard.index else slice(None)
    return sl.start or 0


def local_block(x) -> tuple[np.ndarray, int, int]:
    """The contiguous axis-0 block of `x` addressable by this process.

    Returns (data, lo, hi) with data == x[lo:hi] as a host array. Requires
    the process's shards to tile a contiguous global range — which the
    host-major meshes from make_multihost_mesh guarantee.
    """
    shards = sorted(x.addressable_shards, key=_start)
    if not shards:
        return np.empty((0,) + x.shape[1:], dtype=x.dtype), 0, 0
    lo = _start(shards[0])
    parts = []
    expect = lo
    for s in shards:
        st = _start(s)
        if st < expect:
            continue  # replicated shard (same block on several devices)
        if st != expect:
            raise ValueError(
                "process-addressable shards are not axis-0 contiguous; "
                "build the mesh with make_multihost_mesh()"
            )
        d = np.asarray(s.data)
        parts.append(d)
        expect = st + d.shape[0]
    return np.concatenate(parts, axis=0), lo, expect


def local_rows(x, lo: int, hi: int) -> np.ndarray:
    """Rows [lo, hi) of axis-0-sharded `x`, served from addressable shards."""
    data, blo, bhi = local_block(x)
    if lo < blo or hi > bhi:
        raise IndexError(
            f"rows [{lo}, {hi}) outside this process's block [{blo}, {bhi})"
        )
    return data[lo - blo:hi - blo]


def read_row(x, row: int) -> np.ndarray:
    """One axis-0 row of `x`, touching only the shard that holds it."""
    for s in x.addressable_shards:
        sl = s.index[0] if s.index else slice(None)
        st = sl.start or 0
        sp = sl.stop if sl.stop is not None else st + s.data.shape[0]
        if st <= row < sp:
            return np.asarray(s.data[row - st])
    raise IndexError(f"row {row} is not addressable by this process")


def put_tree(tree, sharding_tree):
    """Place a host pytree onto (possibly multi-process) shardings.

    THE placement discipline for the whole stack (books, order batches,
    restores): single-process takes the plain device_put fast path;
    multi-process builds each global array from the local index ranges of
    this host's full-shape value via make_global.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(tree, sharding_tree)
    return jax.tree.map(
        lambda arr, sh: make_global(arr, sh), tree, sharding_tree
    )


def make_global(host_full: np.ndarray, sharding):
    """A (possibly multi-process) global array from a full-shape host array.

    Each process supplies the same global SHAPE; only the locally-sharded
    index ranges of `host_full` are read, so remote ranges may be padding
    (the order-batch case: every host fills only its own symbol rows).
    """
    import jax

    host_full = np.asarray(host_full)
    return jax.make_array_from_callback(
        host_full.shape, sharding, lambda idx: host_full[idx]
    )
