"""Symbol-sharded engine step: shard_map over a device mesh.

Design (SURVEY.md §5.7-5.8): the symbol axis is this domain's scaling axis —
books are independent per symbol, so the natural mesh layout shards every
[S, ...] array on axis 0 over a 1-D mesh axis ``"sym"``. Each chip runs the
*same* jit'd match step (engine/kernel.py:engine_step_impl) on its local
symbol slice; no collective is needed inside the match itself (books never
interact), which is exactly why this maps perfectly onto SPMD. Collectives
only appear at the edges:

- fill logs and top-of-book stay device-sharded; the host reads per-shard
  segments directly (one transfer per array, already compacted per shard),
- ``all_top_of_book`` demonstrates the ICI publication path: an
  ``all_gather`` over the mesh axis so *every* chip holds the full market
  picture (what a cross-symbol risk check or market-data fanout would read).

The reference's analogous layer simply does not exist — its only
"communication backend" is client-facing gRPC (SURVEY.md §5.8); there is no
server-to-server plane to port, so this module is designed TPU-first from
the north star rather than translated.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
    init_book,
)
from matching_engine_tpu.engine.harness import (
    HostFill,
    HostResult,
    decode_fills,
    decode_results,
)
from matching_engine_tpu.engine.kernel import engine_step_impl
from matching_engine_tpu.parallel import hostlocal

AXIS = "sym"


class ShardedStepOutput(NamedTuple):
    """Per-step results with fill logs kept per-shard.

    Identical to engine.book.StepOutput except the fill log is the
    concatenation of each shard's compacted buffer: fill arrays are
    [n_shards * max_fills], and fill_count / fill_overflow are [n_shards]
    (shard i's valid rows are [i * max_fills, i * max_fills + count[i])).
    fill_sym is already globalized (local slot + shard offset).
    """

    status: jax.Array
    filled: jax.Array
    remaining: jax.Array
    fill_sym: jax.Array
    fill_taker_oid: jax.Array
    fill_maker_oid: jax.Array
    fill_price: jax.Array
    fill_qty: jax.Array
    fill_count: jax.Array
    fill_overflow: jax.Array
    best_bid: jax.Array
    bid_size: jax.Array
    best_ask: jax.Array
    ask_size: jax.Array


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the symbol axis. Defaults to every visible device."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested {n_devices} devices, only {len(devices)} visible"
                )
            devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices).reshape(-1), (AXIS,))


def _book_specs() -> BookBatch:
    lane = P(AXIS, None)
    return BookBatch(
        bid_price=lane, bid_qty=lane, bid_oid=lane, bid_seq=lane,
        ask_price=lane, ask_qty=lane, ask_oid=lane, ask_seq=lane,
        next_seq=P(AXIS),
    )


def _order_specs() -> OrderBatch:
    lane = P(AXIS, None)
    return OrderBatch(op=lane, side=lane, otype=lane, price=lane, qty=lane, oid=lane)


def _out_specs() -> ShardedStepOutput:
    return ShardedStepOutput(
        status=P(AXIS, None), filled=P(AXIS, None), remaining=P(AXIS, None),
        fill_sym=P(AXIS), fill_taker_oid=P(AXIS), fill_maker_oid=P(AXIS),
        fill_price=P(AXIS), fill_qty=P(AXIS),
        fill_count=P(AXIS), fill_overflow=P(AXIS),
        best_bid=P(AXIS), bid_size=P(AXIS), best_ask=P(AXIS), ask_size=P(AXIS),
    )


class ShardedEngine:
    """Owns the sharded step function + sharded book placement for one mesh.

    Usage:
        eng = ShardedEngine(cfg, mesh)
        book = eng.init_book()                 # device-sharded
        book, out = eng.step(book, orders)     # donated, stays sharded
        results, fills, overflow = eng.decode(orders, out)
    """

    def __init__(self, cfg: EngineConfig, mesh: Mesh):
        n = mesh.devices.size
        if cfg.num_symbols % n != 0:
            raise ValueError(
                f"num_symbols={cfg.num_symbols} not divisible by mesh size {n}"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.n_shards = n
        self.local_cfg = dataclasses.replace(cfg, num_symbols=cfg.num_symbols // n)
        self.book_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), _book_specs()
        )
        self.order_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), _order_specs()
        )

        local_cfg = self.local_cfg
        local_s = local_cfg.num_symbols

        def local_step(book: BookBatch, orders: OrderBatch):
            new_book, out = engine_step_impl(local_cfg, book, orders)
            # Globalize fill symbol slots: local index + this shard's offset.
            off = jax.lax.axis_index(AXIS).astype(I32) * local_s
            fill_sym = jnp.where(out.fill_qty > 0, out.fill_sym + off, 0)
            return new_book, ShardedStepOutput(
                status=out.status, filled=out.filled, remaining=out.remaining,
                fill_sym=fill_sym,
                fill_taker_oid=out.fill_taker_oid,
                fill_maker_oid=out.fill_maker_oid,
                fill_price=out.fill_price, fill_qty=out.fill_qty,
                fill_count=out.fill_count.reshape(1),
                fill_overflow=out.fill_overflow.reshape(1),
                best_bid=out.best_bid, bid_size=out.bid_size,
                best_ask=out.best_ask, ask_size=out.ask_size,
            )

        mapped = jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_book_specs(), _order_specs()),
            out_specs=(_book_specs(), _out_specs()),
        )
        self.step = jax.jit(mapped, donate_argnums=0)

        def gather_tob(bb, bs, ba, as_):
            f = jax.shard_map(
                lambda *xs: tuple(
                    jax.lax.all_gather(x, AXIS, tiled=True) for x in xs
                ),
                mesh=mesh,
                in_specs=(P(AXIS),) * 4,
                out_specs=(P(),) * 4,
                # all_gather output is identical on every shard by
                # construction; VMA inference can't see that through the
                # tiled gather, so assert it manually.
                check_vma=False,
            )
            return f(bb, bs, ba, as_)

        # ICI publication path: every chip ends up with the full [S] arrays.
        self.all_top_of_book = jax.jit(gather_tob)

    def init_book(self) -> BookBatch:
        return hostlocal.put_tree(init_book(self.cfg), self.book_sharding)

    def place_orders(self, orders: OrderBatch) -> OrderBatch:
        # Hot path (once per dispatch). Multi-process: each host contributes
        # its addressable symbol rows (remote rows are OP_NOOP padding in
        # this host's batch — the real ops come from their home host).
        return hostlocal.put_tree(orders, self.order_sharding)

    def decode(
        self, batch: OrderBatch, out: ShardedStepOutput
    ) -> tuple[list[HostResult], list[HostFill], bool]:
        """Decode per-order results + per-shard fill segments — reading ONLY
        this process's addressable shards, so the same code serves single-
        controller and multi-host deployments (each host decodes exactly the
        symbols it owns; remote symbols are decoded by their home host)."""
        import numpy as np

        # Results: the local [lo, hi) symbol rows.
        status, lo, hi = hostlocal.local_block(out.status)
        filled = hostlocal.local_rows(out.filled, lo, hi)
        remaining = hostlocal.local_rows(out.remaining, lo, hi)
        local_batch = OrderBatch(*(np.asarray(a)[lo:hi] for a in batch))
        results = decode_results(
            local_batch, status, filled, remaining, sym_offset=lo
        )

        # Fills: fetch each ADDRESSABLE shard's buffer whole and slice on
        # host — never a global read (multi-host), and never a device-side
        # `[:n]` slice, which is a fresh XLA program per distinct count
        # (a compile + execution round trip per step on a tunneled chip).
        per = self.cfg.max_fills
        count_by_shard = {
            (s.index[0].start or 0): int(np.asarray(s.data)[0])
            for s in out.fill_count.addressable_shards
        }
        fill_shards = {
            name: {
                (s.index[0].start or 0) // per: s.data
                for s in getattr(out, name).addressable_shards
            }
            for name in ("fill_sym", "fill_taker_oid", "fill_maker_oid",
                         "fill_price", "fill_qty")
        }
        fills = []
        for shard in sorted(count_by_shard):
            n = count_by_shard[shard]
            if n == 0:
                continue  # zero-fill shards are never fetched
            fills.extend(decode_fills(
                np.asarray(fill_shards["fill_sym"][shard]),
                np.asarray(fill_shards["fill_taker_oid"][shard]),
                np.asarray(fill_shards["fill_maker_oid"][shard]),
                np.asarray(fill_shards["fill_price"][shard]),
                np.asarray(fill_shards["fill_qty"][shard]),
                n,
            ))
        overflow = any(
            bool(np.asarray(s.data).any())
            for s in out.fill_overflow.addressable_shards
        )
        return results, fills, overflow
