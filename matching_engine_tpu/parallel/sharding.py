"""Symbol-sharded engine step: shard_map over a device mesh.

Design (SURVEY.md §5.7-5.8): the symbol axis is this domain's scaling axis —
books are independent per symbol, so the natural mesh layout shards every
[S, ...] array on axis 0 over a 1-D mesh axis ``"sym"``. Each chip runs the
*same* jit'd match step (engine/kernel.py:engine_step_impl) on its local
symbol slice; no collective is needed inside the match itself (books never
interact), which is exactly why this maps perfectly onto SPMD. Collectives
only appear at the edges:

- fill logs and top-of-book stay device-sharded; the host reads per-shard
  segments directly (one transfer per array, already compacted per shard),
- ``all_top_of_book`` demonstrates the ICI publication path: an
  ``all_gather`` over the mesh axis so *every* chip holds the full market
  picture (what a cross-symbol risk check or market-data fanout would read).

The reference's analogous layer simply does not exist — its only
"communication backend" is client-facing gRPC (SURVEY.md §5.8); there is no
server-to-server plane to port, so this module is designed TPU-first from
the north star rather than translated.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax

from matching_engine_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from matching_engine_tpu.engine.book import (
    I32,
    BookBatch,
    EngineConfig,
    OrderBatch,
    init_book,
)
from matching_engine_tpu.engine.harness import (
    HostFill,
    HostResult,
    decode_fills,
    decode_results,
)
from matching_engine_tpu.engine.kernel import engine_step_impl
from matching_engine_tpu.parallel import hostlocal

AXIS = "sym"


class ShardedStepOutput(NamedTuple):
    """Per-step results with fill logs kept per-shard.

    Identical to engine.book.StepOutput except the fill log is the
    concatenation of each shard's compacted buffer: fill arrays are
    [n_shards * max_fills], and fill_count / fill_overflow are [n_shards]
    (shard i's valid rows are [i * max_fills, i * max_fills + count[i])).
    fill_sym is already globalized (local slot + shard offset).
    """

    status: jax.Array
    filled: jax.Array
    remaining: jax.Array
    fill_sym: jax.Array
    fill_taker_oid: jax.Array
    fill_maker_oid: jax.Array
    fill_price: jax.Array
    fill_qty: jax.Array
    fill_count: jax.Array
    fill_overflow: jax.Array
    best_bid: jax.Array
    bid_size: jax.Array
    best_ask: jax.Array
    ask_size: jax.Array


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the symbol axis. Defaults to every visible device."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested {n_devices} devices, only {len(devices)} visible"
                )
            devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.array(devices).reshape(-1), (AXIS,))


def _book_specs() -> BookBatch:
    lane = P(AXIS, None)
    return BookBatch(
        bid_price=lane, bid_qty=lane, bid_oid=lane, bid_seq=lane,
        bid_owner=lane,
        ask_price=lane, ask_qty=lane, ask_oid=lane, ask_seq=lane,
        ask_owner=lane,
        next_seq=P(AXIS),
    )


def _order_specs() -> OrderBatch:
    lane = P(AXIS, None)
    return OrderBatch(op=lane, side=lane, otype=lane, price=lane, qty=lane,
                      oid=lane, owner=lane)


def _out_specs() -> ShardedStepOutput:
    return ShardedStepOutput(
        status=P(AXIS, None), filled=P(AXIS, None), remaining=P(AXIS, None),
        fill_sym=P(AXIS), fill_taker_oid=P(AXIS), fill_maker_oid=P(AXIS),
        fill_price=P(AXIS), fill_qty=P(AXIS),
        fill_count=P(AXIS), fill_overflow=P(AXIS),
        best_bid=P(AXIS), bid_size=P(AXIS), best_ask=P(AXIS), ask_size=P(AXIS),
    )


class ShardedEngine:
    """Owns the sharded step function + sharded book placement for one mesh.

    Usage:
        eng = ShardedEngine(cfg, mesh)
        book = eng.init_book()                 # device-sharded
        book, out = eng.step(book, orders)     # donated, stays sharded
        results, fills, overflow = eng.decode(orders, out)
    """

    def __init__(self, cfg: EngineConfig, mesh: Mesh):
        n = mesh.devices.size
        if cfg.num_symbols % n != 0:
            raise ValueError(
                f"num_symbols={cfg.num_symbols} not divisible by mesh size {n}"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.n_shards = n
        self.local_cfg = dataclasses.replace(cfg, num_symbols=cfg.num_symbols // n)
        self.book_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), _book_specs()
        )
        self.order_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), _order_specs()
        )

        local_cfg = self.local_cfg
        local_s = local_cfg.num_symbols

        def local_step(book: BookBatch, orders: OrderBatch):
            new_book, out = engine_step_impl(local_cfg, book, orders)
            # Globalize fill symbol slots: local index + this shard's offset.
            off = jax.lax.axis_index(AXIS).astype(I32) * local_s
            fill_sym = jnp.where(out.fill_qty > 0, out.fill_sym + off, 0)
            return new_book, ShardedStepOutput(
                status=out.status, filled=out.filled, remaining=out.remaining,
                fill_sym=fill_sym,
                fill_taker_oid=out.fill_taker_oid,
                fill_maker_oid=out.fill_maker_oid,
                fill_price=out.fill_price, fill_qty=out.fill_qty,
                fill_count=out.fill_count.reshape(1),
                fill_overflow=out.fill_overflow.reshape(1),
                best_bid=out.best_bid, bid_size=out.bid_size,
                best_ask=out.best_ask, ask_size=out.ask_size,
            )

        mapped = shard_map(
            local_step,
            mesh=mesh,
            in_specs=(_book_specs(), _order_specs()),
            out_specs=(_book_specs(), _out_specs()),
        )
        self.step = jax.jit(mapped, donate_argnums=0)

        def gather_tob(bb, bs, ba, as_):
            f = shard_map(
                lambda *xs: tuple(
                    jax.lax.all_gather(x, AXIS, tiled=True) for x in xs
                ),
                mesh=mesh,
                in_specs=(P(AXIS),) * 4,
                out_specs=(P(),) * 4,
                # all_gather output is identical on every shard by
                # construction; VMA inference can't see that through the
                # tiled gather, so assert it manually.
                check_vma=False,
            )
            return f(bb, bs, ba, as_)

        # ICI publication path: every chip ends up with the full [S] arrays.
        self.all_top_of_book = jax.jit(gather_tob)

    def _build_auction(self) -> None:
        """Sharded call auction (engine/auction.py on a mesh): symbols are
        independent, so the uncross is pure SPMD with ZERO collectives —
        the same invariant that lets multi-process hosts run at
        independent rates (a collective here would make a lone host's
        RunAuction hang waiting for peers). All-or-nothing is therefore
        PER SHARD: a shard whose record log would overflow aborts its own
        symbols untouched while other shards uncross normally (books are
        independent, so cross-shard atomicity buys nothing). Fill logs
        stay per shard ([n_shards * max_fills], shard i's valid rows
        [i*max_fills, i*max_fills + count[i])), same as the continuous
        step — decode reads addressable shards only."""
        from matching_engine_tpu.engine.auction import (
            apply_uncross,
            compact_records,
            uncross_and_records,
            zero_unless,
        )

        local_cfg = self.local_cfg
        local_s = local_cfg.num_symbols
        n = local_cfg.max_fills
        mesh = self.mesh

        def local_auction(book: BookBatch, mask):
            (fill_b, fill_a, p_star, exec_hi, exec_lo, rec_taker,
             rec_maker, rec_qty, rec_counts) = uncross_and_records(
                local_cfg, book, mask)
            local_total = jnp.sum(rec_counts)
            # PER-SHARD all-or-nothing (no collective — see docstring).
            aborted = local_total > n
            new_book = apply_uncross(book, fill_b, fill_a, mask & ~aborted,
                                     kernel=local_cfg.kernel,
                                     levels=local_cfg.levels)
            r = rec_qty.shape[1]
            off = jax.lax.axis_index(AXIS).astype(I32) * local_s
            sym_ids = jnp.broadcast_to(
                jnp.arange(local_s, dtype=I32)[:, None], (local_s, r)) + off
            price = jnp.broadcast_to(p_star[:, None], (local_s, r))
            f_sym, f_taker, f_maker, f_price, f_qty = compact_records(
                sym_ids, rec_taker, rec_maker, price, rec_qty, n, aborted)
            from matching_engine_tpu.engine.kernel import _top_of_book

            best_bid, bid_size = _top_of_book(
                new_book.bid_price, new_book.bid_qty, True)
            best_ask, ask_size = _top_of_book(
                new_book.ask_price, new_book.ask_qty, False)
            return new_book, (
                zero_unless(p_star, ~aborted),
                zero_unless(exec_lo, ~aborted),
                zero_unless(exec_hi, ~aborted),
                best_bid, bid_size, best_ask, ask_size,
                f_sym, f_taker, f_maker, f_price, f_qty,
                jnp.where(aborted, 0, jnp.minimum(local_total, n))
                .astype(I32).reshape(1),
                aborted.astype(I32).reshape(1),
            )

        out_specs = (
            _book_specs(),
            (P(AXIS),) * 14,
        )
        mapped = shard_map(
            local_auction,
            mesh=mesh,
            in_specs=(_book_specs(), P(AXIS)),
            out_specs=out_specs,
        )
        self._auction_step = jax.jit(mapped, donate_argnums=0)

    def auction(self, book: BookBatch, mask_host):
        """Run the sharded uncross. mask_host: [S] bool numpy. Returns
        (new_book, out_tuple) — decode with decode_auction."""
        if not hasattr(self, "_auction_step"):
            self._build_auction()
        mask = hostlocal.put_tree(
            mask_host, NamedSharding(self.mesh, P(AXIS)))
        return self._auction_step(book, mask)

    def _decode_shard_fills(self, counts, cols: dict) -> list[HostFill]:
        """Per-shard fill-log decode from ADDRESSABLE shards only: fetch
        each shard's buffer whole, slice on host (never a device-side
        [:n] — a fresh XLA program per count), skip zero-count shards.
        `cols` maps the decode_fills column names (sym/taker/maker/price/
        qty) to the [n_shards * max_fills] arrays. Shared by the
        continuous decode and decode_auction."""
        import numpy as np

        per = self.cfg.max_fills
        count_by_shard = {
            (s.index[0].start or 0): int(np.asarray(s.data)[0])
            for s in counts.addressable_shards
        }
        buf = {
            name: {
                (s.index[0].start or 0) // per: s.data
                for s in arr.addressable_shards
            }
            for name, arr in cols.items()
        }
        fills: list[HostFill] = []
        for shard in sorted(count_by_shard):
            c = count_by_shard[shard]
            if c == 0:
                continue  # zero-fill shards are never fetched
            fills.extend(decode_fills(
                np.asarray(buf["sym"][shard]),
                np.asarray(buf["taker"][shard]),
                np.asarray(buf["maker"][shard]),
                np.asarray(buf["price"][shard]),
                np.asarray(buf["qty"][shard]),
                c,
            ))
        return fills

    def decode_auction(self, out):
        """Host view from addressable shards only (multi-process safe).

        Returns (view, fills, aborted_shards): `view` is a dict of THIS
        process's contiguous symbol block (lo, clear_price, executed,
        best_bid, bid_size, best_ask, ask_size); `fills` the local
        shards' bilateral records as HostFill (sym already globalized);
        `aborted_shards` how many LOCAL shards hit the per-shard
        all-or-nothing abort (their symbols are untouched and report
        executed=0; other shards' results are valid). `view` also carries
        `aborted_flags` (this host's per-shard abort booleans) and
        `shard_lo` (its first shard index) so callers can resolve WHICH
        symbols were hit: symbol slot // local_symbols -> shard."""
        import numpy as np

        (clear_p, exec_lo, exec_hi, bb, bs, ba, asz,
         f_sym, f_taker, f_maker, f_price, f_qty, counts, aborted) = out
        clear_local, lo, _ = hostlocal.local_block(clear_p)
        executed = (
            np.asarray(hostlocal.local_block(exec_hi)[0]).astype(np.int64)
            << 15) + np.asarray(hostlocal.local_block(exec_lo)[0])
        view = {
            "lo": lo,
            "clear_price": clear_local,
            "executed": executed,
            "best_bid": hostlocal.local_block(bb)[0],
            "bid_size": hostlocal.local_block(bs)[0],
            "best_ask": hostlocal.local_block(ba)[0],
            "ask_size": hostlocal.local_block(asz)[0],
        }
        fills = self._decode_shard_fills(counts, {
            "sym": f_sym, "taker": f_taker, "maker": f_maker,
            "price": f_price, "qty": f_qty,
        })
        flags_local, shard_lo, _ = hostlocal.local_block(aborted)
        flags_local = np.asarray(flags_local).astype(bool)
        view["aborted_flags"] = flags_local
        view["shard_lo"] = shard_lo
        return view, fills, int(flags_local.sum())

    def init_book(self) -> BookBatch:
        return hostlocal.put_tree(init_book(self.cfg), self.book_sharding)

    def place_orders(self, orders: OrderBatch) -> OrderBatch:
        # Hot path (once per dispatch). Multi-process: each host contributes
        # its addressable symbol rows (remote rows are OP_NOOP padding in
        # this host's batch — the real ops come from their home host).
        return hostlocal.put_tree(orders, self.order_sharding)

    def decode(
        self, batch: OrderBatch, out: ShardedStepOutput
    ) -> tuple[list[HostResult], list[HostFill], bool]:
        """Decode per-order results + per-shard fill segments — reading ONLY
        this process's addressable shards, so the same code serves single-
        controller and multi-host deployments (each host decodes exactly the
        symbols it owns; remote symbols are decoded by their home host)."""
        import numpy as np

        # Results: the local [lo, hi) symbol rows.
        status, lo, hi = hostlocal.local_block(out.status)
        filled = hostlocal.local_rows(out.filled, lo, hi)
        remaining = hostlocal.local_rows(out.remaining, lo, hi)
        local_batch = OrderBatch(*(np.asarray(a)[lo:hi] for a in batch))
        results = decode_results(
            local_batch, status, filled, remaining, sym_offset=lo
        )

        # Fills: the shared per-shard decode (_decode_shard_fills) — never
        # a global read (multi-host), never a device-side [:n] slice.
        fills = self._decode_shard_fills(out.fill_count, {
            "sym": out.fill_sym, "taker": out.fill_taker_oid,
            "maker": out.fill_maker_oid, "price": out.fill_price,
            "qty": out.fill_qty,
        })
        overflow = any(
            bool(np.asarray(s.data).any())
            for s in out.fill_overflow.addressable_shards
        )
        return results, fills, overflow
