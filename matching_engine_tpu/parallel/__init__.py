"""Multi-chip scale-out: symbol-sharded engine over a jax.sharding.Mesh.

The reference has no distributed plane at all (SURVEY.md §2 "Parallelism /
distributed-communication components: NONE"); its scaling ceiling is a global
mutex around SQLite. This package is the TPU-native equivalent the survey
specifies (§5.7-5.8): books sharded over the symbol axis of a device mesh,
the match step run per-shard under shard_map, and top-of-book published
across chips with XLA collectives over ICI.
"""

from matching_engine_tpu.parallel.sharding import (
    ShardedEngine,
    ShardedStepOutput,
    make_mesh,
)

__all__ = ["ShardedEngine", "ShardedStepOutput", "make_mesh"]
