"""Multi-host scale-out: DCN-aware meshes + per-host symbol ownership.

The reference has no server-to-server plane at all (SURVEY.md §5.8 — its
only communication backend is client-facing gRPC), so this layer is designed
TPU-first: `jax.distributed` for process bootstrap, one global Mesh whose
device order is host-major so the symbol axis lands ICI-contiguous on each
host, and XLA collectives that decompose hierarchically (intra-host legs on
ICI, the single cross-host leg on DCN).

Deployment model (matching the symbol-sharded design in sharding.py):

- every host runs the same program and calls `initialize()` (a gated wrapper
  over `jax.distributed.initialize`);
- `make_multihost_mesh()` builds the 1-D symbol mesh over ALL processes'
  devices (host-major order, via mesh_utils on real topologies);
- each host's gRPC gateway accepts orders only for the symbol range
  `local_symbol_slice()` assigns it (a front-end router or client-side
  hashing keeps symbols home); the engine step itself is pure SPMD — no
  cross-host traffic during matching, DCN is touched only by the
  `all_top_of_book` publication gather and by checkpoint collection.

Single-process multi-device (the test/dev case, and the driver's virtual
8-device CPU mesh) uses the same code path: `initialize()` no-ops, the mesh
covers the local devices, and `local_symbol_slice()` returns the full range.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from matching_engine_tpu.parallel.sharding import AXIS


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Bootstrap the JAX distributed runtime; returns True if initialized.

    No-ops (returns False) when single-process: coordinator unset and the
    environment carries no cluster autodetection hints. Safe to call
    unconditionally at server start.
    """
    import os

    explicit = (coordinator_address, num_processes, process_id) != (None, None, None)
    if not explicit and not any(
        v in os.environ for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")
    ):
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_multihost_mesh(devices=None) -> Mesh:
    """1-D symbol mesh over every device of every process, host-major.

    Host-major order means a contiguous block of the symbol axis maps onto
    each host's local chips: the intra-block legs of any collective ride
    ICI, and only one boundary per host pair crosses DCN. On real TPU
    topologies `mesh_utils.create_device_mesh` additionally picks an
    ICI-friendly order within each host.
    """
    if devices is None:
        devices = jax.devices()
    n_procs = max(d.process_index for d in devices) + 1
    if n_procs == 1:
        try:
            from jax.experimental import mesh_utils

            dm = mesh_utils.create_device_mesh((len(devices),), devices=devices)
        except Exception:  # CPU/virtual platforms lack topology info
            dm = np.array(devices)
        return Mesh(dm.reshape(-1), (AXIS,))
    # Multi-process: let mesh_utils pick an ICI-friendly per-host order and
    # keep hosts on the (DCN) outer axis, then flatten host-major; fall back
    # to plain (process, id) order off real hardware.
    try:
        from jax.experimental import mesh_utils

        per_host = len(devices) // n_procs
        dm = mesh_utils.create_hybrid_device_mesh(
            (per_host,), (n_procs,), devices=devices
        )
        return Mesh(dm.reshape(-1), (AXIS,))
    except Exception:
        ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
        return Mesh(np.array(ordered), (AXIS,))


def local_symbol_slice(mesh: Mesh, num_symbols: int) -> slice:
    """The global symbol range whose books live on THIS process's devices.

    A host's gateway only accepts (or is only routed) symbols in its slice;
    everything else about the engine step is global SPMD.
    """
    devs = mesh.devices.reshape(-1)
    n = devs.size
    if num_symbols % n != 0:
        raise ValueError(f"num_symbols={num_symbols} not divisible by mesh size {n}")
    per = num_symbols // n
    pid = jax.process_index()
    mine = [i for i, d in enumerate(devs) if d.process_index == pid]
    if not mine:
        return slice(0, 0)
    lo, hi = min(mine), max(mine)
    if mine != list(range(lo, hi + 1)):
        raise ValueError(
            "mesh device order is not host-contiguous; build it with "
            "make_multihost_mesh() so symbol ownership is a single range"
        )
    return slice(lo * per, (hi + 1) * per)
