"""Multi-host scale-out: DCN-aware meshes + per-host symbol ownership.

The reference has no server-to-server plane at all (SURVEY.md §5.8 — its
only communication backend is client-facing gRPC), so this layer is designed
TPU-first: `jax.distributed` for process bootstrap, one global Mesh whose
device order is host-major so the symbol axis lands ICI-contiguous on each
host, and XLA collectives that decompose hierarchically (intra-host legs on
ICI, the single cross-host leg on DCN).

Deployment model (matching the symbol-sharded design in sharding.py):

- every host runs the same program and calls `initialize()` (a gated wrapper
  over `jax.distributed.initialize`);
- `make_multihost_mesh()` builds the 1-D symbol mesh over ALL processes'
  devices (host-major order, via mesh_utils on real topologies);
- each host's serving edges accept orders only for symbols HOMED on it
  (`symbol_home()` — a stable name hash every host computes identically;
  slot indices recycle, so ownership must be by name, and foreign-homed
  submits reject at admission). A front-end router or client-side hashing
  uses the same function to keep symbols home. The engine step itself is
  pure SPMD — no cross-host traffic during matching, DCN is touched only
  by the `all_top_of_book` publication gather and by checkpoint collection.

Single-process multi-device (the test/dev case, and the driver's virtual
8-device CPU mesh) uses the same code path: `initialize()` no-ops, the mesh
covers the local devices, and `local_symbol_slice()` returns the full range.

Independence note: the engine step contains NO collectives (books never
interact), so hosts drain their dispatch queues at their own pace — no
cross-host lockstep. Only `all_top_of_book` and any future cross-symbol
collective require every process to participate in the same call.
Order-id scope: each host's runner issues "OID-<n>" within its own gateway
and SQLite (symbols are routed home), so ids are unique per home host;
`aggregate_host_stores` below is the namespacing join an operator uses to
read several hosts' stores as one venue-wide view.
Proven end to end by tests/test_multiprocess.py (two real processes,
localhost coordinator, 4+4 virtual CPU devices).
"""

from __future__ import annotations

import os
import zlib

import jax
import numpy as np
from jax.sharding import Mesh

from matching_engine_tpu.parallel.sharding import AXIS


def _cluster_detected(env) -> bool:
    """True when a standard launcher exposes a MULTI-process world this
    process is a rank of — the signals jax.distributed's cluster plugins
    resolve. Presence of a batch allocation alone (e.g. an interactive
    `salloc` shell, SLURM_JOB_ID set but no task rank) is NOT a cluster:
    auto-initializing there would block boot waiting for ranks that never
    connect. ME_NO_AUTO_DISTRIBUTED=1 disables detection entirely."""
    if env.get("ME_NO_AUTO_DISTRIBUTED"):
        return False
    if any(v in env for v in (
        "JAX_COORDINATOR_ADDRESS",   # jax's own env bootstrap
        "COORDINATOR_ADDRESS",       # common wrapper convention
        "MEGASCALE_COORDINATOR_ADDRESS",  # multislice
    )):
        return True
    try:
        if int(env.get("SLURM_NTASKS", "1")) > 1 and "SLURM_PROCID" in env:
            return True  # srun-launched rank of a >1-task step
        if int(env.get("OMPI_COMM_WORLD_SIZE", "1")) > 1:
            return True  # mpirun-launched rank
    except ValueError:
        pass
    # Cloud TPU pod: the worker metadata lists every host.
    return len(env.get("TPU_WORKER_HOSTNAMES", "").split(",")) > 1


def cpu_collectives_available() -> bool:
    """True when this jaxlib can run cross-process collectives on the CPU
    backend (the gloo TCP implementation, jaxlib >= 0.4.34). The
    capability probe tests/test_multiprocess.py skips on: without it a
    multiprocess CPU computation dies at compile time with "Multiprocess
    computations aren't implemented on the CPU backend"."""
    try:
        from jax._src.lib import xla_extension

        return hasattr(xla_extension, "make_gloo_tcp_collectives")
    except ImportError:
        return False


def _enable_cpu_collectives() -> None:
    """Select the gloo CPU collectives implementation when it exists and
    none was chosen. jaxlib ships the implementation but jax defaults
    jax_cpu_collectives_implementation to "none", so a multi-process CPU
    mesh (every tests/test_multiprocess.py scenario, and CI generally)
    fails at compile time unless the flag flips BEFORE the CPU client is
    created — which is why this rides initialize(). Non-CPU backends
    ignore the flag entirely (it only parameterizes CPU client creation),
    so real-TPU runs are unaffected; an operator's explicit choice (env
    JAX_CPU_COLLECTIVES_IMPLEMENTATION or config) is respected."""
    if not cpu_collectives_available():
        return
    try:
        # The flag holder, not jax.config.<name> — 0.4.x defines the enum
        # flag without a Config attribute, while update() still works.
        from jax._src import xla_bridge as _xb

        current = _xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except (ImportError, AttributeError):
        current = None
    if os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION"):
        # Explicit operator choice — respect it even when it reads back
        # as "none" (e.g. disabling gloo to dodge a TCP hang); only the
        # unset default gets auto-selected.
        return
    if current in (None, "none"):
        # None = the private holder moved (API drift) but the capability
        # exists — still attempt the select, else the capability probe
        # says "don't skip" while the tests die at compile time.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, KeyError, ValueError):
            pass  # jax without the flag: nothing to select


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Bootstrap the JAX distributed runtime; returns True if initialized.

    Explicit args always initialize. Otherwise a detected multi-process
    launcher world (srun task ranks, mpirun ranks, Cloud TPU pods,
    megascale — plus JAX_COORDINATOR_ADDRESS-style env bootstrap, see
    _cluster_detected) triggers a no-arg initialize(), which resolves
    coordinator/rank from jax's cluster plugins. Single-process runs with
    none of those markers no-op (returns False); ME_NO_AUTO_DISTRIBUTED=1
    force-disables detection. Safe to call unconditionally at server
    start; a second call (already-initialized) also no-ops.
    """
    import os

    explicit = (coordinator_address, num_processes, process_id) != (None, None, None)
    if not explicit and not _cluster_detected(os.environ):
        return False
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return True  # already initialized
    except (ImportError, AttributeError):
        pass  # private probe unavailable on this jax; initialize() below
        # raises RuntimeError if actually double-initialized, which the
        # except arm treats as non-fatal for detected (non-explicit) runs.
    _enable_cpu_collectives()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        if explicit:
            raise
        # A hint fired but the cluster plugin could not resolve a
        # coordinator (e.g. single-host dev boxes carrying TPU env vars):
        # stay single-process rather than dying at boot.
        print(f"[multihost] cluster hint present but initialize failed "
              f"({e}); continuing single-process")
        return False
    return True


def make_multihost_mesh(devices=None) -> Mesh:
    """1-D symbol mesh over every device of every process, host-major.

    Host-major order means a contiguous block of the symbol axis maps onto
    each host's local chips: the intra-block legs of any collective ride
    ICI, and only one boundary per host pair crosses DCN. On real TPU
    topologies `mesh_utils.create_device_mesh` additionally picks an
    ICI-friendly order within each host.
    """
    if devices is None:
        devices = jax.devices()
    n_procs = max(d.process_index for d in devices) + 1
    if n_procs == 1:
        try:
            from jax.experimental import mesh_utils

            dm = mesh_utils.create_device_mesh((len(devices),), devices=devices)
        except Exception:  # CPU/virtual platforms lack topology info
            dm = np.array(devices)
        return Mesh(dm.reshape(-1), (AXIS,))
    # Multi-process: let mesh_utils pick an ICI-friendly per-host order and
    # keep hosts on the (DCN) outer axis, then flatten host-major; fall back
    # to plain (process, id) order off real hardware.
    try:
        from jax.experimental import mesh_utils

        per_host = len(devices) // n_procs
        dm = mesh_utils.create_hybrid_device_mesh(
            (per_host,), (n_procs,), devices=devices
        )
        return Mesh(dm.reshape(-1), (AXIS,))
    except Exception:
        ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
        return Mesh(np.array(ordered), (AXIS,))


def symbol_home(symbol: str, n_hosts: int) -> int:
    """Deterministic symbol -> home-host mapping (stable CRC32 hash).

    Slot indices are DYNAMIC (recycled when books empty), so slot ranges
    cannot define ownership by name — without a name-based home, two hosts
    whose slots freed up could each accept the same symbol and maintain
    divergent books for it. Every host computes the same mapping; the
    serving edges reject foreign-homed symbols at admission
    (EngineRunner.owns_symbol), and front-end routers/client hashing use
    the same function to send orders to the right host."""
    return zlib.crc32(symbol.encode()) % n_hosts


def local_symbol_slice(mesh: Mesh, num_symbols: int) -> slice:
    """The global symbol range whose books live on THIS process's devices.

    A host's gateway only accepts (or is only routed) symbols in its slice;
    everything else about the engine step is global SPMD.
    """
    devs = mesh.devices.reshape(-1)
    n = devs.size
    if num_symbols % n != 0:
        raise ValueError(f"num_symbols={num_symbols} not divisible by mesh size {n}")
    per = num_symbols // n
    pid = jax.process_index()
    mine = [i for i, d in enumerate(devs) if d.process_index == pid]
    if not mine:
        return slice(0, 0)
    lo, hi = min(mine), max(mine)
    if mine != list(range(lo, hi + 1)):
        raise ValueError(
            "mesh device order is not host-contiguous; build it with "
            "make_multihost_mesh() so symbol ownership is a single range"
        )
    return slice(lo * per, (hi + 1) * per)


def aggregate_host_stores(host_dbs: list[tuple[str, str]]) -> dict:
    """Join several home-hosts' durable stores into one namespaced view.

    Each host's runner issues "OID-<n>" within its OWN gateway and SQLite
    (symbols are routed home), so order ids are unique per host but
    COLLIDE across hosts. This is the aggregator the module docstring's
    caveat promised (VERDICT r4 next-step 9): ids are namespaced
    "<host>/<order_id>", fills keep referential integrity inside their
    host's namespace, and a cross-host home violation (the same SYMBOL
    served by two stores — the one thing routing must prevent) is
    reported rather than silently merged.

    host_dbs: [(host_name, sqlite_path)]. Returns {"orders": {nsid: row},
    "fills": [row], "symbol_conflicts": [(symbol, [hosts])]}.
    """
    import sqlite3

    hosts = [h for h, _ in host_dbs]
    if len(set(hosts)) != len(hosts):
        raise ValueError(f"duplicate host labels in host_dbs: {hosts} — "
                         f"each store must join under a distinct namespace")
    orders: dict[str, dict] = {}
    fills: list[dict] = []
    sym_home: dict[str, set] = {}
    for host, path in host_dbs:
        conn = sqlite3.connect(path)
        try:
            for (oid, client, sym, side, otype, price, qty, rem,
                 status) in conn.execute(
                    "SELECT order_id, client_id, symbol, side, order_type,"
                    " price, quantity, remaining_quantity, status "
                    "FROM orders"):
                nsid = f"{host}/{oid}"
                if nsid in orders:  # impossible: order_id is the PK
                    raise ValueError(f"duplicate id {nsid} within one host")
                orders[nsid] = {
                    "order_id": nsid, "host": host, "client_id": client,
                    "symbol": sym, "side": side, "order_type": otype,
                    "price": price, "quantity": qty, "remaining": rem,
                    "status": status,
                }
                sym_home.setdefault(sym, set()).add(host)
            for oid, cid, price, qty, ts in conn.execute(
                    "SELECT order_id, counter_order_id, price, quantity, ts"
                    " FROM fills"):
                fills.append({
                    "order_id": f"{host}/{oid}",
                    "counter_order_id": f"{host}/{cid}",
                    "price": price, "quantity": qty, "ts": ts,
                })
        finally:
            conn.close()
    return {
        "orders": orders,
        "fills": fills,
        "symbol_conflicts": sorted(
            (s, sorted(h)) for s, h in sym_home.items() if len(h) > 1),
    }
