"""gRPC service/stub adapters for the MatchingEngine contract.

grpcio-tools is not available in this environment, so instead of generated
`*_pb2_grpc.py` we build the equivalent objects directly from the message
classes: a servicer base + `add_to_server` using
`grpc.method_handlers_generic_handler`, and a client stub using channel
`unary_unary` / `unary_stream` callables. Wire behavior is identical to
generated code (method paths, serializers).

Reference parity: the four RPCs at /root/reference/proto/matching_engine.proto:29-35,
plus the CancelOrder/GetMetrics extensions this framework adds.
"""

from __future__ import annotations

import grpc

from matching_engine_tpu.proto import pb2

SERVICE_NAME = "matching_engine.v1.MatchingEngine"

# method name -> (kind, request class, response class)
_METHODS = {
    "SubmitOrder": ("unary_unary", pb2.OrderRequest, pb2.OrderResponse),
    "GetOrderBook": ("unary_unary", pb2.OrderBookRequest, pb2.OrderBookResponse),
    "StreamMarketData": ("unary_stream", pb2.MarketDataRequest, pb2.MarketDataUpdate),
    "StreamOrderUpdates": ("unary_stream", pb2.OrderUpdatesRequest, pb2.OrderUpdate),
    "CancelOrder": ("unary_unary", pb2.CancelRequest, pb2.CancelResponse),
    "AmendOrder": ("unary_unary", pb2.AmendRequest, pb2.AmendResponse),
    "GetMetrics": ("unary_unary", pb2.MetricsRequest, pb2.MetricsResponse),
    "RunAuction": ("unary_unary", pb2.AuctionRequest, pb2.AuctionResponse),
    "SubmitOrderBatch": ("unary_unary", pb2.OrderBatchRequest,
                         pb2.OrderBatchResponse),
    # Client-streaming ingest: chunks of the batch payload in, ONE
    # positional response for the whole stream.
    "SubmitOrderStream": ("stream_unary", pb2.OrderBatchRequest,
                          pb2.OrderBatchResponse),
    "Promote": ("unary_unary", pb2.PromoteRequest, pb2.PromoteResponse),
}


class MatchingEngineServicer:
    """Override any subset of the RPC methods; the rest answer UNIMPLEMENTED
    (matching the reference, whose streaming RPCs fall through to the generated
    base class — see SURVEY.md §3.4)."""

    def SubmitOrder(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "SubmitOrder not implemented")

    def GetOrderBook(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetOrderBook not implemented")

    def StreamMarketData(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "StreamMarketData not implemented")

    def StreamOrderUpdates(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "StreamOrderUpdates not implemented")

    def CancelOrder(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "CancelOrder not implemented")

    def AmendOrder(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "AmendOrder not implemented")

    def GetMetrics(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetMetrics not implemented")

    def RunAuction(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "RunAuction not implemented")

    def SubmitOrderBatch(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "SubmitOrderBatch not implemented")

    def SubmitOrderStream(self, request_iterator, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "SubmitOrderStream not implemented")

    def Promote(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "Promote not implemented")


def add_matching_engine_servicer(servicer: MatchingEngineServicer, server: grpc.Server) -> None:
    handlers = {}
    for name, (kind, req_cls, resp_cls) in _METHODS.items():
        factory = getattr(grpc, f"{kind}_rpc_method_handler")
        handlers[name] = factory(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class MatchingEngineStub:
    """Client stub; one callable attribute per RPC, like generated stubs."""

    def __init__(self, channel: grpc.Channel):
        for name, (kind, req_cls, resp_cls) in _METHODS.items():
            factory = getattr(channel, kind)
            setattr(
                self,
                name,
                factory(
                    f"/{SERVICE_NAME}/{name}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )
