"""Wire contract package.

`matching_engine_pb2` is generated from `matching_engine.proto` (checked in so
no codegen toolchain is needed at runtime; regenerate with
`scripts/regen_proto.sh`). The service/stub adapters live in `rpc.py` —
hand-rolled because this environment ships the grpcio runtime but not
grpcio-tools.
"""

from matching_engine_tpu.proto import matching_engine_pb2 as pb2

Side = pb2.Side
OrderType = pb2.OrderType
BUY = pb2.BUY
SELL = pb2.SELL
LIMIT = pb2.LIMIT
MARKET = pb2.MARKET
Status = pb2.OrderUpdate.Status

__all__ = ["pb2", "Side", "OrderType", "BUY", "SELL", "LIMIT", "MARKET", "Status"]
