"""Wire contract package.

`matching_engine_pb2` is generated from `matching_engine.proto` (checked in so
no codegen toolchain is needed at runtime; additive field changes regenerate
via descriptor surgery with `scripts/regen_pb2.py` — no protoc in this
environment). The service/stub adapters live in `rpc.py` — hand-rolled
because this environment ships the grpcio runtime but not grpcio-tools.
"""

from matching_engine_tpu.proto import matching_engine_pb2 as pb2

Side = pb2.Side
OrderType = pb2.OrderType
BUY = pb2.BUY
SELL = pb2.SELL
LIMIT = pb2.LIMIT
MARKET = pb2.MARKET
Status = pb2.OrderUpdate.Status
TimeInForce = pb2.TimeInForce
TIF_GTC = pb2.TIF_GTC
TIF_IOC = pb2.TIF_IOC
TIF_FOK = pb2.TIF_FOK

# Collapsed (order_type, tif) device codes: the engine carries one small
# int per order (the otype lane) so the dispatch layout stays [S, B, 7].
# MUST match engine/kernel.py's constants (pinned by tests/test_tif.py).
LIMIT_IOC, LIMIT_FOK, MARKET_FOK = 2, 3, 4

_COLLAPSE = {
    (LIMIT, TIF_GTC): LIMIT,
    (MARKET, TIF_GTC): MARKET,
    (MARKET, TIF_IOC): MARKET,  # MARKET is inherently immediate-or-cancel
    (LIMIT, TIF_IOC): LIMIT_IOC,
    (LIMIT, TIF_FOK): LIMIT_FOK,
    (MARKET, TIF_FOK): MARKET_FOK,
}
_SPLIT = {
    LIMIT: (LIMIT, TIF_GTC),
    MARKET: (MARKET, TIF_GTC),
    LIMIT_IOC: (LIMIT, TIF_IOC),
    LIMIT_FOK: (LIMIT, TIF_FOK),
    MARKET_FOK: (MARKET, TIF_FOK),
}


def collapse_otype(order_type: int, tif: int):
    """(wire order_type, wire tif) -> device otype code, or None for an
    invalid combination (the edges reject those)."""
    return _COLLAPSE.get((order_type, tif))


def split_otype(code: int) -> tuple[int, int]:
    """Device otype code -> (wire order_type, wire tif); what storage
    persists (the orders table keeps the reference's 0/1 order_type CHECK
    and records tif in its own column)."""
    return _SPLIT[code]


__all__ = ["pb2", "Side", "OrderType", "BUY", "SELL", "LIMIT", "MARKET",
           "Status", "TimeInForce", "TIF_GTC", "TIF_IOC", "TIF_FOK",
           "LIMIT_IOC", "LIMIT_FOK", "MARKET_FOK",
           "collapse_otype", "split_otype"]
