"""Event sequencer + retransmission store for the sequenced feed.

The serving edges used to fan events straight into bounded subscriber
queues: a slow consumer silently lost the oldest events (streams.py
drop-oldest) and no sequence number existed anywhere in the wire
contract, so a client could neither detect a gap nor recover from one.
Real exchanges solve this with a sequencer + retransmission architecture
(CoinTossX, arXiv:2102.10925; the cloud-exchange sequencer of
arXiv:2402.09527): every event carries a monotonic sequence number and
late/slow consumers recover via replay instead of silent loss.

`FeedSequencer.stamp_*` runs on the dispatch-publish path (under the
dispatch lock, per BATCH of events — the per-event work is one attribute
write, one ring append and shared counter increments) and does two
things atomically per domain:

1. assigns `event.seq = next_seq` for the event's (channel, key) domain
   — channel "md" keys by symbol, channel "ou" by client_id, so each
   subscription's event stream is densely sequenced and gap detection
   needs no filtering;
2. retains the event in that domain's `RetransmissionRing` — a bounded
   deque serving `replay(from_seq)` for gap-fill, with optional disk
   spill of evicted events (atomic segment files, the checkpoint
   tmp+rename pattern) extending the recoverable window beyond memory.

Seq domains (and the spill) are **per boot**: a restarted server rebases
every domain to 1. Spill segments are namespaced under an epoch
directory and stale epochs are purged at init, so a cross-boot replay
can never serve a previous boot's payloads as the requested range; the
service layer clamps ahead-of-head resume cursors and feed.client
detects the rebase (see their docstrings).

Hot-path discipline: the sequencer lock only ever guards dict/deque/list
operations. Spill WRITES run on a background flusher thread (a full
segment is detached under the lock, written outside it), and replay's
disk READS happen after the lock is released — a slow disk degrades the
recoverable window (feed_spill_dropped_events), never the publish path.

Replay is bit-identical: the ring stores the very message objects that
were fanned out (never mutated after publish), and spill segments store
their serialized bytes.
"""

from __future__ import annotations

import base64
import json
import os
import queue
import shutil
import tempfile
import threading
import time
from collections import OrderedDict, deque

from matching_engine_tpu.proto import pb2

CHANNEL_MD = "md"       # keyed by symbol
CHANNEL_OU = "ou"       # keyed by client_id
# Drop-copy audit stream (matching_engine_tpu/audit/): ONE venue-wide seq
# domain (key "") so the whole lifecycle record stream is densely
# sequenced — a gap is evidence of loss between decode and publish, the
# exact failure class the online auditor exists to catch. Events are
# OrderUpdate messages with audit_kind set.
CHANNEL_AUDIT = "audit"
AUDIT_DOMAIN_KEY = ""
# Warm-standby op log (matching_engine_tpu/replication/): ONE venue-wide
# seq domain (key "") so the whole admitted-dispatch stream is densely
# sequenced — a standby replica applies it in seq order and a gap is
# evidence of lost replication input. Events are OrderUpdate messages
# with oplog_kind set (dispatch payloads + heartbeats).
CHANNEL_OPLOG = "oplog"
OPLOG_DOMAIN_KEY = ""
# Event kinds on the oplog channel (OrderUpdate.oplog_kind). Defined here
# rather than in replication/ so the hub can stamp-filter without
# importing the replication package (whose __init__ pulls the server
# stack back in). replication/oplog.py re-exports them.
OPLOG_DISPATCH, OPLOG_HEARTBEAT = 1, 2

_EVENT_CLS = {CHANNEL_MD: pb2.MarketDataUpdate, CHANNEL_OU: pb2.OrderUpdate,
              CHANNEL_AUDIT: pb2.OrderUpdate, CHANNEL_OPLOG: pb2.OrderUpdate}


class RetransmissionRing:
    """Bounded in-memory retransmission store for ONE seq domain.

    Ring entries are (seq, message). Evictions go to the spill buffer
    when one is attached (the FeedSequencer hands full segments to its
    flusher thread); otherwise the oldest seq simply becomes
    unrecoverable — the documented bounded-memory contract, surfaced to
    clients as a detected-but-unfilled gap.
    """

    __slots__ = ("ring", "next_seq", "spill")

    def __init__(self, depth: int, spill=None):
        self.ring: deque = deque(maxlen=max(1, depth))
        self.next_seq = 1
        self.spill = spill

    def append(self, msg) -> int:
        seq = self.next_seq
        self.next_seq = seq + 1
        if self.spill is not None and len(self.ring) == self.ring.maxlen:
            old_seq, old_msg = self.ring[0]
            self.spill.buffer(old_seq, old_msg.SerializeToString())
        self.ring.append((seq, msg))
        return seq

    @property
    def last_seq(self) -> int:
        return self.next_seq - 1

    def first_available(self) -> int:
        """Oldest seq still replayable from memory (next_seq if empty)."""
        return self.ring[0][0] if self.ring else self.next_seq

    def replay(self, from_seq: int, to_seq: int | None = None) -> list:
        """Events with from_seq < seq <= to_seq (to_seq None = head),
        oldest first, memory only — FeedSequencer.replay prepends the
        spilled range."""
        hi = self.last_seq if to_seq is None else min(to_seq, self.last_seq)
        return [m for s, m in self.ring if from_seq < s <= hi]


class _Spill:
    """Disk spill for one domain: evicted events buffer under the
    sequencer lock (list appends only); full segments are written by the
    sequencer's flusher thread as atomic files (tmp + rename, the
    checkpoint atomic-write pattern) named seg_<first>_<last>.json.
    Bounded: oldest segments are deleted past max_segments.

    `_inflight` holds detached-but-unwritten row batches so a replay in
    the detach→write window still sees them (GIL-atomic list ops; the
    replay merge dedups by seq against freshly-written segments)."""

    def __init__(self, root: str, segment: int, max_segments: int, metrics):
        self.root = root
        self.segment = max(1, segment)
        self.max_segments = max(1, max_segments)
        self.metrics = metrics
        self._pending: list[tuple[int, bytes]] = []
        self._inflight: list[list[tuple[int, bytes]]] = []

    # -- under the sequencer lock -----------------------------------------

    def buffer(self, seq: int, payload: bytes) -> None:
        self._pending.append((seq, payload))

    def take_full_segment(self):
        """Detach a full segment's rows for the flusher (None if the
        buffer hasn't reached segment size)."""
        if len(self._pending) < self.segment:
            return None
        rows, self._pending = self._pending, []
        self._inflight.append(rows)
        return rows

    def detach_pending(self):
        """Detach whatever is buffered (flush_spill/shutdown)."""
        if not self._pending:
            return None
        rows, self._pending = self._pending, []
        self._inflight.append(rows)
        return rows

    # -- flusher thread / flush_spill --------------------------------------

    def write_segment(self, rows) -> None:
        first, last = rows[0][0], rows[-1][0]
        try:
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(prefix=".seg-tmp-", dir=self.root)
            with os.fdopen(fd, "w") as f:
                json.dump([[s, base64.b64encode(b).decode()]
                           for s, b in rows], f)
            os.rename(tmp, os.path.join(self.root,
                                        f"seg_{first:016d}_{last:016d}.json"))
            if self.metrics is not None:
                self.metrics.inc("feed_spilled_events", len(rows))
            self._trim()
        except OSError as e:
            # Spill loss degrades the recoverable window, never the feed.
            if self.metrics is not None:
                self.metrics.inc("feed_spill_dropped_events", len(rows))
            print(f"[feed] spill write failed: {type(e).__name__}: {e}")
        finally:
            try:
                self._inflight.remove(rows)
            except ValueError:
                pass

    def _segments(self) -> list[str]:
        try:
            return sorted(n for n in os.listdir(self.root)
                          if n.startswith("seg_") and n.endswith(".json"))
        except OSError:
            return []

    def _trim(self) -> None:
        segs = self._segments()
        for name in segs[:max(0, len(segs) - self.max_segments)]:
            try:
                os.remove(os.path.join(self.root, name))
            except OSError:
                pass

    # -- read path (no sequencer lock held) --------------------------------

    def replay_disk(self, from_seq: int, to_seq: int) -> list[tuple[int, bytes]]:
        """(seq, serialized) pairs with from_seq < seq <= to_seq from the
        flushed segments. Renames are atomic, so concurrent flusher
        writes are either fully visible or not yet."""
        out: list[tuple[int, bytes]] = []
        for name in self._segments():
            try:
                first, last = (int(x) for x in name[4:-5].split("_"))
            except ValueError:
                continue
            if last <= from_seq or first > to_seq:
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    rows = json.load(f)
            except (OSError, ValueError):
                continue
            out.extend((s, base64.b64decode(b)) for s, b in rows
                       if from_seq < s <= to_seq)
        return out


class FeedSequencer:
    """Per-(channel, key) sequencing + retransmission for the feed.

    One instance per shard/host (build_server); the StreamHub calls
    stamp_* under its publish path, the service layer calls replay() for
    `resume_from_seq` streams, and feed.client gap-fills through the
    same RPC surface. The lock guards in-memory state only; all disk IO
    runs off-lock (writes on the flusher thread, reads on the replaying
    RPC thread).
    """

    def __init__(self, metrics=None, depth: int = 1 << 16,
                 spill_dir: str | None = None, spill_segment: int = 1024,
                 max_spill_segments: int = 16, epoch: int | None = None,
                 max_domains: int = 1 << 16):
        self.metrics = metrics
        self.depth = depth
        self.spill_segment = spill_segment
        self.max_spill_segments = max_spill_segments
        self.max_domains = max(1, max_domains)
        # Boot epoch: stamped on every event (feed_epoch) and echoed by
        # resume requests, so a cursor from a previous boot is always
        # distinguishable — even when the new boot's head has already
        # outrun it. Seconds-resolution boot time mixed with the pid;
        # only inequality between boots matters.
        self.epoch = epoch if epoch else (
            (int(time.time()) << 16) | (os.getpid() & 0xFFFF))
        self._lock = threading.Lock()
        # Live domains, LRU by last publish. Past max_domains the
        # least-recently-published domain RETIRES: its ring (and the
        # replay window) is dropped but its next_seq survives in
        # _retired, so a revived domain continues the same seq line —
        # bounding memory at max_domains rings while "millions of
        # client_id domains" cost one small dict entry each.
        self._domains: OrderedDict[tuple[str, str], RetransmissionRing] = \
            OrderedDict()
        self._retired: dict[tuple[str, str], int] = {}  # -> next_seq
        # The drop-copy audit domain (stamp_audit_rows): copy-on-replay
        # chunks of (first_seq, rows, env, n), bounded at `depth` records.
        self._audit_next = 1
        self._audit_chunks: deque = deque()
        self._audit_retained = 0
        self._published = 0  # global publish counter (feed_publish_seq)
        self._ready: list[tuple[_Spill, list]] = []  # detached, unqueued
        self._flush_q: queue.Queue = queue.Queue(maxsize=64)
        self._flusher: threading.Thread | None = None
        self.spill_root = None
        if spill_dir:
            # Seq domains restart at 1 every boot: segments from an older
            # epoch would satisfy a new boot's seq range with the OLD
            # boot's payloads. Namespace per boot and purge stale epochs.
            try:
                os.makedirs(spill_dir, exist_ok=True)
                for name in os.listdir(spill_dir):
                    if name.startswith("epoch-"):
                        shutil.rmtree(os.path.join(spill_dir, name),
                                      ignore_errors=True)
            except OSError:
                pass
            self.spill_root = os.path.join(spill_dir, f"epoch-{self.epoch}")
            # Created eagerly: the live line's dir IS the operator-visible
            # marker of the current epoch (failover runbook), and the
            # promotion test asserts the active epoch by its presence.
            try:
                os.makedirs(self.spill_root, exist_ok=True)
            except OSError:
                pass
            # Spawn the spill flusher here, not lazily on first segment:
            # segments enqueue from every publishing thread OUTSIDE the
            # sequencer lock, so a lazy spawn could race two publishers
            # into two flusher threads (lockset analyzer finding, PR 11).
            self._flusher = threading.Thread(
                target=self._flush_loop, name="feed-spill", daemon=True)
            self._flusher.start()

    def _domain(self, channel: str, key: str) -> RetransmissionRing:
        dom = self._domains.get((channel, key))
        if dom is None:
            spill = None
            if self.spill_root:
                spill = _Spill(
                    os.path.join(self.spill_root, channel,
                                 key.encode().hex() or "_"),
                    self.spill_segment, self.max_spill_segments, self.metrics)
            dom = self._domains[(channel, key)] = RetransmissionRing(
                self.depth, spill=spill)
            # A revived retired domain continues its seq line (a reused
            # seq would corrupt client gap accounting); its pre-retire
            # spill segments are same-epoch and deterministic-path, so
            # they still serve replay.
            retired_next = self._retired.pop((channel, key), None)
            if retired_next is not None:
                dom.next_seq = retired_next
        return dom

    # -- publish path (dispatch lock held by the caller's drain loop) ------

    def _stamp(self, channel: str, updates, key_of) -> None:
        with self._lock:
            for u in updates:
                key = key_of(u)
                dom = self._domain(channel, key)
                u.seq = dom.append(u)
                u.feed_epoch = self.epoch
                self._domains.move_to_end((channel, key))  # LRU touch
                if dom.spill is not None:
                    rows = dom.spill.take_full_segment()
                    if rows is not None:
                        self._ready.append((dom.spill, rows))
            while len(self._domains) > self.max_domains:
                k, old = self._domains.popitem(last=False)
                self._retired[k] = old.next_seq
                if old.spill is not None:
                    rows = old.spill.detach_pending()
                    if rows is not None:
                        self._ready.append((old.spill, rows))
                if self.metrics is not None:
                    self.metrics.inc("feed_domains_retired")
            self._published += len(updates)
            if self.metrics is not None:
                self.metrics.set_gauge("feed_publish_seq", self._published)
            ready, self._ready = self._ready, []
        for spill, rows in ready:  # enqueue outside the lock
            self._enqueue_segment(spill, rows)

    def stamp_market_data(self, updates) -> None:
        self._stamp(CHANNEL_MD, updates, lambda u: u.symbol)
        if self.metrics is not None:
            self.metrics.inc("feed_md_published", len(updates))

    def stamp_order_updates(self, updates) -> None:
        self._stamp(CHANNEL_OU, updates, lambda u: u.client_id)
        if self.metrics is not None:
            self.metrics.inc("feed_ou_published", len(updates))

    def stamp_oplog(self, updates) -> None:
        """Op-log records (replication/oplog.py): one venue-wide domain,
        normal ring/spill retention — the events are already-built
        OrderUpdate protos (one per dispatch + heartbeats), so unlike the
        audit channel there is no per-record materialization to defer.
        The retransmission window is what bounds how far behind a standby
        may fall and still catch up by replay (size --feed-depth /
        --feed-spill-dir accordingly; docs/OPERATIONS.md runbook)."""
        self._stamp(CHANNEL_OPLOG, updates, lambda u: OPLOG_DOMAIN_KEY)
        if self.metrics is not None:
            self.metrics.inc("feed_oplog_published", len(updates))
            # .get(), not [] — a promotion's rebase_epoch clears
            # _domains from another thread; a racing gauge read must
            # degrade to "no update", not KeyError the publisher.
            dom = self._domains.get((CHANNEL_OPLOG, OPLOG_DOMAIN_KEY))
            if dom is not None:
                self.metrics.set_gauge("repl_oplog_head_seq", dom.last_seq)

    def rebase_epoch(self) -> int:
        """Promotion epoch bump (replication/standby.py promote): start a
        FRESH feed epoch — every seq domain rebases to 1, the audit chunk
        store resets, and spill segments from the pre-promotion line are
        purged (a resuming subscriber must never be served the old line's
        payloads as the new epoch's range). Callers quiesce publishing
        first (the standby applier is stopped and pending dispatches
        drained before promote rebases); connected clients observe
        exactly one epoch_rebases increment. Returns the new epoch."""
        # Drain buffered spill rows to disk first so the flusher holds no
        # in-flight batches pointed at directories about to be purged.
        self.flush_spill()
        with self._lock:
            old = self.epoch
            new = (int(time.time()) << 16) | (os.getpid() & 0xFFFF)
            if new <= old:
                new = old + 1  # same second + pid: inequality is the contract
            self.epoch = new
            self._domains.clear()
            self._retired.clear()
            self._audit_next = 1
            self._audit_chunks.clear()
            self._audit_retained = 0
            spill_base = (os.path.dirname(self.spill_root)
                          if self.spill_root else None)
            if spill_base:
                self.spill_root = os.path.join(spill_base,
                                               f"epoch-{self.epoch}")
        if spill_base:
            try:
                for name in os.listdir(spill_base):
                    if (name.startswith("epoch-")
                            and name != f"epoch-{self.epoch}"):
                        shutil.rmtree(os.path.join(spill_base, name),
                                      ignore_errors=True)
                os.makedirs(self.spill_root, exist_ok=True)
            except OSError:
                pass
        return new

    def stamp_audit_rows(self, rows, env, n: int) -> int:
        """Drop-copy records: one venue-wide domain (every serving lane
        publishes into the same seq line through the hub lock, so the
        audit stream is densely sequenced across lanes). Returns the
        FIRST seq of the n-record run [first, first + n).

        Unlike the md/ou channels, retention is COPY-ON-REPLAY: the ring
        stores one (first_seq, rows, env) chunk per dispatch and replay
        materializes the OrderUpdate protos on demand — the drop-copy
        rides the drain loops' publish path, and building + stamping a
        proto per record there is exactly the per-record python the
        audit subsystem promises to keep off the hot path. Live
        subscribers get materialized events from the hub (transient —
        the ring never aliases subscriber queues). Consequence: the
        audit window is memory-bounded at the feed depth in RECORDS;
        --feed-spill-dir does not extend it."""
        with self._lock:
            first = self._audit_next
            self._audit_next = first + n
            self._audit_chunks.append((first, rows, env, n))
            self._audit_retained += n
            # Evict oldest dispatch-chunks past the depth (in RECORDS);
            # the newest chunk always stays, however large.
            while (self._audit_retained > self.depth
                   and len(self._audit_chunks) > 1):
                self._audit_retained -= self._audit_chunks.popleft()[3]
            self._published += n
            if self.metrics is not None:
                self.metrics.set_gauge("feed_publish_seq", self._published)
                self.metrics.inc("feed_audit_published", n)
        return first

    def _audit_materialize(self, chunk, lo: int, hi: int) -> list:
        """Protos for the chunk's records with lo <= seq <= hi (replay
        and gap-fill) — the SAME materializer the hub's live fan-out
        uses, so replayed bytes == live bytes by construction."""
        from matching_engine_tpu.audit.dropcopy import materialize_chunk

        first, rows, env, n = chunk
        return materialize_chunk(rows, env, first, self.epoch, lo=lo, hi=hi)

    def _audit_last_seq(self) -> int:
        return self._audit_next - 1

    def _audit_replay(self, from_seq: int, to_seq: int | None) -> tuple:
        with self._lock:
            hi = self._audit_next - 1 if to_seq is None \
                else min(to_seq, self._audit_next - 1)
            chunks = [c for c in self._audit_chunks
                      if c[0] <= hi and c[0] + c[3] > from_seq + 1]
            if self.metrics is not None:
                self.metrics.inc("feed_retransmit_requests")
        events: list = []
        for c in chunks:  # materialize OUTSIDE the lock (python-proto work)
            events.extend(self._audit_materialize(c, from_seq + 1, hi))
        missed = max(0, (hi - from_seq) - len(events)) if hi > from_seq \
            else 0
        if self.metrics is not None:
            if events:
                self.metrics.inc("feed_retransmit_events", len(events))
            if missed:
                self.metrics.inc("feed_retransmit_misses", missed)
        return events, missed

    # -- spill flusher -----------------------------------------------------

    def _enqueue_segment(self, spill: _Spill, rows) -> None:
        # self._flusher was started in __init__ (spill configured implies
        # spill_root implies the thread exists).
        try:
            self._flush_q.put_nowait((spill, rows))
        except queue.Full:
            # A wedged disk must not grow host memory without bound:
            # drop the segment (the window shrinks, accounted).
            try:
                spill._inflight.remove(rows)
            except ValueError:
                pass
            if self.metrics is not None:
                self.metrics.inc("feed_spill_dropped_events", len(rows))

    def _flush_loop(self) -> None:
        while True:
            spill, rows = self._flush_q.get()
            try:
                spill.write_segment(rows)
            finally:
                self._flush_q.task_done()

    def flush_spill(self) -> None:
        """Write everything buffered to disk and wait for the flusher to
        drain (shutdown/tests)."""
        with self._lock:
            ready, self._ready = self._ready, []
            for dom in self._domains.values():
                if dom.spill is not None:
                    rows = dom.spill.detach_pending()
                    if rows is not None:
                        ready.append((dom.spill, rows))
        for spill, rows in ready:
            spill.write_segment(rows)
        if self._flusher is not None:
            self._flush_q.join()

    # -- read path ---------------------------------------------------------

    def last_seq(self, channel: str, key: str) -> int:
        if channel == CHANNEL_AUDIT:
            with self._lock:
                return self._audit_last_seq()
        with self._lock:
            dom = self._domains.get((channel, key))
            if dom is not None:
                return dom.last_seq
            return self._retired.get((channel, key), 1) - 1

    def replay(self, channel: str, key: str, from_seq: int,
               to_seq: int | None = None) -> tuple[list, int]:
        """Events with from_seq < seq <= to_seq for one domain, oldest
        first. Returns (events, missed): `missed` counts requested seqs
        already evicted past the spill window — the unrecoverable-
        server-side signal (feed_retransmit_misses). Disk reads happen
        after the lock is released. The audit domain materializes its
        copy-on-replay chunks here (no spill; memory-bounded window)."""
        if channel == CHANNEL_AUDIT:
            return self._audit_replay(from_seq, to_seq)
        cls = _EVENT_CLS[channel]
        with self._lock:
            if self.metrics is not None:
                self.metrics.inc("feed_retransmit_requests")
            dom = self._domains.get((channel, key))
            if dom is None:
                head = self._retired.get((channel, key), 1) - 1
                missed = max(0, (head if to_seq is None else
                                 min(to_seq, head)) - from_seq)
                if missed and self.metrics is not None:
                    # Retired domain: the window is gone until it revives.
                    self.metrics.inc("feed_retransmit_misses", missed)
                return [], missed
            hi = dom.last_seq if to_seq is None else min(to_seq, dom.last_seq)
            mem_first = dom.first_available()
            mem_events = dom.replay(from_seq, hi)
            spill = dom.spill
            pending = list(spill._pending) if spill is not None else []
            inflight = list(spill._inflight) if spill is not None else []
        events: list = []
        if spill is not None and from_seq + 1 < mem_first:
            lo_hi = min(hi, mem_first - 1)
            # seg files ∪ in-flight batches ∪ pending buffer, deduped by
            # seq (a batch can be both on disk and still in _inflight for
            # an instant) — all strictly below mem_first, disjoint from
            # the memory slice.
            rows: dict[int, bytes] = {}
            for s, b in spill.replay_disk(from_seq, lo_hi):
                rows[s] = b
            for batch in inflight:
                for s, b in batch:
                    if from_seq < s <= lo_hi:
                        rows[s] = b
            for s, b in pending:
                if from_seq < s <= lo_hi:
                    rows[s] = b
            events = [cls.FromString(rows[s]) for s in sorted(rows)]
        events.extend(mem_events)
        missed = 0
        if hi > from_seq:
            missed = (hi - from_seq) - len(events)
        if self.metrics is not None:
            if events:
                self.metrics.inc("feed_retransmit_events", len(events))
            if missed > 0:
                self.metrics.inc("feed_retransmit_misses", missed)
        return events, max(0, missed)
