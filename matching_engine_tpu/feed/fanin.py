"""Epoch-consistent feed fan-in: K serving lanes → one venue stream.

With ``--serve-shards K`` every lane publishes market data, order
updates, op-log and drop-copy rows into ONE StreamHub, whose single lock
stamps (FeedSequencer) and fans out atomically. That atomicity is the
correctness anchor of the feed layer — and, at K lanes, its scaling
ceiling: every dispatch on every lane serializes its publish tail
through the same hub lock, so feed publishing re-couples the lanes the
shard partition exists to decouple.

This module decouples them with a SEQUENCED MERGE (``--feed-fanin
merged``):

- Each lane publishes through its own `LaneFeedPublisher` — a hub facade
  with its own lock and its own sequencer domain: a per-lane monotonic
  `lane_seq` plus the venue epoch, stamped atomically with enqueue into
  the shared merge queue. A lane's publish tail now costs one uncontended
  lock + one queue put, regardless of K.
- One `FeedFanIn` merger thread (declared role "feed_merger") drains the
  queue, enforces per-lane seq contiguity (out-of-order items park in a
  per-lane reorder buffer; a hole that outlives the gap window is
  DECLARED — ``feed_fanin_gaps`` counts the missing items — and delivery
  continues, mirroring the consumer-side gap-fill contract in
  feed/client.py), and delivers into the real hub. Venue-order stamping
  is UNCHANGED: the merger calls the same `hub.publish_*` entry points,
  so the FeedSequencer stamps inside the hub lock exactly as before —
  but now exactly ONE thread ever contends for it. The auditor's
  stamp-order invariant (observer inside the hub lock) holds for free:
  a single merger delivers in merge order.

Venue order across lanes is ARRIVAL order at the merge (within a lane:
lane_seq order, always). That is the same contract the locked hub gave —
cross-lane interleaving was lock-acquisition order there — so single-hub
mode (``--feed-fanin hub``, the default and the K=1 path) stays
bit-parity-pinned while merged mode changes only WHO serializes.

Trade-off (documented in OPERATIONS.md): merged mode defers the stamp
until the merger delivers, so a dispatch can retire before its feed
events are retained — a crash window the synchronous hub didn't have.
The feed layer is loss-ACCOUNTING by design (seq gaps are detectable and
replayable); deployments that need stamp-before-ack keep ``hub`` mode.
"""

from __future__ import annotations

import queue
import threading
import time

from matching_engine_tpu.utils.obs import warn_rate_limited

_CLOSE = object()

# Payload kinds riding the merge queue.
_MD, _OU, _OPLOG, _AUDIT = 0, 1, 2, 3

# How long a per-lane seq hole may park younger items before the merger
# declares the gap and moves on. Generous: holes only occur when a
# publisher died mid-publish (or a test injected one) — contiguous
# enqueue is atomic with the seq stamp on the healthy path.
GAP_WAIT_S = 0.25


class LaneFeedPublisher:
    """One lane's hub facade: its own sequencer domain (venue epoch +
    per-lane monotonic seq), its own lock, publishing into the shared
    merge queue. Mirrors the StreamHub publish/peek surface the
    dispatcher, runner and drop-copy paths touch; subscription
    management stays on the real hub (readers attach there)."""

    def __init__(self, fanin: "FeedFanIn", lane_id: int):
        self._fanin = fanin
        self._lane_id = lane_id
        self._real_hub = fanin.hub
        # LEVEL "fanin_lane": leaf on the publish path — held only for
        # the (seq++, enqueue) pair, which MUST be atomic: the merger's
        # contiguity check assumes a lane's items enter the queue in seq
        # order (auction/barrier/drop-copy threads publish on a lane too,
        # not just its dispatcher).
        self._lock = threading.Lock()
        self._seq = 0

    # -- peeks / identity (delegated: the real hub owns subscriptions) --

    @property
    def sequencer(self):
        return self._real_hub.sequencer

    def has_market_data_subs(self) -> bool:
        return self._real_hub.has_market_data_subs()

    def has_order_update_subs(self) -> bool:
        return self._real_hub.has_order_update_subs()

    # -- publish surface -----------------------------------------------

    def _submit(self, kind: int, payload) -> None:
        seqr = self._real_hub.sequencer
        epoch = seqr.epoch if seqr is not None else 0
        with self._lock:
            self._seq += 1
            self._fanin._q.put(
                (self._lane_id, epoch, self._seq, kind, payload))

    def publish_market_data(self, updates) -> None:
        if updates:
            self._submit(_MD, updates)

    def publish_order_updates(self, updates) -> None:
        if updates:
            self._submit(_OU, updates)

    def publish_oplog(self, updates) -> None:
        if updates:
            self._submit(_OPLOG, updates)

    def publish_audit_rows(self, rows, env, n: int, drop=None,
                           observer=None) -> list[int]:
        """Async contract: seqs are assigned at merge delivery, so this
        returns [] — the merger increments ``audit_records`` itself
        (audit/dropcopy.py only uses the return for that counter)."""
        self._submit(_AUDIT, (rows, env, n, drop, observer))
        return []


class _LaneMergeState:
    __slots__ = ("expected", "parked", "deadline")

    def __init__(self):
        self.expected = 1          # next lane_seq due from this lane
        self.parked: dict = {}     # lane_seq -> queued item (reorder buf)
        self.deadline = 0.0        # when the oldest hole is declared


class FeedFanIn:
    """The merge point: K LaneFeedPublishers → one merger thread → the
    real StreamHub. Construct with the real hub, hand
    ``lane_publisher(i)`` to each lane's runner/dispatcher/drop-copy as
    their `hub`, and close() AFTER the lanes' dispatchers (drains every
    queued publish before returning)."""

    def __init__(self, hub, num_lanes: int, metrics=None,
                 gap_wait_s: float = GAP_WAIT_S):
        self.hub = hub
        self.metrics = metrics
        self._gap_wait_s = gap_wait_s
        self._q: queue.Queue = queue.Queue()   # unbounded: put never blocks
        self._state = [_LaneMergeState() for _ in range(num_lanes)]
        self._closed = False
        self._merger = threading.Thread(
            target=self._run, name="feed-fanin-merger", daemon=True)
        self._merger.start()

    def lane_publisher(self, lane_id: int) -> LaneFeedPublisher:
        return LaneFeedPublisher(self, lane_id)

    # -- merger thread (declared role "feed_merger") --------------------

    def _run(self) -> None:
        while True:
            # Poll at a CONSTANT fraction of the gap window while any
            # hole is parked (deadline math must not flow into the get:
            # its result carries the payloads onto the replay surfaces,
            # and a wall-clock-derived timeout would taint them for the
            # determinism analyzer); block indefinitely when contiguous.
            timeout = None
            if any(st.parked for st in self._state):
                timeout = self._gap_wait_s / 4
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                self._expire_gaps()
                continue
            if item is _CLOSE:
                # Everything enqueued before close() is already drained
                # (FIFO); flush any parked tail as declared gaps so no
                # delivered-after-a-hole item is silently dropped.
                self._expire_gaps(force=True)
                return
            self._ingest(item)

    def _ingest(self, item) -> None:
        lane, _epoch, seq, kind, payload = item
        st = self._state[lane]
        if seq == st.expected:
            st.expected += 1
            self._deliver(kind, payload)
            while st.expected in st.parked:
                _, k, p = st.parked.pop(st.expected)
                st.expected += 1
                self._deliver(k, p)
            if st.parked:
                st.deadline = time.monotonic() + self._gap_wait_s
        elif seq > st.expected:
            # Hole in the lane's seq line: park until contiguity resumes
            # or the gap window lapses.
            if not st.parked:
                st.deadline = time.monotonic() + self._gap_wait_s
            st.parked[seq] = (seq, kind, payload)
        else:
            # Duplicate/stale (seq already delivered or declared lost).
            if self.metrics is not None:
                self.metrics.inc("feed_fanin_dups")

    def _expire_gaps(self, force: bool = False) -> None:
        now = time.monotonic()
        for lane in range(len(self._state)):
            st = self._state[lane]
            if not st.parked or (not force and now < st.deadline):
                continue
            head = min(st.parked)
            missing = head - st.expected
            if self.metrics is not None:
                self.metrics.inc("feed_fanin_gaps", missing)
            warn_rate_limited(
                "feed-fanin", f"lane {lane}: declared gap of {missing} "
                f"publish batch(es) (seq {st.expected}..{head - 1}); "
                f"resuming at {head}")
            st.expected = head
            while st.expected in st.parked:
                _, k, p = st.parked.pop(st.expected)
                st.expected += 1
                self._deliver(k, p)
            if st.parked:
                st.deadline = now + self._gap_wait_s

    def _deliver(self, kind: int, payload) -> None:
        try:
            if kind == _MD:
                self.hub.publish_market_data(payload)
            elif kind == _OU:
                self.hub.publish_order_updates(payload)
            elif kind == _OPLOG:
                self.hub.publish_oplog(payload)
            else:
                rows, env, n, drop, observer = payload
                delivered = self.hub.publish_audit_rows(
                    rows, env, n, drop=drop, observer=observer)
                if delivered and self.metrics is not None:
                    # The lane facade returned [] to dropcopy; the real
                    # count lands here (same counter, same meaning).
                    self.metrics.inc("audit_records", len(delivered))
        except Exception as e:
            if self.metrics is not None:
                self.metrics.inc("feed_fanin_errors")
            warn_rate_limited(
                "feed-fanin", f"merge delivery failed: "
                f"{type(e).__name__}: {e}")

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        """Drain-then-stop: every publish enqueued before this call is
        delivered (the close sentinel is FIFO-ordered behind them).
        Call after the lane dispatchers are closed — late publishers
        racing close() may lose their tail, exactly like publishing
        into a closed hub."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_CLOSE)
        self._merger.join(timeout=10)
