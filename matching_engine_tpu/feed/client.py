"""Consumer-side feed helper: gap detection + automatic gap-fill.

`SequencedSubscriber` wraps one StreamMarketData / StreamOrderUpdates
subscription and yields events in sequence order:

- tracks the last seen `seq` for its (channel, key) domain;
- on a sequence jump (an upstream drop-oldest loss, or events missed
  while disconnected), opens a SECOND short-lived stream with
  `resume_from_seq` — the server replays the missed range out of the
  retransmission store — splices the recovered events in order, cancels
  the helper stream, and resumes the live one;
- counts what it could not recover (`unrecovered_events`): the server's
  store had already evicted those seqs. Loss is then *detected and
  bounded*, never silent — the property the raw streams lacked.

Conflated subscriptions (`conflate=True`) opt OUT of gap accounting:
skipping intermediate states is the channel's contract, so seq jumps
are expected and the subscriber only tracks monotonicity.

Seq domains are per server boot. A restart rebases every domain to 1;
the subscriber detects the rebase (a below-cursor seq that duplicates
nothing this connection delivered), resets its cursor, and counts it in
`epoch_rebases` — the old epoch's unreceived tail is unknowable, so it
is reported as a rebase, never silently skipped.

Used by `client/cli.py subscribe` (non-zero exit on unrecovered gaps —
the soak/CI feed-integrity assertion) and by tests/test_feed.py.
"""

from __future__ import annotations

import grpc

from matching_engine_tpu.feed.sequencer import (
    CHANNEL_AUDIT,
    CHANNEL_MD,
    CHANNEL_OPLOG,
    CHANNEL_OU,
)
from matching_engine_tpu.proto import pb2


class SequencedSubscriber:
    """Iterate sequenced events for one (channel, key), auto-gap-filling.

    channel: feed.CHANNEL_MD (key = symbol), feed.CHANNEL_OU (key =
    client_id), or feed.CHANNEL_AUDIT (the venue-wide drop-copy stream;
    key ignored — the wire is StreamOrderUpdates with the reserved
    audit client id). `from_seq` resumes after a disconnect: the server
    replays (from_seq, head] before live events. `on_gap(start, end,
    filled, missing)` fires per detected gap — the CLI prints loudly.
    """

    def __init__(self, stub, channel: str, key: str = "", from_seq: int = 0,
                 conflate: bool = False, gap_fill: bool = True,
                 fill_timeout_s: float = 10.0, on_gap=None,
                 on_rebase=None, epoch: int = 0, from_start: bool = False):
        if channel not in (CHANNEL_MD, CHANNEL_OU, CHANNEL_AUDIT,
                           CHANNEL_OPLOG):
            raise ValueError(f"unknown feed channel {channel!r}")
        if conflate and channel != CHANNEL_MD:
            raise ValueError("conflation is a market-data channel option")
        self.stub = stub
        self.channel = channel
        self.key = key
        self.from_seq = from_seq
        # from_start: treat seq 0 as a REAL cursor — the stream must
        # begin at the domain's first retained event, so a first live
        # event with seq > 1 counts as a gap and gap-fills from 0 (the
        # standby replica's contract: it must see EVERY oplog record or
        # account the loss). The server grants a full (0, head] replay
        # for resume_from_seq == 0 on the oplog channel and, via the
        # __dropcopy_all__ reserved id, on the audit channel.
        self.from_start = from_start
        self.conflate = conflate
        self.gap_fill = gap_fill
        self.fill_timeout_s = fill_timeout_s
        self.on_gap = on_gap
        self.on_rebase = on_rebase
        # -- integrity accounting (read after/inside iteration) --
        self.events = 0              # events yielded (live + replay + fill)
        self.last_seq = from_seq     # highest seq yielded
        self.gaps_detected = 0
        self.gap_filled_events = 0
        self.unrecovered_events = 0  # seqs lost for good (store evicted)
        self.conflated_jumps = 0     # seq jumps on a conflated channel
        self.epoch_rebases = 0       # server restarts observed (seqs reset)
        self.filling = False         # a gap-fill is in flight (the
        # consumer may stall up to fill_timeout_s without the stream
        # being idle — watchers pacing on consumption must not time out)
        # Boot epoch the cursor belongs to (echoed on resume requests;
        # learned/refreshed from events). With it, a cross-restart resume
        # is detected even when the new boot's head outran the cursor.
        self.epoch = epoch
        self._call = None
        self._fill_call = None
        self._call_max = 0           # highest seq seen on the live call
        self._cancelled = False

    # -- stream plumbing ---------------------------------------------------

    def _open(self, from_seq: int, timeout: float | None = None):
        if self.channel == CHANNEL_MD:
            return self.stub.StreamMarketData(
                pb2.MarketDataRequest(symbol=self.key,
                                      resume_from_seq=from_seq,
                                      conflate=self.conflate,
                                      feed_epoch=self.epoch),
                timeout=timeout)
        if self.channel == CHANNEL_AUDIT:
            from matching_engine_tpu.audit.dropcopy import (
                AUDIT_CLIENT,
                AUDIT_CLIENT_FULL,
            )

            # from_start needs the _FULL reserved id: only it makes
            # cursor 0 a real from-the-epoch-start cursor server-side
            # (plain __dropcopy__ keeps the legacy live-only attach).
            key = AUDIT_CLIENT_FULL if self.from_start else AUDIT_CLIENT
        elif self.channel == CHANNEL_OPLOG:
            from matching_engine_tpu.replication.oplog import OPLOG_CLIENT

            key = OPLOG_CLIENT
        else:
            key = self.key
        return self.stub.StreamOrderUpdates(
            pb2.OrderUpdatesRequest(client_id=key,
                                    resume_from_seq=from_seq,
                                    feed_epoch=self.epoch),
            timeout=timeout)

    def cancel(self) -> None:
        """Thread/signal-safe stop: cancels the live call AND any
        in-flight gap-fill stream; the iterator finishes cleanly
        (CANCELLED is swallowed). Sticky — a cancel racing ahead of the
        stream open still takes effect."""
        self._cancelled = True
        for call in (self._call, self._fill_call):
            if call is not None:
                call.cancel()

    def _fill(self, last: int, upto: int):
        """Recover (last, upto) via a resume stream against the
        retransmission store; cancels once the range is covered. Yields
        recovered events; accounts the rest as unrecovered."""
        want = upto - last - 1
        got = 0
        call = self._fill_call = self._open(last, timeout=self.fill_timeout_s)
        if self._cancelled:
            call.cancel()
        try:
            for e in call:
                if e.seq <= last or e.seq >= upto:
                    # The resume stream goes live after replay; reaching
                    # (or passing) the gap-closing seq ends the fill.
                    if e.seq >= upto:
                        break
                    continue
                got += 1
                self.gap_filled_events += 1
                yield e
                if got == want:
                    break
        except grpc.RpcError:
            pass  # timeout/cancel: whatever was missing stays missing
        finally:
            # In the finally so an abandoned fill (consumer stopped
            # mid-splice, GeneratorExit) still books its shortfall —
            # the exit-4 integrity contract must not under-count.
            call.cancel()
            self._fill_call = None
            self.unrecovered_events += want - got

    # -- the sequenced iterator --------------------------------------------

    def __iter__(self):
        self._call = self._open(self.from_seq)
        if self._cancelled:
            self._call.cancel()
        self._call_max = 0
        try:
            for e in self._call:
                seq = e.seq
                if seq == 0:
                    # Unsequenced server (feed disabled): plain relay.
                    self.events += 1
                    yield e
                    continue
                ep = e.feed_epoch
                if ep and self.epoch and ep != self.epoch:
                    # The authoritative rebase signal: a different boot
                    # epoch — detected even when the new boot's head has
                    # outrun the stale cursor (seqs alone can't tell a
                    # cross-epoch replay from a same-epoch one). Checked
                    # BEFORE the connection-duplicate cursor: an in-place
                    # rebase (standby promotion under a LIVE stream)
                    # restarts seqs at 1 on the same connection, which
                    # the duplicate check would silently eat. Gap
                    # accounting cannot span epochs; the old epoch's
                    # unreceived tail is unknowable and reported as the
                    # rebase, never silently blended.
                    self.epoch_rebases += 1
                    if self.on_rebase is not None:
                        self.on_rebase(self.last_seq, seq)
                    self.epoch = ep
                    self.last_seq = seq - 1
                    self._call_max = 0  # new seq line, new dedup cursor
                else:
                    if ep and not self.epoch:
                        self.epoch = ep
                    if seq <= self._call_max:
                        continue  # duplicate within this connection
                if seq <= self.last_seq:
                    # Fallback for epoch-less events: below the cursor
                    # yet NOT a duplicate of anything this connection
                    # delivered — the per-boot seq domain was rebased
                    # (server restarted). Reset the cursor.
                    self.epoch_rebases += 1
                    if self.on_rebase is not None:
                        self.on_rebase(self.last_seq, seq)
                    self.last_seq = seq - 1
                if (self.last_seq or self.from_start) \
                        and seq > self.last_seq + 1:
                    if self.conflate:
                        self.conflated_jumps += 1  # expected, not a gap
                    else:
                        self.gaps_detected += 1
                        gap_start, filled = self.last_seq, 0
                        if self.gap_fill:
                            self.filling = True
                            try:
                                for g in self._fill(self.last_seq, seq):
                                    filled += 1
                                    self.last_seq = g.seq
                                    self.events += 1
                                    yield g
                            finally:
                                self.filling = False
                        else:
                            self.unrecovered_events += seq - self.last_seq - 1
                        if self.on_gap is not None:
                            missing = (seq - gap_start - 1) - filled
                            self.on_gap(gap_start, seq, filled, missing)
                self._call_max = seq
                self.last_seq = seq
                self.events += 1
                yield e
        except grpc.RpcError as e:
            if e.code() != grpc.StatusCode.CANCELLED:
                raise
        finally:
            self.cancel()

    def summary(self) -> dict:
        return {
            "channel": self.channel, "key": self.key,
            "events": self.events, "last_seq": self.last_seq,
            "gaps_detected": self.gaps_detected,
            "gap_filled_events": self.gap_filled_events,
            "unrecovered_events": self.unrecovered_events,
            "conflated_jumps": self.conflated_jumps,
            "epoch_rebases": self.epoch_rebases,
            "epoch": self.epoch,
        }
