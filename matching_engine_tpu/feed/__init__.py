"""Sequenced market-data feed (the event-distribution layer).

Between the dispatcher's publish and the streaming RPCs sits this package:

- `sequencer.FeedSequencer` — stamps every market-data / order-update
  event with a per-(channel, key) monotonic `seq` at dispatch-publish
  time and retains recent events in a bounded `RetransmissionRing`
  (optional disk spill) for gap-fill;
- `client.SequencedSubscriber` — the consumer-side helper: detects
  sequence gaps, auto-gap-fills them from the retransmission store via
  `resume_from_seq` replay streams, and accounts for unrecoverable loss.

Seq domains are per (shard, channel, key): each host sequences the
symbols/clients it homes independently ("md"/symbol, "ou"/client_id), so
a subscriber's stream is gap-free exactly when no event for ITS key was
lost — global counters would make every other key's traffic look like a
gap. See docs/OPERATIONS.md "Sequenced feed".
"""

from matching_engine_tpu.feed.fanin import FeedFanIn, LaneFeedPublisher
from matching_engine_tpu.feed.sequencer import (
    AUDIT_DOMAIN_KEY,
    CHANNEL_AUDIT,
    CHANNEL_MD,
    CHANNEL_OU,
    FeedSequencer,
    RetransmissionRing,
)

__all__ = ["AUDIT_DOMAIN_KEY", "CHANNEL_AUDIT", "CHANNEL_MD", "CHANNEL_OU",
           "FeedFanIn", "FeedSequencer", "LaneFeedPublisher",
           "RetransmissionRing"]
